#!/usr/bin/env bash
# Differential acc|speed driver, mirroring the reference's run.sh
# (/root/reference/run.sh): run the native C++ baseline first (if built), then
# the TPU backends, all appending blocks to output.txt for side-by-side diffing.
#
# PLUSS_CLI_FLAGS defaults to --cpu because this image's tunneled-TPU backend
# hangs when the tunnel is wedged; set PLUSS_CLI_FLAGS="" for a real TPU run.
set -e
METHOD=${1:-acc}
N=${2:-128}
MODEL=${MODEL:-gemm}
CLI_FLAGS=${PLUSS_CLI_FLAGS---cpu}

# static spec verification first (pure host analysis, no accelerator, ~1 s):
# a broken spec must fail the driver BEFORE any native build or engine run.
# Diagnostics go to stderr so output.txt keeps only the diffable blocks.
python -m pluss.cli lint --all 1>&2

# schedule-aware analysis gate (placement-refined races, false sharing,
# footprint/MRC bounds — pluss/analysis/{schedule,falseshare,footprint}):
# still pure host analysis, ~20 s for the registry at default sizes.
python -m pluss.cli analyze --all 1>&2

# static prediction gate (tier-1, r12): the sampling-free symbolic
# reuse-interval predictor (pluss/analysis/ri.py) over the whole registry
# at n=16, --check cross-running the engine on every derivable model and
# requiring bit-identical histograms (MRC within ri.MRC_EPS) plus the
# exact plateau inside the heuristic MrcBracket.  The SARIF export is
# smoke-parsed through the structural validator — a malformed log breaks
# CI consumers silently, so it gates here.
PLUSS_PREDICT_SARIF=$(mktemp /tmp/pluss_predict_XXXX.sarif)
JAX_PLATFORMS=cpu python -m pluss.cli predict --all --n 16 --check --cpu \
  --sarif "$PLUSS_PREDICT_SARIF" 1>&2
python -c "import json, sys; from pluss.analysis import sarif; \
doc = json.load(open(sys.argv[1])); errs = sarif.validate(doc); \
assert not errs, errs; print('predict SARIF smoke: valid,', \
    len(doc['runs'][0]['results']), 'result(s)')" "$PLUSS_PREDICT_SARIF" 1>&2
rm -f "$PLUSS_PREDICT_SARIF"

# co-tenancy composition gate (tier-1, r15): the cross-nest CRI
# composition (pluss/analysis/interference.py) on the gemm+syrk pair at
# n=16, --check pinning each workload's composed degraded MRC against
# the interleaved schedule-simulation oracle (exact LRU stack distances
# on the proportional-fair merged stream).  Pure host math — no device.
# The SARIF export (PL801/PL802/PL803 findings) is smoke-parsed through
# the structural validator like the predict gate above.
PLUSS_COT_SARIF=$(mktemp /tmp/pluss_cot_XXXX.sarif)
JAX_PLATFORMS=cpu python -m pluss.cli cotenancy gemm+syrk --n 16 --check \
  --sarif "$PLUSS_COT_SARIF" 1>&2
python -c "import json, sys; from pluss.analysis import sarif; \
doc = json.load(open(sys.argv[1])); errs = sarif.validate(doc); \
assert not errs, errs; print('cotenancy SARIF smoke: valid,', \
    len(doc['runs'][0]['results']), 'result(s)')" "$PLUSS_COT_SARIF" 1>&2
rm -f "$PLUSS_COT_SARIF"

# schedule-tuning gate (tier-1, r16): the proof-carrying auto-optimizer
# (pluss/analysis/tune.py).  First the gemm search with --check — the
# PL901/PL902 winner's predicted MRC must match a live engine run under
# the tuned schedule bit-identically — and the PL9xx SARIF export
# smoke-parsed through the structural validator; then the whole registry
# (--all) searched and cross-checked the same way: any PL904 disagreement
# or PL903 refusal on the 29 families fails the driver here.
PLUSS_TUNE_SARIF=$(mktemp /tmp/pluss_tune_XXXX.sarif)
JAX_PLATFORMS=cpu python -m pluss.cli tune gemm --n 16 --check --cpu \
  --sarif "$PLUSS_TUNE_SARIF" 1>&2
python -c "import json, sys; from pluss.analysis import sarif; \
doc = json.load(open(sys.argv[1])); errs = sarif.validate(doc); \
assert not errs, errs; print('tune SARIF smoke: valid,', \
    len(doc['runs'][0]['results']), 'result(s)')" "$PLUSS_TUNE_SARIF" 1>&2
rm -f "$PLUSS_TUNE_SARIF"
JAX_PLATFORMS=cpu python -m pluss.cli tune --all --n 16 --check --cpu 1>&2

# loop-transformation gate (tier-1, r18): the legality prover + spec-to-
# spec transformer (pluss/analysis/transform.py).  The proven-legal gemm
# interchange must run through the live engine bit-identically to its
# own static MRC prediction (--check; any PL954 disagreement fails the
# driver), and the PL95x SARIF export must survive the structural
# validator.
PLUSS_TF_SARIF=$(mktemp /tmp/pluss_transform_XXXX.sarif)
JAX_PLATFORMS=cpu python -m pluss.cli transform gemm --interchange 0,2 \
  --n 16 --check --cpu --sarif "$PLUSS_TF_SARIF" 1>&2
python -c "import json, sys; from pluss.analysis import sarif; \
doc = json.load(open(sys.argv[1])); errs = sarif.validate(doc); \
assert not errs, errs; print('transform SARIF smoke: valid,', \
    len(doc['runs'][0]['results']), 'result(s)')" "$PLUSS_TF_SARIF" 1>&2
rm -f "$PLUSS_TF_SARIF"

# frontend import smoke (tier-1): the checked-in gemm.ppcg_omp-shaped C
# source → tokenizer → recursive-descent parse → lower → share-span
# derivation → PR-1 analyzer gate → engine run, with --check-model
# asserting the histogram + MRC byte-identical to the registry gemm
# model (the bit-identity gate for machine-derived specs, ~seconds on
# CPU).  The acc-style block goes to stderr: output.txt keeps only the
# diffable reference blocks.
JAX_PLATFORMS=cpu python -m pluss.cli import \
  pluss/frontend/examples/gemm.ppcg_omp.c --run --check-model gemm --cpu 1>&2

# trace replay smoke (tier-1): compressed-wire (d24v) pack → parallel-feed
# replay → fault-interrupted checkpoint --resume equivalence + legacy-
# kernel/serial-feed/plain-pack A/B on a ~1e6-ref synthetic trace, pinned
# to the CPU backend (~10 s).  The replay pipeline — worker pool,
# compactor turnstile, device-side wire decode, staged-ahead h2d — is
# exercised on every PR, not just in the budget-gated bench.  Since r19
# the smoke's last phase forces the fused Pallas pipeline (event
# histogram + d24v decode, interpreter mode on CPU) and pins it
# bit-identical to the XLA path — the kernel-promotion gate.  Runs with
# the telemetry sink ARMED, and the emitted event stream must pass the
# schema check (`pluss stats --check`) — an observability regression
# (malformed records, a broken sink) gates the PR like any other.
PLUSS_OBS_LOG=$(mktemp /tmp/pluss_obs_XXXX.jsonl)
JAX_PLATFORMS=cpu PLUSS_TELEMETRY="$PLUSS_OBS_LOG" \
  python -m pluss.trace_smoke 1>&2
python -m pluss.cli stats "$PLUSS_OBS_LOG" --check 1>&2
rm -f "$PLUSS_OBS_LOG"

# autotune sidecar gate (tier-1, r19): a short forced calibration into a
# throwaway plan-cache dir must persist a geometry sidecar that (a)
# passes `pluss autotune --dry-run` validation and (b) short-circuits a
# second `pluss autotune` with ZERO re-calibration (the persist→consult
# round trip, witnessed by the autotune.hit counter in its telemetry).
PLUSS_AT_DIR=$(mktemp -d /tmp/pluss_at_XXXX)
PLUSS_AT_LOG=$(mktemp /tmp/pluss_at_XXXX.jsonl)
JAX_PLATFORMS=cpu PLUSS_PLAN_CACHE_DIR="$PLUSS_AT_DIR" \
  python -m pluss.cli autotune --refs 60000 --cpu 1>&2
JAX_PLATFORMS=cpu PLUSS_PLAN_CACHE_DIR="$PLUSS_AT_DIR" \
  python -m pluss.cli autotune --dry-run 1>&2
JAX_PLATFORMS=cpu PLUSS_PLAN_CACHE_DIR="$PLUSS_AT_DIR" \
  PLUSS_TELEMETRY="$PLUSS_AT_LOG" \
  python -m pluss.cli autotune --cpu 1>&2
python -c "import json, sys; \
c = {r['name']: r.get('value', 0) \
     for r in map(json.loads, open(sys.argv[1])) \
     if r.get('ev') == 'counter'}; \
assert c.get('autotune.hit', 0) >= 1, f'no sidecar consult: {c}'; \
assert not c.get('autotune.probe'), f'hit still recalibrated: {c}'; \
print('autotune round-trip: hit=%d, zero re-calibration' \
    % c['autotune.hit'])" "$PLUSS_AT_LOG" 1>&2
python -m pluss.cli stats "$PLUSS_AT_LOG" --check 1>&2
rm -rf "$PLUSS_AT_DIR" "$PLUSS_AT_LOG"

# trace residency smoke (tier-1, r13): replay the same trace twice in one
# process with the HBM residency store armed — the first run streams and
# stage-through-populates the store, the second must HIT (residency.hit
# counted, trace.h2d_bytes delta == 0) bit-identically; then a tiny-budget
# store must refuse the staging with a counted fallback while the replay
# completes bit-identically through the streamed path.  Telemetry armed,
# stream schema-checked — the `pluss stats` trace-residency block reads
# off this same file.
PLUSS_RES_LOG=$(mktemp /tmp/pluss_res_XXXX.jsonl)
JAX_PLATFORMS=cpu PLUSS_TELEMETRY="$PLUSS_RES_LOG" \
  python -m pluss.residency_smoke 1>&2
python -m pluss.cli stats "$PLUSS_RES_LOG" --check 1>&2
rm -f "$PLUSS_RES_LOG"

# multichip smoke (tier-1): 8-fake-device sharded execution — streamed
# sharded replay (work-stealing AND static dispatch) bit-identical to the
# single-device replay, quad-nest shard_run (cholesky, the straggler-bound
# window shape) bit-identical to engine.run across steal seeds / window
# kernels / dispatch modes, with the steal telemetry (shard.chunks /
# shard.steals counters, per-device busy-fraction gauges) ARMED and the
# emitted stream gated on `pluss stats --check` — the fleet execution
# path is proven on every PR, not just in the budget-gated bench.
PLUSS_MC_LOG=$(mktemp /tmp/pluss_mc_XXXX.jsonl)
JAX_PLATFORMS=cpu PLUSS_TELEMETRY="$PLUSS_MC_LOG" \
  python -m pluss.multichip_smoke 1>&2
python -m pluss.cli stats "$PLUSS_MC_LOG" --check 1>&2
rm -f "$PLUSS_MC_LOG"

# serve smoke (tier-1): spawn a real `pluss serve` daemon on a unix socket
# and drive ~20 mixed spec/trace requests through the soak load generator —
# including a forced-degraded request (injected OOM ridden through the
# process-safe serve ladder) and a forced shed (admission-bound burst →
# typed Overloaded) — with every response bit-compared against a solo run,
# then drain-and-stop cleanly and schema-check the daemon's telemetry
# stream (the serve SLO block in `pluss stats` reads off this same file).
PLUSS_SERVE_LOG=$(mktemp /tmp/pluss_serve_XXXX.jsonl)
JAX_PLATFORMS=cpu python soak.py --serve 20 "${PLUSS_SERVE_SEED:-20260804}" \
  --telemetry "$PLUSS_SERVE_LOG" 1>&2
python -m pluss.cli stats "$PLUSS_SERVE_LOG" --check 1>&2
rm -f "$PLUSS_SERVE_LOG"

# serve hardening smoke (tier-1, r14): health/ready verbs on a fresh
# daemon, then two injected device dispatch failures trip the circuit
# breaker (threshold 2) — while open, a spec request browns out on the
# host CPU device bit-identically (stamped cpu_brownout) and a trace
# request sheds typed Overloaded with retry_after_ms; after the cooldown
# the half-open probe closes it and readiness returns.  Every admitted
# request is journaled open->done.  Telemetry armed, stream
# schema-checked — the `pluss stats` serve-hardening block reads off
# this same file.
PLUSS_HARD_LOG=$(mktemp /tmp/pluss_hard_XXXX.jsonl)
JAX_PLATFORMS=cpu PLUSS_TELEMETRY="$PLUSS_HARD_LOG" \
  python -m pluss.hardening_smoke 1>&2
python -m pluss.cli stats "$PLUSS_HARD_LOG" --check 1>&2
rm -f "$PLUSS_HARD_LOG"

# observability-plane smoke (tier-1, r20): a daemon with the live
# /metrics pull endpoint — scrape must carry # TYPE/# HELP-hygienic
# serve counters agreeing with the {"op":"metrics"} verb AND the final
# in-process rollup; health carries the SLO burn gauges; an injected
# hung dispatch (hang@1, 1s watchdog) is abandoned and the crash flight
# recorder's flight-<rid>.jsonl passes `pluss stats --check`; the
# smoke's own event stream passes --check and `pluss stats --trace`
# resolves the traced request to its causal span tree
# (admission -> admit -> queue wait -> batch -> demux).
JAX_PLATFORMS=cpu python -m pluss.obsplane_smoke 1>&2

# warm-start smoke (tier-1): the persistent AOT executable cache, proven
# across PROCESS boundaries — two fresh subprocesses run the same small
# model sharing one plan-cache dir.  The first (cold) populates the
# executable sidecars; the second (warm) must restore them: its telemetry
# must show >= 1 plan_cache.aot_hit with engine.compile_s ~ 0 (no XLA
# recompile), and the stream must pass the schema check.  This is the
# r11 gate: a stale-salt bug, a broken sidecar load, or a silent JIT
# fallback all fail the driver here, not in production.
PLUSS_WARM_DIR=$(mktemp -d /tmp/pluss_warm_XXXX)
PLUSS_WARM_LOG=$(mktemp /tmp/pluss_warm_XXXX.jsonl)
JAX_PLATFORMS=cpu PLUSS_PLAN_CACHE_DIR="$PLUSS_WARM_DIR" \
  python -c "from pluss.utils.platform import enable_x64; enable_x64(); \
from pluss import engine; from pluss.models import gemm; \
engine.run(gemm(48))" 1>&2
JAX_PLATFORMS=cpu PLUSS_PLAN_CACHE_DIR="$PLUSS_WARM_DIR" \
  PLUSS_TELEMETRY="$PLUSS_WARM_LOG" \
  python -c "from pluss.utils.platform import enable_x64; enable_x64(); \
import os; from pluss import engine, obs; from pluss.models import gemm; \
obs.configure(os.environ['PLUSS_TELEMETRY']); engine.run(gemm(48)); \
c = obs.counters(); \
assert c.get('engine.plan_cache.aot_hit', 0) >= 1, \
    f'warm process restored no AOT executable: {c}'; \
assert c.get('engine.compile_s', 0.0) < 0.05, \
    f'warm process still paid XLA compile: {c}'; \
obs.flush_metrics(); print('warm-start smoke: aot_hit=%d compile_s=%.3f' \
    % (c.get('engine.plan_cache.aot_hit'), c.get('engine.compile_s', 0.0)))" 1>&2
python -m pluss.cli stats "$PLUSS_WARM_LOG" --check 1>&2
rm -rf "$PLUSS_WARM_DIR" "$PLUSS_WARM_LOG"

# opt-in chaos smoke (PLUSS_CHAOS=1): a short seeded fault-plan soak on the
# CPU backend — every injected fault (OOM / compile / share-cap / corrupt
# cache) must either recover to a bit-exact result via the degradation
# ladder or fail with a classified PlussError.  Seed via PLUSS_CHAOS_SEED
# for a reproducible plan; rounds via PLUSS_CHAOS_ROUNDS.
if [ "${PLUSS_CHAOS:-0}" = 1 ]; then
  python soak.py --chaos "${PLUSS_CHAOS_ROUNDS:-3}" \
    "${PLUSS_CHAOS_SEED:-20260804}" 1>&2
fi

# always try make (incremental, no-op when fresh): a stale prebuilt binary
# would mis-parse the --spec flag used for non-gemm models.  A failed build
# only warns — the Python CLI block below must still run and diagnose.
NATIVE_OK=0
if [ -d pluss/cpp ]; then
  if (cd pluss/cpp && make -s); then
    NATIVE_OK=1
  else
    # a stale prebuilt binary would mis-parse --spec: skip entirely
    echo "run.sh: native build failed; skipping native block" >&2
  fi
fi
if [ "$NATIVE_OK" = 1 ] && [ -f pluss/cpp/build/pluss_cpp ]; then
  if [ "$MODEL" = gemm ]; then
    ./pluss/cpp/build/pluss_cpp "$METHOD" "$N" >> output.txt
  else
    # any registry model: serialize the spec for the native binary; a
    # serialization failure (bad MODEL etc.) skips the native block and
    # lets the CLI below report the real error
    SPEC_BIN=$(mktemp /tmp/pluss_spec_XXXX.bin)
    # values pass via the environment, not textual interpolation: a quote
    # or metacharacter in MODEL must fail cleanly, not edit the program
    if MODEL="$MODEL" N="$N" SPEC_BIN="$SPEC_BIN" python -c "import os; \
from pluss.models import REGISTRY; from pluss import native; \
native.write_spec_file(REGISTRY[os.environ['MODEL']](int(os.environ['N'])), \
os.environ['SPEC_BIN'])"; then
      ./pluss/cpp/build/pluss_cpp "$METHOD" --spec "$SPEC_BIN" >> output.txt
    else
      echo "run.sh: spec serialization failed for MODEL=$MODEL; skipping native block" >&2
    fi
    rm -f "$SPEC_BIN"
  fi
fi

python -m pluss.cli "$METHOD" --model "$MODEL" --n "$N" $CLI_FLAGS >> output.txt
