#!/usr/bin/env bash
# Differential acc|speed driver, mirroring the reference's run.sh
# (/root/reference/run.sh): run the native C++ baseline first (if built), then
# the TPU backends, all appending blocks to output.txt for side-by-side diffing.
#
# PLUSS_CLI_FLAGS defaults to --cpu because this image's tunneled-TPU backend
# hangs when the tunnel is wedged; set PLUSS_CLI_FLAGS="" for a real TPU run.
set -e
METHOD=${1:-acc}
N=${2:-128}
MODEL=${MODEL:-gemm}
CLI_FLAGS=${PLUSS_CLI_FLAGS---cpu}

if [ ! -f pluss/cpp/build/pluss_cpp ] && [ -d pluss/cpp ]; then
  (cd pluss/cpp && make -s)
fi
# the native binary hardwires the GEMM spec; other models compare via the
# ctypes binding (tests/test_native.py)
if [ -f pluss/cpp/build/pluss_cpp ] && [ "$MODEL" = gemm ]; then
  ./pluss/cpp/build/pluss_cpp "$METHOD" "$N" >> output.txt
fi

python -m pluss.cli "$METHOD" --model "$MODEL" --n "$N" $CLI_FLAGS >> output.txt
