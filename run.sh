#!/usr/bin/env bash
# Differential acc|speed driver, mirroring the reference's run.sh
# (/root/reference/run.sh): run the native C++ baseline first (if built), then
# the TPU backends, all appending blocks to output.txt for side-by-side diffing.
set -e
METHOD=${1:-acc}

if [ -f pluss/cpp/build/pluss_cpp ]; then
  ./pluss/cpp/build/pluss_cpp "$METHOD" >> output.txt
elif [ -d pluss/cpp ]; then
  (cd pluss/cpp && make -s) && ./pluss/cpp/build/pluss_cpp "$METHOD" >> output.txt
fi

python -m pluss.cli "$METHOD" >> output.txt
