"""Round benchmark: sampled refs/sec on the flagship GEMM workload, plus the
sort-path metric (syrk — template-ineligible by construction).

Protocol (mirrors the reference's `speed` mode, /root/reference/src/main.rs:23-35):
time (sampler + CRI distribute) over repetitions after one warmup (the warmup
is the XLA-compile analogue of the reference timing a prebuilt binary), then
report refs/sec = total simulated accesses / best seconds.

`vs_baseline` is the speedup over the native C++ runtime (pluss/cpp) running
the SAME workload on this host — the stand-in for the reference's serialized
Rust/C++ backends (its Rayon/spawn backends hold whole-lifetime locks and run
sequentially, SURVEY.md Q2, so the native walk is a faithful proxy).

Prints one JSON line per metric on stdout.  The flagship GEMM line is
emitted FIRST (so a timeout can never lose the headline — round 3's record
died at rc=124 with the flagship still queued) and then RE-emitted as the
final line (the driver's parsed headline is the last JSON line of the run,
see BENCH_r02/r03 "parsed" — consumers must dedup by metric name).  Aux
metrics in between are each gated on a GLOBAL wall budget
(PLUSS_BENCH_BUDGET_S, default 1140 s — just under a presumed ~1200 s
driver timeout so the graceful SKIP path wins the race against a hard
kill): an aux metric whose estimated cost exceeds the remaining budget is
skipped with a logged reason instead of timing the whole bench out.
Native C++ baselines are measured once and cached on disk keyed by a hash
of the native sources, so repeat runs spend the budget on TPU metrics, not
on re-timing an unchanged host binary.

Robustness: this image's sitecustomize registers a tunneled-TPU backend that
can hang indefinitely if the tunnel is wedged, so the accelerator is probed in
a subprocess with a hard timeout; on failure the bench degrades to the host CPU
(smaller N, still reported honestly under a distinct metric name).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

PROBE_TIMEOUT_S = 120
REPS = 3
_T_START = time.monotonic()
# resident replay rate per trace size (refs/s), stashed by
# bench_trace_resident for the streamed line's streamed_vs_resident_ratio
_RESIDENT_RATE: dict[int, float] = {}
# default wall budget: slightly under the 20-minute mark so that if the
# driver wraps the bench in its own ~1200 s timeout, the graceful SKIP
# path always wins the race against a hard rc=124 kill
BUDGET_S = float(os.environ.get("PLUSS_BENCH_BUDGET_S", 1140))
NATIVE_CACHE = ".bench/native_cache.json"


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def remaining_s() -> float:
    """Seconds left of the global wall budget."""
    return BUDGET_S - (time.monotonic() - _T_START)


def budget_ok(label: str, est_s: float) -> bool:
    """True if an aux step estimated at ``est_s`` fits the remaining budget."""
    rem = remaining_s()
    if est_s > rem:
        log(f"bench: SKIP {label}: needs ~{est_s:.0f}s, "
            f"{rem:.0f}s of {BUDGET_S:.0f}s budget left")
        return False
    return True


def _native_src_hash() -> str:
    """Hash of the native runtime sources — invalidates cached baselines."""
    import hashlib

    h = hashlib.sha256()
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "pluss", "cpp")
    for fn in sorted(os.listdir(d)):
        p = os.path.join(d, fn)
        if os.path.isfile(p):
            with open(p, "rb") as f:
                h.update(fn.encode() + b"\0" + f.read())
    return h.hexdigest()[:16]


def cached_native(key: str, measure) -> dict | None:
    """Native-baseline memo: ``measure()`` returns a JSON-able dict (must
    hold at least ``{"s": seconds}``) that is cached on disk until the
    native sources change.  The host binary's speed is a property of this
    box + those sources — re-timing it every round only burns wall budget
    (round 3 spent 300+ s re-measuring identical binaries)."""
    try:
        with open(NATIVE_CACHE) as f:
            cache = json.load(f)
    except (OSError, ValueError):
        cache = {}
    src = _native_src_hash()
    ent = cache.get(key)
    if ent and ent.get("src") == src:
        log(f"bench: native baseline {key}: {ent['s']:.3f}s (cached)")
        return ent
    ent = measure()
    if ent is not None and ent.get("s"):
        ent["src"] = src
        cache[key] = ent
        os.makedirs(".bench", exist_ok=True)
        tmp = NATIVE_CACHE + ".tmp"
        with open(tmp, "w") as f:
            json.dump(cache, f, indent=1)
        os.replace(tmp, NATIVE_CACHE)
    return ent


def cached_native_s(key: str, measure_s, est_s: float = 120) -> float | None:
    """Seconds-returning flavor of :func:`cached_native`, budget-gated: a
    cold-cache measurement only runs if ``est_s`` fits the remaining wall
    budget (a skipped baseline degrades the metric to vs_baseline=null —
    never to a missing metric line)."""
    def measure() -> dict | None:
        if not budget_ok(f"native baseline {key}", est_s):
            return None
        s = measure_s()
        return {"s": s} if s else None

    ent = cached_native(key, measure)
    return ent["s"] if ent else None


def probe_accelerator() -> str | None:
    """Killable accelerator probe (see pluss.utils.platform.probe_accelerator:
    a wedged TPU tunnel must not hang the bench)."""
    from pluss.utils.platform import probe_accelerator as probe

    plat = probe(PROBE_TIMEOUT_S)
    if plat is None:
        log("bench: no usable accelerator (wedged tunnel or CPU-only box)")
    return plat


def native_baseline_s(n: int) -> float | None:
    """Best seconds/run of the native C++ sampler+CRI at size n, or None."""
    from pluss import native

    try:
        ok = native.available(autobuild=True)  # incremental: no stale binary
    except RuntimeError as e:  # compile failure: report, never time stale code
        log(f"bench: native build failed: {e}")
        return None
    if not ok:
        log("bench: native toolchain unavailable")
        return None
    try:
        out = subprocess.run([native.BIN_PATH, "speed", str(n)],
                             capture_output=True,
                             text=True, timeout=3600, check=True).stdout
    except (OSError, subprocess.CalledProcessError,
            subprocess.TimeoutExpired) as e:
        log(f"bench: native baseline run failed: {e}")
        return None
    times = [float(m) for m in re.findall(r"NATIVE C\+\+: ([0-9.]+)", out)]
    return min(times) if times else None


def compile_stamp(c0: dict) -> dict:
    """Metric-line stamp of the compile cost paid since the ``c0``
    counter snapshot (round r11 on): ``compile_s`` is the XLA compile
    wall actually spent, ``warm`` records whether the executables came
    from a cache (AOT sidecar / persistent cache / in-process memo)
    instead of a fresh compile — so the trajectory shows compile cost
    per family instead of burying it in warmup log prose."""
    from pluss import obs

    c1 = obs.counters()

    def d(k: str) -> float:
        return c1.get(k, 0.0) - c0.get(k, 0.0)

    return {"compile_s": round_keep(d("engine.compile_s"), 3),
            "warm": bool(d("engine.compiles") == 0)}


def timed_reps(step, reps: int, label: str):
    """(best seconds, last result, compile stamp) of ``reps`` timed calls
    after one warmup; the stamp (:func:`compile_stamp`) covers the
    warmup, where any compile happens."""
    from pluss import obs

    c0 = obs.counters()
    t0 = time.perf_counter()
    res = step()  # warmup: compile + first run
    log(f"bench: {label} warmup (incl. compile) "
        f"{time.perf_counter() - t0:.2f}s; {res.max_iteration_count} refs/run")
    cstamp = compile_stamp(c0)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        step()
        times.append(time.perf_counter() - t0)
    log(f"bench: {label} per-rep {['%.3f' % t for t in times]} s")
    # best-of-reps on BOTH sides: robust to transient host load, which would
    # otherwise inflate (or deflate) the speedup ratio
    return min(times), res, cstamp


def round_keep(v: float | None, nd: int) -> float | None:
    """Round for record compactness WITHOUT erasing small magnitudes.

    ``round(1.39e-14, 9) == 0.0`` destroyed the r5 error metric's
    machine-readable value while the prose log kept it (ADVICE r5) — any
    value whose rounding would collapse to zero is emitted unrounded, so
    the JSON line always carries at least the information the log does."""
    if v is None:
        return None
    r = round(v, nd)
    return v if (r == 0.0 and v != 0.0) else r


#: metric-record schema: v2 adds the per-record ``schema`` + ``t_wall``
#: stamps (r20) so a record line is attributable to a run without the
#: surrounding file — consumers reading BENCH_r0x tails can dedup and
#: order by wall clock instead of by position
BENCH_SCHEMA = 2
#: every record emitted this run, in order — the self-recorded round
#: file (``BENCH_r06.json`` on) is written from this at exit
_RECORDS: list[dict] = []


def emit_record(rec: dict) -> None:
    """The single stdout sink for metric records: stamps the schema
    version and a wall-clock timestamp on EVERY record, remembers it for
    the self-recorded round file, and prints the JSON line."""
    rec.setdefault("schema", BENCH_SCHEMA)
    rec.setdefault("t_wall", round(time.time(), 3))
    _RECORDS.append(rec)
    print(json.dumps(rec), flush=True)


def next_round_n() -> int:
    """The round number to self-record under: one past the highest
    existing BENCH_r<N>.json (the driver-written trajectory ends at
    r05, so a fresh checkout records r06)."""
    import glob

    seen = [int(m.group(1)) for f in glob.glob("BENCH_r*.json")
            if (m := re.match(r"BENCH_r(\d+)\.json$", os.path.basename(f)))]
    return max(seen, default=5) + 1


def write_round_record(n: int, rc: int) -> None:
    """Self-record the round in the driver's BENCH_r0x shape ({n, cmd,
    rc, tail, parsed}): the trajectory stopped at BENCH_r05 when the
    driver quit writing it, so from r06 on the bench writes its own."""
    path = f"BENCH_r{n:02d}.json"
    lines = [json.dumps(r) for r in _RECORDS]
    parsed = None
    for r in reversed(_RECORDS):
        if "metric" in r:
            parsed = {k: r.get(k) for k in ("metric", "value", "unit",
                                            "vs_baseline")}
            break
    try:
        with open(path, "w") as f:
            json.dump({"n": n, "cmd": "python " + " ".join(sys.argv),
                       "rc": rc, "tail": "\n".join(lines)[-1600:],
                       "parsed": parsed}, f, indent=1)
        log(f"bench: round record -> {path} ({len(lines)} record(s))")
    except OSError as e:
        log(f"bench: cannot write {path}: {e}")


def emit(metric: str, refs: int, best_s: float, base_s: float | None,
         path: str = "", degradations: tuple = (), **extra) -> None:
    """One JSON metric line.  ``path`` names the code path measured
    (engine.describe_path label, or a trace-pipeline name) so the record
    is self-describing — "sortpath" metric names notwithstanding
    (VERDICT r5 task 4; names stay stable for round-over-round diffs).
    ``degradations`` carries the resilience ladder's stamp (empty for a
    clean run), so a degraded run is visible in the perf trajectory
    instead of masquerading as a regression.  Spec metric lines also
    carry ``spec_source`` (registry | dsl | c — via ``extra``, round r08
    on) recording which authoring surface produced the measured spec."""
    vs = base_s / best_s if base_s else None
    refs_per_sec = refs / best_s
    log(f"bench: {metric} best {refs_per_sec:.3e} refs/s"
        + (f", native {base_s:.3f} s/run -> speedup {vs:.2f}x" if vs else "")
        + (f" [degraded: {','.join(degradations)}]" if degradations else ""))
    emit_record({
        "metric": metric,
        "value": round_keep(refs_per_sec, 1),
        "unit": "refs/s",
        "vs_baseline": round_keep(vs, 3),
        "path": path,
        "degradations": list(degradations),
        **extra,
    })


def analysis_fields(spec) -> dict:
    """Static-analyzer stamps for a spec metric line: the global
    footprint (distinct cache lines — the working set the refs/s number
    was measured over) and the schedule-aware false-sharing verdict
    (count of PL501/PL502 findings under the default schedule).  Never
    sinks a metric: any failure degrades to an empty dict."""
    try:
        from pluss.analysis import Severity, falseshare, footprint
        from pluss.config import DEFAULT

        t0 = time.perf_counter()
        fp = footprint.footprints(spec, DEFAULT)
        diags = falseshare.check(spec, DEFAULT)
        n_fs = sum(1 for d in diags if d.severity is Severity.WARNING)
        log(f"bench: analysis stamp for {spec.name}: "
            f"{fp.total} lines, {n_fs} false-sharing finding(s) "
            f"({time.perf_counter() - t0:.1f}s)")
        return {"footprint_lines": fp.total, "false_sharing": n_fs}
    except Exception as e:
        log(f"bench: analysis fields failed for {spec.name}: {e}")
        return {}


def native_spec_s(spec, reps: int = 2) -> float | None:
    """Best seconds/run of the native walk on an arbitrary spec via the
    ctypes runtime (the standalone binary's CLI only builds the GEMM spec)."""
    from pluss import native

    try:
        if not native.available(autobuild=True):
            return None
    except RuntimeError as e:
        log(f"bench: native build failed: {e}")
        return None
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        native.run(spec)
        times.append(time.perf_counter() - t0)
    return min(times)


def synth_trace(path: str, n_refs: int, seed: int = 0) -> None:
    """Write a synthetic DynamoRIO-like byte-address trace (packed LE u64).

    Two-tier working set (hot 2^16 lines / warm 2^22 lines, shuffled per
    batch) — gives a two-knee MRC and a realistic reuse mix.  Written in
    128 MB batches so generation is memory-bounded at any n_refs.
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    batch = 1 << 24
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        written = 0
        while written < n_refs:
            m = min(batch, n_refs - written)
            hot = rng.integers(0, 1 << 16, m // 2, dtype=np.int64)
            warm = rng.integers(0, 1 << 22, m - m // 2, dtype=np.int64)
            lines = np.concatenate([hot, warm])
            rng.shuffle(lines)
            (lines.astype(np.uint64) << np.uint64(6)).astype("<u8").tofile(f)
            written += m
    os.replace(tmp, path)


def bench_trace_device(n_lines: int = 4_200_000) -> None:
    """Device-only trace-scan rate: resident ids, fresh stream offsets.

    The end-to-end trace metric below is gated by this image's tunneled
    h2d feed (~10-30 MB/s, varying several-fold minute to minute); this
    companion metric pins the TPU-native compute rate of the same scan so
    the two factors are separable in the record.  Measures the default
    (segmented whole-batch) kernel; PLUSS_BENCH_TRACE_AB=1 adds a second
    line for the legacy per-window scan so the round record carries the
    A/B directly.
    """
    import numpy as np

    import jax.numpy as jnp
    from pluss import obs, trace

    c0 = obs.counters()
    W, B = trace.TRACE_WINDOW, trace.WINDOWS_PER_BATCH
    batch = W * B
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, n_lines, batch, dtype=np.int32)
                      .reshape(B, W))
    pdt = np.dtype("int32")

    def measure(segmented: bool) -> tuple[int, float]:
        fn = trace._replay_fn(W, "int32", segmented=segmented)
        last = jnp.full((1 << 23,), -1, pdt)
        hist = jnp.zeros((trace.NBINS,), pdt)
        last, hist = fn(last, hist, pdt.type(0), ids, pdt.type(2**31 - 4))
        np.asarray(hist[:1])  # tiny d2h forces completion (block_until_ready
        # does not actually wait over the tunneled backend)
        reps = 12
        t0 = time.perf_counter()
        for b in range(1, reps + 1):   # varying base defeats content caching
            last, hist = fn(last, hist, pdt.type(b * batch), ids,
                            pdt.type(2**31 - 4))
        np.asarray(hist[:1])
        return reps * batch, time.perf_counter() - t0

    refs, dt = measure(True)
    emit("trace_device_scan_refs_per_sec", refs, dt, None,
         path="trace_device_scan(segmented)", batch_windows=B,
         **compile_stamp(c0))
    if os.environ.get("PLUSS_BENCH_TRACE_AB"):
        refs, dt = measure(False)
        emit("trace_device_scan_legacy_refs_per_sec", refs, dt, None,
             path="trace_device_scan(per-window scan)", batch_windows=B)


def ensure_trace(n_refs: int) -> str:
    """Generate (once) and return the cached synthetic trace path."""
    os.makedirs(".bench", exist_ok=True)
    path = f".bench/trace_{n_refs}.bin"
    if not (os.path.exists(path) and os.path.getsize(path) == 8 * n_refs):
        log(f"bench: generating {n_refs}-ref synthetic trace at {path}")
        t0 = time.perf_counter()
        synth_trace(path, n_refs)
        log(f"bench: trace generated in {time.perf_counter() - t0:.1f}s")
    return path


def native_trace_rate(path: str) -> float | None:
    """Native replay rate (refs/s), measured once on a 2^27-ref prefix and
    cached — the baseline for BOTH trace metrics.  Native replay is linear
    in refs (hashmap walk), so the rate scales to any prefix."""
    from pluss import native, trace

    def measure() -> dict | None:
        # cold-cache cost: ~1 GB prefix load + ~30 s native walk — gate it
        # on the global budget so a late cache miss can't starve the
        # metrics that were admitted under small estimates
        if not budget_ok("native trace rate (one-time)", 90):
            return None
        try:
            if not native.available(autobuild=True):
                return None
            import numpy as np

            n = min(1 << 27, os.path.getsize(path) // 8)
            # prefix read, NOT load_trace(path)[:n]: the full-file load
            # would transiently allocate 2x the whole 8 GB trace
            addrs = np.fromfile(path, dtype="<u8", count=n).astype(np.int64)
            t0 = time.perf_counter()
            native.replay(addrs)
            return {"s": time.perf_counter() - t0, "refs": n}
        except (RuntimeError, MemoryError) as e:
            log(f"bench: native trace baseline unavailable: {e}")
            return None

    # keyed by trace file: the native rate depends on the working-set size
    # (hashmap cache behavior), so rates from different traces don't mix
    ent = cached_native(f"trace_replay_rate:{os.path.basename(path)}",
                        measure)
    return ent["refs"] / ent["s"] if ent else None


def cached_pack(path: str, n_refs: int) -> tuple[dict | None, bool, str]:
    """(pack sidecar meta, was_cached, packed path) of the staged
    (packed) trace.  Thin caller of :func:`pluss.trace.pack_cached` —
    the staleness key (ref count + source-trace content + wire-format
    version + batch grid) was promoted there in r13 so every consumer
    shares it — keeping the bench's own concerns here: the ``.bench/``
    naming, the one-time packing budget gate, and the logging behind the
    ``staging_cached`` stamp that distinguishes a round that paid the
    ~minutes repack from one that reused the staged bytes."""
    from pluss import trace

    packed = f".bench/trace_{n_refs}.pack"
    meta, was_cached, _ = trace.pack_cached(path, packed, wire="d24v",
                                            allow_pack=False)
    if was_cached:
        log(f"bench: staged trace pack {packed}: cached "
            f"({meta['n_lines']} line slots, fmt {meta['fmt']})")
        return meta, True, packed
    if os.path.exists(packed):
        log("bench: staged trace pack is stale (source trace, wire "
            "format, or batch grid changed); repacking")
    if not budget_ok("trace pack_file (one-time)", 420):
        return None, False, packed
    log(f"bench: packing trace ids (one-time) at {packed}")
    t0 = time.perf_counter()
    meta, _, _ = trace.pack_cached(path, packed, wire="d24v")
    log(f"bench: packed in {time.perf_counter() - t0:.1f}s "
        f"({meta['n_lines']} line slots, fmt {meta['fmt']})")
    return meta, False, packed


def bench_trace_resident(n_refs: int) -> None:
    """Staged-resident replay (VERDICT r3 task 3b): upload the packed trace
    to HBM once, replay from device memory — upload and replay reported
    separately, so the metric is independent of tunnel h2d weather.  The
    packed-id file is produced once by trace.pack_file and reused across
    rounds via :func:`cached_pack`."""
    import numpy as np

    from pluss import obs, trace

    c0 = obs.counters()
    path = ensure_trace(n_refs)
    meta, staging_cached, packed = cached_pack(path, n_refs)
    if meta is None:
        return
    # staging budget: leave room for the e2e metric after us
    upload_budget = max(30.0, min(remaining_s() * 0.5, 300.0))
    resident, n_run, stats = trace.stage_resident(
        packed, meta, upload_budget_s=upload_budget)
    if n_run == 0:
        log("bench: resident staging yielded no refs; skipping")
        return
    mb = stats["upload_bytes"] / 1e6
    log(f"bench: staged {n_run} refs ({mb:.0f} MB) in "
        f"{stats['upload_s']:.1f}s ({mb / stats['upload_s']:.1f} MB/s)")
    # warmup replay (compiles; also first touch of the resident array),
    # then ONE timed replay at a shifted clock origin — histogram-invariant
    # but a distinct input, so the tunnel's content memo can't serve it
    trace.replay_staged(resident, meta["n_lines"], n_run)
    t0 = time.perf_counter()
    rep = trace.replay_staged(resident, meta["n_lines"], n_run,
                              clock0=1 << 30)
    replay_s = time.perf_counter() - t0
    rate = native_trace_rate(path)
    base_s = n_run / rate if rate else None
    assert int(rep.hist.sum()) == n_run  # BEFORE emit: a corrupt replay
    # must never leave a metric line in the round record
    emit(f"trace{n_refs}_resident_refs_per_sec", n_run, replay_s, base_s,
         path="trace_resident",
         refs_replayed=n_run, refs_requested=n_refs,
         shrunk=bool(n_run != n_refs),
         staging_cached=staging_cached,
         pack_fmt=meta["fmt"],
         upload_s=round(stats["upload_s"], 1),
         upload_mb_s=round(mb / stats["upload_s"], 2),
         **compile_stamp(c0))
    # the resident rate baselines the r13 metrics below AND the streamed
    # e2e line's streamed_vs_resident_ratio (bench_trace runs after us)
    _RESIDENT_RATE[n_refs] = n_run / replay_s
    # r13 warm-replay headline: publish the staged bytes into the
    # residency store under replay_file's own key, then time a
    # replay_file(resident_cache=True) HIT — what a repeat serve request
    # pays: resident replay with ZERO feed bytes (the h2d delta and hit
    # count ride the metric line as proof)
    from pluss import residency

    st = residency.store()
    key = trace._residency_key(path, cls=64, window=trace.TRACE_WINDOW,
                               bw=trace._resolve_bw(None),
                               precompacted=False)
    try:
        st.reserve(int(resident.nbytes), site="bench.residency")
    except Exception as e:
        log(f"bench: residency store cannot fit the staged trace; "
            f"skipping the warm headline: {e}")
        return
    st.put(key, resident, n_lines=meta["n_lines"], n_run=n_run,
           nbytes=int(resident.nbytes), meta={"path": path, "bench": True})
    trace.replay_file(path, limit_refs=n_run, resident_cache=True)  # warm
    ch0 = obs.counters()
    t0 = time.perf_counter()
    rep_w = trace.replay_file(path, limit_refs=n_run, resident_cache=True)
    warm_s = time.perf_counter() - t0
    ch1 = obs.counters()

    def cdelta(k):
        return ch1.get(k, 0.0) - ch0.get(k, 0.0)

    assert int(rep_w.histogram().sum()) == n_run
    assert bool(np.array_equal(rep_w.histogram(), rep.histogram()))
    emit(f"trace{n_refs}_warm_replay_refs_per_sec", n_run, warm_s, replay_s,
         path="trace_residency",
         refs_replayed=n_run, refs_requested=n_refs,
         shrunk=bool(n_run != n_refs),
         residency_hits=int(cdelta("residency.hit")),
         h2d_bytes_delta=int(cdelta("trace.h2d_bytes")))


def bench_trace(n_refs: int) -> None:
    """BASELINE config 5: dynamic trace replay at 1e9 refs, streamed from
    disk (pluss.trace.replay_file) vs the native replay_trace on the same
    addresses.  The trace file is generated once and cached in .bench/."""
    from pluss import obs, trace

    path = ensure_trace(n_refs)
    c_init = obs.counters()   # compile stamp covers warmup + replay
    # warmup on a short prefix: the prefix discovers the same working set,
    # so the full run below hits the jit cache at the same table shape.
    # (One full timed run, not best-of-N: the tunneled TPU's throughput
    # varies several-fold over minutes, so N runs at this scale could eat
    # the whole bench budget without improving the estimate.)
    warm_refs = 32 * (1 << 20)
    t0 = time.perf_counter()
    warm = trace.replay_file(path, limit_refs=warm_refs)
    warm_s = time.perf_counter() - t0
    log(f"bench: trace warmup (incl. compile) {warm_s:.2f}s"
        f" over {warm.total_count} prefix refs")
    # the tunneled h2d feed's throughput swings from ~30 MB/s to <1 MB/s
    # between runs; at the bottom, 1e9 refs would take hours.  Project from
    # the warmup and shrink the replayed prefix to a wall-clock budget —
    # the metric VALUE is a rate either way, and the name carries the
    # actual ref count so a shrunk run is never mistaken for the full one.
    budget_s = min(float(os.environ.get("PLUSS_BENCH_TRACE_BUDGET_S", 900)),
                   max(remaining_s() - 30, 60))  # leave margin to finish
    rate = warm.total_count / max(warm_s, 1e-9)
    n_run = n_refs
    if n_refs / rate > budget_s:
        # the first warmup's rate includes compile + table-growth retraces;
        # re-time a short post-compile prefix so the projection reflects
        # the steady feed before shrinking
        t0 = time.perf_counter()
        trace.replay_file(path, limit_refs=8 * (1 << 20))
        rate = max(rate, 8 * (1 << 20) / max(time.perf_counter() - t0, 1e-9))
        if n_refs / rate > budget_s:
            n_run = max(warm_refs, int(rate * budget_s))
            log(f"bench: projected {n_refs / rate:.0f}s for {n_refs} refs "
                f"at the current feed rate; shrinking to {n_run} refs "
                f"(~{budget_s:.0f}s budget)")
    t0 = time.perf_counter()
    # the deadline (1.3x the projected budget) is the backstop for the
    # feed SLOWING mid-run — a pre-run projection cannot see that
    # (observed: projected at ~23 MB/s, finished at ~5 MB/s, 3x over)
    from pluss.resilience import replay_file_resilient

    c0 = obs.counters()
    rep = replay_file_resilient(
        path, limit_refs=n_run,
        deadline_s=min(budget_s * 1.3, max(remaining_s() - 30, 1)))
    best_s = time.perf_counter() - t0
    n_run = rep.total_count
    log(f"bench: {n_run} refs over {rep.n_lines} line slots")
    # the telemetry breakdown of the measured region, straight onto the
    # metric line: feed_stall_frac is the feed-bound diagnosis (r05's
    # 0.34x was BELIEVED h2d-bound; now the record says where the seconds
    # went), resolvable offline too via `pluss stats` on the stream
    c1 = obs.counters()
    obs_extra: dict = {}
    if obs.enabled():
        def delta(k):
            return c1.get(k, 0.0) - c0.get(k, 0.0)

        stall, h2d_s = delta("trace.prefetch_stall_s"), delta("trace.h2d_s")
        wire_b, dev_b = delta("trace.h2d_bytes"), delta("trace.device_bytes")
        obs_extra = {
            "feed_stall_frac": round_keep(stall / best_s, 4),
            "device_frac": round_keep(delta("trace.device_s") / best_s, 4),
            "h2d_mb_s": round_keep(wire_b / 1e6 / h2d_s, 2)
            if h2d_s > 0 else None,
            # wire-vs-device compression ratio of the feed (1.33 = the
            # plain u24 pack; higher = the d24v wire is earning its keep)
            "wire_ratio": round_keep(dev_b / wire_b, 3) if wire_b else None,
        }
    # the feed configuration the rate was measured under — read off the
    # RESULT (replay_file stamps its effective values, surviving ladder
    # rungs and backend flips), not re-resolved process defaults —
    # straight on the metric line so the BENCH_r0x trajectory records
    # the gap-closure setup (not just its outcome)
    obs_extra["wire"] = rep.wire or trace._resolve_wire(None)
    obs_extra["feed_workers"] = (rep.feed_workers
                                 or trace._resolve_feed_workers(None))
    # streamed-vs-resident gap (r13): how much the residency store's warm
    # path buys over this very streamed rate (<1 = streamed is slower;
    # null when the resident metric was skipped this round)
    res_rate = _RESIDENT_RATE.get(n_refs)
    obs_extra["streamed_vs_resident_ratio"] = (
        round_keep((n_run / best_s) / res_rate, 4) if res_rate else None)
    # native replay is linear in refs, so one measured (refs, seconds) pair
    # scales to whatever prefix the feed budget allowed this round
    rate = native_trace_rate(path)
    base_s = n_run / rate if rate else None
    # the metric NAME keeps the REQUESTED size so round-to-round tracking
    # stays keyed on one string; refs_requested + shrunk let downstream
    # tooling filter budget-shrunk runs without parsing stderr
    emit(f"trace{n_refs}_replay_refs_per_sec", n_run, best_s, base_s,
         path="trace_stream", degradations=tuple(rep.degradations),
         refs_replayed=n_run, refs_requested=n_refs,
         shrunk=bool(n_run != n_refs), **compile_stamp(c_init), **obs_extra)


def bench_pallas(n_refs: int) -> None:
    """Fused-kernel A/B headline (r19): the same streamed replay over the
    same trace prefix with the fused Pallas pipeline (event histogram +
    d24v decode) forced ON vs forced OFF — ``vs_baseline`` IS the fused
    advantage (>1: the fused kernels win).  Skipped with a log line when
    either kernel fails its compile probe on this backend: production
    would be running the loud XLA fallback, and the A/B would measure
    XLA vs XLA."""
    import numpy as np

    from pluss import trace
    from pluss.ops import pallas_decode, pallas_events
    from pluss.utils import envknob

    run_refs = min(n_refs, 64 * (1 << 20))
    path = ensure_trace(n_refs)
    saved = {k: os.environ.get(k)
             for k in ("PLUSS_PALLAS_EVENTS", "PLUSS_PALLAS_DECODE")}

    def set_flag(flag: str | None) -> None:
        for k in saved:
            if flag is None:
                if saved[k] is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = saved[k]
            else:
                os.environ[k] = flag
        envknob._parse_bool.cache_clear()

    try:
        set_flag("1")
        pallas_events.reset_probe()
        pallas_decode.reset_probe()
        if not (pallas_events.probe_ok() and pallas_decode.probe_ok()):
            log("bench: pallas A/B skipped — a fused kernel failed its "
                "compile probe (XLA fallback is the production path)")
            return

        def timed(label: str):
            trace.replay_file(path, limit_refs=run_refs,
                              wire="d24v")      # warm: compile + run
            t0 = time.perf_counter()
            r = trace.replay_file(path, limit_refs=run_refs, wire="d24v")
            dt = time.perf_counter() - t0
            log(f"bench: pallas A/B {label}: "
                f"{r.total_count / dt:.3e} refs/s")
            return r, dt

        fused, fused_s = timed("fused")
        set_flag("0")
        xla, xla_s = timed("xla")
    finally:
        set_flag(None)
    emit(f"trace{run_refs}_pallas_refs_per_sec", fused.total_count,
         fused_s, xla_s, path="trace_stream_fused",
         degradations=tuple(fused.degradations),
         bit_identical=bool(np.array_equal(fused.hist, xla.hist)))


def bench_autotune() -> None:
    """Autotune calibration-cost headline (r19): wall seconds one FORCED
    geometry calibration costs this runtime, with the persisted winner on
    the line.  The sidecar lands beside the .bench AOT sidecars, so every
    later bench/driver run on this box consults it for free."""
    from pluss import autotune

    t0 = time.perf_counter()
    doc = autotune.calibrate(n_refs=1 << 20, force=True)
    cal_s = time.perf_counter() - t0
    log(f"bench: autotune calibrated in {cal_s:.1f}s -> "
        f"{doc['geometry']} ({doc['refs_per_sec']:.3e} refs/s)")
    emit_record({
        "metric": "autotune_calibration_s",
        "value": round_keep(cal_s, 3),
        "unit": "s",
        "vs_baseline": None,
        "path": "autotune",
        "degradations": [],
        "geometry": doc["geometry"],
        "winner_refs_per_sec": round_keep(doc["refs_per_sec"], 1),
    })


def bench_multichip(trace_refs: int) -> None:
    """Multi-chip scale-out headlines (round r09 on): refs/s of the
    work-stealing sharded dispatch vs the single-device engine on the
    quad nests (cholesky/lu — the straggler-bound surface) and the
    streamed headline trace, with ``scaling_efficiency`` and steal stats
    on every line.  Measured in-process when this process already sees a
    multi-device backend; otherwise re-measured in a subprocess on an
    8-fake-device CPU mesh (XLA parses the host-device-count flag once
    per process), clearly labeled ``cpu_fake8`` — either way the record
    carries a MEASUREMENT, not a dry-run ok-bit."""
    import jax

    from pluss import multichip_smoke

    if len(jax.devices()) >= 2:
        multichip_smoke.bench_lines(min(trace_refs, 1 << 27),
                                    label_refs=trace_refs)
        return
    # single visible device (the tunneled TPU): subprocess on a virtual
    # CPU mesh.  The child gets its OWN telemetry sink — inheriting the
    # parent's would truncate the live stream (Telemetry opens 'w').
    budget = max(60, min(int(remaining_s() - 30), 420))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PLUSS_TELEMETRY": ".bench/multichip_telemetry.jsonl"}
    env.pop("PLUSS_XPROF", None)
    env.pop("PLUSS_PROM", None)
    cmd = [sys.executable, "-m", "pluss.multichip_smoke", "--bench",
           "--devices", "8", "--trace-refs", str(min(trace_refs, 1 << 22)),
           "--label-refs", str(trace_refs)]
    log(f"bench: multichip measured in a subprocess (8 fake CPU devices, "
        f"budget {budget}s)")
    try:
        out = subprocess.run(cmd, env=env, capture_output=True, text=True,
                             timeout=budget, check=True)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired) as e:
        tail = (getattr(e, "stderr", "") or "")[-400:]
        log(f"bench: multichip subprocess failed: {e}; stderr tail: {tail}")
        return
    for ln in out.stderr.splitlines():
        if ln.strip():
            log(ln)
    for ln in out.stdout.splitlines():   # bench-schema JSON metric lines
        if not ln.strip():
            continue
        try:
            emit_record(json.loads(ln))   # re-stamp: child lines carry no
        except ValueError:                # schema/t_wall of their own
            print(ln, flush=True)


def bench_serve(n_requests: int = 48) -> None:
    """Serving headline (round r07 on): p50/p99 request latency and
    throughput of an in-process ``pluss serve`` daemon under a mixed,
    coalescible load — batched (max_batch=8) vs unbatched (max_batch=1)
    A/B, so the record shows what shared-dispatch coalescing buys.
    Latencies are CLIENT-side wall times (what a tenant experiences),
    after a per-key warmup so compile time doesn't pollute the quantiles;
    both arms run in one process, so plan/executable caches are equally
    warm and the A/B isolates the batching discipline itself."""
    import tempfile
    import threading

    from pluss.serve import Client, ServeConfig, Server

    pool = [
        {"model": "gemm", "n": 64, "threads": 4, "chunk": 4},
        {"model": "syrk", "n": 32, "threads": 4, "chunk": 4},
        {"model": "mvt", "n": 64, "threads": 4, "chunk": 4},
    ]
    results: dict[str, tuple[float, float, float]] = {}
    for label, mb in (("batched", 8), ("unbatched", 1)):
        sock = tempfile.mktemp(prefix="pluss_bench_serve_",
                               suffix=".sock")
        srv = Server(socket_path=sock,
                     config=ServeConfig(max_batch=mb, max_delay_ms=5.0,
                                        max_queue=256))
        srv.start()
        lat: list[float] = []
        lock = threading.Lock()
        try:
            with Client(sock) as c:   # warm plans + executables per key
                for q in pool:
                    c.request(q)

            def worker(chunk):
                with Client(sock) as c:
                    for q in chunk:
                        t0 = time.perf_counter()
                        r = c.request(q)
                        dt = (time.perf_counter() - t0) * 1e3
                        if r.get("ok"):
                            with lock:
                                lat.append(dt)

            reqs = [dict(pool[i % len(pool)]) for i in range(n_requests)]
            chunks = [reqs[i::4] for i in range(4)]
            threads = [threading.Thread(target=worker, args=(ch,))
                       for ch in chunks if ch]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
        finally:
            srv.shutdown()
        if not lat:
            raise RuntimeError(f"serve bench ({label}): no ok responses")
        lat.sort()
        p50 = lat[len(lat) // 2]
        p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
        results[label] = (p50, p99, len(lat) / wall)
        log(f"bench: serve {label} p50 {p50:.1f} ms, p99 {p99:.1f} ms, "
            f"{len(lat) / wall:.1f} req/s over {len(lat)} requests")
    b, u = results["batched"], results["unbatched"]
    # vs_baseline is "batched advantage": >1 means coalescing won
    for i, (name, unit, vs) in enumerate((
            ("serve_p50_ms", "ms", u[0] / b[0] if b[0] else None),
            ("serve_p99_ms", "ms", u[1] / b[1] if b[1] else None),
            ("serve_reqs_per_sec", "req/s", b[2] / u[2] if u[2] else None))):
        emit_record({
            "metric": name,
            "value": round_keep(b[i], 3),
            "unit": unit,
            "vs_baseline": round_keep(vs, 3),
            "path": "serve_batched",
            "degradations": [],
            "unbatched": round_keep(u[i], 3),
            "requests": n_requests,
        })


#: child of the cold/warm A/B: one fresh process, one full run, counters
#: on stdout.  ``engine.run`` (not the ladder) so the measured wall is
#: plan + compile + execute with nothing absorbing a failure silently.
_WARMSTART_CHILD = r"""
import json, os, sys, time
from pluss.utils.platform import enable_x64
enable_x64()
from pluss import engine, obs
from pluss.models import gemm
obs.configure(os.environ["PLUSS_TELEMETRY"])
n = int(sys.argv[1])
t0 = time.perf_counter()
res = engine.run(gemm(n))
wall = time.perf_counter() - t0
c = obs.counters()
print(json.dumps({
    "first_dispatch_s": wall,
    "compile_s": c.get("engine.compile_s", 0.0),
    "aot_hit": c.get("engine.plan_cache.aot_hit", 0.0),
    "aot_load_fail": c.get("engine.plan_cache.aot_load_fail", 0.0),
    "refs": int(res.max_iteration_count)}))
obs.flush_metrics()
"""


def bench_warmstart(n: int, cpu: bool) -> None:
    """Cold vs warm process start A/B (round r11 on): the same model's
    first-dispatch wall — plan + XLA compile + execute — in two FRESH
    subprocesses sharing one plan-cache directory (the multichip --bench
    subprocess discipline).  The first process is fully cold (fresh
    cache dir, no persistent XLA cache) and populates the AOT executable
    sidecars; the second restores them, so the pair records exactly what
    the warm-start layer buys a new daemon/worker/CLI process.  On a
    CPU-only box the A/B runs at a smaller n (the flagship size cannot
    execute on host), named accordingly — a measurement, not a dry run."""
    import shutil
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="pluss_warmstart_")

    def run_child(tag: str) -> dict:
        env = {**os.environ,
               "PLUSS_PLAN_CACHE_DIR": cache_dir,
               "PLUSS_TELEMETRY": f".bench/warmstart_{tag}.jsonl"}
        # isolate the layer under test: the sidecars must carry the warm
        # start alone, not a shared persistent XLA cache
        for k in ("PLUSS_XLA_CACHE_DIR", "PLUSS_XPROF", "PLUSS_PROM"):
            env.pop(k, None)
        if cpu:
            env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.run(
            [sys.executable, "-c", _WARMSTART_CHILD, str(n)],
            env=env, capture_output=True, text=True,
            timeout=max(120, min(int(remaining_s()), 900)), check=True)
        return json.loads(out.stdout.strip().splitlines()[-1])

    try:
        cold = run_child("cold")
        warm = run_child("warm")
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    ratio = cold["first_dispatch_s"] / warm["first_dispatch_s"] \
        if warm["first_dispatch_s"] else None
    log(f"bench: warmstart gemm{n}: cold {cold['first_dispatch_s']:.2f}s "
        f"(compile {cold['compile_s']:.2f}s) vs warm "
        f"{warm['first_dispatch_s']:.2f}s (compile {warm['compile_s']:.2f}s,"
        f" {int(warm['aot_hit'])} sidecar hit(s)) -> {ratio:.2f}x")
    for tag, rec, vs in (("cold", cold, None), ("warm", warm, ratio)):
        emit_record({
            "metric": f"gemm{n}_{tag}_start_s",
            "value": round_keep(rec["first_dispatch_s"], 3),
            "unit": "s",
            "vs_baseline": round_keep(vs, 3),
            "path": "engine.run(fresh process)" + ("+cpu" if cpu else ""),
            "degradations": [],
            "compile_s": round_keep(rec["compile_s"], 3),
            "warm": bool(rec["aot_hit"] > 0),
            "aot_hit": int(rec["aot_hit"]),
            "aot_load_fail": int(rec["aot_load_fail"]),
            "refs": rec["refs"],
        })


def bench_serve_warm(n: int = 64) -> None:
    """What ``--warm`` buys a daemon's FIRST tenant (round r11 on): start
    an in-process server with background warmup, wait for warm_done, and
    measure the very first request's client-side latency — the cold-start
    SLO number the serving story was missing."""
    import tempfile

    from pluss import obs
    from pluss.serve import Client, ServeConfig, Server

    sock = tempfile.mktemp(prefix="pluss_bench_servewarm_", suffix=".sock")
    srv = Server(socket_path=sock,
                 config=ServeConfig(warm=f"gemm:{n}", max_batch=8))
    srv.start()
    try:
        deadline = time.monotonic() + max(
            60, min(remaining_s() * 0.5, 600))
        while time.monotonic() < deadline:
            c = obs.counters()
            if c.get("serve.warmed", 0) + c.get("serve.warm_fail", 0) >= 1:
                break
            time.sleep(0.2)
        with Client(sock) as cl:
            t0 = time.perf_counter()
            r = cl.request({"model": "gemm", "n": n})
            ms = (time.perf_counter() - t0) * 1e3
    finally:
        srv.shutdown()
    if not r.get("ok"):
        raise RuntimeError(f"serve warm first request failed: {r}")
    warmed = bool(obs.counters().get("serve.warmed", 0))
    log(f"bench: serve --warm first request {ms:.1f} ms "
        f"(warmed={warmed})")
    emit_record({
        "metric": "serve_warm_first_request_ms",
        "value": round_keep(ms, 3),
        "unit": "ms",
        "vs_baseline": None,
        "path": "serve(--warm gemm)",
        "degradations": [],
        "warmed": warmed,
    })


def bench_serve_trace_warm(n_refs: int = 1 << 22,
                           n_requests: int = 8) -> None:
    """Warm-trace serving headline (r13): p50 client-side latency of
    REPEAT trace requests against an in-process daemon riding the
    residency store — the first request pays streaming + stage-through
    population, every repeat replays the HBM entry with zero feed bytes.
    The cold first latency rides the line as the baseline, so the record
    shows what residency buys a trace tenant."""
    import tempfile

    from pluss import obs, trace
    from pluss.serve import Client, ServeConfig, Server

    path = ensure_trace(n_refs)
    # size the request window so ONE staging batch covers the trace: at
    # the default 2^20 window a small trace pads to a 16M-ref batch and
    # the kernel (identical warm and cold) drowns the residency signal
    win = max(1 << 14, n_refs // trace.WINDOWS_PER_BATCH)
    sock = tempfile.mktemp(prefix="pluss_bench_servetrace_", suffix=".sock")
    srv = Server(socket_path=sock, config=ServeConfig(max_batch=4))
    srv.start()
    c0 = obs.counters()
    cold = None
    lat: list[float] = []
    try:
        with Client(sock) as c:
            for i in range(n_requests):
                t0 = time.perf_counter()
                r = c.request({"trace": path, "window": win,
                               "id": f"warmtrace-{i}"})
                dt = (time.perf_counter() - t0) * 1e3
                if not r.get("ok"):
                    raise RuntimeError(f"serve trace request failed: {r}")
                if i == 0:
                    cold = dt
                else:
                    lat.append(dt)
    finally:
        srv.shutdown()
    lat.sort()
    p50 = lat[len(lat) // 2]
    hits = int(obs.counters().get("residency.hit", 0)
               - c0.get("residency.hit", 0))
    log(f"bench: serve trace cold {cold:.1f} ms, warm p50 {p50:.1f} ms "
        f"over {len(lat)} repeats ({hits} residency hits)")
    emit_record({
        "metric": "serve_trace_warm_p50_ms",
        "value": round_keep(p50, 3),
        "unit": "ms",
        "vs_baseline": round_keep(cold / p50, 3) if p50 else None,
        "path": "serve(trace, resident)",
        "degradations": [],
        "cold_first_ms": round_keep(cold, 3),
        "residency_hits": hits,
        "refs": n_refs,
    })


def bench_import(reps: int = 3) -> None:
    """Frontend ingestion throughput (round r08 on): parse + lower +
    share-span derivation + PR-1 analyzer gate for the checked-in
    PolyBench pragma-C corpus (pluss/frontend/polybench.py), reported as
    specs/sec.  Pure host work — a sanity rate recording that the
    authoring path stays interactive (thousands of user-submitted nests
    per daemon-minute), not a device metric."""
    from pluss.frontend import polybench

    specs = polybench.import_polybench()   # warmup: imports, regex, jit
    t0 = time.perf_counter()
    for _ in range(reps):
        polybench.import_polybench()
    dt = time.perf_counter() - t0
    n = reps * len(specs)
    log(f"bench: frontend imported {len(specs)} polybench families x"
        f"{reps} in {dt:.2f}s ({n / dt:.1f} specs/s)")
    emit_record({
        "metric": "import_polybench_specs_per_sec",
        "value": round_keep(n / dt, 3),
        "unit": "specs/s",
        "vs_baseline": None,
        "path": "frontend.import(c)+lint",
        "degradations": [],
        "spec_source": "c",
        "families": sorted(specs),
    })


def bench_predict(check_n: int = 16) -> None:
    """Static-prediction headlines (round r12 on): wall time of the
    sampling-free symbolic MRC path (pluss/analysis/ri.py) on the flagship
    gemm-1024 shape — zero device dispatches, so this is the latency a
    `pluss predict` / serve-admission consumer pays instead of a sampled
    engine run — plus the max pointwise |predicted - engine| MRC error
    across the whole registry at a cross-checkable size (bit-identical
    histograms make this float-summation-order noise, ~1e-16; anything
    larger is a derivation bug)."""
    import numpy as np

    from pluss import cri, engine
    from pluss import mrc as mrc_mod
    from pluss.analysis import ri
    from pluss.config import DEFAULT
    from pluss.models import REGISTRY, gemm

    spec = gemm(1024)
    t0 = time.perf_counter()
    rep = ri.predict(spec, DEFAULT)
    dt = time.perf_counter() - t0
    method = rep.prediction.method
    log(f"bench: static predict gemm1024 ({method}): {dt * 1e3:.0f} ms "
        f"for {rep.prediction.accesses} accesses, zero device dispatches")
    emit_record({
        "metric": "gemm1024_static_predict_ms",
        "value": round_keep(dt * 1e3, 3),
        "unit": "ms",
        "vs_baseline": None,
        "path": f"analysis.ri.predict({method})",
        "degradations": [],
        "spec_source": "registry",
        "derivable": rep.prediction.derivable,
        "plateau_in_bracket": rep.plateau_in_bracket,
    })

    max_err, worst, n_checked = 0.0, None, 0
    for name in sorted(REGISTRY):
        s = REGISTRY[name](check_n)
        r = ri.predict(s, DEFAULT)
        if not r.prediction.derivable:
            continue
        res = engine.run(s, DEFAULT)
        theirs = mrc_mod.aet_mrc(
            cri.distribute(res.noshare_list(), res.share_list(),
                           DEFAULT.thread_num), DEFAULT)
        m = min(len(r.curve), len(theirs))
        err = float(np.max(np.abs(np.asarray(r.curve[:m])
                                  - np.asarray(theirs[:m]))))
        n_checked += 1
        if err > max_err:
            max_err, worst = err, name
    log(f"bench: predict max abs MRC error vs engine over {n_checked} "
        f"families at n={check_n}: {max_err:.2e}"
        + (f" ({worst})" if worst else ""))
    emit_record({
        "metric": "predict_max_abs_err",
        # UNROUNDED magnitudes survive (the r5 round_keep lesson): a
        # bit-identical histogram gives ~1e-16 summation-order noise here
        "value": round_keep(max_err, 9),
        "unit": "abs_mrc_error",
        "vs_baseline": None,
        "path": "analysis.ri.predict vs engine.run",
        "degradations": [],
        "spec_source": "registry",
        "families_checked": n_checked,
        "n": check_n,
        "worst_family": worst,
    })


def bench_cotenancy(n: int = 16) -> None:
    """Co-tenancy composition headlines (round r15 on): wall time of the
    full `pluss cotenancy` pipeline (derive -> heterogeneous-rate CRI
    composition -> AET read-off) on the gemm+syrk pair — pure host math,
    the latency a serve interference advisory pays — plus the composed
    curves' max pointwise error against the interleaved schedule-
    simulation oracle (exact LRU stack distances on the merged stream)."""
    import numpy as np

    from pluss.analysis import interference as itf
    from pluss.config import DEFAULT

    t0 = time.perf_counter()
    inputs, _ = itf.from_models(["gemm", "syrk"], DEFAULT, n=n)
    rep = itf.compose(inputs, DEFAULT)
    dt = time.perf_counter() - t0
    log(f"bench: cotenancy gemm+syrk compose at n={n}: {dt * 1e3:.0f} ms, "
        f"{len(rep.verdicts)} verdict(s), zero device dispatches")
    emit_record({
        "metric": "cotenancy_predict_ms",
        "value": round_keep(dt * 1e3, 3),
        "unit": "ms",
        "vs_baseline": None,
        "path": "analysis.interference.compose(gemm+syrk)",
        "degradations": [],
        "spec_source": "registry",
        "n": n,
        "verdicts": [v.code for v in rep.verdicts],
    })

    oracle = itf.oracle_mrcs(inputs, DEFAULT)
    max_err, worst = 0.0, None
    for w, pred, orc in zip(inputs, rep.degraded_curves, oracle):
        m = min(len(pred), len(orc))
        err = float(np.max(np.abs(np.asarray(pred[:m]) - orc[:m])))
        if err > max_err:
            max_err, worst = err, w.name
    log(f"bench: cotenancy max abs composed-MRC error vs oracle at "
        f"n={n}: {max_err:.3g}" + (f" ({worst})" if worst else ""))
    emit_record({
        "metric": "cotenancy_max_abs_err",
        "value": round_keep(max_err, 9),
        "unit": "abs_mrc_error",
        "vs_baseline": None,
        "path": "analysis.interference vs schedule-simulation oracle",
        "degradations": [],
        "spec_source": "registry",
        "n": n,
        "worst_workload": worst,
    })


def bench_tune(n: int = 128) -> None:
    """Schedule-tuning headline (round r16 on): wall time of the full
    proof-carrying `pluss tune` search (pluss/analysis/tune.py) on gemm
    over the default (threads, chunk) space — footprint floors, dominance
    pruning, per-fiber derivation, hierarchy read-offs, verdict — with
    the engine's dispatch counter witnessing that the whole search is
    host math (zero device dispatches, by construction and by check)."""
    from pluss import engine
    from pluss.analysis import tune as tune_mod
    from pluss.models import gemm

    spec = gemm(n)
    d0 = engine.DEVICE_DISPATCHES
    t0 = time.perf_counter()
    rep = tune_mod.tune(spec)
    dt = time.perf_counter() - t0
    dispatched = engine.DEVICE_DISPATCHES - d0
    if dispatched:
        raise RuntimeError(
            f"tune search touched the device: {dispatched} dispatch(es)")
    log(f"bench: tune gemm{n} over {len(rep.candidates)} candidates: "
        f"{dt * 1e3:.0f} ms host-only ({rep.n_pruned} pruned, "
        f"{rep.n_derived} derived, verdict {rep.code})")
    emit_record({
        "metric": "tune_gemm_ms",
        "value": round_keep(dt * 1e3, 3),
        "unit": "ms",
        "vs_baseline": None,
        "path": "analysis.tune.tune(gemm)",
        "degradations": [],
        "spec_source": "registry",
        "n": n,
        "candidates": len(rep.candidates),
        "pruned": rep.n_pruned,
        "derived": rep.n_derived,
        "verdict": rep.code,
        "device_dispatches": dispatched,
    })


def bench_transform(n: int = 64) -> None:
    """Loop-transformation headlines (round r18 on): wall time of the
    full transform-space search (`pluss tune --transforms` —
    pluss/analysis/transform.py: legality proofs over the dependence
    vectors, then one tune pass per proven-legal transform) on gemm at a
    1 KB LLC, with the engine dispatch counter witnessing the search is
    host math; plus the headline the search exists to find — the static
    LLC miss-ratio delta of the best proven-legal tiled schedule vs the
    untransformed PL901 winner (negative = the transform wins)."""
    from pluss import engine
    from pluss.analysis import transform as tf
    from pluss.analysis import tune as tune_mod
    from pluss.model import hierarchy as hier_mod
    from pluss.models import gemm

    spec = gemm(n)
    hier = hier_mod.HierarchyConfig(levels_kb=(1,), assoc=0, policy="lru")
    cands = tune_mod.space((1, 2, 4), (1, 4))
    d0 = engine.DEVICE_DISPATCHES
    t0 = time.perf_counter()
    rep = tf.search_transforms(spec, candidates=cands, hier=hier)
    dt = time.perf_counter() - t0
    dispatched = engine.DEVICE_DISPATCHES - d0
    if dispatched:
        raise RuntimeError(
            f"transform search touched the device: {dispatched} "
            "dispatch(es)")
    n_legal = sum(1 for e in rep.entries if e.transform.code == "PL951")
    log(f"bench: transform search gemm{n}: {dt * 1e3:.0f} ms host-only "
        f"({len(rep.entries)} transform(s), {n_legal} legal, best "
        f"{rep.best.transform.label() if rep.best else 'identity'}, "
        f"delta {rep.delta})")
    emit_record({
        "metric": "transform_search_ms",
        "value": round_keep(dt * 1e3, 3),
        "unit": "ms",
        "vs_baseline": None,
        "path": "analysis.transform.search_transforms(gemm)",
        "degradations": [],
        "spec_source": "registry",
        "n": n,
        "transforms": len(rep.entries),
        "legal": n_legal,
        "device_dispatches": dispatched,
    })
    if rep.best is not None and rep.delta is not None:
        emit_record({
            "metric": "gemm_tiled_predicted_mr_delta",
            "value": round_keep(rep.delta, 9),
            "unit": "miss_ratio_delta",
            "vs_baseline": None,
            "path": "analysis.transform.search_transforms(gemm) best vs "
                    "untransformed PL901 winner",
            "degradations": [],
            "spec_source": "registry",
            "n": n,
            "best_transform": rep.best.transform.label(),
            "best_schedule": rep.best.tune.winner.candidate.label(),
            "target_kb": rep.target_kb,
        })


def bench_serve_placement(n_requests: int = 48) -> None:
    """Interference-aware placement A/B (round r16 on): client-side p99
    under an ADVERSARIAL co-tenant mix — one tenant's backlog alternating
    workloads whose pairwise composed interference differs, so the
    placement chooser (PLUSS_SERVE_PLACEMENT=on) has real reordering
    decisions — against the advisory-only control (off, the default) as
    ``vs_baseline``.  Both arms run in one process with equally warm
    caches; max_batch=1 keeps every dispatch a distinct placement
    decision.  Ordering is the only degree of freedom, so any p99 delta
    is the placement discipline itself.  The pair-cost memo is pre-warmed
    alongside the plan caches (a long-lived daemon pays each pair's
    derivation exactly once, bounded by the memo) so the A/B measures
    steady-state placement, not the one-time fill."""
    import tempfile
    import threading

    from pluss.serve import Client, ServeConfig, Server
    from pluss.serve.protocol import parse_request

    # adversarial mix: distinct dispatch keys from one tenant, queued
    # deep enough that the chooser sees a multi-request backlog
    pool = [
        {"model": "gemm", "n": 32, "threads": 4, "chunk": 4},
        {"model": "stencil3d", "n": 32, "threads": 4, "chunk": 4},
        {"model": "atax", "n": 32, "threads": 4, "chunk": 4},
        {"model": "syrk", "n": 32, "threads": 4, "chunk": 4},
    ]
    results: dict[str, tuple[float, float]] = {}
    for label, knob in (("placement", "on"), ("advisory_only", "off")):
        sock = tempfile.mktemp(prefix="pluss_bench_place_", suffix=".sock")
        prev = os.environ.get("PLUSS_SERVE_PLACEMENT")
        os.environ["PLUSS_SERVE_PLACEMENT"] = knob
        try:
            srv = Server(socket_path=sock,
                         config=ServeConfig(max_batch=1, max_queue=256))
        finally:
            if prev is None:
                os.environ.pop("PLUSS_SERVE_PLACEMENT", None)
            else:
                os.environ["PLUSS_SERVE_PLACEMENT"] = prev
        srv.start()
        lat: list[float] = []
        lock = threading.Lock()
        try:
            with Client(sock) as c:   # warm plans + executables per key
                for q in pool:
                    c.request(q)
            if srv.batcher.placer is not None:   # warm the pair-cost memo
                parsed = [parse_request(dict(q)) for q in pool]
                for a in parsed:
                    srv.batcher.placer.note_dispatch(a)
                    srv.batcher.placer.choose(tuple(parsed))
                srv.batcher.placer.note_dispatch(parsed[0])

            def worker(chunk):
                with Client(sock) as c:
                    for q in chunk:
                        t0 = time.perf_counter()
                        r = c.request(q)
                        dt = (time.perf_counter() - t0) * 1e3
                        if r.get("ok"):
                            with lock:
                                lat.append(dt)

            reqs = [dict(pool[i % len(pool)]) for i in range(n_requests)]
            chunks = [reqs[i::4] for i in range(4)]
            threads = [threading.Thread(target=worker, args=(ch,))
                       for ch in chunks if ch]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            srv.shutdown()
        if not lat:
            raise RuntimeError(
                f"serve placement bench ({label}): no ok responses")
        lat.sort()
        results[label] = (lat[len(lat) // 2],
                          lat[min(len(lat) - 1, int(0.99 * len(lat)))])
        log(f"bench: serve placement={knob} p50 {results[label][0]:.1f} "
            f"ms, p99 {results[label][1]:.1f} ms over {len(lat)} requests")
    on, off = results["placement"], results["advisory_only"]
    emit_record({
        "metric": "serve_placement_p99_ms",
        "value": round_keep(on[1], 3),
        "unit": "ms",
        # >1 means placement-aware beat the advisory-only control
        "vs_baseline": round_keep(off[1] / on[1] if on[1] else None, 3),
        "path": "serve(PLUSS_SERVE_PLACEMENT=on)",
        "degradations": [],
        "advisory_only_p99_ms": round_keep(off[1], 3),
        "placement_p50_ms": round_keep(on[0], 3),
        "advisory_only_p50_ms": round_keep(off[0], 3),
        "requests": n_requests,
    })


def main() -> int:
    os.chdir(os.path.dirname(os.path.abspath(__file__)))
    # persistent XLA compilation cache: the flagship compiles cost minutes
    # over the tunnel; caching them in-repo makes repeat bench runs (and the
    # driver's round-end run on this same box) warm-start in seconds
    from pluss.utils.platform import enable_x64

    enable_x64()
    # telemetry on by default for bench runs: the event stream is part of
    # the round record (feed_stall_frac etc. on the metric lines come from
    # counter deltas; `pluss stats .bench/telemetry.jsonl` re-derives the
    # full breakdown offline).  PLUSS_TELEMETRY overrides the sink path.
    from pluss import obs

    if not obs.enabled():
        obs.configure(".bench/telemetry.jsonl")
    from pluss import plancache

    plancache.arm_xla_cache(os.path.abspath(".bench/jit_cache"),
                            min_compile_s=5.0)
    plat = probe_accelerator()
    if plat is None:
        from pluss.utils.platform import force_cpu

        force_cpu()
        log("bench: running CPU fallback at N=128")
    else:
        log(f"bench: accelerator platform {plat!r}")

    from pluss import cri, engine
    from pluss.config import DEFAULT
    from pluss.models import gemm, syrk

    from pluss.resilience import run_resilient

    def step_of(spec, backend="vmap"):
        def step():
            # the degradation ladder keeps the metric line alive under
            # OOM/compile failures (stamped, so a degraded number is
            # visible in the trajectory, never silently slower)
            res = run_resilient(spec, backend=backend)
            cri.distribute(res.noshare_list(), res.share_list(),
                           DEFAULT.thread_num)
            return res
        return step

    if plat is None:
        best_s, res, cstamp = timed_reps(step_of(gemm(128)), REPS, "gemm128")
        emit("gemm128_sampler_refs_per_sec_cpu_fallback",
             res.max_iteration_count, best_s,
             cached_native_s("gemm128", lambda: native_baseline_s(128)),
             path=engine.describe_path(gemm(128)),
             degradations=tuple(res.degradations),
             spec_source="registry", **cstamp,
             **analysis_fields(gemm(128)))
        try:
            bench_serve(24)
        except Exception as e:
            log(f"bench: serve metric failed: {e}")
        try:
            bench_import()
        except Exception as e:
            log(f"bench: import metric failed: {e}")
        if budget_ok("predict", 120):
            try:
                bench_predict()
            except Exception as e:
                log(f"bench: predict metric failed: {e}")
        if budget_ok("cotenancy", 60):
            try:
                bench_cotenancy()
            except Exception as e:
                log(f"bench: cotenancy metric failed: {e}")
        if budget_ok("tune", 60):
            try:
                bench_tune()
            except Exception as e:
                log(f"bench: tune metric failed: {e}")
        if budget_ok("transform", 60):
            try:
                bench_transform()
            except Exception as e:
                log(f"bench: transform metric failed: {e}")
        if budget_ok("serve_placement", 120):
            try:
                bench_serve_placement()
            except Exception as e:
                log(f"bench: serve placement metric failed: {e}")
        if budget_ok("warmstart", 180):
            try:
                bench_warmstart(128, cpu=True)
            except Exception as e:
                log(f"bench: warmstart metric failed: {e}")
        if budget_ok("serve_warm", 90):
            try:
                bench_serve_warm(24)
            except Exception as e:
                log(f"bench: serve warm metric failed: {e}")
        if budget_ok("serve_trace_warm", 90):
            try:
                bench_serve_trace_warm(1 << 20, n_requests=6)
            except Exception as e:
                log(f"bench: serve trace warm metric failed: {e}")
        if budget_ok("multichip", 240):
            try:
                bench_multichip(
                    int(os.environ.get("PLUSS_BENCH_TRACE_REFS",
                                       1_000_000_000)))
            except Exception as e:
                log(f"bench: multichip metric failed: {e}")
        # r19 headlines on the CPU fallback too (interpreter-mode Pallas;
        # a small trace keeps the A/B inside the fallback budget)
        if budget_ok("pallas_ab", 120):
            try:
                bench_pallas(1 << 22)
            except Exception as e:
                log(f"bench: pallas A/B metric failed: {e}")
        if budget_ok("autotune", 180):
            try:
                bench_autotune()
            except Exception as e:
                log(f"bench: autotune metric failed: {e}")
        return 0

    # headline FIRST (round 3's record has rc=124 with this metric still
    # queued): BASELINE.json config 2, GEMM 1024^3 (4.3e9 refs).  The
    # native baseline is budget-gated inside cached_native_s, so a cold
    # cache can degrade vs_baseline to null but can never block the line.
    # try/except so a mid-rep worker death still lets the aux metrics run
    # (a partial record beats an empty one).
    flagship = None
    flagship_extra: dict = {}
    try:
        best_s, res, cstamp = timed_reps(step_of(gemm(1024)), REPS,
                                         "gemm1024")
        flagship_extra.update(cstamp)
        try:  # label-only: must never sink an already-measured flagship
            flag_path = engine.describe_path(gemm(1024))
        except Exception as e:
            log(f"bench: describe_path(gemm1024) failed: {e}")
            flag_path = ""
        flagship = ("gemm1024_sampler_refs_per_sec",
                    res.max_iteration_count, best_s,
                    cached_native_s("gemm1024",
                                    lambda: native_baseline_s(1024)),
                    flag_path, tuple(res.degradations))
        # headline FIRST, stamps after: the analyzer stamp costs ~10 s
        # and must never stand between a measured flagship and its
        # emission (the rc=124 precedent) — the re-emission at the end
        # carries the stamped version
        emit(*flagship, spec_source="registry", **cstamp)
        flagship_extra.update(analysis_fields(gemm(1024)))
    except Exception as e:
        log(f"bench: FLAGSHIP gemm1024 metric failed: {e}")

    def native_s_of(key, spec):
        return cached_native_s(key, lambda: native_spec_s(spec))

    # mixed-coefficient metric (VERDICT r1 weak #1 / r2 task 1): syrk's
    # A refs are template-ineligible by construction; since round 3
    # they ride the interleave overlay (pluss.overlay) instead of the
    # device sort — same metric name as r01/r02 for comparability
    if budget_ok("syrk1024", 90):
        try:
            n_syrk = 1024
            best_s, res, cstamp = timed_reps(step_of(syrk(n_syrk)), 2,
                                             f"syrk{n_syrk}")
            emit(f"syrk{n_syrk}_sortpath_refs_per_sec",
                 res.max_iteration_count, best_s,
                 native_s_of("syrk1024", syrk(n_syrk)),
                 path=engine.describe_path(syrk(n_syrk)),
                 degradations=tuple(res.degradations),
                 spec_source="registry", **cstamp,
                 **analysis_fields(syrk(n_syrk)))
        except Exception as e:  # never let an aux metric sink the record
            log(f"bench: syrk metric failed: {e}")

    # triangular metric (VERDICT r2 task 4): bounded inner loops take the
    # clock-table + device-sort path — no template, no overlay.
    from pluss.models import syrk_triangular

    if budget_ok("syrktri1024", 180):
        try:
            spec_tri = syrk_triangular(1024)
            # default backend: engine auto-reroutes this over-ceiling plan
            # to the dispatch-sliced vmap path (r3's single-executable
            # multi-thread variants all killed the tunneled worker)
            best_s, res, cstamp = timed_reps(step_of(spec_tri), 1,
                                             "syrktri1024")
            emit("syrktri1024_sortpath_refs_per_sec",
                 res.max_iteration_count, best_s,
                 native_s_of("syrktri1024", spec_tri),
                 path=engine.describe_path(spec_tri),
                 degradations=tuple(res.degradations),
                 spec_source="registry", **cstamp,
                 **analysis_fields(spec_tri))
        except Exception as e:
            log(f"bench: triangular metric failed: {e}")

    # trace-replay metrics (VERDICT r1 weak #4 / BASELINE config 5):
    # device-only scan rate first (robust), then 1e9 refs streamed from
    # disk end-to-end (gated by the tunnel's h2d feed)
    if budget_ok("trace_device", 60):
        try:
            bench_trace_device()
        except Exception as e:
            log(f"bench: trace device metric failed: {e}")
    trace_refs = int(os.environ.get("PLUSS_BENCH_TRACE_REFS", 1_000_000_000))
    if budget_ok("trace_resident", 120):
        try:
            bench_trace_resident(trace_refs)
        except Exception as e:
            log(f"bench: trace resident metric failed: {e}")
    if budget_ok("trace_e2e", 150):  # bench_trace self-shrinks to the budget
        try:
            bench_trace(trace_refs)
        except Exception as e:
            log(f"bench: trace metric failed: {e}")

    # fused-kernel A/B + autotune calibration cost (round r19 on): the
    # Pallas pipeline's measured advantage over the XLA path on this
    # backend, and what one forced geometry calibration costs (its winner
    # persists beside the .bench AOT sidecars for every later run)
    if budget_ok("pallas_ab", 180):
        try:
            bench_pallas(trace_refs)
        except Exception as e:
            log(f"bench: pallas A/B metric failed: {e}")
    if budget_ok("autotune", 240):
        try:
            bench_autotune()
        except Exception as e:
            log(f"bench: autotune metric failed: {e}")

    # multi-chip scale-out headlines (round r09 on): work-stealing sharded
    # dispatch vs single device on the quad nests + the streamed trace,
    # scaling_efficiency + steal stats stamped on every line
    if budget_ok("multichip", 300):
        try:
            bench_multichip(trace_refs)
        except Exception as e:
            log(f"bench: multichip metric failed: {e}")

    # warm-start headlines (round r11 on): what the persistent AOT
    # executable cache buys a FRESH process — cold vs warm first-dispatch
    # wall in two subprocesses sharing one plan-cache dir, plus the first
    # request latency of a --warm'ed daemon
    if budget_ok("warmstart", 300):
        try:
            bench_warmstart(1024, cpu=False)
        except Exception as e:
            log(f"bench: warmstart metric failed: {e}")
    if budget_ok("serve_warm", 120):
        try:
            bench_serve_warm(64)
        except Exception as e:
            log(f"bench: serve warm metric failed: {e}")
    # warm-trace serving headline (r13): repeat trace requests riding the
    # residency store vs the cold streamed first request
    if budget_ok("serve_trace_warm", 120):
        try:
            bench_serve_trace_warm()
        except Exception as e:
            log(f"bench: serve trace warm metric failed: {e}")

    # serving headline (round r07 on): what a tenant of `pluss serve`
    # experiences — p50/p99 latency and req/s, batched vs unbatched A/B
    if budget_ok("serve", 90):
        try:
            bench_serve()
        except Exception as e:
            log(f"bench: serve metric failed: {e}")

    # frontend ingestion throughput (round r08 on): host-only, ~seconds
    if budget_ok("import_polybench", 30):
        try:
            bench_import()
        except Exception as e:
            log(f"bench: import metric failed: {e}")

    # static-prediction headlines (round r12 on): host-only symbolic MRC
    # latency on the flagship shape + registry-wide max error vs the engine
    if budget_ok("predict", 120):
        try:
            bench_predict()
        except Exception as e:
            log(f"bench: predict metric failed: {e}")

    # co-tenancy composition headlines (round r15 on): host-only compose
    # latency + composed-MRC error vs the schedule-simulation oracle
    if budget_ok("cotenancy", 60):
        try:
            bench_cotenancy()
        except Exception as e:
            log(f"bench: cotenancy metric failed: {e}")

    # schedule-tuning headlines (round r16 on): host-only proof-carrying
    # search latency (zero-dispatch witnessed) + the placement-aware vs
    # advisory-only serve p99 A/B under an adversarial co-tenant mix
    if budget_ok("tune", 60):
        try:
            bench_tune()
        except Exception as e:
            log(f"bench: tune metric failed: {e}")
    # transform-space search headline (round r18 on): host-only latency
    # + the best tiled schedule's static LLC miss-ratio delta
    if budget_ok("transform", 60):
        try:
            bench_transform()
        except Exception as e:
            log(f"bench: transform metric failed: {e}")
    if budget_ok("serve_placement", 120):
        try:
            bench_serve_placement()
        except Exception as e:
            log(f"bench: serve placement metric failed: {e}")

    # accuracy half of the north star (BASELINE.json: "miss-ratio-curve L2
    # error vs C++ baseline" within 1%): MRC of the TPU pipeline vs the
    # native C++ runtime on the reference workload.  The acc-mode byte-diff
    # tests prove histogram identity; this line puts the number in the
    # round record next to the speed half.  Deliberately LAST among the aux
    # metrics: on a tight budget the round-over-round comparable metrics
    # above must win the remaining budget over this (new in r4) line.
    if budget_ok("gemm_mrc_l2", 60):
        try:
            from pluss import mrc as mrc_mod
            from pluss import native

            res = engine.run(gemm(128))
            ri = cri.distribute(res.noshare_list(), res.share_list(),
                                DEFAULT.thread_num)
            ours = mrc_mod.aet_mrc(ri)
            if native.available(autobuild=True):
                theirs = native.run(gemm(128)).mrc()
                err = mrc_mod.l2_error(ours, theirs)
                log(f"bench: gemm128 MRC L2 error vs native C++: {err:.2e}")
                emit_record({
                    "metric": "gemm128_mrc_l2_error_vs_native",
                    # UNROUNDED: round(err, 9) erased the 1.39e-14 in the
                    # r5 record (ADVICE r5, BENCH_r05.json value 0.0)
                    "value": err, "unit": "relative_l2",
                    "vs_baseline": None,
                    "path": engine.describe_path(gemm(128)) + "+cri+aet",
                })
        except Exception as e:
            log(f"bench: mrc l2 metric failed: {e}")

    # re-emit the flagship LAST: the round record's parsed headline is the
    # final JSON line of the run (see BENCH_r02/r03 "parsed"), and an aux
    # metric must not displace the north-star number from it.  Identical
    # payload to the first emission — purely a record-ordering concern.
    if flagship is not None:
        log("bench: re-emitting flagship line as the record headline")
        emit(*flagship, spec_source="registry",
             **flagship_extra)
    return 0


if __name__ == "__main__":
    import signal

    def _sigterm(signum, frame):
        # a supervisor timeout must still leave a round record behind:
        # write what was measured so far, marked rc=124
        write_round_record(next_round_n(), 124)
        sys.exit(124)

    signal.signal(signal.SIGTERM, _sigterm)
    _rc = main()
    write_round_record(next_round_n(), _rc)
    sys.exit(_rc)
