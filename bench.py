"""Round benchmark: sampled refs/sec on the flagship GEMM workload.

Protocol (mirrors the reference's `speed` mode, /root/reference/src/main.rs:23-35):
time (sampler + CRI distribute) over 3 repetitions after one warmup (the warmup
is the XLA-compile analogue of the reference timing a prebuilt binary), then
report refs/sec = total simulated accesses / mean seconds.

`vs_baseline` is the speedup over the native C++ runtime (pluss/cpp) running
the SAME workload on this host — the stand-in for the reference's serialized
Rust/C++ backends (its Rayon/spawn backends hold whole-lifetime locks and run
sequentially, SURVEY.md Q2, so the native walk is a faithful proxy).

Prints exactly ONE JSON line on stdout; all diagnostics go to stderr.

Robustness: this image's sitecustomize registers a tunneled-TPU backend that
can hang indefinitely if the tunnel is wedged, so the accelerator is probed in
a subprocess with a hard timeout; on failure the bench degrades to the host CPU
(smaller N, still reported honestly under a distinct metric name).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

PROBE_TIMEOUT_S = 120
REPS = 3


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def probe_accelerator() -> str | None:
    """Killable accelerator probe (see pluss.utils.platform.probe_accelerator:
    a wedged TPU tunnel must not hang the bench)."""
    from pluss.utils.platform import probe_accelerator as probe

    plat = probe(PROBE_TIMEOUT_S)
    if plat is None:
        log("bench: no usable accelerator (wedged tunnel or CPU-only box)")
    return plat


def native_baseline_s(n: int) -> float | None:
    """Best seconds/run of the native C++ sampler+CRI at size n, or None."""
    from pluss import native

    try:
        ok = native.available(autobuild=True)  # incremental: no stale binary
    except RuntimeError as e:  # compile failure: report, never time stale code
        log(f"bench: native build failed: {e}")
        return None
    if not ok:
        log("bench: native toolchain unavailable")
        return None
    try:
        out = subprocess.run([native.BIN_PATH, "speed", str(n)],
                             capture_output=True,
                             text=True, timeout=3600, check=True).stdout
    except (OSError, subprocess.CalledProcessError,
            subprocess.TimeoutExpired) as e:
        log(f"bench: native baseline run failed: {e}")
        return None
    times = [float(m) for m in re.findall(r"NATIVE C\+\+: ([0-9.]+)", out)]
    return min(times) if times else None


def main() -> int:
    os.chdir(os.path.dirname(os.path.abspath(__file__)))
    plat = probe_accelerator()
    if plat is None:
        from pluss.utils.platform import force_cpu

        force_cpu()
        n, metric = 128, "gemm128_sampler_refs_per_sec_cpu_fallback"
        log("bench: running CPU fallback at N=128")
    else:
        # BASELINE.json config 2: GEMM 1024^3 speed mode (4.3e9 refs/run)
        n, metric = 1024, "gemm1024_sampler_refs_per_sec"
        log(f"bench: accelerator platform {plat!r}, N={n}")

    from pluss import cri, engine
    from pluss.config import DEFAULT
    from pluss.models import gemm

    spec = gemm(n)

    def step():
        res = engine.run(spec)
        cri.distribute(res.noshare_list(), res.share_list(),
                       DEFAULT.thread_num)
        return res

    t0 = time.perf_counter()
    res = step()  # warmup: compile + first run
    log(f"bench: warmup (incl. compile) {time.perf_counter() - t0:.2f}s; "
        f"{res.max_iteration_count} refs/run")

    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        step()
        times.append(time.perf_counter() - t0)
    # best-of-reps on BOTH sides: robust to transient host load, which would
    # otherwise inflate (or deflate) the speedup ratio
    best_s = min(times)
    refs_per_sec = res.max_iteration_count / best_s
    log(f"bench: per-rep {['%.3f' % t for t in times]} s, "
        f"best {refs_per_sec:.3e} refs/s")

    base_s = native_baseline_s(n)
    vs = None
    if base_s:
        vs = base_s / best_s  # same workload, same count: speedup = time ratio
        log(f"bench: native C++ baseline {base_s:.3f} s/run -> speedup {vs:.2f}x")

    print(json.dumps({
        "metric": metric,
        "value": round(refs_per_sec, 1),
        "unit": "refs/s",
        "vs_baseline": round(vs, 3) if vs is not None else None,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
