"""Round benchmark: sampled refs/sec on the flagship GEMM workload, plus the
sort-path metric (syrk — template-ineligible by construction).

Protocol (mirrors the reference's `speed` mode, /root/reference/src/main.rs:23-35):
time (sampler + CRI distribute) over repetitions after one warmup (the warmup
is the XLA-compile analogue of the reference timing a prebuilt binary), then
report refs/sec = total simulated accesses / best seconds.

`vs_baseline` is the speedup over the native C++ runtime (pluss/cpp) running
the SAME workload on this host — the stand-in for the reference's serialized
Rust/C++ backends (its Rayon/spawn backends hold whole-lifetime locks and run
sequentially, SURVEY.md Q2, so the native walk is a faithful proxy).

Prints one JSON line PER METRIC on stdout — the flagship GEMM line LAST (it
is the round's headline number); all diagnostics go to stderr.

Robustness: this image's sitecustomize registers a tunneled-TPU backend that
can hang indefinitely if the tunnel is wedged, so the accelerator is probed in
a subprocess with a hard timeout; on failure the bench degrades to the host CPU
(smaller N, still reported honestly under a distinct metric name).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

PROBE_TIMEOUT_S = 120
REPS = 3


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def probe_accelerator() -> str | None:
    """Killable accelerator probe (see pluss.utils.platform.probe_accelerator:
    a wedged TPU tunnel must not hang the bench)."""
    from pluss.utils.platform import probe_accelerator as probe

    plat = probe(PROBE_TIMEOUT_S)
    if plat is None:
        log("bench: no usable accelerator (wedged tunnel or CPU-only box)")
    return plat


def native_baseline_s(n: int) -> float | None:
    """Best seconds/run of the native C++ sampler+CRI at size n, or None."""
    from pluss import native

    try:
        ok = native.available(autobuild=True)  # incremental: no stale binary
    except RuntimeError as e:  # compile failure: report, never time stale code
        log(f"bench: native build failed: {e}")
        return None
    if not ok:
        log("bench: native toolchain unavailable")
        return None
    try:
        out = subprocess.run([native.BIN_PATH, "speed", str(n)],
                             capture_output=True,
                             text=True, timeout=3600, check=True).stdout
    except (OSError, subprocess.CalledProcessError,
            subprocess.TimeoutExpired) as e:
        log(f"bench: native baseline run failed: {e}")
        return None
    times = [float(m) for m in re.findall(r"NATIVE C\+\+: ([0-9.]+)", out)]
    return min(times) if times else None


def timed_reps(step, reps: int, label: str):
    """(best seconds, last result) of ``reps`` timed calls after one warmup."""
    t0 = time.perf_counter()
    res = step()  # warmup: compile + first run
    log(f"bench: {label} warmup (incl. compile) "
        f"{time.perf_counter() - t0:.2f}s; {res.max_iteration_count} refs/run")
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        step()
        times.append(time.perf_counter() - t0)
    log(f"bench: {label} per-rep {['%.3f' % t for t in times]} s")
    # best-of-reps on BOTH sides: robust to transient host load, which would
    # otherwise inflate (or deflate) the speedup ratio
    return min(times), res


def emit(metric: str, refs: int, best_s: float, base_s: float | None,
         **extra) -> None:
    vs = base_s / best_s if base_s else None
    refs_per_sec = refs / best_s
    log(f"bench: {metric} best {refs_per_sec:.3e} refs/s"
        + (f", native {base_s:.3f} s/run -> speedup {vs:.2f}x" if vs else ""))
    print(json.dumps({
        "metric": metric,
        "value": round(refs_per_sec, 1),
        "unit": "refs/s",
        "vs_baseline": round(vs, 3) if vs is not None else None,
        **extra,
    }), flush=True)


def native_spec_s(spec, reps: int = 2) -> float | None:
    """Best seconds/run of the native walk on an arbitrary spec via the
    ctypes runtime (the standalone binary's CLI only builds the GEMM spec)."""
    from pluss import native

    try:
        if not native.available(autobuild=True):
            return None
    except RuntimeError as e:
        log(f"bench: native build failed: {e}")
        return None
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        native.run(spec)
        times.append(time.perf_counter() - t0)
    return min(times)


def synth_trace(path: str, n_refs: int, seed: int = 0) -> None:
    """Write a synthetic DynamoRIO-like byte-address trace (packed LE u64).

    Two-tier working set (hot 2^16 lines / warm 2^22 lines, shuffled per
    batch) — gives a two-knee MRC and a realistic reuse mix.  Written in
    128 MB batches so generation is memory-bounded at any n_refs.
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    batch = 1 << 24
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        written = 0
        while written < n_refs:
            m = min(batch, n_refs - written)
            hot = rng.integers(0, 1 << 16, m // 2, dtype=np.int64)
            warm = rng.integers(0, 1 << 22, m - m // 2, dtype=np.int64)
            lines = np.concatenate([hot, warm])
            rng.shuffle(lines)
            (lines.astype(np.uint64) << np.uint64(6)).astype("<u8").tofile(f)
            written += m
    os.replace(tmp, path)


def bench_trace_device(n_lines: int = 4_200_000) -> None:
    """Device-only trace-scan rate: resident ids, fresh stream offsets.

    The end-to-end trace metric below is gated by this image's tunneled
    h2d feed (~10-30 MB/s, varying several-fold minute to minute); this
    companion metric pins the TPU-native compute rate of the same scan so
    the two factors are separable in the record.
    """
    import numpy as np

    import jax.numpy as jnp
    from pluss import trace

    W, B = trace.TRACE_WINDOW, trace.WINDOWS_PER_BATCH
    batch = W * B
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, n_lines, batch, dtype=np.int32)
                      .reshape(B, W))
    fn = trace._replay_fn(W, "int32")
    pdt = np.dtype("int32")
    last = jnp.full((1 << 23,), -1, pdt)
    hist = jnp.zeros((trace.NBINS,), pdt)
    last, hist = fn(last, hist, pdt.type(0), ids, pdt.type(2**31 - 4))
    np.asarray(hist[:1])  # tiny d2h forces completion (block_until_ready
    # does not actually wait over the tunneled backend)
    reps = 12
    t0 = time.perf_counter()
    for b in range(1, reps + 1):   # varying base defeats content caching
        last, hist = fn(last, hist, pdt.type(b * batch), ids,
                        pdt.type(2**31 - 4))
    np.asarray(hist[:1])
    dt = time.perf_counter() - t0
    emit("trace_device_scan_refs_per_sec", reps * batch, dt, None)


def bench_trace(n_refs: int) -> None:
    """BASELINE config 5: dynamic trace replay at 1e9 refs, streamed from
    disk (pluss.trace.replay_file) vs the native replay_trace on the same
    addresses.  The trace file is generated once and cached in .bench/."""
    from pluss import native, trace

    os.makedirs(".bench", exist_ok=True)
    path = f".bench/trace_{n_refs}.bin"
    if not (os.path.exists(path) and os.path.getsize(path) == 8 * n_refs):
        log(f"bench: generating {n_refs}-ref synthetic trace at {path}")
        t0 = time.perf_counter()
        synth_trace(path, n_refs)
        log(f"bench: trace generated in {time.perf_counter() - t0:.1f}s")
    # warmup on a short prefix: the prefix discovers the same working set,
    # so the full run below hits the jit cache at the same table shape.
    # (One full timed run, not best-of-N: the tunneled TPU's throughput
    # varies several-fold over minutes, so N runs at this scale could eat
    # the whole bench budget without improving the estimate.)
    warm_refs = 32 * (1 << 20)
    t0 = time.perf_counter()
    warm = trace.replay_file(path, limit_refs=warm_refs)
    warm_s = time.perf_counter() - t0
    log(f"bench: trace warmup (incl. compile) {warm_s:.2f}s"
        f" over {warm.total_count} prefix refs")
    # the tunneled h2d feed's throughput swings from ~30 MB/s to <1 MB/s
    # between runs; at the bottom, 1e9 refs would take hours.  Project from
    # the warmup and shrink the replayed prefix to a wall-clock budget —
    # the metric VALUE is a rate either way, and the name carries the
    # actual ref count so a shrunk run is never mistaken for the full one.
    budget_s = float(os.environ.get("PLUSS_BENCH_TRACE_BUDGET_S", 900))
    rate = warm.total_count / max(warm_s, 1e-9)
    n_run = n_refs
    if n_refs / rate > budget_s:
        # the first warmup's rate includes compile + table-growth retraces;
        # re-time a short post-compile prefix so the projection reflects
        # the steady feed before shrinking
        t0 = time.perf_counter()
        trace.replay_file(path, limit_refs=8 * (1 << 20))
        rate = max(rate, 8 * (1 << 20) / max(time.perf_counter() - t0, 1e-9))
        if n_refs / rate > budget_s:
            n_run = max(warm_refs, int(rate * budget_s))
            log(f"bench: projected {n_refs / rate:.0f}s for {n_refs} refs "
                f"at the current feed rate; shrinking to {n_run} refs "
                f"(~{budget_s:.0f}s budget)")
    t0 = time.perf_counter()
    rep = trace.replay_file(path, limit_refs=n_run)
    best_s = time.perf_counter() - t0
    log(f"bench: {rep.total_count} refs over {rep.n_lines} line slots")
    base_s = None
    try:
        if native.available(autobuild=True):
            # host RAM; excluded from timing.  Same prefix as the device run
            addrs = trace.load_trace(path)[:n_run]
            t0 = time.perf_counter()
            native.replay(addrs)
            base_s = time.perf_counter() - t0
    except (RuntimeError, MemoryError) as e:
        log(f"bench: native trace baseline unavailable: {e}")
    # the metric NAME keeps the REQUESTED size so round-to-round tracking
    # stays keyed on one string; check refs_replayed (and the stderr log)
    # to see whether a slow feed shrank the actually-replayed prefix
    emit(f"trace{n_refs}_replay_refs_per_sec", n_run, best_s, base_s,
         refs_replayed=n_run)


def main() -> int:
    os.chdir(os.path.dirname(os.path.abspath(__file__)))
    # persistent XLA compilation cache: the flagship compiles cost minutes
    # over the tunnel; caching them in-repo makes repeat bench runs (and the
    # driver's round-end run on this same box) warm-start in seconds
    import jax

    from pluss.utils.platform import enable_x64

    enable_x64()
    os.makedirs(".bench/jit_cache", exist_ok=True)
    jax.config.update("jax_compilation_cache_dir",
                      os.path.abspath(".bench/jit_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    plat = probe_accelerator()
    if plat is None:
        from pluss.utils.platform import force_cpu

        force_cpu()
        log("bench: running CPU fallback at N=128")
    else:
        log(f"bench: accelerator platform {plat!r}")

    from pluss import cri, engine
    from pluss.config import DEFAULT
    from pluss.models import gemm, syrk

    def step_of(spec, backend="vmap"):
        def step():
            res = engine.run(spec, backend=backend)
            cri.distribute(res.noshare_list(), res.share_list(),
                           DEFAULT.thread_num)
            return res
        return step

    if plat is not None:
        # mixed-coefficient metric (VERDICT r1 weak #1 / r2 task 1): syrk's
        # A refs are template-ineligible by construction; since round 3
        # they ride the interleave overlay (pluss.overlay) instead of the
        # device sort — same metric name as r01/r02 for comparability
        n_syrk = 1024
        best_s, res = timed_reps(step_of(syrk(n_syrk)), 2, f"syrk{n_syrk}")
        emit(f"syrk{n_syrk}_sortpath_refs_per_sec", res.max_iteration_count,
             best_s, native_spec_s(syrk(n_syrk)))

        # triangular metric (VERDICT r2 task 4): bounded inner loops take
        # the clock-table + device-sort path — no template, no overlay.
        # seq backend: the 4-thread vmap of 16.8M-entry triangular sort
        # windows exceeds what the device survives at n=1024 (worker
        # crash); one thread at a time is the honest runnable config.
        from pluss.models import syrk_triangular

        try:
            spec_tri = syrk_triangular(1024)
            best_s, res = timed_reps(step_of(spec_tri, backend="seq"), 1,
                                     "syrktri1024(seq)")
            emit("syrktri1024_sortpath_refs_per_sec",
                 res.max_iteration_count, best_s, native_spec_s(spec_tri))
        except Exception as e:  # never let an aux metric sink the headline
            log(f"bench: triangular metric failed: {e}")

        # trace-replay metrics (VERDICT r1 weak #4 / BASELINE config 5):
        # device-only scan rate first (robust), then 1e9 refs streamed from
        # disk end-to-end (gated by the tunnel's h2d feed)
        try:
            bench_trace_device()
        except Exception as e:
            log(f"bench: trace device metric failed: {e}")
        try:
            bench_trace(int(os.environ.get("PLUSS_BENCH_TRACE_REFS",
                                           1_000_000_000)))
        except Exception as e:  # never let the aux metric sink the headline
            log(f"bench: trace metric failed: {e}")

        # headline (LAST): BASELINE.json config 2, GEMM 1024^3 (4.3e9 refs)
        n, metric = 1024, "gemm1024_sampler_refs_per_sec"
    else:
        n, metric = 128, "gemm128_sampler_refs_per_sec_cpu_fallback"

    best_s, res = timed_reps(step_of(gemm(n)), REPS, f"gemm{n}")
    emit(metric, res.max_iteration_count, best_s, native_baseline_s(n))
    return 0


if __name__ == "__main__":
    sys.exit(main())
