"""Seeded soaks: property soak, chaos (fault-plan) soak, and serve soak.

Property soak (default): re-wraps tests/test_property.py's differential
properties with a larger example budget and a fresh seed.  Not part of the
suite; run manually: ``python soak.py [examples] [seed]``.

Chaos soak (``python soak.py --chaos N [seed]``): N rounds, each running
the resilient executor under a fresh seeded random fault plan
(:meth:`pluss.resilience.FaultPlan.random` — injected OOMs, compile
failures, share-cap overflows, corrupt plan-cache entries) on a workload
drawn from a small pool.  Every round must either recover to a result
BIT-IDENTICAL to the clean run or fail with a classified ``PlussError``
— a raw XLA/OS exception escaping is a soak failure.  The seed is printed
so any failure replays exactly.  Needs no hypothesis install (run.sh's
opt-in chaos smoke uses it on bare images).

Serve soak (``python soak.py --serve N [seed] [--chaos]
[--telemetry PATH]``): the load generator for the ``pluss serve`` daemon.
Spawns a real daemon subprocess (CPU backend, telemetry armed, a fault
plan injected via ``PLUSS_FAULT_PLAN`` — a fixed early OOM by default,
a seeded random plan under ``--chaos``), then:

1. forces a SHED: a ``sleep_ms`` request holds the device loop while a
   burst overflows the admission bound — the overflow must come back as
   typed ``Overloaded`` errors, never silence or a crash;
2. drives N interleaved requests (registry models at several schedules,
   an inline-JSON spec, packed-trace replays) from concurrent client
   threads, with every response compared BIT-IDENTICAL (mrc + histogram)
   against a solo in-process run of the same prediction — including the
   response(s) the injected fault degraded through the ladder, and every
   neighbor in their batches;
3. drains the daemon cleanly (``{"op": "shutdown"}``) and checks it
   exited 0.

Failures (missing shed, missing degradation, any divergence, raw errors,
unclean exit) are counted and exit nonzero.  run.sh's tier-1 serve smoke
is ``soak.py --serve 20`` + ``pluss stats --check`` on the stream.
"""

import sys
import time


def chaos(n_rounds: int, sd: int) -> int:
    import os
    import random
    import tempfile

    # self-contained env setup (NOT tests.conftest: that module imports
    # pytest, which bare images don't ship, and pays the shard-backend
    # probe — pure waste for a single-process CPU soak).  The plan cache
    # points at a throwaway dir and stays ENABLED: disabling it would
    # turn every injected corrupt_cache fault into a no-op and the soak's
    # quarantine coverage into a lie.
    os.environ.pop("PLUSS_NO_PLAN_CACHE", None)
    os.environ["PLUSS_PLAN_CACHE_DIR"] = tempfile.mkdtemp(
        prefix="pluss_chaos_cache_")
    from pluss.utils.platform import enable_x64, force_cpu

    # 8 virtual devices: the kill-mid-sweep scenario below runs the sweep
    # across device groups, like the production fleet path
    force_cpu(8)
    enable_x64()
    from pluss import engine, obs
    from pluss.config import SamplerConfig
    from pluss.models import REGISTRY
    from pluss.resilience import FaultPlan, PlussError, run_resilient
    from pluss.resilience import faults

    # the soak records its own telemetry stream (PLUSS_TELEMETRY overrides
    # the sink): the summary below — faults fired vs ladder rungs taken —
    # is read back off the live counters, so it can never drift from what
    # the injector and the ladder actually recorded
    if not obs.enabled():
        obs.configure(os.path.join(os.environ["PLUSS_PLAN_CACHE_DIR"],
                                   "chaos_telemetry.jsonl"))

    pool = [("gemm", 16, SamplerConfig(cls=8)),
            ("syrk", 12, SamplerConfig(cls=8)),
            ("mvt", 16, SamplerConfig()),
            ("gemm", 13, SamplerConfig(thread_num=2, chunk_size=3))]
    rng = random.Random(sd)
    failures = 0
    for i in range(n_rounds):
        name, n, cfg = rng.choice(pool)
        plan = FaultPlan.random(sd + i, n_faults=rng.randint(1, 3))
        spec = REGISTRY[name](n)
        clean = engine.run(spec, cfg)
        faults.install(plan)
        t0 = time.perf_counter()
        res = None
        try:
            res = run_resilient(spec, cfg)
            ok = (res.noshare_dense.tolist() == clean.noshare_dense.tolist()
                  and res.share_raw == clean.share_raw)
            status = "bit-exact" if ok else "MISMATCH"
            if not ok:
                failures += 1
        except PlussError as e:
            # a classified failure is an acceptable outcome (e.g. a plan
            # whose faults outnumber the retry budget); a RAW exception
            # below is not
            status = f"classified {type(e).__name__}"
        except BaseException as e:  # noqa: BLE001 — this IS the assertion
            status = f"RAW ESCAPE {type(e).__name__}: {e}"
            failures += 1
        finally:
            faults.install(None)
        deg = ",".join(res.degradations) if res is not None else ""
        print(f"chaos[{i}] {name}{n} plan={plan.describe()}: {status}"
              + (f" (degraded: {deg})" if deg else "")
              + f" in {time.perf_counter() - t0:.1f}s", flush=True)
    failures += _chaos_sweep_kill(sd)
    c = obs.counters()

    def breakdown(prefix: str) -> str:
        parts = [f"{k[len(prefix):]}={int(v)}" for k, v in sorted(c.items())
                 if k.startswith(prefix)]
        return " (" + ",".join(parts) + ")" if parts else ""

    tel = obs.active()
    print("chaos telemetry: "
          f"{int(c.get('resilience.faults_fired', 0))} fault(s) fired"
          f"{breakdown('resilience.faults_fired.')} vs "
          f"{int(c.get('resilience.rungs_taken', 0))} ladder rung(s) taken"
          f"{breakdown('resilience.rungs_taken.')}, "
          f"{int(c.get('resilience.share_cap_raises', 0))} share-cap "
          f"raise(s), {int(c.get('resilience.retries', 0))} plain "
          "retr(y/ies)"
          + (f"; event stream at {tel.path}" if tel else ""), flush=True)
    obs.flush_metrics()
    print(f"chaos soak: {n_rounds} rounds, {failures} failure(s), seed {sd}",
          flush=True)
    return 1 if failures else 0


def _chaos_sweep_kill(sd: int) -> int:
    """Kill a sweep WORKER PROCESS mid-sweep, then assert journaled
    elastic recovery: the resumed device-group sweep restores every
    journaled point (ZERO recomputation of finished work), computes only
    the remainder, and the final curves are bit-identical to a clean
    serial sweep.  Returns the failure count (0 = pass)."""
    import os
    import subprocess
    import tempfile

    from pluss import obs, sweep as sweep_mod
    from pluss.config import SamplerConfig
    from pluss.models import REGISTRY
    from pluss.resilience.journal import Journal

    ts, cks = (1, 2, 4, 8), (2, 4)
    total = len(ts) * len(cks)
    jr_path = os.path.join(tempfile.mkdtemp(prefix="pluss_chaos_sweep_"),
                           "sweep.jsonl")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PLUSS_FAULT_PLAN", None)
    env.pop("PLUSS_TELEMETRY", None)   # the child must not truncate ours
    repo = os.path.dirname(os.path.abspath(__file__))
    proc = subprocess.Popen(
        [sys.executable, "-m", "pluss.cli", "sweep", "--cpu",
         "--model", "gemm", "--n", "16",
         "--sweep-threads", ",".join(map(str, ts)),
         "--sweep-chunks", ",".join(map(str, cks)),
         "--journal", jr_path, "--resume", "--device-groups", "2"],
        cwd=repo, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    # wait for >= 2 journaled points, then SIGKILL — a worker death in
    # the realistic shape (no cleanup, mid-flight points lost)
    deadline = time.time() + 240
    while time.time() < deadline:
        try:
            if sum(1 for ln in open(jr_path)) >= 2:
                break
        except OSError:
            pass
        if proc.poll() is not None:
            break
        time.sleep(0.25)
    killed = proc.poll() is None
    if killed:
        proc.kill()
    proc.wait()
    if not killed:
        print("chaos sweep-kill: sweep finished before the kill landed; "
              "recovery still asserted on the full journal", flush=True)
    finished = len(Journal(jr_path))
    c0 = obs.counters()
    pts = sweep_mod.sweep(REGISTRY["gemm"](16), ts, cks, SamplerConfig(),
                          journal=jr_path, resume=True, device_groups=2)
    c1 = obs.counters()
    restored = int(c1.get("sweep.points_restored", 0)
                   - c0.get("sweep.points_restored", 0))
    ran = int(c1.get("sweep.points_run", 0) - c0.get("sweep.points_run", 0))
    clean = sweep_mod.sweep(REGISTRY["gemm"](16), ts, cks, SamplerConfig())
    same = all(a.curve.tolist() == b.curve.tolist()
               and a.total_refs == b.total_refs
               for a, b in zip(pts, clean))
    ok = (restored == finished and ran == total - finished and same
          and len(pts) == total)
    print(f"chaos sweep-kill: {finished} point(s) journaled before the "
          f"kill; resumed sweep restored {restored}, recomputed {ran} "
          f"(zero recompute of finished points: "
          f"{restored == finished and ran == total - finished}), curves "
          f"{'bit-identical' if same else 'DIVERGED'} vs clean serial",
          flush=True)
    if not ok:
        print("chaos sweep-kill: FAIL", flush=True)
    return 0 if ok else 1


def serve(n_requests: int, sd: int, chaos: bool,
          telemetry: str | None) -> int:
    import io
    import json
    import os
    import subprocess
    import tempfile
    import threading

    import numpy as np

    os.environ.pop("PLUSS_FAULT_PLAN", None)   # solo baselines stay clean
    from pluss.utils.platform import enable_x64, force_cpu

    force_cpu()
    enable_x64()
    from pluss import cri, engine, mrc, trace
    from pluss.config import SamplerConfig
    from pluss.models import REGISTRY
    from pluss.serve import Client
    from pluss.serve.protocol import spec_to_json

    tmp = tempfile.mkdtemp(prefix="pluss_serve_soak_")
    sock = os.path.join(tmp, "serve.sock")
    tel = telemetry or os.path.join(tmp, "serve_telemetry.jsonl")
    trace_path = os.path.join(tmp, "refs.bin")
    rng_np = np.random.default_rng(sd)
    rng_np.integers(0, 4096, 20_000).astype("<u8").tofile(trace_path)

    # request pool: mixed kinds, several schedules, >1 distinct batch key
    inline = spec_to_json(REGISTRY["gemm"](13))
    inline["name"] = "tenant_gemm13"
    pool = [
        {"model": "gemm", "n": 16, "threads": 2, "chunk": 2},
        {"model": "mvt", "n": 16, "threads": 4, "chunk": 4},
        {"model": "syrk", "n": 12, "threads": 2, "chunk": 4},
        {"spec": inline, "threads": 2, "chunk": 2},
        {"trace": trace_path},
    ]

    max_queue = 4
    if chaos:
        from pluss.resilience import FaultPlan

        fault_plan = FaultPlan.random(sd, n_faults=2).describe()
    else:
        # fixed early OOM: an EARLY engine dispatch of the daemon fails
        # injected and must recover through the serve ladder.  @2, not @1:
        # hit 1 is phase 0's warm-SLO request, whose latency must stay a
        # clean measurement — the shed burst right after it takes the hit.
        fault_plan = "oom@2"
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PLUSS_FAULT_PLAN": fault_plan,
           "PLUSS_PLAN_CACHE_DIR": os.path.join(tmp, "plan_cache")}
    env.pop("PLUSS_TELEMETRY", None)   # the daemon gets --telemetry
    err_path = os.path.join(tmp, "daemon.err")
    daemon = subprocess.Popen(
        [sys.executable, "-m", "pluss.cli", "serve", "--socket", sock,
         "--cpu", "--telemetry", tel, "--max-batch", "8",
         "--max-queue", str(max_queue), "--max-delay-ms", "25",
         "--warm", "gemm:16:2:2"],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        env=env, stderr=open(err_path, "w"))
    print(f"serve soak seed {sd}: daemon pid {daemon.pid}, fault plan "
          f"{fault_plan!r}, telemetry {tel}", flush=True)
    for _ in range(240):
        if os.path.exists(sock) or daemon.poll() is not None:
            break
        time.sleep(0.5)
    failures = 0
    if daemon.poll() is not None or not os.path.exists(sock):
        print("serve soak: daemon failed to come up; stderr tail:")
        print(open(err_path).read()[-2000:])
        return 1
    try:
        # ---- phase 0: warm-start SLO.  The daemon came up with
        # --warm gemm:16:2:2 (pool[0]'s exact shape); wait for the
        # background warmup to land, then time the daemon's very FIRST
        # request.  A warmed daemon must answer it near steady state —
        # within 2x the steady p50 measured at the end of the run
        # (asserted under the deterministic plan only; a random chaos
        # fault may legitimately slow any request it lands on).
        warm_deadline = time.monotonic() + 120
        warm_ok = False
        while time.monotonic() < warm_deadline:
            try:
                with open(tel) as fh:
                    txt = fh.read()
            except FileNotFoundError:
                txt = ""
            if '"serve.warm_error"' in txt:
                break
            if '"serve.warm_done"' in txt:
                warm_ok = True
                break
            if daemon.poll() is not None:
                break
            time.sleep(0.2)
        if not warm_ok:
            print("serve soak: FAIL — daemon never reported warm_done")
            failures += 1
        with Client(sock) as c0:
            tq0 = time.perf_counter()
            first_resp = c0.request(dict(pool[0], output="both"))
            first_ms = (time.perf_counter() - tq0) * 1e3
        if not first_resp.get("ok"):
            print(f"serve soak: FAIL — warm first request got {first_resp}")
            failures += 1

        # ---- phase 1: force a shed (typed Overloaded, never a crash)
        holder = Client(sock)
        hid = holder.send({"sleep_ms": 1200})
        time.sleep(0.2)   # let the hold reach the device loop
        with Client(sock) as burst:
            ids = [burst.send({"model": "gemm", "n": 16, "threads": 2,
                               "chunk": 2}) for _ in range(max_queue + 6)]
            outcomes = [burst.recv(i) for i in ids]
        shed = [r for r in outcomes
                if not r.get("ok")
                and r.get("error", {}).get("type") == "Overloaded"]
        raw = [r for r in outcomes
               if not r.get("ok")
               and r.get("error", {}).get("type")
               not in ("Overloaded", "DeadlineExceeded")]
        # the injected fault may fire on the BURST's dispatch (it is the
        # daemon's first) — degradations there count, and served burst
        # responses join the bit-compare below
        phase1_degraded = sum(1 for r in outcomes
                              if r.get("ok") and r.get("degradations"))
        print(f"serve soak: shed burst -> {len(shed)} Overloaded, "
              f"{sum(1 for r in outcomes if r.get('ok'))} served, "
              f"{phase1_degraded} degraded", flush=True)
        if not shed:
            print("serve soak: FAIL — burst past the admission bound "
                  "shed nothing")
            failures += 1
        if raw:
            print(f"serve soak: FAIL — untyped burst errors: {raw[:2]}")
            failures += 1
        holder.recv(hid)
        holder.close()

        # ---- phase 2: N mixed requests from concurrent clients
        rng = __import__("random").Random(sd)
        reqs = [dict(rng.choice(pool), output="both", id=f"r{i}")
                for i in range(n_requests)]
        responses: dict[str, dict] = {}
        rlock = threading.Lock()

        def worker(chunk):
            with Client(sock) as c:
                for q in chunk:
                    r = c.request(q)
                    with rlock:
                        responses[q["id"]] = r

        n_workers = min(4, max(1, n_requests))
        chunks = [reqs[i::n_workers] for i in range(n_workers)]
        threads = [threading.Thread(target=worker, args=(ch,))
                   for ch in chunks if ch]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0

        # ---- solo baselines (clean in-process runs), then bit-compare
        solo: dict[str, dict] = {}

        def solo_payload(q) -> dict:
            cfg = SamplerConfig(thread_num=q.get("threads", 4),
                                chunk_size=q.get("chunk", 4))
            if "trace" in q:
                ri = trace.replay_file(q["trace"], "u64",
                                       cls=cfg.cls).histogram()
            else:
                if "model" in q:
                    spec = REGISTRY[q["model"]](q["n"])
                else:
                    from pluss.serve.protocol import spec_from_json

                    spec = spec_from_json(q["spec"])
                res = engine.run(spec, cfg)
                ri = cri.distribute(res.noshare_list(), res.share_list(),
                                    cfg.thread_num)
            curve = mrc.aet_mrc(ri, cfg)
            return {"mrc": [[int(c), float(m)]
                            for c, m in mrc.dedup_lines(curve)],
                    "histogram": {str(int(k)): float(v)
                                  for k, v in sorted(ri.items())}}

        def key_of(q) -> str:
            return json.dumps({k: q[k] for k in
                               ("model", "n", "spec", "trace", "threads",
                                "chunk") if k in q}, sort_keys=True)

        degraded = phase1_degraded
        mismatches = 0
        burst_q = dict(pool[0], output="both")
        bk = key_of(burst_q)
        solo[bk] = solo_payload(burst_q)
        if first_resp.get("ok"):
            if first_resp.get("degradations"):
                degraded += 1
            if first_resp.get("mrc") != solo[bk]["mrc"]:
                mismatches += 1
                print("serve soak: FAIL — the warm first response "
                      f"diverged (degradations="
                      f"{first_resp.get('degradations')})")
        for r in outcomes:
            if r.get("ok") and r.get("mrc") != solo[bk]["mrc"]:
                mismatches += 1
                print("serve soak: FAIL — a burst response diverged "
                      f"(degradations={r.get('degradations')})")
        for q in reqs:
            r = responses.get(q["id"])
            if r is None or not r.get("ok"):
                print(f"serve soak: FAIL — {q['id']} got {r}")
                failures += 1
                continue
            k = key_of(q)
            if k not in solo:
                solo[k] = solo_payload(q)
            if r.get("degradations"):
                degraded += 1
            if r["mrc"] != solo[k]["mrc"] \
                    or r["histogram"] != solo[k]["histogram"]:
                mismatches += 1
                print(f"serve soak: FAIL — {q['id']} diverged from the "
                      f"solo run (degradations={r.get('degradations')})")
        if mismatches:
            failures += 1
        if not chaos and not degraded:
            # the fixed oom@1 plan must have degraded SOMETHING
            print("serve soak: FAIL — injected fault degraded no request")
            failures += 1
        occup = len([r for r in responses.values() if r.get("ok")])
        batches = {r.get("batched") for r in responses.values()
                   if r.get("ok")}
        print(f"serve soak: {n_requests} mixed requests in {dt:.1f}s "
              f"({n_requests / dt:.1f} req/s), {occup} ok, "
              f"{degraded} degraded via the ladder, {mismatches} "
              f"divergence(s); batch occupancies seen {sorted(batches)}",
              flush=True)

        # ---- steady-state p50 of the warm entry's shape, closing the
        # phase-0 SLO: 5 serial requests over hot executables
        steadies = []
        with Client(sock) as c0:
            for _ in range(5):
                ts = time.perf_counter()
                c0.request(dict(pool[0], output="both"))
                steadies.append((time.perf_counter() - ts) * 1e3)
        steady_p50 = sorted(steadies)[len(steadies) // 2]
        print(f"serve soak: warm first request {first_ms:.1f} ms vs "
              f"steady p50 {steady_p50:.1f} ms", flush=True)
        # floor the denominator: at trivial request cost the 2x bound
        # would be asserting on scheduler noise, not on compile work
        if not chaos and first_ms > 2.0 * max(steady_p50, 50.0):
            print(f"serve soak: FAIL — warmed daemon's first request "
                  f"({first_ms:.1f} ms) exceeded 2x steady p50 "
                  f"({steady_p50:.1f} ms)")
            failures += 1

        # ---- repeated-trace phase (r13): a FRESH trace at a non-default
        # window — new XLA shapes, so the first request pays compile +
        # streaming + stage-through population, while repeats must ride
        # the daemon's HBM residency store.  r1 cold, r2 first store hit
        # (pays the resident kernel's compile), r3 warm steady state:
        # warm must beat cold >= 5x (floored — at trivial cost the bound
        # would assert on scheduler noise), and all three must be
        # bit-identical to a solo in-process replay.
        res_trace = os.path.join(tmp, "refs_resident.bin")
        # sized for signal on the CPU tier-1 backend: 32k refs at window
        # 2048 keep the padded staging batch (16 windows x 2048 refs)
        # small enough that the warm hit's kernel is ~15 ms, while the
        # cold request still pays the full fresh-shape XLA compile +
        # stream + stage-through (~450 ms) — the warm/cold gap this
        # phase asserts on is the store skipping that whole cold side
        rng_np.integers(0, 2048, 32_000).astype("<u8").tofile(res_trace)
        res_win = 2048
        # output=histogram: the bit-identity carrier (the MRC is a pure
        # function of it, solo-compared in phase 2 already) without the
        # per-request curve shaping, which would pad cold and warm alike
        # and drown the residency signal this phase exists to measure
        rq = {"trace": res_trace, "window": res_win, "output": "histogram"}
        lat3: list[float] = []
        resp3: list[dict] = []
        with Client(sock) as c:
            for i in range(6):
                ts = time.perf_counter()
                r = c.request(dict(rq, id=f"res{i}"))
                lat3.append((time.perf_counter() - ts) * 1e3)
                resp3.append(r)
        # cold = r0 (streams + compiles + stage-through populates); r1 is
        # the first hit (pays the resident kernel's compile); warm = the
        # best steady hit after that
        cold_ms, warm_ms = lat3[0], min(lat3[2:])
        print(f"serve soak: repeated trace cold {cold_ms:.1f} ms -> warm "
              f"{warm_ms:.1f} ms ({cold_ms / max(warm_ms, 1e-9):.1f}x)",
              flush=True)
        bad3 = [r for r in resp3 if not r.get("ok")]
        if bad3:
            print(f"serve soak: FAIL — repeated-trace request(s) failed: "
                  f"{bad3[:2]}")
            failures += 1
        else:
            cfg3 = SamplerConfig(thread_num=4, chunk_size=4)
            ri3 = trace.replay_file(res_trace, "u64", cls=cfg3.cls,
                                    window=res_win).histogram()
            want_hist = {str(int(k)): float(v)
                         for k, v in sorted(ri3.items())}
            for i, r in enumerate(resp3):
                if r["histogram"] != want_hist:
                    print(f"serve soak: FAIL — repeated-trace response "
                          f"res{i} diverged from the solo replay "
                          f"(degradations={r.get('degradations')})")
                    failures += 1
            if not chaos and cold_ms < 5.0 * max(warm_ms, 50.0):
                print(f"serve soak: FAIL — warm repeated-trace request "
                      f"({warm_ms:.1f} ms) is not >= 5x faster than the "
                      f"cold one ({cold_ms:.1f} ms)")
                failures += 1

        # ---- drain and stop
        with Client(sock) as c:
            c.request({"op": "shutdown"})
        rc = daemon.wait(timeout=60)
        if rc != 0:
            print(f"serve soak: FAIL — daemon exited {rc}; stderr tail:")
            print(open(err_path).read()[-2000:])
            failures += 1
        # shutdown flushed cumulative counters into the stream: the
        # repeated-trace phase must have actually ridden the store
        try:
            tel_txt = open(tel).read()
        except OSError:
            tel_txt = ""
        if '"residency.hit"' not in tel_txt:
            print("serve soak: FAIL — daemon telemetry recorded no "
                  "residency.hit for the repeated-trace phase")
            failures += 1

        # ---- crash/recover phase (r14): SIGKILL a journaled daemon
        # mid-load, restart it with --recover, and pin the kill-recover
        # invariant: completed journal entries are NEVER re-dispatched
        # (witnessed by the engine's device-dispatch count), while the
        # requests that died queued are replayed and their parked
        # answers — collected via {"op": "result"} — are bit-identical
        # to solo runs.
        jdir = os.path.join(tmp, "journal")
        sock2 = os.path.join(tmp, "serve2.sock")
        tel3 = os.path.join(tmp, "serve3_telemetry.jsonl")
        env2 = dict(env)
        env2.pop("PLUSS_FAULT_PLAN", None)   # a clean crash, not chaos
        err2 = os.path.join(tmp, "daemon2.err")
        err3 = os.path.join(tmp, "daemon3.err")
        here = os.path.dirname(os.path.abspath(__file__))
        daemon2 = subprocess.Popen(
            [sys.executable, "-m", "pluss.cli", "serve", "--socket", sock2,
             "--cpu", "--journal-dir", jdir, "--max-batch", "1",
             "--max-queue", "32"],
            cwd=here, env=env2, stderr=open(err2, "w"))
        daemon3 = None
        try:
            for _ in range(240):
                if os.path.exists(sock2) or daemon2.poll() is not None:
                    break
                time.sleep(0.5)
            if daemon2.poll() is not None:
                print("serve soak: FAIL — journaled daemon died at start; "
                      "stderr tail:")
                print(open(err2).read()[-2000:])
                failures += 1
                raise RuntimeError("crash-phase daemon failed to start")
            # two requests fully answered BEFORE the crash: their journal
            # entries are marked done and must never re-dispatch
            dones = [dict(pool[0], output="both", id="done-0"),
                     dict(pool[1], output="both", id="done-1")]
            with Client(sock2) as c:
                for q in dones:
                    r = c.request(q)
                    if not r.get("ok"):
                        print(f"serve soak: FAIL — pre-crash {q['id']} "
                              f"got {r}")
                        failures += 1
            # hold the device loop, queue three requests, then SIGKILL
            # with all three journaled open and none answered
            holder2 = Client(sock2)
            holder2.send({"sleep_ms": 8000})
            time.sleep(0.2)
            pends = [dict(pool[0], output="both", id="pend-0"),
                     dict(pool[2], output="both", id="pend-1"),
                     {"trace": trace_path, "output": "both",
                      "id": "pend-2"}]
            p2 = Client(sock2)
            for q in pends:
                p2.send(q)
            jfile = os.path.join(jdir, "serve_journal.jsonl")
            for _ in range(100):   # all three journaled open?
                try:
                    if '"pend-2"' in open(jfile).read():
                        break
                except OSError:
                    pass
                time.sleep(0.1)
            daemon2.kill()   # SIGKILL: no drain, no journal completion
            daemon2.wait()
            holder2.close()
            p2.close()
            # restart on the same socket with --recover: still-open
            # entries replay through normal admission, answers park.
            # Readiness is ping-until-answer — the DEAD daemon's socket
            # file still exists, so its presence proves nothing.
            daemon3 = subprocess.Popen(
                [sys.executable, "-m", "pluss.cli", "serve", "--socket",
                 sock2, "--cpu", "--recover", jdir,
                 "--telemetry", tel3],
                cwd=here, env=env2, stderr=open(err3, "w"))
            up = False
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if daemon3.poll() is not None:
                    break
                try:
                    with Client(sock2, timeout=5) as c:
                        up = c.request({"op": "ping"}).get("ok", False)
                    if up:
                        break
                except OSError:
                    time.sleep(0.3)
            if not up:
                print("serve soak: FAIL — recovery daemon never answered "
                      "ping; stderr tail:")
                print(open(err3).read()[-2000:])
                failures += 1
                raise RuntimeError("recovery daemon failed to start")
            recovered: dict[str, dict] = {}
            with Client(sock2) as c:
                for q in pends:
                    rid = q["id"]
                    deadline = time.monotonic() + 120
                    while time.monotonic() < deadline:
                        r = c.request({"op": "result", "id": rid})
                        if r.get("op") != "result":
                            break
                        time.sleep(0.2)
                    recovered[rid] = r
                st = c.request({"op": "stats"})
            for q in pends:
                r = recovered[q["id"]]
                if not r.get("ok"):
                    print(f"serve soak: FAIL — recovered {q['id']} got "
                          f"{r}")
                    failures += 1
                    continue
                k = key_of(q)
                if k not in solo:
                    solo[k] = solo_payload(q)
                if r.get("mrc") != solo[k]["mrc"] \
                        or r.get("histogram") != solo[k]["histogram"]:
                    print(f"serve soak: FAIL — recovered {q['id']} "
                          "diverged from the solo run (degradations="
                          f"{r.get('degradations')})")
                    failures += 1
            n_rec = st.get("counters", {}).get("serve.journal.recovered",
                                               0)
            if n_rec != len(pends):
                print(f"serve soak: FAIL — serve.journal.recovered = "
                      f"{n_rec}, want {len(pends)}")
                failures += 1
            # the zero-recompute witness: only the two open SPEC entries
            # may have dispatched (the trace replay never bumps the
            # engine's counter); a re-run of done-0/done-1 would show here
            nd = st.get("device_dispatches", -1)
            if not 0 <= nd <= 2:
                print(f"serve soak: FAIL — recovery daemon made {nd} "
                      "device dispatches (done entries re-ran?)")
                failures += 1
            print(f"serve soak: crash/recover -> {len(pends)} entries "
                  f"replayed ({n_rec} counted), {nd} device dispatch(es), "
                  "recovered responses bit-identical to solo", flush=True)
            with Client(sock2) as c:
                c.request({"op": "shutdown"})
            rc3 = daemon3.wait(timeout=60)
            if rc3 != 0:
                print(f"serve soak: FAIL — recovery daemon exited {rc3}; "
                      "stderr tail:")
                print(open(err3).read()[-2000:])
                failures += 1
        except RuntimeError:
            pass   # already counted as a failure above
        finally:
            for dm in (daemon2, daemon3):
                if dm is not None and dm.poll() is None:
                    dm.kill()
                    dm.wait()

        # ---- placement phase (r16): an ADVERSARIAL co-tenant mix —
        # one tenant's backlog alternating distinct dispatch keys, built
        # up behind a held device loop so the placement chooser faces
        # real decisions — driven through a placement-aware daemon
        # (PLUSS_SERVE_PLACEMENT=on) and the advisory-only control.
        # Placement is ordering-only, so every response in BOTH arms
        # must be bit-identical to the solo baselines; the on-arm must
        # additionally witness actual choices in its counters.
        adv_pool = [dict(pool[i], output="both") for i in range(3)]
        for arm in ("on", "off"):
            sockp = os.path.join(tmp, f"serve_place_{arm}.sock")
            telp = os.path.join(tmp, f"serve_place_{arm}.jsonl")
            errp = os.path.join(tmp, f"daemon_place_{arm}.err")
            envp = dict(env2)
            envp["PLUSS_SERVE_PLACEMENT"] = arm
            daemonp = subprocess.Popen(
                [sys.executable, "-m", "pluss.cli", "serve", "--socket",
                 sockp, "--cpu", "--max-batch", "1", "--max-queue", "32",
                 "--telemetry", telp],
                cwd=here, env=envp, stderr=open(errp, "w"))
            try:
                for _ in range(240):
                    if os.path.exists(sockp) or daemonp.poll() is not None:
                        break
                    time.sleep(0.5)
                if daemonp.poll() is not None or not os.path.exists(sockp):
                    print(f"serve soak: FAIL — placement={arm} daemon "
                          "died at start; stderr tail:")
                    print(open(errp).read()[-2000:])
                    failures += 1
                    continue
                holderp = Client(sockp)
                holderp.send({"sleep_ms": 1500})
                time.sleep(0.2)   # let the hold reach the device loop
                advs = [dict(adv_pool[i % len(adv_pool)],
                             id=f"pl{arm}-{i}") for i in range(9)]
                with Client(sockp) as c:
                    ids = [c.send(q) for q in advs]
                    got = {i: c.recv(i) for i in ids}
                    stp = c.request({"op": "stats"})
                    c.request({"op": "shutdown"})
                holderp.close()
                rcp = daemonp.wait(timeout=60)
                if rcp != 0:
                    print(f"serve soak: FAIL — placement={arm} daemon "
                          f"exited {rcp}; stderr tail:")
                    print(open(errp).read()[-2000:])
                    failures += 1
                arm_mis = 0
                for q in advs:
                    r = got.get(q["id"])
                    if r is None or not r.get("ok"):
                        print(f"serve soak: FAIL — placement={arm} "
                              f"{q['id']} got {r}")
                        failures += 1
                        continue
                    k = key_of(q)
                    if k not in solo:
                        solo[k] = solo_payload(q)
                    if r["mrc"] != solo[k]["mrc"] \
                            or r["histogram"] != solo[k]["histogram"]:
                        arm_mis += 1
                        print(f"serve soak: FAIL — placement={arm} "
                              f"{q['id']} diverged from the solo run")
                if arm_mis:
                    failures += 1
                n_choices = stp.get("counters", {}).get(
                    "serve.placement.choices", 0)
                if arm == "on" and not n_choices:
                    print("serve soak: FAIL — placement-aware daemon "
                          "recorded no placement choices under backlog")
                    failures += 1
                if arm == "off" and n_choices:
                    print("serve soak: FAIL — advisory-only control "
                          f"recorded {n_choices} placement choice(s)")
                    failures += 1
                print(f"serve soak: placement={arm} -> {len(advs)} "
                      f"adversarial-mix responses bit-identical to solo, "
                      f"{int(n_choices)} placement choice(s)", flush=True)
            finally:
                if daemonp.poll() is None:
                    daemonp.kill()
                    daemonp.wait()

        # ---- observability phase (r20): tracing armed end-to-end.  A
        # daemon with the live /metrics endpoint and a flight-recorder
        # dir; two injected dispatch failures (threshold 2) OPEN the
        # breaker, whose transition dumps the telemetry ring — the dump
        # must pass `pluss stats --check`.  After the cooldown probe
        # re-closes it, a traced request per pool shape runs; every rid
        # must resolve via `pluss stats --trace` to its causal span tree
        # (admission verdict -> admit -> queue wait -> batch -> demux,
        # with the plan-cache / residency attribution riding along), the
        # traced responses must stay bit-identical to the solo runs, and
        # the final /metrics scrape must agree with the daemon's own
        # counter rollup.
        import re as _re
        import urllib.request as _url

        from pluss.obs import stats as stats_mod

        sock4 = os.path.join(tmp, "serve_obs.sock")
        tel4 = os.path.join(tmp, "serve_obs_telemetry.jsonl")
        flid = os.path.join(tmp, "flight")
        err4 = os.path.join(tmp, "daemon_obs.err")
        env4 = dict(env2)
        env4["PLUSS_FAULT_PLAN"] = "dispatch_fail@1,dispatch_fail@2"
        env4["PLUSS_SERVE_BREAKER_THRESHOLD"] = "2"
        env4["PLUSS_SERVE_BREAKER_COOLDOWN_S"] = "0.5"
        daemon4 = subprocess.Popen(
            [sys.executable, "-m", "pluss.cli", "serve", "--socket",
             sock4, "--cpu", "--telemetry", tel4, "--metrics-port", "0",
             "--flight-dir", flid, "--max-batch", "8", "--max-queue",
             "32", "--max-delay-ms", "25"],
            cwd=here, env=env4, stderr=open(err4, "w"))
        try:
            for _ in range(240):
                if os.path.exists(sock4) or daemon4.poll() is not None:
                    break
                time.sleep(0.5)
            if daemon4.poll() is not None or not os.path.exists(sock4):
                print("serve soak: FAIL — obs daemon died at start; "
                      "stderr tail:")
                print(open(err4).read()[-2000:])
                failures += 1
                raise RuntimeError("obs daemon failed to start")
            mport = None
            for _ in range(100):
                m = _re.search(r"metrics on http://127\.0\.0\.1:(\d+)",
                               open(err4).read())
                if m:
                    mport = int(m.group(1))
                    break
                time.sleep(0.1)
            if mport is None:
                print("serve soak: FAIL — obs daemon printed no metrics "
                      "endpoint")
                failures += 1
                raise RuntimeError("no metrics endpoint")

            with Client(sock4) as c:
                # trip the breaker: two serial injected dispatch failures
                for i in range(2):
                    r = c.request(dict(pool[0], output="both",
                                       id=f"obs-bad-{i}"))
                    if r.get("ok") or r.get("error", {}).get("type") \
                            != "ResourceExhausted":
                        print(f"serve soak: FAIL — injected obs failure "
                              f"{i} not classified: {r}")
                        failures += 1
                dump_paths = []
                for _ in range(100):   # breaker-open transition dumps
                    try:
                        dump_paths = sorted(
                            os.path.join(flid, f)
                            for f in os.listdir(flid)
                            if f.startswith("flight-"))
                    except OSError:
                        dump_paths = []
                    if dump_paths:
                        break
                    time.sleep(0.1)
                if not dump_paths:
                    print("serve soak: FAIL — breaker open left no "
                          "flight dump in " + flid)
                    failures += 1
                else:
                    rc4 = stats_mod.main(dump_paths[0], io.StringIO(),
                                         sys.stderr, check=True)
                    if rc4 != 0:
                        print("serve soak: FAIL — breaker flight dump "
                              "failed stats --check")
                        failures += 1
                time.sleep(0.8)   # cooldown -> half-open probe re-closes
                obs_reqs = [dict(pool[i], output="both", id=f"obs-{i}")
                            for i in (0, 1, 2, 4)]
                obs_resps = {}
                for q in obs_reqs:
                    obs_resps[q["id"]] = c.request(q)
                text4 = _url.urlopen(
                    f"http://127.0.0.1:{mport}/metrics",
                    timeout=10).read().decode()
                st4 = c.request({"op": "stats"})
                c.request({"op": "shutdown"})
            rc = daemon4.wait(timeout=60)
            if rc != 0:
                print(f"serve soak: FAIL — obs daemon exited {rc}; "
                      "stderr tail:")
                print(open(err4).read()[-2000:])
                failures += 1

            for q in obs_reqs:
                r = obs_resps[q["id"]]
                if not r.get("ok"):
                    print(f"serve soak: FAIL — traced {q['id']} got {r}")
                    failures += 1
                    continue
                k = key_of(q)
                if k not in solo:
                    solo[k] = solo_payload(q)
                if r["mrc"] != solo[k]["mrc"] \
                        or r["histogram"] != solo[k]["histogram"]:
                    print(f"serve soak: FAIL — traced {q['id']} diverged "
                          f"from the solo run (degradations="
                          f"{r.get('degradations')})")
                    failures += 1

            # /metrics pull plane == the daemon's own rollup
            c4 = st4.get("counters", {})
            for key, prom in (("serve.ok", "pluss_serve_ok"),
                              ("serve.requests.spec",
                               "pluss_serve_requests_spec")):
                m = _re.search(rf"^{prom} (\S+)$", text4, _re.M)
                got = float(m.group(1)) if m else None
                if got != c4.get(key, 0.0):
                    print(f"serve soak: FAIL — /metrics {prom}={got} "
                          f"disagrees with rollup {key}="
                          f"{c4.get(key)}")
                    failures += 1

            # every traced rid resolves to its causal span tree
            if stats_mod.main(tel4, io.StringIO(), sys.stderr,
                              check=True) != 0:
                print("serve soak: FAIL — obs daemon stream failed "
                      "stats --check")
                failures += 1
            tree_fails = 0
            for q in obs_reqs:
                if not obs_resps[q["id"]].get("ok"):
                    continue
                buf = io.StringIO()
                rc5 = stats_mod.main(tel4, buf, sys.stderr,
                                     trace=q["id"])
                tree = buf.getvalue()
                want = ["admission.verdict", "serve.admit",
                        "serve.queue_wait", "serve.batch", "serve.demux",
                        "residency.consult" if "trace" in q
                        else "plan_cache.consult"]
                missing = [w for w in want if w not in tree]
                if rc5 != 0 or missing:
                    tree_fails += 1
                    print(f"serve soak: FAIL — stats --trace {q['id']} "
                          f"missing {missing}:\n{tree}")
            if tree_fails:
                failures += 1
            print(f"serve soak: obs phase -> breaker flight dump "
                  f"checked, {len(obs_reqs)} traced rids resolved to "
                  f"span trees, /metrics == rollup", flush=True)
        except RuntimeError:
            pass   # already counted as a failure above
        finally:
            if daemon4.poll() is None:
                daemon4.kill()
                daemon4.wait()
    finally:
        if daemon.poll() is None:
            daemon.kill()
            daemon.wait()
    print(f"serve soak: {failures} failure(s), seed {sd}; telemetry "
          f"stream at {tel}", flush=True)
    return 1 if failures else 0


def soak(name, inner, budget, sd, **strats):
    from hypothesis import HealthCheck, given, seed, settings

    t0 = time.perf_counter()
    fn = seed(sd)(settings(
        max_examples=budget, deadline=None,
        suppress_health_check=list(HealthCheck),
    )(given(**strats)(inner)))
    fn()
    print(f"soak {name}: {budget} examples OK in "
          f"{time.perf_counter() - t0:.0f}s", flush=True)


def main():
    sys.path.insert(0, ".")
    if len(sys.argv) > 1 and sys.argv[1] == "--serve":
        rest = sys.argv[2:]
        tel = None
        if "--telemetry" in rest:
            i = rest.index("--telemetry")
            tel = rest[i + 1]
            del rest[i:i + 2]
        chaos_flag = "--chaos" in rest
        rest = [a for a in rest if a != "--chaos"]
        n = int(rest[0]) if rest else 20
        sd = int(rest[1]) if len(rest) > 1 else int(time.time())
        sys.exit(serve(n, sd, chaos_flag, tel))
    if len(sys.argv) > 1 and sys.argv[1] == "--chaos":
        n = int(sys.argv[2]) if len(sys.argv) > 2 else 5
        sd = int(sys.argv[3]) if len(sys.argv) > 3 else int(time.time())
        print(f"chaos soak seed {sd}", flush=True)
        sys.exit(chaos(n, sd))

    from hypothesis import strategies as st

    import tests.conftest  # noqa: F401  (CPU mesh + x64 + no plan cache)
    import tests.test_property as tp

    budget = int(sys.argv[1]) if len(sys.argv) > 1 else 150
    sd = int(sys.argv[2]) if len(sys.argv) > 2 else int(time.time())
    print(f"soak seed {sd}", flush=True)
    soak("specs", tp.test_random_specs_match_oracle.hypothesis.inner_test,
         budget, sd, spec=tp.specs(), cfg=tp.configs(),
         window=st.sampled_from([None, 64, 256]))
    soak("schedules",
         tp.test_random_schedules_match_oracle.hypothesis.inner_test,
         max(1, (2 * budget) // 3), sd + 1, args=tp.schedules())
    soak("shard", tp.test_random_specs_shard_matches_oracle.hypothesis
         .inner_test, max(1, budget // 3), sd + 2, spec=tp.specs(),
         cfg=tp.configs())


if __name__ == "__main__":
    main()
