"""Seeded hypothesis soak over the property generators (run per round).

Re-wraps tests/test_property.py's differential properties with a larger
example budget and a fresh seed.  Not part of the suite; run manually:
``python soak.py [examples] [seed]``.
"""

import sys
import time

from hypothesis import HealthCheck, given, seed, settings, strategies as st

sys.path.insert(0, ".")
import tests.conftest  # noqa: F401  (CPU mesh + x64 + no plan cache)
import tests.test_property as tp


def soak(name, inner, budget, sd, **strats):
    t0 = time.perf_counter()
    fn = seed(sd)(settings(
        max_examples=budget, deadline=None,
        suppress_health_check=list(HealthCheck),
    )(given(**strats)(inner)))
    fn()
    print(f"soak {name}: {budget} examples OK in "
          f"{time.perf_counter() - t0:.0f}s", flush=True)


def main():
    budget = int(sys.argv[1]) if len(sys.argv) > 1 else 150
    sd = int(sys.argv[2]) if len(sys.argv) > 2 else int(time.time())
    print(f"soak seed {sd}", flush=True)
    soak("specs", tp.test_random_specs_match_oracle.hypothesis.inner_test,
         budget, sd, spec=tp.specs(), cfg=tp.configs(),
         window=st.sampled_from([None, 64, 256]))
    soak("schedules",
         tp.test_random_schedules_match_oracle.hypothesis.inner_test,
         max(1, (2 * budget) // 3), sd + 1, args=tp.schedules())
    soak("shard", tp.test_random_specs_shard_matches_oracle.hypothesis
         .inner_test, max(1, budget // 3), sd + 2, spec=tp.specs(),
         cfg=tp.configs())


if __name__ == "__main__":
    main()
