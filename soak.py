"""Seeded soaks: hypothesis property soak + chaos (fault-plan) soak.

Property soak (default): re-wraps tests/test_property.py's differential
properties with a larger example budget and a fresh seed.  Not part of the
suite; run manually: ``python soak.py [examples] [seed]``.

Chaos soak (``python soak.py --chaos N [seed]``): N rounds, each running
the resilient executor under a fresh seeded random fault plan
(:meth:`pluss.resilience.FaultPlan.random` — injected OOMs, compile
failures, share-cap overflows, corrupt plan-cache entries) on a workload
drawn from a small pool.  Every round must either recover to a result
BIT-IDENTICAL to the clean run or fail with a classified ``PlussError``
— a raw XLA/OS exception escaping is a soak failure.  The seed is printed
so any failure replays exactly.  Needs no hypothesis install (run.sh's
opt-in chaos smoke uses it on bare images).
"""

import sys
import time


def chaos(n_rounds: int, sd: int) -> int:
    import os
    import random
    import tempfile

    # self-contained env setup (NOT tests.conftest: that module imports
    # pytest, which bare images don't ship, and pays the shard-backend
    # probe — pure waste for a single-process CPU soak).  The plan cache
    # points at a throwaway dir and stays ENABLED: disabling it would
    # turn every injected corrupt_cache fault into a no-op and the soak's
    # quarantine coverage into a lie.
    os.environ.pop("PLUSS_NO_PLAN_CACHE", None)
    os.environ["PLUSS_PLAN_CACHE_DIR"] = tempfile.mkdtemp(
        prefix="pluss_chaos_cache_")
    from pluss.utils.platform import enable_x64, force_cpu

    force_cpu()
    enable_x64()
    from pluss import engine, obs
    from pluss.config import SamplerConfig
    from pluss.models import REGISTRY
    from pluss.resilience import FaultPlan, PlussError, run_resilient
    from pluss.resilience import faults

    # the soak records its own telemetry stream (PLUSS_TELEMETRY overrides
    # the sink): the summary below — faults fired vs ladder rungs taken —
    # is read back off the live counters, so it can never drift from what
    # the injector and the ladder actually recorded
    if not obs.enabled():
        obs.configure(os.path.join(os.environ["PLUSS_PLAN_CACHE_DIR"],
                                   "chaos_telemetry.jsonl"))

    pool = [("gemm", 16, SamplerConfig(cls=8)),
            ("syrk", 12, SamplerConfig(cls=8)),
            ("mvt", 16, SamplerConfig()),
            ("gemm", 13, SamplerConfig(thread_num=2, chunk_size=3))]
    rng = random.Random(sd)
    failures = 0
    for i in range(n_rounds):
        name, n, cfg = rng.choice(pool)
        plan = FaultPlan.random(sd + i, n_faults=rng.randint(1, 3))
        spec = REGISTRY[name](n)
        clean = engine.run(spec, cfg)
        faults.install(plan)
        t0 = time.perf_counter()
        res = None
        try:
            res = run_resilient(spec, cfg)
            ok = (res.noshare_dense.tolist() == clean.noshare_dense.tolist()
                  and res.share_raw == clean.share_raw)
            status = "bit-exact" if ok else "MISMATCH"
            if not ok:
                failures += 1
        except PlussError as e:
            # a classified failure is an acceptable outcome (e.g. a plan
            # whose faults outnumber the retry budget); a RAW exception
            # below is not
            status = f"classified {type(e).__name__}"
        except BaseException as e:  # noqa: BLE001 — this IS the assertion
            status = f"RAW ESCAPE {type(e).__name__}: {e}"
            failures += 1
        finally:
            faults.install(None)
        deg = ",".join(res.degradations) if res is not None else ""
        print(f"chaos[{i}] {name}{n} plan={plan.describe()}: {status}"
              + (f" (degraded: {deg})" if deg else "")
              + f" in {time.perf_counter() - t0:.1f}s", flush=True)
    c = obs.counters()

    def breakdown(prefix: str) -> str:
        parts = [f"{k[len(prefix):]}={int(v)}" for k, v in sorted(c.items())
                 if k.startswith(prefix)]
        return " (" + ",".join(parts) + ")" if parts else ""

    tel = obs.active()
    print("chaos telemetry: "
          f"{int(c.get('resilience.faults_fired', 0))} fault(s) fired"
          f"{breakdown('resilience.faults_fired.')} vs "
          f"{int(c.get('resilience.rungs_taken', 0))} ladder rung(s) taken"
          f"{breakdown('resilience.rungs_taken.')}, "
          f"{int(c.get('resilience.share_cap_raises', 0))} share-cap "
          f"raise(s), {int(c.get('resilience.retries', 0))} plain "
          "retr(y/ies)"
          + (f"; event stream at {tel.path}" if tel else ""), flush=True)
    obs.flush_metrics()
    print(f"chaos soak: {n_rounds} rounds, {failures} failure(s), seed {sd}",
          flush=True)
    return 1 if failures else 0


def soak(name, inner, budget, sd, **strats):
    from hypothesis import HealthCheck, given, seed, settings

    t0 = time.perf_counter()
    fn = seed(sd)(settings(
        max_examples=budget, deadline=None,
        suppress_health_check=list(HealthCheck),
    )(given(**strats)(inner)))
    fn()
    print(f"soak {name}: {budget} examples OK in "
          f"{time.perf_counter() - t0:.0f}s", flush=True)


def main():
    sys.path.insert(0, ".")
    if len(sys.argv) > 1 and sys.argv[1] == "--chaos":
        n = int(sys.argv[2]) if len(sys.argv) > 2 else 5
        sd = int(sys.argv[3]) if len(sys.argv) > 3 else int(time.time())
        print(f"chaos soak seed {sd}", flush=True)
        sys.exit(chaos(n, sd))

    from hypothesis import strategies as st

    import tests.conftest  # noqa: F401  (CPU mesh + x64 + no plan cache)
    import tests.test_property as tp

    budget = int(sys.argv[1]) if len(sys.argv) > 1 else 150
    sd = int(sys.argv[2]) if len(sys.argv) > 2 else int(time.time())
    print(f"soak seed {sd}", flush=True)
    soak("specs", tp.test_random_specs_match_oracle.hypothesis.inner_test,
         budget, sd, spec=tp.specs(), cfg=tp.configs(),
         window=st.sampled_from([None, 64, 256]))
    soak("schedules",
         tp.test_random_schedules_match_oracle.hypothesis.inner_test,
         max(1, (2 * budget) // 3), sd + 1, args=tp.schedules())
    soak("shard", tp.test_random_specs_shard_matches_oracle.hypothesis
         .inner_test, max(1, budget // 3), sd + 2, spec=tp.specs(),
         cfg=tp.configs())


if __name__ == "__main__":
    main()
