"""Sampled iteration points: uniform-interleaving order, equality, hashing.

The reference ships an ``Iteration`` type with a total-order comparator and a
hasher (``/root/reference/src/iteration.rs:1-213``; C++ twin
``c_lib/test/runtime/pluss_utils.h:38-285``).  It is dead code in the live
samplers (SURVEY.md §2 note) because they enumerate *every* iteration, but it
is the declared API for **true subset sampling**: hold sampled iteration
points in ordered sets that reflect the simulated uniform interleaving of the
static schedule, dedupe them by hash, and resume walks from a point (the
:class:`pluss.sched.ChunkSchedule` start-point API).

TPU-idiomatic shape: points live in struct-of-arrays form and the total order
becomes a lexicographic **key matrix** consumed by one ``np.lexsort`` (host,
plan time) or ``jnp.lexsort`` (device) — sorting N sampled points is one
vectorized sort, not N·log N comparator calls.  The scalar
:func:`compare` is kept as the executable specification the vectorized keys
are tested against.

Order semantics (``iteration.rs:151-194``, the ``IterationComp`` used by
ordered sets):

1. chunk round ``cid`` (``getStaticChunkID``), then in-chunk ``pos`` —
   uniform interleaving: all threads execute position p of round r together;
2. the non-parallel iteration variables in index order (the parallel one is
   skipped — it only determines cid/tid/pos);
3. thread id;
4. reference priority, **reversed** (higher priority = earlier in program
   order, ``iteration.rs:123-129``).

The sibling ``compare`` method (``iteration.rs:63-133``) omits step 3 (tid) —
a reference quirk; the set-ordering ``IterationComp`` semantics above are the
canonical ones here.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from pluss.sched import ChunkSchedule

#: bits per iteration variable in the packed identity bitmap
#: (``iteration.rs:204``: ``bitmap |= iv << (i*14)``).
HASH_IV_BITS = 14
#: number of leading ivs the bitmap keeps (``i = 2`` countdown, iteration.rs:202-208).
HASH_IV_SLOTS = 3


@dataclasses.dataclass(frozen=True)
class IterationPoint:
    """One sampled access point: reference name + iteration vector.

    ``ivs`` are iteration *values* (``start + step*index``), as the reference
    stores them.  ``pidx`` is the parallel dimension's index within ``ivs``;
    ``priority`` is the reference's topological order (higher = earlier in the
    loop body).  Mirrors ``Iteration::new`` (iteration.rs:20-51) with the
    (cid, tid, pos) decomposition delegated to :class:`ChunkSchedule`.
    """

    name: str
    ivs: tuple[int, ...]
    priority: int = 1
    parallel: bool = True
    pidx: int = 0

    def decompose(self, sched: ChunkSchedule) -> tuple[int, int, int]:
        """(cid, tid, pos) under the static schedule (iteration.rs:31-39);
        dummy zeros outside a parallel region (iteration.rs:37-38)."""
        if not self.parallel:
            return 0, 0, 0
        v = self.ivs[self.pidx]
        return (
            sched.static_chunk_id(v),
            sched.static_tid(v),
            sched.static_thread_local_pos(v),
        )


def compare(a: IterationPoint, b: IterationPoint, sched: ChunkSchedule) -> int:
    """Scalar ``IterationComp`` total order (iteration.rs:151-194): -1/0/+1.

    This is the executable specification; :func:`order_keys` must sort any
    batch identically (tested in ``tests/test_iteration.py``).
    """
    if a.parallel:
        (ac, at, ap), (bc, bt, bp) = a.decompose(sched), b.decompose(sched)
        if ac != bc:
            return -1 if ac < bc else 1
        if ap != bp:
            return -1 if ap < bp else 1
    common = min(len(a.ivs), len(b.ivs))
    for i in range(common):
        if a.parallel and i == a.pidx:
            continue
        if a.ivs[i] != b.ivs[i]:
            return -1 if a.ivs[i] < b.ivs[i] else 1
    if a.parallel and at != bt:
        return -1 if at < bt else 1
    if a.priority != b.priority:
        # higher priority executes earlier (iteration.rs:186-189 reverse)
        return -1 if a.priority > b.priority else 1
    return 0


def order_keys(
    ivs: np.ndarray,
    priorities: np.ndarray,
    sched: ChunkSchedule,
    pidx: int = 0,
    lengths: np.ndarray | None = None,
) -> list[np.ndarray]:
    """Lexicographic key columns (major first) for a batch of points.

    ``ivs``: [N, D] iteration values, rows padded beyond each point's real
    length; ``lengths``: [N] real lengths (default: all D).  A padded slot
    gets a value below the column minimum, so a shorter point ties-then-wins
    against deeper points sharing its prefix.

    Mixed-depth precondition: against deeper points with an equal common
    prefix the scalar comparator defers to priority (program order), while
    pad-low always places the shorter point first — the two agree exactly
    when shallower refs textually *precede* the deeper loop (the
    PLUSS-generated pattern: init refs before the accumulation loop, as in
    every spec in :mod:`pluss.models`).  A shallow ref placed *after* an
    inner loop would need pad-high instead; batches mixing both shapes are
    outside this function's contract (use :func:`compare`).

    Use as ``np.lexsort(tuple(reversed(order_keys(...))))``.
    """
    ivs = np.asarray(ivs, np.int64)
    N, D = ivs.shape
    if lengths is None:
        lengths = np.full(N, D, np.int64)
    par = ivs[:, pidx]
    cid = np.array([sched.static_chunk_id(int(v)) for v in par], np.int64)
    tid = np.array([sched.static_tid(int(v)) for v in par], np.int64)
    pos = np.array([sched.static_thread_local_pos(int(v)) for v in par], np.int64)
    cols: list[np.ndarray] = [cid, pos]
    slot = np.arange(D)[None, :]
    mask = slot < lengths[:, None]
    lo = ivs.min() - 1
    padded = np.where(mask, ivs, lo)
    for i in range(D):
        if i == pidx:
            continue
        cols.append(padded[:, i])
    cols.append(tid)
    cols.append(-np.asarray(priorities, np.int64))
    return cols


def interleaved_argsort(
    ivs: np.ndarray,
    priorities: np.ndarray,
    sched: ChunkSchedule,
    pidx: int = 0,
    lengths: np.ndarray | None = None,
) -> np.ndarray:
    """Stable argsort of a point batch into uniform-interleaving order."""
    cols = order_keys(ivs, priorities, sched, pidx, lengths)
    return np.lexsort(tuple(reversed(cols)))


def iv_bitmap(ivs: np.ndarray, lengths: np.ndarray | None = None) -> np.ndarray:
    """Packed identity bitmap of the first 3 ivs (iteration.rs:198-212).

    ``bitmap = iv0 << 28 | iv1 << 14 | iv2`` with 14-bit fields; like the
    reference, values >= 2^14 overflow into neighboring fields (truncation is
    part of the contract — it is a *hash*, equality still compares full ivs,
    iteration.rs:137-149).
    """
    ivs = np.asarray(ivs, np.uint64)
    N, D = ivs.shape
    if lengths is None:
        lengths = np.full(N, D, np.int64)
    out = np.zeros(N, np.uint64)
    for i in range(min(D, HASH_IV_SLOTS)):
        shift = np.uint64((HASH_IV_SLOTS - 1 - i) * HASH_IV_BITS)
        out |= np.where(i < lengths, ivs[:, i], 0).astype(np.uint64) << shift
    return out


def point_hash(name_ids: np.ndarray, ivs: np.ndarray,
               lengths: np.ndarray | None = None) -> np.ndarray:
    """Vectorized point hash: the reference hashes (name, bitmap)
    (iteration.rs:199-210); here a 64-bit mix of the interned name id and
    :func:`iv_bitmap` — same collision semantics (ivs past the third slot and
    overflowing bits do not contribute)."""
    bm = iv_bitmap(ivs, lengths)
    h = np.asarray(name_ids, np.uint64) * np.uint64(0x9E3779B97F4A7C15)
    h ^= bm + np.uint64(0x9E3779B97F4A7C15) + (h << np.uint64(6)) + (h >> np.uint64(2))
    return h


def dedup(name_ids: np.ndarray, ivs: np.ndarray,
          lengths: np.ndarray | None = None) -> np.ndarray:
    """Indices of the first occurrence of each distinct point, in input order.

    Equality follows ``PartialEq`` (iteration.rs:137-149): same name and same
    full iteration vector (no truncation — unlike the hash).
    """
    ivs = np.asarray(ivs, np.int64)
    N, D = ivs.shape
    if lengths is None:
        lengths = np.full(N, D, np.int64)
    mask = np.arange(D)[None, :] < lengths[:, None]
    canon = np.where(mask, ivs, np.iinfo(np.int64).min)
    rec = np.concatenate(
        [np.asarray(name_ids, np.int64)[:, None], lengths[:, None], canon], axis=1
    )
    _, first = np.unique(rec, axis=0, return_index=True)
    return np.sort(first)
