"""Persisted batch-geometry autotuner (r19 tentpole, part 3).

The streamed replay's throughput knobs — window size, windows per batch,
staged-ahead depth, feed queue depth, reader pool width, wire encoding,
and the fused Pallas kernels — ship with CPU-guessed defaults.  This
module times SHORT calibration replays of a synthetic trace over a
one-at-a-time candidate grid and persists the winning geometry beside the
PR-11 AOT sidecars, keyed by :func:`pluss.plancache.runtime_salt`: each
(jax version, backend, device kind, NBINS) runtime self-tunes once, and
every later run consults the stored winner instead of re-guessing.

Disciplines (all PR-11 plan-cache policy):

- sidecar lives in ``engine._plan_cache_root()`` as
  ``autotune-<sha256(runtime_salt())[:16]>.json``; written atomically
  (tmp + ``os.replace``), never partially visible;
- the salt rides in the filename AND the payload — a runtime switch
  resolves to a different slot (a miss), and a doctored/copied file whose
  embedded salt disagrees is counted ``autotune.stale`` and ignored;
- unparseable or schema-invalid bytes are quarantined
  (:func:`pluss.resilience.errors.quarantine_artifact`), counted, and
  recalibrated from scratch — never a crash;
- every consulted load counts ``autotune.hit`` (once per process),
  every calibration point ``autotune.probe`` — ``pluss stats`` renders
  the block.

Bit-identity gate: every calibration point's histogram is compared to
the first point's — a geometry knob that changed the RESULT is a bug,
and that point is disqualified loudly rather than timed.

Consult surface: :func:`consult` feeds ``replay_file``'s None-defaulted
kwargs and the Pallas kernels' ``enabled()`` resolution
(``pluss/ops/pallas_events.py``, ``pluss/ops/pallas_decode.py``);
``pluss serve --warm`` announces the tuned geometry it warms with.
``PLUSS_AUTOTUNE=0`` switches consultation off (explicit env/kwargs
always win anyway — the tuned value only ever fills a default).
"""

from __future__ import annotations

import hashlib
import json
import os
import sys

#: geometry schema: field -> (type, validator).  ``pallas`` covers both
#: fused kernels (events + decode); ``wire`` is stored RESOLVED
#: ("pack"/"d24v"), never "auto".
_FIELDS = {
    "window": lambda v: isinstance(v, int) and v >= 1,
    "batch_windows": lambda v: isinstance(v, int) and v >= 1,
    "stage_depth": lambda v: isinstance(v, int) and v >= 1,
    "queue_depth": lambda v: isinstance(v, int) and v >= 1,
    "feed_workers": lambda v: isinstance(v, int) and v >= 1,
    "wire": lambda v: v in ("pack", "d24v"),
    "pallas": lambda v: isinstance(v, bool),
}

#: memoized sidecar loads, keyed by path (one hit/stale count per
#: process, and the consult in a hot default-resolution path costs a
#: dict lookup, not a disk read)
_cache: dict[str, dict | None] = {}


def invalidate() -> None:
    """Forget memoized sidecar loads (tests; after :func:`calibrate`)."""
    _cache.clear()


def sidecar_path() -> str | None:
    """Disk slot of this runtime's tuned geometry, or None when the plan
    cache is off (PLUSS_NO_PLAN_CACHE, or no cache dir configured)."""
    from pluss import engine, plancache

    root = engine._plan_cache_root()
    if root is None:
        return None
    slot = hashlib.sha256(
        plancache.runtime_salt().encode()).hexdigest()[:16]
    return os.path.join(root, f"autotune-{slot}.json")


def enabled() -> bool:
    """Whether default resolution consults the tuned geometry at all
    (``PLUSS_AUTOTUNE``, envknob policy, default on)."""
    from pluss.utils.envknob import env_bool

    return env_bool("PLUSS_AUTOTUNE", True)


def consult(field: str):
    """The tuned value of one geometry field, or None — no sidecar, a
    salt mismatch, consultation disabled, or the field absent.  Explicit
    kwargs and PLUSS_* env overrides beat this by construction: callers
    only consult when resolving a None default."""
    doc = _load()
    if doc is None:
        return None
    v = doc.get("geometry", {}).get(field)
    return v if field not in _FIELDS or v is None or _FIELDS[field](v) \
        else None


def tuned_geometry() -> dict | None:
    """The whole persisted geometry dict (a copy), or None."""
    doc = _load()
    return dict(doc["geometry"]) if doc else None


def _load() -> dict | None:
    if not enabled():
        return None
    path = sidecar_path()
    if path is None:
        return None
    if path not in _cache:
        _cache[path] = _read(path)
    return _cache[path]


def _read(path: str) -> dict | None:
    """Load + validate one sidecar.  Counter discipline (``autotune.*``):
    ``hit`` on a valid consulted load, ``stale`` on a salt mismatch or a
    quarantined corrupt file; a plain absent file is silent (the common
    un-calibrated state)."""
    from pluss import obs, plancache

    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
        geo = doc["geometry"]
        salt = doc["salt"]
        if not isinstance(geo, dict) or not isinstance(salt, str):
            raise ValueError("sidecar schema: geometry/salt malformed")
        bad = [k for k, ok in _FIELDS.items() if k in geo and not ok(geo[k])]
        if bad:
            raise ValueError(f"invalid geometry fields: {', '.join(bad)}")
    except Exception as e:
        from pluss.resilience.errors import quarantine_artifact

        obs.counter_add("autotune.stale")
        quarantine_artifact(
            path, "autotune geometry sidecar", e,
            action="recalibrate with `pluss autotune --force`")
        return None
    if salt != plancache.runtime_salt():
        obs.counter_add("autotune.stale")
        print(f"pluss: autotune sidecar {path} was calibrated on a "
              f"different runtime ({salt}); ignoring it — recalibrate "
              f"with `pluss autotune`", file=sys.stderr)
        return None
    obs.counter_add("autotune.hit")
    obs.trace_event("autotune.consult", outcome="hit",
                    **{k: v for k, v in doc["geometry"].items()
                       if isinstance(v, (int, float))})
    return doc


def _save(doc: dict) -> str | None:
    """Atomic sidecar write (tmp + rename, the AOT pattern): readers see
    the old geometry or the new one, never half a JSON document."""
    import uuid

    path = sidecar_path()
    if path is None:
        return None
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    invalidate()
    return path


def _base_geometry(n_refs: int) -> dict:
    """The shipped defaults as a calibration starting point, with the
    window scaled so the calibration trace spans multiple windows."""
    import jax

    from pluss import trace

    window = trace.TRACE_WINDOW
    while window > max(1 << 12, n_refs // 4):
        window //= 4
    return {
        "window": window,
        "batch_windows": trace.WINDOWS_PER_BATCH,
        "stage_depth": 2,
        "queue_depth": 2,
        "feed_workers": trace._default_feed_workers(),
        "wire": trace._resolve_wire(None),
        "pallas": jax.default_backend() != "cpu",
    }


def _candidates(base: dict) -> list[dict]:
    """One-at-a-time variations around the base — coordinate probes, not
    a cross product: the knobs are near-independent (feed vs kernel vs
    transport), and a short calibration cannot resolve interactions
    anyway."""
    cands = [dict(base)]
    for delta in (
        {"batch_windows": max(2, base["batch_windows"] // 2)},
        {"batch_windows": base["batch_windows"] * 2},
        {"window": max(1 << 12, base["window"] // 4)},
        {"wire": "pack" if base["wire"] == "d24v" else "d24v"},
        {"feed_workers": base["feed_workers"] + 1},
        {"stage_depth": base["stage_depth"] + 2},
        {"queue_depth": base["queue_depth"] + 2},
        {"pallas": not base["pallas"]},
    ):
        c = dict(base)
        c.update(delta)
        if c not in cands:
            cands.append(c)
    return cands


def _synth_trace(path: str, n_refs: int, seed: int = 7) -> None:
    """Synthetic u64 address stream with a hot set, a scan, and a cold
    tail — enough reuse-distance spread that geometry differences move
    real work, not just padding."""
    import numpy as np

    rng = np.random.default_rng(seed)
    thirds = n_refs // 3
    hot = rng.integers(0, 1 << 14, thirds)
    scan = np.arange(thirds, dtype=np.int64) % (1 << 18)
    cold = rng.integers(0, 1 << 22, n_refs - 2 * thirds)
    addrs = np.concatenate([hot, scan, cold])
    (addrs.astype(np.uint64) * 64).tofile(path)


def _time_point(path: str, geo: dict) -> tuple[object, float]:
    """One calibration point: replay twice (warm compile, then timed)
    under the candidate geometry.  The Pallas toggle rides the env knobs
    — the kernel memo keys include the resolved flag, so flips retrace
    rather than reuse."""
    import time

    from pluss import trace
    from pluss.ops import pallas_decode, pallas_events

    saved = {k: os.environ.get(k)
             for k in ("PLUSS_PALLAS_EVENTS", "PLUSS_PALLAS_DECODE")}
    flag = "1" if geo["pallas"] else "0"
    os.environ["PLUSS_PALLAS_EVENTS"] = flag
    os.environ["PLUSS_PALLAS_DECODE"] = flag
    try:
        kw = dict(window=geo["window"], batch_windows=geo["batch_windows"],
                  stage_depth=geo["stage_depth"],
                  queue_depth=geo["queue_depth"],
                  feed_workers=geo["feed_workers"], wire=geo["wire"])
        trace.replay_file(path, **kw)            # warm: compile + run
        t0 = time.perf_counter()
        r = trace.replay_file(path, **kw)
        dt = time.perf_counter() - t0
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return r, dt


def calibrate(n_refs: int = 1 << 20, force: bool = False,
              trace_path: str | None = None,
              out=sys.stderr) -> dict:
    """Search the geometry grid on a short synthetic replay and persist
    the winner for this runtime.  Returns the sidecar document (also when
    persistence is off — the caller still gets the measured winner).

    An existing valid sidecar short-circuits (zero re-calibration —
    ``autotune.hit`` witnesses the consult) unless ``force``."""
    import tempfile
    import time

    import numpy as np

    from pluss import obs, plancache

    if not force:
        doc = _load()
        if doc is not None:
            out.write(f"autotune: valid geometry for "
                      f"{plancache.runtime_salt()} already persisted "
                      f"(--force recalibrates)\n")
            return doc

    t_start = time.perf_counter()
    tmpdir = None
    path = trace_path
    if path is None:
        tmpdir = tempfile.mkdtemp(prefix="pluss-autotune-")
        path = os.path.join(tmpdir, "calib.u64")
        _synth_trace(path, n_refs)
    try:
        base = _base_geometry(n_refs)
        best = None
        ref_hist = None
        for geo in _candidates(base):
            obs.counter_add("autotune.probe")
            try:
                r, dt = _time_point(path, geo)
            except Exception as e:
                out.write(f"autotune: point {geo} failed "
                          f"({type(e).__name__}: {e}); skipped\n")
                continue
            hist = np.asarray(r.hist, np.int64)
            if ref_hist is None:
                ref_hist = hist
            elif not np.array_equal(hist, ref_hist):
                out.write(f"autotune: point {geo} changed the histogram "
                          f"— geometry must be result-invariant; "
                          f"disqualified\n")
                continue
            rps = r.total_count / max(dt, 1e-9)
            out.write(f"autotune: {rps:12.0f} refs/s  {geo}\n")
            if best is None or rps > best[0]:
                best = (rps, dict(geo))
        if best is None:
            raise RuntimeError("autotune: every calibration point failed")
        elapsed = time.perf_counter() - t_start
        doc = {
            "version": 1,
            "salt": plancache.runtime_salt(),
            "geometry": best[1],
            "refs_per_sec": round(best[0], 1),
            "calibration": {
                "n_refs": int(n_refs if trace_path is None else
                              os.path.getsize(path) // 8),
                "points": len(_candidates(base)),
                "elapsed_s": round(elapsed, 3),
            },
        }
        where = _save(doc)
        if where:
            out.write(f"autotune: persisted winner to {where} "
                      f"({elapsed:.1f}s)\n")
        else:
            out.write("autotune: plan cache disabled "
                      "(PLUSS_NO_PLAN_CACHE / no cache dir) — winner "
                      "NOT persisted\n")
        return doc
    finally:
        if tmpdir is not None:
            import shutil

            shutil.rmtree(tmpdir, ignore_errors=True)


def dry_run(out=sys.stdout) -> int:
    """Validate the persisted sidecar without calibrating: report its
    status and the tuned geometry.  Exit code 1 only when a file exists
    but fails validation (corrupt → quarantined, or wrong salt) — the
    run.sh gate treats that as a broken artifact, while 'none yet' is a
    healthy state."""
    path = sidecar_path()
    if path is None:
        out.write("autotune: plan cache disabled; no sidecar to check\n")
        return 0
    if not os.path.exists(path):
        out.write(f"autotune: no sidecar yet at {path} "
                  f"(run `pluss autotune` to calibrate)\n")
        return 0
    doc = _read(path)   # bypasses the PLUSS_AUTOTUNE consult switch
    if doc is None:
        out.write(f"autotune: sidecar {path} failed validation "
                  f"(quarantined or salt-stale)\n")
        return 1
    out.write(f"autotune: valid sidecar {path}\n")
    out.write(f"  salt: {doc['salt']}\n")
    if "refs_per_sec" in doc:
        out.write(f"  calibrated: {doc['refs_per_sec']:.0f} refs/s over "
                  f"{doc.get('calibration', {}).get('n_refs', '?')} refs\n")
    for k in sorted(doc["geometry"]):
        out.write(f"  {k:<16} {doc['geometry'][k]}\n")
    return 0
