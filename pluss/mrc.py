"""AET -> miss-ratio curve, exact C++ semantics in closed form.

The reference's ``pluss_AET`` (``/root/reference/c_lib/test/runtime/
pluss_utils.h:758-804``) computes P(reuse > t) from the final reuse-interval
histogram, then sweeps cache sizes c advancing a scalar time cursor while
``sum_P < c`` — an O(max_reuse) serial loop.  (The Rust port ``utils.rs:21-86``
iterates keys in the wrong direction and is dead code — SURVEY.md Q4; this
module implements the C++ semantics.)

Because P is a step function over histogram keys, the cursor's running sum is
piecewise *linear* in t, so the first t with ``S(t) >= c`` has a closed form per
segment and the whole curve falls out of a searchsorted — no serial sweep.  The
per-step float accumulation of the reference is reproduced to ~1e-13 relative
(repeated-add vs multiply rounding), far inside the 1e-5 dedup epsilon and the
1% L2 acceptance bar (BASELINE.md north star).

P construction (pluss_utils.h:761-781): iterate keys descending, excluding the
cold key -1 but *seeding* the accumulator with its count; P[k] = acc/total
before adding k's own count; finally P[0] is forced to 1.0.
"""

from __future__ import annotations

import numpy as np

from pluss.config import MRC_DEDUP_EPS, SamplerConfig, DEFAULT


def survival(rihist: dict) -> tuple[np.ndarray, np.ndarray]:
    """(keys ascending, P values) of the AET survival map, C++ semantics."""
    total = float(sum(rihist.values()))
    if total == 0.0:
        return np.array([0], np.int64), np.array([1.0])
    keys = sorted(k for k in rihist if k != -1)
    acc = float(rihist.get(-1, 0.0))
    P = {}
    for k in reversed(keys):
        P[k] = acc / total
        acc += float(rihist[k])
    P[0] = 1.0  # pluss_utils.h:781 overwrites/creates key 0
    ks = np.array(sorted(P), np.int64)
    vs = np.array([P[int(k)] for k in ks])
    return ks, vs


def aet_times(rihist: dict, cfg: SamplerConfig = DEFAULT) -> np.ndarray:
    """AET eviction times t*(c) for c = 0..min(max_key, cache entries).

    ``t*(c)`` is the first time cursor position with cumulative survival
    ``S(t) >= c`` — the Average Eviction Time of a cache of size c under
    this reuse distribution.  This is the quantity the reference's
    ``pluss_AET`` sweep tracks implicitly; exposing it first-class is
    what makes the r15 hierarchy/co-tenancy read-offs possible: a
    co-runner's degraded miss ratio is ITS survival evaluated at the
    MERGED stream's eviction times (:mod:`pluss.model.hierarchy`).

    Empty / cold-only histograms return the single time [0].
    """
    if not rihist:
        return np.array([0], np.int64)
    max_rt = max(rihist.keys())
    if max_rt < 0:
        return np.array([0], np.int64)
    ks, vs = survival(rihist)

    # segments [ks[j], ks[j+1]-1] with constant step value vs[j]; the cursor
    # never passes max_rt (`t <= max_RT` guard, pluss_utils.h:787)
    ends = np.append(ks[1:] - 1, max_rt)
    lens = (ends - ks + 1).astype(np.float64)
    seg_cum = np.cumsum(vs * lens)            # S at each segment end

    c_max = min(max_rt, cfg.aet_cache_entries)
    cs = np.arange(0, c_max + 1, dtype=np.float64)
    j = np.searchsorted(seg_cum, cs, side="left")
    j = np.minimum(j, len(ks) - 1)
    prev_cum = np.where(j > 0, seg_cum[j - 1], 0.0)
    # first t in segment j with S(t) >= c: t = ks[j] + ceil((c-prev)/v) - 1
    # v > 0 whenever need > 0 (a zero-step segment cannot be the first to reach c)
    v = vs[j]
    need = np.maximum(cs - prev_cum, 0.0)
    steps = np.ceil(need / np.where(v > 0, v, 1.0))
    t = ks[j] + np.maximum(steps - 1, 0).astype(np.int64)
    return np.minimum(t, max_rt)


def survival_at(rihist: dict, t: np.ndarray) -> np.ndarray:
    """P(reuse > t) of ``rihist``'s survival step function at times ``t``.

    ``survival_at(h, aet_times(h, cfg))`` IS ``aet_mrc(h, cfg)`` — same
    arrays, same lookups, bit-identical.  With a DIFFERENT histogram it
    reads one workload's miss ratio off another (merged) stream's
    eviction clock, the co-tenancy composition read-off."""
    ks, vs = survival(rihist)
    # MRC[c] = P at the largest key <= t* (the cursor's prev_t); ks always
    # contains 0 (survival forces P[0]), so the clamp only guards t < 0
    seg_of_t = np.maximum(np.searchsorted(ks, t, side="right") - 1, 0)
    return vs[seg_of_t]


def aet_mrc(rihist: dict, cfg: SamplerConfig = DEFAULT) -> np.ndarray:
    """Miss ratio per cache size c = 0..min(max_key, cache entries).

    Returns ``mrc`` with ``mrc[c]`` = the value the reference stores in
    ``_MRC[c]`` (pluss_utils.h:786-802).  Empty histogram -> one-point [1.0].
    """
    if not rihist:
        return np.array([1.0])
    max_rt = max(rihist.keys())
    if max_rt < 0:
        return np.array([1.0])
    return survival_at(rihist, aet_times(rihist, cfg))


def plateau_of(rihist: dict, mrc: np.ndarray) -> int | None:
    """Exact plateau location: the first cache size whose miss ratio is
    the curve's terminal compulsory-miss value, or None if the curve
    never reaches it inside the modeled cache range.

    The terminal value is ``cold/total`` by the same float division the
    survival map performs (the descending accumulator's FIRST emitted P
    is exactly ``acc/total`` with ``acc`` still the seed cold count), so
    reaching the floor is an exact float equality, not an epsilon test;
    the curve is non-increasing, so the matching suffix is one run and
    its first index IS the plateau."""
    total = float(sum(rihist.values()))
    if total == 0.0:
        return 0
    floor = float(rihist.get(-1, 0.0)) / total
    if float(mrc[-1]) != floor:
        return None
    hit = np.flatnonzero(np.asarray(mrc) == floor)
    return int(hit[0])


def dedup_lines(mrc: np.ndarray) -> list[tuple[int, float]]:
    """The reference's run-collapsing printer (pluss_utils.h:851-883): for each
    run of c whose miss ratios differ from the run head by < 1e-5, print the
    head and (if distinct) the tail."""
    n = len(mrc)
    lines: list[tuple[int, float]] = []
    i1 = 0
    while i1 < n:
        i2 = i1
        while i2 + 1 < n and mrc[i1] - mrc[i2 + 1] < MRC_DEDUP_EPS:
            i2 += 1
        lines.append((i1, float(mrc[i1])))
        if i1 != i2:
            lines.append((i2, float(mrc[i2])))
        i1 = i2 + 1
    return lines


def write_mrc(path: str, mrc: np.ndarray) -> None:
    """``pluss_write_mrc_to_file`` (pluss_utils.h:885-913)."""
    with open(path, "w") as f:
        f.write("miss ratio\n")
        for c, v in dedup_lines(mrc):
            f.write(f"{c}, {v:g}\n")


def l2_error(a: np.ndarray, b: np.ndarray) -> float:
    """Relative L2 distance on the common prefix — the acceptance metric
    (BASELINE.md: MRC within 1% L2 error)."""
    n = min(len(a), len(b))
    if n == 0:
        return 0.0
    x, y = np.asarray(a[:n], float), np.asarray(b[:n], float)
    denom = float(np.linalg.norm(y)) or 1.0
    return float(np.linalg.norm(x - y)) / denom
