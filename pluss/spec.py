"""Loop-nest & reference specs: the declarative replacement for generated samplers.

The reference encodes each workload as compiler-*generated state-machine code*
(e.g. the GEMM walk in ``/root/reference/src/gemm_sampler.rs:56-293`` and
``c_lib/test/sampler/gemm-t4-pluss-pro-model-ri-omp.cpp:37-333``): one hardcoded
``if ref == "C0" ...`` block per static reference, with the iteration vector
mutated in place.  That design needs new generated code per workload and walks one
access at a time.

Here a workload is a small declarative tree of :class:`Loop` and :class:`Ref`
nodes.  Because every loop is rectangular (constant trip count), the *position in
the access stream* and the *element address* of every occurrence of every static
reference are affine functions of the iteration vector.  The XLA engine
(:mod:`pluss.engine`) exploits that to enumerate whole reference streams with
broadcasted ``iota`` arithmetic — no per-access control flow, no state machine.

Semantics preserved from the reference:

- Program order of references inside a loop body = their order in ``Loop.body``
  (the reference's ref priority / topological order, ``src/iteration.rs:123-129``).
- One logical clock per simulated thread, incremented once per access
  (``gemm_sampler.rs:133``: ``count[tid] += 1`` in every state).
- Share classification happens per *static reference* with a span threshold:
  a reuse is "share" (crosses threads) iff it is closer to the carrying-loop span
  than to 0 — ``distance_to(reuse,0) > distance_to(reuse,span)``
  (``gemm_sampler.rs:199``, ``…omp.cpp:203``).  For integer reuse/span this is
  exactly ``2*reuse > span``.
"""

from __future__ import annotations

import dataclasses
from typing import Union

from pluss.config import SamplerConfig, DEFAULT


@dataclasses.dataclass(frozen=True)
class Ref:
    """One static memory reference inside a loop body.

    ``addr_terms`` maps *loop depth* (0 = the nest's outermost/parallel loop) to
    the row-major address coefficient; the element address of an occurrence is
    ``addr_base + sum(coef * iv[depth])`` over the terms, with ``iv`` the actual
    iteration *values* (``start + step*index``), matching the reference's
    ``GetAddress_*`` functions (``…omp.cpp:12-35``).

    ``share_span``: if not None, reuses observed at this reference are tested for
    cross-thread sharing against this span (see module docstring).  The GEMM
    value 16513 comes from the generated comment ``(((1)*((128-0)/1)+1)*((128-0)/1)+1)``
    (``…omp.cpp:202``), i.e. ``(trip+1)*trip + 1`` of the carrying loop.
    """

    name: str
    array: str
    addr_terms: tuple[tuple[int, int], ...]
    addr_base: int = 0
    share_span: int | None = None


@dataclasses.dataclass(frozen=True)
class Loop:
    """A rectangular loop: ``for iv in (start, start+step, ...) x trip: body``.

    ``body`` is an ordered tuple of :class:`Ref` and nested :class:`Loop` items,
    executed in order each iteration.
    """

    trip: int
    body: tuple[Union["Loop", Ref], ...]
    start: int = 0
    step: int = 1


@dataclasses.dataclass(frozen=True)
class LoopNestSpec:
    """A workload: a sequence of parallel loop nests over named arrays.

    ``arrays``: (name, total elements) per array, in declaration order.  The
    cold-miss flush order of the reference (C, then A, then B for GEMM —
    ``gemm_sampler.rs:280-282``) is the order of this tuple.

    ``nests``: each entry is one ``#pragma pluss parallel`` loop
    (``c_lib/test/gemm.ppcg_omp.c:90``); its outermost dimension is chunked over
    simulated threads by the dispatcher.  Nests execute back-to-back; per-thread
    clocks and last-access tables persist across nests and are flushed once at
    the end, matching the generated sampler pattern (``…omp.cpp:306-319``).
    """

    name: str
    arrays: tuple[tuple[str, int], ...]
    nests: tuple[Loop, ...]

    def array_index(self, name: str) -> int:
        for i, (a, _) in enumerate(self.arrays):
            if a == name:
                return i
        raise KeyError(name)

    def line_counts(self, cfg: SamplerConfig = DEFAULT) -> list[int]:
        """Cache lines per array: ceil(elements * DS / CLS)."""
        return [-(-n * cfg.ds // cfg.cls) for _, n in self.arrays]

    def line_bases(self, cfg: SamplerConfig = DEFAULT) -> list[int]:
        """Exclusive prefix sum of line_counts: global line-id base per array."""
        bases, acc = [], 0
        for n in self.line_counts(cfg):
            bases.append(acc)
            acc += n
        return bases

    def total_lines(self, cfg: SamplerConfig = DEFAULT) -> int:
        return sum(self.line_counts(cfg))


def loop_size(item: Union[Loop, Ref]) -> int:
    """Total accesses performed by one execution of ``item``."""
    if isinstance(item, Ref):
        return 1
    return item.trip * sum(loop_size(b) for b in item.body)


@dataclasses.dataclass(frozen=True)
class FlatRef:
    """A reference flattened against its enclosing loop chain.

    For occurrence with per-level indices ``idx[0..d]`` (index space, not value
    space) the stream position inside one execution of the nest is::

        pos = offset + sum(idx[l] * pos_stride[l])

    and the element address is::

        addr = addr_base + sum(addr_coef[l] * (start[l] + step[l]*idx[l]))

    ``pos_stride[l]`` is the access count of one iteration of loop ``l``'s body.
    """

    ref: Ref
    trips: tuple[int, ...]
    starts: tuple[int, ...]
    steps: tuple[int, ...]
    pos_strides: tuple[int, ...]
    offset: int
    addr_coefs: tuple[int, ...]  # dense, one per enclosing loop depth


def flatten_nest(nest: Loop) -> list[FlatRef]:
    """Flatten one parallel nest into per-reference affine occurrence specs."""
    out: list[FlatRef] = []

    def walk(loop: Loop, chain: list[Loop], offset: int) -> None:
        chain = chain + [loop]
        body_off = 0
        for item in loop.body:
            if isinstance(item, Ref):
                trips = tuple(l.trip for l in chain)
                starts = tuple(l.start for l in chain)
                steps = tuple(l.step for l in chain)
                strides = tuple(sum(loop_size(b) for b in l.body) for l in chain)
                coefs = [0] * len(chain)
                for depth, coef in item.addr_terms:
                    if depth >= len(chain):
                        raise ValueError(
                            f"ref {item.name}: addr term depth {depth} exceeds "
                            f"loop chain depth {len(chain)}"
                        )
                    coefs[depth] += coef
                out.append(
                    FlatRef(
                        ref=item,
                        trips=trips,
                        starts=starts,
                        steps=steps,
                        pos_strides=strides,
                        offset=offset + body_off,
                        addr_coefs=tuple(coefs),
                    )
                )
                body_off += 1
            else:
                walk(item, chain, offset + body_off)
                body_off += loop_size(item)

    walk(nest, [], 0)
    return out


def nest_iteration_size(nest: Loop) -> int:
    """Accesses per iteration of the nest's outermost (parallel) loop."""
    return sum(loop_size(b) for b in nest.body)


def share_span_formula(trip: int, start: int = 0, step: int = 1) -> int:
    """The generated share-threshold: ``((1*((trip-start)/step)+1)*((trip-start)/step)+1)``.

    From the generated comparison at ``…omp.cpp:202`` /
    ``gemm_sampler.rs:198-199`` — for GEMM-128 this is 129*128+1 = 16513.
    """
    t = (trip - start) // step
    return (t + 1) * t + 1
