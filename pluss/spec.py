"""Loop-nest & reference specs: the declarative replacement for generated samplers.

The reference encodes each workload as compiler-*generated state-machine code*
(e.g. the GEMM walk in ``/root/reference/src/gemm_sampler.rs:56-293`` and
``c_lib/test/sampler/gemm-t4-pluss-pro-model-ri-omp.cpp:37-333``): one hardcoded
``if ref == "C0" ...`` block per static reference, with the iteration vector
mutated in place.  That design needs new generated code per workload and walks one
access at a time.

Here a workload is a small declarative tree of :class:`Loop` and :class:`Ref`
nodes.  Specs need not be hand-written: :mod:`pluss.frontend` derives them
from a Python loop-nest DSL or from ``#pragma pluss parallel`` C source
(the shape this IR was modeled on), analyzer-verified; :mod:`pluss.models`
holds the hand-written corpus, and :mod:`pluss.spec_codec` is the one
JSON encoding shared by serving, the frontend, and the CLI.  Because every loop is rectangular (constant trip count), the *position in
the access stream* and the *element address* of every occurrence of every static
reference are affine functions of the iteration vector.  The XLA engine
(:mod:`pluss.engine`) exploits that to enumerate whole reference streams with
broadcasted ``iota`` arithmetic — no per-access control flow, no state machine.

Semantics preserved from the reference:

- Program order of references inside a loop body = their order in ``Loop.body``
  (the reference's ref priority / topological order, ``src/iteration.rs:123-129``).
- One logical clock per simulated thread, incremented once per access
  (``gemm_sampler.rs:133``: ``count[tid] += 1`` in every state).
- Share classification happens per *static reference* with a span threshold:
  a reuse is "share" (crosses threads) iff it is closer to the carrying-loop span
  than to 0 — ``distance_to(reuse,0) > distance_to(reuse,span)``
  (``gemm_sampler.rs:199``, ``…omp.cpp:203``).  For integer reuse/span this is
  exactly ``2*reuse > span``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Union

from pluss.config import SamplerConfig, DEFAULT


class SpecContractError(ValueError):
    """A Loop/Ref tree outside the engine's declarative contract.

    ``code`` is the stable diagnostic code (PL4xx, see
    :mod:`pluss.analysis.diagnostics`) the static analyzer surfaces the
    violation under; plan-time callers keep seeing a plain ``ValueError``
    (this is a subclass), so nothing about the failure mode changes for
    them — the code is extra, machine-readable identity.
    """

    code = "PL407"  # generic "spec rejected by flatten" fallback

    def __init__(self, message: str, code: str | None = None):
        super().__init__(message)
        if code is not None:
            self.code = code


@dataclasses.dataclass(frozen=True)
class Ref:
    """One static memory reference inside a loop body.

    ``addr_terms`` maps *loop depth* (0 = the nest's outermost/parallel loop) to
    the row-major address coefficient; the element address of an occurrence is
    ``addr_base + sum(coef * iv[depth])`` over the terms, with ``iv`` the actual
    iteration *values* (``start + step*index``), matching the reference's
    ``GetAddress_*`` functions (``…omp.cpp:12-35``).

    ``share_span``: if not None, reuses observed at this reference are tested for
    cross-thread sharing against this span (see module docstring).  The GEMM
    value 16513 comes from the generated comment ``(((1)*((128-0)/1)+1)*((128-0)/1)+1)``
    (``…omp.cpp:202``), i.e. ``(trip+1)*trip + 1`` of the carrying loop.

    ``is_write``: True for stores.  The engine's reuse/share walk does not
    distinguish loads from stores (neither does the reference's state
    machine), but the static analyzer (:mod:`pluss.analysis`) needs the
    distinction to prove or refute cross-thread races on the parallel
    dimension, so every model spec declares it.

    ``dtype_bytes``: optional element width in bytes for this reference's
    array, overriding the machine-model default ``SamplerConfig.ds`` in
    the false-sharing analysis (:mod:`pluss.analysis.falseshare`) — a
    float32 field in a double-default model packs twice as many elements
    per cache line, which is exactly what decides whether neighboring
    parallel iterations falsely share a line.  The engine's element→line
    rule — and therefore the footprint/cold oracle
    (:mod:`pluss.analysis.footprint`), which must match the engine
    exactly — stays on ``cfg.ds`` (one global width per run, like the
    reference's ``-DDS``); all refs of one array must agree on the
    override.
    """

    name: str
    array: str
    addr_terms: tuple[tuple[int, int], ...]
    addr_base: int = 0
    share_span: int | None = None
    is_write: bool = False
    dtype_bytes: int | None = None


@dataclasses.dataclass(frozen=True)
class Loop:
    """A loop: ``for iv in (start, start+step, ...) x trip: body``.

    ``body`` is an ordered tuple of :class:`Ref` and nested :class:`Loop` items,
    executed in order each iteration.

    ``bound_coef``: optional ``(a, b)`` making this an inner TRIANGULAR loop:
    its effective trip at parallel index ``k`` (0-based index of the nest's
    outermost loop) is ``a + b*k``, e.g. PolyBench 4.2 syrk's ``j <= i`` is
    ``(1, 1)``.  ``trip`` must be the static maximum (``a + b*(ptrip-1)``).
    Restrictions (validated by :func:`flatten_nest`): only inner loops may be
    bounded, bounds depend on the parallel index alone, and bounded loops
    must not nest inside each other — that keeps every stream position
    AFFINE in ``k``, which is what lets the engine enumerate triangular
    nests with the same iota arithmetic as rectangular ones (plus one
    per-thread clock table for the varying per-iteration body size).

    ``start_coef``: the loop's first VALUE is ``start + start_coef*k`` —
    upper-triangular iteration like trmm's ``k in [i+1, m)`` is
    ``start=1, start_coef=1, bound_coef=(m-1, -1)``.  Affects addresses only
    (iteration values), never stream positions.

    ``bound_level``: which enclosing loop's INDEX the bound references —
    0 (default) is the parallel loop (the contract above); ``l > 0`` makes
    this a DOUBLY-triangular loop whose trip is ``a + b*idx[l]`` (cholesky's
    ``k < j`` inside ``j < i`` is ``bound_coef=(0, 1), bound_level=1``).
    Stream positions then become quadratic in the indices; the closed forms
    stay exact via ``tri(x) = x*(x-1)/2`` terms (see :func:`flatten_nest_quad`).
    Restrictions (validated): the referenced level must have
    ``start=0, step=1, start_coef=0`` (so index == value on every walker),
    and a loop bounded on an inner level must not itself contain bounded
    loops (degree <= 2).
    """

    trip: int
    body: tuple[Union["Loop", Ref], ...]
    start: int = 0
    step: int = 1
    bound_coef: tuple[int, int] | None = None
    start_coef: int = 0
    bound_level: int = 0


@dataclasses.dataclass(frozen=True)
class LoopNestSpec:
    """A workload: a sequence of parallel loop nests over named arrays.

    ``arrays``: (name, total elements) per array, in declaration order.  The
    cold-miss flush order of the reference (C, then A, then B for GEMM —
    ``gemm_sampler.rs:280-282``) is the order of this tuple.

    ``nests``: each entry is one ``#pragma pluss parallel`` loop
    (``c_lib/test/gemm.ppcg_omp.c:90``); its outermost dimension is chunked over
    simulated threads by the dispatcher.  Nests execute back-to-back; per-thread
    clocks and last-access tables persist across nests and are flushed once at
    the end, matching the generated sampler pattern (``…omp.cpp:306-319``).
    """

    name: str
    arrays: tuple[tuple[str, int], ...]
    nests: tuple[Loop, ...]

    def array_index(self, name: str) -> int:
        for i, (a, _) in enumerate(self.arrays):
            if a == name:
                return i
        raise KeyError(name)

    def line_counts(self, cfg: SamplerConfig = DEFAULT) -> list[int]:
        """Cache lines per array: ceil(elements * DS / CLS)."""
        return [-(-n * cfg.ds // cfg.cls) for _, n in self.arrays]

    def line_bases(self, cfg: SamplerConfig = DEFAULT) -> list[int]:
        """Exclusive prefix sum of line_counts: global line-id base per array."""
        bases, acc = [], 0
        for n in self.line_counts(cfg):
            bases.append(acc)
            acc += n
        return bases

    def total_lines(self, cfg: SamplerConfig = DEFAULT) -> int:
        return sum(self.line_counts(cfg))


def loop_size(item: Union[Loop, Ref]) -> int:
    """Total accesses performed by one execution of ``item`` (static max for
    bounded loops — their ``trip`` is the declared maximum)."""
    if isinstance(item, Ref):
        return 1
    return item.trip * sum(loop_size(b) for b in item.body)


def nest_depth(item: Union[Loop, Ref]) -> int:
    """Deepest loop-chain length under ``item`` (a bare Ref is depth 0).
    The band size the transform prover permutes/tiles over."""
    if isinstance(item, Ref):
        return 0
    return 1 + max((nest_depth(b) for b in item.body), default=0)


def loop_size_affine(item: Union[Loop, Ref]) -> tuple[int, int]:
    """Accesses of one execution of ``item`` as ``c0 + c1*k`` (``k`` = the
    parallel index).  Rejects a bounded loop containing another bounded
    loop — that product would be quadratic in ``k``, outside the affine
    contract the engine's iota enumeration relies on."""
    if isinstance(item, Ref):
        return (1, 0)
    b0 = b1 = 0
    for b in item.body:
        c0, c1 = loop_size_affine(b)
        b0 += c0
        b1 += c1
    if item.bound_coef is not None:
        if item.bound_level:
            raise ValueError(
                "loop bounded on an inner level (bound_level > 0): sizes "
                "are quadratic — use the quad accounting "
                "(nest_iteration_sizes / flatten_nest_quad)"
            )
        if b1:
            raise ValueError(
                "triangular (bounded) loops must not nest inside each other"
            )
        a, b = item.bound_coef
        return (a * b0, b * b0)
    return (item.trip * b0, item.trip * b1)


@dataclasses.dataclass(frozen=True)
class FlatRef:
    """A reference flattened against its enclosing loop chain.

    For occurrence with per-level indices ``idx[0..d]`` (index space, not value
    space) at parallel index ``k`` the stream position inside one execution of
    the nest is::

        pos = (offset + offset_k*k) + sum(idx[l] * (pos_stride[l] + pos_stride_k[l]*k))

    and the element address is::

        addr = addr_base + sum(addr_coef[l] * (start[l] + step[l]*idx[l]))

    ``pos_stride[l]`` is the access count of one iteration of loop ``l``'s
    body; the ``*_k`` terms are its slope in ``k`` (nonzero only when a
    triangular loop sits below — see :class:`Loop` ``bound_coef``).
    ``bounds[l]`` is loop ``l``'s ``(a, b)`` bound or None; a bounded
    level's valid index range is ``idx[l] < a + b*k``.
    """

    ref: Ref
    trips: tuple[int, ...]
    starts: tuple[int, ...]
    steps: tuple[int, ...]
    pos_strides: tuple[int, ...]
    offset: int
    addr_coefs: tuple[int, ...]  # dense, one per enclosing loop depth
    pos_strides_k: tuple[int, ...] = ()
    offset_k: int = 0
    bounds: tuple[tuple[int, int] | None, ...] = ()
    #: per-level start slope: iv[l] = starts[l] + starts_k[l]*k + idx[l]*steps[l]
    starts_k: tuple[int, ...] = ()
    #: QUAD nests only — per-level coefficient of ``tri(idx[l]) = idx*(idx-1)/2``
    #: in the position (zero tuple/0 for affine nests, so every consumer may
    #: evaluate them unconditionally):
    pos_quads: tuple[int, ...] = ()
    #: coefficient of ``tri(k)`` in the position offset (k = parallel index)
    offset_g2: int = 0
    #: inner-level bound masks: entries ``(level, a, b, ref_level)`` meaning
    #: ``idx[level] < a + b*idx[ref_level]`` with ``ref_level >= 1`` (the
    #: parallel-level bounds stay in ``bounds``)
    inner_bounds: tuple[tuple[int, int, int, int], ...] = ()


def nest_is_quad(nest: Loop) -> bool:
    """True when the nest needs the quadratic-position flatten: a bound
    referencing an inner level, or bounded loops nested inside each other
    (their trip PRODUCT is quadratic in the parallel index)."""
    def bounded_inside_bounded(item) -> bool:
        if isinstance(item, Ref):
            return False
        if item.bound_coef is not None and any(
                _nest_any(b, lambda l: l.bound_coef is not None)
                for b in item.body if isinstance(b, Loop)):
            return True
        return any(bounded_inside_bounded(b) for b in item.body)

    return nest_has_inner_bounds(nest) or bounded_inside_bounded(nest)


def flatten_nest(nest: Loop) -> list[FlatRef]:
    """Flatten one parallel nest into per-reference affine occurrence specs
    (dispatches to :func:`flatten_nest_quad` for quadratic nests)."""
    if nest_is_quad(nest):
        return flatten_nest_quad(nest)
    out: list[FlatRef] = []
    if nest.bound_coef is not None or nest.start_coef:
        raise SpecContractError(
            "the parallel (outermost) loop must be rectangular; bound_coef/"
            "start_coef are for inner loops",
            "PL401",
        )

    def check_bound(loop: Loop) -> None:
        a, b = loop.bound_coef
        ends = (a, a + b * (nest.trip - 1))
        if min(ends) < 0 or max(ends) > loop.trip:
            raise SpecContractError(
                f"bound {loop.bound_coef} leaves [0, trip={loop.trip}] over "
                f"parallel indices [0, {nest.trip})",
                "PL402",
            )

    def walk(loop: Loop, chain: list[Loop], off0: int, off1: int) -> None:
        chain = chain + [loop]
        b_off0 = b_off1 = 0
        for item in loop.body:
            if isinstance(item, Ref):
                trips = tuple(l.trip for l in chain)
                starts = tuple(l.start for l in chain)
                steps = tuple(l.step for l in chain)
                s_aff = []
                for l in chain:
                    s0 = s1 = 0
                    for b in l.body:
                        c0, c1 = loop_size_affine(b)
                        s0 += c0
                        s1 += c1
                    s_aff.append((s0, s1))
                coefs = [0] * len(chain)
                for depth, coef in item.addr_terms:
                    if not 0 <= depth < len(chain):
                        raise SpecContractError(
                            f"ref {item.name}: addr term depth {depth} exceeds "
                            f"loop chain depth {len(chain)}",
                            "PL403",
                        )
                    coefs[depth] += coef
                out.append(
                    FlatRef(
                        ref=item,
                        trips=trips,
                        starts=starts,
                        steps=steps,
                        pos_strides=tuple(s[0] for s in s_aff),
                        offset=off0 + b_off0,
                        addr_coefs=tuple(coefs),
                        pos_strides_k=tuple(s[1] for s in s_aff),
                        offset_k=off1 + b_off1,
                        bounds=tuple(l.bound_coef for l in chain),
                        starts_k=tuple(l.start_coef for l in chain),
                    )
                )
                b_off0 += 1
            else:
                if item.bound_coef is not None:
                    check_bound(item)
                walk(item, chain, off0 + b_off0, off1 + b_off1)
                s0, s1 = loop_size_affine(item)
                b_off0 += s0
                b_off1 += s1

    walk(nest, [], 0, 0)
    return out


def nest_iteration_size(nest: Loop) -> int:
    """MAX accesses per iteration of the nest's outermost (parallel) loop
    (for bounded nests: the size evaluated at its worst parallel index —
    used for static shapes and window sizing)."""
    if nest_is_quad(nest):
        return int(_nest_sizes_full(nest).max())
    n0, n1 = nest_iteration_size_affine(nest)
    if n1 == 0:
        return n0
    return max(n0, n0 + n1 * (nest.trip - 1))


def nest_iteration_sizes(nest: Loop, gs) -> "np.ndarray":
    """EXACT accesses per parallel iteration at parallel indices ``gs`` —
    valid for any supported nest (affine or quad).  The quad clock tables
    are built from this (the affine fast path keeps the ``n0 + n1*g``
    closed form).  The full [trip] vector is computed once per nest and
    memoized (one engine.run consults it from geometry sizing, the clock
    table, and sampling)."""
    import numpy as np

    return _nest_sizes_full(nest)[np.asarray(gs, np.int64)]


def slot_sizes(nest: Loop, owned, trip: int, chunk_size: int):
    """``(slot, valid)``: exact accesses at every (thread, round,
    chunk-slot) of an ``owned`` chunk matrix (invalid slots 0), for any
    supported nest shape — the single home of the per-slot size rule
    shared by the engine's clock tables and sampling's window counts."""
    import numpy as np

    g = owned[:, :, None].astype(np.int64) * chunk_size \
        + np.arange(chunk_size)
    valid = (owned[:, :, None] >= 0) & (g < trip)
    if nest_is_quad(nest):
        sizes = nest_iteration_sizes(nest, np.clip(g, 0, trip - 1))
        slot = np.where(valid, sizes, 0)
    else:
        n0, n1 = nest_iteration_size_affine(nest)
        slot = np.where(valid, n0 + n1 * g, 0)
    return slot, valid


@functools.lru_cache(maxsize=128)
def _nest_sizes_full(nest: Loop) -> "np.ndarray":
    import numpy as np

    gs = np.arange(nest.trip, dtype=np.int64)

    def size(item, env: dict, level: int) -> "np.ndarray | int":
        # env maps enclosing level -> index value (np array over gs or int);
        # ``level`` is the depth ``item`` itself sits at (refs: unused)
        if isinstance(item, Ref):
            return 1
        if item.bound_coef is None:
            trips = item.trip
        else:
            a, b = item.bound_coef
            trips = a + b * np.asarray(env[item.bound_level])
        if not _any_child_bounded_on(item, level):
            body = sum(size(b, {**env, level: 0}, level + 1)
                       for b in item.body)
            return trips * body
        # some descendant's trip references THIS loop's index: sum per-t
        tmax = int(np.max(trips))
        total = np.zeros_like(gs)
        for t in range(tmax):
            live = t < trips
            body = sum(size(b, {**env, level: t}, level + 1)
                       for b in item.body)
            total = total + np.where(live, body, 0)
        return total

    body = sum(size(b, {0: gs}, 1) for b in nest.body)
    return np.broadcast_to(np.asarray(body, np.int64), gs.shape).copy()


def _any_child_bounded_on(loop: Loop, level: int) -> bool:
    """True when any loop in ``loop``'s body tree is bounded on ``level``."""
    return any(
        _nest_any(b, lambda l: l.bound_coef is not None
                  and l.bound_level == level)
        for b in loop.body if isinstance(b, Loop)
    )


def _nest_any(nest: Loop, pred) -> bool:
    """True when ``pred(loop)`` holds for any loop in the nest tree."""
    def walk(item) -> bool:
        if isinstance(item, Ref):
            return False
        return pred(item) or any(walk(b) for b in item.body)

    return walk(nest)


def nest_has_bounds(nest: Loop) -> bool:
    """True when any loop in the nest is bounded (``bound_coef``).

    This — not the NET body slope ``n1`` — must select the triangular
    (clock-table) position path: sibling bounded loops with canceling
    slopes (e.g. ``(1, 1)`` next to ``(1, -1)``) leave the total body size
    constant while refs after the first sibling still have nonzero
    ``offset_k``, which the rectangular closed form drops."""
    return _nest_any(nest, lambda l: l.bound_coef is not None)


def nest_has_inner_bounds(nest: Loop) -> bool:
    """True when any loop's bound references an INNER level (``bound_level
    > 0``) — the doubly-triangular (quadratic-position) contract.  Such
    nests flatten via :func:`flatten_nest_quad` and always take the
    engine's clock-table sort path."""
    return _nest_any(
        nest,
        lambda l: l.bound_coef is not None and l.bound_level > 0,
    )


def nest_has_varying_start(nest: Loop) -> bool:
    """True when any loop in the nest has a nonzero ``start_coef`` — such
    nests break the template path's shift-invariance even when their trip
    counts are constant, because iteration VALUES (addresses) shift with
    the parallel index."""
    return _nest_any(nest, lambda l: bool(l.start_coef))


def _tri_of_const(c: int) -> int:
    return c * (c - 1) // 2


class _QuadContractError(SpecContractError):
    code = "PL405"

    def __init__(self, what: str):
        super().__init__(
            f"outside the quadratic position contract: {what} (positions "
            "must stay degree <= 2 with integer closed forms)"
        )


def _fadd(a: dict, b: dict) -> dict:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) + v
    return {k: v for k, v in out.items() if v}


def _fscale(f: dict, c: int) -> dict:
    return {k: cv for k, v in f.items() if (cv := v * c)}


def _fsum_over(f: dict, tdesc) -> dict:
    """``sum_{t in [0, T)} f(t, ...)`` over the position-form monomial basis
    ``{1, g, tri(g)='g2', idx_l=('i',l), tri(idx_l)=('t',l), idx_l*g=('ig',l)}``.

    ``tdesc``: ``('const', c)`` | ``('g', a, b)`` (T = a + b*g) |
    ``('idx', m, a, b)`` (T = a + b*idx_m).  The summand references the
    summation variable via the ``self_level`` keys, split off below.
    Anything that would leave the basis (degree 3, inner-inner crosses)
    raises :class:`_QuadContractError` — exactness is never approximated.
    """
    kind = tdesc[0]
    self_l = tdesc[1] if kind == "idx" else None
    # split f = A + B*t (+ C*tri(t) + D*t*g, each legal only case-by-case)
    A = dict(f)
    B = A.pop(("i", "self"), 0)
    C = A.pop(("t", "self"), 0)
    D = A.pop(("ig", "self"), 0)
    if C:
        raise _QuadContractError("summing a tri(t) term (degree 3)")

    def tri_of_T() -> dict:
        # tri(a + b*v) = b^2*tri(v) + (b*(b-1)//2 + a*b)*v + tri(a)
        if kind == "const":
            return {"1": _tri_of_const(tdesc[1])}
        a, b = tdesc[-2], tdesc[-1]
        lin = b * (b - 1) // 2 + a * b
        vkey_l, vkey_t = (("g", "g2") if kind == "g"
                          else (("i", self_l), ("t", self_l)))
        return {vkey_t: b * b, vkey_l: lin, "1": _tri_of_const(a)}

    def times_T(form: dict) -> dict:
        # form * (a + b*v); form holds NO self keys (split off above)
        if kind == "const":
            return _fscale(form, tdesc[1])
        a, b = tdesc[-2], tdesc[-1]
        res = _fscale(form, a)
        if b == 0:
            return res
        for k, v in form.items():
            c = v * b
            if k == "1":
                lift = {("g" if kind == "g" else ("i", self_l)): c}
            elif kind == "g" and k == "g":
                # g * g = 2*tri(g) + g
                lift = {"g2": 2 * c, "g": c}
            elif kind == "g" and isinstance(k, tuple) and k[0] == "i":
                lift = {("ig", k[1]): c}
            elif kind == "idx" and k == "g":
                lift = {("ig", self_l): c}
            elif kind == "idx" and k == ("i", self_l):
                lift = {("t", self_l): 2 * c, ("i", self_l): c}
            else:
                raise _QuadContractError(f"product {k} * bound variable")
            res = _fadd(res, lift)
        return res

    out = _fadd(times_T(A), _fscale(tri_of_T(), B))
    if D:
        # sum_{t<T} D*t*g = D*g*tri(T): integral only for a constant T
        if kind != "const":
            raise _QuadContractError("t*g term under a varying bound")
        out = _fadd(out, {"g": D * _tri_of_const(tdesc[1])})
    return out


def _self_keys(f: dict, level: int) -> dict:
    """Rekey ``level``'s monomials to the ``'self'`` markers _fsum_over
    splits on (the caller is about to sum over that level's index)."""
    ren = {("i", level): ("i", "self"), ("t", level): ("t", "self"),
           ("ig", level): ("ig", "self")}
    return {ren.get(k, k): v for k, v in f.items()}


def flatten_nest_quad(nest: Loop) -> list[FlatRef]:
    """Quad-contract flatten: same :class:`FlatRef` output as
    :func:`flatten_nest` plus the degree-2 fields (``pos_quads``,
    ``offset_g2``, ``inner_bounds``).  Within-iteration positions are
    assembled symbolically over the form basis above, so a loop bounded on
    an INNER level (``bound_level > 0`` — cholesky's ``k < j < i``) gets
    exact closed-form stream positions without any state machine.

    Validated restrictions (each raises): the parallel loop rectangular
    (as before); a bound may reference one enclosing level; the referenced
    inner level must have ``start=0, step=1, start_coef=0`` (index ==
    value on every walker — oracle and native reuse their value vectors);
    loops bounded on an inner level must not contain bounded loops.
    Varying starts (``start_coef``) remain fully supported anywhere else:
    they shift iteration VALUES (addresses, via ``FlatRef.starts_k``),
    never stream positions, so the position algebra is untouched by them.
    Shapes whose positions would leave the degree-2 basis (triple bound
    chains, nussinov-style cross bounds) raise at plan time rather than
    ever emitting approximate positions.
    """
    out: list[FlatRef] = []
    if nest.bound_coef is not None or nest.start_coef:
        raise SpecContractError(
            "the parallel (outermost) loop must be rectangular; bound_coef/"
            "start_coef are for inner loops",
            "PL401",
        )

    def tdesc_of(loop: Loop, level: int, chain: list[Loop]):
        if loop.bound_coef is None:
            return ("const", loop.trip)
        a, b = loop.bound_coef
        if loop.bound_level == 0:
            return ("g", a, b)
        m = loop.bound_level
        if not 0 < m < level:
            raise SpecContractError(
                f"bound_level {m} must name an enclosing loop "
                f"(this loop sits at depth {level})",
                "PL404",
            )
        ref = chain[m]
        if ref.start or ref.step != 1 or ref.start_coef:
            raise _QuadContractError(
                "the bound-referenced level must have start=0, step=1, "
                "start_coef=0 (index == value)"
            )
        if any(_nest_any(b, lambda l: l.bound_coef is not None)
               for b in loop.body if isinstance(b, Loop)):
            raise _QuadContractError(
                "a loop bounded on an inner level must not contain "
                "bounded loops"
            )
        return ("idx", m, a, b)

    def size_form(item, level: int, chain: list[Loop]) -> dict:
        if isinstance(item, Ref):
            return {"1": 1}
        body = {}
        for b in item.body:
            body = _fadd(body, size_form(b, level + 1, chain + [item]))
        return _fsum_over(_self_keys(body, level),
                          tdesc_of(item, level, chain))

    def static_max_index(level: int, chain: list[Loop]) -> int:
        """Largest index the loop at ``level`` can reach (static trips are
        declared maxima, so trip-1 bounds every bound chain)."""
        return chain[level].trip - 1

    def check_bound(loop: Loop, level: int, chain: list[Loop]) -> None:
        a, b = loop.bound_coef
        if not 0 <= loop.bound_level < level:
            raise SpecContractError(
                f"bound_level {loop.bound_level} must name an enclosing "
                f"loop (this loop sits at depth {level})",
                "PL404",
            )
        hi = static_max_index(loop.bound_level, chain) \
            if loop.bound_level else nest.trip - 1
        ends = (a, a + b * hi)
        if min(ends) < 0 or max(ends) > loop.trip:
            raise SpecContractError(
                f"bound {loop.bound_coef} leaves [0, trip={loop.trip}] over "
                f"referenced indices [0, {hi}]",
                "PL402",
            )

    def emit(item: Ref, chain: list[Loop], form: dict) -> None:
        d = len(chain)
        coefs = [0] * d
        for depth, coef in item.addr_terms:
            if not 0 <= depth < d:
                raise SpecContractError(
                    f"ref {item.name}: addr term depth {depth} exceeds "
                    f"loop chain depth {d}",
                    "PL403",
                )
            coefs[depth] += coef
        bounds = []
        inner = []
        for l, lp in enumerate(chain):
            if lp.bound_coef is None or lp.bound_level == 0:
                bounds.append(lp.bound_coef)
            else:
                bounds.append(None)
                inner.append((l, *lp.bound_coef, lp.bound_level))
        leftovers = set(form) - {"1", "g", "g2"} - {
            ("i", l) for l in range(1, d)} - {("t", l) for l in range(1, d)
        } - {("ig", l) for l in range(1, d)}
        if leftovers:
            raise _QuadContractError(f"unplaced position terms {leftovers}")
        out.append(FlatRef(
            ref=item,
            trips=tuple(l.trip for l in chain),
            starts=tuple(l.start for l in chain),
            steps=tuple(l.step for l in chain),
            pos_strides=tuple(form.get(("i", l), 0) for l in range(d)),
            offset=form.get("1", 0),
            addr_coefs=tuple(coefs),
            pos_strides_k=tuple(form.get(("ig", l), 0) for l in range(d)),
            offset_k=form.get("g", 0),
            bounds=tuple(bounds),
            starts_k=tuple(l.start_coef for l in chain),
            pos_quads=tuple(form.get(("t", l), 0) for l in range(d)),
            offset_g2=form.get("g2", 0),
            inner_bounds=tuple(inner),
        ))

    def walk(loop: Loop, chain: list[Loop], off: dict) -> None:
        chain = chain + [loop]
        level = len(chain) - 1
        if level > 0:
            if loop.bound_coef is not None:
                check_bound(loop, level, chain)
            # prefix of earlier iterations of THIS level: sum the body's
            # one-iteration size over t in [0, idx_level)
            body = {}
            for b in loop.body:
                body = _fadd(body, size_form(b, level + 1, chain))
            off = _fadd(off, _fsum_over(_self_keys(body, level),
                                        ("idx", level, 0, 1)))
        b_off: dict = {}
        for item in loop.body:
            if isinstance(item, Ref):
                emit(item, chain, _fadd(off, b_off))
                b_off = _fadd(b_off, {"1": 1})
            else:
                walk(item, chain, _fadd(off, b_off))
                b_off = _fadd(b_off, size_form(item, level + 1, chain))
    walk(nest, [], {})
    return out


def nest_iteration_size_affine(nest: Loop) -> tuple[int, int]:
    """Accesses per parallel iteration as ``n0 + n1*k`` (n1 != 0 marks a
    triangular nest)."""
    n0 = n1 = 0
    for b in nest.body:
        c0, c1 = loop_size_affine(b)
        n0 += c0
        n1 += c1
    return n0, n1


def share_span_formula(trip: int, start: int = 0, step: int = 1) -> int:
    """The generated share-threshold: ``((1*((trip-start)/step)+1)*((trip-start)/step)+1)``.

    From the generated comparison at ``…omp.cpp:202`` /
    ``gemm_sampler.rs:198-199`` — for GEMM-128 this is 129*128+1 = 16513.
    """
    t = (trip - start) // step
    return (t + 1) * t + 1
