"""Loop-nest & reference specs: the declarative replacement for generated samplers.

The reference encodes each workload as compiler-*generated state-machine code*
(e.g. the GEMM walk in ``/root/reference/src/gemm_sampler.rs:56-293`` and
``c_lib/test/sampler/gemm-t4-pluss-pro-model-ri-omp.cpp:37-333``): one hardcoded
``if ref == "C0" ...`` block per static reference, with the iteration vector
mutated in place.  That design needs new generated code per workload and walks one
access at a time.

Here a workload is a small declarative tree of :class:`Loop` and :class:`Ref`
nodes.  Because every loop is rectangular (constant trip count), the *position in
the access stream* and the *element address* of every occurrence of every static
reference are affine functions of the iteration vector.  The XLA engine
(:mod:`pluss.engine`) exploits that to enumerate whole reference streams with
broadcasted ``iota`` arithmetic — no per-access control flow, no state machine.

Semantics preserved from the reference:

- Program order of references inside a loop body = their order in ``Loop.body``
  (the reference's ref priority / topological order, ``src/iteration.rs:123-129``).
- One logical clock per simulated thread, incremented once per access
  (``gemm_sampler.rs:133``: ``count[tid] += 1`` in every state).
- Share classification happens per *static reference* with a span threshold:
  a reuse is "share" (crosses threads) iff it is closer to the carrying-loop span
  than to 0 — ``distance_to(reuse,0) > distance_to(reuse,span)``
  (``gemm_sampler.rs:199``, ``…omp.cpp:203``).  For integer reuse/span this is
  exactly ``2*reuse > span``.
"""

from __future__ import annotations

import dataclasses
from typing import Union

from pluss.config import SamplerConfig, DEFAULT


@dataclasses.dataclass(frozen=True)
class Ref:
    """One static memory reference inside a loop body.

    ``addr_terms`` maps *loop depth* (0 = the nest's outermost/parallel loop) to
    the row-major address coefficient; the element address of an occurrence is
    ``addr_base + sum(coef * iv[depth])`` over the terms, with ``iv`` the actual
    iteration *values* (``start + step*index``), matching the reference's
    ``GetAddress_*`` functions (``…omp.cpp:12-35``).

    ``share_span``: if not None, reuses observed at this reference are tested for
    cross-thread sharing against this span (see module docstring).  The GEMM
    value 16513 comes from the generated comment ``(((1)*((128-0)/1)+1)*((128-0)/1)+1)``
    (``…omp.cpp:202``), i.e. ``(trip+1)*trip + 1`` of the carrying loop.
    """

    name: str
    array: str
    addr_terms: tuple[tuple[int, int], ...]
    addr_base: int = 0
    share_span: int | None = None


@dataclasses.dataclass(frozen=True)
class Loop:
    """A loop: ``for iv in (start, start+step, ...) x trip: body``.

    ``body`` is an ordered tuple of :class:`Ref` and nested :class:`Loop` items,
    executed in order each iteration.

    ``bound_coef``: optional ``(a, b)`` making this an inner TRIANGULAR loop:
    its effective trip at parallel index ``k`` (0-based index of the nest's
    outermost loop) is ``a + b*k``, e.g. PolyBench 4.2 syrk's ``j <= i`` is
    ``(1, 1)``.  ``trip`` must be the static maximum (``a + b*(ptrip-1)``).
    Restrictions (validated by :func:`flatten_nest`): only inner loops may be
    bounded, bounds depend on the parallel index alone, and bounded loops
    must not nest inside each other — that keeps every stream position
    AFFINE in ``k``, which is what lets the engine enumerate triangular
    nests with the same iota arithmetic as rectangular ones (plus one
    per-thread clock table for the varying per-iteration body size).

    ``start_coef``: the loop's first VALUE is ``start + start_coef*k`` —
    upper-triangular iteration like trmm's ``k in [i+1, m)`` is
    ``start=1, start_coef=1, bound_coef=(m-1, -1)``.  Affects addresses only
    (iteration values), never stream positions.
    """

    trip: int
    body: tuple[Union["Loop", Ref], ...]
    start: int = 0
    step: int = 1
    bound_coef: tuple[int, int] | None = None
    start_coef: int = 0


@dataclasses.dataclass(frozen=True)
class LoopNestSpec:
    """A workload: a sequence of parallel loop nests over named arrays.

    ``arrays``: (name, total elements) per array, in declaration order.  The
    cold-miss flush order of the reference (C, then A, then B for GEMM —
    ``gemm_sampler.rs:280-282``) is the order of this tuple.

    ``nests``: each entry is one ``#pragma pluss parallel`` loop
    (``c_lib/test/gemm.ppcg_omp.c:90``); its outermost dimension is chunked over
    simulated threads by the dispatcher.  Nests execute back-to-back; per-thread
    clocks and last-access tables persist across nests and are flushed once at
    the end, matching the generated sampler pattern (``…omp.cpp:306-319``).
    """

    name: str
    arrays: tuple[tuple[str, int], ...]
    nests: tuple[Loop, ...]

    def array_index(self, name: str) -> int:
        for i, (a, _) in enumerate(self.arrays):
            if a == name:
                return i
        raise KeyError(name)

    def line_counts(self, cfg: SamplerConfig = DEFAULT) -> list[int]:
        """Cache lines per array: ceil(elements * DS / CLS)."""
        return [-(-n * cfg.ds // cfg.cls) for _, n in self.arrays]

    def line_bases(self, cfg: SamplerConfig = DEFAULT) -> list[int]:
        """Exclusive prefix sum of line_counts: global line-id base per array."""
        bases, acc = [], 0
        for n in self.line_counts(cfg):
            bases.append(acc)
            acc += n
        return bases

    def total_lines(self, cfg: SamplerConfig = DEFAULT) -> int:
        return sum(self.line_counts(cfg))


def loop_size(item: Union[Loop, Ref]) -> int:
    """Total accesses performed by one execution of ``item`` (static max for
    bounded loops — their ``trip`` is the declared maximum)."""
    if isinstance(item, Ref):
        return 1
    return item.trip * sum(loop_size(b) for b in item.body)


def loop_size_affine(item: Union[Loop, Ref]) -> tuple[int, int]:
    """Accesses of one execution of ``item`` as ``c0 + c1*k`` (``k`` = the
    parallel index).  Rejects a bounded loop containing another bounded
    loop — that product would be quadratic in ``k``, outside the affine
    contract the engine's iota enumeration relies on."""
    if isinstance(item, Ref):
        return (1, 0)
    b0 = b1 = 0
    for b in item.body:
        c0, c1 = loop_size_affine(b)
        b0 += c0
        b1 += c1
    if item.bound_coef is not None:
        if b1:
            raise ValueError(
                "triangular (bounded) loops must not nest inside each other"
            )
        a, b = item.bound_coef
        return (a * b0, b * b0)
    return (item.trip * b0, item.trip * b1)


@dataclasses.dataclass(frozen=True)
class FlatRef:
    """A reference flattened against its enclosing loop chain.

    For occurrence with per-level indices ``idx[0..d]`` (index space, not value
    space) at parallel index ``k`` the stream position inside one execution of
    the nest is::

        pos = (offset + offset_k*k) + sum(idx[l] * (pos_stride[l] + pos_stride_k[l]*k))

    and the element address is::

        addr = addr_base + sum(addr_coef[l] * (start[l] + step[l]*idx[l]))

    ``pos_stride[l]`` is the access count of one iteration of loop ``l``'s
    body; the ``*_k`` terms are its slope in ``k`` (nonzero only when a
    triangular loop sits below — see :class:`Loop` ``bound_coef``).
    ``bounds[l]`` is loop ``l``'s ``(a, b)`` bound or None; a bounded
    level's valid index range is ``idx[l] < a + b*k``.
    """

    ref: Ref
    trips: tuple[int, ...]
    starts: tuple[int, ...]
    steps: tuple[int, ...]
    pos_strides: tuple[int, ...]
    offset: int
    addr_coefs: tuple[int, ...]  # dense, one per enclosing loop depth
    pos_strides_k: tuple[int, ...] = ()
    offset_k: int = 0
    bounds: tuple[tuple[int, int] | None, ...] = ()
    #: per-level start slope: iv[l] = starts[l] + starts_k[l]*k + idx[l]*steps[l]
    starts_k: tuple[int, ...] = ()


def flatten_nest(nest: Loop) -> list[FlatRef]:
    """Flatten one parallel nest into per-reference affine occurrence specs."""
    out: list[FlatRef] = []
    if nest.bound_coef is not None or nest.start_coef:
        raise ValueError(
            "the parallel (outermost) loop must be rectangular; bound_coef/"
            "start_coef are for inner loops"
        )

    def check_bound(loop: Loop) -> None:
        a, b = loop.bound_coef
        ends = (a, a + b * (nest.trip - 1))
        if min(ends) < 0 or max(ends) > loop.trip:
            raise ValueError(
                f"bound {loop.bound_coef} leaves [0, trip={loop.trip}] over "
                f"parallel indices [0, {nest.trip})"
            )

    def walk(loop: Loop, chain: list[Loop], off0: int, off1: int) -> None:
        chain = chain + [loop]
        b_off0 = b_off1 = 0
        for item in loop.body:
            if isinstance(item, Ref):
                trips = tuple(l.trip for l in chain)
                starts = tuple(l.start for l in chain)
                steps = tuple(l.step for l in chain)
                s_aff = []
                for l in chain:
                    s0 = s1 = 0
                    for b in l.body:
                        c0, c1 = loop_size_affine(b)
                        s0 += c0
                        s1 += c1
                    s_aff.append((s0, s1))
                coefs = [0] * len(chain)
                for depth, coef in item.addr_terms:
                    if depth >= len(chain):
                        raise ValueError(
                            f"ref {item.name}: addr term depth {depth} exceeds "
                            f"loop chain depth {len(chain)}"
                        )
                    coefs[depth] += coef
                out.append(
                    FlatRef(
                        ref=item,
                        trips=trips,
                        starts=starts,
                        steps=steps,
                        pos_strides=tuple(s[0] for s in s_aff),
                        offset=off0 + b_off0,
                        addr_coefs=tuple(coefs),
                        pos_strides_k=tuple(s[1] for s in s_aff),
                        offset_k=off1 + b_off1,
                        bounds=tuple(l.bound_coef for l in chain),
                        starts_k=tuple(l.start_coef for l in chain),
                    )
                )
                b_off0 += 1
            else:
                if item.bound_coef is not None:
                    check_bound(item)
                walk(item, chain, off0 + b_off0, off1 + b_off1)
                s0, s1 = loop_size_affine(item)
                b_off0 += s0
                b_off1 += s1

    walk(nest, [], 0, 0)
    return out


def nest_iteration_size(nest: Loop) -> int:
    """MAX accesses per iteration of the nest's outermost (parallel) loop
    (for bounded nests: the affine size evaluated at its worst parallel
    index — used for static shapes and window sizing)."""
    n0, n1 = nest_iteration_size_affine(nest)
    if n1 == 0:
        return n0
    return max(n0, n0 + n1 * (nest.trip - 1))


def _nest_any(nest: Loop, pred) -> bool:
    """True when ``pred(loop)`` holds for any loop in the nest tree."""
    def walk(item) -> bool:
        if isinstance(item, Ref):
            return False
        return pred(item) or any(walk(b) for b in item.body)

    return walk(nest)


def nest_has_bounds(nest: Loop) -> bool:
    """True when any loop in the nest is bounded (``bound_coef``).

    This — not the NET body slope ``n1`` — must select the triangular
    (clock-table) position path: sibling bounded loops with canceling
    slopes (e.g. ``(1, 1)`` next to ``(1, -1)``) leave the total body size
    constant while refs after the first sibling still have nonzero
    ``offset_k``, which the rectangular closed form drops."""
    return _nest_any(nest, lambda l: l.bound_coef is not None)


def nest_has_varying_start(nest: Loop) -> bool:
    """True when any loop in the nest has a nonzero ``start_coef`` — such
    nests break the template path's shift-invariance even when their trip
    counts are constant, because iteration VALUES (addresses) shift with
    the parallel index."""
    return _nest_any(nest, lambda l: bool(l.start_coef))


def nest_iteration_size_affine(nest: Loop) -> tuple[int, int]:
    """Accesses per parallel iteration as ``n0 + n1*k`` (n1 != 0 marks a
    triangular nest)."""
    n0 = n1 = 0
    for b in nest.body:
        c0, c1 = loop_size_affine(b)
        n0 += c0
        n1 += c1
    return n0, n1


def share_span_formula(trip: int, start: int = 0, step: int = 1) -> int:
    """The generated share-threshold: ``((1*((trip-start)/step)+1)*((trip-start)/step)+1)``.

    From the generated comparison at ``…omp.cpp:202`` /
    ``gemm_sampler.rs:198-199`` — for GEMM-128 this is 129*128+1 = 16513.
    """
    t = (trip - start) // step
    return (t + 1) * t + 1
