"""pluss.obs — structured telemetry for the whole pipeline.

One substrate (counters / gauges / spans / events → an append-only JSONL
sink, :mod:`pluss.obs.telemetry`), optional xprof trace annotation
(:mod:`pluss.obs.xprof`, ``PLUSS_XPROF=dir``), and the ``pluss stats``
aggregator (:mod:`pluss.obs.stats`).  Disabled (the default) every hook
is a near-free no-op and the instrumented pipelines are bit-identical —
telemetry is observably passive, enforced by tests/test_obs.py.

Enable with ``PLUSS_TELEMETRY=<events.jsonl>`` or ``--telemetry`` on the
CLI; ``PLUSS_PROM=<file>`` additionally exports a Prometheus-style
textfile at shutdown.
"""

from pluss.obs.telemetry import (  # noqa: F401
    NOOP_SPAN,
    SCHEMA_VERSION,
    LatencyReservoir,
    Telemetry,
    active,
    configure,
    counter_add,
    counters,
    enabled,
    ensure_session,
    event,
    flush_metrics,
    gauge_set,
    gauges,
    render_prom,
    shutdown,
    span,
    trace_event,
)
