"""Per-request trace context: the id that stitches one serve request's
telemetry back together across threads.

A serve request ``rid`` crosses many threads on its way to an answer:
the connection handler admits it, a device loop coalesces it into a
batch, a background compile may park it, and the trace feed pool encodes
its windows off-thread.  Every one of those stages already records
spans/events into the PR-5 telemetry stream — this module adds the ONE
missing bit, a propagated trace id, so ``pluss stats --trace <rid>``
can later rebuild the request's causal story from the flat stream.

Two propagation primitives:

- :func:`bind` — a context manager installing ``rid`` as the current
  trace id on THIS thread (a ``threading.local`` stack, so nested binds
  restore correctly — e.g. a batch dispatch bound to the lead request
  re-binding per member for the demux spans);
- :func:`capture` / :func:`attach` — the explicit cross-thread handoff:
  the submitting side captures a token (just the current id), the worker
  side attaches it around the work it performs on that request's behalf
  (feed-pool encode jobs, background compiles).

The telemetry layer (:mod:`pluss.obs.telemetry`) consults
:func:`current` when recording spans (captured at ``__enter__``, so the
stamp names the context the work STARTED under) and events.  The
disabled-telemetry path never reaches this module: ``obs.span`` and
friends return before any context lookup, so the None-check no-op
contract of PR 5 is untouched, and binding a context cannot perturb the
observed computation — it only adds a field to records that were being
written anyway (bit-identity pinned by tests/test_tracectx.py).
"""

from __future__ import annotations

import contextlib
import threading

_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current() -> str | None:
    """The innermost bound trace id on this thread, or None."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


@contextlib.contextmanager
def bind(trace_id: str | None):
    """Install ``trace_id`` as the current trace context for the body.

    ``None`` is accepted and means "no context" (a no-op), so call sites
    can bind unconditionally: ``with bind(req.id if traced else None)``.
    """
    if trace_id is None:
        yield
        return
    st = _stack()
    st.append(str(trace_id))
    try:
        yield
    finally:
        if st and st[-1] == str(trace_id):
            st.pop()


def capture() -> str | None:
    """A handoff token for the current context (None when unbound).

    The token is deliberately just the trace id: handing it to a worker
    thread and :func:`attach`-ing it there is equivalent to the worker
    having been bound by the submitter.
    """
    return current()


def attach(token: str | None):
    """Re-enter a :func:`capture`-d context on another thread."""
    return bind(token)
