"""Crash flight recorder: a bounded ring of recent telemetry records
inside the serve daemon, dumped to disk when something goes wrong.

Post-mortems today require having armed ``--telemetry`` BEFORE the
incident; the events leading into a watchdog abandon or a breaker trip
are otherwise simply gone.  The flight recorder closes that gap: it taps
the telemetry session (creating a MEMORY-ONLY session when none is
configured — zero bytes written anywhere in steady state) and keeps the
last ``ring`` records in a deque.  On a trigger — watchdog abandon,
breaker open, forced drain, escaped dispatch exception — the ring is
dumped atomically to ``flight-<rid-or-ts>.jsonl`` in the configured
directory, as a VALID telemetry stream: a fresh ``meta`` record first,
the ring's records (their original timestamps and trace stamps intact),
then a cumulative counter snapshot, and NO ``end`` record — exactly the
shape of a stream truncated by a crash, which ``pluss stats --check``
accepts (dangling span parents in a truncated stream are notes, not
violations).  ``pluss stats flight-*.jsonl [--trace rid]`` then reads it
like any other stream.

Ring size via ``PLUSS_FLIGHT_RING`` (default 4096 records); dump
directory via the server's ``--flight-dir`` / ``PLUSS_FLIGHT_DIR``
(default: the current directory).  Dumps are throttled per reason
(default 10 s) so a flapping trigger cannot fill the disk.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time

from pluss.obs import telemetry
from pluss.utils.envknob import env_float, env_int

#: record kinds held in the ring: stream bodies only (a dump writes its
#: own meta, and an ``end`` would mark the dump as a finished stream,
#: turning its legitimately-dangling span parents into violations)
_RING_KINDS = ("span", "counter", "gauge", "event")


class FlightRecorder:
    """Tap → ring → atomic dump.  Thread-safe; the tap runs on every
    emitting thread and must stay O(1) (one deque append)."""

    def __init__(self, out_dir: str | None = None,
                 ring: int | None = None,
                 throttle_s: float | None = None):
        self.out_dir = out_dir or os.environ.get("PLUSS_FLIGHT_DIR") or "."
        cap = ring if ring is not None \
            else env_int("PLUSS_FLIGHT_RING", 4096, minimum=16)
        self.throttle_s = throttle_s if throttle_s is not None \
            else env_float("PLUSS_FLIGHT_THROTTLE_S", 10.0, 0.0)
        self._ring: collections.deque = collections.deque(maxlen=cap)
        self._lock = threading.Lock()
        self._tel: telemetry.Telemetry | None = None
        self._last_dump: dict[str, float] = {}
        self.dumps: list[str] = []

    # -- lifecycle ----------------------------------------------------------

    def arm(self) -> None:
        """Start recording.  Installs the tap on the active telemetry
        session, creating a memory-only one when telemetry is disabled —
        the daemon's instrumentation then feeds the ring (and nothing
        else: no sink file exists until a dump fires)."""
        if self._tel is not None:
            return
        self._tel = telemetry.ensure_session()
        self._tel.add_tap(self._tap)

    def disarm(self) -> None:
        if self._tel is not None:
            self._tel.remove_tap(self._tap)
            self._tel = None

    def _tap(self, rec: dict) -> None:
        if rec.get("ev") in _RING_KINDS:
            self._ring.append(rec)

    # -- dumping ------------------------------------------------------------

    def dump(self, reason: str, rid: str | None = None) -> str | None:
        """Write the ring as ``flight-<rid-or-ts>.jsonl``; returns the
        path, or None when throttled or the write failed (a flight dump
        must never take the daemon down with it)."""
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(reason)
            if last is not None and now - last < self.throttle_s:
                return None
            self._last_dump[reason] = now
            snap = list(self._ring)
        tel = self._tel
        tag = _sanitize(rid) if rid else f"{time.time():.3f}"
        path = os.path.join(self.out_dir, f"flight-{tag}.jsonl")
        meta = {"ev": "meta", "schema": telemetry.SCHEMA_VERSION,
                "pid": os.getpid(), "argv": sys.argv[:8],
                "t_wall": round(time.time(), 3), "clock": "monotonic",
                "flight_reason": reason}
        if rid:
            meta["flight_trace"] = rid
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(json.dumps(meta, separators=(",", ":")) + "\n")
                for rec in snap:
                    f.write(json.dumps(rec, separators=(",", ":")) + "\n")
                if tel is not None:
                    t = round(time.monotonic() - tel._t0, 6)
                    for name, v in sorted(tel.counters().items()):
                        f.write(json.dumps(
                            {"ev": "counter", "name": name, "value": v,
                             "t": t}, separators=(",", ":")) + "\n")
            os.replace(tmp, path)
        except (OSError, TypeError, ValueError) as e:
            print(f"flight recorder: dump for {reason!r} failed: {e}",
                  file=sys.stderr)
            return None
        from pluss import obs

        obs.counter_add("flight.dumps")
        obs.event("flight.dump", reason=reason, path=path,
                  records=len(snap))
        self.dumps.append(path)
        print(f"flight recorder: {reason} -> {path} "
              f"({len(snap)} ring record(s))", file=sys.stderr)
        return path


def _sanitize(rid: str) -> str:
    out = "".join(c if c.isalnum() or c in "-_." else "_" for c in rid)
    return out[:80] or "rid"
