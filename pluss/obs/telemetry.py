"""Structured telemetry core: counters, gauges, spans, and a JSONL sink.

The runtime is a multi-stage pipeline (plan → compile → window dispatch →
CRI post-pass; pack → double-buffered h2d feed → segmented replay; sharded
runs; a degradation ladder) whose behavior was only visible through ad-hoc
``perf_counter`` locals and bench tail text.  This module is the single
substrate every layer records into:

- **counters** — monotonically accumulated numbers (floats allowed: stall
  *seconds* are a counter), cumulative per process;
- **gauges** — last-value-wins samples (queue occupancy, heartbeat age);
- **spans** — monotonic-clock wall intervals, nestable per thread (a
  ``threading.local`` stack provides parent ids), with free-form
  attributes;
- **events** — discrete occurrences (a fault fired, a ladder rung taken).

Everything lands in ONE append-only JSONL stream using the resilience
Journal's write discipline (one record = one line = one ``write()`` +
flush, so a crash can only tear the final line; ``pluss stats --check``
tolerates exactly that).  Counters/gauges are additionally snapshotted as
records at every :func:`flush_metrics` and at shutdown, and can be
exported as a Prometheus-style textfile (:meth:`Telemetry.write_prom`).

The DISABLED path is the design center: with no sink configured every
module-level helper is a global-read + ``None``-check (and ``span()``
returns one shared no-op singleton), so instrumented production code pays
effectively nothing — and, enforced by tests, telemetry is observably
passive: histograms and MRCs are bit-identical with it on or off.

Enable via ``PLUSS_TELEMETRY=<path>`` (read once, lazily) or explicitly
with :func:`configure` (the CLI's ``--telemetry`` flag).
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time

from pluss.obs import tracectx

#: event-stream schema version, stamped on the meta line; ``pluss stats
#: --check`` refuses streams from a NEWER schema than it understands
SCHEMA_VERSION = 1

#: record kinds a stream may contain (the single source for stats --check)
EVENT_KINDS = ("meta", "span", "counter", "gauge", "event", "end")


class _NoopSpan:
    """The shared disabled-path span: every method is a no-op returning
    self, so ``with span(...) as s: s.set(x=1)`` costs two attribute
    lookups when telemetry is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("_tel", "name", "attrs", "_start", "_id", "_parent",
                 "_trace")

    def __init__(self, tel: "Telemetry", name: str, attrs: dict):
        self._tel = tel
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        tel = self._tel
        stack = tel._span_stack()
        self._parent = stack[-1] if stack else None
        self._id = tel._new_id()
        stack.append(self._id)
        # the trace stamp names the request context the work STARTED
        # under (a batch dispatch re-binding per member still attributes
        # the enclosing span to the lead request it entered with)
        self._trace = tracectx.current()
        self._start = time.monotonic()
        return self

    def set(self, **attrs):
        """Attach/override attributes mid-span (recorded at exit)."""
        self.attrs.update(attrs)
        return self

    def __exit__(self, etype, evalue, tb):
        dur = time.monotonic() - self._start
        tel = self._tel
        stack = tel._span_stack()
        if stack and stack[-1] == self._id:
            stack.pop()
        rec = {
            "ev": "span",
            "id": self._id,
            "name": self.name,
            "t": round(self._start - tel._t0, 6),
            "dur": round(dur, 6),
        }
        if self._parent is not None:
            rec["parent"] = self._parent
        if self._trace is not None:
            rec["trace"] = self._trace
        if self.attrs:
            rec["attrs"] = self.attrs
        if etype is not None:
            rec["error"] = etype.__name__
        th = threading.current_thread().name
        if th != "MainThread":
            rec["thread"] = th
        tel._emit(rec)
        return False


class Telemetry:
    """One process-wide telemetry session bound to a JSONL sink file.

    Thread-safe throughout: counters/gauges mutate under one lock, every
    record is a single locked ``write()`` + flush (the Journal's torn-
    line-only crash contract), and span nesting state is per-thread.
    """

    def __init__(self, path: str | None, prom_path: str | None = None):
        self.path = path
        self.prom_path = prom_path
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._tls = threading.local()
        self._id = 0
        self._t0 = time.monotonic()
        self._closed = False
        self._taps: tuple = ()
        if path is None:
            # memory-only session: no sink file — records exist only for
            # taps (the serve flight recorder's post-mortem ring) and the
            # in-memory counter/gauge maps.  Bounded by construction: the
            # maps are keyed aggregates and taps own their retention.
            self._f = None
        else:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            # one run = one stream: truncate, then append-only for the
            # run's lifetime (pluss stats reads a single run's tree)
            self._f = open(path, "w")
        self._emit({"ev": "meta", "schema": SCHEMA_VERSION,
                    "pid": os.getpid(), "argv": sys.argv[:8],
                    "t_wall": round(time.time(), 3), "clock": "monotonic"})

    # -- internals ----------------------------------------------------------

    def _span_stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _new_id(self) -> int:
        with self._lock:
            self._id += 1
            return self._id

    def _emit(self, rec: dict) -> None:
        for tap in self._taps:
            try:
                tap(rec)
            except Exception:
                pass   # a broken tap must never sink the observed run
        if self._f is None:
            return
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        with self._lock:
            if self._closed:
                return
            try:
                self._f.write(line)
                self._f.flush()
            except OSError as e:
                # ENOSPC / read-only fs mid-run: observability must never
                # sink the run it observes — disable the sink with one
                # notice and let the computation finish (counters keep
                # accumulating in memory, they just can't flush)
                self._closed = True
                try:
                    self._f.close()
                except OSError:
                    pass
                print(f"telemetry: sink write to {self.path} failed "
                      f"({e}); disabling the event stream",
                      file=sys.stderr)

    def add_tap(self, fn) -> None:
        """Register ``fn(record_dict)`` to observe every emitted record
        (the flight recorder's feed).  Taps run outside the sink lock on
        the emitting thread and must be fast and non-raising; exceptions
        are swallowed.  The tuple swap keeps iteration lock-free."""
        with self._lock:
            self._taps = (*self._taps, fn)

    def remove_tap(self, fn) -> None:
        with self._lock:
            self._taps = tuple(t for t in self._taps if t is not fn)

    @staticmethod
    def _num(name: str, value) -> float:
        v = float(value)
        if v != v:  # NaN would poison every later aggregate silently
            raise ValueError(f"telemetry value for {name!r} is NaN")
        return v

    # -- recording API ------------------------------------------------------

    def counter_add(self, name: str, value: float = 1) -> None:
        v = self._num(name, value)
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + v

    def gauge_set(self, name: str, value: float) -> None:
        v = self._num(name, value)
        with self._lock:
            self._gauges[name] = v
        self._emit({"ev": "gauge", "name": name,
                    "value": v, "t": round(time.monotonic() - self._t0, 6)})

    def event(self, name: str, **attrs) -> None:
        stack = self._span_stack()
        rec = {"ev": "event", "name": name,
               "t": round(time.monotonic() - self._t0, 6)}
        if stack:
            rec["parent"] = stack[-1]
        tr = tracectx.current()
        if tr is not None:
            rec["trace"] = tr
        if attrs:
            rec["attrs"] = attrs
        self._emit(rec)

    def span(self, name: str, **attrs) -> _Span:
        return _Span(self, name, attrs)

    # -- snapshots / export -------------------------------------------------

    def counters(self) -> dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def flush_metrics(self) -> None:
        """Write the cumulative counter values (and last gauge values) as
        records.  Values are CUMULATIVE, so ``pluss stats`` takes the last
        record per name — flushing often only adds durability."""
        t = round(time.monotonic() - self._t0, 6)
        for name, v in sorted(self.counters().items()):
            self._emit({"ev": "counter", "name": name, "value": v, "t": t})

    def write_prom(self, path: str | None = None) -> str:
        """Prometheus-textfile-collector export of the current counters and
        gauges (atomic tmp + replace).  Returns the path written.  The
        text itself comes from :func:`render_prom` — the SAME renderer the
        serve daemon's live ``/metrics`` endpoint serves, so a scrape and
        the textfile can never drift in format."""
        path = path or self.prom_path
        if not path:
            raise ValueError("no prometheus textfile path configured")
        text = render_prom(self.counters(), self.gauges())
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)
        return path

    def close(self) -> None:
        if self._closed:
            return
        self.flush_metrics()
        self._emit({"ev": "end",
                    "dur": round(time.monotonic() - self._t0, 6)})
        with self._lock:
            self._closed = True
            if self._f is not None:
                try:
                    self._f.flush()
                    os.fsync(self._f.fileno())
                except OSError:
                    pass
                self._f.close()
        if self.prom_path:
            try:
                self.write_prom()
            except OSError as e:
                print(f"telemetry: prometheus export failed: {e}",
                      file=sys.stderr)


class LatencyReservoir:
    """Thread-safe sliding window of the most recent ``capacity`` samples
    with quantile reads — the SLO substrate of the serving layer (p50/p99
    request latency published as gauges).

    A plain ring, not a sketch: at serving rates the window is a few
    thousand floats, and exact quantiles over "the recent past" are what
    an operator actually wants from a gauge.  ``add`` is O(1) under one
    lock; ``quantile`` sorts a snapshot (O(n log n) but only on publish,
    which the server throttles)."""

    __slots__ = ("_cap", "_ring", "_n", "_lock")

    def __init__(self, capacity: int = 2048):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._cap = capacity
        self._ring: list[float] = []
        self._n = 0          # total samples ever added
        self._lock = threading.Lock()

    def add(self, value: float) -> None:
        v = float(value)
        with self._lock:
            if len(self._ring) < self._cap:
                self._ring.append(v)
            else:
                self._ring[self._n % self._cap] = v
            self._n += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    def quantile(self, q: float) -> float | None:
        """The ``q``-quantile (0..1, nearest-rank) of the current window,
        or None when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            snap = list(self._ring)
        if not snap:
            return None
        snap.sort()
        return snap[min(len(snap) - 1, int(q * len(snap)))]


def _prom_name(name: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    if not out or not (out[0].isalpha() or out[0] == "_"):
        out = "_" + out
    return "pluss_" + out


def _prom_value(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def render_prom(counters: dict[str, float], gauges: dict[str, float],
                quantiles: dict[str, dict[str, float]] | None = None
                ) -> str:
    """The one Prometheus text renderer (exposition format 0.0.4): used
    by the shutdown textfile export AND the serve daemon's live
    ``/metrics`` endpoint, so the two surfaces cannot drift.  Counters
    render as ``counter``, gauges as ``gauge``, and ``quantiles`` (name
    -> {"0.5": v, ...}, e.g. a latency reservoir) as ``summary`` series
    with a ``quantile`` label.  Names are sanitized by :func:`_prom_name`
    (prefix ``pluss_``, every non-alphanumeric byte -> ``_``), and every
    family carries ``# HELP``/``# TYPE`` header lines."""
    lines: list[str] = []

    def family(name: str, kind: str, help_text: str) -> str:
        pn = _prom_name(name)
        lines.append(f"# HELP {pn} {help_text}")
        lines.append(f"# TYPE {pn} {kind}")
        return pn

    for name, v in sorted(counters.items()):
        pn = family(name, "counter",
                    f"pluss cumulative counter {name}")
        lines.append(f"{pn} {_prom_value(v)}")
    for name, v in sorted(gauges.items()):
        pn = family(name, "gauge", f"pluss gauge {name}")
        lines.append(f"{pn} {_prom_value(v)}")
    for name, qs in sorted((quantiles or {}).items()):
        pn = family(name, "summary",
                    f"pluss latency reservoir {name}")
        for q, v in sorted(qs.items(), key=lambda kv: float(kv[0])):
            if v is None:
                continue
            lines.append(f'{pn}{{quantile="{float(q)}"}} '
                         f"{_prom_value(v)}")
    return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# module-level session: the fast path every instrumented module calls.

_active: Telemetry | None = None
_bootstrapped = False
_atexit_registered = False
_suspended = 0


def suspend_env_bootstrap() -> None:
    """Hold off the lazy ``PLUSS_TELEMETRY`` bootstrap (telemetry calls
    are dropped meanwhile).  For windows where opening the env-named sink
    would be WRONG — e.g. a multi-process bring-up before this process
    knows its index, where N workers would all truncate one shared path
    (:func:`pluss.parallel.multihost.initialize` re-aims, then resumes).
    Explicit :func:`configure` calls are unaffected."""
    global _suspended
    _suspended += 1


def resume_env_bootstrap() -> None:
    global _suspended
    _suspended = max(0, _suspended - 1)


def _bootstrap() -> None:
    global _bootstrapped
    if _suspended:
        return   # stay un-bootstrapped: retry after the suspension lifts
    _bootstrapped = True
    path = os.environ.get("PLUSS_TELEMETRY")
    if path:
        configure(path, os.environ.get("PLUSS_PROM") or None)


def configure(path: str | None, prom_path: str | None = None
              ) -> Telemetry | None:
    """Install (or with ``path=None``, re-read ``PLUSS_TELEMETRY``/
    ``PLUSS_PROM`` from the environment for) the process-wide session.
    An existing session is closed first — one sink at a time.  An
    unopenable sink path (read-only fs, bad component) warns and leaves
    telemetry DISABLED instead of raising: observability must never
    abort the run it would have observed, not even at open time."""
    global _active, _bootstrapped, _atexit_registered
    if path is None:
        _bootstrapped = False
        shutdown()
        _bootstrap()
        return _active
    shutdown()
    _bootstrapped = True
    try:
        _active = Telemetry(path, prom_path
                            or os.environ.get("PLUSS_PROM") or None)
    except OSError as e:
        print(f"telemetry: cannot open sink {path} ({e}); telemetry "
              "disabled", file=sys.stderr)
        return None
    if not _atexit_registered:
        _atexit_registered = True
        atexit.register(shutdown)
    return _active


def shutdown() -> None:
    """Flush metrics, close the sink, and disable telemetry."""
    global _active
    t = _active
    _active = None
    if t is not None:
        t.close()


def active() -> Telemetry | None:
    if not _bootstrapped:
        _bootstrap()
    return _active


def configured() -> bool:
    """Whether a session is already installed, WITHOUT triggering the
    lazy env bootstrap — for probes inside bootstrap-sensitive windows
    (a multi-process bring-up deciding whether to suspend it)."""
    return _active is not None


def enabled() -> bool:
    return active() is not None


def counter_add(name: str, value: float = 1) -> None:
    t = _active if _bootstrapped else active()
    if t is not None:
        t.counter_add(name, value)


def gauge_set(name: str, value: float) -> None:
    t = _active if _bootstrapped else active()
    if t is not None:
        t.gauge_set(name, value)


def event(name: str, **attrs) -> None:
    t = _active if _bootstrapped else active()
    if t is not None:
        t.event(name, **attrs)


def span(name: str, **attrs):
    """A context-manager span, or the shared no-op when disabled."""
    t = _active if _bootstrapped else active()
    if t is None:
        return NOOP_SPAN
    return t.span(name, **attrs)


def counters() -> dict[str, float]:
    """Cumulative counter snapshot ({} when disabled) — bench uses deltas
    of this around a measured region to stamp its metric lines."""
    t = _active if _bootstrapped else active()
    return t.counters() if t is not None else {}


def gauges() -> dict[str, float]:
    t = _active if _bootstrapped else active()
    return t.gauges() if t is not None else {}


def flush_metrics() -> None:
    t = _active if _bootstrapped else active()
    if t is not None:
        t.flush_metrics()


def trace_event(name: str, **attrs) -> None:
    """An event emitted ONLY when a request trace context is bound.

    The attribution hook for cache layers (plan cache, residency,
    autotune): inside a serve request the hit/miss lands in the stream
    stamped ``trace=<rid>``; outside one (engine tests, bench, CLI runs)
    nothing is emitted, so existing streams and golden outputs are
    byte-identical to before.  Order of checks matters: the telemetry
    None-check comes first, keeping the disabled path free of any
    context lookup."""
    t = _active if _bootstrapped else active()
    if t is not None and tracectx.current() is not None:
        t.event(name, **attrs)


def ensure_session() -> Telemetry:
    """The active session, creating a MEMORY-ONLY one (no sink file) if
    telemetry is disabled.  The serve daemon calls this so its flight
    recorder can ring-buffer records for post-mortems even when the
    operator never armed ``--telemetry`` — the memory session writes no
    bytes anywhere until a dump is triggered."""
    global _active, _bootstrapped, _atexit_registered
    t = active()
    if t is not None:
        return t
    _bootstrapped = True
    _active = Telemetry(None)
    if not _atexit_registered:
        _atexit_registered = True
        atexit.register(shutdown)
    return _active
