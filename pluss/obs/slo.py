"""Multi-window SLO error-budget burn-rate over serve request outcomes.

The router tier the ROADMAP wants cannot weight daemons on cumulative
counters: ``serve.shed`` forever remembers a bad minute from last week.
What a balancer needs is *burn rate* — the fraction of the error budget
the daemon is consuming RIGHT NOW — and the standard multi-window form
(one fast window to catch a cliff, one slow window to catch a smolder)
so a transient blip doesn't flap the readiness signal.

The monitor buckets outcomes per second (``record(ok=...)``), prunes
past the slow window, and exposes ``burn(window_s)`` = (bad / total) /
``target``: burn 1.0 means failing at exactly the budgeted rate, 14.4
(the classic fast-page multiplier) means the whole month's budget would
be gone in ~2 hours.  Good/bad totals also land on the cumulative
counters ``serve.slo.good`` / ``serve.slo.bad`` for the stream record.

Transitions are the only events: when the fast or slow window crosses
its burn threshold, one ``slo.burn`` event records ``state="burning"``
(or ``"recovered"``) with the measured burn — a steady-state daemon
emits nothing, however long it burns or idles.  Live gauges
(``serve.slo.burn_fast``/``burn_slow``) are published by the server's
existing SLO publish loop, not per-request.

Knobs (:mod:`pluss.utils.envknob` warn-and-default discipline):
``PLUSS_SLO_TARGET`` (budgeted bad fraction, default 0.01),
``PLUSS_SLO_FAST_S`` / ``PLUSS_SLO_SLOW_S`` (window lengths, default
60 / 600), ``PLUSS_SLO_BURN_FAST`` / ``PLUSS_SLO_BURN_SLOW`` (burn
thresholds, default 14.4 / 3.0 — the conventional paging pair), and
``PLUSS_SLO_MIN_COUNT`` (default 100): a window with fewer outcomes
than this never reports burning — a burn RATE on three requests is
noise, and paging/readiness decisions need volume behind them.
"""

from __future__ import annotations

import threading
import time

from pluss import obs
from pluss.utils.envknob import env_float, env_int


def _knobs() -> dict:
    return {
        "target": env_float("PLUSS_SLO_TARGET", 0.01, 1e-6),
        "fast_s": env_float("PLUSS_SLO_FAST_S", 60.0, 1.0),
        "slow_s": env_float("PLUSS_SLO_SLOW_S", 600.0, 1.0),
        "burn_fast": env_float("PLUSS_SLO_BURN_FAST", 14.4, 0.0),
        "burn_slow": env_float("PLUSS_SLO_BURN_SLOW", 3.0, 0.0),
        "min_count": env_int("PLUSS_SLO_MIN_COUNT", 100, minimum=1),
    }


class SloMonitor:
    """Per-second outcome buckets with multi-window burn-rate reads.

    ``record`` is O(1) amortized (one dict update + a prune bounded by
    elapsed seconds); ``burn`` sums at most ``window_s`` buckets.  All
    state mutates under one lock — record() is called from connection
    and device-loop threads concurrently.
    """

    def __init__(self, target: float | None = None,
                 fast_s: float | None = None,
                 slow_s: float | None = None,
                 burn_fast: float | None = None,
                 burn_slow: float | None = None,
                 min_count: int | None = None,
                 clock=time.monotonic):
        k = _knobs()
        self.target = float(target if target is not None else k["target"])
        self.fast_s = float(fast_s if fast_s is not None else k["fast_s"])
        self.slow_s = float(slow_s if slow_s is not None else k["slow_s"])
        self.slow_s = max(self.slow_s, self.fast_s)
        self.burn_fast = float(burn_fast if burn_fast is not None
                               else k["burn_fast"])
        self.burn_slow = float(burn_slow if burn_slow is not None
                               else k["burn_slow"])
        self.min_count = int(min_count if min_count is not None
                             else k["min_count"])
        self._clock = clock
        self._lock = threading.Lock()
        #: second -> [total, bad]
        self._buckets: dict[int, list[float]] = {}
        self._burning = {"fast": False, "slow": False}
        obs.gauge_set("serve.slo.target", self.target)

    # -- recording ----------------------------------------------------------

    def record(self, ok: bool) -> None:
        """One finished request outcome.  ``ok=False`` covers every way a
        request burns budget: admission shed, deadline exceeded, watchdog
        abandon, forced-drain retryable — the caller decides."""
        now = self._clock()
        sec = int(now)
        with self._lock:
            b = self._buckets.setdefault(sec, [0.0, 0.0])
            b[0] += 1
            if not ok:
                b[1] += 1
            self._prune(now)
        obs.counter_add("serve.slo.bad" if not ok else "serve.slo.good")
        self._check_transitions()

    def _prune(self, now: float) -> None:
        horizon = int(now - self.slow_s) - 1
        if len(self._buckets) > self.slow_s + 2:
            for sec in [s for s in self._buckets if s < horizon]:
                del self._buckets[sec]

    # -- reads --------------------------------------------------------------

    def _window(self, window_s: float) -> tuple[float, float]:
        now = self._clock()
        lo = int(now - window_s)
        total = bad = 0.0
        with self._lock:
            for sec, (t, b) in self._buckets.items():
                if sec >= lo:
                    total += t
                    bad += b
        return total, bad

    def burn(self, window_s: float) -> float:
        """Error-budget burn rate over the trailing window: (bad/total) /
        target.  0.0 on an idle window — no traffic burns no budget."""
        total, bad = self._window(window_s)
        if total <= 0:
            return 0.0
        return (bad / total) / self.target

    def burn_rates(self) -> tuple[float, float]:
        return self.burn(self.fast_s), self.burn(self.slow_s)

    def burning_fast(self) -> bool:
        """The readiness-gate signal: the fast window is over threshold
        (the daemon is torching its budget right now).  Volume-gated:
        below ``min_count`` outcomes in the window it reports False — a
        burn rate computed on a handful of requests is noise."""
        total, bad = self._window(self.fast_s)
        if total < self.min_count:
            return False
        return (bad / total) / self.target >= self.burn_fast

    # -- transition events --------------------------------------------------

    def _check_transitions(self) -> None:
        for window, thresh, wsec in (("fast", self.burn_fast, self.fast_s),
                                     ("slow", self.burn_slow, self.slow_s)):
            total, bad = self._window(wsec)
            if total < self.min_count:
                continue   # same volume gate as burning_fast
            rate = (bad / total) / self.target
            burning = rate >= thresh
            with self._lock:
                was = self._burning[window]
                if burning == was:
                    continue
                self._burning[window] = burning
            obs.event("slo.burn", window=window,
                      state="burning" if burning else "recovered",
                      burn=round(rate, 3), threshold=thresh)
