"""Optional xprof integration: jax.profiler sessions + trace annotations.

``PLUSS_XPROF=<dir>`` arms both halves:

- :func:`session` — a refcounted ``jax.profiler.start_trace(dir)`` /
  ``stop_trace()`` pair around a top-level operation (engine run, trace
  replay, the CLI's timed region).  Refcounted because sessions cannot
  nest (``sweep`` runs ``engine.run`` inside its own scope): only the
  outermost enter starts the profiler, only the outermost exit stops it
  and dumps the xprof trace into the directory (view with ``tensorboard
  --logdir <dir>`` or xprof).
- :func:`annotate` — a named ``jax.profiler.TraceAnnotation`` around one
  dispatch, so the device timeline labels each batch/slice with the
  pluss-level operation that issued it.

With the env var unset both are near-free no-ops (one ``environ.get`` +
``None`` check), and any profiler failure degrades to a no-op with one
stderr notice — observability must never sink the run it observes.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading

_lock = threading.Lock()
_depth = 0
_broken = False
#: module state, not per-frame: with overlapping sessions on different
#: threads exiting non-LIFO, the frame that drops _depth to 0 need not be
#: the frame that started the profiler — whoever reaches 0 stops it
_started = False


def _dir() -> str | None:
    return os.environ.get("PLUSS_XPROF") or None


def enabled() -> bool:
    return _dir() is not None and not _broken


@contextlib.contextmanager
def session():
    """Profile the enclosed region into ``$PLUSS_XPROF`` (outermost wins)."""
    global _depth, _broken, _started
    d = _dir()
    if d is None or _broken:
        yield
        return
    import jax

    with _lock:
        _depth += 1
        if _depth == 1 and not _started:
            try:
                jax.profiler.start_trace(d)
                _started = True
            except Exception as e:  # profiler wedged: degrade, don't sink
                _broken = True
                print(f"xprof: start_trace({d}) failed, disabling "
                      f"profiling: {e}", file=sys.stderr)
    try:
        yield
    finally:
        with _lock:
            _depth -= 1
            if _depth == 0 and _started:
                _started = False
                try:
                    jax.profiler.stop_trace()
                except Exception as e:
                    _broken = True
                    print(f"xprof: stop_trace failed: {e}", file=sys.stderr)


def annotate(name: str):
    """Named TraceAnnotation context for one dispatch (no-op when off)."""
    if _dir() is None or _broken:
        return contextlib.nullcontext()
    import jax

    return jax.profiler.TraceAnnotation(name)
