"""``pluss stats``: aggregate one telemetry JSONL stream into a report.

Renders the span tree (per name-path: count, total wall incl. children,
self time excl. children), event counts, counter/gauge rollups
(cumulative counters: the LAST record per name wins), and — when the
trace-replay counters are present — the replay time breakdown the feed-
bound diagnosis needs: reader prefetch-stall seconds, h2d staging time
and MB/s, per-batch device time, checkpoint cost, what fraction of the
replay's wall clock those buckets account for, plus the parallel-feed
extras: concurrent wire-encode seconds across the worker pool and the
wire-vs-device byte ratio (how much the compressed d24v wire shaved off
the transport).

When the serving counters are present (a ``pluss serve`` daemon's
stream), a "serve SLO" block renders request outcomes, p50/p99 latency,
batch occupancy, queue pressure, and the per-request ladder activity.

``--check`` validates the stream against the schema instead (exit 1 on
any violation).  A torn FINAL line is tolerated with a notice — that is
the expected crash artifact of the sink's append discipline; torn or
alien lines anywhere else are violations.
"""

from __future__ import annotations

import json

from pluss.obs.telemetry import EVENT_KINDS, SCHEMA_VERSION


def load(path: str) -> tuple[list[dict], list[str], list[str]]:
    """(records, problems, notes) of one stream.  ``problems`` are schema
    violations (--check failures); ``notes`` are tolerated oddities."""
    problems: list[str] = []
    notes: list[str] = []
    records: list[dict] = []
    with open(path, "rb") as f:
        raw = f.read()
    lines = raw.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()
    for i, line in enumerate(lines):
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict) or "ev" not in rec:
                raise ValueError("not a telemetry record")
        except ValueError as e:
            if i == len(lines) - 1:
                notes.append(f"dropped torn final line (crash artifact): "
                             f"{line[:40]!r}")
                break
            problems.append(f"line {i + 1}: unparseable record: {e}")
            continue
        records.append(rec)
    p2, n2 = _check_schema(records)
    return records, problems + p2, notes + n2


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _check_schema(records: list[dict]) -> tuple[list[str], list[str]]:
    """(problems, notes).  A dangling span parent is a PROBLEM in a
    finished stream (it has an ``end`` record — the sink closed cleanly,
    so every parent must have recorded) but only a NOTE in a truncated
    one: children record at exit before their still-open ancestors, so a
    crash mid-span legitimately orphans them (the same tolerance as the
    torn final line)."""
    problems: list[str] = []
    notes: list[str] = []
    if not records:
        return ["empty stream (no records)"], notes
    finished = any(r.get("ev") == "end" for r in records)
    span_ids: set[int] = set()
    for r in records:
        if r.get("ev") == "span":
            sid = r.get("id")
            if isinstance(sid, int):
                if sid in span_ids:
                    problems.append(f"duplicate span id {sid}")
                span_ids.add(sid)
    for i, r in enumerate(records, 1):
        ev = r["ev"]
        if ev not in EVENT_KINDS:
            problems.append(f"record {i}: unknown ev kind {ev!r}")
            continue
        if ev == "meta":
            if i != 1:
                problems.append(f"record {i}: meta must be the first record")
            schema = r.get("schema")
            if not isinstance(schema, int) or schema > SCHEMA_VERSION:
                problems.append(
                    f"record {i}: schema {schema!r} is newer than this "
                    f"reader understands ({SCHEMA_VERSION})")
            continue
        if i == 1:
            problems.append("record 1: stream must start with a meta record")
        if ev == "span":
            if not isinstance(r.get("name"), str) or not r.get("name"):
                problems.append(f"record {i}: span without a name")
            if not isinstance(r.get("id"), int):
                problems.append(f"record {i}: span without an integer id")
            if not _is_num(r.get("t")) or not _is_num(r.get("dur")) \
                    or r.get("dur", 0) < 0:
                problems.append(f"record {i}: span needs numeric t and "
                                "non-negative dur")
            par = r.get("parent")
            if par is not None and par not in span_ids:
                msg = (f"record {i}: span parent {par!r} matches no span "
                       "in the stream")
                if finished:
                    problems.append(msg)
                else:
                    notes.append(msg + " (open ancestor lost to a crash; "
                                 "aggregating at the root)")
        elif ev in ("counter", "gauge"):
            if not isinstance(r.get("name"), str) or not r.get("name"):
                problems.append(f"record {i}: {ev} without a name")
            if not _is_num(r.get("value")):
                problems.append(f"record {i}: {ev} {r.get('name')!r} "
                                "without a numeric value")
        elif ev == "event":
            if not isinstance(r.get("name"), str) or not r.get("name"):
                problems.append(f"record {i}: event without a name")
        elif ev == "end":
            if not _is_num(r.get("dur")):
                problems.append(f"record {i}: end record without a dur")
    return problems, notes


# ---------------------------------------------------------------------------
# aggregation


class _Node:
    __slots__ = ("name", "count", "total", "self_s", "children")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.self_s = 0.0
        self.children: dict[str, _Node] = {}


def _span_tree(records: list[dict]) -> _Node:
    """Aggregate span instances into a name-path tree (root is synthetic).

    Children are emitted before parents (spans record at exit), so paths
    resolve in a second pass over the id → record map.  A span whose
    parent never recorded (e.g. torn by a crash) aggregates at the root.
    """
    spans = {r["id"]: r for r in records
             if r.get("ev") == "span" and isinstance(r.get("id"), int)}
    child_dur: dict[int, float] = {}
    for r in spans.values():
        p = r.get("parent")
        if p is not None:
            child_dur[p] = child_dur.get(p, 0.0) + float(r.get("dur", 0.0))

    def path_of(r: dict) -> tuple[str, ...]:
        names: list[str] = []
        seen: set[int] = set()
        cur: dict | None = r
        while cur is not None and cur["id"] not in seen:
            seen.add(cur["id"])
            names.append(str(cur.get("name", "?")))
            cur = spans.get(cur.get("parent"))
        return tuple(reversed(names))

    root = _Node("")
    for r in spans.values():
        node = root
        for name in path_of(r):
            node = node.children.setdefault(name, _Node(name))
        node.count += 1
        dur = float(r.get("dur", 0.0))
        node.total += dur
        node.self_s += max(0.0, dur - child_dur.get(r["id"], 0.0))
    return root


def _metric_rollup(records: list[dict], kind: str) -> dict[str, float]:
    """Last value per name (counter records are cumulative snapshots)."""
    out: dict[str, float] = {}
    for r in records:
        if r.get("ev") == kind and isinstance(r.get("name"), str) \
                and _is_num(r.get("value")):
            out[r["name"]] = float(r["value"])
    return out


def _fmt_val(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else f"{v:.6g}"


def _render_spans(node: _Node, lines: list[str], depth: int) -> None:
    kids = sorted(node.children.values(),
                  key=lambda n: (-n.total, n.name))
    for k in kids:
        label = "  " + ". " * depth + k.name
        lines.append(f"{label:<44} {k.count:>5}  {k.total:>9.3f}s "
                     f"{k.self_s:>9.3f}s")
        _render_spans(k, lines, depth + 1)


def trace_breakdown(counters: dict[str, float],
                    wall: float | None) -> list[str]:
    """The feed-bound diagnosis block: stall / h2d / device / checkpoint
    buckets vs the replay's wall time.  Empty when the trace counters are
    absent from the stream."""
    buckets = [("reader prefetch stall", "trace.prefetch_stall_s"),
               ("h2d staging", "trace.h2d_s"),
               ("device compute", "trace.device_s"),
               ("checkpoint saves", "trace.ckpt_save_s"),
               ("table growth", "trace.grow_s")]
    if not any(k in counters for _, k in buckets):
        return []
    lines = ["trace replay breakdown:"]
    if wall is not None:
        lines.append(f"  {'wall (trace.replay_file span)':<28} {wall:>9.3f}s")
    accounted = 0.0
    for label, key in buckets:
        if key not in counters:
            continue
        v = counters[key]
        accounted += v
        pct = f"  {100.0 * v / wall:>5.1f}%" if wall else ""
        extra = ""
        if key == "trace.device_s" and counters.get("trace.batches"):
            nb = counters["trace.batches"]
            extra = f"  ({v / nb:.4f}s/batch over {int(nb)} batches)"
        lines.append(f"  {label:<28} {v:>9.3f}s{pct}{extra}")
    if wall:
        lines.append(f"  {'accounted':<28} {accounted:>9.3f}s of "
                     f"{wall:.3f}s wall ({100.0 * accounted / wall:.1f}%)")
    h2d_b, h2d_s = counters.get("trace.h2d_bytes"), counters.get("trace.h2d_s")
    if h2d_b and h2d_s:
        lines.append(f"  {'h2d rate':<28} {h2d_b / 1e6 / h2d_s:>9.1f} MB/s")
    # feed-worker wire-encode runs CONCURRENTLY with the buckets above
    # (pool threads), so it reports beside the wall accounting, not in it
    enc = counters.get("trace.wire_encode_s")
    if enc is not None:
        lines.append(f"  {'wire encode (feed workers)':<28} {enc:>9.3f}s"
                     "  (concurrent)")
    dev_b = counters.get("trace.device_bytes")
    if h2d_b and dev_b:
        lines.append(
            f"  {'wire compression':<28} {h2d_b / 1e6:>9.1f} MB wire vs "
            f"{dev_b / 1e6:.1f} MB device ({dev_b / h2d_b:.2f}x)")
    if counters.get("trace.refs_replayed") and wall:
        lines.append(f"  {'replay rate':<28} "
                     f"{counters['trace.refs_replayed'] / wall:>9.3g} refs/s")
    return lines


def serve_breakdown(counters: dict[str, float],
                    gauges: dict[str, float]) -> list[str]:
    """The serving SLO block: request outcomes, latency quantiles, batch
    occupancy (how many requests each device dispatch served), queue
    pressure, and the per-request resilience activity.  Empty when the
    serve counters are absent from the stream."""
    total = counters.get("serve.requests")
    if not total:
        return []
    lines = ["serve SLO:"]
    kinds = [f"{k[len('serve.requests.'):]} {int(v)}"
             for k, v in sorted(counters.items())
             if k.startswith("serve.requests.")]
    lines.append(f"  {'requests':<28} {int(total):>9}"
                 + (f"  ({', '.join(kinds)})" if kinds else ""))
    for label, key in (("ok", "serve.ok"),
                       ("errors", "serve.errors"),
                       ("shed (admission)", "serve.shed"),
                       ("deadline exceeded", "serve.deadline_exceeded"),
                       ("admission rejects", "serve.admission_rejects")):
        v = counters.get(key)
        if v:
            pct = 100.0 * v / total
            lines.append(f"  {label:<28} {int(v):>9}  ({pct:.1f}%)")
    p50, p99 = gauges.get("serve.p50_ms"), gauges.get("serve.p99_ms")
    if p50 is not None or p99 is not None:
        lines.append(
            f"  {'latency p50 / p99':<28} "
            f"{_fmt_val(p50) if p50 is not None else '?':>9} / "
            f"{_fmt_val(p99) if p99 is not None else '?'} ms")
    batches = counters.get("serve.batches")
    if batches:
        members = counters.get("serve.batched_requests", 0.0)
        lines.append(
            f"  {'batches dispatched':<28} {int(batches):>9}  "
            f"(occupancy {members / batches:.2f} req/dispatch, "
            f"{int(members - batches)} dispatch(es) coalesced away)")
    qd = gauges.get("serve.queue_depth")
    if qd is not None:
        lines.append(f"  {'queue depth (last)':<28} {_fmt_val(qd):>9}")
    rungs = counters.get("resilience.rungs_taken")
    if rungs:
        per = [f"{k[len('resilience.rungs_taken.'):]}={int(v)}"
               for k, v in sorted(counters.items())
               if k.startswith("resilience.rungs_taken.")]
        lines.append(f"  {'ladder rungs taken':<28} {int(rungs):>9}"
                     + (f"  ({', '.join(per)})" if per else ""))
    hits = counters.get("engine.plan_cache.hit")
    if hits is not None or counters.get("engine.plan_cache.miss"):
        miss = counters.get("engine.plan_cache.miss", 0.0)
        ev = counters.get("engine.plan_cache.evict", 0.0)
        lines.append(f"  {'plan cache hit/miss/evict':<28} "
                     f"{int(hits or 0):>9} / {int(miss)} / {int(ev)}")
    return lines


def warmstart_breakdown(counters: dict[str, float],
                        gauges: dict[str, float]) -> list[str]:
    """The warm-start block: XLA compile seconds actually paid by this
    process, AOT executable sidecar hit/miss/restore-failure traffic,
    single-flight dedup, and serve/sweep warmup activity.  Empty when the
    stream has no compile or AOT activity at all (a fully warm process
    that restored nothing shows its aot_hit count here)."""
    keys = ("engine.compiles", "engine.compile_s",
            "engine.plan_cache.aot_hit", "engine.plan_cache.aot_miss",
            "engine.plan_cache.aot_load_fail")
    if not any(counters.get(k) for k in keys):
        return []
    lines = ["warm start:"]
    comp = counters.get("engine.compiles", 0.0)
    comp_s = counters.get("engine.compile_s", 0.0)
    lines.append(f"  {'compiles paid':<28} {int(comp):>9}  "
                 f"({comp_s:.3f}s wall)")
    hit = counters.get("engine.plan_cache.aot_hit", 0.0)
    miss = counters.get("engine.plan_cache.aot_miss", 0.0)
    fail = counters.get("engine.plan_cache.aot_load_fail", 0.0)
    lines.append(f"  {'aot exe hit/miss/load_fail':<28} "
                 f"{int(hit):>9} / {int(miss)} / {int(fail)}")
    waits = counters.get("engine.compile_singleflight_waits")
    if waits:
        lines.append(f"  {'single-flight dedup waits':<28} {int(waits):>9}")
    warmed = counters.get("serve.warmed")
    if warmed or counters.get("serve.warm_fail"):
        wf = counters.get("serve.warm_fail", 0.0)
        lines.append(f"  {'serve warmup (ok / fail)':<28} "
                     f"{int(warmed or 0):>9} / {int(wf)}")
    parked = counters.get("serve.compile_parked")
    if parked:
        lines.append(f"  {'serve batches parked':<28} {int(parked):>9}")
    pre = counters.get("sweep.precompiles")
    if pre:
        lines.append(f"  {'sweep points precompiled':<28} {int(pre):>9}")
    infl = gauges.get("serve.compile_inflight")
    if infl is not None:
        lines.append(f"  {'compile in flight (last)':<28} "
                     f"{_fmt_val(infl):>9}")
    return lines


def shard_breakdown(counters: dict[str, float],
                    gauges: dict[str, float]) -> list[str]:
    """The multi-chip scale-out block: chunk dispatch volume, how much
    rebalancing the work-stealing dispatcher actually did, and each
    device's busy fraction (a balanced fleet shows near-equal fractions;
    a straggler-bound one shows the gap stealing is closing).  Empty when
    the stream has no shard dispatch activity."""
    chunks = counters.get("shard.chunks")
    if not chunks:
        return []
    lines = ["shard scale-out:"]
    lines.append(f"  {'chunks dispatched':<28} {int(chunks):>9}")
    steals = counters.get("shard.steals", 0.0)
    lines.append(f"  {'chunks stolen':<28} {int(steals):>9}  "
                 f"({100.0 * steals / chunks:.1f}%)")
    busy = sorted(((int(k.rsplit(".", 1)[1]), v)
                   for k, v in gauges.items()
                   if k.startswith("shard.device_busy_frac.")))
    if busy:
        lines.append("  device busy fractions (last) "
                     + " ".join(f"d{i}={v:.2f}" for i, v in busy))
    retries = counters.get("engine.share_cap_retries")
    if retries:
        lines.append(f"  {'share-cap retries':<28} {int(retries):>9}")
    deaths = counters.get("multihost.worker_deaths")
    if deaths:
        salv = counters.get("multihost.salvages", 0.0)
        lines.append(f"  {'worker deaths / salvages':<28} "
                     f"{int(deaths):>9} / {int(salv)}")
    return lines


def residency_breakdown(counters: dict[str, float],
                        gauges: dict[str, float]) -> list[str]:
    """The trace residency block (r13): HBM store hit traffic, eviction
    and fallback pressure, stage-through population, and the last
    resident footprint vs budget.  Empty when the stream has no
    residency activity at all."""
    hits = counters.get("residency.hit", 0.0)
    misses = counters.get("residency.miss", 0.0)
    keys = ("residency.hit", "residency.miss", "residency.evict",
            "residency.stage_through", "residency.fallback")
    if not any(counters.get(k) for k in keys):
        return []
    lines = ["trace residency:"]
    total = hits + misses
    rate = f"  ({100.0 * hits / total:.1f}% hit)" if total else ""
    lines.append(f"  {'store hits / misses':<28} "
                 f"{int(hits):>9} / {int(misses)}{rate}")
    st = counters.get("residency.stage_through")
    if st:
        lines.append(f"  {'entries staged through':<28} {int(st):>9}")
    ev = counters.get("residency.evict")
    if ev:
        lines.append(f"  {'LRU evictions':<28} {int(ev):>9}")
    fb = counters.get("residency.fallback")
    if fb:
        lines.append(f"  {'budget fallbacks (streamed)':<28} {int(fb):>9}")
    pins = counters.get("residency.pin")
    if pins:
        lines.append(f"  {'replay pins':<28} {int(pins):>9}")
    res = gauges.get("trace.hbm_resident_bytes")
    if res is not None:
        lines.append(f"  {'resident bytes (last)':<28} "
                     f"{res / 1e6:>9.1f} MB")
    qh = gauges.get("serve.queue_hbm_bytes")
    if qh:
        lines.append(f"  {'queued HBM demand (last)':<28} "
                     f"{qh / 1e6:>9.1f} MB")
    return lines


_BREAKER_STATE = {0: "closed", 1: "half_open", 2: "open"}


def hardening_breakdown(counters: dict[str, float],
                        gauges: dict[str, float]) -> list[str]:
    """The fleet-hardening block (r14): request-journal traffic and
    recovery, circuit-breaker trips with brown-out/shed volume, watchdog
    abandons, tenant rate-limit sheds, and connection-layer protection.
    Empty when none of the hardening machinery fired (a healthy daemon
    with no journal configured prints nothing here)."""
    keys = ("serve.journal.appended", "serve.journal.recovered",
            "serve.breaker.open", "serve.breaker.brownout",
            "serve.breaker.shed", "serve.watchdog.abandoned",
            "serve.fairness.rate_limited", "serve.conn_shed",
            "serve.conn_idle_closed", "serve.drain_forced")
    if not any(counters.get(k) for k in keys) \
            and gauges.get("serve.breaker.state") is None:
        return []
    lines = ["serve hardening:"]
    app = counters.get("serve.journal.appended")
    if app or counters.get("serve.journal.completed"):
        comp = counters.get("serve.journal.completed", 0.0)
        lines.append(f"  {'journal appended / done':<28} "
                     f"{int(app or 0):>9} / {int(comp)}")
    rec = counters.get("serve.journal.recovered")
    if rec:
        exp = counters.get("serve.journal.expired", 0.0)
        lines.append(f"  {'recovered (of them expired)':<28} "
                     f"{int(rec):>9}  ({int(exp)} expired)")
    rot = counters.get("serve.journal.rotations")
    if rot:
        lines.append(f"  {'journal compactions':<28} {int(rot):>9}")
    jfail = counters.get("serve.journal.append_fail")
    if jfail:
        lines.append(f"  {'journal append failures':<28} {int(jfail):>9}")
    opens = counters.get("serve.breaker.open")
    if opens or gauges.get("serve.breaker.state") is not None:
        closes = counters.get("serve.breaker.close", 0.0)
        reopens = counters.get("serve.breaker.reopen", 0.0)
        state = gauges.get("serve.breaker.state")
        now = f"  (now {_BREAKER_STATE.get(int(state), '?')})" \
            if state is not None else ""
        lines.append(f"  {'breaker open/close/reopen':<28} "
                     f"{int(opens or 0):>9} / {int(closes)} / "
                     f"{int(reopens)}{now}")
    bo = counters.get("serve.breaker.brownout")
    if bo:
        lines.append(f"  {'spec brown-outs (cpu)':<28} {int(bo):>9}")
    bs = counters.get("serve.breaker.shed")
    if bs:
        lines.append(f"  {'trace sheds (breaker open)':<28} {int(bs):>9}")
    ab = counters.get("serve.watchdog.abandoned")
    if ab:
        abr = counters.get("serve.watchdog.abandoned_requests", 0.0)
        lines.append(f"  {'watchdog abandons':<28} {int(ab):>9}  "
                     f"({int(abr)} request(s) answered retryable)")
    rl = counters.get("serve.fairness.rate_limited")
    if rl:
        lines.append(f"  {'tenant rate-limit sheds':<28} {int(rl):>9}")
    at = gauges.get("serve.fairness.active_tenants")
    if at:
        lines.append(f"  {'active tenants (last)':<28} {_fmt_val(at):>9}")
    cs = counters.get("serve.conn_shed")
    ic = counters.get("serve.conn_idle_closed")
    if cs or ic:
        lines.append(f"  {'conns shed / idle-closed':<28} "
                     f"{int(cs or 0):>9} / {int(ic or 0)}")
    df = counters.get("serve.drain_forced")
    if df:
        lines.append(f"  {'forced drains':<28} {int(df):>9}")
    return lines


def interference_breakdown(counters: dict[str, float],
                           gauges: dict[str, float]) -> list[str]:
    """The co-tenancy interference block (r15): advisory stamps the
    serving path attached to responses dispatched with other workloads
    queued behind them, how many crossed the severe (PL801) bar, and the
    last observed miss-ratio inflation.  Empty when no dispatch ever had
    a co-tenant (solo traffic prints nothing here)."""
    adv = counters.get("serve.interference.advisories")
    errs = counters.get("serve.interference.errors")
    if not adv and not errs:
        return []
    lines = ["co-tenancy interference:"]
    sev = counters.get("serve.interference.severe", 0.0)
    lines.append(f"  {'advisories (of them severe)':<28} "
                 f"{int(adv or 0):>9}  ({int(sev)} PL801)")
    infl = gauges.get("serve.interference.last_inflation")
    if infl is not None:
        lines.append(f"  {'last inflation':<28} {_fmt_val(infl):>9}")
    if errs:
        lines.append(f"  {'advisory errors (no stamp)':<28} "
                     f"{int(errs):>9}")
    return lines


def placement_breakdown(counters: dict[str, float],
                        gauges: dict[str, float]) -> list[str]:
    """The interference-aware placement block (r16): how often the
    batcher's lead pick consulted the static pairwise-interference cost
    (``PLUSS_SERVE_PLACEMENT=on``), how many picks actually reordered
    within a tenant's backlog, memo efficiency, and the last chosen
    pair's predicted cost.  Empty on the advisory-only A/B control."""
    ch = counters.get("serve.placement.choices")
    errs = counters.get("serve.placement.errors")
    if not ch and not errs:
        return []
    lines = ["interference-aware placement:"]
    re_ = counters.get("serve.placement.reorders", 0.0)
    lines.append(f"  {'choices (of them reorders)':<28} "
                 f"{int(ch or 0):>9}  ({int(re_)} reordered)")
    mh = counters.get("serve.placement.memo_hits")
    if mh:
        lines.append(f"  {'pair-cost memo hits':<28} {int(mh):>9}")
    hr = counters.get("serve.placement.head_rescues")
    if hr:
        lines.append(f"  {'starvation-guard rescues':<28} {int(hr):>9}")
    cost = gauges.get("serve.placement.last_cost")
    if cost is not None:
        lines.append(f"  {'last pair cost':<28} {_fmt_val(cost):>9}")
    if errs:
        lines.append(f"  {'placement errors (FIFO kept)':<28} "
                     f"{int(errs):>9}")
    return lines


def autotune_breakdown(counters: dict[str, float],
                       gauges: dict[str, float]) -> list[str]:
    """The fused-kernel / autotuner block (r19): Pallas compile-probe
    traffic and loud XLA fallbacks, plus the geometry sidecar's consult
    outcomes — ``hit`` means a later run reused the persisted winner with
    zero re-calibration, ``stale`` a salt-mismatched or quarantined
    sidecar, ``probe`` one timed calibration point.  Empty when the
    stream has neither Pallas nor autotune activity."""
    keys = ("pallas.probe", "pallas.fallback",
            "autotune.probe", "autotune.hit", "autotune.stale")
    if not any(counters.get(k) for k in keys):
        return []
    lines = ["kernels & autotune:"]
    pp = counters.get("pallas.probe")
    if pp or counters.get("pallas.fallback"):
        fb = counters.get("pallas.fallback", 0.0)
        lines.append(f"  {'pallas probes / fallbacks':<28} "
                     f"{int(pp or 0):>9} / {int(fb)}"
                     + ("  (fused kernels DISABLED, XLA path)"
                        if fb else ""))
    hit = counters.get("autotune.hit")
    stale = counters.get("autotune.stale")
    if hit or stale:
        lines.append(f"  {'geometry hits / stale':<28} "
                     f"{int(hit or 0):>9} / {int(stale or 0)}")
    cal = counters.get("autotune.probe")
    if cal:
        lines.append(f"  {'calibration points timed':<28} {int(cal):>9}")
    return lines


def slo_breakdown(counters: dict[str, float],
                  gauges: dict[str, float]) -> list[str]:
    """The SLO burn-rate block (r20): error-budget consumption over the
    daemon's lifetime plus the live multi-window burn gauges the router
    tier keys off.  Empty when the SLO monitor never recorded an outcome
    (non-serve streams print nothing here)."""
    good = counters.get("serve.slo.good", 0.0)
    bad = counters.get("serve.slo.bad", 0.0)
    total = good + bad
    if not total:
        return []
    lines = ["serve SLO burn:"]
    target = gauges.get("serve.slo.target")
    frac = bad / total
    burn = f"  (burn {frac / target:.2f}x budget)" if target else ""
    lines.append(f"  {'outcomes good / bad':<28} "
                 f"{int(good):>9} / {int(bad)}  ({100.0 * frac:.2f}% bad)"
                 + burn)
    if target is not None:
        lines.append(f"  {'budgeted bad fraction':<28} "
                     f"{100.0 * target:>8.2f}%")
    bf = gauges.get("serve.slo.burn_fast")
    bs = gauges.get("serve.slo.burn_slow")
    if bf is not None or bs is not None:
        lines.append(
            f"  {'burn fast / slow (last)':<28} "
            f"{_fmt_val(round(bf, 3)) if bf is not None else '?':>9} / "
            f"{_fmt_val(round(bs, 3)) if bs is not None else '?'}")
    dumps = counters.get("flight.dumps")
    if dumps:
        lines.append(f"  {'flight recorder dumps':<28} {int(dumps):>9}")
    return lines


# ---------------------------------------------------------------------------
# per-request trace view (--trace)


def _trace_matches(rec: dict, rid: str) -> bool:
    """A record belongs to request ``rid`` when stamped with its trace id
    directly, or — for the coalesced batch span — when ``rid`` appears in
    the span's ``traces`` member list."""
    if rec.get("trace") == rid:
        return True
    attrs = rec.get("attrs")
    if isinstance(attrs, dict):
        tr = attrs.get("traces")
        if isinstance(tr, (list, tuple)) and rid in tr:
            return True
    return False


def _attr_suffix(rec: dict) -> str:
    attrs = rec.get("attrs")
    parts = []
    if rec.get("error"):
        parts.append(f"error={rec['error']}")
    if isinstance(attrs, dict):
        for k in sorted(attrs):
            v = attrs[k]
            if isinstance(v, float):
                v = _fmt_val(round(v, 6))
            elif isinstance(v, (list, tuple)):
                v = ",".join(str(x) for x in v)
            parts.append(f"{k}={v}")
    return ("  [" + " ".join(parts) + "]") if parts else ""


def render_trace(records: list[dict], rid: str, out) -> int:
    """Render one request's causal span tree: every span/event stamped
    with ``rid``, nested by parent where the parent is also part of the
    trace (cross-thread stages whose parent span belongs to another
    thread's bookkeeping list at the root, ordered by start time)."""
    spans = [r for r in records
             if r.get("ev") == "span" and _trace_matches(r, rid)
             and isinstance(r.get("id"), int)]
    events = [r for r in records
              if r.get("ev") == "event" and _trace_matches(r, rid)]
    if not spans and not events:
        out.write(f"trace {rid}: no records in stream\n")
        return 1
    sel = {r["id"] for r in spans}
    kids: dict[int, list[dict]] = {}
    roots: list[dict] = []
    for r in spans:
        p = r.get("parent")
        (kids.setdefault(p, []) if p in sel else roots).append(r)
    ev_kids: dict[int, list[dict]] = {}
    loose: list[dict] = []
    for e in events:
        p = e.get("parent")
        (ev_kids.setdefault(p, []) if p in sel else loose).append(e)
    out.write(f"trace {rid}: {len(spans)} span(s), {len(events)} "
              f"event(s)\n")
    out.write(f"  {'stage':<44} {'start':>10} {'dur':>10}\n")

    def emit(rec: dict, depth: int) -> None:
        label = "  " + ". " * depth + str(rec.get("name", "?"))
        t = float(rec.get("t", 0.0))
        dur = float(rec.get("dur", 0.0))
        out.write(f"{label:<46} {t:>9.4f}s {dur:>9.4f}s"
                  f"{_attr_suffix(rec)}\n")
        branch = [(float(c.get("t", 0.0)), 0, "span", c)
                  for c in kids.get(rec["id"], [])]
        branch += [(float(e.get("t", 0.0)), 1, "event", e)
                   for e in ev_kids.get(rec["id"], [])]
        for _, _, kind, item in sorted(branch, key=lambda x: (x[0], x[1])):
            if kind == "span":
                emit(item, depth + 1)
            else:
                emit_event(item, depth + 1)

    def emit_event(rec: dict, depth: int) -> None:
        label = "  " + ". " * depth + "* " + str(rec.get("name", "?"))
        t = float(rec.get("t", 0.0))
        out.write(f"{label:<46} {t:>9.4f}s {'-':>10}"
                  f"{_attr_suffix(rec)}\n")

    items = [(float(r.get("t", 0.0)), 0, "span", r) for r in roots]
    items += [(float(e.get("t", 0.0)), 1, "event", e) for e in loose]
    for _, _, kind, item in sorted(items, key=lambda x: (x[0], x[1])):
        if kind == "span":
            emit(item, 0)
        else:
            emit_event(item, 0)
    return 0


# ---------------------------------------------------------------------------
# live tail (--follow)


def _follow_line(rec: dict) -> str | None:
    """One-line live rendering of a record; None skips it (meta noise)."""
    ev = rec.get("ev")
    tr = f"  trace={rec['trace']}" if rec.get("trace") else ""
    t = rec.get("t")
    ts = f"{float(t):>9.3f}s " if _is_num(t) else " " * 11
    if ev == "span":
        return (f"{ts}span  {rec.get('name', '?'):<36} "
                f"{float(rec.get('dur', 0.0)):>9.4f}s{tr}"
                f"{_attr_suffix(rec)}")
    if ev == "event":
        return (f"{ts}event {rec.get('name', '?'):<36} {'':>10}{tr}"
                f"{_attr_suffix(rec)}")
    if ev in ("counter", "gauge"):
        return (f"{ts}{ev:<5} {rec.get('name', '?'):<36} "
                f"{_fmt_val(rec.get('value', 0)):>10}")
    if ev == "end":
        return f"{ts}end   (stream closed, {rec.get('dur', '?')}s wall)"
    return None


def follow(path: str, out, err, poll_s: float = 0.25,
           max_idle_s: float | None = None) -> int:
    """Tail a growing telemetry stream, one line per record, until the
    ``end`` record lands (daemon shut down) or the reader is interrupted.
    Only COMPLETE lines render — a partially-flushed record waits for its
    newline, mirroring the sink's torn-line discipline."""
    import time as _time

    buf = b""
    pos = 0
    idle = 0.0
    appeared = False
    try:
        while True:
            try:
                with open(path, "rb") as f:
                    f.seek(pos)
                    chunk = f.read()
                appeared = True
            except FileNotFoundError:
                # tailing a stream the daemon hasn't created yet is the
                # normal startup race — wait it out inside the idle budget
                idle += poll_s
                if max_idle_s is not None and idle >= max_idle_s:
                    if appeared:
                        return 0
                    err.write(f"pluss stats: follow: no such stream "
                              f"{path}\n")
                    return 2
                _time.sleep(poll_s)
                continue
            if chunk:
                idle = 0.0
                pos += len(chunk)
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if not isinstance(rec, dict):
                        continue
                    rendered = _follow_line(rec)
                    if rendered is not None:
                        out.write(rendered + "\n")
                        out.flush()
                    if rec.get("ev") == "end":
                        return 0
            else:
                idle += poll_s
                if max_idle_s is not None and idle >= max_idle_s:
                    return 0
                _time.sleep(poll_s)
    except KeyboardInterrupt:
        return 0
    except OSError as e:
        err.write(f"pluss stats: follow: {e}\n")
        return 2


def render(records: list[dict], out) -> None:
    """Write the human report for one loaded stream."""
    n_spans = sum(1 for r in records if r.get("ev") == "span")
    n_events = sum(1 for r in records if r.get("ev") == "event")
    finished = any(r.get("ev") == "end" for r in records)
    out.write(f"telemetry stream: {len(records)} records, {n_spans} "
              f"span(s), {n_events} event(s)"
              + ("" if finished else "  [no end record: stream truncated]")
              + "\n")
    root = _span_tree(records)
    if root.children:
        lines = [f"  {'span':<42} {'n':>5}  {'total':>10} {'self':>10}"]
        _render_spans(root, lines, 0)
        out.write("spans:\n" + "\n".join(lines) + "\n")
    ev_counts: dict[str, int] = {}
    for r in records:
        if r.get("ev") == "event" and isinstance(r.get("name"), str):
            ev_counts[r["name"]] = ev_counts.get(r["name"], 0) + 1
    if ev_counts:
        out.write("events:\n")
        for name in sorted(ev_counts):
            out.write(f"  {name:<42} {ev_counts[name]:>7}\n")
    counters = _metric_rollup(records, "counter")
    if counters:
        out.write("counters:\n")
        for name in sorted(counters):
            out.write(f"  {name:<42} {_fmt_val(counters[name]):>12}\n")
    gauges = _metric_rollup(records, "gauge")
    if gauges:
        out.write("gauges (last value):\n")
        for name in sorted(gauges):
            out.write(f"  {name:<42} {_fmt_val(gauges[name]):>12}\n")
    replay = root.children.get("trace.replay_file")
    wall = replay.total if replay is not None else None
    block = trace_breakdown(counters, wall)
    if block:
        out.write("\n".join(block) + "\n")
    sblock = serve_breakdown(counters, gauges)
    if sblock:
        out.write("\n".join(sblock) + "\n")
    wblock = warmstart_breakdown(counters, gauges)
    if wblock:
        out.write("\n".join(wblock) + "\n")
    shblock = shard_breakdown(counters, gauges)
    if shblock:
        out.write("\n".join(shblock) + "\n")
    rblock = residency_breakdown(counters, gauges)
    if rblock:
        out.write("\n".join(rblock) + "\n")
    hblock = hardening_breakdown(counters, gauges)
    if hblock:
        out.write("\n".join(hblock) + "\n")
    iblock = interference_breakdown(counters, gauges)
    if iblock:
        out.write("\n".join(iblock) + "\n")
    pblock = placement_breakdown(counters, gauges)
    if pblock:
        out.write("\n".join(pblock) + "\n")
    ablock = autotune_breakdown(counters, gauges)
    if ablock:
        out.write("\n".join(ablock) + "\n")
    slblock = slo_breakdown(counters, gauges)
    if slblock:
        out.write("\n".join(slblock) + "\n")


def main(path: str, out, err, check: bool = False,
         trace: str | None = None, follow_stream: bool = False) -> int:
    """Entry point behind ``pluss stats <events.jsonl> [--check]
    [--trace RID] [--follow]``."""
    import os

    if not os.path.exists(path):
        err.write(f"pluss stats: no such file: {path}\n")
        return 2
    if follow_stream:
        return follow(path, out, err)
    records, problems, notes = load(path)
    for n in notes:
        err.write(f"pluss stats: note: {n}\n")
    if check:
        for p in problems:
            err.write(f"pluss stats: {path}: {p}\n")
        if problems:
            err.write(f"pluss stats: {path}: {len(problems)} schema "
                      "violation(s)\n")
            return 1
        out.write(f"pluss stats: {path}: ok "
                  f"({len(records)} records)\n")
        return 0
    if problems:
        for p in problems:
            err.write(f"pluss stats: {path}: {p}\n")
    if trace is not None:
        return render_trace(records, trace, out)
    render(records, out)
    return 0
