"""Model constants and run configuration.

The reference hardcodes these as compile-time ``-D`` macros
(``/root/reference/c_lib/test/Makefile:12-13``) and duplicated Rust ``const``s
(``/root/reference/src/gemm_sampler.rs:27-30``, ``src/utils.rs:10-11``).  Here they
live in one runtime-configurable dataclass; every named quirk constant of the
reference's statistics pipeline is spelled out with its provenance so golden-output
parity is auditable.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class SamplerConfig:
    """Schedule + machine-model parameters of one sampling run.

    Mirrors the reference's compile-time configuration surface:

    - ``thread_num``  — ``-DTHREAD_NUM=4``   (Makefile:12-13)
    - ``chunk_size``  — ``-DCHUNK_SIZE=4``
    - ``ds``          — ``-DDS=8``   element size in bytes
    - ``cls``         — ``-DCLS=64`` cache-line size in bytes
    - ``cache_kb``    — ``POLYBENCH_CACHE_SIZE_KB`` default 2560 (pluss.cpp:9-11)
    """

    thread_num: int = 4
    chunk_size: int = 4
    ds: int = 8
    cls: int = 64
    cache_kb: int = 2560

    @property
    def lines_per_element_div(self) -> int:
        """Elements per cache line: ``CLS // DS`` (address -> line is addr*DS//CLS)."""
        return self.cls // self.ds

    @property
    def aet_cache_entries(self) -> int:
        """AET sweep bound: ``cache_kb * 1024 / sizeof(double)``
        (pluss_utils.h:785: ``cs = 2560 * 1024 / sizeof(double)``)."""
        return self.cache_kb * 1024 // 8


# --- Statistics-model quirk constants (behavioral contract, SURVEY.md §5) ------

#: NBD point-mass cutoff: thread-local reuse n >= NBD_CUTOFF_COEF*(T-1)/T is
#: emitted as a point mass at T*n instead of a negative-binomial dilation
#: (pluss_utils.h:993-997, src/utils.rs:216-221).  3000 for T=4.
NBD_CUTOFF_COEF = 4000.0

#: NBD tail truncation: pmf terms are accumulated until the running mass
#: exceeds this value; the crossing term is included (pluss_utils.h:1001-1008).
NBD_MASS_CUT = 0.9999

#: MRC printer dedup epsilon: runs of miss ratios whose successive difference is
#: below this are collapsed (pluss_utils.h:863, 899).
MRC_DEDUP_EPS = 1e-5

#: AET vestigial first-step epsilon (pluss_utils.h:798): with MRC_pred=-1 the
#: branch `MRC_pred - P[prev_t] < 1e-4` is always true, so every c gets an entry.
AET_PRED_EPS = 1e-4

#: Number of dense histogram slots used by the XLA engine.  Slot 0 holds the
#: cold-miss key (-1); slot 1+e holds the log2 bin with key 2**e.  48 exponent
#: slots cover reuse intervals up to 2**47 (a 140-trillion-access stream).
NBINS = 49

#: Default capacity for the fixed-size unique-value extraction of "share"
#: (cross-thread) reuse values, which the reference keeps raw (unbinned) until
#: the racetrack post-pass (pluss_utils.h:928-937; SURVEY.md Q6).
SHARE_CAP = 1024

DEFAULT = SamplerConfig()
