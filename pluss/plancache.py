"""Warm-start layer: runtime salt, XLA persistent-cache arming, AOT
executable sidecars beside the disk plan cache, and a single-flight
compile registry.

Why a second artifact class: the disk plan cache (:mod:`pluss.engine`)
persists HOST-side analysis only (WindowTemplate + verified OverlayPlans)
— the XLA executables themselves lived in per-process memos, so every
fresh process (a ``pluss serve`` daemon start, each sweep worker, every
CLI run) re-paid seconds-to-tens of compile before its first useful
dispatch (BENCH_r05: gemm1024 warmup incl. compile 5.77 s against
0.488 s steady-state reps).  This module gives compiled executables the
same disk persistence and hygiene the plan artifacts already have:

- :func:`runtime_salt` — jax version + backend + device kind + the NBINS
  grid constant.  Serialized executables are PJRT-runtime-specific in a
  way host-side plans are not, so AOT sidecars carry the runtime
  identity ON TOP of the plan-source hash; a jax upgrade or a backend
  switch can never load a stale executable, while plain plan entries
  keep the cheaper source-only salt.
- :func:`aot_save` / :func:`aot_load` — sidecar slots
  (``<group>.aot-<slot>.exe``) beside the plan pickles: written
  atomically, chaos-corruptible and quarantined exactly like the
  pickles (the PR-2 ``plan_cache.get`` fault site), and LRU-evicted as
  one group with their plan pickle (``engine._plan_cache_evict``).
- :func:`aot_supported` — one serialize/pickle/deserialize round-trip
  probe per backend; a PJRT runtime that cannot deserialize degrades
  every caller to plain JIT (bit-identical results, just cold), with
  ``engine.plan_cache.aot_load_fail`` counting the failed restores.
- :class:`CompileRegistry` — in-process single-flight: N concurrent
  requests for one key run ONE build; waiters share the result or the
  SAME raised exception, so an in-flight compile failure rejects every
  waiter with the identical typed error.  (``functools.lru_cache`` does
  NOT dedupe concurrent builds — two threads racing a cold key both
  trace and compile.)
"""

from __future__ import annotations

import functools
import os
import threading

from pluss import obs


def runtime_salt() -> str:
    """Runtime identity of the ACTIVE backend's serialized executables.

    Folded into every AOT sidecar slot (path hash AND payload, belt and
    braces): a deserialized executable is only valid on the exact PJRT
    runtime that produced it, so the salt pins jax version, backend,
    device kind, and the histogram grid constant the kernels bake in.
    Plan pickles deliberately do NOT use this — they are host math,
    portable across jax versions, and keyed by the source hash alone
    (``engine._plan_cache_salt``)."""
    import jax

    return _runtime_salt(jax.default_backend())


@functools.lru_cache(maxsize=None)
def _runtime_salt(backend: str) -> str:
    import jax

    from pluss.config import NBINS

    try:
        kind = jax.devices(backend)[0].device_kind
    except Exception:
        kind = "unknown"
    return f"jax={jax.__version__}/{backend}/{kind}/nbins={NBINS}"


def arm_xla_cache(path: str | None = None,
                  min_compile_s: float | None = None) -> str | None:
    """Arm JAX's persistent compilation cache (the HLO->binary layer
    below the AOT sidecars — it dedupes compiles across DIFFERENT plan
    keys that lower to equal HLO, and covers backends the sidecar probe
    rejects).  Directory: ``path`` arg, else ``PLUSS_XLA_CACHE_DIR``;
    returns the armed directory or None when unset.  The min-compile-time
    floor (``PLUSS_XLA_CACHE_MIN_COMPILE_S``, default 1.0 s) keeps tier-1
    fast: trivial test kernels never pay the cache-write fsync."""
    import jax

    path = path or os.environ.get("PLUSS_XLA_CACHE_DIR")
    if not path:
        return None
    os.makedirs(path, exist_ok=True)
    if min_compile_s is None:
        min_compile_s = float(
            os.environ.get("PLUSS_XLA_CACHE_MIN_COMPILE_S", 1.0))
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_s))
    return path


def aot_supported() -> bool:
    """Whether this process can serialize AND restore executables on the
    active backend — probed once per backend with a trivial kernel.
    ``PLUSS_NO_AOT=1`` force-disables (sidecar reads and writes both)."""
    if os.environ.get("PLUSS_NO_AOT"):
        return False
    import jax

    return _aot_probe(jax.default_backend())


@functools.lru_cache(maxsize=None)
def _aot_probe(backend: str) -> bool:
    import pickle

    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        from jax.experimental import serialize_executable as se

        exe = jax.jit(lambda x: x + 1).lower(
            jax.ShapeDtypeStruct((2,), jnp.int32)).compile()
        blob = pickle.dumps(se.serialize(exe))
        restored = se.deserialize_and_load(*pickle.loads(blob))
        return bool(
            (np.asarray(restored(jnp.zeros(2, jnp.int32))) == 1).all())
    except Exception as e:  # noqa: BLE001 — degrade to JIT, loudly, once
        import sys

        print(f"pluss: AOT executable cache disabled on backend "
              f"{backend!r} ({type(e).__name__}: {e}); executables will "
              "JIT per process", file=sys.stderr)
        return False


def _kernel_flavor() -> tuple:
    """The resolved fused-kernel configuration this process traces with
    (events + d24v decode, Pallas vs XLA) — folded into every AOT slot
    hash: the two paths are bit-identical but their EXECUTABLES are not,
    so a flavor flip (env knob, autotune geometry, probe fallback) must
    land on a different sidecar rather than replay the other path's
    bytes.  Deliberately NOT part of :func:`runtime_salt`: the autotuner
    sidecar is keyed by the salt and itself feeds this resolution — a
    salt that depended on it would chase its own tail."""
    from pluss.ops import pallas_decode, pallas_events

    return ("ev-pallas" if pallas_events.enabled() else "ev-xla",
            "dec-pallas" if pallas_decode.enabled() else "dec-xla")


def aot_path(group: str | None, parts: tuple) -> str | None:
    """Disk slot for one serialized executable, or None when the plan
    cache is off or the plan has no stable group key.  ``group`` is the
    owning plan-cache entry's key (sidecars of one entry share its
    prefix, so eviction unlinks them as a unit); ``parts`` identify the
    executable within the group (backend path, segment, slice length,
    thread batch, share cap) — the resolved kernel flavor
    (:func:`_kernel_flavor`) rides alongside them."""
    if group is None:
        return None
    from pluss import engine

    root = engine._plan_cache_root()
    if root is None:
        return None
    import hashlib

    slot = hashlib.sha256(
        repr((runtime_salt(), _kernel_flavor()) + parts).encode()
    ).hexdigest()[:16]
    os.makedirs(root, exist_ok=True)
    return os.path.join(root, f"{group}.aot-{slot}.exe")


def aot_load(path: str | None):
    """Restore a serialized executable from its sidecar, or None.

    Counter discipline (``engine.plan_cache.*``): ``aot_hit`` on a
    successful restore (recency touched for the LRU, like plan hits),
    ``aot_miss`` when the slot is empty or carries a different runtime
    salt (a stale-but-wellformed entry is a miss — the fresh compile
    overwrites it), ``aot_load_fail`` (+ ``corrupt`` for bad bytes) when
    the slot exists but cannot be restored — those quarantine to
    ``*.corrupt`` exactly like plan pickles, so a poisoned sidecar is
    paid once, not every process start."""
    if path is None or not aot_supported():
        return None
    if not os.path.exists(path):
        obs.counter_add("engine.plan_cache.aot_miss")
        return None
    import pickle

    from pluss.resilience import faults
    from pluss.resilience.errors import quarantine_artifact

    faults.corrupt("plan_cache.get", path)   # chaos: corrupt_cache site
    try:
        with open(path, "rb") as f:
            salt, ser, in_tree, out_tree = pickle.load(f)
    except Exception as e:  # noqa: BLE001 — quarantine, degrade to JIT
        obs.counter_add("engine.plan_cache.corrupt")
        obs.counter_add("engine.plan_cache.aot_load_fail")
        quarantine_artifact(path, "AOT executable sidecar", e,
                            action="recompiling")
        return None
    if salt != runtime_salt():
        obs.counter_add("engine.plan_cache.aot_miss")
        return None
    try:
        from jax.experimental import serialize_executable as se

        exe = se.deserialize_and_load(ser, in_tree, out_tree)
    except Exception as e:  # noqa: BLE001 — PJRT refused the bytes
        obs.counter_add("engine.plan_cache.aot_load_fail")
        quarantine_artifact(path, "AOT executable sidecar", e,
                            action="recompiling")
        return None
    obs.counter_add("engine.plan_cache.aot_hit")
    obs.trace_event("plan_cache.aot_consult", outcome="hit")
    try:
        os.utime(path)   # refresh the GROUP's LRU recency
    except OSError:
        pass
    return exe


def aot_save(path: str | None, exe) -> bool:
    """Serialize ``exe`` into its sidecar slot (atomic tmp + rename, the
    plan pickles' write discipline).  Best-effort: serialization refusals
    are counted (``aot_save_fail``) and swallowed — the in-process memo
    still has the executable; only the NEXT process stays cold."""
    if path is None or not aot_supported():
        return False
    import pickle
    import uuid

    try:
        from jax.experimental import serialize_executable as se

        ser, in_tree, out_tree = se.serialize(exe)
        payload = (runtime_salt(), ser, in_tree, out_tree)
        tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(payload, f)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    except Exception:  # noqa: BLE001 — cold next process, not an error
        obs.counter_add("engine.plan_cache.aot_save_fail")
        return False
    from pluss import engine

    engine._plan_cache_evict()
    return True


class LazyAotFn:
    """Per-shape AOT wrapper around a jitted fn whose shapes (or device
    placement) are only known at call time.

    Eager AOT (``engine._aot_executable``) lowers from ShapeDtypeStructs
    and suits fns with one static signature on the default device.  This
    wrapper instead lowers from the FIRST CONCRETE CALL per argument
    signature — capturing committed-device placement and ad-hoc shapes
    (the trace replay step's growing line table, per-device shard chunk
    executables) — then restores/saves the executable through the same
    sidecar slots.  Any AOT failure degrades that signature to the plain
    jitted fn: bit-identical, just cold.  ``call_fallback=True`` also
    retries a restored executable's call-time refusal (e.g. a PJRT
    device-binding mismatch after a topology change) through the jit
    path once, then pins the fallback."""

    def __init__(self, jf, group: str | None, parts: tuple,
                 call_fallback: bool = False):
        self._jf = jf
        self._group = group
        self._parts = parts
        self._call_fallback = call_fallback
        self._exes: dict = {}
        self._lock = threading.Lock()

    @staticmethod
    def _sig(a):
        shp = getattr(a, "shape", None)
        dt = getattr(a, "dtype", None)
        if shp is None or dt is None:
            return type(a).__name__
        return (tuple(shp), str(dt))

    def _resolve(self, sig, args):
        with self._lock:
            exe = self._exes.get(sig)
            if exe is not None:
                return exe
            path = aot_path(self._group, self._parts + (sig,))
            exe = aot_load(path)
            if exe is None:
                import time as _time

                t0 = _time.perf_counter()
                try:
                    exe = self._jf.lower(*args).compile()
                except Exception:  # noqa: BLE001 — degrade to plain JIT
                    obs.counter_add("engine.aot_lower_fail")
                    self._exes[sig] = self._jf
                    return self._jf
                obs.counter_add("engine.compiles")
                obs.counter_add("engine.compile_s",
                                _time.perf_counter() - t0)
                if path is not None:
                    aot_save(path, exe)
            self._exes[sig] = exe
            return exe

    def __call__(self, *args):
        sig = tuple(self._sig(a) for a in args)
        exe = self._resolve(sig, args)
        if exe is self._jf or not self._call_fallback:
            return exe(*args)
        try:
            return exe(*args)
        except Exception:  # noqa: BLE001 — restored exe refused the call
            obs.counter_add("engine.aot_call_fail")
            with self._lock:
                self._exes[sig] = self._jf
            return self._jf(*args)


class _Flight:
    __slots__ = ("done", "result", "exc")

    def __init__(self):
        self.done = threading.Event()
        self.result = None
        self.exc: BaseException | None = None


class CompileRegistry:
    """Single-flight deduplication of concurrent expensive builds.

    The first caller for a key is the LEADER and runs ``build()``;
    callers arriving while that build is in flight block on it and
    receive the leader's result — or the leader's exception object
    re-raised, so a failed compile rejects every waiter with the same
    typed error instead of each waiter retrying the doomed compile.
    Entries are dropped on completion: failures are never cached (the
    next cold caller retries fresh) and results live in the caller's own
    memo (``engine._compiled``'s lru, the on-plan slice caches), so the
    registry holds no long-lived references.
    """

    def __init__(self, gauge: str | None = None):
        self._lock = threading.Lock()
        self._inflight: dict = {}
        self._gauge = gauge

    def inflight(self) -> int:
        """Builds currently in flight (the serve SLO publisher exports
        this as the ``serve.compile_inflight`` gauge)."""
        with self._lock:
            return len(self._inflight)

    def _publish(self) -> None:
        if self._gauge:
            obs.gauge_set(self._gauge, float(len(self._inflight)))

    def do(self, key, build):
        """Return ``build()``'s value for ``key``, building at most once
        across concurrent callers.  Do not nest ``do`` calls for one key
        inside ``build`` (the leader would wait on itself)."""
        with self._lock:
            fl = self._inflight.get(key)
            leader = fl is None
            if leader:
                fl = _Flight()
                self._inflight[key] = fl
                self._publish()
        if not leader:
            obs.counter_add("engine.compile_singleflight_waits")
            fl.done.wait()
            if fl.exc is not None:
                raise fl.exc
            return fl.result
        try:
            fl.result = build()
            return fl.result
        except BaseException as e:
            fl.exc = e
            raise
        finally:
            with self._lock:
                self._inflight.pop(key, None)
                self._publish()
            fl.done.set()
