"""`acc` / `speed` CLI — the reference's differential-test driver, TPU-native.

Mirrors the reference's entry points (``/root/reference/src/main.rs:12-37``,
``c_lib/test/sampler/…omp.cpp:334-362``, ``run.sh``):

- ``acc``: run each backend once; print a full block (timing banner, the three
  histogram dumps, "max iteration traversed") per backend.  Unlike the
  reference's Rust binary, global state is fresh per backend run (SURVEY.md Q1
  is a bug we fix, not a behavior we keep), so every block is directly
  comparable — the reference's C++ binaries behave this way too (fresh process
  per run).
- ``speed``: N timed reps per backend (reference: 3), banner+seconds each.

Backends mirror the reference's trio (rayon / spawn / seq) as:
``vmap`` (simulated threads as a vmap axis), ``shard`` (stream windows over the
device mesh, :mod:`pluss.parallel.shard`), ``seq`` (one thread at a time).

Extra subcommands: ``mrc`` exposes the reference's dormant titular capability
(AET -> miss-ratio curve, pluss_utils.h:758-804) as a live, tested path;
``trace`` replays a raw address file through :mod:`pluss.trace` (the
reference's disabled ``pluss_access`` dynamic path, BASELINE config 5);
``lint`` runs the static spec analyzer (:mod:`pluss.analysis`) over one
model (or ``--all``) with no device, no JAX tracing and no stream
enumeration — bounds proofs, race detection, share-span validation and
contract checks as stable PLxxx diagnostics (``--json`` for tooling).
``--verify`` opts the engine modes into the same analysis as a pre-pass:
ERROR-level findings abort before any compilation.
``stats`` aggregates a telemetry event stream (``--telemetry`` /
``PLUSS_TELEMETRY`` on any engine mode records one): span tree,
counter/gauge rollups, and the trace-replay time breakdown;
``--check`` validates the stream against the schema instead
(:mod:`pluss.obs`).

The timed region matches the reference: ``sampler() + pluss_cri_distribute``
(…omp.cpp:337-339).  Compilation is excluded by a warmup call — the analogue of
the reference timing a prebuilt binary, documented here because the reference's
C++ flushes the data cache before timing instead (pluss.cpp:71-81); a TPU
executable cache plays the role of the built binary, not the data cache.
"""

from __future__ import annotations

import argparse
import sys
import time

from pluss import cri, engine, mrc
from pluss.config import SHARE_CAP, SamplerConfig
from pluss.io import acc_block, speed_block
from pluss.models import REGISTRY

BACKENDS = ("vmap", "shard", "seq")


def _sampler_of(backend: str, spec, cfg: SamplerConfig, share_cap: int,
                window: int | None = None, start_point: int | None = None,
                dispatch: str | None = None):
    """() -> (result, rihist) closure for one backend."""
    if backend == "shard":
        from pluss.parallel.shard import default_mesh, shard_run

        mesh = default_mesh()
        run_once = lambda: shard_run(spec, cfg, share_cap, mesh,
                                     start_point=start_point,
                                     window_accesses=window,
                                     dispatch=dispatch)
    else:
        run_once = lambda: engine.run(spec, cfg, share_cap,
                                      start_point=start_point,
                                      window_accesses=window, backend=backend)

    def step():
        res = run_once()
        ri = cri.distribute(res.noshare_list(), res.share_list(), cfg.thread_num)
        return res, ri

    return step


def _timed(step, profile_dir: str | None = None):
    """Time one (sampler + distribute) step — the reference's timed region
    (…omp.cpp:337-339).  ``profile_dir`` wraps the step in a jax profiler
    trace (the observability hook the reference's DEBUG prints stand in for)."""
    import contextlib

    ctx = contextlib.nullcontext()
    if profile_dir:
        import jax

        ctx = jax.profiler.trace(profile_dir)
    with ctx:
        t0 = time.perf_counter()
        res, ri = step()
        dt = time.perf_counter() - t0
    return dt, res, ri


def banner_of(backend: str) -> str:
    return {"vmap": "TPU VMAP", "shard": "TPU SHARD", "seq": "TPU SEQ"}[backend]


def _footprint_doc(fp, bracket) -> dict:
    """JSON view of one model's footprint/MRC-bound report."""
    return {
        "total_lines": fp.total,
        "per_array": {a: int(n) for a, n in zip(fp.arrays, fp.per_array)},
        "per_thread_cold": [int(c) for c in fp.cold],
        "accesses": fp.accesses,
        "mrc_floor": bracket.floor,
        "mrc_plateau_bounds": [bracket.c_lo, bracket.c_hi],
        "guaranteed_reuse": bracket.guaranteed_reuse,
        "levels": [
            {"nest": lv.nest, "path": lv.path, "depth": lv.depth,
             "lines_lo": lv.lines_lo, "lines_hi": lv.lines_hi}
            for lv in fp.levels
        ],
    }


def _cache_geometry_or_usage(args, p):
    """The shared cache-geometry parse (analyze/cotenancy/tune): one
    helper (:func:`pluss.model.hierarchy.cache_geometry`), so the three
    surfaces agree about the LLC by construction.  Malformed flags are
    usage errors, never tracebacks."""
    from pluss.model import hierarchy as hier_mod

    try:
        return hier_mod.cache_geometry(args.cache_kb, args.cache_levels,
                                       args.assoc)
    except ValueError as e:
        p.error(f"{args.mode} mode: {e}")


def _lint_main(args, out, cfg: SamplerConfig | None = None,
               hier=None) -> int:
    """``pluss lint|analyze <model|--all> [--json]`` — pure host analysis,
    exits 1 when any model has ERROR-level diagnostics.  ``analyze``
    (``cfg`` set) adds the schedule-aware passes: placement-refined race
    verdicts (PL304/PL305), line-granular false-sharing detection
    (PL5xx), and the footprint/MRC-bound report under the shared
    ``hier`` cache geometry."""
    import json as json_mod

    from pluss import analysis

    if args.all:
        # each builder's default size — the shapes the benchmarks and the
        # differential driver actually run
        targets = [(name, REGISTRY[name]()) for name in sorted(REGISTRY)]
    else:
        targets = [(args.model, REGISTRY[args.model](args.n))]
    all_diags = []
    footprints: dict[str, dict] = {}
    predictions: dict[str, dict] = {}
    hierarchies: dict[str, dict] = {}
    depvectors: dict[str, dict] = {}
    errors = 0
    for name, spec in targets:
        if cfg is None:
            diags = analysis.lint_spec(spec)
        else:
            diags, fp = analysis.analyze_spec(spec, cfg)
            footprints[spec.name] = _footprint_doc(
                fp, analysis.footprint.mrc_bracket(spec, cfg, fp))
            # per-pair dependence direction/distance vectors (pluss/
            # analysis/depvec.py): the PL301/302 race findings get the
            # vector evidence that justified them appended, and the
            # transform prover's raw material lands on the doc
            from pluss.analysis import depvec as depvec_mod

            vecs = depvec_mod.spec_vectors(spec)
            depvectors[spec.name] = depvec_mod.doc_of(vecs)
            diags = depvec_mod.annotate_races(diags, vecs)
            # the symbolic reuse-interval verdict rides the analyze
            # report: derivability, method, and the exact plateau next to
            # the heuristic bracket above (PL704 = soundness alarm)
            from pluss.analysis import ri

            rep = ri.predict(spec, cfg)
            predictions[spec.name] = ri.report_doc(rep)
            diags = diags + rep.prediction.diagnostics
            if rep.rihist is not None:
                # AET-exact hierarchy read-offs from the derived
                # histogram (pluss/model/hierarchy.py; PLUSS_CACHE_*
                # knobs pick levels/assoc/policy)
                from pluss.model import hierarchy as hier_mod

                hierarchies[spec.name] = hier_mod.hierarchy_doc(
                    rep.rihist, cfg, hier)
        all_diags += analysis.with_model(diags, spec.name)
        errors += analysis.error_count(diags)
    mode = "lint" if cfg is None else "analyze"
    if args.sarif:
        from pluss.analysis import sarif as sarif_mod

        sarif_mod.write_sarif(args.sarif, all_diags)
        print(f"pluss {mode}: SARIF log at {args.sarif}", file=sys.stderr)
    if args.json:
        doc = json_mod.loads(analysis.format_json(all_diags))
        if cfg is not None:
            doc["schedule"] = {"threads": cfg.thread_num,
                               "chunk": cfg.chunk_size,
                               "ds": cfg.ds, "cls": cfg.cls}
            doc["footprint"] = footprints
            doc["prediction"] = predictions
            doc["hierarchy"] = hierarchies
            doc["depvectors"] = depvectors
        out.write(json_mod.dumps(doc, indent=1) + "\n")
    else:
        text = analysis.format_text(all_diags)
        if text:
            out.write(text + "\n")
        if cfg is not None:
            for name, doc in footprints.items():
                out.write(
                    f"{name}: footprint {doc['total_lines']} lines "
                    f"({', '.join(f'{a}={n}' for a, n in doc['per_array'].items())}); "
                    f"cold/thread {doc['per_thread_cold']}; MRC floor "
                    f"{doc['mrc_floor']:.6g}, plateau in "
                    f"[{doc['mrc_plateau_bounds'][0]}, "
                    f"{doc['mrc_plateau_bounds'][1]}]\n")
                out.write(_prediction_line(name, predictions[name]))
                if name in hierarchies:
                    from pluss.model import hierarchy as hier_mod

                    for line in hier_mod.render_hierarchy(
                            hierarchies[name], indent="    "):
                        out.write(f"  {line}\n" if line == "hierarchy:"
                                  else f"{line}\n")
                if name in depvectors:
                    from pluss.analysis import depvec as depvec_mod

                    for line in depvec_mod.render(depvectors[name]):
                        out.write(f"  {line}\n")
        n_warn = sum(1 for d in all_diags
                     if d.severity is analysis.Severity.WARNING)
        out.write(f"pluss {mode}: {len(targets)} model(s), {errors} "
                  f"error(s), {n_warn} warning(s)\n")
    return 1 if errors else 0


def _prediction_line(name: str, doc: dict) -> str:
    """One text-report line per model from a ``ri.report_doc`` dict."""
    if not doc["derivable"]:
        codes = ",".join(sorted({d["code"]
                                 for d in doc.get("diagnostics", ())}))
        return f"{name}: prediction not derivable ({codes})\n"
    where = "unreachable"
    if "mrc_plateau_exact" in doc:
        where = (f"{doc['mrc_plateau_exact']} "
                 + ("inside" if doc["plateau_in_bracket"] else "OUTSIDE")
                 + " the bracket")
    g = f", G={doc['period_horizon']}" if "period_horizon" in doc else ""
    return (f"{name}: prediction {doc['method']}{g}, {doc['accesses']} "
            f"accesses, exact plateau {where}\n")


def _predict_main(args, p, out, setup_platform) -> int:
    """``pluss predict <model|--all> [--json|--check|--sarif]`` — the
    sampling-free static MRC path (:mod:`pluss.analysis.ri`): symbolic
    per-thread reuse-interval histograms composed through CRI + AET with
    ZERO device dispatches.  ``--check`` additionally runs the engine on
    every derivable target and requires bit-identical histograms (MRC
    within ``ri.MRC_EPS``) — the cross-validation gate run.sh pins."""
    import json as json_mod

    from pluss import analysis
    from pluss.analysis import ri

    if args.target is not None and args.all:
        p.error("predict mode: give a model or --all, not both")
    if args.target is not None:
        if args.target not in REGISTRY:
            p.error(f"predict mode: unknown model {args.target!r}")
        args.model = args.target
    cfg = SamplerConfig(thread_num=args.threads, chunk_size=args.chunk)
    if args.all:
        targets = [(nm, REGISTRY[nm](args.n)) for nm in sorted(REGISTRY)]
    else:
        targets = [(args.model, REGISTRY[args.model](args.n))]
    docs: dict[str, dict] = {}
    reports = []
    all_diags = []
    errors = 0
    for name, spec in targets:
        rep = ri.predict(spec, cfg)
        reports.append((name, spec, rep))
        docs[spec.name] = ri.report_doc(rep)
        all_diags += analysis.with_model(rep.prediction.diagnostics,
                                         spec.name)
        errors += analysis.error_count(rep.prediction.diagnostics)
    rc = 1 if errors else 0
    if args.check:
        # cross-validate every derivable prediction against a real
        # engine run (the only device work in this mode, and only here)
        setup_platform()
        for name, spec, rep in reports:
            if not rep.prediction.derivable:
                print(f"pluss predict: {spec.name}: check skipped "
                      "(not derivable)", file=sys.stderr)
                continue
            res = engine.run(spec, cfg, SHARE_CAP)
            ok, detail = ri.check_against_engine(rep, res, cfg)
            docs[spec.name]["check"] = detail
            if not ok:
                rc = 1
                print(f"pluss predict: {spec.name}: CHECK FAILED "
                      f"{detail}", file=sys.stderr)
            else:
                kind = "bit-identical" if detail["mrc_exact"] \
                    else f"l2={detail['mrc_l2_error']:.2e}"
                print(f"pluss predict: {spec.name}: histograms "
                      f"bit-identical to engine.run, MRC {kind}",
                      file=sys.stderr)
    if args.sarif:
        from pluss.analysis import sarif as sarif_mod

        sarif_mod.write_sarif(args.sarif, all_diags)
        print(f"pluss predict: SARIF log at {args.sarif}",
              file=sys.stderr)
    if args.json:
        doc = {"schedule": {"threads": cfg.thread_num,
                            "chunk": cfg.chunk_size,
                            "ds": cfg.ds, "cls": cfg.cls},
               "models": docs}
        out.write(json_mod.dumps(doc, indent=1) + "\n")
    else:
        for name, spec, rep in reports:
            out.write(_prediction_line(spec.name, docs[spec.name]))
        n_derived = sum(1 for _, _, r in reports
                        if r.prediction.derivable)
        out.write(f"pluss predict: {n_derived}/{len(reports)} model(s) "
                  f"derivable, {errors} error(s)\n")
    return rc


def _cotenancy_main(args, p, out) -> int:
    """``pluss cotenancy <a+b[+...]> [--json|--sarif|--check]`` — the
    cross-nest co-tenancy composition (:mod:`pluss.analysis.
    interference`): per-workload degraded MRCs off the merged stream's
    AET clock plus PL801/PL802/PL803 verdicts.  ``--check`` pins the
    composed curves against the interleaved schedule-simulation oracle
    (pure host numpy; no device).  Malformed target lists are usage
    errors, never tracebacks."""
    import json as json_mod

    from pluss.analysis import interference

    if not args.target:
        p.error("cotenancy mode requires a modelA+modelB[+...] target")
    names = [t.strip() for t in args.target.split("+")]
    if any(not t for t in names):
        p.error(f"cotenancy mode: malformed target {args.target!r} "
                "(empty workload name)")
    unknown = [t for t in names if t not in REGISTRY]
    if unknown:
        p.error(f"cotenancy mode: unknown model(s) "
                f"{', '.join(map(repr, unknown))}")
    if len(names) < 2:
        p.error("cotenancy mode: co-tenancy needs >= 2 workloads "
                f"(got {args.target!r}; join them with '+')")
    # the shared geometry parse: --cache-kb / --cache-levels retarget the
    # verdict point AND the read-off LLC together (the r16 drift fix)
    llc_kb, _hier = _cache_geometry_or_usage(args, p)
    cfg = SamplerConfig(thread_num=args.threads, chunk_size=args.chunk,
                        **({} if llc_kb is None
                           else {"cache_kb": llc_kb}))
    inputs, refusals = interference.from_models(names, cfg, args.n)
    if len(inputs) < 2:
        rep = interference.CotenancyReport(
            tuple(names), cfg.cache_kb,
            interference.interference_threshold(), [], [], [], [], {},
            refusals)
    else:
        rep = interference.compose(inputs, cfg)
        rep.diagnostics = refusals + rep.diagnostics
    rc = 1 if len(inputs) < 2 else 0
    doc = rep.doc()
    if args.check and len(inputs) >= 2:
        ok, detail = interference.check_against_oracle(rep, inputs, cfg)
        doc["check"] = detail
        for wd in detail["per_workload"]:
            status = "ok" if wd["ok"] else "CHECK FAILED"
            print(f"pluss cotenancy: {wd['workload']}: {status} "
                  f"(max|err| {wd['max_abs_err']:.3g}, mae "
                  f"{wd['mae']:.3g}, edge {wd['edge_err']:.3g}, solo "
                  f"max|err| {wd['solo_max_abs_err']:.3g})",
                  file=sys.stderr)
        if not ok:
            rc = 1
    elif args.check:
        print("pluss cotenancy: check skipped (fewer than 2 composable "
              "workloads)", file=sys.stderr)
    if args.sarif:
        from pluss.analysis import sarif as sarif_mod

        sarif_mod.write_sarif(args.sarif, rep.diagnostics)
        print(f"pluss cotenancy: SARIF log at {args.sarif}",
              file=sys.stderr)
    if args.json:
        doc["schedule"] = {"threads": cfg.thread_num,
                           "chunk": cfg.chunk_size,
                           "ds": cfg.ds, "cls": cfg.cls}
        out.write(json_mod.dumps(doc, indent=1) + "\n")
    else:
        for d in rep.diagnostics:
            if d.code == "PL803":
                out.write(d.format() + "\n")
        for v in rep.verdicts:
            out.write(f"{v.name}: solo {v.solo_mr:.6g} -> degraded "
                      f"{v.degraded_mr:.6g} (+{v.inflation:.6g}) "
                      f"[{v.code}] share p={v.p:.4g}\n")
        n_sev = sum(1 for v in rep.verdicts if v.code == "PL801")
        n_ref = sum(1 for d in rep.diagnostics if d.code == "PL803")
        out.write(f"pluss cotenancy: {len(names)} workload(s) at "
                  f"{rep.cache_kb} KB, threshold {rep.threshold:g}: "
                  f"{n_sev} severe, {len(rep.verdicts) - n_sev} benign, "
                  f"{n_ref} refused\n")
    return rc


def _tune_main(args, p, out, setup_platform) -> int:
    """``pluss tune <model|--all> [--json|--check|--sarif]`` — the
    proof-carrying static schedule auto-optimizer (:mod:`pluss.analysis.
    tune`): exhaustive-with-pruning search over (threads, chunk, window,
    share_cap) — axes from --sweep-threads/--sweep-chunks/--window/
    --share-cap — scored entirely on the host at the declared LLC
    (--cache-kb / --cache-levels / --assoc, or the PLUSS_CACHE_* env).
    Typed verdicts: PL901 proven-best, PL902 tie-within-epsilon, PL903
    refusal (rc 1), PL904 engine cross-check alarm under ``--check``
    (the only device work in this mode)."""
    import json as json_mod

    from pluss import analysis
    from pluss.analysis import tune as tune_mod

    if args.target is not None and args.all:
        p.error("tune mode: give a model or --all, not both")
    if args.target is not None:
        if args.target not in REGISTRY:
            p.error(f"tune mode: unknown model {args.target!r}")
        args.model = args.target
    llc_kb, hier = _cache_geometry_or_usage(args, p)
    try:
        ts = [int(t) for t in args.sweep_threads.split(",")]
        cks = [int(c) for c in args.sweep_chunks.split(",")]
    except ValueError:
        p.error("tune mode: malformed --sweep-threads/--sweep-chunks "
                "(want comma-separated ints)")
    cands = tune_mod.space(ts, cks, (args.window,), (args.share_cap,))
    if args.transforms:
        # the PR-18 extension: search (transform, schedule) pairs, not
        # just schedules — one model at a time (the space is per-spec)
        if args.all:
            p.error("tune mode: --transforms wants a single model, "
                    "not --all")
        return _tune_transforms(args, out, setup_platform, cands, hier)
    if args.all:
        targets = [(nm, REGISTRY[nm](args.n)) for nm in sorted(REGISTRY)]
    else:
        targets = [(args.model, REGISTRY[args.model](args.n))]
    docs: dict[str, dict] = {}
    reports = []
    all_diags = []
    rc = 0
    for name, spec in targets:
        rep = tune_mod.tune(spec, candidates=cands, hier=hier)
        reports.append((name, spec, rep))
        docs[spec.name] = rep.doc()
        all_diags += analysis.with_model(rep.diagnostics, spec.name)
        if rep.code == "PL903":
            rc = 1
    if args.check:
        # cross-validate each winner against ONE live engine run under
        # the tuned schedule (the only device work in tune mode)
        setup_platform()
        for name, spec, rep in reports:
            if rep.winner is None:
                print(f"pluss tune: {spec.name}: check skipped "
                      "(refused)", file=sys.stderr)
                continue
            ok, detail, diags = tune_mod.check_winner(spec, rep)
            docs[spec.name]["check"] = detail
            all_diags += analysis.with_model(diags, spec.name)
            if not ok:
                rc = 1
                print(f"pluss tune: {spec.name}: CHECK FAILED (PL904) "
                      f"{detail}", file=sys.stderr)
            else:
                kind = "bit-identical" if detail["mrc_exact"] \
                    else f"l2={detail['mrc_l2_error']:.2e}"
                print(f"pluss tune: {spec.name}: winner "
                      f"{rep.winner.candidate.label()} verified against "
                      f"engine.run (histograms bit-identical, MRC "
                      f"{kind})", file=sys.stderr)
    if args.sarif:
        from pluss.analysis import sarif as sarif_mod

        sarif_mod.write_sarif(args.sarif, all_diags)
        print(f"pluss tune: SARIF log at {args.sarif}", file=sys.stderr)
    if args.json:
        doc = {"target_kb": reports[0][2].target_kb,
               "hierarchy": docs[reports[0][1].name]["hierarchy"],
               "models": docs}
        out.write(json_mod.dumps(doc, indent=1) + "\n")
    else:
        for name, spec, rep in reports:
            v = rep.diagnostics[0]
            out.write(f"{spec.name}: [{v.code}] {v.message}\n")
        n_best = sum(1 for _, _, r in reports if r.code == "PL901")
        n_tie = sum(1 for _, _, r in reports if r.code == "PL902")
        n_ref = sum(1 for _, _, r in reports if r.code == "PL903")
        out.write(f"pluss tune: {len(reports)} model(s) over "
                  f"{len(cands)} candidate(s) at "
                  f"{reports[0][2].target_kb} KB LLC: {n_best} "
                  f"proven-best, {n_tie} tie(s), {n_ref} refused\n")
    return rc


def _tune_transforms(args, out, setup_platform, cands, hier) -> int:
    """``pluss tune --transforms <model>`` — extend the PL901 dominance-
    pruned schedule search over the legal transform space (:mod:`pluss.
    analysis.transform`): every proven-legal interchange / hierarchy-
    laddered tiling / fusion of the model is tuned at the declared LLC,
    and the best (transform, schedule) pair is reported with its static
    MRC delta against the untransformed winner.  ``--check`` cross-
    validates that winner with ONE engine run of the TRANSFORMED spec
    (the only device work in this mode)."""
    import json as json_mod

    from pluss import analysis
    from pluss.analysis import transform as tf
    from pluss.analysis import tune as tune_mod

    spec = REGISTRY[args.model](args.n)
    rep = tf.search_transforms(spec, candidates=cands, hier=hier)
    doc = rep.doc()
    all_diags = analysis.with_model(rep.diagnostics, spec.name)
    rc = 1 if any(d.code == "PL903" for d in rep.diagnostics) else 0
    if args.check and rep.best is not None:
        # one live engine run of the winning TRANSFORMED spec under its
        # tuned schedule — bit-identity or PL904, like plain tune
        setup_platform()
        ok, detail, diags = tune_mod.check_winner(
            rep.best.transform.spec, rep.best.tune)
        doc["check"] = detail
        all_diags += analysis.with_model(diags, spec.name)
        if not ok:
            rc = 1
            print(f"pluss tune: {spec.name}: transformed winner CHECK "
                  f"FAILED (PL904) {detail}", file=sys.stderr)
        else:
            kind = "bit-identical" if detail["mrc_exact"] \
                else f"l2={detail['mrc_l2_error']:.2e}"
            print(f"pluss tune: {spec.name}: transformed winner "
                  f"{rep.best.transform.label()} + "
                  f"{rep.best.tune.winner.candidate.label()} verified "
                  f"against engine.run (histograms bit-identical, MRC "
                  f"{kind})", file=sys.stderr)
    elif args.check:
        print(f"pluss tune: {spec.name}: transform check skipped (no "
              "transform beats the untransformed winner)",
              file=sys.stderr)
    if args.sarif:
        from pluss.analysis import sarif as sarif_mod

        sarif_mod.write_sarif(args.sarif, all_diags)
        print(f"pluss tune: SARIF log at {args.sarif}", file=sys.stderr)
    if args.json:
        out.write(json_mod.dumps(doc, indent=1) + "\n")
    else:
        for d in rep.diagnostics:
            out.write(f"{spec.name}: [{d.code}] {d.message}\n")
        if rep.best is not None:
            out.write(f"pluss tune: {spec.name}: best transform "
                      f"{rep.best.transform.label()} + "
                      f"{rep.best.tune.winner.candidate.label()} "
                      f"(predicted miss {rep.best.score():.6g}, delta "
                      f"{rep.delta:+.6g}) at {rep.target_kb} KB LLC\n")
        else:
            out.write(f"pluss tune: {spec.name}: no transform beats "
                      f"the untransformed winner at {rep.target_kb} KB "
                      "LLC\n")
    return rc


def _autotune_main(args, p, out, setup_platform) -> int:
    """``pluss autotune [--force] [--dry-run] [--refs N]`` — calibrate
    and persist the streamed-replay batch geometry for THIS runtime
    (:mod:`pluss.autotune`), or with ``--dry-run`` just validate the
    persisted sidecar.  The winner feeds ``replay_file``'s defaults and
    the fused-kernel resolution on every later run (witnessed by the
    ``autotune.hit`` counter — zero re-calibration)."""
    from pluss import autotune

    if args.force and args.dry_run:
        p.error("autotune mode: --force and --dry-run are exclusive "
                "(--dry-run never calibrates)")
    if args.dry_run:
        # pure sidecar validation: no device, no platform setup
        return autotune.dry_run(out)
    setup_platform()
    kw = {} if args.refs is None else {"n_refs": args.refs}
    doc = autotune.calibrate(force=args.force, out=sys.stderr, **kw)
    geo = doc["geometry"]
    out.write("pluss autotune: winner "
              + "  ".join(f"{k}={geo[k]}" for k in sorted(geo))
              + f"  ({doc.get('refs_per_sec', 0):.0f} refs/s)\n")
    return 0


def _transform_main(args, p, out, setup_platform) -> int:
    """``pluss transform <model> (--interchange A,B | --tile L:S,... |
    --fuse A+B) [--json|--sarif|--check|--register]`` — the proof-
    carrying loop-transformation prover and spec-to-spec transformer
    (:mod:`pluss.analysis.transform`).  Typed verdicts: PL951 proven
    legal (the transformed nest is an ordinary LoopNestSpec —
    printable, registerable, servable), PL952 proven illegal with the
    concrete violating pair, PL953 typed refusal; rc 0 only on PL951.
    ``--check`` runs the TRANSFORMED spec once through the live engine
    and requires the static MRC prediction to match bit-identically
    (PL954 alarm otherwise)."""
    import json as json_mod

    from pluss import analysis, spec_codec
    from pluss.analysis import transform as tf

    if not args.target:
        p.error("transform mode requires a model (e.g. `pluss "
                "transform gemm --interchange 0,2`)")
    if args.target not in REGISTRY:
        p.error(f"transform mode: unknown model {args.target!r}")
    picked = [f for f in (args.interchange, args.tile, args.fuse)
              if f is not None]
    if len(picked) != 1:
        p.error("transform mode wants exactly one of "
                "--interchange/--tile/--fuse")
    spec = REGISTRY[args.target](args.n)
    cfg = SamplerConfig(thread_num=args.threads, chunk_size=args.chunk)
    try:
        if args.interchange is not None:
            a, b = tf.parse_interchange(args.interchange)
            rep = tf.interchange(spec, a, b)
        elif args.tile is not None:
            rep = tf.tile(spec, tf.parse_tile(args.tile))
        else:
            na, nb = tf.parse_fuse(args.fuse)
            rep = tf.fuse(spec, na, nb)
    except ValueError as e:
        p.error(f"transform mode: {e}")
    doc = rep.doc()
    diags = analysis.with_model(rep.diagnostics, spec.name)
    rc = 0 if rep.code == "PL951" else 1
    if args.check:
        if rep.spec is None:
            print(f"pluss transform: {spec.name}: check skipped "
                  f"({rep.code}: no transformed spec)", file=sys.stderr)
        else:
            setup_platform()
            ok, detail, cdiags = tf.check_transform(rep, cfg)
            doc["check"] = detail
            diags += analysis.with_model(cdiags, spec.name)
            if detail.get("skipped"):
                print(f"pluss transform: {rep.spec.name}: check "
                      f"skipped (prediction refused: "
                      f"{detail['codes']})", file=sys.stderr)
            elif not ok:
                rc = 1
                print(f"pluss transform: {rep.spec.name}: CHECK FAILED "
                      f"(PL954) {detail}", file=sys.stderr)
            else:
                kind = "bit-identical" if detail["mrc_exact"] \
                    else f"l2={detail['mrc_l2_error']:.2e}"
                print(f"pluss transform: {rep.spec.name}: verified "
                      f"against engine.run (histograms bit-identical, "
                      f"MRC {kind})", file=sys.stderr)
    if args.register and rep.spec is not None:
        import os

        os.makedirs(args.registry_dir, exist_ok=True)
        path = os.path.join(args.registry_dir, f"{rep.spec.name}.json")
        with open(path, "w") as f:
            f.write(spec_codec.dump_spec(rep.spec) + "\n")
        print(f"pluss transform: registered {rep.spec.name} -> {path} "
              f"(PLUSS_SPEC_DIR={args.registry_dir} serves it as a "
              "registry model)", file=sys.stderr)
    if args.sarif:
        from pluss.analysis import sarif as sarif_mod

        sarif_mod.write_sarif(args.sarif, diags)
        print(f"pluss transform: SARIF log at {args.sarif}",
              file=sys.stderr)
    if args.json:
        out.write(json_mod.dumps(doc, indent=1) + "\n")
    else:
        for d in diags:
            out.write(d.format() + "\n")
        tail = f" -> {rep.spec.name}" if rep.spec is not None else ""
        out.write(f"pluss transform: {spec.name}: {rep.label()}"
                  f"{tail} [{rep.code}]\n")
    return rc


def _verify_spec(spec, cfg: SamplerConfig, out_err) -> int:
    """The ``--verify`` pre-pass: the full schedule-aware analysis (lint
    + placement refinement + false sharing) under the RUN's own schedule,
    before any compilation.  Returns the number of ERROR diagnostics
    (caller aborts when nonzero); errors and warnings go to stderr so
    they never pollute the acc/speed block diffs."""
    from pluss import analysis

    diags, _ = analysis.analyze_spec(spec, cfg)
    diags = analysis.with_model(diags, spec.name)
    text = analysis.format_text(diags)
    if text:
        out_err.write(text + "\n")
    return analysis.error_count(diags)


def _run_spec_block(spec, cfg: SamplerConfig, args, out):
    """One acc-style block (timed vmap run + the three histogram dumps)
    for a frontend-derived or file-loaded spec — the same diffable
    format as `pluss acc`."""
    step = _sampler_of("vmap", spec, cfg, args.share_cap, args.window,
                       args.start_point)
    step()  # warmup: exclude compilation from the timed region
    dt, res, ri = _timed(step, args.profile)
    acc_block(f"TPU IMPORT {spec.name}", dt, res.noshare_list(),
              res.share_list(), ri, res.max_iteration_count, out)
    return res, ri


def _check_against_model(args, cfg: SamplerConfig, res, ri, spec,
                         ref) -> int:
    """The import bit-identity gate: the registry model at --n, same
    schedule, must produce byte-identical histograms and MRC.  ``ref``
    is the reference ``(res, curve)`` — engine run AND its MRC computed
    ONCE by the caller, not once per derived spec."""
    import numpy as np

    ref_res, ref_curve = ref
    same_hist = (res.noshare_list() == ref_res.noshare_list()
                 and res.share_list() == ref_res.share_list())
    same_mrc = np.array_equal(mrc.aet_mrc(ri, cfg), ref_curve)
    if same_hist and same_mrc:
        print(f"pluss import: {spec.name}: histogram + MRC byte-"
              f"identical to registry {args.check_model}({args.n})",
              file=sys.stderr)
        return 0
    print(f"pluss import: {spec.name}: DIVERGES from registry "
          f"{args.check_model}({args.n}) "
          f"(histograms {'==' if same_hist else '!='}, "
          f"MRC {'==' if same_mrc else '!='})", file=sys.stderr)
    return 1


def _import_main(args, p, out, setup_platform) -> int:
    """``pluss import <file.py|file.c> [--run|--json|--register]``."""
    import json as json_mod

    from pluss import analysis, frontend, spec_codec

    if not args.target:
        p.error("import mode requires a source file (.py DSL or "
                ".c pragma-C)")
    if args.check_model is not None and args.check_model not in REGISTRY:
        p.error(f"--check-model: unknown model {args.check_model!r}")
    try:
        # --verify upgrades the admission gate to the schedule-aware
        # PR-3 analysis under the CLI's own (--threads, --chunk)
        gate_cfg = SamplerConfig(thread_num=args.threads,
                                 chunk_size=args.chunk) \
            if args.verify else None
        pairs = frontend.import_path(args.target, gate_cfg)
    except frontend.FrontendError as e:
        # typed rejection: PL6xx grammar findings, or the analyzer's own
        # diagnostics when the gate refused a grammatical source
        for d in e.diagnostics:
            print(d.format(), file=sys.stderr)
        print(f"pluss import: {args.target}: rejected ({e.code})",
              file=sys.stderr)
        return 1
    for spec, diags in pairs:
        text = analysis.format_text(diags)
        if text:      # warnings only — errors raised above
            print(text, file=sys.stderr)
    print(f"pluss import: {args.target}: {len(pairs)} spec(s) derived, "
          f"analyzer-clean ({', '.join(s.name for s, _ in pairs)})",
          file=sys.stderr)
    if args.json:
        docs = [spec_codec.spec_to_json(s) for s, _ in pairs]
        out.write(json_mod.dumps(docs[0] if len(docs) == 1 else docs,
                                 indent=1) + "\n")
    if args.register:
        import os

        os.makedirs(args.registry_dir, exist_ok=True)
        for spec, _ in pairs:
            path = os.path.join(args.registry_dir, f"{spec.name}.json")
            with open(path, "w") as f:
                f.write(spec_codec.dump_spec(spec) + "\n")
            print(f"pluss import: registered {spec.name} -> {path} "
                  f"(PLUSS_SPEC_DIR={args.registry_dir} serves it as a "
                  "registry model)", file=sys.stderr)
    rc = 0
    if args.predict:
        # frontend-derived specs ride the same static-prediction path as
        # registry models: host-only, zero device dispatches
        from pluss.analysis import ri

        cfg = SamplerConfig(thread_num=args.threads,
                            chunk_size=args.chunk)
        for spec, _ in pairs:
            rep = ri.predict(spec, cfg)
            doc = ri.report_doc(rep)
            out.write(_prediction_line(spec.name, doc))
            rc |= 1 if analysis.error_count(
                rep.prediction.diagnostics) else 0
    if args.run or args.check_model:
        setup_platform()
        run_cfg = SamplerConfig(thread_num=args.threads,
                                chunk_size=args.chunk)
        ref = None
        if args.check_model:   # the reference runs once, not per spec
            ref_res, ref_ri = _sampler_of(
                "vmap", REGISTRY[args.check_model](args.n), run_cfg,
                args.share_cap, args.window, args.start_point)()
            ref = (ref_res, mrc.aet_mrc(ref_ri, run_cfg))
        for spec, _ in pairs:
            res, ri = _run_spec_block(spec, run_cfg, args, out)
            if ref is not None:
                rc |= _check_against_model(args, run_cfg, res, ri, spec,
                                           ref)
    return rc


def _spec_main(args, p, out, setup_platform) -> int:
    """``pluss spec dump <model>`` / ``pluss spec load <file.json>``."""
    from pluss import analysis, spec_codec
    from pluss.resilience.errors import InvalidRequest

    verb = args.target
    if verb not in ("dump", "load"):
        p.error("spec mode: `pluss spec dump <model>` or "
                "`pluss spec load <file.json> [--run]`")
    if verb == "dump":
        if not args.arg2:
            # an omitted model must not silently dump the --model
            # default (the `pluss lint gemm` stray-positional class)
            p.error("spec dump requires a model name "
                    "(`pluss spec dump <model> [--n N]`)")
        model = args.arg2
        if model not in REGISTRY:
            p.error(f"spec dump: unknown model {model!r}")
        out.write(spec_codec.dump_spec(REGISTRY[model](args.n)) + "\n")
        return 0
    if not args.arg2:
        p.error("spec load requires a spec JSON file path")
    try:
        spec = spec_codec.load_spec_file(args.arg2)
    except InvalidRequest as e:
        print(f"pluss spec load: {e}", file=sys.stderr)
        return 1
    # loaded specs pass the same lint gate as served/imported ones
    diags = analysis.with_model(analysis.lint_spec(spec), spec.name)
    text = analysis.format_text(diags)
    if text:
        print(text, file=sys.stderr)
    if analysis.error_count(diags):
        print(f"pluss spec load: {spec.name} rejected by the static "
              "analyzer", file=sys.stderr)
        return 1
    if args.run:
        setup_platform()
        cfg = SamplerConfig(thread_num=args.threads,
                            chunk_size=args.chunk)
        _run_spec_block(spec, cfg, args, out)
    else:
        from pluss.spec import loop_size

        total = sum(loop_size(n) for n in spec.nests)
        out.write(f"{spec.name}: {len(spec.nests)} nest(s), "
                  f"{len(spec.arrays)} array(s), {total} accesses; "
                  "lint clean\n")
    return 0


def main(argv: list[str] | None = None) -> int:
    from pluss.utils.platform import enable_x64

    enable_x64()
    p = argparse.ArgumentParser(prog="pluss", description=__doc__)
    p.add_argument("mode",
                   choices=("acc", "speed", "mrc", "trace", "sweep",
                            "sample", "lint", "analyze", "predict",
                            "cotenancy", "tune", "transform", "stats",
                            "serve", "import", "spec", "autotune"))
    p.add_argument("target", nargs="?", default=None,
                   help="stats mode: telemetry event stream (events.jsonl) "
                        "to aggregate; import mode: the .py (DSL) or .c "
                        "(pragma-C) source file; spec mode: dump | load; "
                        "predict mode: the model to predict; cotenancy "
                        "mode: the co-scheduled workloads as "
                        "modelA+modelB[+...]; tune mode: the model to "
                        "auto-tune; transform mode: the model to "
                        "transform")
    p.add_argument("arg2", nargs="?", default=None,
                   help="spec mode: the model to dump / the spec JSON "
                        "file to load")
    p.add_argument("--check", action="store_true",
                   help="stats mode: validate the event stream against "
                        "the telemetry schema instead of rendering it "
                        "(exit 1 on any violation)")
    p.add_argument("--trace", default=None, metavar="RID",
                   help="stats mode: render the causal span tree of ONE "
                        "request (every span/event stamped trace=RID) "
                        "instead of the aggregate rollup")
    p.add_argument("--follow", action="store_true",
                   help="stats mode: live-tail a growing event stream, "
                        "rendering records as they land (stops at the "
                        "stream's end record or Ctrl-C)")
    p.add_argument("--telemetry", metavar="PATH", default=None,
                   help="write a structured telemetry event stream "
                        "(spans/counters/gauges as JSONL) to PATH; "
                        "equivalently set PLUSS_TELEMETRY.  Aggregate "
                        "with `pluss stats PATH`")
    p.add_argument("--all", action="store_true",
                   help="lint/analyze mode: analyze every registered model "
                        "family (at each builder's default size) instead "
                        "of --model/--n")
    p.add_argument("--json", action="store_true",
                   help="lint/analyze/predict mode: machine-readable "
                        "output")
    p.add_argument("--sarif", metavar="PATH", default=None,
                   help="lint/analyze/predict mode: additionally export "
                        "the PLxxx findings as a SARIF 2.1.0 log at PATH "
                        "(CI code-scanning annotations)")
    p.add_argument("--verify", action="store_true",
                   help="run the schedule-aware static analyzer before "
                        "the engine modes; ERROR diagnostics abort the "
                        "run")
    p.add_argument("--rates", default="0.05,0.1,0.25,0.5,1.0",
                   help="sample-mode sampling rates (comma list)")
    p.add_argument("--sample-mode", default="uniform",
                   choices=("uniform", "prefix"),
                   help="sample-mode estimator: uniform random windows with "
                        "warm-up context, or the prefix (warm-up-then-"
                        "measure) chain")
    p.add_argument("--context", type=int, default=None,
                   help="sample-mode warm-up context windows (default: "
                        "auto-sized to the largest share span)")
    p.add_argument("--sweep-threads", default="1,2,4,8",
                   help="sweep-mode thread counts (comma list)")
    p.add_argument("--sweep-chunks", default="1,4,16",
                   help="sweep-mode chunk sizes (comma list)")
    p.add_argument("--cache-lines", default="512,4096,40960",
                   help="sweep-mode cache sizes (lines) for the table")
    p.add_argument("--file", help="trace-mode input file of raw addresses")
    p.add_argument("--fmt", default="u64", choices=("u64", "text"),
                   help="trace file format (packed LE uint64 | text)")
    p.add_argument("--model", default="gemm", choices=sorted(REGISTRY))
    p.add_argument("--n", type=int, default=128, help="problem size")
    p.add_argument("--backends", default=None,
                   help="comma list of " + ",".join(BACKENDS)
                        + " (default: all three)")
    p.add_argument("--shard-dispatch", default=None,
                   choices=("auto", "steal", "static"),
                   help="shard backend / sharded trace replay: chunk "
                        "dispatch mode — steal (host-side work-stealing "
                        "over per-device chunk queues; single-process "
                        "default), static (one shard_map program; the "
                        "multi-process mode), or auto (PLUSS_SHARD_DISPATCH "
                        "env).  Bit-identical either way")
    p.add_argument("--device-groups", type=int, default=None,
                   help="sweep mode: split the local devices into this "
                        "many groups and run one sweep point per group "
                        "concurrently (journaled elastic recovery requeues "
                        "a point whose worker dies); default serial")
    p.add_argument("--threads", type=int, default=4, help="simulated threads")
    p.add_argument("--chunk", type=int, default=4, help="schedule chunk size")
    p.add_argument("--cache-kb", type=int, default=None, metavar="KB",
                   help="analyze/cotenancy/tune mode: largest-cache "
                        "capacity in KB — the verdict/tuning point AND "
                        "the hierarchy read-off LLC, parsed through one "
                        "shared geometry helper so the modes can't drift "
                        "(default: the SamplerConfig cache_kb)")
    p.add_argument("--cache-levels", default=None, metavar="KB:KB:...",
                   help="analyze/cotenancy/tune mode: declared cache "
                        "hierarchy levels in KB, ascending (e.g. "
                        "32:512:8192) — overrides PLUSS_CACHE_LEVELS; "
                        "the last level is the verdict/tuning LLC.  "
                        "Mutually exclusive with --cache-kb")
    p.add_argument("--assoc", type=int, default=None, metavar="WAYS",
                   help="analyze/cotenancy/tune mode: ways per set for "
                        "the hierarchy model (0 = fully associative; "
                        "overrides PLUSS_CACHE_ASSOC)")
    p.add_argument("--reps", type=int, default=3, help="speed-mode repetitions")
    p.add_argument("--share-cap", type=int, default=SHARE_CAP)
    p.add_argument("--window", type=int, default=None,
                   help="scan-window size override (accesses per window)")
    p.add_argument("--batch-windows", type=int, default=None,
                   help="trace mode: windows per device batch (default "
                        "from PLUSS_BATCH_WINDOWS or 16) — one segmented "
                        "sort-kernel dispatch covers the whole batch, so "
                        "bigger batches amortize dispatch cost; part of "
                        "the checkpoint identity")
    p.add_argument("--feed-workers", type=int, default=None,
                   help="trace mode: reader/packer worker threads feeding "
                        "the replay pipeline (default PLUSS_FEED_WORKERS "
                        "or backend-aware: 1 on CPU, most host cores on "
                        "accelerators); must be >= 1")
    p.add_argument("--wire", default=None,
                   choices=("auto", "pack", "d24v"),
                   help="trace mode: h2d wire encoding — pack (fixed-"
                        "width u16/u24/i32), d24v (delta+zigzag+nibble "
                        "bit-pack, decoded on device), or auto (default; "
                        "PLUSS_WIRE env, else d24v on accelerators / "
                        "pack on CPU).  Histogram-invariant; part of the "
                        "checkpoint identity")
    p.add_argument("--resident-cache", default=None,
                   action=argparse.BooleanOptionalAction,
                   help="trace mode: keep the staged trace resident in "
                        "device memory (the r13 HBM residency store) so "
                        "repeat replays skip host staging entirely; "
                        "--no-resident-cache forces the plain streamed "
                        "path.  Default: off for one-shot CLI replays "
                        "(the daemon enables it per request)")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="serve mode: unix socket path to listen on")
    p.add_argument("--port", type=int, default=None,
                   help="serve mode: TCP port to listen on (0 = ephemeral; "
                        "bound address printed on stderr)")
    p.add_argument("--host", default="127.0.0.1",
                   help="serve mode: TCP bind host (default 127.0.0.1)")
    p.add_argument("--max-queue", type=int, default=128,
                   help="serve mode: admission bound — requests past this "
                        "queue depth are SHED with a typed Overloaded "
                        "error instead of queued")
    p.add_argument("--max-batch", type=int, default=16,
                   help="serve mode: most requests one shared dispatch "
                        "may coalesce (1 disables batching)")
    p.add_argument("--max-delay-ms", type=float, default=10.0,
                   help="serve mode: adaptive batch window — the longest "
                        "a request waits for compatible stragglers before "
                        "dispatching as-is")
    p.add_argument("--default-deadline-ms", type=float, default=None,
                   help="serve mode: default per-request deadline for "
                        "requests that do not carry deadline_ms")
    p.add_argument("--heartbeat-dir", default=None, metavar="DIR",
                   help="serve mode: multihost heartbeat directory to "
                        "export heartbeat_age_s gauges from on the "
                        "prometheus refresh timer")
    p.add_argument("--num-processes", type=int, default=None,
                   help="serve mode: worker count watched under "
                        "--heartbeat-dir")
    p.add_argument("--prom-refresh-s", type=float, default=5.0,
                   help="serve mode: SLO gauge + prometheus textfile "
                        "(PLUSS_PROM) refresh period")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="serve mode: expose a live prometheus pull "
                        "endpoint (GET /metrics) on this localhost port "
                        "(0 = ephemeral; resolved port printed on stderr)")
    p.add_argument("--flight-dir", default=None, metavar="DIR",
                   help="serve mode: directory for crash flight-recorder "
                        "dumps (flight-<id>.jsonl on watchdog abandon, "
                        "breaker open, forced drain, or dispatch crash; "
                        "also PLUSS_FLIGHT_DIR; default cwd)")
    p.add_argument("--warm", default=None, metavar="MODELS",
                   help="serve mode: background-precompile these models at "
                        "daemon start (comma-separated "
                        "name[:n[:threads[:chunk]]] entries, or 'all' for "
                        "every registry model) so first requests dispatch "
                        "warm")
    p.add_argument("--journal-dir", default=None, metavar="DIR",
                   help="serve mode: crash-safe request journal directory "
                        "(also PLUSS_SERVE_JOURNAL) — accepted requests "
                        "are journaled before dispatch and marked done on "
                        "reply, so a restart replays what was lost")
    p.add_argument("--recover", default=None, metavar="DIR",
                   help="serve mode: recover from the request journal in "
                        "DIR at startup (implies --journal-dir DIR): "
                        "still-open entries replay through normal "
                        "admission and their answers park for "
                        '{"op": "result", "id": rid} collection')
    p.add_argument("--drain-timeout-s", type=float, default=60.0,
                   help="serve mode: HARD bound on shutdown drain — past "
                        "it, still-pending requests are answered typed "
                        "retryable and the daemon exits 0 (a supervisor "
                        "restart with --recover serves them)")
    p.add_argument("--xla-cache", default=None, metavar="DIR",
                   help="arm JAX's persistent compilation cache in DIR "
                        "(default $PLUSS_XLA_CACHE_DIR when set): compiled "
                        "HLO survives process death, on top of the plan "
                        "cache's AOT executable sidecars")
    p.add_argument("--run", action="store_true",
                   help="import / spec-load mode: after the analyzer "
                        "gate, run the derived spec through the engine "
                        "and print the acc-style block (timing banner + "
                        "histogram dumps)")
    p.add_argument("--check-model", default=None, metavar="MODEL",
                   help="import mode: also run the registry MODEL (at "
                        "--n) and require histogram + MRC byte-identical "
                        "to the imported spec's run — the frontend "
                        "bit-identity gate (exit 1 on divergence)")
    p.add_argument("--predict", action="store_true",
                   help="import mode: run the sampling-free static MRC "
                        "predictor (pluss/analysis/ri.py) on each "
                        "imported spec — no device work")
    p.add_argument("--register", action="store_true",
                   help="import mode: write each derived spec as codec "
                        "JSON into --registry-dir; set PLUSS_SPEC_DIR to "
                        "that directory and every pluss entry point "
                        "(CLI --model, serve requests) sees them as "
                        "registry models")
    p.add_argument("--registry-dir", default=".pluss_registry",
                   metavar="DIR",
                   help="import --register target directory (default "
                        ".pluss_registry)")
    p.add_argument("--interchange", metavar="A,B", default=None,
                   help="transform mode: interchange band levels A and "
                        "B of nest 0 (legality proven from the "
                        "dependence vectors first; e.g. 0,2)")
    p.add_argument("--tile", metavar="L:S,...", default=None,
                   help="transform mode: tile loop level L with size S "
                        "(a comma list tiles a contiguous band; each "
                        "size must divide its trip; e.g. 0:8,1:8,2:8)")
    p.add_argument("--fuse", metavar="A+B", default=None,
                   help="transform mode: fuse adjacent top-level nests "
                        "A and B (e.g. 0+1)")
    p.add_argument("--transforms", action="store_true",
                   help="tune mode: extend the schedule search over the "
                        "legal transform space (interchanges, "
                        "hierarchy-laddered tilings, fusions) and "
                        "report the best transformed schedule with its "
                        "static MRC delta vs the untransformed winner")
    p.add_argument("--force", action="store_true",
                   help="autotune mode: recalibrate even when a valid "
                        "geometry sidecar is already persisted for this "
                        "runtime")
    p.add_argument("--dry-run", action="store_true",
                   help="autotune mode: validate the persisted sidecar "
                        "and print the tuned geometry WITHOUT running "
                        "any calibration (exit 1 only when a sidecar "
                        "exists but fails validation)")
    p.add_argument("--refs", type=int, default=None,
                   help="autotune mode: calibration replay length in "
                        "references (default 2^20); smaller is faster "
                        "but noisier")
    p.add_argument("--start-point", type=int, default=None,
                   help="resume sampling from this parallel-loop iteration "
                        "value (the reference's setStartPoint capability)")
    p.add_argument("--out", default="mrc.csv", help="mrc-mode output file")
    p.add_argument("--resume", action="store_true",
                   help="sweep mode: journal every finished point and skip "
                        "points already journaled (interrupted sweeps "
                        "recompute zero finished points); trace mode: "
                        "checkpoint the replay every few batches and "
                        "continue from an existing checkpoint")
    p.add_argument("--journal", default=None,
                   help="sweep journal / trace checkpoint path override "
                        "(defaults derive from the model or trace file)")
    p.add_argument("--cpu", action="store_true",
                   help="force the host CPU backend (8 virtual devices)")
    p.add_argument("--profile", metavar="DIR",
                   help="write a jax profiler trace of the timed region to "
                        "DIR (view with tensorboard or xprof)")
    args = p.parse_args(argv)

    if args.target is not None and args.mode not in ("stats", "import",
                                                     "spec", "predict",
                                                     "cotenancy", "tune",
                                                     "transform"):
        # the optional positionals exist only for `stats <events.jsonl>`,
        # `import <file>`, `spec <dump|load> <what>`, `predict <model>`,
        # `cotenancy <a+b>`, `tune <model>`, and `transform <model>`;
        # anywhere else a stray argument must stay the usage error it
        # always was (`pluss lint gemm` would otherwise silently lint
        # the DEFAULT model and report it clean)
        p.error(f"unexpected argument {args.target!r} for mode "
                f"{args.mode!r} (positional input is for stats/import/"
                "spec/predict/cotenancy/tune/transform modes only; use "
                "--model/--file)")
    if args.arg2 is not None and args.mode != "spec":
        p.error(f"unexpected argument {args.arg2!r} for mode "
                f"{args.mode!r}")

    if args.mode == "stats":
        # pure host aggregation of a recorded stream: no accelerator, no
        # platform setup, and no telemetry session of its own
        from pluss.obs import stats as stats_mod

        if not args.target:
            p.error("stats mode requires an events.jsonl path")
        if args.check and (args.trace or args.follow):
            p.error("stats --check excludes --trace/--follow")
        return stats_mod.main(args.target, sys.stdout, sys.stderr,
                              check=args.check, trace=args.trace,
                              follow_stream=args.follow)

    from pluss import obs

    if args.telemetry:
        obs.configure(args.telemetry)

    if args.mode in ("lint", "analyze"):
        # pure host analysis: no accelerator probe, no platform setup —
        # a broken spec must be reportable from any box, instantly.
        # analyze adds the schedule-aware passes under the CLI's own
        # (--threads, --chunk) schedule and the shared cache geometry
        cfg = hier = None
        if args.mode == "analyze":
            llc_kb, hier = _cache_geometry_or_usage(args, p)
            cfg = SamplerConfig(thread_num=args.threads,
                                chunk_size=args.chunk,
                                **({} if llc_kb is None
                                   else {"cache_kb": llc_kb}))
        return _lint_main(args, sys.stdout, cfg, hier)

    def setup_platform() -> None:
        from pluss import plancache

        # arm before any compile: --xla-cache, else $PLUSS_XLA_CACHE_DIR
        plancache.arm_xla_cache(args.xla_cache)
        if args.cpu:
            from pluss.utils.platform import force_cpu

            force_cpu(8)
            return
        # a wedged TPU tunnel hangs any jax op forever; probe killably and
        # degrade to the CPU backend instead of hanging the driver.  Skip
        # when the process is already pinned to CPU (tests, prior force_cpu).
        import jax

        from pluss.utils.platform import force_cpu, probe_accelerator

        if jax.config.jax_platforms != "cpu" and probe_accelerator() is None:
            print("pluss: no usable accelerator, falling back to CPU",
                  file=sys.stderr)
            force_cpu(8)

    if args.mode == "import":
        # the authoring frontend (pluss/frontend): derive analyzer-
        # verified specs from DSL or pragma-C source.  Device-free unless
        # --run/--check-model asks for an engine run.
        return _import_main(args, p, sys.stdout, setup_platform)

    if args.mode == "spec":
        # shared-codec verbs: `spec dump <model>` / `spec load <file.json>`
        return _spec_main(args, p, sys.stdout, setup_platform)

    if args.mode == "predict":
        # sampling-free static MRC: the whole path is host arithmetic, so
        # no platform setup — --check alone boots a device for the
        # engine cross-run
        return _predict_main(args, p, sys.stdout, setup_platform)

    if args.mode == "cotenancy":
        # cross-nest co-tenancy interference (pluss/analysis/
        # interference.py): pure host math end to end — even --check,
        # whose oracle is a numpy schedule simulation, never boots a
        # device
        return _cotenancy_main(args, p, sys.stdout)

    if args.mode == "tune":
        # proof-carrying schedule auto-optimizer (pluss/analysis/
        # tune.py): the search is host math with zero dispatches —
        # --check alone boots a device for the winner's engine cross-run
        return _tune_main(args, p, sys.stdout, setup_platform)

    if args.mode == "transform":
        # loop-transformation legality prover + spec-to-spec transformer
        # (pluss/analysis/transform.py): host math end to end — --check
        # alone boots a device to run the TRANSFORMED spec once
        return _transform_main(args, p, sys.stdout, setup_platform)

    if args.mode == "autotune":
        # persisted batch-geometry calibration (pluss/autotune.py):
        # --dry-run only validates the sidecar (no device); a real
        # calibration times short replays on the live backend
        return _autotune_main(args, p, sys.stdout, setup_platform)

    setup_platform()

    if args.mode == "serve":
        # the long-lived multi-tenant prediction daemon (pluss/serve):
        # JSONL requests over a unix socket or localhost TCP, shared-
        # dispatch batching, per-request resilience, SLO telemetry
        from pluss.serve import ServeConfig, Server

        if (args.socket is None) == (args.port is None):
            p.error("serve mode requires exactly one of --socket/--port")
        scfg = ServeConfig(
            max_queue=args.max_queue,
            max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms,
            default_deadline_ms=args.default_deadline_ms,
            prom_refresh_s=args.prom_refresh_s,
            heartbeat_dir=args.heartbeat_dir,
            num_processes=args.num_processes,
            warm=args.warm,
            journal_dir=args.recover or args.journal_dir,
            drain_timeout_s=args.drain_timeout_s,
            metrics_port=args.metrics_port,
            flight_dir=args.flight_dir,
        )
        server = Server(socket_path=args.socket, port=args.port,
                        host=args.host, config=scfg)
        try:
            server.start()
        except OSError as e:
            print(f"pluss serve: cannot bind {args.socket or args.port}: "
                  f"{e}", file=sys.stderr)
            return 2
        print(f"pluss serve: listening on {server.address} "
              f"(max_queue={scfg.max_queue}, max_batch={scfg.max_batch}, "
              f"max_delay_ms={scfg.max_delay_ms:g}); SIGTERM or a "
              '{"op": "shutdown"} line drains and stops', file=sys.stderr,
              flush=True)
        if server.metrics_port is not None:
            print(f"pluss serve: metrics on "
                  f"http://127.0.0.1:{server.metrics_port}/metrics",
                  file=sys.stderr, flush=True)
        server.serve_forever()
        print("pluss serve: drained and stopped", file=sys.stderr)
        obs.flush_metrics()
        return 0

    spec = REGISTRY[args.model](args.n)
    cfg = SamplerConfig(thread_num=args.threads, chunk_size=args.chunk)
    if args.verify:
        n_err = _verify_spec(spec, cfg, sys.stderr)
        if n_err:
            print(f"pluss: --verify found {n_err} error(s) in "
                  f"{spec.name}; refusing to run", file=sys.stderr)
            return 2
    backends_explicit = args.backends is not None
    backends = [b.strip()
                for b in (args.backends or "vmap,shard,seq").split(",")
                if b.strip()]
    for b in backends:
        if b not in BACKENDS:
            p.error(f"unknown backend {b!r}")

    out = sys.stdout
    if args.mode == "acc":
        for b in backends:
            step = _sampler_of(b, spec, cfg, args.share_cap,
                               args.window, args.start_point,
                               args.shard_dispatch)
            step()  # warmup: exclude compilation from the timed region
            dt, res, ri = _timed(step, args.profile)
            acc_block(banner_of(b), dt, res.noshare_list(), res.share_list(),
                      ri, res.max_iteration_count, out)
    elif args.mode == "speed":
        for b in backends:
            step = _sampler_of(b, spec, cfg, args.share_cap,
                               args.window, args.start_point,
                               args.shard_dispatch)
            step()  # warmup once per backend
            times = [_timed(step)[0] for _ in range(args.reps)]
            speed_block(banner_of(b), times, out)
    elif args.mode == "mrc":
        step = _sampler_of(backends[0], spec, cfg, args.share_cap,
                           args.window, args.start_point,
                           args.shard_dispatch)
        _, res, ri = _timed(step, args.profile)
        curve = mrc.aet_mrc(ri, cfg)
        mrc.write_mrc(args.out, curve)
        out.write(f"wrote {len(mrc.dedup_lines(curve))} MRC lines to "
                  f"{args.out} (curve over {len(curve)} cache sizes)\n")
    elif args.mode == "sample":
        # the reference's dormant true-sampling surface, live: estimate the
        # MRC from a fraction of windows and report the error budget
        # (--window is the K-chunk span knob, pluss/sampling.py)
        from pluss import sampling

        rates = [float(x) for x in args.rates.split(",") if x]
        if args.sample_mode == "prefix" and args.context is not None:
            print("pluss: --context is ignored in prefix mode (the chain "
                  "is its own context)", file=sys.stderr)
        tbl = sampling.mrc_error_table(spec, cfg, rates,
                                       share_cap=args.share_cap,
                                       window_accesses=args.window,
                                       context_windows=args.context,
                                       mode=args.sample_mode)
        out.write(f"{spec.name}: sampled-MRC L2 error vs full enumeration\n")
        out.write("rate,walked_fraction,l2_error\n")
        for rate, frac, err in tbl:
            out.write(f"{rate:g},{frac:.6g},{err:.6g}\n")
    elif args.mode == "sweep":
        # the tool's raison d'etre: predicted MRCs across parallel schedules
        # (the reference rebuilds per -DTHREAD_NUM/-DCHUNK_SIZE combination)
        from pluss import sweep as sweep_mod

        ts = [int(x) for x in args.sweep_threads.split(",") if x]
        cks = [int(x) for x in args.sweep_chunks.split(",") if x]
        cls_ = [int(x) for x in args.cache_lines.split(",") if x]
        journal = args.journal
        if journal is None and args.resume:
            journal = f".pluss_sweep_{args.model}_{args.n}.jsonl"
        if args.resume:
            print(f"pluss: sweep journal at {journal} (resume on)",
                  file=sys.stderr)
        if args.device_groups is not None and args.device_groups > 1:
            print(f"pluss: sweep across {args.device_groups} device "
                  "group(s), one point per group (elastic requeue on "
                  "worker death)", file=sys.stderr)
        pts = sweep_mod.sweep(spec, ts, cks, cfg, args.share_cap,
                              journal=journal, resume=args.resume,
                              device_groups=args.device_groups)
        out.write(f"{spec.name}: predicted miss ratios\n")
        out.write(sweep_mod.table(pts, cls_) + "\n")
        # one report surface for the static analyzer's carried-level
        # classifications (PL303) and the resilience stamps in the table
        levels = sweep_mod.carried_levels(spec)
        if levels:
            out.write(levels + "\n")
        # footprint + per-schedule false-sharing stamps (the analyzer's
        # schedule-aware passes, sharing one profiled analysis)
        sched_block = sweep_mod.schedule_analysis(spec, pts)
        if sched_block:
            out.write(sched_block + "\n")
        # static prediction per schedule point: derivability + exact
        # plateau vs the heuristic bracket (pluss/analysis/ri.py)
        pred_block = sweep_mod.prediction_block(spec, pts)
        if pred_block:
            out.write(pred_block + "\n")
        # multi-level AET read-offs per schedule point (pluss/model/
        # hierarchy.py: PLUSS_CACHE_LEVELS / _ASSOC / _POLICY)
        hier_block = sweep_mod.hierarchy_block(spec, pts)
        if hier_block:
            out.write(hier_block + "\n")
        # proof-carrying tune over the same swept axes: each sampled
        # point's miss ratio at the tuning LLC vs the proven-best
        # schedule's predicted score (pluss/analysis/tune.py)
        tuned = sweep_mod.tuned_block(spec, pts)
        if tuned:
            out.write(tuned + "\n")
        # transform-space search over the same axes: the best proven-
        # legal (transform, schedule) pair and its static MRC delta
        # (pluss/analysis/transform.py)
        trans = sweep_mod.transform_block(spec, pts)
        if trans:
            out.write(trans + "\n")
    else:  # trace: dynamic replay (BASELINE config 5; bypasses CRI like the
        # reference's pluss_access path — see pluss/trace.py)
        if not args.file:
            p.error("trace mode requires --file")
        from pluss import trace as trace_mod
        from pluss.io import print_histogram

        # u64 files stream from disk in bounded memory (64 MB batches);
        # text files are small by nature and go through the in-memory path.
        # --backends shard (EXPLICIT, alone): device-sharded replay (segment
        # scans + tail exchange over the mesh) — the scale-out variant; it
        # holds the whole trace in host memory, so the default backend list
        # (which merely contains "shard") must not select it
        t0 = time.perf_counter()
        win = args.window or trace_mod.TRACE_WINDOW
        # None defers to the module defaults (PLUSS_BATCH_WINDOWS /
        # PLUSS_FEED_WORKERS / PLUSS_WIRE envs); explicit values —
        # including invalid ones — pass through so the trace layer's
        # validation rejects them loudly
        bw_kw = {"batch_windows": args.batch_windows} \
            if args.batch_windows is not None else {}
        feed_kw = {}
        if args.feed_workers is not None:
            feed_kw["feed_workers"] = args.feed_workers
        if args.wire is not None:
            feed_kw["wire"] = args.wire
        res_kw = {"resident_cache": args.resident_cache} \
            if args.resident_cache is not None else {}
        if backends_explicit and backends != ["shard"]:
            # an explicit backend choice other than exactly 'shard' is
            # silently a no-op here — say so (mirrors the --window notice)
            print(
                f"pluss: trace mode ignores --backends {','.join(backends)}; "
                "it streams on one device unless --backends is exactly "
                "'shard' (device-sharded replay)",
                file=sys.stderr,
            )
        if backends == ["shard"]:
            import jax as _jax

            if feed_kw:
                # the sharded replay has its own per-device slice feed;
                # the parallel pool + wire knobs only steer the
                # single-device streamed pipeline (mirrors --window)
                print("pluss: --feed-workers/--wire have no effect on "
                      "the sharded replay", file=sys.stderr)

            if args.fmt == "u64" and _jax.process_count() > 1:
                # multi-process: shard_replay_file's single-host compactor
                # would diverge across processes (it raises) — keep the
                # collectives-only in-memory path, which stays correct
                # (every process compacts the full trace identically)
                if args.resume or args.journal:
                    print("pluss: --resume/--journal have no effect on "
                          "multi-process sharded replay", file=sys.stderr)
                if args.batch_windows is not None:
                    print("pluss: --batch-windows has no effect on the "
                          "in-memory sharded replay", file=sys.stderr)
                rep = trace_mod.shard_replay(
                    trace_mod.load_trace(args.file, args.fmt),
                    cls=cfg.cls, window=win)
            elif args.fmt == "u64":
                # disk-streamed sharded replay: bounded host memory, and
                # --journal/--resume arm the sharded checkpoint (PR-2
                # follow-up: the journal substrate now covers this path)
                ckpt = None
                if args.resume or args.journal:
                    ckpt = args.journal or (args.file + ".shard.ckpt")
                    print(f"pluss: shard-trace checkpoint at {ckpt} "
                          f"(resume {'on' if args.resume else 'off'})",
                          file=sys.stderr)
                rep = trace_mod.shard_replay_file(
                    args.file, cls=cfg.cls, window=win,
                    checkpoint_path=ckpt, resume=args.resume,
                    dispatch=args.shard_dispatch, **bw_kw, **res_kw)
            else:
                if args.resume or args.journal:
                    print("pluss: --resume/--journal have no effect on "
                          f"sharded {args.fmt} traces (checkpointing is "
                          "u64-only)", file=sys.stderr)
                if args.batch_windows is not None:
                    print("pluss: --batch-windows has no effect on the "
                          "in-memory sharded replay", file=sys.stderr)
                rep = trace_mod.shard_replay(
                    trace_mod.load_trace(args.file, args.fmt),
                    cls=cfg.cls, window=win)
        else:
            from pluss.resilience import replay_file_resilient

            # --journal alone arms checkpoint WRITING (crash insurance on
            # a first long run); --resume additionally loads an existing
            # checkpoint — same semantics split as the sweep mode
            ckpt = None
            if args.resume or args.journal:
                ckpt = args.journal or (args.file + ".ckpt.npz")
                print(f"pluss: trace checkpoint at {ckpt} "
                      f"(resume {'on' if args.resume else 'off'})",
                      file=sys.stderr)
            rep = replay_file_resilient(args.file, args.fmt, cls=cfg.cls,
                                        window=win, checkpoint_path=ckpt,
                                        resume=args.resume, **bw_kw,
                                        **feed_kw, **res_kw)
        dt = time.perf_counter() - t0
        if getattr(rep, "degradations", ()):
            # stderr: the stdout block format is diffed byte-for-byte
            print("pluss: trace replay degraded: "
                  + ",".join(rep.degradations), file=sys.stderr)
        out.write(f"TPU TRACE: {dt:0.6f}\n")
        print_histogram("Start to dump reuse time", rep.histogram(), out)
        curve = mrc.aet_mrc(rep.histogram(), cfg)
        mrc.write_mrc(args.out, curve)
        out.write(f"{rep.total_count} refs over {rep.n_lines} lines; "
                  f"wrote MRC to {args.out}\n")
    # counters land in the stream even when the process is long-lived
    # (the session itself closes at exit, or at the next configure)
    obs.flush_metrics()
    return 0


if __name__ == "__main__":
    sys.exit(main())
