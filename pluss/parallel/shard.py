"""Device-sharded sampling: round windows over a mesh, histograms over ICI.

The reference's only cross-worker interaction is a post-hoc sum of per-thread
histograms (``/root/reference/src/utils.rs:154-176,310-322``; its "backends"
are OpenMP / Rayon / std::thread fan-outs of the same walk, SURVEY.md §2).
Here the scalable axis is different and strictly stronger: the **stream**
dimension is sharded.  Each simulated thread's access stream is cut into one
round-window per device (the same windows the single-device engine scans);
every device sorts its window locally, and the only cross-device state is a
dense per-line boundary exchange:

- each device emits ``tail_pos[line]`` (last local position) per segment;
- an ``all_gather`` + masked max over earlier segments yields each segment's
  ``prev_last[line]`` — the carried LAT table the scan path threads serially;
- window heads resolve against it (reuse, share, or cold);
- histograms merge with ``psum`` over ICI, exactly the reference's
  all-reduce-by-summation (SURVEY.md §2 "communication backend").

Segments are ordered ``(nest, device)``: all devices' windows of nest 0
precede nest 1's, matching the global clock.  This is the moral equivalent of
ring/blockwise sequence parallelism for long streams — small carried state,
local heavy compute, one boundary collective — and it runs unchanged on a
multi-host mesh (DCN collectives) because only ``all_gather``/``psum`` are
used.  No point-to-point communication is ever needed (SURVEY.md §5).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from pluss.config import DEFAULT, NBINS, SHARE_CAP, SamplerConfig
from pluss.engine import (
    SamplerResult,
    StreamPlan,
    merge_share_windows,
    plan,
    window_stream,
)
from pluss.ops.reuse import (
    bin_histogram,
    boundary_arrays,
    event_histogram,
    log2_bin,
    share_mask,
    share_unique,
    window_events,
)
from pluss.spec import LoopNestSpec


def default_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` (default: all) local devices."""
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        raise ValueError(f"requested {n} devices, only {len(devs)} visible")
    return Mesh(np.asarray(devs[:n]), ("d",))


def _tpl_dense(tpl, tid, d, n_lines, pos_dtype, nb):
    """Template path: dense (head_pos, head_span, tail_pos) for window ``d``
    of thread ``tid`` — no stream materialization.

    The shift arithmetic mirrors the engine's ``ultra_step`` (units per chunk
    offset, positions per window); the dense arrays are built with one
    ``dynamic_update_slice`` per contiguous line run (scatter fallback for
    fragmented line sets).
    """
    pdt = jnp.dtype(pos_dtype)
    units = (d - tpl.w0) * tpl.unit_w + (tid - tpl.t0)
    dpos = jnp.asarray(tpl.pos_shift, pdt) * (d - tpl.w0).astype(pdt) + nb

    def dense(runs, lines, dlines, vals, fill):
        out = jnp.full((n_lines,), fill, vals.dtype)
        if runs is None:
            idx = jnp.asarray(lines) + jnp.asarray(dlines) * units
            return out.at[idx].set(vals, unique_indices=True)
        for ls, off, ln, dl in runs:
            out = jax.lax.dynamic_update_slice(
                out, vals[int(off):int(off) + int(ln)],
                (int(ls) + int(dl) * units,),
            )
        return out

    hpos = jnp.asarray(tpl.head_pos.astype(pos_dtype)) + dpos
    tpos = jnp.asarray(tpl.tail_pos.astype(pos_dtype)) + dpos
    head_pos = dense(tpl.head_runs, tpl.head_line, tpl.head_dline, hpos, -1)
    head_span = dense(tpl.head_runs, tpl.head_line, tpl.head_dline,
                      jnp.asarray(tpl.head_span), 0)
    tail_pos = dense(tpl.tail_runs, tpl.tail_line, tpl.tail_dline, tpos, -1)
    return head_pos, head_span, tail_pos


def _nest_results(np_, ni: int, tids, pl: StreamPlan, share_cap: int, d):
    """[T, ...] results of one nest's window on this device.

    Each device holds window ``d`` of the nest.  When that window is clean
    for every thread it takes the static-template path; otherwise it sorts.
    The choice is per DEVICE: under ``shard_map`` (unlike ``vmap``)
    ``lax.cond`` on the device index is a real branch, so ragged schedules
    (odd trips, partial last rounds) only pay the sort on the devices that
    own the unclean windows.  Static in-window share values of template
    windows are added host-side in :func:`shard_run` (uncapped, like
    ``engine.run``) — the template branch emits none.
    """
    cfg = pl.cfg
    bases = pl.spec.line_bases(cfg)
    n_lines = pl.spec.total_lines(cfg)
    pdt = jnp.dtype(pl.pos_dtype)
    nest_base = jnp.asarray(pl.nest_base.astype(pl.pos_dtype))

    def tpl_all(_):
        def one(t):
            tpl = np_.tpl
            hp, hs, tp = _tpl_dense(tpl, t, d, n_lines, pl.pos_dtype,
                                    nest_base[ni, t])
            hist0 = jnp.asarray(tpl.local_hist.astype(pl.pos_dtype))
            if np_.var_refs:
                # template-ineligible arrays sort inside the clean window
                # too (engine._split_ref_groups); their lines are disjoint
                # from the template's, so the dense boundary arrays merge
                # with a simple where
                key_s, pos_s, span_s, valid_i = window_stream(
                    np_, cfg, jnp.asarray(np_.owned)[t],
                    d * np_.window_rounds, nest_base[ni, t], bases,
                    pl.spec.array_index, pdt, refs=np_.var_refs,
                )
                ev, _ = window_events(key_s, pos_s, span_s, valid_i, None)
                sv, sc, snu = share_unique(ev, share_cap)
                vhp, vhs, vtp = boundary_arrays(key_s, pos_s, span_s, ev,
                                                n_lines)
                hist0 = hist0 + event_histogram(ev)
                vset = vhp >= 0
                hp = jnp.where(vset, vhp, hp)
                hs = jnp.where(vset, vhs, hs)
                tp = jnp.where(vtp >= 0, vtp, tp)
            else:
                sv = jnp.zeros((share_cap,), pdt)
                sc = jnp.zeros((share_cap,), jnp.int32)
                snu = jnp.int32(0)
            return (hist0, sv, sc, snu, hp, hs, tp)
        return jax.vmap(one)(tids)

    def sort_all(_):
        def one(t):
            key_s, pos_s, span_s, valid_i = window_stream(
                np_, cfg, jnp.asarray(np_.owned)[t],
                d * np_.window_rounds, nest_base[ni, t], bases,
                pl.spec.array_index, pdt,
            )
            ev, _ = window_events(key_s, pos_s, span_s, valid_i, None)
            sv, sc, snu = share_unique(ev, share_cap)
            hp, hs, tp = boundary_arrays(key_s, pos_s, span_s, ev, n_lines)
            return (event_histogram(ev), sv, sc, snu, hp, hs, tp)
        return jax.vmap(one)(tids)

    mask = np_.ultra_windows()            # [NW] bool, static
    if not mask.any():
        return sort_all(0)
    if mask.all():
        return tpl_all(0)                 # common case: no sort branch at all
    # branch outputs mix device-invariant constants (template) with
    # device-varying values (sort); unify the vma types for lax.cond
    def _vary_leaf(y):
        if "d" in getattr(jax.typeof(y), "vma", frozenset()):
            return y
        return jax.lax.pcast(y, ("d",), to="varying")

    vary = lambda f: lambda x: jax.tree.map(_vary_leaf, f(x))
    return jax.lax.cond(jnp.asarray(mask)[d], vary(tpl_all), vary(sort_all), 0)


def _shard_body(tids, pl: StreamPlan, share_cap: int, D: int):
    d = jax.lax.axis_index("d")
    N = len(pl.nests)
    per_nest = [
        _nest_results(np_, ni, tids, pl, share_cap, d)
        for ni, np_ in enumerate(pl.nests)
    ]
    (hist, sv, sc, snu, head_pos, head_span, tail_pos) = jax.tree.map(
        lambda *xs: jnp.stack(xs, axis=1), *per_nest
    )
    # tail exchange: [D, T, N, L] — the only cross-device state
    tails_all = jax.lax.all_gather(tail_pos, "d")
    ni_idx = jnp.arange(N)
    dev_idx = jnp.arange(D)
    prevs = []
    for ni in range(N):
        # segments (nj, e) strictly before (ni, d) in global clock order
        earlier = (ni_idx[None, :] < ni) | (
            (ni_idx[None, :] == ni) & (dev_idx[:, None] < d)
        )
        m = earlier[:, None, :, None]  # [D, 1, N, 1]
        prevs.append(jnp.max(jnp.where(m, tails_all, -1), axis=(0, 2)))
    prev = jnp.stack(prevs, axis=1)  # [T, N, L]

    has_head = head_pos >= 0
    head_evt = has_head & (prev >= 0)
    cold = has_head & (prev < 0)
    reuse = jnp.where(head_evt, head_pos - prev, 0)
    share = head_evt & share_mask(reuse, head_span)
    nevt = head_evt & ~share
    bins = jnp.where(nevt, log2_bin(reuse), 0)
    w = (cold | nevt).astype(hist.dtype)
    head_hist = jax.vmap(
        lambda bb, ww: bin_histogram(bb.ravel(), ww.ravel())
    )(bins, w)
    total = hist.sum(axis=1) + head_hist            # [T, NBINS]
    total = jax.lax.psum(total, "d")                # replicated merge over ICI
    head_share = jnp.where(share, reuse, -1)        # [T, N, L] raw values
    return total, sv[None], sc[None], snu[None], head_share[None]


@functools.lru_cache(maxsize=32)
def _compiled(spec: LoopNestSpec, cfg: SamplerConfig, share_cap: int,
              mesh: Mesh, assignment=None, start_point=None):
    D = mesh.devices.size
    pl = plan(spec, cfg, assignment, start_point, n_windows=D)
    f = jax.shard_map(
        lambda t: _shard_body(t, pl, share_cap, D),
        mesh=mesh,
        in_specs=P(),
        out_specs=(P(), P("d"), P("d"), P("d"), P("d")),
    )
    return pl, jax.jit(f)


def shard_run(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
              share_cap: int = SHARE_CAP,
              mesh: Mesh | None = None,
              assignment=None, start_point=None) -> SamplerResult:
    """Run the sampler with stream windows sharded over a device mesh.

    ``assignment``/``start_point``: dynamic chunk->thread maps and the
    setStartPoint resume rule, as in :func:`pluss.engine.run`.
    """
    mesh = mesh or default_mesh()
    if assignment is not None:
        assignment = tuple(
            tuple(a) if a is not None else None for a in assignment
        )
    if mesh.devices.size == 1:
        # a 1-device "mesh" would make the whole stream one window; the
        # windowed engine is the same computation with bounded memory
        from pluss import engine

        return engine.run(spec, cfg, share_cap, assignment=assignment,
                          start_point=start_point)
    pl, f = _compiled(spec, cfg, share_cap, mesh, assignment, start_point)
    tids = jnp.arange(cfg.thread_num, dtype=jnp.int32)
    hist, sv, sc, snu, head_share = f(tids)
    # [D, T, N, ...] -> [T, D, N, ...]: merge_share_windows flattens every
    # non-thread axis anyway, so one transpose covers all nests at once
    sv, sc, snu = np.asarray(sv), np.asarray(sc), np.asarray(snu)
    T = cfg.thread_num
    share_raw = merge_share_windows(
        [sv.transpose(1, 0, 2, 3)], [sc.transpose(1, 0, 2, 3)],
        [snu.transpose(1, 0, 2)], share_cap, T,
    )
    hv = np.asarray(head_share)
    for dev in range(hv.shape[0]):
        for t in range(T):
            for v in hv[dev, t][hv[dev, t] >= 0].tolist():
                share_raw[t][v] = share_raw[t].get(v, 0) + 1
    # static in-window share of template nests: one copy per (thread, ultra
    # window) — exactly the devices whose cond took the template branch
    # (same ultra_windows() mask as the branch selection, by construction)
    from pluss.engine import add_static_share

    add_static_share(share_raw,
                     [(n, int(n.ultra_windows().sum())) for n in pl.nests])
    return SamplerResult(
        noshare_dense=np.asarray(hist, np.int64),
        share_raw=share_raw,
        share_ratio=T - 1,
        max_iteration_count=pl.total_count,
    )
