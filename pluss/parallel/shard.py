"""Device-sharded sampling: round windows over a mesh, histograms over ICI.

The reference's only cross-worker interaction is a post-hoc sum of per-thread
histograms (``/root/reference/src/utils.rs:154-176,310-322``; its "backends"
are OpenMP / Rayon / std::thread fan-outs of the same walk, SURVEY.md §2).
Here the scalable axis is different and strictly stronger: the **stream**
dimension is sharded.  Each simulated thread's access stream is cut into one
round-window per device (the same windows the single-device engine scans);
every device sorts its window locally, and the only cross-device state is a
dense per-line boundary exchange:

- each device emits ``tail_pos[line]`` (last local position) per segment;
- an ``all_gather`` + masked max over earlier segments yields each segment's
  ``prev_last[line]`` — the carried LAT table the scan path threads serially;
- window heads resolve against it (reuse, share, or cold);
- histograms merge with ``psum`` over ICI, exactly the reference's
  all-reduce-by-summation (SURVEY.md §2 "communication backend").

Segments are ordered ``(nest, device)``: all devices' windows of nest 0
precede nest 1's, matching the global clock.  This is the moral equivalent of
ring/blockwise sequence parallelism for long streams — small carried state,
local heavy compute, one boundary collective — and it runs unchanged on a
multi-host mesh (DCN collectives) because only ``all_gather``/``psum`` are
used.  No point-to-point communication is ever needed (SURVEY.md §5).

Two DISPATCH modes drive the same window bodies (``PLUSS_SHARD_DISPATCH``):

- ``static`` — the original single ``shard_map`` program: device ``d`` owns
  windows ``d*S .. d*S+S-1``, heads settle in one collective exchange.  The
  only mode available under multi-process execution (it is collectives-only,
  so it rides DCN).
- ``steal`` (default on a single process) — a host-side work-stealing chunk
  dispatcher (:mod:`pluss.parallel.steal`): windows split into ~4 chunks per
  device, each chunk one per-device executable producing its own
  (histogram, heads, tails, share-uniques); an idle device steals the tail
  half of the fullest victim's deque, and the host merges chunk boundaries
  with a running prefix-max in canonical stream order.  Because the merge
  order is canonical, steal-order permutations are bit-identical by
  construction — stragglers (quad nests' late windows) stop gating the mesh
  without costing determinism.

Both modes run the windows through the PR-4 segmented sort kernel
(:func:`pluss.ops.reuse.batch_events` — one sort, one carried gather, one
tail scatter per window) by default; ``PLUSS_SHARD_SEGMENTED=0`` /
``segmented=False`` keeps the legacy ghost-merged formulation for A/B,
pinned bit-identical by tests/test_steal.py.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from pluss import obs
from pluss.config import DEFAULT, NBINS, SHARE_CAP, SamplerConfig
from pluss.utils import compat
from pluss.engine import (
    SamplerResult,
    StreamPlan,
    _array_ranges,
    _auto_share_cap,
    _sort_window,
    _window_parts,
    ShareCapExceeded,
    add_static_share,
    merge_share_windows,
    natural_n_windows,
    shard_plan_cached,
)
from pluss.ops.reuse import (
    batch_events,
    bin_histogram,
    event_histogram,
    log2_bin,
    share_mask,
    share_unique,
)
from pluss.spec import LoopNestSpec


def default_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` (default: all) local devices."""
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        raise ValueError(f"requested {n} devices, only {len(devs)} visible")
    return Mesh(np.asarray(devs[:n]), ("d",))


def device_fingerprint(devices) -> tuple:
    """Stable identity of a device set for cross-run cache keys (the r13
    residency store): a resident entry staged on one device set must
    never be served to a replay running on another — a mesh reshape, a
    force_cpu fallback, or a different device count each change the
    fingerprint, so the lookup just misses."""
    return tuple((d.platform, int(d.id)) for d in devices)


#: dispatch-mode selector (``dispatch=`` kwarg / ``PLUSS_SHARD_DISPATCH``
#: env / ``--shard-dispatch``): ``steal`` = host-side work-stealing chunk
#: dispatcher, ``static`` = the single shard_map program, ``auto`` = steal
#: on a single process, static under multi-process (the steal dispatcher
#: places chunks on ADDRESSABLE devices; cross-process placement needs the
#: collectives-only program)
DISPATCH_CHOICES = ("auto", "steal", "static")


def _resolve_dispatch(dispatch: str | None) -> str:
    """Validate a dispatch selector; ``auto`` stays ``auto`` (the caller
    finalizes it with :func:`_auto_steal`, which needs the run's size).
    Explicit bad values fail loudly; a malformed ``PLUSS_SHARD_DISPATCH``
    warns and falls back (envknob policy)."""
    if dispatch is None:
        from pluss.utils.envknob import env_choice

        dispatch = env_choice("PLUSS_SHARD_DISPATCH", "auto",
                              DISPATCH_CHOICES)
    if dispatch not in DISPATCH_CHOICES:
        raise ValueError(
            f"unknown shard dispatch {dispatch!r} (choices: "
            f"{', '.join(DISPATCH_CHOICES)})")
    if dispatch == "steal" and jax.process_count() > 1:
        raise RuntimeError(
            "dispatch='steal' places chunks on addressable devices only; "
            "multi-process meshes need dispatch='static' (or 'auto', "
            "which picks it)")
    return dispatch


def _auto_steal(total_refs: int) -> bool:
    """The ``auto`` policy: work-steal when the run is LONG enough for
    straggler imbalance to matter.  Stealing pays per-device executables
    (D small compiles instead of one SPMD program) and a host-side merge
    — pure overhead on a sub-second run, a wash-to-win on the multi-
    minute quad nests and 1e9-ref replays it exists for.  Threshold:
    ``PLUSS_SHARD_STEAL_MIN_REFS`` total accesses (default 2^23).
    Multi-process execution always takes the collectives-only static
    program (steal chunks are placed on addressable devices)."""
    if jax.process_count() > 1:
        return False
    from pluss.utils.envknob import env_int

    return total_refs >= env_int("PLUSS_SHARD_STEAL_MIN_REFS", 1 << 23,
                                 minimum=0)


def _shard_segmented_default() -> bool:
    """Segmented (batch_events) window kernel by default — one sort, one
    carried gather, one tail scatter per window instead of the ghost-merged
    two-sort formulation.  ``PLUSS_SHARD_SEGMENTED=0`` keeps the legacy
    path for A/B (bit-identical; tests pin it)."""
    env = os.environ.get("PLUSS_SHARD_SEGMENTED")
    if env is not None:
        return env.lower() not in ("0", "false", "off", "")
    return True


def _steal_seed(steal_seed: int | None) -> int:
    """Steal-schedule seed (``PLUSS_SHARD_STEAL_SEED``): permutes the
    chunk->device map and victim tie-breaks — NEVER the merged result
    (the determinism tests sweep it)."""
    if steal_seed is not None:
        return int(steal_seed)
    from pluss.utils.envknob import env_int

    return env_int("PLUSS_SHARD_STEAL_SEED", 0, minimum=0)


def _batch_window(np_, refs, cfg, owned_row, w, nb, bases, array_index, pdt,
                  last_pos, clock_row=None):
    """One window over ``refs`` through the PR-4 segmented kernel.

    The enumerated window parts feed :func:`pluss.ops.reuse.batch_events`
    directly: one (line, pos) sort, heads resolved by ONE gather against
    the dense carried table, tails written by ONE permutation scatter —
    no ghost entries in the sort and no second compaction sort
    (``extract_tails``).  Bit-identical to the ghost-merged
    :func:`pluss.engine._sort_window` because reuse gaps are pairwise
    same-line quantities, invariant under how the carry is resolved.
    Returns ``(new_last_pos, ev)`` with the sorted arrays riding in
    ``ev["key"]/["pos"]/["span"]`` for the device-head capture.
    """
    r0 = w * np_.window_rounds
    parts = _window_parts(np_, refs, cfg, owned_row, r0, nb, bases,
                          array_index, pdt, clock_row)
    ev, last_pos = batch_events(
        jnp.concatenate([p[0] for p in parts]),
        jnp.concatenate([p[1] for p in parts]),
        jnp.concatenate([p[3] for p in parts]),
        last_pos,
        span=jnp.concatenate([p[2] for p in parts]),
        pos_sorted=False,
    )
    return last_pos, ev


def _tpl_dense(tpl, tid, d, n_lines, pos_dtype, nb):
    """Template path: dense (head_pos, head_span, tail_pos) for window ``d``
    of thread ``tid`` — no stream materialization.

    The shift arithmetic mirrors the engine's ``ultra_step`` (units per chunk
    offset, positions per window); the dense arrays are built with one
    ``dynamic_update_slice`` per contiguous line run (scatter fallback for
    fragmented line sets).
    """
    pdt = jnp.dtype(pos_dtype)
    units = (d - tpl.w0) * tpl.unit_w + (tid - tpl.t0)
    dpos = jnp.asarray(tpl.pos_shift, pdt) * (d - tpl.w0).astype(pdt) + nb

    def dense(runs, lines, dlines, vals, fill):
        out = jnp.full((n_lines,), fill, vals.dtype)
        if runs is None:
            idx = jnp.asarray(lines) + jnp.asarray(dlines) * units
            return out.at[idx].set(vals, unique_indices=True)
        for ls, off, ln, dl in runs:
            out = jax.lax.dynamic_update_slice(
                out, vals[int(off):int(off) + int(ln)],
                (int(ls) + int(dl) * units,),
            )
        return out

    hpos = jnp.asarray(tpl.head_pos.astype(pos_dtype)) + dpos
    tpos = jnp.asarray(tpl.tail_pos.astype(pos_dtype)) + dpos
    head_pos = dense(tpl.head_runs, tpl.head_line, tpl.head_dline, hpos, -1)
    head_span = dense(tpl.head_runs, tpl.head_line, tpl.head_dline,
                      jnp.asarray(tpl.head_span), 0)
    tail_pos = dense(tpl.tail_runs, tpl.tail_line, tpl.tail_dline, tpos, -1)
    return head_pos, head_span, tail_pos


#: vma marking for shard_map unification (template constants are
#: device-invariant; sorted-stream values are varying) — identity on jax
#: versions without the vma system (pluss.utils.compat)
_vary = compat.vary


def _capture_heads(head_pos, head_span, cold, key_s, pos_s, span_s,
                   n_lines: int):
    """Record first-in-device touches from one sorted sub-window.

    A line's device-local cold happens at most once across the device's
    sub-windows (afterwards the carried table resolves it), so the update
    is a permutation: non-cold entries scatter into private dump slots past
    ``n_lines`` (the same trick as ops.reuse.window_events' tail update).
    ``head_span``/``span_s`` may be None (the trace path has no share
    classification).
    """
    w = key_s.shape[0]
    tgt = jnp.where(cold, key_s, n_lines + jnp.arange(w, dtype=key_s.dtype))
    ext_p = jnp.concatenate([head_pos, jnp.zeros((w,), head_pos.dtype)])
    head_pos = ext_p.at[tgt].set(pos_s, unique_indices=True)[:n_lines]
    if head_span is None:
        return head_pos, None
    ext_s = jnp.concatenate([head_span, jnp.zeros((w,), head_span.dtype)])
    head_span = ext_s.at[tgt].set(span_s, unique_indices=True)[:n_lines]
    return head_pos, head_span


def _nest_results(np_, ni: int, tids, pl: StreamPlan, share_cap: int,
                  w_ids, segmented: bool = True, vary=None):
    """[T, ...] results of one nest's ``w_ids`` windows on this executor.

    ``w_ids`` — a traced [S] int32 array of GLOBAL window indices — is
    scanned sequentially per thread, carrying ``(last_pos, hist, head_pos,
    head_span)`` — the engine's windowed scan nested inside the shard, so
    per-executor sort memory is bounded by the engine's window target no
    matter how large the workload (round-1 verdict weak #3).  The static
    shard_map path passes device ``d``'s contiguous ``d*S .. d*S+S-1``;
    the work-stealing dispatcher passes one chunk's window range — both
    produce the same boundary contract: a window access with no in-scope
    predecessor is captured as a HEAD (not a cold miss) for the
    cross-scope exchange, and the final carry IS the scope's tail table.

    Each window takes the static-template path when clean for every
    thread, the sort path otherwise (``lax.cond`` per window: the window
    id is a real traced value, so ragged schedules only pay the sort
    where they are ragged).  ``segmented`` selects the sort-path kernel:
    the PR-4 :func:`pluss.ops.reuse.batch_events` formulation (default)
    or the legacy ghost-merged ``_sort_window`` (A/B, bit-identical).
    Static in-window share values of template windows are added host-side
    in :func:`shard_run` (uncapped, like ``engine.run``).

    ``vary``: vma marker for shard_map unification (:data:`_vary`); the
    chunk executables run OUTSIDE shard_map and pass identity.
    """
    if vary is None:
        vary = _vary
    cfg = pl.cfg
    bases = pl.spec.line_bases(cfg)
    n_lines = pl.spec.total_lines(cfg)
    pdt = jnp.dtype(pl.pos_dtype)
    nest_base = jnp.asarray(pl.nest_base.astype(pl.pos_dtype))
    win_shift = np_.window_rounds * cfg.chunk_size * np_.body
    all_ranges = _array_ranges(np_.refs, pl.spec, cfg)
    var_ranges = _array_ranges(np_.var_refs, pl.spec, cfg)
    mask = np_.ultra_windows()            # [NW] bool, static

    def one(t):
        owned_row = jnp.asarray(np_.owned)[t]
        nb = nest_base[ni, t]
        clock_row = None if np_.clock is None else jnp.asarray(np_.clock)[t]

        def sorted_events(refs, ranges, w, last_pos, with_clock: bool):
            """(last_pos, ev) of one sort-path window — segmented
            (batch_events) or legacy (ghost-merged) kernel; ``ev`` always
            carries the sorted key/pos/span for the head capture."""
            cr = clock_row if with_clock else None
            if segmented:
                return _batch_window(
                    np_, refs, cfg, owned_row, w, nb, bases,
                    pl.spec.array_index, pdt, last_pos, cr)
            last_pos, _, ev, (key_s, pos_s, span_s) = _sort_window(
                np_, refs, ranges, cfg, owned_row, w, nb, bases,
                pl.spec.array_index, pdt, last_pos, win_shift,
                with_hist=False, clock_row=cr,
            )
            ev = dict(ev, key=key_s, pos=pos_s, span=span_s)
            return last_pos, ev

        def sort_body(carry, w):
            last_pos, hist, head_pos, head_span = carry
            last_pos, ev = sorted_events(np_.refs, all_ranges, w, last_pos,
                                         with_clock=True)
            hist = hist + event_histogram(ev, include_cold=False)
            head_pos, head_span = _capture_heads(
                head_pos, head_span, ev["cold"], ev["key"], ev["pos"],
                ev["span"], n_lines,
            )
            sv, sc, snu = share_unique(ev, share_cap)
            return (last_pos, hist, head_pos, head_span), (sv, sc, snu)

        def ultra_body(carry, w):
            last_pos, hist, head_pos, head_span = carry
            # template-ineligible arrays sort inside the clean window too
            # (engine._split_ref_groups); their lines are disjoint from the
            # template's, so the dense merges below never collide
            ev_var = None
            if np_.var_refs:
                last_pos, ev_var = sorted_events(
                    np_.var_refs, var_ranges, w, last_pos, with_clock=False)
                hist = hist + event_histogram(ev_var, include_cold=False)
                head_pos, head_span = _capture_heads(
                    head_pos, head_span, ev_var["cold"], ev_var["key"],
                    ev_var["pos"], ev_var["span"], n_lines)
            hp, hs, tp = _tpl_dense(np_.tpl, t, w, n_lines, pl.pos_dtype, nb)
            m = hp >= 0                       # lines headed in this window
            evt = m & (last_pos >= 0)         # resolved against device carry
            cold = m & (last_pos < 0)         # first-in-device: capture
            reuse = jnp.where(evt, hp - last_pos, 0)
            share = evt & share_mask(reuse, hs)
            nevt = evt & ~share
            bins = jnp.where(nevt, log2_bin(reuse), 0)
            hist = (hist
                    + jnp.asarray(np_.tpl.local_hist.astype(pl.pos_dtype))
                    + bin_histogram(bins, nevt.astype(pdt)))
            head_pos = jnp.where(cold, hp, head_pos)
            head_span = jnp.where(cold, hs, head_span)
            last_pos = jnp.where(tp >= 0, tp, last_pos)
            dev = {"reuse": reuse, "share": share}
            if ev_var is not None:
                dev = {k: jnp.concatenate([dev[k], ev_var[k]]) for k in dev}
            sv, sc, snu = share_unique(dev, share_cap)
            return (last_pos, hist, head_pos, head_span), (sv, sc, snu)

        if not mask.any():
            body = sort_body
        elif mask.all() and np_.tpl is not None:
            body = ultra_body
        else:
            def body(carry, w):
                return jax.lax.cond(
                    jnp.asarray(mask)[w],
                    lambda c: vary(ultra_body(c, w)),
                    lambda c: vary(sort_body(c, w)),
                    carry,
                )

        init = vary((
            jnp.full((n_lines,), -1, pdt),        # last_pos (ends as tails)
            jnp.zeros((NBINS,), pdt),             # hist
            jnp.full((n_lines,), -1, pdt),        # head_pos
            jnp.zeros((n_lines,), jnp.int32),     # head_span
        ))
        (tail_pos, hist, head_pos, head_span), (sv, sc, snu) = jax.lax.scan(
            body, init, jnp.asarray(w_ids, jnp.int32),
        )
        return (hist, sv, sc, snu, head_pos, head_span, tail_pos)

    return jax.vmap(one)(tids)


def _shard_body(tids, pl: StreamPlan, share_cap: int, D: int, S: int,
                segmented: bool = True):
    d = jax.lax.axis_index("d")
    N = len(pl.nests)
    w_ids = (d * S + jnp.arange(S, dtype=jnp.int32)).astype(jnp.int32)
    per_nest = [
        _nest_results(np_, ni, tids, pl, share_cap, w_ids, segmented)
        for ni, np_ in enumerate(pl.nests)
    ]
    (hist, sv, sc, snu, head_pos, head_span, tail_pos) = jax.tree.map(
        lambda *xs: jnp.stack(xs, axis=1), *per_nest
    )
    # tail exchange: [D, T, N, L] — the only cross-device state
    tails_all = jax.lax.all_gather(tail_pos, "d")
    ni_idx = jnp.arange(N)
    dev_idx = jnp.arange(D)
    prevs = []
    for ni in range(N):
        # segments (nj, e) strictly before (ni, d) in global clock order
        earlier = (ni_idx[None, :] < ni) | (
            (ni_idx[None, :] == ni) & (dev_idx[:, None] < d)
        )
        m = earlier[:, None, :, None]  # [D, 1, N, 1]
        prevs.append(jnp.max(jnp.where(m, tails_all, -1), axis=(0, 2)))
    prev = jnp.stack(prevs, axis=1)  # [T, N, L]

    has_head = head_pos >= 0
    head_evt = has_head & (prev >= 0)
    cold = has_head & (prev < 0)
    reuse = jnp.where(head_evt, head_pos - prev, 0)
    share = head_evt & share_mask(reuse, head_span)
    nevt = head_evt & ~share
    bins = jnp.where(nevt, log2_bin(reuse), 0)
    w = (cold | nevt).astype(hist.dtype)
    head_hist = jax.vmap(
        lambda bb, ww: bin_histogram(bb.ravel(), ww.ravel())
    )(bins, w)
    total = hist.sum(axis=1) + head_hist            # [T, NBINS]
    total = jax.lax.psum(total, "d")                # replicated merge over ICI
    head_share = jnp.where(share, reuse, -1)        # [T, N, L] raw values
    # replicate the per-device outputs (all small): in multi-PROCESS runs a
    # host can only read addressable shards, so device-sharded outputs would
    # not be fetchable — all_gather makes every output host-readable on
    # every process (the DCN story stays collectives-only).  The pmax over
    # identical gathered copies is an identity that PROVES replication to
    # shard_map's vma check, keeping out_specs=P() statically valid.
    return (total,) + tuple(
        jax.lax.pmax(jax.lax.all_gather(x, "d"), "d")
        for x in (sv, sc, snu, head_share)
    )


def _shard_geometry(spec: LoopNestSpec, cfg: SamplerConfig, D: int,
                    assignment, start_point, window_accesses):
    """(plan, S): the shared window grid of BOTH dispatch modes.

    Sub-windows per device: enough that each sub-window stays near the
    engine's window target, so per-device sort memory is bounded by the
    same constant as the single-device scan regardless of workload size.
    Overlays/rowpriv off: the shard window sorts the full var_refs, so
    the budget guard must size that stream (and the overlay verification
    cost would be pure waste here).  One plan (engine.shard_plan_cached)
    serves static and steal dispatch alike, so a dispatch-mode flip
    reuses the host analysis AND the chunk executables cached on it."""
    S = max(1, -(-natural_n_windows(spec, cfg, assignment, start_point,
                                    window_accesses) // D))
    pl = shard_plan_cached(spec, cfg, assignment, start_point,
                           window_accesses, D * S)
    return pl, S


@functools.lru_cache(maxsize=32)
def _compiled(spec: LoopNestSpec, cfg: SamplerConfig, share_cap: int,
              mesh: Mesh, assignment=None, start_point=None,
              window_accesses=None, segmented: bool = True):
    D = mesh.devices.size
    pl, S = _shard_geometry(spec, cfg, D, assignment, start_point,
                            window_accesses)
    from pluss.ops import pallas_events

    # suppressing(): no pallas_call replication rule under shard_map —
    # the body's event_histogram dispatch must bake in the XLA path
    f = compat.shard_map(
        pallas_events.suppressing(
            lambda t: _shard_body(t, pl, share_cap, D, S, segmented)),
        mesh=mesh,
        in_specs=P(),
        out_specs=P(),
    )
    return pl, jax.jit(f)


# ---------------------------------------------------------------------------
# work-stealing chunk dispatch (single-process): per-device executables over
# window chunks + a host-side canonical-order boundary merge.


def _chunk_windows_of(S: int) -> int:
    """Windows per chunk: ~4 chunks per device's static share, so an idle
    device always has something to steal (``PLUSS_SHARD_CHUNK_WINDOWS``
    overrides)."""
    from pluss.utils.envknob import env_int

    env = os.environ.get("PLUSS_SHARD_CHUNK_WINDOWS")
    if env is not None:
        return env_int("PLUSS_SHARD_CHUNK_WINDOWS", max(1, S // 4))
    return max(1, S // 4)


def _chunk_plan(pl: StreamPlan, S: int) -> list[tuple[int, int, int]]:
    """[(nest, w_lo, w_len)] chunks in canonical (global stream) order."""
    cw = _chunk_windows_of(S)
    chunks = []
    for ni, np_ in enumerate(pl.nests):
        for lo in range(0, np_.n_windows, cw):
            chunks.append((ni, lo, min(cw, np_.n_windows - lo)))
    return chunks


def _chunk_fn(pl: StreamPlan, share_cap: int, ni: int, L: int,
              segmented: bool, device):
    """Jitted per-device chunk executable: (tids, w_ids[L]) ->
    (hist, sv, sc, snu, head_pos, head_span, tail_pos).

    Cached ON the plan object (the engine._slice_fn discipline: a
    module-level memo closing over ``pl`` would keep every plan alive
    forever); keyed by (nest, chunk length, kernel, cap, device), so every
    same-length chunk of a nest reuses one executable per device and a
    dispatch-mode flip or share-cap retry compiles only what changed.
    """
    cache = getattr(pl, "_chunk_fns", None)
    if cache is None:
        cache = {}
        object.__setattr__(pl, "_chunk_fns", cache)
    key = (ni, L, segmented, share_cap, device.id, jax.default_backend())
    if key in cache:
        return cache[key]

    def f(tids, w_ids):
        return _nest_results(pl.nests[ni], ni, tids, pl, share_cap, w_ids,
                             segmented, vary=lambda tree: tree)

    # lazy AOT over the jit: the executable is committed to ``device`` by
    # its first call's arg placement (an eager ShapeDtypeStruct lower would
    # pin device 0), so lowering waits for the concrete call; a restored
    # sidecar that refuses the call (device-binding mismatch after a
    # topology change) falls back to the jit path per call_fallback
    from pluss import plancache

    fn = plancache.LazyAotFn(
        jax.jit(f), getattr(pl, "_exe_group", None),
        ("chunk", ni, L, segmented, share_cap, device.id),
        call_fallback=True)
    cache[key] = fn
    return fn


def _run_steal(pl: StreamPlan, share_cap: int, devices, S: int,
               segmented: bool, seed: int):
    """Dispatch the chunk plan over ``devices`` with work stealing.

    Returns (chunks, results{chunk_id: numpy tuple}, stats).  Results are
    fetched to host inside each worker, so device memory holds one chunk's
    outputs per device at a time.
    """
    from pluss.parallel.steal import StealDispatcher

    chunks = _chunk_plan(pl, S)
    T = pl.cfg.thread_num
    tids_h = np.arange(T, dtype=np.int32)
    results: dict[int, tuple] = {}
    if getattr(pl, "_chunk_fns", None) is None:
        # eager init on the dispatching thread: two workers racing the
        # first getattr would otherwise each install a fresh dict and one
        # would silently drop the other's compiled entry
        object.__setattr__(pl, "_chunk_fns", {})

    def run_chunk(di, ci):
        ni, lo, ln = chunks[ci]
        dev = devices[di]
        fn = _chunk_fn(pl, share_cap, ni, ln, segmented, dev)
        out = fn(jax.device_put(tids_h, dev),
                 jax.device_put(np.arange(lo, lo + ln, dtype=np.int32),
                                dev))
        results[ci] = tuple(np.asarray(x) for x in out)

    disp = StealDispatcher(len(chunks), len(devices), run_chunk, seed=seed)
    stats = disp.run()
    return chunks, results, stats


def np_head_hist(reuse_vals: np.ndarray) -> np.ndarray:
    """[NBINS] host twin of the device head binning: slot ``1+e`` for
    reuse in ``[2^e, 2^{e+1})`` via the frexp exponent (exact for int
    reuse < 2^53 — the same formulation engine._build_template uses).
    Slots past NBINS drop, exactly like the device one-hot matmul.  The
    ONE home of this rule: both boundary merges (the chunked shard_run
    and the steal-dispatch trace replay) bin through it, so they can
    never diverge."""
    slots = np.frexp(reuse_vals.astype(np.float64))[1].astype(np.int64)
    return np.bincount(slots, minlength=NBINS)[:NBINS]


def _merge_chunks(pl: StreamPlan, chunks, results, share_cap: int):
    """Canonical-order boundary merge of the chunk outputs.

    Heads of chunk ``k`` resolve against the running per-line prefix-max
    of earlier chunks' tails — the host twin of ``_shard_body``'s masked
    all_gather/max exchange, and the reason steal-order permutations are
    bit-identical: only the (fixed) chunk partition and this (fixed)
    merge order reach the result.  Raises :class:`ShareCapExceeded` when
    any device window dropped surplus share uniques.
    """
    cfg = pl.cfg
    T = cfg.thread_num
    n_lines = pl.spec.total_lines(cfg)
    prev = np.full((T, n_lines), -1, np.int64)
    hist = np.zeros((T, NBINS), np.int64)
    head_share: list[dict] = [dict() for _ in range(T)]
    sv_n: list[list] = [[] for _ in pl.nests]
    sc_n: list[list] = [[] for _ in pl.nests]
    snu_n: list[list] = [[] for _ in pl.nests]
    for ci, (ni, _, _) in enumerate(chunks):
        h, sv, sc, snu, hp, hs, tp = results[ci]
        hist += np.asarray(h, np.int64)
        sv_n[ni].append(sv)
        sc_n[ni].append(sc)
        snu_n[ni].append(snu)
        hp = hp.astype(np.int64)
        tp = tp.astype(np.int64)
        has = hp >= 0
        evt = has & (prev >= 0)
        cold = has & (prev < 0)
        reuse = np.where(evt, hp - prev, 0)
        share = evt & share_mask(reuse, hs.astype(np.int64))
        nevt = evt & ~share
        hist[:, 0] += cold.sum(axis=1)
        for t in range(T):
            r = reuse[t][nevt[t]]
            if r.size:
                hist[t] += np_head_hist(r)
            shv = reuse[t][share[t]]
            if shv.size:
                uv, uc = np.unique(shv, return_counts=True)
                d = head_share[t]
                for v, c in zip(uv.tolist(), uc.tolist()):
                    d[v] = d.get(v, 0) + int(c)
        prev = np.where(tp >= 0, tp, prev)
    share_raw = merge_share_windows(
        [np.concatenate(s, axis=1) for s in sv_n],
        [np.concatenate(s, axis=1) for s in sc_n],
        [np.concatenate(s, axis=1) for s in snu_n],
        share_cap, T,
    )
    for t in range(T):
        d = share_raw[t]
        for v, c in head_share[t].items():
            d[v] = d.get(v, 0) + c
    return hist, share_raw


def _add_head_share(share_raw: list[dict], head_share: np.ndarray,
                    T: int) -> None:
    """Fold the static path's gathered raw head-share values ([D, T, N, L],
    -1 = none) into the per-thread dicts — one vectorized unique/count
    pass per thread instead of the former per-value Python triple loop
    (a host hot loop at D=8)."""
    for t in range(T):
        vals = head_share[:, t]
        vals = vals[vals >= 0]
        if not vals.size:
            continue
        uv, uc = np.unique(vals, return_counts=True)
        d = share_raw[t]
        for v, c in zip(uv.tolist(), uc.tolist()):
            d[v] = d.get(v, 0) + int(c)


def shard_run(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
              share_cap: int = SHARE_CAP,
              mesh: Mesh | None = None,
              assignment=None, start_point=None,
              window_accesses: int | None = None,
              dispatch: str | None = None,
              segmented: bool | None = None,
              steal_seed: int | None = None) -> SamplerResult:
    """Run the sampler with stream windows sharded over a device mesh.

    ``assignment``/``start_point``: dynamic chunk->thread maps and the
    setStartPoint resume rule, as in :func:`pluss.engine.run`;
    ``window_accesses`` overrides the per-sub-window access target
    (default engine.WINDOW_TARGET).

    ``dispatch``: ``steal`` (host-side work-stealing chunk dispatch — the
    single-process default), ``static`` (one shard_map program — the
    multi-process mode), or ``auto``/None (``PLUSS_SHARD_DISPATCH``).
    ``segmented``: window-kernel A/B (``PLUSS_SHARD_SEGMENTED``; default
    the PR-4 batch_events kernel).  ``steal_seed`` permutes the steal
    schedule — never the result.  All three are bit-identity-invariant,
    pinned by tests/test_steal.py.

    A device window that overflows ``share_cap`` retries ITERATIVELY at a
    covering cap (the engine.run contract; formerly a recursive call —
    deep retries can no longer hit the interpreter recursion limit), each
    attempt counted on ``engine.share_cap_retries``.
    """
    from pluss.resilience import faults

    faults.check("shard.run")   # chaos injection site (per entry attempt)
    mesh = mesh or default_mesh()
    if assignment is not None:
        assignment = tuple(
            tuple(a) if a is not None else None for a in assignment
        )
    if mesh.devices.size == 1:
        # a 1-device "mesh" would make the whole stream one window; the
        # windowed engine is the same computation with bounded memory
        from pluss import engine

        return engine.run(spec, cfg, share_cap, assignment=assignment,
                          start_point=start_point,
                          window_accesses=window_accesses)
    mode = _resolve_dispatch(dispatch)
    if mode == "auto":
        # the plan memo is shared with the execution below — sizing the
        # auto decision costs no extra host analysis
        pl0, _ = _shard_geometry(spec, cfg, mesh.devices.size, assignment,
                                 start_point, window_accesses)
        mode = "steal" if _auto_steal(pl0.total_count) else "static"
    if segmented is None:
        segmented = _shard_segmented_default()
    cap = share_cap
    while True:   # share-cap auto-retry: iterative, never recursive
        try:
            if mode == "steal":
                res = _shard_run_steal(spec, cfg, cap, mesh, assignment,
                                       start_point, window_accesses,
                                       bool(segmented),
                                       _steal_seed(steal_seed))
            else:
                res = _shard_run_static(spec, cfg, cap, mesh, assignment,
                                        start_point, window_accesses,
                                        bool(segmented))
            return res
        except ShareCapExceeded as e:
            # device windows dropped surplus uniques: same graceful
            # auto-retry contract as engine.run / run_sliced (counts
            # engine.share_cap_retries per attempt, raises past ceiling)
            cap = _auto_share_cap(e, cap)


def _shard_run_static(spec, cfg, share_cap, mesh, assignment, start_point,
                      window_accesses, segmented: bool) -> SamplerResult:
    """One static-dispatch attempt (raises ShareCapExceeded to the retry
    loop in :func:`shard_run`)."""
    T = cfg.thread_num
    D = mesh.devices.size
    pl, f = _compiled(spec, cfg, share_cap, mesh, assignment, start_point,
                      window_accesses, segmented)
    tids = jnp.arange(T, dtype=jnp.int32)
    with obs.span("shard.dispatch", model=spec.name, backend="static",
                  devices=D, segmented=segmented):
        hist, sv, sc, snu, head_share = f(tids)
        hist = np.asarray(hist, np.int64)   # the fetch forces the dispatch
    obs.counter_add("engine.refs_processed", pl.total_count)
    # [D, T, N, S, ...] -> [T, D, N, S, ...]: merge_share_windows flattens
    # every non-thread axis anyway, so one swap covers all nests/sub-windows
    sv, sc, snu = np.asarray(sv), np.asarray(sc), np.asarray(snu)
    share_raw = merge_share_windows(
        [np.moveaxis(sv, 1, 0)], [np.moveaxis(sc, 1, 0)],
        [np.moveaxis(snu, 1, 0)], share_cap, T,
    )
    _add_head_share(share_raw, np.asarray(head_share), T)
    # static in-window share of template nests: one copy per (thread, ultra
    # window) — exactly the devices whose cond took the template branch
    # (same ultra_windows() mask as the branch selection, by construction)
    add_static_share(share_raw,
                     [(n, int(n.ultra_windows().sum())) for n in pl.nests])
    return SamplerResult(
        noshare_dense=hist,
        share_raw=share_raw,
        share_ratio=T - 1,
        max_iteration_count=pl.total_count,
        dispatch_stats={"dispatch": "static", "devices": D},
    )


def _shard_run_steal(spec, cfg, share_cap, mesh, assignment, start_point,
                     window_accesses, segmented: bool,
                     seed: int) -> SamplerResult:
    """One work-stealing-dispatch attempt (raises ShareCapExceeded to the
    retry loop in :func:`shard_run`)."""
    T = cfg.thread_num
    devices = list(mesh.devices.ravel())
    D = len(devices)
    pl, S = _shard_geometry(spec, cfg, D, assignment, start_point,
                            window_accesses)
    with obs.span("shard.dispatch", model=spec.name, backend="steal",
                  devices=D, segmented=segmented) as sp:
        chunks, results, stats = _run_steal(pl, share_cap, devices, S,
                                            segmented, seed)
        hist, share_raw = _merge_chunks(pl, chunks, results, share_cap)
        sp.set(chunks=len(chunks), steals=stats["steals"])
    obs.counter_add("engine.refs_processed", pl.total_count)
    obs.counter_add("shard.chunks", len(chunks))
    obs.counter_add("shard.steals", stats["steals"])
    for i, bf in enumerate(stats["busy_frac"]):
        obs.gauge_set(f"shard.device_busy_frac.{i}", round(bf, 4))
    add_static_share(share_raw,
                     [(n, int(n.ultra_windows().sum())) for n in pl.nests])
    return SamplerResult(
        noshare_dense=hist,
        share_raw=share_raw,
        share_ratio=T - 1,
        max_iteration_count=pl.total_count,
        dispatch_stats={"dispatch": "steal", "devices": D,
                        "chunks": len(chunks), "steals": stats["steals"],
                        "busy_frac": stats["busy_frac"],
                        "ran_by": stats["ran_by"]},
    )
