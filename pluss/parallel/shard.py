"""Device-sharded sampling: round windows over a mesh, histograms over ICI.

The reference's only cross-worker interaction is a post-hoc sum of per-thread
histograms (``/root/reference/src/utils.rs:154-176,310-322``; its "backends"
are OpenMP / Rayon / std::thread fan-outs of the same walk, SURVEY.md §2).
Here the scalable axis is different and strictly stronger: the **stream**
dimension is sharded.  Each simulated thread's access stream is cut into one
round-window per device (the same windows the single-device engine scans);
every device sorts its window locally, and the only cross-device state is a
dense per-line boundary exchange:

- each device emits ``tail_pos[line]`` (last local position) per segment;
- an ``all_gather`` + masked max over earlier segments yields each segment's
  ``prev_last[line]`` — the carried LAT table the scan path threads serially;
- window heads resolve against it (reuse, share, or cold);
- histograms merge with ``psum`` over ICI, exactly the reference's
  all-reduce-by-summation (SURVEY.md §2 "communication backend").

Segments are ordered ``(nest, device)``: all devices' windows of nest 0
precede nest 1's, matching the global clock.  This is the moral equivalent of
ring/blockwise sequence parallelism for long streams — small carried state,
local heavy compute, one boundary collective — and it runs unchanged on a
multi-host mesh (DCN collectives) because only ``all_gather``/``psum`` are
used.  No point-to-point communication is ever needed (SURVEY.md §5).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from pluss.config import DEFAULT, NBINS, SHARE_CAP, SamplerConfig
from pluss.utils import compat
from pluss.engine import (
    SamplerResult,
    StreamPlan,
    _array_ranges,
    _sort_window,
    ShareCapExceeded,
    merge_share_windows,
    natural_n_windows,
    plan,
)
from pluss.ops.reuse import (
    bin_histogram,
    event_histogram,
    log2_bin,
    share_mask,
    share_unique,
)
from pluss.spec import LoopNestSpec


def default_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` (default: all) local devices."""
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        raise ValueError(f"requested {n} devices, only {len(devs)} visible")
    return Mesh(np.asarray(devs[:n]), ("d",))


def _tpl_dense(tpl, tid, d, n_lines, pos_dtype, nb):
    """Template path: dense (head_pos, head_span, tail_pos) for window ``d``
    of thread ``tid`` — no stream materialization.

    The shift arithmetic mirrors the engine's ``ultra_step`` (units per chunk
    offset, positions per window); the dense arrays are built with one
    ``dynamic_update_slice`` per contiguous line run (scatter fallback for
    fragmented line sets).
    """
    pdt = jnp.dtype(pos_dtype)
    units = (d - tpl.w0) * tpl.unit_w + (tid - tpl.t0)
    dpos = jnp.asarray(tpl.pos_shift, pdt) * (d - tpl.w0).astype(pdt) + nb

    def dense(runs, lines, dlines, vals, fill):
        out = jnp.full((n_lines,), fill, vals.dtype)
        if runs is None:
            idx = jnp.asarray(lines) + jnp.asarray(dlines) * units
            return out.at[idx].set(vals, unique_indices=True)
        for ls, off, ln, dl in runs:
            out = jax.lax.dynamic_update_slice(
                out, vals[int(off):int(off) + int(ln)],
                (int(ls) + int(dl) * units,),
            )
        return out

    hpos = jnp.asarray(tpl.head_pos.astype(pos_dtype)) + dpos
    tpos = jnp.asarray(tpl.tail_pos.astype(pos_dtype)) + dpos
    head_pos = dense(tpl.head_runs, tpl.head_line, tpl.head_dline, hpos, -1)
    head_span = dense(tpl.head_runs, tpl.head_line, tpl.head_dline,
                      jnp.asarray(tpl.head_span), 0)
    tail_pos = dense(tpl.tail_runs, tpl.tail_line, tpl.tail_dline, tpos, -1)
    return head_pos, head_span, tail_pos


#: vma marking for shard_map unification (template constants are
#: device-invariant; sorted-stream values are varying) — identity on jax
#: versions without the vma system (pluss.utils.compat)
_vary = compat.vary


def _capture_heads(head_pos, head_span, cold, key_s, pos_s, span_s,
                   n_lines: int):
    """Record first-in-device touches from one sorted sub-window.

    A line's device-local cold happens at most once across the device's
    sub-windows (afterwards the carried table resolves it), so the update
    is a permutation: non-cold entries scatter into private dump slots past
    ``n_lines`` (the same trick as ops.reuse.window_events' tail update).
    ``head_span``/``span_s`` may be None (the trace path has no share
    classification).
    """
    w = key_s.shape[0]
    tgt = jnp.where(cold, key_s, n_lines + jnp.arange(w, dtype=key_s.dtype))
    ext_p = jnp.concatenate([head_pos, jnp.zeros((w,), head_pos.dtype)])
    head_pos = ext_p.at[tgt].set(pos_s, unique_indices=True)[:n_lines]
    if head_span is None:
        return head_pos, None
    ext_s = jnp.concatenate([head_span, jnp.zeros((w,), head_span.dtype)])
    head_span = ext_s.at[tgt].set(span_s, unique_indices=True)[:n_lines]
    return head_pos, head_span


def _nest_results(np_, ni: int, tids, pl: StreamPlan, share_cap: int, d,
                  S: int):
    """[T, ...] results of one nest's S sub-windows on this device.

    Device ``d`` owns global windows ``d*S .. d*S+S-1`` and scans them
    sequentially per thread, carrying ``(last_pos, hist, head_pos,
    head_span)`` — the engine's windowed scan nested inside the shard, so
    per-device sort memory is bounded by the engine's window target no
    matter how large the workload (round-1 verdict weak #3).  Differences
    from the single-device scan: a sub-window access with no in-device
    predecessor is captured as a device HEAD (not a cold miss) for the
    cross-device exchange, and the final carry IS the device's tail table.

    Each sub-window takes the static-template path when clean for every
    thread, the ghost-merged sort path otherwise (``lax.cond`` per
    sub-window: under ``shard_map`` the device index is a real branch, so
    ragged schedules only pay the sort where they are ragged).  Static
    in-window share values of template sub-windows are added host-side in
    :func:`shard_run` (uncapped, like ``engine.run``).
    """
    cfg = pl.cfg
    bases = pl.spec.line_bases(cfg)
    n_lines = pl.spec.total_lines(cfg)
    pdt = jnp.dtype(pl.pos_dtype)
    nest_base = jnp.asarray(pl.nest_base.astype(pl.pos_dtype))
    win_shift = np_.window_rounds * cfg.chunk_size * np_.body
    all_ranges = _array_ranges(np_.refs, pl.spec, cfg)
    var_ranges = _array_ranges(np_.var_refs, pl.spec, cfg)
    mask = np_.ultra_windows()            # [NW] bool, static

    def one(t):
        owned_row = jnp.asarray(np_.owned)[t]
        nb = nest_base[ni, t]
        clock_row = None if np_.clock is None else jnp.asarray(np_.clock)[t]

        def sort_body(carry, w):
            last_pos, hist, head_pos, head_span = carry
            last_pos, _, ev, (key_s, pos_s, span_s) = _sort_window(
                np_, np_.refs, all_ranges, cfg, owned_row, w, nb, bases,
                pl.spec.array_index, pdt, last_pos, win_shift,
                with_hist=False, clock_row=clock_row,
            )
            hist = hist + event_histogram(ev, include_cold=False)
            head_pos, head_span = _capture_heads(
                head_pos, head_span, ev["cold"], key_s, pos_s, span_s,
                n_lines,
            )
            sv, sc, snu = share_unique(ev, share_cap)
            return (last_pos, hist, head_pos, head_span), (sv, sc, snu)

        def ultra_body(carry, w):
            last_pos, hist, head_pos, head_span = carry
            # template-ineligible arrays sort inside the clean window too
            # (engine._split_ref_groups); their lines are disjoint from the
            # template's, so the dense merges below never collide
            ev_var = None
            if np_.var_refs:
                last_pos, _, ev_var, (vk, vp, vs) = _sort_window(
                    np_, np_.var_refs, var_ranges, cfg, owned_row, w, nb,
                    bases, pl.spec.array_index, pdt, last_pos, win_shift,
                    with_hist=False,
                )
                hist = hist + event_histogram(ev_var, include_cold=False)
                head_pos, head_span = _capture_heads(
                    head_pos, head_span, ev_var["cold"], vk, vp, vs, n_lines)
            hp, hs, tp = _tpl_dense(np_.tpl, t, w, n_lines, pl.pos_dtype, nb)
            m = hp >= 0                       # lines headed in this window
            evt = m & (last_pos >= 0)         # resolved against device carry
            cold = m & (last_pos < 0)         # first-in-device: capture
            reuse = jnp.where(evt, hp - last_pos, 0)
            share = evt & share_mask(reuse, hs)
            nevt = evt & ~share
            bins = jnp.where(nevt, log2_bin(reuse), 0)
            hist = (hist
                    + jnp.asarray(np_.tpl.local_hist.astype(pl.pos_dtype))
                    + bin_histogram(bins, nevt.astype(pdt)))
            head_pos = jnp.where(cold, hp, head_pos)
            head_span = jnp.where(cold, hs, head_span)
            last_pos = jnp.where(tp >= 0, tp, last_pos)
            dev = {"reuse": reuse, "share": share}
            if ev_var is not None:
                dev = {k: jnp.concatenate([dev[k], ev_var[k]]) for k in dev}
            sv, sc, snu = share_unique(dev, share_cap)
            return (last_pos, hist, head_pos, head_span), (sv, sc, snu)

        if not mask.any():
            body = sort_body
        elif mask.all() and np_.tpl is not None:
            body = ultra_body
        else:
            def body(carry, w):
                return jax.lax.cond(
                    jnp.asarray(mask)[w],
                    lambda c: _vary(ultra_body(c, w)),
                    lambda c: _vary(sort_body(c, w)),
                    carry,
                )

        init = _vary((
            jnp.full((n_lines,), -1, pdt),        # last_pos (ends as tails)
            jnp.zeros((NBINS,), pdt),             # hist
            jnp.full((n_lines,), -1, pdt),        # head_pos
            jnp.zeros((n_lines,), jnp.int32),     # head_span
        ))
        (tail_pos, hist, head_pos, head_span), (sv, sc, snu) = jax.lax.scan(
            lambda c, s: body(c, (d * S + s).astype(jnp.int32)),
            init, jnp.arange(S, dtype=jnp.int32),
        )
        return (hist, sv, sc, snu, head_pos, head_span, tail_pos)

    return jax.vmap(one)(tids)


def _shard_body(tids, pl: StreamPlan, share_cap: int, D: int, S: int):
    d = jax.lax.axis_index("d")
    N = len(pl.nests)
    per_nest = [
        _nest_results(np_, ni, tids, pl, share_cap, d, S)
        for ni, np_ in enumerate(pl.nests)
    ]
    (hist, sv, sc, snu, head_pos, head_span, tail_pos) = jax.tree.map(
        lambda *xs: jnp.stack(xs, axis=1), *per_nest
    )
    # tail exchange: [D, T, N, L] — the only cross-device state
    tails_all = jax.lax.all_gather(tail_pos, "d")
    ni_idx = jnp.arange(N)
    dev_idx = jnp.arange(D)
    prevs = []
    for ni in range(N):
        # segments (nj, e) strictly before (ni, d) in global clock order
        earlier = (ni_idx[None, :] < ni) | (
            (ni_idx[None, :] == ni) & (dev_idx[:, None] < d)
        )
        m = earlier[:, None, :, None]  # [D, 1, N, 1]
        prevs.append(jnp.max(jnp.where(m, tails_all, -1), axis=(0, 2)))
    prev = jnp.stack(prevs, axis=1)  # [T, N, L]

    has_head = head_pos >= 0
    head_evt = has_head & (prev >= 0)
    cold = has_head & (prev < 0)
    reuse = jnp.where(head_evt, head_pos - prev, 0)
    share = head_evt & share_mask(reuse, head_span)
    nevt = head_evt & ~share
    bins = jnp.where(nevt, log2_bin(reuse), 0)
    w = (cold | nevt).astype(hist.dtype)
    head_hist = jax.vmap(
        lambda bb, ww: bin_histogram(bb.ravel(), ww.ravel())
    )(bins, w)
    total = hist.sum(axis=1) + head_hist            # [T, NBINS]
    total = jax.lax.psum(total, "d")                # replicated merge over ICI
    head_share = jnp.where(share, reuse, -1)        # [T, N, L] raw values
    # replicate the per-device outputs (all small): in multi-PROCESS runs a
    # host can only read addressable shards, so device-sharded outputs would
    # not be fetchable — all_gather makes every output host-readable on
    # every process (the DCN story stays collectives-only).  The pmax over
    # identical gathered copies is an identity that PROVES replication to
    # shard_map's vma check, keeping out_specs=P() statically valid.
    return (total,) + tuple(
        jax.lax.pmax(jax.lax.all_gather(x, "d"), "d")
        for x in (sv, sc, snu, head_share)
    )


@functools.lru_cache(maxsize=32)
def _compiled(spec: LoopNestSpec, cfg: SamplerConfig, share_cap: int,
              mesh: Mesh, assignment=None, start_point=None,
              window_accesses=None):
    D = mesh.devices.size
    # sub-windows per device: enough that each sub-window stays near the
    # engine's window target, so per-device sort memory is bounded by the
    # same constant as the single-device scan regardless of workload size
    S = max(1, -(-natural_n_windows(spec, cfg, assignment, start_point,
                                    window_accesses) // D))
    # overlays off: the shard ultra window sorts the full var_refs, so the
    # budget guard must size that stream (and the overlay verification cost
    # would be pure waste here)
    pl = plan(spec, cfg, assignment, start_point, n_windows=D * S,
              build_overlays=False, build_rowpriv=False)
    f = compat.shard_map(
        lambda t: _shard_body(t, pl, share_cap, D, S),
        mesh=mesh,
        in_specs=P(),
        out_specs=P(),
    )
    return pl, jax.jit(f)


def shard_run(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
              share_cap: int = SHARE_CAP,
              mesh: Mesh | None = None,
              assignment=None, start_point=None,
              window_accesses: int | None = None) -> SamplerResult:
    """Run the sampler with stream windows sharded over a device mesh.

    ``assignment``/``start_point``: dynamic chunk->thread maps and the
    setStartPoint resume rule, as in :func:`pluss.engine.run`;
    ``window_accesses`` overrides the per-sub-window access target
    (default engine.WINDOW_TARGET).
    """
    from pluss.resilience import faults

    faults.check("shard.run")   # chaos injection site (per entry attempt)
    mesh = mesh or default_mesh()
    if assignment is not None:
        assignment = tuple(
            tuple(a) if a is not None else None for a in assignment
        )
    if mesh.devices.size == 1:
        # a 1-device "mesh" would make the whole stream one window; the
        # windowed engine is the same computation with bounded memory
        from pluss import engine

        return engine.run(spec, cfg, share_cap, assignment=assignment,
                          start_point=start_point,
                          window_accesses=window_accesses)
    pl, f = _compiled(spec, cfg, share_cap, mesh, assignment, start_point,
                      window_accesses)
    tids = jnp.arange(cfg.thread_num, dtype=jnp.int32)
    hist, sv, sc, snu, head_share = f(tids)
    # [D, T, N, S, ...] -> [T, D, N, S, ...]: merge_share_windows flattens
    # every non-thread axis anyway, so one swap covers all nests/sub-windows
    sv, sc, snu = np.asarray(sv), np.asarray(sc), np.asarray(snu)
    T = cfg.thread_num
    try:
        share_raw = merge_share_windows(
            [np.moveaxis(sv, 1, 0)], [np.moveaxis(sc, 1, 0)],
            [np.moveaxis(snu, 1, 0)], share_cap, T,
        )
    except ShareCapExceeded as e:
        # device windows dropped surplus uniques: same graceful auto-retry
        # contract as engine.run / run_sliced
        from pluss.engine import _auto_share_cap

        return shard_run(spec, cfg, _auto_share_cap(e, share_cap), mesh,
                         assignment, start_point, window_accesses)
    hv = np.asarray(head_share)
    for dev in range(hv.shape[0]):
        for t in range(T):
            for v in hv[dev, t][hv[dev, t] >= 0].tolist():
                share_raw[t][v] = share_raw[t].get(v, 0) + 1
    # static in-window share of template nests: one copy per (thread, ultra
    # window) — exactly the devices whose cond took the template branch
    # (same ultra_windows() mask as the branch selection, by construction)
    from pluss.engine import add_static_share

    add_static_share(share_raw,
                     [(n, int(n.ultra_windows().sum())) for n in pl.nests])
    return SamplerResult(
        noshare_dense=np.asarray(hist, np.int64),
        share_raw=share_raw,
        share_ratio=T - 1,
        max_iteration_count=pl.total_count,
    )
