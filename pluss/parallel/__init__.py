"""Device-parallel execution: stream-sharded sampling over a ``jax.sharding.Mesh``.

TPU-native replacement for the reference's shared-memory fan-outs (OpenMP /
Rayon / std::thread, SURVEY.md §2): windows of the simulated-thread streams are
sharded over devices with ``shard_map``, boundary state is exchanged with one
``all_gather``, and histograms merge with ``psum`` over ICI (DCN across hosts).
"""

from pluss.parallel.shard import default_mesh, shard_run

__all__ = ["default_mesh", "shard_run", "multihost"]
