"""Host-side work-stealing chunk dispatch for the sharded backend.

The static shard split (``S = ceil(n_windows / D)`` sub-windows per device,
one ``shard_map`` program) makes every device wait for the slowest one: a
quad nest's late windows carry up to ~2x the sort volume of its early ones
(the straggler behind the volatile 95x-155x syrk_tri rounds), and a static
split pins that imbalance for the whole run.  Because window results only
interact at the boundary merge (heads of chunk ``k`` resolve against the
running max over earlier chunks' tails — :func:`pluss.parallel.shard`),
ANY assignment of window chunks to devices yields the identical merged
result, so the assignment can be dynamic:

- :class:`StealDispatcher` — chunks known up front (``shard_run``): each
  device worker owns a contiguous block deque (stream locality); an idle
  worker STEALS the tail half of the fullest victim's deque.  The steal
  schedule never reaches the result: outputs are keyed by chunk id and
  merged in canonical stream order, so steal-order permutations are
  bit-identical by construction (pinned by tests/test_steal.py).
- :class:`QueueDispatcher` — chunks produced over time (the streamed
  sharded replay, where a sequential reader+compactor feeds them): a
  bounded queue with per-device consumer threads; an idle device pulls
  the next produced chunk, and a pull of a chunk the static split would
  have homed elsewhere counts as a steal.

Workers are host THREADS: each one drives its own device's dispatch
stream (jax releases the GIL inside XLA execution and transfers), so D
devices compute concurrently while the host merges nothing until the end.
"""

from __future__ import annotations

import collections
import random
import threading
import time


class StealDispatcher:
    """Per-worker deques + steal-half-on-idle over a fixed chunk list.

    ``run_chunk(worker_idx, chunk_id)`` executes one chunk on worker
    ``worker_idx``'s device and stores its own result (keyed by
    ``chunk_id``); this class only schedules.  ``seed`` permutes the
    initial block deal (a rotation) and victim tie-breaks — it changes
    WHICH device computes a chunk, never the merged result, which is
    exactly what the determinism tests vary.
    """

    def __init__(self, n_chunks: int, n_workers: int, run_chunk,
                 seed: int = 0):
        if n_chunks < 0 or n_workers < 1:
            raise ValueError(f"bad dispatcher geometry: {n_chunks} chunks, "
                             f"{n_workers} workers")
        self.n_chunks = n_chunks
        self.n_workers = n_workers
        self.run_chunk = run_chunk
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._deques: list[collections.deque] = [
            collections.deque() for _ in range(n_workers)]
        # contiguous block deal (stream locality), rotated by the seed so
        # different seeds genuinely permute the chunk->device map
        rot = self._rng.randrange(n_workers) if n_workers > 1 else 0
        for ci in range(n_chunks):
            self._deques[(ci * n_workers // max(1, n_chunks) + rot)
                         % n_workers].append(ci)
        self.steals = 0
        self.busy_s = [0.0] * n_workers
        self.chunks_run = [0] * n_workers
        self.ran_by: dict[int, int] = {}   # chunk id -> worker that ran it
        self._errors: list[BaseException] = []

    def _next(self, wi: int) -> int | None:
        with self._lock:
            dq = self._deques[wi]
            if not dq:
                # steal HALF of the fullest victim's tail (tail = the
                # chunks the victim would reach last); rng breaks ties so
                # seeds explore different schedules
                best = max(len(d) for d in self._deques)
                if best == 0:
                    return None
                cands = [j for j, d in enumerate(self._deques)
                         if len(d) == best and j != wi]
                if not cands:
                    return None
                vd = self._deques[self._rng.choice(cands)]
                take = (len(vd) + 1) // 2
                grabbed = [vd.pop() for _ in range(take)]
                grabbed.reverse()
                dq.extend(grabbed)
                self.steals += 1
            return dq.popleft()

    def _worker(self, wi: int) -> None:
        while True:
            if self._errors:
                return   # fail fast: someone else's chunk already died
            ci = self._next(wi)
            if ci is None:
                return
            t0 = time.perf_counter()
            try:
                self.run_chunk(wi, ci)
            except BaseException as e:  # noqa: BLE001 — re-raised in run()
                with self._lock:
                    self._errors.append(e)
                return
            with self._lock:
                self.busy_s[wi] += time.perf_counter() - t0
                self.chunks_run[wi] += 1
                self.ran_by[ci] = wi

    def run(self) -> dict:
        """Dispatch every chunk; returns schedule stats.  Re-raises the
        first worker error after the surviving workers drain."""
        t0 = time.perf_counter()
        if self.n_workers == 1 or self.n_chunks <= 1:
            # degenerate shapes run inline (no thread overhead)
            self._worker(0)
        else:
            threads = [threading.Thread(target=self._worker, args=(wi,),
                                        daemon=True,
                                        name=f"pluss-steal-{wi}")
                       for wi in range(self.n_workers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        if self._errors:
            raise self._errors[0]
        wall = max(time.perf_counter() - t0, 1e-9)
        return {
            "steals": self.steals,
            "chunks": self.n_chunks,
            "wall_s": wall,
            "busy_s": list(self.busy_s),
            "busy_frac": [min(1.0, b / wall) for b in self.busy_s],
            "chunks_per_worker": list(self.chunks_run),
            "ran_by": dict(self.ran_by),
        }


class QueueDispatcher:
    """Bounded-queue dispatch for chunks PRODUCED over time.

    The streamed sharded replay's chunks come out of a sequential
    reader+compactor (stream-order-stateful, so production order is
    fixed); per-device consumer threads pull the next produced chunk —
    work-conserving by construction.  A pull of a chunk whose static
    home (``chunk_id * n_workers // n_chunks``) is a different device
    counts as a steal, so the telemetry records how much rebalancing
    the dynamic dispatch actually did.
    """

    _DONE = object()

    def __init__(self, n_workers: int, run_chunk, depth: int = 2):
        import queue

        if n_workers < 1 or depth < 1:
            raise ValueError(f"bad dispatcher geometry: {n_workers} "
                             f"workers, depth {depth}")
        self.n_workers = n_workers
        self.run_chunk = run_chunk
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._lock = threading.Lock()
        self.steals = 0
        self.chunks = 0
        self.busy_s = [0.0] * n_workers
        self.chunks_run = [0] * n_workers
        self._errors: list[BaseException] = []

    def _worker(self, wi: int, n_chunks: int) -> None:
        while True:
            item = self._q.get()
            if item is self._DONE:
                self._q.put(self._DONE)   # pass the sentinel on
                return
            ci, payload = item
            if self._errors:
                continue   # drain mode: keep the producer unblocked
            t0 = time.perf_counter()
            try:
                self.run_chunk(wi, ci, payload)
            except BaseException as e:  # noqa: BLE001 — re-raised in run()
                with self._lock:
                    self._errors.append(e)
                continue
            with self._lock:
                self.busy_s[wi] += time.perf_counter() - t0
                self.chunks_run[wi] += 1
                if n_chunks and ci * self.n_workers // n_chunks != wi:
                    self.steals += 1

    def run(self, produce, n_chunks: int) -> dict:
        """Drain ``produce`` (an iterator of ``(chunk_id, payload)``)
        through the worker pool.  Producer exceptions re-raise here after
        the workers stop; worker exceptions stop the producer."""
        t0 = time.perf_counter()
        threads = [threading.Thread(target=self._worker,
                                    args=(wi, n_chunks), daemon=True,
                                    name=f"pluss-qsteal-{wi}")
                   for wi in range(self.n_workers)]
        for t in threads:
            t.start()
        produce_err: BaseException | None = None
        try:
            for item in produce:
                if self._errors:
                    break
                self._q.put(item)
                self.chunks += 1
        except BaseException as e:  # noqa: BLE001 — re-raised below
            produce_err = e
        self._q.put(self._DONE)
        for t in threads:
            t.join()
        if produce_err is not None:
            raise produce_err
        if self._errors:
            raise self._errors[0]
        wall = max(time.perf_counter() - t0, 1e-9)
        return {
            "steals": self.steals,
            "chunks": self.chunks,
            "wall_s": wall,
            "busy_s": list(self.busy_s),
            "busy_frac": [min(1.0, b / wall) for b in self.busy_s],
            "chunks_per_worker": list(self.chunks_run),
        }
