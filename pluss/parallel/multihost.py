"""Multi-host (multi-process) execution over DCN — the distributed backend.

The reference has no communication backend at all (shared memory + locks,
SURVEY.md §2/§5); this framework's cross-device story is XLA collectives, which
makes multi-host support a *configuration* problem rather than a code path:
:func:`pluss.parallel.shard.shard_run` only uses ``all_gather`` and ``psum``,
both of which XLA routes over ICI within a slice and DCN across hosts, with no
point-to-point communication anywhere.  This module provides the standard
JAX multi-process bring-up around it.

Usage (one process per host, e.g. under SLURM/GKE or manual bring-up)::

    from pluss.parallel.multihost import initialize, global_mesh
    initialize(coordinator_address="host0:1234", num_processes=4, process_id=i)
    mesh = global_mesh()                      # 1-D mesh over ALL devices
    res = shard_run(gemm(1024), mesh=mesh)    # same call as single-host

Single-host callers never need this module (``default_mesh()`` covers them).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """``jax.distributed.initialize`` pass-through.

    With no arguments, JAX auto-detects the cluster environment (TPU pod
    metadata, SLURM, GKE); explicit arguments cover manual bring-up.  Safe to
    call once per process, before any other JAX API touches a backend.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(axis: str = "d") -> Mesh:
    """1-D mesh over every device of every participating process.

    ``shard_run`` shards stream windows over this axis; each process feeds
    the same (replicated) inputs, per JAX's multi-process SPMD model.
    """
    return Mesh(np.asarray(jax.devices()), (axis,))


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    """True on the process that should own printing/IO (process 0)."""
    return jax.process_index() == 0
