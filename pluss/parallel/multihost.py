"""Multi-host (multi-process) execution over DCN — the distributed backend.

The reference has no communication backend at all (shared memory + locks,
SURVEY.md §2/§5); this framework's cross-device story is XLA collectives, which
makes multi-host support a *configuration* problem rather than a code path:
:func:`pluss.parallel.shard.shard_run` only uses ``all_gather`` and ``psum``,
both of which XLA routes over ICI within a slice and DCN across hosts, with no
point-to-point communication anywhere.  (The work-stealing chunk dispatch —
PR 9 — is a SINGLE-process mode: it places chunks on addressable devices, so
``shard_run``'s ``auto`` dispatch resolves to the static collectives-only
program whenever ``jax.process_count() > 1``; the two are bit-identical, so
a fleet mixing single-process steal runs with multi-process static runs
stays exactly comparable.)  This module provides the standard JAX
multi-process bring-up around it, **hardened** (PR 2):

- :func:`initialize` retries the coordinator connect under a bounded
  exponential backoff and a per-attempt timeout, classifying terminal
  failures as :class:`~pluss.resilience.errors.CollectiveError` — a slow
  coordinator or a bring-up race no longer surfaces as a raw RPC error;
- :func:`start_heartbeat` / :func:`dead_workers` give every process a
  file-based liveness channel on shared storage (collectives carry no
  liveness: a dead peer just hangs the collective forever);
- :func:`watched_shard_run` runs the SPMD computation under a watchdog
  that detects a stopped heartbeat within ``timeout_s`` and — on the
  coordinator — SALVAGES the run by recomputing on local devices only
  (``shard_run`` ≡ ``engine.run`` semantically, so the salvage result is
  bit-identical, only slower), stamped ``local_salvage``.

Usage (one process per host, e.g. under SLURM/GKE or manual bring-up)::

    from pluss.parallel.multihost import initialize, global_mesh
    initialize(coordinator_address="host0:1234", num_processes=4, process_id=i)
    mesh = global_mesh()                      # 1-D mesh over ALL devices
    res = shard_run(gemm(1024), mesh=mesh)    # same call as single-host

Single-host callers never need this module (``default_mesh()`` covers them).
"""

from __future__ import annotations

import json
import os
import threading
import time

import jax
import numpy as np
from jax.sharding import Mesh

from pluss import obs
from pluss.resilience.errors import WorkerDied, classify
from pluss.resilience import faults
from pluss.utils import envknob


def heartbeat_interval_s() -> float:
    """File-heartbeat write period: ``PLUSS_HEARTBEAT_S`` (default 0.5 s).
    Real clusters on NFS/GCS-fuse want seconds, local tests sub-second —
    the ROADMAP PR-2 follow-up knob, now config instead of a constant.
    Lenient warn-once parse (:mod:`pluss.utils.envknob`): a typo'd knob
    on one worker must not crash a pod bring-up."""
    return envknob.env_float("PLUSS_HEARTBEAT_S", 0.5, 0.01)


def heartbeat_timeout_s() -> float:
    """Staleness threshold for declaring a worker dead:
    ``PLUSS_HEARTBEAT_TIMEOUT_S`` (default 5 s, and never below 2
    heartbeat intervals — a timeout tighter than the beat period would
    declare every healthy worker dead)."""
    v = envknob.env_float("PLUSS_HEARTBEAT_TIMEOUT_S", 5.0, 0.05)
    return max(v, 2 * heartbeat_interval_s())


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               connect_timeout_s: float = 60.0,
               max_retries: int = 3,
               backoff_s: float = 1.0) -> None:
    """``jax.distributed.initialize`` with bounded retry + backoff.

    With no arguments, JAX auto-detects the cluster environment (TPU pod
    metadata, SLURM, GKE); explicit arguments cover manual bring-up.  Safe to
    call once per process, before any other JAX API touches a backend.

    Bring-up races (workers starting before the coordinator binds) and
    transient DCN failures retry up to ``max_retries`` times with
    exponential backoff; a terminal failure raises
    :class:`~pluss.resilience.errors.CollectiveError` naming the attempt
    count instead of a raw RPC exception.
    """
    from pluss.obs import telemetry as obs_telemetry

    if process_id is not None and process_id != 0 \
            and os.environ.get("PLUSS_TELEMETRY"):
        # explicit bring-up names this worker's index up front: re-aim
        # its telemetry sink NOW, before anything below (including the
        # chaos injector's fault counters) can lazily bootstrap the
        # SHARED coordinator path and truncate the coordinator's stream
        obs.configure(f"{os.environ['PLUSS_TELEMETRY']}.p{process_id}")
    # auto-detected clusters don't know their index until init completes:
    # HOLD the lazy env bootstrap through bring-up (pre-init telemetry —
    # e.g. a chaos fault at multihost.init — is dropped rather than
    # truncating the shared path), then re-aim and resume
    suspend = process_id is None and not obs_telemetry.configured() \
        and bool(os.environ.get("PLUSS_TELEMETRY"))
    if suspend:
        obs_telemetry.suspend_env_bootstrap()
    kwargs = dict(coordinator_address=coordinator_address,
                  num_processes=num_processes, process_id=process_id)
    last: BaseException | None = None
    t_init = time.monotonic()
    try:
        for attempt in range(max_retries):
            try:
                faults.check("multihost.init")   # chaos injection site
                try:
                    jax.distributed.initialize(
                        initialization_timeout=int(connect_timeout_s),
                        **kwargs)
                except TypeError:
                    # older jax: no initialization_timeout parameter
                    jax.distributed.initialize(**kwargs)
                if suspend:
                    suspend = False
                    obs_telemetry.resume_env_bootstrap()
                # per-process telemetry sink FIRST (before this function's
                # own counters can bootstrap a shared-path session), then
                # the bring-up metrics
                _per_process_sink()
                obs.counter_add("multihost.init_attempts", attempt + 1)
                obs.counter_add("multihost.init_s",
                                time.monotonic() - t_init)
                return
            except BaseException as e:  # noqa: BLE001 — classified below
                if isinstance(e, (KeyboardInterrupt, SystemExit)):
                    raise
                last = e
                if attempt + 1 < max_retries:
                    delay = backoff_s * (2 ** attempt)
                    print(f"multihost: initialize attempt {attempt + 1}/"
                          f"{max_retries} failed ({e}); retrying in "
                          f"{delay:.1f}s", flush=True)
                    time.sleep(delay)
    finally:
        if suspend:
            obs_telemetry.resume_env_bootstrap()
    err = classify(last, site="multihost.init")
    err.args = (f"distributed initialize failed after {max_retries} "
                f"attempts: {err.args[0]}",)
    raise err


def _per_process_sink() -> None:
    """Give every non-coordinator process its own telemetry file.

    The sink truncates its path on open, so N workers inheriting one
    ``PLUSS_TELEMETRY`` path would clobber each other's (and the
    coordinator's) stream.  Called right after ``jax.distributed``
    bring-up — before any telemetry in this process has bootstrapped, as
    long as the caller follows the documented order (initialize first) —
    it re-aims process ``i > 0`` at ``<path>.p<i>``; the coordinator
    keeps the bare path, so ``pluss stats <path>`` reads the
    coordinator's stream as before.
    """
    path = os.environ.get("PLUSS_TELEMETRY")
    if not path or jax.process_count() <= 1 or jax.process_index() == 0:
        return
    target = f"{path}.p{jax.process_index()}"
    tel = obs.active()
    if tel is not None and tel.path == target:
        return   # already re-aimed (explicit process_id at bring-up)
    obs.configure(target)


def global_mesh(axis: str = "d") -> Mesh:
    """1-D mesh over every device of every participating process.

    ``shard_run`` shards stream windows over this axis; each process feeds
    the same (replicated) inputs, per JAX's multi-process SPMD model.
    """
    return Mesh(np.asarray(jax.devices()), (axis,))


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    """True on the process that should own printing/IO (process 0)."""
    return jax.process_index() == 0


# ---------------------------------------------------------------------------
# liveness: file heartbeats + watchdog.  Collectives have no failure
# detection — a dead peer hangs all_gather/psum forever — so liveness runs
# out-of-band on storage every participant can reach (the coordinator's
# working dir under single-host tests; NFS/GCS-fuse in real clusters).

def _hb_path(hb_dir: str, process_index: int) -> str:
    return os.path.join(hb_dir, f"hb.{process_index}.json")


def start_heartbeat(hb_dir: str, process_index: int | None = None,
                    interval_s: float | None = None):
    """Write ``hb.<i>.json`` every ``interval_s`` from a daemon thread.

    ``interval_s`` defaults to :func:`heartbeat_interval_s`
    (``PLUSS_HEARTBEAT_S``, 0.5 s).  Returns a zero-argument ``stop()``
    callable.  The beat payload carries a monotonic-ish wall timestamp and
    the beat count; staleness is judged by :func:`dead_workers` against
    file mtime, so clock skew between hosts only matters at
    shared-filesystem granularity.

    This is also the ``kill_worker`` chaos site: a fault plan entry
    ``kill_worker@i`` hard-exits process ``i`` from inside its heartbeat
    thread (``os._exit(43)`` — no cleanup, exactly like a SIGKILL'd or
    OOM-killed worker).
    """
    pid = jax.process_index() if process_index is None else process_index
    if interval_s is None:
        interval_s = heartbeat_interval_s()
    os.makedirs(hb_dir, exist_ok=True)
    stop = threading.Event()

    def beat() -> None:
        n = 0
        while not stop.is_set():
            tmp = _hb_path(hb_dir, pid) + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"t": time.time(), "n": n, "pid": os.getpid()}, f)
            os.replace(tmp, _hb_path(hb_dir, pid))
            n += 1
            # chaos kill AFTER the first beat lands: a worker that dies
            # mid-run (the realistic shape) has beaten at least once, so
            # the coordinator's watchdog is already armed when it stops
            if faults.should_kill("multihost.heartbeat", pid):
                os._exit(43)
            stop.wait(interval_s)

    t = threading.Thread(target=beat, daemon=True, name=f"pluss-hb-{pid}")
    t.start()

    def stopper() -> None:
        stop.set()
        t.join(timeout=5)

    return stopper


#: last heartbeat-age gauge publication (monotonic): watchdogs poll
#: dead_workers at 4 Hz for the whole run, so gauges are sampled at most
#: once per beat interval — liveness VERDICTS stay per-poll, only the
#: telemetry sampling is throttled (58k flushed records per half-hour run
#: otherwise, plausibly onto NFS)
_last_age_gauge = 0.0


def dead_workers(hb_dir: str, num_processes: int,
                 stale_s: float | None = None) -> list[int]:
    """Process indices whose heartbeat is missing or older than ``stale_s``
    (default :func:`heartbeat_timeout_s`, ``PLUSS_HEARTBEAT_TIMEOUT_S``).

    A missing file within the first ``stale_s`` of observation counts as
    dead only after the grace window — callers should begin watching only
    once all workers have beaten at least once (watched_shard_run waits
    for first beats before arming the watchdog).

    Each worker's heartbeat age is published as a telemetry gauge
    (``multihost.heartbeat_age_s.<i>``; a missing file gauges -1), sampled
    at most once per beat interval, so liveness is an observable trend,
    not only a boolean verdict.
    """
    global _last_age_gauge
    if stale_s is None:
        stale_s = heartbeat_timeout_s()
    obs_on = obs.enabled()
    if obs_on:
        mono = time.monotonic()
        if mono - _last_age_gauge < heartbeat_interval_s():
            obs_on = False
        else:
            _last_age_gauge = mono
    now = time.time()
    dead = []
    for i in range(num_processes):
        p = _hb_path(hb_dir, i)
        try:
            age = now - os.path.getmtime(p)
        except OSError:
            if obs_on:
                obs.gauge_set(f"multihost.heartbeat_age_s.{i}", -1.0)
            dead.append(i)
            continue
        if obs_on:
            obs.gauge_set(f"multihost.heartbeat_age_s.{i}", round(age, 3))
        if age > stale_s:
            dead.append(i)
    return dead


def start_heartbeat_exporter(hb_dir: str, num_processes: int,
                             interval_s: float | None = None):
    """Long-poll exporter: refresh the ``multihost.heartbeat_age_s.<i>``
    gauges (via :func:`dead_workers`) AND rewrite the Prometheus textfile
    on a timer, so fleet health is scrapeable from a RUNNING daemon — the
    shutdown-time-only export left a long-lived ``pluss serve`` process
    invisible to a scraper for its whole life (recorded PR-5 follow-up).

    ``interval_s`` defaults to ``PLUSS_PROM_REFRESH_S`` (5 s, floored at
    the heartbeat interval — refreshing faster than beats arrive only
    re-publishes the same ages).  The textfile rewrite needs a configured
    ``PLUSS_PROM`` path; without one the timer still refreshes the gauges
    into the event stream.  Returns a ``stop()`` callable (idempotent,
    joins the thread); the thread is a daemon, so a forgotten stop never
    blocks process exit.  Failures inside one tick are swallowed after a
    one-line notice — an exporter must never take down the daemon it
    observes."""
    if interval_s is None:
        interval_s = envknob.env_float("PLUSS_PROM_REFRESH_S", 5.0, 0.1)
    interval_s = max(interval_s, heartbeat_interval_s())
    stop_ev = threading.Event()
    warned = [False]

    def tick() -> None:
        try:
            dead_workers(hb_dir, num_processes)
            tel = obs.active()
            if tel is not None and tel.prom_path:
                tel.write_prom()
        except Exception as e:  # noqa: BLE001 — observer must not kill host
            if not warned[0]:
                warned[0] = True
                import sys

                print(f"multihost: heartbeat exporter tick failed ({e}); "
                      "continuing", file=sys.stderr)

    def loop() -> None:
        while not stop_ev.wait(interval_s):
            tick()

    t = threading.Thread(target=loop, name="pluss-hb-exporter", daemon=True)
    t.start()

    def stop() -> None:
        stop_ev.set()
        t.join(timeout=5)
        tick()   # one final refresh so the textfile reflects shutdown state

    return stop


def watched_shard_run(spec, cfg=None, share_cap: int | None = None,
                      mesh: Mesh | None = None, *,
                      hb_dir: str, num_processes: int | None = None,
                      timeout_s: float = 60.0,
                      stale_s: float | None = None,
                      first_beat_timeout_s: float = 30.0,
                      salvage: bool = True, **kw):
    """``shard_run`` under a worker-death watchdog.

    Runs the SPMD call in a daemon thread; the main thread polls the
    heartbeat directory.  If a worker stops beating (or the run exceeds
    ``timeout_s``), the hung collective is ABANDONED (daemon thread — a
    dead peer makes it unjoinable by design) and:

    - on the coordinator with ``salvage=True``: the run is recomputed on
      LOCAL devices only via ``engine.run`` — semantically identical
      (tests assert bit-equality), stamped
      ``degradations=('worker_died:<ids>', 'local_salvage')``;
    - otherwise :class:`WorkerDied` is raised, naming the dead processes.

    The watchdog only arms after every worker has produced a first beat
    (bounded by ``first_beat_timeout_s``), so slow bring-up is not
    mistaken for death.

    ``**kw`` forwards to :func:`shard_run` — including ``dispatch=``:
    under multi-process execution the ``auto`` default resolves to the
    static collectives-only program (the only mode a watchdog over DCN
    collectives is FOR; the single-process work-stealing dispatcher has
    no hangable collective and needs no watchdog), and the subprocess
    salvage path is dispatch-agnostic because ``shard_run`` ≡
    ``engine.run`` bit-for-bit in every mode.
    """
    from pluss.config import DEFAULT, SHARE_CAP
    from pluss.parallel.shard import shard_run

    cfg = cfg if cfg is not None else DEFAULT
    share_cap = share_cap or SHARE_CAP
    nproc = num_processes or process_count()
    if stale_s is None:
        stale_s = heartbeat_timeout_s()
    box: dict = {}

    def target() -> None:
        t0 = time.monotonic()
        try:
            box["res"] = shard_run(spec, cfg, share_cap, mesh, **kw)
            # the SPMD wall clock — collectives included — of the watched
            # run; a hung collective never records one (the span + death
            # event carry that story instead)
            obs.counter_add("multihost.shard_run_s",
                            time.monotonic() - t0)
        except BaseException as e:  # noqa: BLE001 — classified by consumer
            box["err"] = e

    t = threading.Thread(target=target, daemon=True,
                         name="pluss-watched-shard-run")
    t.start()

    deadline = time.time() + timeout_s
    armed = False
    arm_deadline = time.time() + first_beat_timeout_s
    dead: list[int] = []
    while t.is_alive() and time.time() < deadline:
        if not armed:
            if not dead_workers(hb_dir, nproc, stale_s=1e18):
                armed = True   # every worker has beaten at least once
            elif time.time() > arm_deadline:
                armed = True   # never-beaten workers now count as dead
        if armed:
            dead = dead_workers(hb_dir, nproc, stale_s)
            if dead:
                break
        t.join(timeout=0.25)
    if not t.is_alive():
        if "err" in box:
            # a peer death often surfaces as a collective ERROR rather
            # than a hang (runtime-dependent); give the liveness channel
            # one staleness window to attribute it before concluding the
            # computation itself was at fault.  Only workers that HAVE
            # beaten can be declared dead here — a missing first beat
            # (slow shared-storage propagation during bring-up) must not
            # let a fast compile error masquerade as a worker death
            grace = time.time() + stale_s + 2.0
            while not dead and time.time() < grace:
                dead = [i for i in dead_workers(hb_dir, nproc, stale_s)
                        if os.path.exists(_hb_path(hb_dir, i))]
                if dead:
                    break
                time.sleep(0.25)
            if not dead:
                raise classify(box["err"], site="shard.run")
        else:
            return box["res"]
    if not dead:   # run still alive but over the deadline: recheck liveness
        dead = dead_workers(hb_dir, nproc, stale_s)
    err = WorkerDied(
        f"worker(s) {dead or '<unknown>'} stopped heartbeating; "
        f"abandoning the hung collective", site="multihost.watch",
        process_ids=tuple(dead))
    obs.counter_add("multihost.worker_deaths", max(1, len(dead)))
    obs.event("multihost.worker_died", processes=list(dead),
              model=getattr(spec, "name", "?"))
    if salvage and is_coordinator():
        print(f"multihost: {err}; salvaging in a clean subprocess",
              flush=True)
        obs.counter_add("multihost.salvages")
        res = _salvage_subprocess(spec, cfg, share_cap,
                                  kw.get("window_accesses"),
                                  kw.get("assignment"),
                                  kw.get("start_point"))
        res.degradations = (
            f"worker_died:{','.join(map(str, dead)) or '?'}",
            "local_salvage")
        return res
    raise err


def _salvage_subprocess(spec, cfg, share_cap: int,
                        window_accesses: int | None,
                        assignment=None, start_point: int | None = None,
                        timeout_s: float = 600.0):
    """Recompute ``engine.run`` in a FRESH single-process interpreter.

    The salvage cannot run in-process: the abandoned collective still
    occupies the wedged PJRT execution queue (a salvage ``engine.run`` on
    the same backend would block behind it), and jax's coordination
    service will eventually hard-abort a process whose peer died.  A
    clean CPU subprocess has neither problem; spec/cfg/result travel by
    pickle (both are plain dataclasses).  Semantically identical to the
    sharded run — ``shard_run`` ≡ ``engine.run`` is the backend
    equivalence the parallel test suite asserts bit-for-bit.
    """
    import pickle
    import subprocess
    import sys
    import tempfile

    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    with tempfile.TemporaryDirectory() as td:
        inp, outp = os.path.join(td, "in.pkl"), os.path.join(td, "out.pkl")
        with open(inp, "wb") as f:
            # the FULL run coordinate travels: a salvage that silently
            # dropped assignment/start_point would return a result for a
            # different schedule than the caller asked for
            pickle.dump({"spec": spec, "cfg": cfg, "share_cap": share_cap,
                         "window_accesses": window_accesses,
                         "assignment": assignment,
                         "start_point": start_point}, f)
        code = (
            "import pickle, sys\n"
            "from pluss.utils.platform import force_cpu, enable_x64\n"
            "force_cpu(); enable_x64()\n"
            "from pluss import engine\n"
            "p = pickle.load(open(sys.argv[1], 'rb'))\n"
            "res = engine.run(p['spec'], p['cfg'], p['share_cap'],\n"
            "                 assignment=p['assignment'],\n"
            "                 start_point=p['start_point'],\n"
            "                 window_accesses=p['window_accesses'])\n"
            "pickle.dump(res, open(sys.argv[2], 'wb'))\n"
        )
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": repo + os.pathsep
               + os.environ.get("PYTHONPATH", "")}
        # the child must NOT rejoin the dead cluster — and must not open
        # the coordinator's LIVE telemetry sink (Telemetry truncates its
        # path on open: the child would destroy the very stream recording
        # this salvage) or its profiler session
        for var in ("JAX_COORDINATOR_ADDRESS", "XLA_FLAGS",
                    "PLUSS_FAULT_PLAN", "PLUSS_TELEMETRY", "PLUSS_PROM",
                    "PLUSS_XPROF"):
            env.pop(var, None)
        proc = subprocess.run(
            [sys.executable, "-c", code, inp, outp],
            env=env, capture_output=True, text=True, timeout=timeout_s)
        if proc.returncode != 0:
            raise WorkerDied(
                "local salvage subprocess failed: "
                f"{proc.stderr[-500:]}", site="multihost.salvage")
        with open(outp, "rb") as f:
            return pickle.load(f)
