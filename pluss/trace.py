"""Dynamic trace replay: reuse histograms from raw address streams.

The live reference samples *statically* (no trace), but its runtime keeps a
disabled trace-driven API — ``pluss_access(addr)`` masking addresses to cache
lines and probing a global last-access map (``/root/reference/c_lib/test/
runtime/pluss.cpp:126-402``, ``CACHE_MASK`` at :13) — and BASELINE.json
config 5 calls for replaying raw DynamoRIO-style memory traces at 1e9 refs.

TPU-native design: the same windowed sort-based extraction as the static
engine (:mod:`pluss.ops.reuse`), fed by a *compacted* line-id stream instead of
affine enumeration:

1. Host pass: mask raw byte addresses to cache lines (``addr >> log2(CLS)``)
   and remap to dense ids — small line ranges map by offset directly; sparse
   traces go through cluster probing (discovered memory regions with slack id
   space; only cluster MISSES are ever sorted) — the TPU equivalent of the
   reference's unbounded ``unordered_map`` LAT over raw lines, in bounded
   memory.
2. Device kernel: the whole ``[batch_windows * window]`` batch is one
   segmented sort-based reuse extraction (:func:`pluss.ops.reuse.batch_events`
   — one stable key sort, one carried gather, one tail scatter, PARDA/SHARDS
   style) carrying ``last_pos[line]`` + the dense histogram across batches —
   arbitrarily long streams in bounded device memory (donated carry).  The
   pre-round-6 per-window ``lax.scan`` formulation stays the default on
   the CPU backend (where the single-threaded big sort loses) and remains
   available everywhere via ``segmented=False`` / ``PLUSS_TRACE_SEGMENTED``
   for A/B verification (bit-identical histograms by construction;
   asserted by the property suite, tests/test_trace_property.py).

A replayed trace is single-clock (one logical time per access, the reference's
``pluss_access`` semantics), so the result feeds :func:`pluss.mrc.aet_mrc`
directly — no CRI dilation, exactly like the reference's trace path, which
bypasses the CRI model entirely.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from pluss import obs
from pluss.config import NBINS
from pluss.obs import xprof
from pluss.ops.reuse import (
    batch_events,
    bin_histogram,
    event_histogram,
    log2_bin,
    sort_stream,
    window_events,
)

#: default accesses per device window; 2^20 wins the sort-cost vs
#: scan-step-count tradeoff on TPU (measured 2026-07-30)
TRACE_WINDOW = 1 << 20


def lines_of(addrs: np.ndarray, cls: int = 64) -> np.ndarray:
    """Mask byte addresses to cache-line ids (the reference's CACHE_MASK
    shift, pluss.cpp:13,137)."""
    if cls & (cls - 1):
        raise ValueError(f"cache line size {cls} is not a power of two")
    return np.asarray(addrs, np.int64) >> int(cls).bit_length() - 1


@dataclasses.dataclass
class ReplayResult:
    """Dense log2 reuse histogram of one replayed stream.

    ``hist[0]`` = cold (first-touch) count, ``hist[1+e]`` = reuses in
    [2^e, 2^{e+1}).  ``histogram()`` returns the reference-keyed dict view
    (cold key -1), directly consumable by :func:`pluss.mrc.aet_mrc`.
    """

    hist: np.ndarray          # [NBINS] int64
    total_count: int
    n_lines: int
    #: degradation-ladder rungs taken (pluss.resilience) — empty for a
    #: clean first-attempt replay
    degradations: tuple = ()
    #: effective streamed-feed configuration of the run that produced
    #: this result (:func:`replay_file` stamps both; consumers that
    #: record the measurement setup — bench — read them off the result
    #: instead of re-resolving process defaults, which a degradation
    #: rung or backend flip may have left behind).  Empty/0 from
    #: constructors with no streamed feed.
    wire: str = ""
    feed_workers: int = 0

    def histogram(self) -> dict:
        out = {-1: float(self.hist[0])}
        for e in range(NBINS - 1):
            if self.hist[1 + e]:
                out[1 << e] = float(self.hist[1 + e])
        return out


def _env_int(name: str, default: int, minimum: int = 1) -> int:
    """An integer env knob, parsed leniently (warn + fall back, never
    crash an import or an hours-long replay) — the shared policy lives
    in :mod:`pluss.utils.envknob`.  Explicit kwargs keep their loud
    validation at the use sites (:func:`_resolve_bw`, the queue-depth
    check)."""
    from pluss.utils.envknob import env_int

    return env_int(name, default, minimum)


#: default windows shipped to the device per batch; one compile serves a
#: trace of any length because every batch has the same
#: [batch_windows, window] shape.  Raised 8 -> 16 with the segmented batch
#: kernel (one sort per batch means bigger batches amortize dispatch +
#: table-touch cost instead of lengthening a scan chain).  Overridable
#: per-process via PLUSS_BATCH_WINDOWS, per-call via the ``batch_windows``
#: kwarg, and on the CLI via ``pluss trace --batch-windows``.
WINDOWS_PER_BATCH = _env_int("PLUSS_BATCH_WINDOWS", 16)


def _tuned(field: str):
    """The autotuned geometry's value for one replay knob, or None.
    Consulted LAST in every default resolution — explicit kwargs and
    PLUSS_* env overrides always win; the tuned value only replaces the
    shipped backend guess (:mod:`pluss.autotune`)."""
    from pluss import autotune

    return autotune.consult(field)


def _resolve_window(window: int | None) -> int:
    """The effective replay window: explicit kwarg > autotuned geometry
    > :data:`TRACE_WINDOW`.  Histograms are window-invariant (PR-4:
    reuse gaps are partition-invariant), so the tuned value is purely a
    throughput knob — but it IS part of the checkpoint identity, so it
    resolves once, up front."""
    if window is not None:
        w = int(window)
        if w < 1:
            raise ValueError(f"window must be >= 1, got {w}")
        return w
    t = _tuned("window")
    return int(t) if t else TRACE_WINDOW


def _resolve_bw(batch_windows: int | None) -> int:
    """The effective windows-per-batch, validated.  A non-positive value
    must fail loudly here: ``batch_windows=-4`` would otherwise return an
    all-zero histogram that still claims full coverage (zero batches
    dispatched), and 0 would silently alias the default.  Default chain:
    kwarg > PLUSS_BATCH_WINDOWS > autotuned geometry > 16."""
    if batch_windows is None:
        bw = None
        if "PLUSS_BATCH_WINDOWS" not in os.environ:
            bw = _tuned("batch_windows")
        bw = int(bw) if bw else WINDOWS_PER_BATCH
    else:
        bw = int(batch_windows)
    if bw < 1:
        raise ValueError(f"batch_windows must be >= 1, got {bw}")
    return bw


def _resolve_stage_depth(stage_depth: int | None) -> int:
    """Staged-ahead device batches: kwarg > PLUSS_TRACE_STAGE_DEPTH >
    autotuned geometry > 2 (the classic double buffer)."""
    if stage_depth is None:
        if "PLUSS_TRACE_STAGE_DEPTH" not in os.environ:
            t = _tuned("stage_depth")
            if t:
                return int(t)
        return _env_int("PLUSS_TRACE_STAGE_DEPTH", 2)
    sd = int(stage_depth)
    if sd < 1:
        # depth 0 would stage nothing and replay zero batches while
        # claiming success — same failure class as batch_windows<1
        raise ValueError(f"stage_depth must be >= 1, got {sd}")
    return sd


def _resolve_queue_depth(queue_depth: int | None) -> int:
    """Feed queue bound: kwarg > PLUSS_TRACE_QUEUE_DEPTH > autotuned
    geometry > 2."""
    if queue_depth is None:
        if "PLUSS_TRACE_QUEUE_DEPTH" not in os.environ:
            t = _tuned("queue_depth")
            if t:
                return int(t)
        return _env_int("PLUSS_TRACE_QUEUE_DEPTH", 2)
    qd = int(queue_depth)
    if qd < 1:
        # queue.Queue(maxsize=0) means UNBOUNDED — the reader would buffer
        # the whole trace and break the bounded-host-memory contract
        raise ValueError(f"queue_depth must be >= 1, got {qd}")
    return qd


def _segmented_default() -> bool:
    """Whole-batch segmented kernel by default on accelerators, where one
    big parallel sort beats a serial window chain; on the CPU backend the
    legacy per-window scan stays the default (the single-threaded sort
    makes segmented ~1.3x slower there — PARITY.md round-6 A/B).
    PLUSS_TRACE_SEGMENTED overrides either way (=1 forces segmented on
    CPU, =0 forces the scan on an accelerator)."""
    env = os.environ.get("PLUSS_TRACE_SEGMENTED")
    if env is not None:
        return env.lower() not in ("0", "false", "off", "")
    return jax.default_backend() != "cpu"


#: streamed-feed wire selector (``--wire`` / ``PLUSS_WIRE`` / the
#: ``wire`` kwarg): ``pack`` = the fixed-width u16/u24/i32 packs,
#: ``d24v`` = the delta+zigzag+nibble bit-packed compressed wire
#: (:mod:`pluss.ops.wirecodec`, decoded on device), ``auto`` = d24v on
#: accelerators (the PCIe/tunnel bytes ARE the streamed bottleneck —
#: BENCH_r04/r05 ``upload_mb_s``), plain pack on the CPU backend (no
#: transport to compress for, and the decode gathers would only add
#: host work).  Histograms are wire-invariant by construction; the
#: property suite pins it.
WIRE_CHOICES = ("auto", "pack", "d24v")


def _resolve_wire(wire: str | None) -> str:
    """The effective wire format.  Explicit bad values fail loudly; a
    malformed PLUSS_WIRE warns and falls back (envknob policy)."""
    if wire is None:
        from pluss.utils.envknob import env_choice

        wire = env_choice("PLUSS_WIRE", "auto", WIRE_CHOICES)
    if wire not in WIRE_CHOICES:
        raise ValueError(
            f"unknown wire format {wire!r} (choices: "
            f"{', '.join(WIRE_CHOICES)})")
    if wire == "auto":
        t = _tuned("wire")
        if t in ("pack", "d24v"):
            return t
        return "d24v" if jax.default_backend() != "cpu" else "pack"
    return wire


def _default_feed_workers() -> int:
    """Backend-aware default for the reader/packer pool: on the CPU
    backend the replay kernel computes on the same cores, so extra feed
    threads only oversubscribe the box the tier-1 suites run on —
    default 1 (the single-reader pipeline).  On accelerators the host
    cores idle while the device computes; use most of them."""
    if jax.default_backend() == "cpu":
        return 1
    ncpu = os.cpu_count() or 1
    return max(2, min(8, ncpu - 1))


def _resolve_feed_workers(feed_workers: int | None) -> int:
    """Validated reader/packer worker count.  An explicit 0/-1 must fail
    loudly (a zero-worker pool would deliver nothing and hang the feed);
    a malformed PLUSS_FEED_WORKERS warns and falls back to the backend
    default, same as every other env knob."""
    if feed_workers is None:
        if "PLUSS_FEED_WORKERS" not in os.environ:
            t = _tuned("feed_workers")
            if t:
                return int(t)
        return _env_int("PLUSS_FEED_WORKERS", _default_feed_workers())
    fw = int(feed_workers)
    if fw < 1:
        raise ValueError(f"feed_workers must be >= 1, got {fw}")
    return fw


class _threaded:
    """Run a generator in a daemon thread behind a bounded queue.

    ``with _threaded(gen_fn) as it:`` yields the generator's items in
    order; generator exceptions re-raise at the consumer; leaving the
    context releases a producer blocked on a full queue.
    """

    _DONE = object()

    def __init__(self, gen_fn, depth: int = 2):
        import queue
        import threading

        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._t = threading.Thread(
            target=self._run, args=(gen_fn,), daemon=True)

    def _put(self, item) -> bool:
        import queue

        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def _run(self, gen_fn):
        try:
            for item in gen_fn():
                if not self._put(item):
                    return
            self._put(self._DONE)
        except BaseException as e:
            self._put(e)

    def __enter__(self):
        self._t.start()
        return self

    def qsize(self) -> int:
        """Instantaneous queue occupancy (telemetry gauge: a persistently
        EMPTY queue means the consumer is starved — the feed is the
        bottleneck; persistently full means the device is)."""
        return self._q.qsize()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._DONE:
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item

    def __exit__(self, *exc):
        self._stop.set()
        self._t.join(timeout=60)
        return False


class _FeedPool:
    """Ordered N-worker feed pipeline: read (parallel) → compact
    (stream-order turnstile) → wire-encode (parallel) → strict in-order
    delivery.

    The single reader thread (:class:`_threaded`) tops out at the
    sequential read+compact+pack rate — 23-33 MB/s recorded
    (BENCH_r04/r05 ``upload_mb_s``) against a device kernel holding
    ~6.8e7 refs/s resident, the 37x streamed-vs-resident gap.  Batch
    extents are independent on disk and the pack/encode is
    embarrassingly parallel per extent, so N workers overlap them; only
    the compactor stage is order-dependent (cluster discovery mutates
    shared state and is part of the checkpoint identity), so it runs
    under a turnstile admitting batches in exact stream order.  numpy
    reads and packs release the GIL, so the overlap is real under
    CPython.

    Delivery is strictly in batch order, and a worker exception is
    delivered at ITS batch index — after every earlier batch — so fault
    injection and checkpoint/resume keep the same prefix semantics as
    the single reader.  ``claim_fn(b)`` runs under the claim lock in
    exact batch order: the chaos-injection site lives there, so
    ``trace_loss@n`` keeps firing on the n-th *stream* batch, not on
    whichever worker races to the site first.  In-flight batches
    (claimed but not yet consumed) are bounded by ``depth + workers``.
    """

    def __init__(self, b0: int, end: int, claim_fn, read_fn, compact_fn,
                 encode_fn, workers: int, depth: int):
        import threading

        from pluss.obs import tracectx

        # serve attribution: the pool is built on the replay thread,
        # which runs under the request's trace context — capture it here
        # so every worker's spans/events resolve to the same request
        self._trace_token = tracectx.capture()
        self._end = end
        self._claim_fn, self._read_fn = claim_fn, read_fn
        self._compact_fn, self._encode_fn = compact_fn, encode_fn
        self.workers = workers
        self._budget = depth + workers
        self._cv = threading.Condition()
        self._next_claim = b0
        self._turn = b0
        self._next_out = b0
        self._done: dict[int, object] = {}
        self._stop = False
        self.busy = 0          # workers mid-batch (telemetry gauge)
        self.encode_s = 0.0    # summed wire-encode seconds across workers
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"pluss-feed-{i}")
            for i in range(workers)]

    def __enter__(self):
        for t in self._threads:
            t.start()
        return self

    def __exit__(self, *exc):
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=60)
        return False

    def qsize(self) -> int:
        """Finished batches awaiting in-order delivery (the occupancy
        gauge: persistently zero means the feed is the bottleneck)."""
        return len(self._done)

    def __iter__(self):
        return self

    def __next__(self):
        with self._cv:
            if self._next_out >= self._end:
                raise StopIteration
            while self._next_out not in self._done:
                if not any(t.is_alive() for t in self._threads):
                    raise RuntimeError(
                        f"feed pool lost batch {self._next_out}: all "
                        "workers exited without delivering it")
                self._cv.wait(0.5)
            item = self._done.pop(self._next_out)
            self._next_out += 1
            self._cv.notify_all()
        if isinstance(item, BaseException):
            raise item
        return item

    def _run(self):
        import time as _time

        from pluss.obs import tracectx

        with tracectx.attach(self._trace_token):
            self._run_inner(_time)

    def _run_inner(self, _time):
        while True:
            err = None
            with self._cv:
                while (not self._stop and self._next_claim < self._end
                       and self._next_claim - self._next_out
                       >= self._budget):
                    self._cv.wait(0.5)
                if self._stop or self._next_claim >= self._end:
                    return
                b = self._next_claim
                self._next_claim += 1
                self.busy += 1
                try:
                    self._claim_fn(b)   # ordered under the lock
                except BaseException as e:
                    err = e
            raw = mid = item = None
            if err is None:
                try:
                    raw = self._read_fn(b)
                except BaseException as e:
                    err = e
            # compact turnstile: strictly in stream order.  A FAILED
            # batch still takes and releases its turn — later batches
            # (doomed to be discarded once the error is delivered at b)
            # must not deadlock behind it.
            with self._cv:
                while not self._stop and self._turn != b:
                    self._cv.wait(0.5)
                if self._stop:
                    self.busy -= 1
                    return
            if err is None:
                try:
                    mid = self._compact_fn(b, raw)
                except BaseException as e:
                    err = e
            with self._cv:
                self._turn = b + 1
                self._cv.notify_all()
            enc = 0.0
            if err is None:
                t0 = _time.perf_counter()
                try:
                    item = self._encode_fn(b, mid)
                except BaseException as e:
                    err = e
                enc = _time.perf_counter() - t0
            with self._cv:
                self.busy -= 1
                if err is None:
                    self.encode_s += enc
                self._done[b] = err if err is not None else item
                self._cv.notify_all()


#: packed-trace wire-format version, stamped in pack_file's sidecar.  Bump
#: whenever the on-disk id encoding (u16/u24/i32 packing, byte order, the
#: compaction semantics feeding it) changes meaning — consumers that cache
#: packed traces across runs (bench.py) key on it so a stale pack from an
#: older format can never be replayed silently.
WIRE_VERSION = 1


def _pack24(ids: np.ndarray) -> np.ndarray:
    """[n] int32 line ids < 2^24 -> [n, 3] little-endian bytes.

    The tunneled-TPU h2d path runs at tens of MB/s, so trace replay is
    transfer-bound end-to-end (device compute is ~25x faster than the
    feed); shipping 3 bytes/ref instead of 4 is a direct 4/3 speedup.
    The device widens the bytes back in :func:`_replay_fn` — negligible
    next to the batch sort.  Vectorized as one little-endian int32
    reinterpret + a single strided copy dropping the high byte: two passes
    over the data instead of three masked shift/store passes — the pack
    runs on the host core shared with the PJRT client and must never gate
    the overlapped h2d feed.
    """
    b4 = np.ascontiguousarray(ids, dtype="<i4").view(np.uint8)
    return np.ascontiguousarray(b4.reshape(-1, 4)[:, :3])


def _pack16(ids: np.ndarray) -> np.ndarray:
    """[n] int32 line ids < 2^16 -> u16.  Same rationale as :func:`_pack24`
    (the h2d feed bounds replay end-to-end); for traces whose working set
    fits 65,536 line slots this halves the bytes vs the int32 feed and is
    2/3 of the 24-bit pack.  The device widens u16 back in the replay step."""
    return ids.astype(np.uint16)


def _pack_ids(ids: np.ndarray, n_lines: int) -> np.ndarray:
    """Tightest FIXED-WIDTH wire format the line-table size allows (the
    ``pack`` wire; :func:`_encode_wire` layers the content-adaptive
    ``d24v`` compression on top)."""
    if n_lines <= 1 << 16:
        return _pack16(ids)
    if n_lines < 1 << 24:
        return _pack24(ids)
    return ids


#: one d24v-encoded batch as it rides the feed queue (host numpy arrays
#: until the staging step device_puts them as a pytree)
_WireD24V = collections.namedtuple("_WireD24V", ("payload", "wm"))

#: batches above this many ids stay on the plain pack even under
#: ``wire=d24v``: the decode kernel's bit-offset math is int32
_D24V_MAX_BATCH = 1 << 26


def _encode_wire(ids: np.ndarray, n_lines: int, wirefmt: str):
    """One padded batch slice -> what ships over the h2d transport: a
    :class:`_WireD24V` under the compressed wire (tables under 2^24
    lines), else the fixed-width pack."""
    if wirefmt == "d24v" and n_lines < 1 << 24 \
            and ids.shape[0] <= _D24V_MAX_BATCH:
        from pluss.ops import wirecodec

        return _WireD24V(*wirecodec.encode_d24v(ids))
    return _pack_ids(ids, n_lines)


def _extent_reader(path: str, batch: int, n: int):
    """Raw u64 extent reader (shared by the replay and pack feeds).
    Extents are independent on disk, so each read opens its own handle
    (an OS open+seek costs nothing next to a 100+ MB read) — this is
    what lets N feed workers read concurrently.  Never reads past ``n``:
    a limit_refs prefix must not compact (or grow the device table with)
    addresses it will mask out anyway."""
    def read_raw(b):
        with open(path, "rb") as f:
            f.seek(b * batch * 8)
            return np.fromfile(f, dtype="<u8",
                               count=min(batch, n - b * batch))
    return read_raw


def _compact_stage(comp, shift: int, precompacted: bool, snapshot: bool):
    """Raw addresses -> ``(dense ids, table size, compactor snapshot)``
    (STATEFUL: feed pools run this under the stream-order turnstile).
    The snapshot rides WITH the batch so a checkpointing/journaling
    consumer records state consistent with what it has actually
    consumed, even while producers run ahead; ``snapshot=False`` skips
    it for consumers that never persist (it costs a table copy)."""
    def compact_batch(b, raw):
        ids = comp.map_raw(raw, 0 if precompacted else shift)
        if ids is None:
            lines = raw.astype(np.int64) if precompacted \
                else raw.astype(np.int64) >> shift
            ids = comp.map(lines)
        return ids, comp.next_free, comp.snapshot() if snapshot else None
    return compact_batch


def _decode_impl(fused: bool):
    """The d24v decoder implementation behind both jitted wrappers: the
    Pallas VMEM kernel (:mod:`pluss.ops.pallas_decode`) when the fused
    flag resolved on, else the XLA chain — bit-identical by the r19
    equivalence matrix, so the choice is pure throughput."""
    if fused:
        from pluss.ops import pallas_decode

        return pallas_decode.decode_d24v
    from pluss.ops import wirecodec

    return wirecodec.decode_d24v


def _decode_fused() -> bool:
    """Resolve the fused-decode flag OUTSIDE the jitted wrappers (probe
    runs eagerly here, and the memo keys stay honest across env/autotune
    flips mid-process)."""
    from pluss.ops import pallas_decode

    return pallas_decode.enabled()


def _decode_fn(backend: str):
    """Jitted d24v -> int32 expansion (``wirecodec.decode_d24v`` or its
    Pallas twin).  A SEPARATE executable from the replay kernel, so the
    handful of payload shapes (wirecodec.pad_len quantizes them) retrace
    only this small decode — never the batch sort."""
    return _decode_fn_cached(backend, _decode_fused())


@functools.lru_cache(maxsize=8)
def _decode_fn_cached(backend: str, fused: bool):
    return jax.jit(_decode_impl(fused))


def _stage_decode_fn(backend: str):
    """Jitted d24v record -> the resident u24 byte layout: the
    PCIe/tunnel carries the compressed record, HBM holds the same
    3 B/ref layout :func:`replay_staged` already consumes."""
    return _stage_decode_fn_cached(backend, _decode_fused())


@functools.lru_cache(maxsize=8)
def _stage_decode_fn_cached(backend: str, fused: bool):
    decode = _decode_impl(fused)

    def f(payload, wm, count, batch):
        ids = decode(payload, wm)
        ids = jnp.zeros((batch,), jnp.int32).at[:count].set(ids[:count])
        u = ids.astype(jnp.uint32)
        return jnp.stack(
            [u & 0xFF, (u >> 8) & 0xFF, (u >> 16) & 0xFF],
            axis=-1).astype(jnp.uint8)

    return jax.jit(f, static_argnums=(2, 3))


def _widen_ids(line_w):
    """Inverse of :func:`_pack_ids` on device (u8 [n,3] 24-bit | u8 [n,4]
    little-endian int32 | u16 | int32)."""
    if line_w.dtype == jnp.uint8:      # byte-packed (24-bit or LE int32)
        b = line_w.astype(jnp.int32)
        out = b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16)
        if line_w.shape[-1] == 4:      # i32 wire format (ids < 2^31)
            out = out | (b[:, 3] << 24)
        return out
    if line_w.dtype == jnp.uint16:
        return line_w.astype(jnp.int32)
    return line_w


def _replay_fn(window: int, pos_dtype_name: str,
               segmented: bool | None = None):
    """Batched replay step.  Not keyed by the line-table size OR the batch
    width: ``jit`` retraces on a new ``last_pos`` / ids shape, which is
    exactly what the streaming path's geometric table growth (and a
    ``--batch-windows`` override) needs."""
    if segmented is None:
        segmented = _segmented_default()
    # the donation decision is backend-dependent, so the backend is part of
    # the cache key — a force_cpu fallback after an accelerator run must not
    # reuse a donating executable (and vice versa).  The fused-events flag
    # is resolved HERE (outside the jit — the probe may compile) and keyed:
    # an env/autotune flip mid-process retraces instead of replaying the
    # other path's executable.
    from pluss.ops import pallas_events

    return _replay_fn_cached(window, pos_dtype_name, jax.default_backend(),
                             bool(segmented), pallas_events.enabled())


def _scan_batch(last_pos, hist, base, ids, n_valid, window: int, pdt):
    """LEGACY per-window scan of one [batch_windows, window] id batch.

    ids: int32, or [.., window, 3] uint8 (24-bit packed) or [.., window, 4]
    uint8 (LE int32 wire) or uint16 (_pack_ids — the h2d feed is the
    bottleneck); base: batch stream offset; n_valid: total stream length —
    padding is always the stream tail, so validity is just pos < n_valid
    (a scalar ships per batch instead of a [batch] bool array: on a 1-core
    host the numpy staging of big transfers starves the PJRT client thread
    and serializes the pipe).

    Kept behind ``segmented=False`` as the A/B reference for
    :func:`_segmented_batch`: the scan serializes the device into an
    n/window dependency chain, which is why it lost to the native replay
    end-to-end (r05: 0.34x) and was replaced as the default.
    """
    pos = (
        base
        + jnp.arange(ids.shape[0], dtype=pdt)[:, None] * window
        + jnp.arange(window, dtype=pdt)[None, :]
    )
    valid = pos < n_valid

    def step(carry, xs):
        last_pos, hist = carry
        line_w, pos_w, valid_w = xs
        line_w = _widen_ids(line_w)   # u8[n,3|4] / u16 packed feeds
        # trace windows arrive in stream order: stable single-key sort,
        # no span payload (the trace path has no share classification)
        ev, last_pos = window_events(
            *sort_stream(line_w, pos_w, None, valid_w, pos_sorted=True),
            last_pos,
        )
        return (last_pos, hist + event_histogram(ev)), None

    (last_pos, hist), _ = jax.lax.scan(
        step, (last_pos, hist), (ids, pos, valid)
    )
    return last_pos, hist


def _segmented_batch(last_pos, hist, base, ids, n_valid, pdt):
    """Whole-batch segmented reuse kernel (the default since round 6).

    The entire [batch_windows, window] batch is flattened and processed as
    ONE :func:`pluss.ops.reuse.batch_events` call: positions are the
    stream order itself, so a single stable key sort realizes the
    (line, pos) order, every intra-batch reuse is a segment-internal
    position diff computed in parallel, and the persistent ``last_pos``
    table is touched once — one gather resolving first-occurrence heads,
    one scatter writing last-occurrence tails.  The cross-batch dependency
    chain collapses from n/window scan steps to n_batches gather/scatters.
    Bit-identical to :func:`_scan_batch` (reuse gaps are partition-
    invariant; histogram accumulation is integer-exact on both paths).
    """
    flat = ids.reshape((ids.shape[0] * ids.shape[1],) + ids.shape[2:])
    line = _widen_ids(flat)           # u8[n,3|4] / u16 packed feeds
    pos = base + jnp.arange(flat.shape[0], dtype=pdt)
    ev, last_pos = batch_events(line, pos, pos < n_valid, last_pos)
    return last_pos, hist + event_histogram(ev)


@functools.lru_cache(maxsize=None)
def _trace_cache_salt() -> str:
    """Source identity of the replay kernel for AOT sidecar grouping.

    ``engine._plan_cache_salt`` deliberately excludes this module (loop-
    nest plans don't depend on it), so trace sidecars carry their own
    source hash: an edit to the replay step or the reuse kernels
    invalidates every persisted trace executable."""
    import hashlib

    h = hashlib.sha256()
    here = os.path.dirname(os.path.abspath(__file__))
    for name in ("trace.py", os.path.join("ops", "reuse.py"),
                 os.path.join("ops", "pallas_events.py"),
                 os.path.join("ops", "pallas_decode.py")):
        with open(os.path.join(here, name), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


@functools.lru_cache(maxsize=32)
def _replay_fn_cached(window: int, pos_dtype_name: str, backend: str,
                      segmented: bool, fused: bool):
    import hashlib

    pdt = jnp.dtype(pos_dtype_name)

    def run(last_pos, hist, base, ids, n_valid):
        if segmented:
            return _segmented_batch(last_pos, hist, base, ids, n_valid, pdt)
        return _scan_batch(last_pos, hist, base, ids, n_valid, window, pdt)

    # donating the carry keeps last_pos/hist in place on device across
    # batches; the CPU backend does not support donation and would warn once
    # per batch, so donate only off-CPU (there the copy is cheap anyway)
    donate = (0, 1) if backend != "cpu" else ()
    group = hashlib.sha256(repr(
        (_trace_cache_salt(), "trace", window, pos_dtype_name, segmented,
         fused)
    ).encode()).hexdigest()[:32]
    # per-shape AOT over the jit: the replay step retraces on table growth
    # / --batch-windows, so each signature gets its own sidecar slot
    from pluss import plancache

    return plancache.LazyAotFn(
        jax.jit(run, donate_argnums=donate), group,
        ("trace", window, pos_dtype_name, segmented, fused))


def replay(addrs: np.ndarray, cls: int = 64, window: int = TRACE_WINDOW,
           precompacted: bool = False, batch_windows: int | None = None,
           segmented: bool | None = None) -> ReplayResult:
    """Replay a raw address stream into a reuse histogram.

    ``addrs``: 1-D array of byte addresses (or dense line ids when
    ``precompacted`` — e.g. synthetic workloads that already index lines).
    ``batch_windows``/``segmented`` default to the module/env settings
    (:data:`WINDOWS_PER_BATCH`, :func:`_segmented_default`).
    """
    addrs = np.asarray(addrs)
    if addrs.ndim != 1:
        raise ValueError("trace must be a 1-D address stream")
    n = addrs.shape[0]
    if n == 0:
        return ReplayResult(np.zeros(NBINS, np.int64), 0, 0)
    lines = addrs.astype(np.int64) if precompacted else lines_of(addrs, cls)
    ids, n_lines = _compact(lines, window)
    return _replay_ids(ids, n_lines, n, window, batch_windows, segmented)


def _compact(lines: np.ndarray, window: int) -> tuple[np.ndarray, int]:
    """Dense int32 ids + table size for a whole line array.

    Dense-range shortcut: when the touched lines span a small range the
    offset IS the id — no vocabulary pass at all (last_pos is sized by the
    range; untouched slots just stay -1).  Otherwise incremental cluster
    probing (the streaming path with one source)."""
    lo_line, hi_line = int(lines.min()), int(lines.max())
    if hi_line - lo_line < 1 << 24:
        return (lines - lo_line).astype(np.int32), hi_line - lo_line + 1
    comp = _Compactor()
    ids = np.empty(len(lines), np.int32)
    for lo in range(0, len(lines), window):
        ids[lo:lo + window] = comp.map(lines[lo:lo + window])
    return ids, comp.next_free


class _Compactor:
    """Incremental cluster-probing line→dense-id table.

    Real traces touch a few contiguous memory regions, so instead of a
    per-chunk sort into a line vocabulary, probe each chunk against the
    discovered cluster table (one searchsorted over ~dozens of clusters) and
    sort only the MISSES — which vanish once the working set is discovered.
    A new cluster reserves ``slack`` id slots past its observed end so
    right-growth keeps already-assigned ids stable; ids are region offsets,
    so ``next_free`` counts allocated table slots (>= touched lines).
    State persists across :meth:`map` calls — the streaming path feeds
    disk batches through one instance.
    """

    def __init__(self, slack: int = 1024):
        self.slack = slack
        self.starts = np.empty(0, np.int64)   # cluster start line, sorted
        self.widths = np.empty(0, np.int64)   # id slots allocated
        self.bases = np.empty(0, np.int64)    # cluster's first id
        self.next_free = 0
        self._native = None  # lazy: pluss.native.line_mapper()

    def snapshot(self) -> dict:
        """JSON-able state for checkpoint/resume: the whole id assignment
        is these few arrays (dozens of clusters), so a resumed stream maps
        every line to the identical dense id."""
        return {"slack": self.slack, "starts": self.starts.tolist(),
                "widths": self.widths.tolist(), "bases": self.bases.tolist(),
                "next_free": int(self.next_free)}

    @classmethod
    def restore(cls, snap: dict) -> "_Compactor":
        comp = cls(slack=int(snap["slack"]))
        comp.starts = np.asarray(snap["starts"], np.int64)
        comp.widths = np.asarray(snap["widths"], np.int64)
        comp.bases = np.asarray(snap["bases"], np.int64)
        comp.next_free = int(snap["next_free"])
        return comp

    def map_raw(self, raw: np.ndarray, shift: int) -> np.ndarray | None:
        """Fused native fast path: u64 byte addresses -> int32 ids in one
        C pass, valid only while the table holds a single cluster that
        covers the whole chunk.  Returns None to fall back to
        ``map(lines)`` (which also discovers new clusters)."""
        if len(self.starts) != 1:
            return None
        if self._native is None:
            from pluss import native

            self._native = native.line_mapper() or False
        if self._native is False:
            return None
        return self._native(raw, shift, int(self.starts[0]),
                            int(self.widths[0]), int(self.bases[0]))

    def _map_into(self, chunk, out):
        cl = np.searchsorted(self.starts, chunk, side="right") - 1
        clc = np.maximum(cl, 0)
        inside = (cl >= 0) & (chunk < self.starts[clc] + self.widths[clc])
        out[inside] = (self.bases[clc] + (chunk - self.starts[clc]))[inside]
        return inside

    def map(self, chunk: np.ndarray) -> np.ndarray:
        """Dense int32 ids of one chunk of line numbers (grows the table)."""
        if len(self.starts) == 1:
            # single discovered region (the common case once the working set
            # stabilizes): containment is a min/max check and mapping is one
            # vectorized subtract — ~6x cheaper than the general probe, which
            # matters because the host core is shared with the PJRT client
            s0 = int(self.starts[0])
            if int(chunk.min()) >= s0 and int(chunk.max()) < s0 + int(self.widths[0]):
                return (chunk - (s0 - int(self.bases[0]))).astype(np.int32)
        out = np.empty(len(chunk), np.int32)
        inside = self._map_into(chunk, out) if len(self.starts) else \
            np.zeros(len(chunk), bool)
        miss = chunk[~inside]
        if not miss.size:
            return out
        mu = np.unique(miss)
        brk = np.nonzero(np.diff(mu) > self.slack)[0] + 1
        seg_s = mu[np.concatenate([[0], brk])]
        seg_e = mu[np.concatenate([brk - 1, [len(mu) - 1]])]
        for s, e in zip(seg_s.tolist(), seg_e.tolist()):
            # clamp the slack so cluster ranges never overlap the next one
            j = np.searchsorted(self.starts, s, side="right")
            limit = int(self.starts[j]) if j < len(self.starts) else None
            w = e - s + 1 + self.slack
            if limit is not None:
                w = min(w, limit - s)
            self.starts = np.insert(self.starts, j, s)
            self.widths = np.insert(self.widths, j, w)
            self.bases = np.insert(self.bases, j, self.next_free)
            self.next_free += w
        sub = np.empty(miss.size, np.int32)
        ok = self._map_into(miss, sub)
        assert ok.all()
        out[~inside] = sub
        if self.next_free >= 1 << 31:
            raise RuntimeError(
                "trace line-id space exhausted; lines too fragmented for "
                "cluster compaction"
            )
        return out


def _replay_ids(ids: np.ndarray, n_lines: int, n: int, window: int,
                batch_windows: int | None = None,
                segmented: bool | None = None) -> ReplayResult:
    """Stream dense line ids through the device kernel in fixed-shape
    batches."""
    bw = _resolve_bw(batch_windows)
    batch = bw * window
    n_batches = -(-n // batch)
    pos_dtype = "int32" if n_batches * batch < 2**31 - 2 else "int64"
    if pos_dtype == "int64" and not jax.config.jax_enable_x64:
        raise RuntimeError(
            f"trace of {n} accesses needs int64 positions; enable jax_enable_x64"
        )
    fn = _replay_fn(window, pos_dtype, segmented)
    pdt = np.dtype(pos_dtype)
    last_pos = jnp.full((n_lines,), -1, pdt)
    hist = jnp.zeros((NBINS,), pdt)
    for b in range(n_batches):
        lo = b * batch
        chunk = ids[lo:lo + batch]
        pad = batch - len(chunk)
        if pad:
            chunk = np.concatenate([chunk, np.zeros(pad, np.int32)])
        chunk = _pack_ids(chunk, n_lines)   # u16 / 24-bit packed feed
        shaped = chunk.reshape((bw, window) + chunk.shape[1:])
        last_pos, hist = fn(
            last_pos, hist, pdt.type(lo), jnp.asarray(shaped),
            pdt.type(n),
        )
    return ReplayResult(np.asarray(hist, np.int64), n, n_lines)


def _trace_fingerprint(path: str) -> str:
    """Cheap content identity of a trace file: sha256 of the first 1 MB.

    The checkpoint identity must bind the FILE, not just its shape — a
    regenerated trace with the same record count (bench generators use a
    fixed n_refs) would otherwise accept a stale checkpoint and splice a
    different trace's carries into the replay."""
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        h.update(f.read(1 << 20))
    return h.hexdigest()[:16]


def _ckpt_save(path: str, b_next: int, n: int, window: int, cls: int,
               precompacted: bool, fp: str, last_pos, hist,
               comp_snap: dict, batch_windows: int, wirefmt: str) -> None:
    """Atomic replay checkpoint: everything a resumed run needs to continue
    bit-identically (device carries + compactor id table + position), plus
    the FULL run identity — (n, window, cls, precompacted, batch_windows,
    wirefmt) all change the compaction/batching/feed semantics and ``fp``
    binds the source file's content, so a mismatch on any of them must
    start fresh, never splice.  The wire format is histogram-invariant,
    but it joins the identity anyway: a resume must never silently blend
    two encodings of one stream (the same rule the pack journal applies).

    Only the LIVE prefix of ``last_pos`` (the compactor's ``next_free``
    slots) is d2h-fetched and written — every slot past it is still the
    initial -1 (ids are always < next_free), so the padding is
    reconstructed on load instead of round-tripping a mostly-empty
    ``capacity``-sized array through the tunnel and the disk."""
    import json

    tmp = f"{path}.tmp.{os.getpid()}.npz"
    capacity = int(last_pos.shape[0])
    live = min(int(comp_snap["next_free"]), capacity)
    # slice ON DEVICE before the d2h fetch: only the live prefix crosses
    # the (tunneled, tens-of-MB/s) transport, not the whole padded table
    np.savez(tmp,
             last_pos=np.asarray(last_pos[:live]),
             capacity=np.int64(capacity),
             hist=np.asarray(hist),
             b_next=np.int64(b_next), n=np.int64(n),
             window=np.int64(window), cls=np.int64(cls),
             bw=np.int64(batch_windows),
             precompacted=np.int64(bool(precompacted)),
             fp=np.frombuffer(fp.encode(), np.uint8),
             wirefmt=np.frombuffer(wirefmt.encode(), np.uint8),
             comp=np.frombuffer(json.dumps(comp_snap).encode(), np.uint8))
    os.replace(tmp, path)


def _ckpt_load(path: str, n: int, window: int, cls: int,
               precompacted: bool, fp: str, batch_windows: int,
               wirefmt: str):
    """(b_next, last_pos, hist, comp) from a checkpoint, or None when the
    checkpoint is absent or describes a different run identity.  The
    ``last_pos`` carry is reconstructed at full capacity from the saved
    live prefix (see :func:`_ckpt_save`)."""
    import json
    import sys

    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            if "bw" not in z.files or "capacity" not in z.files \
                    or "wirefmt" not in z.files:
                print(f"trace: checkpoint {path} is from an older layout; "
                      "starting fresh", file=sys.stderr)
                return None
            ident = (int(z["n"]), int(z["window"]), int(z["cls"]),
                     int(z["precompacted"]), bytes(z["fp"]).decode(),
                     int(z["bw"]), bytes(z["wirefmt"]).decode())
            if ident != (n, window, cls, int(bool(precompacted)), fp,
                         batch_windows, wirefmt):
                print(f"trace: checkpoint {path} is for a different run "
                      f"(n, window, cls, precompacted, file, bw, "
                      f"wire)={ident}; starting fresh", file=sys.stderr)
                return None
            comp = _Compactor.restore(
                json.loads(bytes(z["comp"]).decode()))
            lp = z["last_pos"]
            cap = int(z["capacity"])
            if lp.shape[0] < cap:   # re-pad the saved live prefix
                lp = np.concatenate(
                    [lp, np.full((cap - lp.shape[0],), -1, lp.dtype)])
            return int(z["b_next"]), lp, z["hist"], comp
    except Exception as e:
        # same policy as the plan cache: quarantine the bad bytes and
        # start fresh — the source trace is intact, so a corrupt
        # checkpoint costs a recompute, never the run
        from pluss.resilience.errors import quarantine_artifact

        quarantine_artifact(path, "trace replay-checkpoint", e,
                            action="starting fresh")
        return None


def replay_file(path: str, fmt: str = "u64", cls: int = 64,
                window: int | None = None, precompacted: bool = False,
                initial_capacity: int = 1 << 20,
                limit_refs: int | None = None,
                pipeline: bool = True,
                deadline_s: float | None = None,
                checkpoint_path: str | None = None,
                checkpoint_every: int = 16,
                resume: bool = False,
                batch_windows: int | None = None,
                queue_depth: int | None = None,
                segmented: bool | None = None,
                feed_workers: int | None = None,
                wire: str | None = None,
                stage_depth: int | None = None,
                resident_cache: bool | None = None) -> ReplayResult:
    """Replay a trace FILE in bounded host memory (BASELINE config 5 scale).

    Unlike ``replay(load_trace(path))``, which slurps the whole file, this
    streams disk batches (``batch_windows * window`` addresses ≈ 128 MB at
    the defaults) through the incremental compactor straight into the
    device kernel, so a 1e9-ref / 8 GB trace replays without ever holding
    more than a couple of batches on the host.  The device line table
    starts at ``initial_capacity`` ids and doubles as the compactor
    discovers the working set (each growth retraces the jitted step —
    O(log) growths).

    The feed is a PARALLEL, DEPTH-CONFIGURABLE pipeline: ``feed_workers``
    reader/packer threads split the file into batch-aligned extents and
    read + wire-encode them concurrently (the compactor stage runs under
    a stream-order turnstile, :class:`_FeedPool`); the consumer keeps up
    to ``stage_depth`` batches' ``device_put`` (and, under the compressed
    wire, their device-side decode) dispatched ahead of the kernel, so
    host encode of batch ``b+2`` and upload of ``b+1`` both overlap
    device compute of ``b``.

    ``batch_windows``: windows per device batch (default
    :data:`WINDOWS_PER_BATCH`); part of the checkpoint identity.
    ``queue_depth``: feed queue bound (default ``PLUSS_TRACE_QUEUE_DEPTH``
    env or 2) — deeper queues absorb burstier disk/compaction latency at
    the cost of more in-flight host batches.
    ``segmented``: kernel selector for A/B verification (default:
    backend-aware — segmented on accelerators, the legacy per-window scan
    on CPU; ``PLUSS_TRACE_SEGMENTED`` overrides either way).
    ``feed_workers``: reader/packer pool width (default
    ``PLUSS_FEED_WORKERS`` env, else backend-aware — 1 on the CPU
    backend, most host cores on accelerators); 1 keeps the single
    reader thread.
    ``wire``: h2d encoding — ``pack`` (fixed-width u16/u24/i32),
    ``d24v`` (delta+zigzag+nibble bit-pack, decoded on device), or
    ``auto``/None (``PLUSS_WIRE`` env, else d24v on accelerators, pack
    on CPU).  Histogram-invariant; part of the checkpoint identity so
    resumes never splice across encodings.
    ``stage_depth``: staged-ahead device batches (default
    ``PLUSS_TRACE_STAGE_DEPTH`` env or 2 — the classic double buffer).

    ``resident_cache``: ride the device-resident trace store
    (:mod:`pluss.residency`, r13).  ``True`` checks the store first — a
    hit replays via :func:`replay_staged` with ZERO feed bytes — and on
    a miss stages the decoded batches through into the store while
    streaming (budget-gated; an entry that can't fit falls back to the
    plain stream, counted).  ``None``/``False`` (the default) keeps the
    store out of the path entirely.  Checkpointed, resumed, and
    truncated runs never publish (their staging is partial by design).

    ``deadline_s``: optional wall clock cap — the batch loop stops cleanly
    after the batch in flight when exceeded, returning the refs actually
    replayed (``total_count`` reflects the truncation).  A pre-run
    projection cannot defend against the tunneled feed SLOWING mid-run
    (observed: a run projected fine at ~23 MB/s finished at ~5 MB/s).

    ``checkpoint_path`` + ``resume``: crash recovery for multi-minute
    replays.  Every ``checkpoint_every`` batches the device carries
    (``last_pos``, ``hist``), the compactor's id table, and the stream
    position are written atomically; ``resume=True`` continues from the
    checkpoint instead of batch 0 — bit-identical to an uninterrupted run,
    recomputing only the batches after the last checkpoint (``pluss trace
    --resume``).  A checkpoint for a different (refs, window) shape is
    ignored with a notice, never silently mixed in.

    Every None-defaulted geometry knob (``window``, ``batch_windows``,
    ``queue_depth``, ``feed_workers``, ``wire``, ``stage_depth``, and the
    fused Pallas kernels) resolves through the persisted autotuner
    (:mod:`pluss.autotune`) before falling back to the shipped backend
    guess — explicit kwargs and PLUSS_* env overrides always win.
    """
    window = _resolve_window(window)
    if fmt == "text":  # line-oriented; no random access worth streaming
        return replay(load_trace(path, fmt), cls, window,
                      precompacted=precompacted,
                      batch_windows=batch_windows, segmented=segmented)
    if fmt != "u64":
        raise ValueError(f"unknown trace format {fmt!r}")
    if resident_cache is not None and not isinstance(resident_cache, bool):
        raise ValueError(
            f"resident_cache must be a bool or None, got {resident_cache!r}")
    n = _u64_count(path)
    if limit_refs is not None:
        n = min(n, limit_refs)  # prefix replay (e.g. compile warmup)
    if n == 0:
        return ReplayResult(np.zeros(NBINS, np.int64), 0, 0)
    if cls & (cls - 1):
        raise ValueError(f"cache line size {cls} is not a power of two")
    shift = int(cls).bit_length() - 1
    bw = _resolve_bw(batch_windows)
    batch = bw * window
    n_batches = -(-n // batch)
    pos_dtype = "int32" if n_batches * batch < 2**31 - 2 else "int64"
    if pos_dtype == "int64" and not jax.config.jax_enable_x64:
        raise RuntimeError(
            f"trace of {n} accesses needs int64 positions; enable jax_enable_x64"
        )
    # r13 residency: a checkpointed/resumed run re-enters mid-stream, so
    # its staging would be partial — the store stays out of its path
    use_store = bool(resident_cache) and checkpoint_path is None \
        and not resume
    res_store = res_key = None
    if use_store:
        from pluss import residency

        res_store = residency.store()
        res_key = _residency_key(path, cls=cls, window=window, bw=bw,
                                 precompacted=precompacted)
        ent = res_store.lookup_pin(res_key, n_run=n)
        if ent is not None:
            # HIT: replay straight off the resident bytes — zero feed,
            # zero h2d.  The entry is pinned (read-only input) for the
            # kernel's duration; LAT table and histogram are per-replay
            try:
                return replay_staged(ent.value, ent.n_lines, ent.n_run,
                                     window, segmented=segmented)
            finally:
                res_store.unpin(res_key)
    fn = _replay_fn(window, pos_dtype, segmented)
    pdt = np.dtype(pos_dtype)
    wirefmt = _resolve_wire(wire)
    workers = _resolve_feed_workers(feed_workers)
    sd = _resolve_stage_depth(stage_depth)

    b0 = 0
    comp0 = _Compactor()
    fp = _trace_fingerprint(path) if checkpoint_path else ""
    ck = _ckpt_load(checkpoint_path, n, window, cls, precompacted, fp, bw,
                    wirefmt) \
        if resume and checkpoint_path else None
    if ck is not None:
        b0, ck_last_pos, ck_hist, comp0 = ck
        import sys

        print(f"trace: resuming from checkpoint at batch {b0}/{n_batches} "
              f"({min(n, b0 * batch)} refs already replayed)",
              file=sys.stderr)
    if b0 >= n_batches:   # checkpoint already covers the whole stream
        return ReplayResult(np.asarray(ck_hist, np.int64), n,
                            comp0.next_free, wire=wirefmt,
                            feed_workers=workers)

    comp = comp0
    enc_acc = [0.0]   # wire-encode seconds of the single-reader paths
    read_raw = _extent_reader(path, batch, n)
    compact_batch = _compact_stage(comp, shift, precompacted,
                                   snapshot=bool(checkpoint_path))

    def encode_batch(b, mid):
        """Pad to the fixed batch shape and wire-encode (pure per-extent
        work — embarrassingly parallel across pool workers)."""
        ids, n_lines_b, snap_b = mid
        pad = batch - len(ids)
        if pad:
            ids = np.concatenate([ids, np.zeros(pad, np.int32)])
        return _encode_wire(ids, n_lines_b, wirefmt), n_lines_b, snap_b

    def batches():
        """Single-reader feed: the same three stages, run inline in
        stream order (``feed_workers=1`` behind the bounded queue, or
        ``pipeline=False`` fully inline for debugging/A-B)."""
        from pluss.resilience import faults

        for b in range(b0, n_batches):
            faults.check("trace.read_batch")  # chaos injection site
            mid = compact_batch(b, read_raw(b))
            t0 = _time.perf_counter()
            item = encode_batch(b, mid)
            enc_acc[0] += _time.perf_counter() - t0
            yield item

    # pipelined host side: feed_workers reader/packer threads stream disk
    # batches through the (stateful, hence turnstiled) compactor while
    # the main thread stages/dispatches to the device — the
    # disk+compaction+encode latency hides behind earlier batches'
    # transfer and kernel.  The queue bound keeps host memory at a few
    # in-flight batches; numpy IO, packing, and the native compactor
    # pass release the GIL, so the overlap is real even on one core.
    # ``pipeline=False`` runs the same stages inline (debugging / A-B).
    import contextlib

    qd = _resolve_queue_depth(queue_depth)
    if not pipeline:
        src = contextlib.nullcontext(batches())
    elif workers > 1:
        from pluss.resilience import faults

        src = _FeedPool(b0, n_batches,
                        lambda b: faults.check("trace.read_batch"),
                        read_raw, compact_batch, encode_batch,
                        workers, qd)
    else:
        src = _threaded(batches, depth=qd)
    import time as _time

    t0 = _time.perf_counter()
    if ck is not None:
        capacity = len(ck_last_pos)
        last_pos = jnp.asarray(ck_last_pos.astype(pdt))
        hist = jnp.asarray(ck_hist.astype(pdt))
        n_lines = comp0.next_free
        done = min(n, b0 * batch)
    else:
        capacity = initial_capacity
        last_pos = jnp.full((capacity,), -1, pdt)
        hist = jnp.zeros((NBINS,), pdt)
        n_lines = 0
        done = 0
    done0 = done   # checkpoint-restored refs: not THIS run's work

    # structured loop accounting (replaces the old ad-hoc t0 locals): the
    # main thread is, at any instant, in exactly one of these buckets, so
    # their sum accounts for the replay's wall clock — `pluss stats`
    # renders the breakdown and the feed-bound diagnosis reads off it.
    # Accumulated locally either way (a handful of perf_counter calls per
    # multi-M-ref batch); recorded only when telemetry is enabled.
    st = {"prefetch_stall_s": 0.0, "h2d_s": 0.0, "device_s": 0.0,
          "ckpt_save_s": 0.0, "grow_s": 0.0}
    st_n = {"h2d_bytes": 0, "device_bytes": 0, "batches": 0,
            "ckpt_saves": 0, "growths": 0}
    obs_on = obs.enabled()
    backend = jax.default_backend()

    # r13 stage-through: the store missed, so accumulate each decoded
    # batch into the resident u24 layout WHILE streaming — this run
    # populates the store for the next one at no extra feed cost.
    # Budget-gated up front (an unfittable trace streams plain, counted);
    # abandoned if the line table outgrows the 3-byte layout; published
    # only when the stream completes fully (no truncation, no fault).
    st_acc = None
    st_fn = None
    if use_store:
        from pluss.resilience.errors import ResourceExhausted

        try:
            res_store.reserve(n_batches * batch * 3)
            st_acc = jnp.zeros((n_batches, bw, window, 3), jnp.uint8)
            st_fn = _stage_through_fn(backend)
        except ResourceExhausted:
            st_acc = None   # reserve counted the fallback; stream plain

    def stage(item):
        """Start one batch's h2d transfer NOW.  ``device_put`` (and the
        d24v device-side decode dispatch) are async, so staging right
        after dispatching an earlier batch's kernel overlaps upload with
        compute; the compressed wire ships its payload+width-map and
        expands to the int32 layout on device."""
        if item is None:
            return None
        w, n_lines_b, snap_b = item
        if isinstance(w, _WireD24V):
            nbytes = w.payload.nbytes + w.wm.nbytes
            flat = _decode_fn(backend)(jax.device_put(w.payload),
                                       jax.device_put(w.wm))
            shaped = flat[:batch].reshape(bw, window)
        else:
            nbytes = w.nbytes
            shaped = jax.device_put(w.reshape((bw, window) + w.shape[1:]))
        return shaped, n_lines_b, snap_b, nbytes

    with obs.span("trace.replay_file", refs=n, window=window,
                  batch_windows=bw, resume_batch=b0, feed_workers=workers,
                  wire=wirefmt) as sp, \
            xprof.session(), src as it:
        stream = iter(it)
        from collections import deque

        pending: deque = deque()
        exhausted = False
        feed_err: BaseException | None = None
        truncated = False

        def pump():
            """Refill the staged-ahead pipeline to ``stage_depth``
            batches, splitting time blocked on the feed (prefetch stall:
            the feed is behind) from time handing bytes to the device
            (h2d staging dispatch).  Dispatch-only, so it returns while
            the transfers and decodes run behind the kernel.

            A feed/staging error is HELD, not raised: batches already
            staged must still be processed (and checkpointed) before the
            error surfaces, so a fault in batch b+sd never costs batch
            b's durable point — the same guarantee the double buffer
            gave at depth 1, kept at every depth."""
            nonlocal exhausted, feed_err
            while not exhausted and len(pending) < sd:
                t1 = _time.perf_counter()
                try:
                    item = next(stream, None)
                except BaseException as e:
                    feed_err = e
                    exhausted = True
                    st["prefetch_stall_s"] += _time.perf_counter() - t1
                    break
                t2 = _time.perf_counter()
                st["prefetch_stall_s"] += t2 - t1
                if item is None:
                    exhausted = True
                    break
                out = stage(item)
                st["h2d_s"] += _time.perf_counter() - t2
                st_n["h2d_bytes"] += out[3]
                # what the kernel consumes after widening/decode: the
                # wire-vs-device ratio reads straight off the counters
                st_n["device_bytes"] += batch * 4
                pending.append(out)

        try:
            pump()
            b = b0
            while pending:
                ids_dev, n_lines, snap, _ = pending.popleft()
                if n_lines > capacity:
                    tg = _time.perf_counter()
                    while capacity < n_lines:
                        capacity *= 2
                    last_pos = jnp.concatenate(
                        [last_pos,
                         jnp.full((capacity - last_pos.shape[0],), -1, pdt)]
                    )
                    st["grow_s"] += _time.perf_counter() - tg
                    st_n["growths"] += 1
                if st_acc is not None:
                    if n_lines >= 1 << 24:
                        # ids stopped fitting 3 bytes — the resident u24
                        # layout can't hold this trace; abandon, counted
                        st_acc = None
                        obs.counter_add("residency.fallback")
                    else:
                        st_acc = st_fn(st_acc, ids_dev, jnp.int32(b))
                td = _time.perf_counter()
                with xprof.annotate("pluss.trace.batch"):
                    last_pos, hist = fn(
                        last_pos, hist, pdt.type(b * batch), ids_dev,
                        pdt.type(n),
                    )
                st["device_s"] += _time.perf_counter() - td
                st_n["batches"] += 1
                if obs_on and pipeline:
                    obs.gauge_set("trace.queue_occupancy", it.qsize())
                    if isinstance(it, _FeedPool):
                        obs.gauge_set("trace.feed_workers_busy", it.busy)
                done = min(n, (b + 1) * batch)
                if checkpoint_path and done < n \
                        and (b + 1 - b0) % checkpoint_every == 0:
                    # the d2h fetch synchronizes the dispatch queue — that
                    # is the price of a durable point; checkpoint_every
                    # amortizes.  The save runs BEFORE the next prefetch: a
                    # reader fault in batch b+1 must never cost batch b's
                    # durable point
                    tc = _time.perf_counter()
                    _ckpt_save(checkpoint_path, b + 1, n, window, cls,
                               precompacted, fp, last_pos, hist, snap, bw,
                               wirefmt)
                    st["ckpt_save_s"] += _time.perf_counter() - tc
                    st_n["ckpt_saves"] += 1
                # the cheap unsynced clock runs every batch; the device
                # sync (which is what makes the elapsed time REAL under
                # async dispatch) is only paid once the unsynced time is
                # already over — so a fast run never syncs, and a slow
                # feed cannot overshoot by more than one batch
                if deadline_s is not None and done < n \
                        and _time.perf_counter() - t0 > deadline_s:
                    ts = _time.perf_counter()
                    np.asarray(hist[:1])
                    st["device_s"] += _time.perf_counter() - ts
                    if _time.perf_counter() - t0 > deadline_s:
                        # truncation is clean at a batch boundary: every
                        # processed position is < done, none beyond
                        # dispatched
                        if obs_on:
                            obs.event("trace.deadline_truncated",
                                      done=done, refs=n)
                        truncated = True
                        break
                # staged-ahead pipeline: up to stage_depth batches'
                # device_put/decode are dispatched while this batch's
                # kernel runs (dispatch above is async; the checkpoint
                # branch is a no-op on all but every checkpoint_every-th
                # batch), so the h2d feed and the kernel overlap instead
                # of being paid serially.  Staged batches dropped at a
                # deadline break are harmless — they never dispatch
                # compute
                pump()
                b += 1
            if feed_err is not None and not truncated:
                # every staged batch has been processed and checkpointed;
                # NOW the held feed error surfaces (a deadline break
                # instead discards it with the rest of the in-flight feed)
                raise feed_err
            # the final d2h fetch is what forces every outstanding
            # dispatch to completion — that wait is device time
            td = _time.perf_counter()
            hist_np = np.asarray(hist, np.int64)
            st["device_s"] += _time.perf_counter() - td
        finally:
            # recorded even when the replay aborts mid-stream (an injected
            # DataLoss, a real read failure): the partial run's breakdown
            # is exactly what the post-mortem wants to see
            if obs_on:
                for k, v in st.items():
                    obs.counter_add(f"trace.{k}", v)
                for k, v in st_n.items():
                    obs.counter_add(f"trace.{k}", v)
                # host wire-encode seconds run CONCURRENTLY with the
                # main-thread buckets above (pool workers), so this is a
                # separate counter, not a wall bucket
                obs.counter_add(
                    "trace.wire_encode_s",
                    it.encode_s if isinstance(it, _FeedPool) else enc_acc[0])
                # only the refs THIS run replayed: a resumed run's span
                # wall covers the tail after the checkpoint, so counting
                # the restored prefix would inflate every rate derived
                # from (refs_replayed / wall)
                obs.counter_add("trace.refs_replayed", done - done0)
                sp.set(refs_replayed=done - done0, stream_done=done,
                       n_lines=n_lines)
                obs.flush_metrics()
    if checkpoint_path and done >= n:
        # a finished run retires its checkpoint: a later DIFFERENT run
        # must not resume from this one's final state
        try:
            os.unlink(checkpoint_path)
        except OSError:
            pass
    if st_acc is not None and done >= n and not truncated:
        # the stream completed: the accumulated staging is the whole
        # trace, byte-identical to stage_resident's — publish it
        res_store.put(res_key, st_acc, n_lines=n_lines, n_run=n,
                      nbytes=st_acc.nbytes, meta={"path": path,
                                                  "stage_through": True})
        obs.counter_add("residency.stage_through")
        obs.trace_event("residency.stage_through",
                        nbytes=int(st_acc.nbytes))
    return ReplayResult(hist_np, done, n_lines, wire=wirefmt,
                        feed_workers=workers)


def pack_file(path: str, out_path: str, cls: int = 64,
              window: int = TRACE_WINDOW, precompacted: bool = False,
              limit_refs: int | None = None,
              resume: bool = False, _wide: bool = False,
              batch_windows: int | None = None,
              feed_workers: int | None = None,
              wire: str | None = None) -> dict:
    """Compact + pack a raw u64 trace ONCE, writing the replay wire format.

    Streams the trace through the same incremental compactor as
    :func:`replay_file` — reusing its parallel reader/packer pool
    (``feed_workers``), so the pack runs at N-worker rate while only the
    order-dependent compactor stage serializes — and writes the packed
    dense-id stream plus a JSON sidecar (``out_path + '.json'``) with
    ``{n, n_lines, fmt}``.  The host-side compaction of a 1e9-ref trace
    costs minutes single-threaded; paying it once lets
    :func:`replay_resident` stage straight from disk on every later run.
    Returns the sidecar dict.

    Wire format: 24-bit/ref (``fmt: u24``) while the id table fits 2^24
    lines — decided by the FINAL table size, which is unknown mid-stream,
    so the 3-byte format is written optimistically and the pack RESTARTS
    in the 4-byte little-endian int32 format (``fmt: i32``) the moment
    the table overflows (real traces that blow 2^24 lines blow it early,
    so the wasted prefix is small).  ``wire='d24v'`` writes the
    COMPRESSED wire instead (``fmt: d24v``): per-batch records of
    ``u32 payload_len | width map | bit-packed payload``, with the
    record offsets in the sidecar so staging reads them in parallel and
    the device decodes them straight into HBM (the 3 GB pack of a
    1e9-ref trace crosses PCIe as a fraction of itself).  The on-disk
    format never depends on the backend, so ``auto`` here means the
    fixed-width pack.  The staging/replay side widens/decodes any format
    on device.

    Progress journals to ``out_path + '.journal'`` per flushed batch (the
    output offset + the compactor's id table); ``resume=True`` after a
    crash truncates the partial ``.tmp`` to the last journaled batch
    boundary and continues — byte-identical to an uninterrupted pack, with
    zero batches recompacted before the checkpoint.  The journal records
    the wire format, so a resumed pack can never splice across formats
    (an i32 fallback stays i32, a d24v pack stays d24v).
    """
    import json

    from pluss.resilience import faults
    from pluss.resilience.journal import Journal

    n = _u64_count(path)
    if limit_refs is not None:
        n = min(n, limit_refs)
    if cls & (cls - 1):
        raise ValueError(f"cache line size {cls} is not a power of two")
    if wire is not None and wire not in WIRE_CHOICES:
        raise ValueError(
            f"unknown wire format {wire!r} (choices: "
            f"{', '.join(WIRE_CHOICES)})")
    workers = _resolve_feed_workers(feed_workers)
    shift = int(cls).bit_length() - 1
    bw = _resolve_bw(batch_windows)
    batch = bw * window
    if wire == "d24v" and batch > _D24V_MAX_BATCH:
        # the decode kernel's bit-offset math is int32 (same ceiling
        # _encode_wire enforces on the streamed feed) — a pack written
        # past it would decode GARBAGE at stage time, so fail at pack
        # time, loudly
        raise ValueError(
            f"d24v records cap at {_D24V_MAX_BATCH} refs/batch "
            f"(int32 decode offsets); batch_windows*window = {batch} — "
            "reduce the batch or pack with wire='pack'")
    n_batches = -(-n // batch)
    comp = _Compactor()
    tmp = out_path + ".tmp"
    jpath = out_path + ".journal"
    b0 = 0
    fp = _trace_fingerprint(path)
    fmt = "i32" if _wide else ("d24v" if wire == "d24v" else "u24")
    offsets: list[int] = []   # d24v record offsets (sidecar, for staging)
    if resume and not _wide and os.path.exists(jpath):
        rec0 = Journal(jpath).get({"batch": 0})
        if rec0 is not None and rec0.get("fmt") == "i32":
            # the crashed pack had already fallen back to the wide wire
            # format; resume in it instead of re-deciding from scratch
            return pack_file(path, out_path, cls, window, precompacted,
                            limit_refs, resume=True, _wide=True,
                            batch_windows=bw, feed_workers=workers)
        if rec0 is not None and rec0.get("fmt") == "d24v" \
                and wire in (None, "auto"):
            # same continuation rule for the compressed format: a crashed
            # d24v pack resumed without re-passing wire='d24v' must stay
            # d24v (an explicit wire='pack' still overrides — identity
            # mismatch below, fresh u24 pack)
            fmt = "d24v"
    if resume and os.path.exists(jpath) and os.path.exists(tmp):
        jr = Journal(jpath)
        best = None
        # bw is part of the identity: journal "batch" indices count
        # bw-sized batches, so a resumed pack must slice identically
        ident = {"n": n, "window": window, "cls": cls,
                 "precompacted": bool(precompacted), "fp": fp, "fmt": fmt,
                 "bw": bw}
        out_bytes_seen: list[int] = []   # out_bytes after batch j, in order
        for b in range(n_batches):
            rec = jr.get({"batch": b})
            if rec is None:
                break
            if any(rec.get(k) != v for k, v in ident.items()):
                best = None   # journal from a different pack run
                out_bytes_seen = []
                break
            best = rec
            out_bytes_seen.append(rec["out_bytes"])
        if best is not None and os.path.getsize(tmp) < best["out_bytes"]:
            # the journal line outlived the data it describes (e.g. a
            # power loss between data flush and durability): truncating
            # FORWARD would zero-extend the stream — walk back to the
            # last batch whose bytes are actually on disk
            size = os.path.getsize(tmp)
            while best is not None and best["out_bytes"] > size:
                b_prev = best["key"]["batch"] - 1
                best = jr.get({"batch": b_prev}) if b_prev >= 0 else None
        if best is not None:
            b0 = best["key"]["batch"] + 1
            comp = _Compactor.restore(best["comp"])
            # record b starts where batch b-1's bytes ended
            offsets = [0] + out_bytes_seen[:b0 - 1]
            with open(tmp, "r+b") as out:
                out.truncate(best["out_bytes"])
            import sys

            print(f"trace: resuming pack at batch {b0}/{n_batches} "
                  f"({best['out_bytes']} bytes already packed)",
                  file=sys.stderr)
    if b0 == 0:
        # fresh start: a STALE journal from an earlier crashed pack must
        # not survive — a later resume's contiguity scan would splice its
        # leftover high-batch records onto the new run's prefix and
        # truncate() past EOF (zero-extending a corrupt output)
        try:
            os.unlink(jpath)
        except OSError:
            pass
        offsets = []
    journal = Journal(jpath)

    read_raw = _extent_reader(path, batch, n)
    compact_batch = _compact_stage(comp, shift, precompacted, snapshot=True)

    def encode_rec(b, mid):
        """The on-disk record bytes of one batch (parallel across pool
        workers).  An over-2^24 table skips encoding — the consumer
        restarts the whole pack on the wide wire before writing it."""
        ids, nl, snap = mid
        if not _wide and nl >= 1 << 24:
            return None, nl, snap
        if fmt == "d24v":
            from pluss.ops import wirecodec

            payload, wm = wirecodec.encode_d24v(ids)
            used = wirecodec.used_bytes(wm)
            rec = (np.asarray([used], dtype="<u4"), wm, payload[:used])
        elif _wide:
            rec = (np.ascontiguousarray(ids, dtype="<i4"),)
        else:
            rec = (_pack24(ids),)
        return rec, nl, snap

    def items():
        for b in range(b0, n_batches):
            faults.check("trace.read_batch")  # chaos injection site
            yield encode_rec(b, compact_batch(b, read_raw(b)))

    import contextlib

    if workers > 1:
        src = _FeedPool(b0, n_batches,
                        lambda b: faults.check("trace.read_batch"),
                        read_raw, compact_batch, encode_rec, workers,
                        depth=2)
    else:
        src = contextlib.nullcontext(items())
    with obs.span("trace.pack_file", refs=n, fmt=fmt, resume_batch=b0,
                  feed_workers=workers), \
            src as it, open(tmp, "r+b" if b0 else "wb") as out:
        out.seek(0, os.SEEK_END)
        for b, item in zip(range(b0, n_batches), it):
            rec, nl, snap = item
            if not _wide and nl >= 1 << 24:
                import sys

                print(f"trace: line table overflowed 2^24 ids at batch "
                      f"{b}; restarting the pack in the int32 wire "
                      "format", file=sys.stderr)
                try:
                    os.unlink(jpath)
                except OSError:
                    pass
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return pack_file(path, out_path, cls, window,
                                precompacted, limit_refs, _wide=True,
                                batch_windows=bw, feed_workers=workers)
            if fmt == "d24v":
                offsets.append(out.tell())
            for arr in rec:
                arr.tofile(out)
            out.flush()
            # the DATA must be durable before the journal line that
            # promises it exists — otherwise a power loss can leave a
            # journal entry pointing past the real end of the file
            os.fsync(out.fileno())
            journal.record({"batch": b}, out_bytes=out.tell(),
                           comp=snap, n=n, window=window,
                           cls=cls, precompacted=bool(precompacted),
                           fp=fp, fmt=fmt, bw=bw)
    os.replace(tmp, out_path)
    # src_fp + wire bind the pack to its source trace's content and this
    # module's wire-format version: cross-run pack caches (bench.py) key
    # on them so a regenerated trace or a format change forces a repack
    meta = {"n": n, "n_lines": comp.next_free, "fmt": fmt,
            "src_fp": fp, "wire": WIRE_VERSION}
    if fmt == "d24v":
        # staging needs the record grid: records are variable-length and
        # cut at the PACK-time batch, so replay must slice identically
        meta["batch"] = batch
        meta["offsets"] = offsets
    # atomic sidecar: a reader (pack_cached staleness check, a concurrent
    # serve warm) must see the old complete meta or the new complete meta,
    # never a torn write
    sidecar_tmp = out_path + ".json.tmp"
    with open(sidecar_tmp, "w") as f:
        json.dump(meta, f)
    os.replace(sidecar_tmp, out_path + ".json")
    try:
        os.unlink(jpath)   # the pack is durable; the journal is spent
    except OSError:
        pass
    obs.counter_add("trace.pack_refs", n)
    return meta


def pack_cached(path: str, packed_path: str | None = None, *,
                cls: int = 64, window: int = TRACE_WINDOW,
                precompacted: bool = False,
                limit_refs: int | None = None,
                batch_windows: int | None = None,
                feed_workers: int | None = None,
                wire: str = "d24v",
                allow_pack: bool = True) -> tuple[dict | None, bool, str]:
    """Disk pack cache: ``(sidecar meta, was_cached, packed path)``.

    The middle tier of the trace residency ladder (HBM entry → THIS →
    raw trace): :func:`pack_file` once per (source content, wire
    version, batch grid), then every staging — bench rounds, serve
    warms, `pluss trace` — reuses the bytes.  An existence-only check
    would happily replay a stale pack after the source regenerated or
    the wire format changed; the staleness key is the same
    src-fingerprint + :data:`WIRE_VERSION` + batch-grid identity the
    bench cache always used (promoted here in r13 so every consumer
    shares it).  A key mismatch forces a repack, never a silent stale
    replay; the sidecar is written atomically (tmp + ``os.replace``).

    ``allow_pack=False`` probes only: a fresh pack returns as usual, a
    missing/stale one returns ``(None, False, packed)`` without paying
    the repack — callers with their own packing budget (the bench) gate
    on that before calling again with packing allowed.
    """
    import json

    packed = packed_path if packed_path is not None else path + ".pack"
    sidecar = packed + ".json"
    n = _u64_count(path)
    if limit_refs is not None:
        n = min(n, limit_refs)
    bw = _resolve_bw(batch_windows)
    if os.path.exists(packed) and os.path.exists(sidecar):
        try:
            with open(sidecar) as f:
                meta = json.load(f)
        except ValueError:
            meta = {}
        # d24v packs are only stageable at their own batch grid, so a
        # batch_windows/window change forces a repack; the fixed-width
        # formats slice at any grid
        fmt_ok = meta.get("fmt") in ("u24", "i32") or (
            meta.get("fmt") == "d24v"
            and meta.get("batch") == bw * window)
        if meta.get("n") == n \
                and meta.get("src_fp") == _trace_fingerprint(path) \
                and meta.get("wire") == WIRE_VERSION and fmt_ok:
            return meta, True, packed
    if not allow_pack:
        return None, False, packed
    meta = pack_file(path, packed, cls=cls, window=window,
                     precompacted=precompacted, limit_refs=limit_refs,
                     batch_windows=bw, feed_workers=feed_workers,
                     wire=wire)
    return meta, False, packed


def _residency_key(path: str, *, cls: int, window: int, bw: int,
                   precompacted: bool, devices=None) -> tuple:
    """Identity of one trace's resident staging.  A regenerated trace
    (content fingerprint + size), a wire-format bump, or a different
    window / batch grid / line size / device set each produce a
    different key — the store can never serve stale ids, it just
    misses.  ``n_run`` (the replayed prefix) is checked at lookup
    against the entry, not in the key, so one trace never holds two
    near-identical resident copies."""
    from pluss.parallel.shard import device_fingerprint

    if devices is None:
        devices = jax.local_devices()[:1]
    try:
        size = os.path.getsize(path)
    except OSError:
        size = -1
    return ("trace", _trace_fingerprint(path), size, WIRE_VERSION,
            int(cls), int(window), int(bw), bool(precompacted),
            device_fingerprint(devices))


@functools.lru_cache(maxsize=4)
def _stage_through_fn(backend: str):
    """Accumulate ONE streamed batch into the resident u24 byte layout
    while the stream runs (r13 stage-through): whatever the feed staged
    — the u24/i32-LE byte pack, the u16 pack, or d24v-decoded int32 ids
    — widens on device and restacks to the same 3 B/ref bytes
    :func:`stage_resident` writes, so a stage-through entry is
    byte-identical to a direct staging of the pack.  Zero padding is
    symmetric by construction: the streamed feed zero-pads ids before
    encoding, the direct staging zero-pads the raw record bytes."""
    def f(acc, ids_dev, b):
        flat = _widen_ids(ids_dev.reshape((-1,) + ids_dev.shape[2:]))
        u = flat.astype(jnp.uint32)
        chunk = jnp.stack(
            [u & 0xFF, (u >> 8) & 0xFF, (u >> 16) & 0xFF],
            axis=-1).astype(jnp.uint8).reshape((1,) + acc.shape[1:])
        return jax.lax.dynamic_update_slice(
            acc, chunk, (b, jnp.int32(0), jnp.int32(0), jnp.int32(0)))

    donate = (0,) if backend != "cpu" else ()
    return jax.jit(f, donate_argnums=donate)


def ensure_resident(path: str, *, cls: int = 64, window: int = TRACE_WINDOW,
                    precompacted: bool = False,
                    limit_refs: int | None = None,
                    packed_path: str | None = None,
                    upload_budget_s: float | None = None,
                    batch_windows: int | None = None,
                    feed_workers: int | None = None,
                    wire: str = "d24v"):
    """Pack (disk-cached), stage, and PUBLISH one trace into the
    residency store: the explicit population path (serve ``--warm``
    trace entries, the bench warm headline).  Returns the
    :class:`pluss.residency.Entry` — from the store on a hit or a full
    staging; an ``upload_budget_s``-shrunk prefix returns an
    UNPUBLISHED entry (``meta['published']`` False) because the
    sidecar's ``n_lines`` is only exact for the full pack, and a store
    hit must be bit-identical to the streamed run it replaces.

    Raises :class:`~pluss.resilience.errors.ResourceExhausted`
    (degradable) when the staged bytes can never fit the budget — the
    caller's ladder degrades to the streamed path.
    """
    from pluss import residency

    st = residency.store()
    n_file = _u64_count(path)
    n_req = n_file if limit_refs is None else min(n_file, limit_refs)
    bw = _resolve_bw(batch_windows)
    key = _residency_key(path, cls=cls, window=window, bw=bw,
                         precompacted=precompacted)
    ent = st.lookup_pin(key, n_run=n_req)
    if ent is not None:
        st.unpin(key)
        return ent
    meta, _, packed = pack_cached(path, packed_path, cls=cls, window=window,
                                  precompacted=precompacted,
                                  limit_refs=limit_refs,
                                  batch_windows=bw,
                                  feed_workers=feed_workers, wire=wire)
    bpr = 4 if meta["fmt"] == "i32" else 3
    batch = bw * window
    nbytes = -(-n_req // batch) * batch * bpr
    st.reserve(nbytes)   # raises ResourceExhausted (degradable) on no-fit
    resident, n_run, info = stage_resident(
        packed, meta, window, limit_refs=n_req,
        upload_budget_s=upload_budget_s, batch_windows=bw,
        feed_workers=feed_workers)
    if n_run == n_req:
        return st.put(key, resident, n_lines=meta["n_lines"], n_run=n_run,
                      nbytes=resident.nbytes,
                      meta={"path": path, "packed": packed,
                            "published": True, **info})
    # budget-shrunk prefix: usable by the caller, never served from the
    # store (its exact line count is unknown)
    obs.counter_add("residency.fallback")
    return residency.Entry(key=key, value=resident,
                           n_lines=meta["n_lines"], n_run=n_run,
                           nbytes=0 if resident is None else resident.nbytes,
                           meta={"path": path, "packed": packed,
                                 "published": False, **info})


@functools.lru_cache(maxsize=4)
def _stage_fn(backend: str):
    """Donating writer that lands one uploaded batch in the resident array."""
    def put(resident, chunk, b):
        return jax.lax.dynamic_update_slice(
            resident, chunk, (b, jnp.int32(0), jnp.int32(0), jnp.int32(0)))

    donate = (0,) if backend != "cpu" else ()
    return jax.jit(put, donate_argnums=donate)


@functools.lru_cache(maxsize=8)
def _resident_fn(window: int, pos_dtype_name: str, backend: str,
                 segmented: bool, fused: bool):
    """One-dispatch replay over the device-resident packed trace: an outer
    scan over batches, each batch the same kernel as the streamed path
    (segmented whole-batch by default; per-window legacy scan for A/B).
    Batch count and width come from the resident array's shape, so one
    cached wrapper serves every ``--batch-windows`` setting (jit retraces
    per shape)."""
    pdt = jnp.dtype(pos_dtype_name)

    def run(resident, last_pos, hist, n_valid, clock0):
        # clock0 shifts the logical-clock origin: reuse distances are
        # position DIFFERENCES, so the histogram is invariant under it —
        # it exists so repeat benchmark replays are distinct inputs (the
        # tunneled backend memoizes (executable, inputs) -> result; a
        # second bit-identical call would "run" in microseconds).  The
        # caller shifts n_valid by the same amount.
        n_batches = resident.shape[0]
        batch = resident.shape[1] * window

        def outer(carry, xs):
            last_pos, hist = carry
            b, ids = xs
            base = clock0 + b.astype(pdt) * batch
            if segmented:
                last_pos, hist = _segmented_batch(
                    last_pos, hist, base, ids, n_valid, pdt)
            else:
                last_pos, hist = _scan_batch(
                    last_pos, hist, base, ids, n_valid, window, pdt)
            return (last_pos, hist), None

        (last_pos, hist), _ = jax.lax.scan(
            outer, (last_pos, hist),
            (jnp.arange(n_batches, dtype=jnp.int32), resident))
        return last_pos, hist

    donate = (1, 2) if backend != "cpu" else ()
    return jax.jit(run, donate_argnums=donate)


def replay_resident(packed_path: str, meta: dict,
                    window: int = TRACE_WINDOW,
                    limit_refs: int | None = None,
                    upload_budget_s: float | None = None,
                    clock0: int = 0,
                    stats: dict | None = None,
                    batch_windows: int | None = None,
                    segmented: bool | None = None,
                    feed_workers: int | None = None) -> ReplayResult:
    """Replay from DEVICE memory: stage the packed trace into HBM once,
    then run the whole scan in one dispatch at device rate.

    The streamed path (:func:`replay_file`) is bounded end-to-end by this
    image's tunneled h2d feed (single-digit MB/s in bad weather); here the
    upload and the replay are separate phases, reported separately via
    ``stats`` (``upload_s``, ``upload_bytes``, ``replay_s``, ``refs``) —
    upload cost amortizes over any number of replays/configurations of the
    same trace.  A 1e9-ref trace packs to 3 GB and fits HBM whole.

    ``meta`` is :func:`pack_file`'s sidecar.  ``upload_budget_s`` caps the
    staging phase: when the feed is too slow, the staged prefix shrinks and
    the replay covers ``stats['refs']`` accesses (same honest-shrink
    contract as the bench's end-to-end metric).
    """
    resident, n_run, stats2 = stage_resident(
        packed_path, meta, window, limit_refs, upload_budget_s,
        batch_windows=batch_windows, feed_workers=feed_workers)
    if stats is not None:
        stats.update(stats2)
    if n_run == 0:
        return ReplayResult(np.zeros(NBINS, np.int64), 0, 0)
    return replay_staged(resident, meta["n_lines"], n_run, window,
                         clock0=clock0, stats=stats, segmented=segmented)


def stage_resident(packed_path: str, meta: dict,
                   window: int = TRACE_WINDOW,
                   limit_refs: int | None = None,
                   upload_budget_s: float | None = None,
                   batch_windows: int | None = None,
                   feed_workers: int | None = None):
    """Upload a packed trace into HBM.  Returns ``(resident, n_run, stats)``
    — the device array ([n_batches, batch_windows, window, 3|4] u8 —
    last dim 3 for ``u24``/``d24v``, 4 for ``i32``), the staged ref count
    (may be a prefix under ``upload_budget_s``), and ``{upload_s,
    upload_bytes}``.  Staging once serves any number of
    :func:`replay_staged` calls (which read the batch width back off the
    resident array's shape).

    Reads ride the same ``feed_workers`` pool as :func:`replay_file`, so
    disk reads of record ``b+1`` overlap the (async) upload of ``b``; a
    ``d24v`` pack ships its COMPRESSED records over the transport and a
    jitted kernel decodes them straight into the resident u24 layout —
    PCIe carries a fraction of the 3 GB the u24 pack would ship.
    """
    import time

    if meta["fmt"] not in ("u24", "i32", "d24v"):
        raise ValueError(f"unknown packed trace format {meta['fmt']!r}")
    d24v = meta["fmt"] == "d24v"
    bpr = 4 if meta["fmt"] == "i32" else 3   # resident HBM bytes per ref
    n = meta["n"] if limit_refs is None else min(meta["n"], limit_refs)
    if n == 0:
        return None, 0, {"upload_s": 0.0, "upload_bytes": 0}
    bw = _resolve_bw(batch_windows)
    batch = bw * window
    n_batches = -(-n // batch)
    backend = jax.default_backend()
    stage = _stage_fn(backend)
    workers = _resolve_feed_workers(feed_workers)
    if d24v:
        if meta.get("batch") != batch:
            raise ValueError(
                f"d24v pack {packed_path} was cut at {meta.get('batch')} "
                f"refs/batch; this replay slices at {batch} "
                "(batch_windows * window) — match the pack's batching or "
                "repack")
        offsets = meta["offsets"]
        dec = _stage_decode_fn(backend)

    def read_fixed(b):
        """One fixed-width record, zero-padded to the batch shape."""
        with open(packed_path, "rb") as f:
            f.seek(b * batch * bpr)
            raw = np.fromfile(f, dtype=np.uint8,
                              count=min(batch, n - b * batch) * bpr)
        rec_bytes = len(raw)
        pad = batch * bpr - rec_bytes
        if pad:
            raw = np.concatenate([raw, np.zeros(pad, np.uint8)])
        return raw, rec_bytes

    def read_d24v(b):
        """One compressed record (header | width map | payload), padded
        for the decode kernel.  Truncation is a classified DataLoss
        naming the record — never a silent short decode."""
        from pluss.ops import wirecodec
        from pluss.resilience.errors import DataLoss

        count = min(batch, meta["n"] - b * batch)
        nb_blocks = -(-count // wirecodec.BLOCK)
        with open(packed_path, "rb") as f:
            f.seek(offsets[b])
            hdr = np.fromfile(f, dtype="<u4", count=1)
            wm = np.fromfile(f, dtype=np.uint8, count=nb_blocks)
            used = int(hdr[0]) if hdr.size else -1
            payload = np.fromfile(f, dtype=np.uint8, count=max(used, 0))
        if used < 0 or wm.size != nb_blocks or payload.size != used:
            raise DataLoss(
                f"truncated d24v pack {packed_path}: record {b} at byte "
                f"offset {offsets[b]} is cut short", site="trace.load")
        pp = np.zeros(wirecodec.pad_len(used), np.uint8)
        pp[:used] = payload
        return (pp, wm, count), 4 + wm.nbytes + used

    read_rec = read_d24v if d24v else read_fixed
    import contextlib

    if workers > 1:
        src = _FeedPool(0, n_batches, lambda b: None, read_rec,
                        lambda b, raw: raw, lambda b, mid: mid,
                        workers, depth=2)
    else:
        src = contextlib.nullcontext(read_rec(b) for b in range(n_batches))

    t0 = time.perf_counter()
    with obs.span("trace.stage_resident", refs=n, fmt=meta["fmt"],
                  batch_windows=bw, feed_workers=workers) as sp, \
            src as it:
        resident = jnp.zeros((n_batches, bw, window, bpr), jnp.uint8)
        staged = 0
        payload_bytes = 0   # real file bytes, excluding final-batch padding
        for b, (raw, rec_bytes) in zip(range(n_batches), it):
            payload_bytes += rec_bytes
            if d24v:
                pp, wm, count = raw
                chunk = dec(jnp.asarray(pp), jnp.asarray(wm), count,
                            batch).reshape(1, bw, window, 3)
            else:
                chunk = jnp.asarray(raw.reshape(1, bw, window, bpr))
            resident = stage(resident, chunk, jnp.int32(b))
            staged = b + 1
            if upload_budget_s is not None and staged < n_batches \
                    and staged % 16 == 0:
                # transfers are ASYNC: without a periodic sync the loop
                # finishes in milliseconds and the budget check never
                # sees real elapsed time (observed: 427s staged past a
                # 300s cap)
                np.asarray(resident[0, 0, 0, :1])
                if time.perf_counter() - t0 > upload_budget_s:
                    break
        np.asarray(resident[0, 0, 0, :1])  # force staging completion (tiny
        # d2h; block_until_ready does not actually wait over the tunnel)
        upload_s = time.perf_counter() - t0
        sp.set(staged_batches=staged, shrunk=staged < n_batches)
    obs.counter_add("trace.upload_s", upload_s)
    obs.counter_add("trace.upload_bytes", payload_bytes)
    if staged < n_batches:
        # budget-shrunk prefix: keep only the staged leading batches
        resident = jax.lax.slice_in_dim(resident, 0, staged, axis=0)
    return resident, min(n, staged * batch), {
        "upload_s": upload_s,
        "upload_bytes": payload_bytes if d24v else staged * batch * bpr}


def replay_staged(resident, n_lines: int, n_run: int,
                  window: int = TRACE_WINDOW, clock0: int = 0,
                  stats: dict | None = None,
                  segmented: bool | None = None) -> ReplayResult:
    """Replay an already-staged resident trace (see :func:`stage_resident`).

    ``clock0`` shifts the logical-clock origin — histogram-invariant, but
    makes repeat replays distinct inputs for the tunnel's content memo."""
    import time

    n_batches = resident.shape[0]
    batch = resident.shape[1] * window
    pos_dtype = ("int32" if clock0 + n_batches * batch < 2**31 - 2
                 else "int64")
    if pos_dtype == "int64" and not jax.config.jax_enable_x64:
        raise RuntimeError(
            f"trace of {n_run} accesses needs int64 positions; enable "
            "jax_enable_x64")
    pdt = np.dtype(pos_dtype)
    if segmented is None:
        segmented = _segmented_default()
    from pluss.ops import pallas_events

    fn = _resident_fn(window, pos_dtype, jax.default_backend(),
                      bool(segmented), pallas_events.enabled())
    last_pos = jnp.full((n_lines,), -1, pdt)
    hist = jnp.zeros((NBINS,), pdt)
    t0 = time.perf_counter()
    with obs.span("trace.replay_staged", refs=n_run), xprof.session(), \
            xprof.annotate("pluss.trace.replay_staged"):
        last_pos, hist = fn(resident, last_pos, hist,
                            pdt.type(clock0 + n_run), pdt.type(clock0))
        hist_np = np.asarray(hist, np.int64)   # d2h forces completion
    replay_s = time.perf_counter() - t0
    # resident refs get their OWN counter: trace.refs_replayed feeds the
    # streamed-path rate (refs / replay_file span wall) in `pluss stats`,
    # and one process often runs both paths (bench) — mixing them would
    # inflate the streamed rate by the resident volume
    obs.counter_add("trace.resident_replay_s", replay_s)
    obs.counter_add("trace.resident_refs", n_run)
    if stats is not None:
        stats["replay_s"] = replay_s
        stats["refs"] = n_run
    return ReplayResult(hist_np, n_run, n_lines)


def shard_replay(addrs: np.ndarray, cls: int = 64, mesh=None,
                 window: int = TRACE_WINDOW,
                 precompacted: bool = False) -> ReplayResult:
    """Replay one address stream SHARDED over a device mesh.

    The strict scan carry would serialize the stream; instead each device
    scans a contiguous segment of it, capturing accesses with no in-segment
    predecessor as HEADS, and one ``all_gather`` + masked-max over earlier
    segments resolves them against the carried tail tables — the same
    boundary exchange as the static shard backend
    (:mod:`pluss.parallel.shard`), collectives-only and therefore
    multi-host-ready.  Exact, not approximate: bit-identical to
    :func:`replay`.  This is the long-stream scale-out story for the trace
    path (BASELINE config 5 at pod scale); :func:`replay_file` remains the
    bounded-host-memory story.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from pluss.parallel.shard import _capture_heads, _vary, default_mesh

    mesh = mesh or default_mesh()
    D = mesh.devices.size
    if D == 1:
        return replay(addrs, cls, window, precompacted)
    addrs = np.asarray(addrs)
    if addrs.ndim != 1:
        raise ValueError("trace must be a 1-D address stream")
    n = addrs.shape[0]
    if n == 0:
        return ReplayResult(np.zeros(NBINS, np.int64), 0, 0)
    lines = addrs.astype(np.int64) if precompacted else lines_of(addrs, cls)
    ids, n_lines = _compact(lines, window)

    S = max(1, -(-n // (D * window)))
    total = D * S * window
    pos_dtype = "int32" if total < 2**31 - 2 else "int64"
    if pos_dtype == "int64" and not jax.config.jax_enable_x64:
        raise RuntimeError(
            f"trace of {n} accesses needs int64 positions; enable jax_enable_x64"
        )
    pdt = jnp.dtype(pos_dtype)
    ids_pad = np.zeros(total, np.int32)
    ids_pad[:n] = ids
    ids3 = ids_pad.reshape(D, S, window)

    def body(seg):
        d = jax.lax.axis_index("d")
        seg = seg[0]
        # cast BEFORE multiplying: d is int32 (axis_index) and the product
        # D*S*window is exactly what the int64 position path exists for
        base = d.astype(pdt) * (S * window)
        init = _vary((
            jnp.full((n_lines,), -1, pdt),   # last_pos (ends as tails)
            jnp.zeros((NBINS,), pdt),        # hist
            jnp.full((n_lines,), -1, pdt),   # head_pos
        ))

        def step(carry, xs):
            last_pos, hist, head_pos = carry
            s, line_w = xs
            pos_w = base + s.astype(pdt) * window + jnp.arange(window, dtype=pdt)
            valid_w = pos_w < n
            key_s, pos_s, span_s, valid_i = sort_stream(
                line_w, pos_w, None, valid_w, pos_sorted=True)
            ev, last_pos = window_events(key_s, pos_s, span_s, valid_i,
                                         last_pos)
            hist = hist + event_histogram(ev, include_cold=False)
            # first-in-segment touches: unique per line across the scan
            head_pos, _ = _capture_heads(head_pos, None, ev["cold"],
                                         key_s, pos_s, None, n_lines)
            return (last_pos, hist, head_pos), None

        (tail_pos, hist, head_pos), _ = jax.lax.scan(
            step, init, (jnp.arange(S, dtype=jnp.int32), seg))
        tails_all = jax.lax.all_gather(tail_pos, "d")       # [D, L]
        earlier = jnp.arange(D) < d
        prev = jnp.max(jnp.where(earlier[:, None], tails_all, -1), axis=0)
        has_head = head_pos >= 0
        evt = has_head & (prev >= 0)
        cold = has_head & (prev < 0)
        reuse = jnp.where(evt, head_pos - prev, 0)
        bins = jnp.where(evt, log2_bin(reuse), 0)
        hist = hist + bin_histogram(bins, evt.astype(pdt)).at[0].add(
            cold.sum().astype(pdt))
        return jax.lax.psum(hist, "d")

    from pluss.ops import pallas_events
    from pluss.utils import compat

    # suppressing(): no pallas_call replication rule under shard_map —
    # the body's event_histogram dispatch must bake in the XLA path
    f = jax.jit(compat.shard_map(pallas_events.suppressing(body),
                                 mesh=mesh, in_specs=P("d"),
                                 out_specs=P()))
    hist = f(ids3)
    return ReplayResult(np.asarray(hist, np.int64), n, n_lines)


@functools.lru_cache(maxsize=8)
def _steal_chunk_fn(backend: str, pos_dtype_name: str, fused: bool = False):
    """Per-device chunk executable of the work-stealing sharded replay:
    ONE :func:`pluss.ops.reuse.batch_events` call covers the whole chunk
    (the PR-4 segmented kernel — sort, carried gather, tail scatter), with
    a fresh carry per chunk; first-in-chunk touches are captured as HEADS
    for the host-side boundary merge.  ``L`` (the line-table capacity at
    the chunk's compaction time) is static — growth retraces, like
    :func:`replay_file`'s."""
    from pluss.parallel.shard import _capture_heads

    pdt = jnp.dtype(pos_dtype_name)

    def f(ids, base, n_valid, L):
        pos = base + jnp.arange(ids.shape[0], dtype=pdt)
        ev, tail = batch_events(ids, pos, pos < n_valid,
                                jnp.full((L,), -1, pdt))
        hist = event_histogram(ev, include_cold=False)
        head, _ = _capture_heads(jnp.full((L,), -1, pdt), None, ev["cold"],
                                 ev["key"], ev["pos"], None, L)
        return hist, head, tail

    return jax.jit(f, static_argnums=(3,))


def _steal_boundary_merge(results: dict, n_chunks: int, L: int,
                          np_head_hist) -> np.ndarray:
    """Canonical-order boundary merge of per-chunk (hist, heads, tails)
    results (the host twin of the static path's all_gather + masked-max
    tail exchange).  Stream order is fixed here regardless of which
    device ran which chunk — steal-order permutations (and the r13
    grouped-entry hit path, which re-dispatches stored chunks) are
    bit-identical by construction."""
    prev = np.full(L, -1, np.int64)
    hist = np.zeros(NBINS, np.int64)
    for k in range(n_chunks):
        h, hp, tp = results.pop(k)
        hist += np.asarray(h, np.int64)
        if hp.shape[0] < L:   # chunk ran at a pre-growth capacity
            pad = np.full(L - hp.shape[0], -1, hp.dtype)
            hp = np.concatenate([hp, pad])
            tp = np.concatenate([tp, pad])
        hp = hp.astype(np.int64)
        evt = (hp >= 0) & (prev >= 0)
        hist[0] += int(((hp >= 0) & (prev < 0)).sum())
        r = (hp - prev)[evt]
        if r.size:
            hist += np_head_hist(r)   # the shared binning rule
        prev = np.where(tp >= 0, tp.astype(np.int64), prev)
    return hist


def _shard_replay_file_steal(path: str, cls: int, mesh, window: int,
                             precompacted: bool,
                             batch_windows: int,
                             resident_cache: bool = False) -> ReplayResult:
    """Work-stealing sharded replay: a sequential reader+compactor feeds
    chunk ids into a bounded queue; per-device workers pull the next
    produced chunk (:class:`pluss.parallel.steal.QueueDispatcher` — idle
    devices rebalance themselves, counted as steals), and the host merges
    chunk boundaries with a running prefix-max in stream order.  The merge
    order is canonical, so the pull schedule never reaches the result —
    bit-identical to :func:`replay_file` / the static sharded scan.

    ``resident_cache=True`` additionally rides the r13 residency store: a
    trace too big for one chip is kept as ONE grouped entry of per-device
    chunk id arrays (byte-accounted as a unit); a hit skips the whole
    read+compact feed and re-dispatches the stored chunks straight into
    the same canonical merge."""
    from pluss import obs as _obs
    from pluss.parallel.shard import np_head_hist
    from pluss.parallel.steal import QueueDispatcher
    from pluss.resilience import faults

    devices = list(mesh.devices.ravel())
    D = len(devices)
    n = _u64_count(path)
    if n == 0:
        return ReplayResult(np.zeros(NBINS, np.int64), 0, 0)
    if cls & (cls - 1):
        raise ValueError(f"cache line size {cls} is not a power of two")
    shift = int(cls).bit_length() - 1
    bw = _resolve_bw(batch_windows)
    chunk = bw * window
    n_chunks = -(-n // chunk)
    pos_dtype = "int32" if n < 2**31 - 2 else "int64"
    if pos_dtype == "int64" and not jax.config.jax_enable_x64:
        raise RuntimeError(
            f"trace of {n} accesses needs int64 positions; enable "
            "jax_enable_x64")
    npdt = np.dtype(pos_dtype)
    from pluss.ops import pallas_events as _pe

    fn = _steal_chunk_fn(jax.default_backend(), pos_dtype, _pe.enabled())

    res_store = res_key = None
    if resident_cache:
        from pluss import residency

        res_store = residency.store()
        res_key = _residency_key(path, cls=cls, window=window, bw=bw,
                                 precompacted=precompacted, devices=devices)
        ent = res_store.lookup_pin(res_key, n_run=n)
        if ent is not None:
            # grouped-entry HIT: the compacted per-device chunks are
            # already in device memory — re-run the chunk kernels over
            # them (async dispatch pipelines across devices) and merge
            # in the same canonical order; no read, no compact, no h2d
            try:
                results = {}
                for k, (ids_dev, cap_k) in enumerate(ent.value):
                    out = fn(ids_dev, npdt.type(k * chunk), npdt.type(n),
                             int(cap_k))
                    results[k] = out
                results = {k: tuple(np.asarray(x) for x in v)
                           for k, v in results.items()}
                hist = _steal_boundary_merge(results, n_chunks,
                                             ent.n_lines, np_head_hist)
                _obs.counter_add("trace.shard_refs_replayed", n)
                return ReplayResult(hist, n, ent.n_lines)
            finally:
                res_store.unpin(res_key)

    comp = _Compactor()
    read_raw = _extent_reader(path, chunk, n)
    compact = _compact_stage(comp, shift, precompacted, snapshot=False)
    results: dict[int, tuple] = {}
    staged: dict[int, tuple] = {}
    st_through = res_store is not None
    if st_through:
        from pluss.resilience.errors import ResourceExhausted

        try:
            # compacted ids ship int32: 4 B/ref, grouped as one entry
            res_store.reserve(n_chunks * chunk * 4)
        except ResourceExhausted:
            st_through = False   # reserve counted the fallback

    def produce():
        for k in range(n_chunks):
            faults.check("trace.read_batch")  # chaos injection site
            ids, cap_k, _ = compact(k, read_raw(k))
            if len(ids) < chunk:
                ids = np.concatenate(
                    [ids, np.zeros(chunk - len(ids), np.int32)])
            yield k, (ids, cap_k)

    def run_chunk(wi, k, payload):
        ids, cap_k = payload
        dev = devices[wi]
        ids_dev = jax.device_put(ids, dev)
        out = fn(ids_dev, npdt.type(k * chunk), npdt.type(n), int(cap_k))
        if st_through:
            staged[k] = (ids_dev, cap_k)
        results[k] = tuple(np.asarray(x) for x in out)

    disp = QueueDispatcher(D, run_chunk, depth=D + 2)
    with _obs.span("trace.shard_replay_file", refs=n, devices=D,
                   dispatch="steal") as sp:
        stats = disp.run(produce(), n_chunks)
        L = comp.next_free
        hist = _steal_boundary_merge(results, n_chunks, L, np_head_hist)
        sp.set(chunks=n_chunks, steals=stats["steals"])
    if st_through and len(staged) == n_chunks:
        value = tuple(staged[k] for k in range(n_chunks))
        res_store.put(res_key, value, n_lines=comp.next_free, n_run=n,
                      nbytes=sum(int(v[0].nbytes) for v in value),
                      meta={"path": path, "grouped": True, "devices": D})
        _obs.counter_add("residency.stage_through")
        _obs.trace_event("residency.stage_through",
                         nbytes=sum(int(v[0].nbytes) for v in value))
    _obs.counter_add("shard.chunks", n_chunks)
    _obs.counter_add("shard.steals", stats["steals"])
    _obs.counter_add("trace.shard_refs_replayed", n)
    for i, bf in enumerate(stats["busy_frac"]):
        _obs.gauge_set(f"shard.device_busy_frac.{i}", round(bf, 4))
    return ReplayResult(hist, n, comp.next_free)


def shard_replay_file(path: str, cls: int = 64, mesh=None,
                      window: int = TRACE_WINDOW,
                      precompacted: bool = False,
                      batch_windows: int = WINDOWS_PER_BATCH,
                      initial_capacity: int = 1 << 20,
                      checkpoint_path: str | None = None,
                      checkpoint_every: int = 4,
                      resume: bool = False,
                      dispatch: str | None = None,
                      resident_cache: bool | None = None) -> ReplayResult:
    """Device-sharded replay streamed from DISK in bounded host memory.

    :func:`shard_replay` holds the whole compacted trace in host RAM —
    fine for demonstrating the exchange, wrong at the 1e9-ref scale it
    targets.  Here each device's segment streams from its own file offsets
    (``replay_file``'s offset math per segment) in ``batch_windows``-sized
    slices: one ``shard_map`` call per slice scans it with DEVICE-RESIDENT
    sharded carries (last_pos / hist / head_pos per device), and a final
    call runs the cross-segment head exchange (``all_gather`` + masked max
    + ``psum``) exactly like :func:`shard_replay`.  Host transient memory
    is one [D, batch_windows, window] slice; results are bit-identical to
    :func:`replay_file` / :func:`replay`.

    Line-id consistency: a single host-side compactor maps every slice (in
    a fixed device-major order), so ids agree across segments.  Under
    multi-process ``jax.distributed`` each process would discover clusters
    in a different order; that needs a pre-agreed table, so this path
    requires a single process (or ``precompacted`` ids).

    ``checkpoint_path`` + ``resume``: crash recovery, same contract as
    :func:`replay_file` — every ``checkpoint_every`` step calls, the
    sharded device carries (last_pos / hist / head_pos, all [D, cap]) are
    fetched and written to ``checkpoint_path + '.npz'`` while the stream
    position, compactor table, and run identity journal to
    ``checkpoint_path`` as an atomic JSONL record
    (:class:`pluss.resilience.journal.Journal`, PR-2 substrate);
    ``resume=True`` restores the carries sharded back onto the mesh and
    continues from the recorded call — bit-identical to an uninterrupted
    run.  A checkpoint for a different (file, shape, mesh) identity is
    ignored with a notice, never spliced.

    ``dispatch``: ``steal`` (single-process default — per-device workers
    pull chunks off a bounded queue fed by the sequential
    reader+compactor, so a device that finishes early immediately serves
    the next chunk instead of idling behind the static segment split),
    ``static`` (the shard_map segment scan — the multi-process mode, and
    the only mode that checkpoints: the checkpoint identity IS the static
    segment grid, so ``checkpoint_path`` pins it), or ``auto``/None
    (``PLUSS_SHARD_DISPATCH``).  Bit-identical either way.

    ``resident_cache``: steal-dispatch only — keep the compacted
    per-device chunks as ONE grouped entry in the r13 residency store
    (:mod:`pluss.residency`), so a repeat replay of a trace too big for
    one chip skips the read+compact feed entirely.  Ignored (with the
    store untouched) on the static path, whose device carries are
    rebuilt per call.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pluss.parallel.shard import _capture_heads, _vary, default_mesh
    from pluss.resilience.journal import Journal
    from pluss.utils import compat

    batch_windows = _resolve_bw(batch_windows)
    mesh = mesh or default_mesh()
    D = mesh.devices.size
    if jax.process_count() > 1 and not precompacted:
        raise RuntimeError(
            "shard_replay_file needs precompacted ids under multi-process "
            "execution (per-process cluster discovery would diverge)"
        )
    from pluss.parallel.shard import _auto_steal, _resolve_dispatch

    if resident_cache is not None and not isinstance(resident_cache, bool):
        raise ValueError(
            f"resident_cache must be a bool or None, got {resident_cache!r}")
    eff = _resolve_dispatch(dispatch)
    if eff == "auto":
        eff = "steal" if _auto_steal(_u64_count(path)) else "static"
    if eff == "steal" and checkpoint_path is not None:
        if dispatch == "steal":
            import sys

            print("trace: checkpointing pins the static sharded dispatch "
                  "(the checkpoint identity is the static segment grid); "
                  "using dispatch='static'", file=sys.stderr)
        eff = "static"
    if eff == "steal" and D > 1:
        return _shard_replay_file_steal(path, cls, mesh, window,
                                        precompacted, batch_windows,
                                        resident_cache=bool(resident_cache))
    n = _u64_count(path)
    if n == 0:
        return ReplayResult(np.zeros(NBINS, np.int64), 0, 0)
    if cls & (cls - 1):
        raise ValueError(f"cache line size {cls} is not a power of two")
    shift = int(cls).bit_length() - 1
    S = max(1, -(-n // (D * window)))
    total = D * S * window
    pos_dtype = "int32" if total < 2**31 - 2 else "int64"
    if pos_dtype == "int64" and not jax.config.jax_enable_x64:
        raise RuntimeError(
            f"trace of {n} accesses needs int64 positions; enable jax_enable_x64"
        )
    pdt = jnp.dtype(pos_dtype)
    npdt = np.dtype(pos_dtype)
    SB = min(batch_windows, S)
    n_calls = -(-S // SB)
    comp = _Compactor()
    step_cache: dict = {}
    # the CPU backend does not support donation (would warn once per call)
    donate = (1, 2, 3) if jax.default_backend() != "cpu" else ()

    def read_slice(f, d: int, k: int) -> np.ndarray:
        """Device d's k-th slice of ids, zero-padded to SB*window.

        The read clips at BOTH the stream end and the segment end — when S
        is not a multiple of batch_windows the final slice would otherwise
        spill into segment d+1, whose owner also processes those refs."""
        from pluss.resilience import faults

        faults.check("trace.read_batch")  # chaos injection site
        lo = d * S * window + k * SB * window
        seg_end = (d + 1) * S * window
        count = max(0, min(SB * window, n - lo, seg_end - lo))
        out = np.zeros(SB * window, np.int32)
        if count:
            f.seek(lo * 8)
            raw = np.fromfile(f, dtype="<u8", count=count)
            ids = comp.map_raw(raw, 0 if precompacted else shift)
            if ids is None:
                lines = raw.astype(np.int64) if precompacted \
                    else raw.astype(np.int64) >> shift
                ids = comp.map(lines)
            out[:count] = ids
        return out

    def step_call(L: int):
        """shard_map: scan one [SB, window] slice per device, carrying
        (last_pos, hist, head_pos).  Cached per table capacity — growth
        retraces, like replay_file's."""
        if L in step_cache:
            return step_cache[L]

        def body(k0, last_pos, hist, head_pos, seg):
            d = jax.lax.axis_index("d")
            seg, last_pos = seg[0], last_pos[0]
            hist, head_pos = hist[0], head_pos[0]
            base = d.astype(pdt) * (S * window)

            def step(carry, xs):
                last_pos, hist, head_pos = carry
                s, line_w = xs
                pos_w = base + s.astype(pdt) * window \
                    + jnp.arange(window, dtype=pdt)
                # s >= S marks a ragged final slice's padding windows: their
                # positions fall inside the NEXT device's segment and must
                # not be counted here
                valid_w = (pos_w < n) & (s < S)
                key_s, pos_s, span_s, valid_i = sort_stream(
                    line_w, pos_w, None, valid_w, pos_sorted=True)
                ev, last_pos = window_events(key_s, pos_s, span_s, valid_i,
                                             last_pos)
                hist = hist + event_histogram(ev, include_cold=False)
                head_pos, _ = _capture_heads(head_pos, None, ev["cold"],
                                             key_s, pos_s, None, L)
                return (last_pos, hist, head_pos), None

            (last_pos, hist, head_pos), _ = jax.lax.scan(
                step, _vary((last_pos, hist, head_pos)),
                (k0 + jnp.arange(SB, dtype=jnp.int32), seg))
            return (last_pos[None], hist[None], head_pos[None])

        from pluss.ops import pallas_events

        # suppressing(): no pallas_call replication rule under shard_map
        fn = jax.jit(
            compat.shard_map(pallas_events.suppressing(body), mesh=mesh,
                             in_specs=(P(), P("d"), P("d"), P("d"), P("d")),
                             out_specs=(P("d"), P("d"), P("d"))),
            donate_argnums=donate,
        )
        step_cache[L] = fn
        return fn

    def finish_call(L: int):
        def body(last_pos, hist, head_pos):
            d = jax.lax.axis_index("d")
            last_pos, hist, head_pos = last_pos[0], hist[0], head_pos[0]
            tails_all = jax.lax.all_gather(last_pos, "d")      # [D, L]
            earlier = jnp.arange(D) < d
            prev = jnp.max(jnp.where(earlier[:, None], tails_all, -1),
                           axis=0)
            has_head = head_pos >= 0
            evt = has_head & (prev >= 0)
            cold = has_head & (prev < 0)
            reuse = jnp.where(evt, head_pos - prev, 0)
            bins = jnp.where(evt, log2_bin(reuse), 0)
            hist = hist + bin_histogram(bins, evt.astype(pdt)).at[0].add(
                cold.sum().astype(pdt))
            return jax.lax.psum(hist, "d")

        return jax.jit(compat.shard_map(
            body, mesh=mesh, in_specs=(P("d"), P("d"), P("d")),
            out_specs=P()))

    sh = NamedSharding(mesh, P("d"))
    capacity = initial_capacity

    def dev_full(cap):
        return (
            jax.device_put(np.full((D, cap), -1, npdt), sh),
            jax.device_put(np.zeros((D, NBINS), npdt), sh),
            jax.device_put(np.full((D, cap), -1, npdt), sh),
        )

    ident = {"n": n, "window": window, "cls": cls,
             "precompacted": bool(precompacted), "D": D, "SB": SB,
             "fp": _trace_fingerprint(path) if checkpoint_path else ""}
    jr = Journal(checkpoint_path) if checkpoint_path else None
    npz_path = checkpoint_path + ".npz" if checkpoint_path else None
    k0 = 0
    last_pos = hist = head_pos = None
    #: a pre-existing checkpoint belonging to a DIFFERENT run must not be
    #: retired at the end of THIS run — that run may still want to resume
    foreign_ckpt = False
    if jr is not None and len(jr):
        rec0 = jr.get({"shard_ckpt": 1})
        foreign_ckpt = rec0 is None or any(
            rec0.get(k_) != v for k_, v in ident.items())
        if foreign_ckpt and not resume:
            # the caller aimed a fresh run at someone else's checkpoint:
            # the first checkpoint write below will overwrite it — say so
            # BEFORE it happens, not after the other run fails to resume
            import sys

            print(f"trace: {checkpoint_path} holds a checkpoint for a "
                  "DIFFERENT run; this run will overwrite it at its "
                  "first checkpoint", file=sys.stderr)
    if resume and jr is not None and len(jr) and os.path.exists(npz_path):
        rec = jr.get({"shard_ckpt": 1})
        if rec is None or any(rec.get(k_) != v for k_, v in ident.items()):
            import sys

            print(f"trace: shard checkpoint {checkpoint_path} is for a "
                  "different run; starting fresh", file=sys.stderr)
        else:
            try:
                with np.load(npz_path) as z:
                    if int(z["k_next"]) != rec["k_next"]:
                        raise ValueError(
                            "journal/array checkpoint out of step")
                    k0 = int(z["k_next"])
                    capacity = int(z["capacity"])
                    last_pos = jax.device_put(
                        z["last_pos"].astype(npdt), sh)
                    hist = jax.device_put(z["hist"].astype(npdt), sh)
                    head_pos = jax.device_put(
                        z["head_pos"].astype(npdt), sh)
                    comp = _Compactor.restore(rec["comp"])
                import sys

                print(f"trace: resuming sharded replay at call "
                      f"{k0}/{n_calls}", file=sys.stderr)
            except Exception as e:
                from pluss.resilience.errors import quarantine_artifact

                quarantine_artifact(npz_path, "shard replay-checkpoint",
                                    e, action="starting fresh")
                k0 = 0
                last_pos = None
    if last_pos is None:
        last_pos, hist, head_pos = dev_full(capacity)

    def save_ckpt(k_next: int) -> None:
        # d2h fetch synchronizes the mesh — the price of a durable point;
        # the arrays land first (atomic replace), then the journal line
        # that promises them (same ordering rule as pack_file)
        nonlocal foreign_ckpt
        foreign_ckpt = False   # the checkpoint now describes THIS run
        tmp = f"{npz_path}.tmp.{os.getpid()}.npz"
        np.savez(tmp, k_next=np.int64(k_next),
                 capacity=np.int64(capacity),
                 last_pos=np.asarray(last_pos),
                 hist=np.asarray(hist),
                 head_pos=np.asarray(head_pos))
        os.replace(tmp, npz_path)
        jr.record({"shard_ckpt": 1}, k_next=k_next,
                  comp=comp.snapshot(), **ident)

    with open(path, "rb") as f:
        for k in range(k0, n_calls):
            ids = np.stack([read_slice(f, d, k) for d in range(D)])
            if comp.next_free > capacity:
                # table growth: re-pad the carries at the new capacity
                # (growth is rare: O(log) times over a whole trace)
                lp, hi, hp = (np.asarray(last_pos), np.asarray(hist),
                              np.asarray(head_pos))
                while capacity < comp.next_free:
                    capacity *= 2
                pad = capacity - lp.shape[1]
                last_pos = jax.device_put(np.concatenate(
                    [lp, np.full((D, pad), -1, npdt)], axis=1), sh)
                hist = jax.device_put(hi, sh)
                head_pos = jax.device_put(np.concatenate(
                    [hp, np.full((D, pad), -1, npdt)], axis=1), sh)
            last_pos, hist, head_pos = step_call(capacity)(
                npdt.type(k * SB),
                last_pos, hist, head_pos,
                jax.device_put(ids.reshape(D, SB, window), sh),
            )
            if jr is not None and k + 1 < n_calls \
                    and (k + 1 - k0) % checkpoint_every == 0:
                save_ckpt(k + 1)
    out = finish_call(capacity)(last_pos, hist, head_pos)
    if jr is not None and not foreign_ckpt:
        # a finished run retires its checkpoint (a later DIFFERENT run
        # must not resume from this one's final state) — but never a
        # checkpoint that belongs to SOMEONE ELSE's interrupted run
        for p_ in (checkpoint_path, npz_path):
            try:
                os.unlink(p_)
            except OSError:
                pass
    return ReplayResult(np.asarray(out, np.int64), n, comp.next_free)


def _u64_count(path: str) -> int:
    """Record count of a packed-u64 trace, REJECTING truncated files.

    A byte length that is not a multiple of 8 means the capture (or a
    copy) was cut mid-record; silently flooring the count would misparse
    every later analysis, so it is a classified
    :class:`~pluss.resilience.errors.DataLoss` naming the exact offset.
    """
    from pluss.resilience.errors import DataLoss

    size = os.path.getsize(path)
    if size % 8:
        raise DataLoss(
            f"truncated u64 trace {path}: {size} bytes is not a multiple "
            f"of 8 ({size % 8} trailing bytes after the last whole record "
            f"at byte offset {size - size % 8})", site="trace.load")
    return size // 8


def load_trace(path: str, fmt: str = "u64") -> np.ndarray:
    """Load a trace file.

    ``fmt``: ``u64`` — packed little-endian uint64 byte addresses (the shape
    DynamoRIO's memtrace samples reduce to); ``text`` — one address per line,
    decimal or 0x-hex.

    Malformed input is a classified :class:`DataLoss` naming the byte
    offset (u64: length not a multiple of 8) or line number (text: a line
    that parses as neither decimal nor 0x-hex) — never a silent misparse.
    """
    if fmt == "u64":
        _u64_count(path)
        return np.fromfile(path, dtype="<u8").astype(np.int64)
    if fmt == "text":
        from pluss.resilience.errors import DataLoss

        out = []
        with open(path) as f:
            for lineno, s in enumerate(f, 1):
                s = s.strip()
                if not s:
                    continue
                try:
                    out.append(int(s, 0))
                except ValueError:
                    raise DataLoss(
                        f"garbage text-trace line {lineno} of {path}: "
                        f"{s[:40]!r} is neither decimal nor 0x-hex",
                        site="trace.load") from None
        return np.asarray(out, np.int64)
    raise ValueError(f"unknown trace format {fmt!r}")
