"""Dynamic trace replay: reuse histograms from raw address streams.

The live reference samples *statically* (no trace), but its runtime keeps a
disabled trace-driven API — ``pluss_access(addr)`` masking addresses to cache
lines and probing a global last-access map (``/root/reference/c_lib/test/
runtime/pluss.cpp:126-402``, ``CACHE_MASK`` at :13) — and BASELINE.json
config 5 calls for replaying raw DynamoRIO-style memory traces at 1e9 refs.

TPU-native design: the same windowed sort-based extraction as the static
engine (:mod:`pluss.ops.reuse`), fed by a *compacted* line-id stream instead of
affine enumeration:

1. Host pass: mask raw byte addresses to cache lines (``addr >> log2(CLS)``)
   and remap to dense ids — small line ranges map by offset directly; sparse
   traces go through cluster probing (discovered memory regions with slack id
   space; only cluster MISSES are ever sorted) — the TPU equivalent of the
   reference's unbounded ``unordered_map`` LAT over raw lines, in bounded
   memory.
2. Device scan: ``lax.scan`` over fixed-size windows carrying
   ``last_pos[line]`` + the dense histogram, identical to the static path —
   arbitrarily long streams in bounded device memory (donated carry).

A replayed trace is single-clock (one logical time per access, the reference's
``pluss_access`` semantics), so the result feeds :func:`pluss.mrc.aet_mrc`
directly — no CRI dilation, exactly like the reference's trace path, which
bypasses the CRI model entirely.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from pluss.config import NBINS
from pluss.ops.reuse import event_histogram, sort_stream, window_events

#: default accesses per device window; 2^20 wins the sort-cost vs
#: scan-step-count tradeoff on TPU (measured 2026-07-30)
TRACE_WINDOW = 1 << 20


def lines_of(addrs: np.ndarray, cls: int = 64) -> np.ndarray:
    """Mask byte addresses to cache-line ids (the reference's CACHE_MASK
    shift, pluss.cpp:13,137)."""
    if cls & (cls - 1):
        raise ValueError(f"cache line size {cls} is not a power of two")
    return np.asarray(addrs, np.int64) >> int(cls).bit_length() - 1


@dataclasses.dataclass
class ReplayResult:
    """Dense log2 reuse histogram of one replayed stream.

    ``hist[0]`` = cold (first-touch) count, ``hist[1+e]`` = reuses in
    [2^e, 2^{e+1}).  ``histogram()`` returns the reference-keyed dict view
    (cold key -1), directly consumable by :func:`pluss.mrc.aet_mrc`.
    """

    hist: np.ndarray          # [NBINS] int64
    total_count: int
    n_lines: int

    def histogram(self) -> dict:
        out = {-1: float(self.hist[0])}
        for e in range(NBINS - 1):
            if self.hist[1 + e]:
                out[1 << e] = float(self.hist[1 + e])
        return out


#: windows shipped to the device per batch; one compile serves a trace of any
#: length because every batch has the same [WINDOWS_PER_BATCH, window] shape
WINDOWS_PER_BATCH = 8


@functools.lru_cache(maxsize=16)
def _replay_fn(window: int, n_lines: int, pos_dtype_name: str):
    pdt = jnp.dtype(pos_dtype_name)

    def run(last_pos, hist, base, ids, valid):
        # ids, valid: [WINDOWS_PER_BATCH, window]; base: batch stream offset
        pos = (
            base
            + jnp.arange(WINDOWS_PER_BATCH, dtype=pdt)[:, None] * window
            + jnp.arange(window, dtype=pdt)[None, :]
        )

        def step(carry, xs):
            last_pos, hist = carry
            line_w, pos_w, valid_w = xs
            # trace windows arrive in stream order: stable single-key sort,
            # no span payload (the trace path has no share classification)
            ev, last_pos = window_events(
                *sort_stream(line_w, pos_w, None, valid_w, pos_sorted=True),
                last_pos,
            )
            return (last_pos, hist + event_histogram(ev)), None

        (last_pos, hist), _ = jax.lax.scan(
            step, (last_pos, hist), (ids, pos, valid)
        )
        return last_pos, hist

    # donating the carry keeps last_pos/hist in place on device across
    # batches; the CPU backend does not support donation and would warn once
    # per batch, so donate only off-CPU (there the copy is cheap anyway)
    donate = (0, 1) if jax.default_backend() != "cpu" else ()
    return jax.jit(run, donate_argnums=donate)


def replay(addrs: np.ndarray, cls: int = 64, window: int = TRACE_WINDOW,
           precompacted: bool = False) -> ReplayResult:
    """Replay a raw address stream into a reuse histogram.

    ``addrs``: 1-D array of byte addresses (or dense line ids when
    ``precompacted`` — e.g. synthetic workloads that already index lines).
    """
    addrs = np.asarray(addrs)
    if addrs.ndim != 1:
        raise ValueError("trace must be a 1-D address stream")
    n = addrs.shape[0]
    if n == 0:
        return ReplayResult(np.zeros(NBINS, np.int64), 0, 0)
    lines = addrs.astype(np.int64) if precompacted else lines_of(addrs, cls)

    # dense-range shortcut: when the touched lines span a small range the
    # offset IS the id — no vocabulary pass at all (last_pos is sized by the
    # range; untouched slots just stay -1)
    lo_line, hi_line = int(lines.min()), int(lines.max())
    if hi_line - lo_line < 1 << 24:
        ids = (lines - lo_line).astype(np.int32)
        return _replay_ids(ids, int(hi_line - lo_line + 1), n, window)

    # host compaction by CLUSTER PROBING: real traces touch a few contiguous
    # memory regions, so instead of a per-chunk sort into a line vocabulary,
    # probe each chunk against the discovered cluster table (one searchsorted
    # over ~dozens of clusters) and sort only the MISSES — which vanish once
    # the working set is discovered.  A new cluster reserves `slack` id slots
    # past its observed end so right-growth keeps already-assigned ids
    # stable; ids are region offsets, so `n_lines` counts allocated table
    # slots (>= touched lines).
    slack = 1024
    starts = np.empty(0, np.int64)   # cluster start line, sorted
    widths = np.empty(0, np.int64)   # id slots allocated to the cluster
    bases = np.empty(0, np.int64)    # cluster's first id
    next_free = 0
    ids = np.empty(n, np.int32)

    def map_into(chunk, out):
        cl = np.searchsorted(starts, chunk, side="right") - 1
        clc = np.maximum(cl, 0)
        inside = (cl >= 0) & (chunk < starts[clc] + widths[clc])
        out[inside] = (bases[clc] + (chunk - starts[clc]))[inside]
        return inside

    for lo in range(0, n, window):
        chunk = lines[lo:lo + window]
        view = ids[lo:lo + window]
        inside = map_into(chunk, view) if len(starts) else \
            np.zeros(len(chunk), bool)
        miss = chunk[~inside]
        if not miss.size:
            continue
        mu = np.unique(miss)
        brk = np.nonzero(np.diff(mu) > slack)[0] + 1
        seg_s = mu[np.concatenate([[0], brk])]
        seg_e = mu[np.concatenate([brk - 1, [len(mu) - 1]])]
        for s, e in zip(seg_s.tolist(), seg_e.tolist()):
            # clamp the slack so cluster ranges never overlap the next one
            j = np.searchsorted(starts, s, side="right")
            limit = int(starts[j]) if j < len(starts) else None
            w = e - s + 1 + slack
            if limit is not None:
                w = min(w, limit - s)
            starts = np.insert(starts, j, s)
            widths = np.insert(widths, j, w)
            bases = np.insert(bases, j, next_free)
            next_free += w
        sub = np.empty(miss.size, np.int32)
        ok = map_into(miss, sub)
        assert ok.all()
        view[~inside] = sub
        if next_free >= 1 << 31:
            raise RuntimeError(
                "trace line-id space exhausted; lines too fragmented for "
                "cluster compaction"
            )
    return _replay_ids(ids, next_free, n, window)


def _replay_ids(ids: np.ndarray, n_lines: int, n: int,
                window: int) -> ReplayResult:
    """Stream dense line ids through the device scan in fixed-shape batches."""
    batch = WINDOWS_PER_BATCH * window
    n_batches = -(-n // batch)
    pos_dtype = "int32" if n_batches * batch < 2**31 - 2 else "int64"
    if pos_dtype == "int64" and not jax.config.jax_enable_x64:
        raise RuntimeError(
            f"trace of {n} accesses needs int64 positions; enable jax_enable_x64"
        )
    fn = _replay_fn(window, n_lines, pos_dtype)
    pdt = np.dtype(pos_dtype)
    last_pos = jnp.full((n_lines,), -1, pdt)
    hist = jnp.zeros((NBINS,), pdt)
    for b in range(n_batches):
        lo = b * batch
        chunk = ids[lo:lo + batch]
        pad = batch - len(chunk)
        valid = np.ones(batch, bool)
        if pad:
            chunk = np.concatenate([chunk, np.zeros(pad, np.int32)])
            valid[batch - pad:] = False
        last_pos, hist = fn(
            last_pos, hist, pdt.type(lo),
            jnp.asarray(chunk.reshape(WINDOWS_PER_BATCH, window)),
            jnp.asarray(valid.reshape(WINDOWS_PER_BATCH, window)),
        )
    return ReplayResult(np.asarray(hist, np.int64), n, n_lines)


def load_trace(path: str, fmt: str = "u64") -> np.ndarray:
    """Load a trace file.

    ``fmt``: ``u64`` — packed little-endian uint64 byte addresses (the shape
    DynamoRIO's memtrace samples reduce to); ``text`` — one address per line,
    decimal or 0x-hex.
    """
    if fmt == "u64":
        return np.fromfile(path, dtype="<u8").astype(np.int64)
    if fmt == "text":
        with open(path) as f:
            return np.asarray([int(s, 0) for s in f if s.strip()], np.int64)
    raise ValueError(f"unknown trace format {fmt!r}")
