"""Sweep groups: closed-form histograms for D+S array pairs in triangular
nests — the companion of :mod:`pluss.rowpriv` for the OTHER half of the
stream.

After row-private extraction, syrk_tri's device sort still walks its ``A``
array: ``D = A0 = A[i][k]`` (the top row, walked by the mid loop, moving
with the parallel loop) and ``S = A1 = A[j][k]`` (a sweep over all rows
``j <= i`` every iteration) — the mixed-coefficient pair that defeats
templates (round 2) and, in its rectangular form, motivated the
interleave overlay (round 3).  The triangular variant yields to a
per-iteration closed form.  With line ``(r, o)`` = row r, column-octave
``o = k // lpe``:

- S touches ``(r, o)`` once per ``k`` of octave o (at ``j = r``): ``lpe``
  touches with uniform gap ``S_k``, one head per iteration;
- D touches only the top row ``(g, o)``: per ``k``, ``m`` consecutive
  touches (the inner loop sweeps j with D's line fixed) at gap ``s_j``,
  the S touch at ``j = g`` rides ``off_S - off_D`` behind D's last, and
  the bridge back to the next ``k``'s first D touch closes the octave;
- cross-ITERATION heads resolve against the previous owned iteration's
  octave-o last touch — closed form because the schedule is — and rows
  the triangle just grew are colds.

Six gap classes, affine in ``(g, o)``.  Share classification applies the
ACCESSING ref's span per class, so the big cross-iteration heads land raw
in the share dict (exact values, exact counts) and everything else bins —
no device work at all.

The whole A contribution becomes a host-precomputed ``[T, NW, NBINS]``
histogram table plus per-thread static share (value, count) lists.  With
both C (rowpriv) and A (here) closed-formed, syrk_tri's windows are pure
table adds.

Exactness is checked, not argued (the overlay/rowpriv contract): a
per-slot COUNT INVARIANT (class counts must sum to the iteration's exact
D+S stream length) runs for every slot, and sampled (previous, current)
iteration pairs — including chunk jumps and first-slot colds — replay
through a brute two-iteration lexsort oracle; any mismatch disables the
group and the refs stay on the device sort path.

Replaces the reference's hashmap walk behavior on these accesses
(``/root/reference/src/gemm_sampler.rs:123-133``) at zero device work per
window.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

from pluss.config import NBINS, SamplerConfig
from pluss.ops.reuse import share_mask
from pluss.spec import FlatRef, LoopNestSpec


def eligible(spec: LoopNestSpec, ni: int, frs: list[FlatRef],
             cfg: SamplerConfig, sched) -> str | None:
    """None if the array's refs form an eligible (D, S) sweep pair."""
    arr = frs[0].ref.array
    from pluss.spec import flatten_nest

    for oi, nest in enumerate(spec.nests):
        if oi != ni and any(fr.ref.array == arr
                            for fr in flatten_nest(nest)):
            return f"array {arr} is touched by nest {oi} too"
    if len(frs) != 2:
        return "not exactly two refs"
    d = [fr for fr in frs if fr.addr_coefs[0]]
    s = [fr for fr in frs if not fr.addr_coefs[0]]
    if len(d) != 1 or len(s) != 1:
        return "no unique (moving, sweeping) split"
    d, s = d[0], s[0]
    if len(d.trips) != 3 or len(s.trips) != 3:
        return "level chain is not (parallel, mid, inner)"
    if d.trips != s.trips or d.pos_strides != s.pos_strides or \
            d.pos_strides_k != s.pos_strides_k or d.bounds != s.bounds or \
            d.starts != s.starts or d.steps != s.steps or \
            (d.starts_k or (0, 0, 0)) != (s.starts_k or (0, 0, 0)):
        return "refs differ beyond their position offset"
    if d.bounds is None or d.bounds[2] != (1, 1) or d.bounds[1] is not None:
        return "inner bound is not the (1, 1) triangle"
    if any(d.starts[1:]) or any(d.steps[l] != 1 for l in (1, 2)) or \
            (d.starts_k and any(d.starts_k)):
        return "mid/inner walks are not 0-based unit walks"
    c0 = d.addr_coefs[0]
    # D: addr = base + c0*g + 1*k (top row, walked by the mid loop);
    # S: addr = base + c0*j + 1*k (row j, same column walk)
    if d.addr_coefs[1] != 1 or d.addr_coefs[2] != 0:
        return "moving ref is not a mid-walked top row"
    if s.addr_coefs[1] != 1 or s.addr_coefs[2] != c0:
        return "sweeping ref does not stride the same row space"
    if s.offset <= d.offset or s.offset_k != d.offset_k:
        return "sweeping ref does not trail the moving ref in the body"
    if d.ref.addr_base != s.ref.addr_base:
        return "refs disagree on the base address"
    if d.ref.share_span:
        return "moving ref carries a share span"
    if sched.start != 0 or sched.step != 1:
        return "parallel loop is not a 0-based unit walk"
    ds, cls = cfg.ds, cfg.cls
    if cls % ds:
        return "element size does not divide the line size"
    lpe = cls // ds
    K = d.trips[1]
    if K % lpe:
        return "mid trip not a whole number of line octaves"
    if (c0 * ds) % cls or (d.ref.addr_base * ds) % cls:
        return "rows are not cache-line aligned"
    if d.trips[2] - 1 >= c0:
        return "row walk spills into the next row"
    return None


def brute_pair_hist(d: FlatRef, s: FlatRef, cfg: SamplerConfig,
                    g_prev: int | None, g: int,
                    clk_prev: int, clk: int):
    """(hist [NBINS], share {value: count}) of iteration ``g``'s D+S
    events, with iteration ``g_prev`` (same thread) as the warm-up that
    seeds the table — the verification oracle for one slot."""
    ds, cls = cfg.ds, cfg.cls

    def stream(fr, gi, clk0):
        m = min(1 + gi, fr.trips[2])
        K = fr.trips[1]
        k = np.arange(K)[:, None]
        j = np.arange(m)[None, :]
        sk = fr.pos_strides[1] + (fr.pos_strides_k[1] if fr.pos_strides_k
                                  else 0) * gi
        sj = fr.pos_strides[2] + (fr.pos_strides_k[2] if fr.pos_strides_k
                                  else 0) * gi
        pos = clk0 + fr.offset + fr.offset_k * gi + k * sk + j * sj
        addr = fr.ref.addr_base + fr.addr_coefs[0] * gi \
            + fr.addr_coefs[1] * k + fr.addr_coefs[2] * j
        addr = np.broadcast_to(addr, pos.shape)
        span = fr.ref.share_span or 0
        return (pos.ravel(), (addr.ravel() * ds) // cls,
                np.full(pos.size, span, np.int64))

    parts = []
    if g_prev is not None:
        parts += [stream(d, g_prev, clk_prev), stream(s, g_prev, clk_prev)]
    parts += [stream(d, g, clk), stream(s, g, clk)]
    pos = np.concatenate([p[0] for p in parts])
    line = np.concatenate([p[1] for p in parts])
    span = np.concatenate([p[2] for p in parts])
    order = np.lexsort((pos, line))
    line_s, pos_s, span_s = line[order], pos[order], span[order]
    same = np.concatenate([[False], line_s[1:] == line_s[:-1]])
    cur = pos_s >= clk
    hist = np.zeros(NBINS, np.int64)
    share: dict = {}
    gaps = pos_s[1:] - pos_s[:-1]
    ev = same[1:] & cur[1:]
    sh = ev & share_mask(gaps, span_s[1:])
    ns = ev & ~sh
    if ns.any():
        np.add.at(hist, np.frexp(gaps[ns].astype(np.float64))[1]
                  .astype(np.int64), 1)
    for v in gaps[sh].tolist():
        share[v] = share.get(v, 0) + 1
    hist[0] = int((~same & cur).sum())
    return hist, share


def _derive_thread(d: FlatRef, s: FlatRef, cfg: SamplerConfig, sched,
                   owned_row: np.ndarray, W: int, NW: int,
                   clock_row: np.ndarray):
    """One thread's A-contribution: (hist_w [NW, NBINS], share dict,
    slot table for verification) — or None if any invariant fails."""
    ds, cls = cfg.ds, cfg.cls
    lpe = cls // ds
    CS = cfg.chunk_size
    K = d.trips[1]
    C = K // lpe
    mt = d.trips[2]

    slots = owned_row[:, None].astype(np.int64) * CS + np.arange(CS)
    slots = slots.reshape(-1)
    valid = (np.repeat(owned_row >= 0, CS)) & (slots < sched.trip)
    idx = np.nonzero(valid)[0]
    if idx.size == 0:
        return np.zeros((NW, NBINS), np.int64), {}, []
    g = slots[idx]
    clk = clock_row[idx]
    win = idx // (W * CS)
    m = np.minimum(1 + g, mt)
    S_k = d.pos_strides[1] + (d.pos_strides_k[1] if d.pos_strides_k
                              else 0) * g
    s_j = d.pos_strides[2] + (d.pos_strides_k[2] if d.pos_strides_k
                              else 0) * g
    off_D = d.offset + d.offset_k * g
    off_S = s.offset + s.offset_k * g
    n_s = idx.size
    # previous owned iteration (shift by one in the valid sequence)
    has_prev = np.arange(n_s) > 0
    m_prev = np.where(has_prev, np.concatenate([[0], m[:-1]]), 0)
    clk_prev = np.concatenate([[0], clk[:-1]])
    S_k_prev = np.concatenate([[0], S_k[:-1]])
    off_S_prev = np.concatenate([[0], off_S[:-1]])

    hist_w = np.zeros((NW, NBINS), np.int64)
    share: dict = {}
    total = np.zeros(n_s, np.int64)   # per-slot event count invariant

    def emit(vals, counts, span, win_idx):
        """One gap class: split share/noshare, bin, count."""
        vals = np.asarray(vals, np.int64)
        counts = np.asarray(counts, np.int64)
        vals, counts = np.broadcast_arrays(vals, counts)
        live = counts > 0
        if not live.any():
            return True
        if (vals[live] < 1).any():
            return False
        w_idx = np.broadcast_to(win_idx, vals.shape)
        np.add.at(total, np.broadcast_to(
            np.arange(n_s).reshape((-1,) + (1,) * (vals.ndim - 1)),
            vals.shape)[live], counts[live])
        sh = live & share_mask(vals, np.int64(span)) if span else \
            np.zeros_like(live)
        ns = live & ~sh
        if ns.any():
            bins = np.frexp(vals[ns].astype(np.float64))[1].astype(np.int64)
            np.add.at(hist_w, (w_idx[ns], bins), counts[ns])
        if sh.any():
            for v, cnt in zip(vals[sh].tolist(), counts[sh].tolist()):
                share[v] = share.get(v, 0) + cnt
        return True

    span_S = s.ref.share_span or 0
    o = np.arange(C)[None, :]                     # [1, C] octave ids
    winc = np.broadcast_to(win[:, None], (n_s, C))

    ok = True
    # A. S intra-octave gaps: rows r < g, lpe touches per line at gap S_k
    ok = ok and (lpe == 1 or emit(S_k, (m - 1) * C * (lpe - 1), span_S,
                                  win))
    # B. cross-iteration heads: rows r <= g_prev (every previously-touched
    # row, INCLUDING the previous collision row — its octave-last touch is
    # the trailing S ref either way, so one class covers all)
    vB = (clk - clk_prev)[:, None] + o * lpe * (S_k - S_k_prev)[:, None] \
        - (lpe - 1) * S_k_prev[:, None] + (off_S - off_S_prev)[:, None]
    ok = ok and emit(vB, np.where(has_prev[:, None], m_prev[:, None], 0),
                     span_S, winc)
    # C. colds: the rows the triangle grew this iteration
    cold = (m - m_prev) * C
    np.add.at(hist_w, (win, np.zeros(n_s, np.int64)), cold)
    np.add.at(total, np.arange(n_s), cold)
    # D. D's walk on the top row: m consecutive touches per k at gap s_j
    ok = ok and emit(s_j, K * (m - 1), 0, win)
    # E. D-last -> the trailing S touch (every k)
    ok = ok and emit(off_S - off_D, np.full(n_s, K), span_S, win)
    # F. S -> next k's first D touch (k not octave-last)
    vF = S_k - (m - 1) * s_j - (off_S - off_D)
    ok = ok and (lpe == 1 or emit(vF, C * (lpe - 1), 0, win))
    if not ok:
        return None
    # invariant: every D+S access of the iteration is exactly one event or
    # cold — a wrong count formula cannot ship silently
    if not (total == 2 * m * K).all():
        return None
    return hist_w, share, list(zip(idx.tolist(), g.tolist(),
                                   clk.tolist()))


def build_sweepgroup(spec: LoopNestSpec, ni: int, refs, cfg: SamplerConfig,
                     sched, owned: np.ndarray, W: int, NW: int,
                     clock: np.ndarray):
    """(sort_refs, hist_w [T, NW, NBINS] | None, share_adds | None).

    ``share_adds``: per thread, a dict of raw share value -> count to add
    at finalize time (the closed-formed refs' share events).
    """
    if os.environ.get("PLUSS_NO_SWEEPGROUP"):
        return tuple(refs), None, None
    T = owned.shape[0]
    by_arr: dict[str, list] = {}
    for fr in refs:
        by_arr.setdefault(fr.ref.array, []).append(fr)
    hist_total = None
    share_total = None
    done = set()
    for arr, frs in by_arr.items():
        if eligible(spec, ni, frs, cfg, sched) is not None:
            continue
        d = next(fr for fr in frs if fr.addr_coefs[0])
        s = next(fr for fr in frs if not fr.addr_coefs[0])
        per_t = []
        failed = False
        for t in range(T):
            out = _derive_thread(d, s, cfg, sched, owned[t], W, NW,
                                 clock[t])
            if out is None:
                failed = True
                break
            per_t.append(out)
        if failed:
            continue
        # verification: replay sampled slots through the brute pair oracle
        if not _verify(d, s, cfg, per_t, owned, W, NW, clock):
            continue
        hw = np.stack([p[0] for p in per_t])
        if hist_total is None:
            hist_total = hw
            share_total = [dict(p[1]) for p in per_t]
        else:
            hist_total = hist_total + hw
            for t in range(T):
                for v, cnt in per_t[t][1].items():
                    share_total[t][v] = share_total[t].get(v, 0) + cnt
        done.add(arr)
    if not done:
        return tuple(refs), None, None
    sort_refs = tuple(fr for fr in refs if fr.ref.array not in done)
    return sort_refs, hist_total, tuple(share_total)


def _verify(d, s, cfg, per_t, owned, W, NW, clock) -> bool:
    """Brute-replay sampled (prev, cur) slot pairs per thread.

    The closed form's per-slot contribution is recovered by diffing
    cumulative tables — instead, re-derive each sampled slot ALONE via a
    single-slot `_derive_thread` call on a synthetic one-slot schedule...
    that would not exercise the prev-coupling, so the oracle replays the
    (prev, cur) pair directly and the closed form is evaluated for the
    pair's second slot by construction: sample slots where the pair's
    events can be isolated — the FIRST slot (cold-only) plus slots whose
    brute pair events equal (closed_form[cur slot]).  Mechanically: for
    each sampled cur slot, brute = events of cur given prev warm-up; the
    per-slot closed-form contribution is recomputed by running
    `_derive_thread` on a 2-slot owned sequence {prev, cur}, whose second
    slot's events are exactly the pair's.
    """
    from pluss.sched import ChunkSchedule

    T = owned.shape[0]
    CS = cfg.chunk_size
    for t in range(min(T, 2)):
        slots = per_t[t][2]
        if not slots:
            continue
        picks = sorted({0, 1, len(slots) // 2, len(slots) - 1}
                       & set(range(len(slots))))
        for pi in picks:
            idx, g, clk = slots[pi]
            if pi == 0:
                gp = None
                clkp = 0
            else:
                _, gp, clkp = slots[pi - 1]
            want_h, want_s = brute_pair_hist(d, s, cfg, gp, g, clkp, clk)
            got = _slot_contribution(d, s, cfg, gp, g, clkp, clk)
            if got is None:
                return False
            got_h, got_s = got
            if not (want_h == got_h).all() or want_s != got_s:
                return False
    return True


def _slot_contribution(d, s, cfg, g_prev, g, clk_prev, clk):
    """Closed-form (hist, share) of ONE slot, via a 2-slot derivation."""
    class _Sched:
        trip = max(g + 1, 1 + (g_prev if g_prev is not None else 0) + 1)
        start = 0
        step = 1

    # synthetic one-thread schedule owning exactly the pair (chunk size 1)
    cfg1 = dataclasses.replace(cfg, chunk_size=1, thread_num=1)
    if g_prev is None:
        owned_row = np.asarray([g], np.int32)
        clock_row = np.asarray([clk], np.int64)
    else:
        owned_row = np.asarray([g_prev, g], np.int32)
        clock_row = np.asarray([clk_prev, clk], np.int64)
    NW1 = len(owned_row)
    out = _derive_thread(d, s, cfg1, _Sched, owned_row, 1, NW1, clock_row)
    if out is None:
        return None
    hist_w, share, _ = out
    if g_prev is None:
        return hist_w[0], share
    # second slot's hist is its window row; share dict mixes both slots'
    # share events — subtract the first slot's own (prev-less) share
    first = _derive_thread(d, s, cfg1, _Sched,
                           np.asarray([g_prev], np.int32), 1, 1,
                           np.asarray([clk_prev], np.int64))
    if first is None:
        return None
    share2 = dict(share)
    for v, cnt in first[1].items():
        share2[v] = share2.get(v, 0) - cnt
        if share2[v] == 0:
            del share2[v]
    return hist_w[1], share2
