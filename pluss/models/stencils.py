"""Conv2d 3x3 and 7-point stencil-3D iteration spaces (BASELINE.json config 4).

Non-GEMM affine nests exercising multi-term addresses with constant bases
(neighbor offsets).  Authored in the reference's generated-sampler style (see
``pluss.models.polybench`` docstring); the reference itself has no such kernels,
so the share-span choice is ours: refs whose address depends on the parallel
iterator *plus a nonzero offset* (halo rows/planes) reach across chunk
boundaries, so they carry the cross-thread test with the generated formula
``(trip+1)*trip+1`` of the loop just below the parallel one.
"""

from __future__ import annotations

from pluss.spec import Loop, LoopNestSpec, Ref, share_span_formula


def conv2d(n: int = 128) -> LoopNestSpec:
    """3x3 convolution: ``out[i][j] = sum_{di,dj} W[di][dj] * in[i+di][j+dj]``.

    ``in`` is n x n, ``out`` is (n-2) x (n-2), W is 3x3.  Per (i,j): 9
    interleaved (W load, in load) pairs then the out store.
    """
    m = n - 2
    span = share_span_formula(m)
    body = []
    for di in range(3):
        for dj in range(3):
            body.append(Ref(f"W{di}{dj}", "W", addr_terms=(), addr_base=di * 3 + dj))
            body.append(
                Ref(
                    f"I{di}{dj}",
                    "in",
                    addr_terms=((0, n), (1, 1)),
                    addr_base=di * n + dj,
                    share_span=span if di != 0 else None,
                )
            )
    body.append(Ref("O0", "out", addr_terms=((0, m), (1, 1))))
    nest = Loop(trip=m, body=(Loop(trip=m, body=tuple(body)),))
    return LoopNestSpec(
        name=f"conv2d{n}",
        arrays=(("out", m * m), ("in", n * n), ("W", 9)),
        nests=(nest,),
    )


def stencil3d(n: int = 32) -> LoopNestSpec:
    """7-point 3D stencil: center + 6 face neighbors, parallel over i planes.

    ``in``/``out`` are n^3; interior (n-2)^3 is updated.  Neighbor loads are
    emitted center-first then -i,+i,-j,+j,-k,+k, followed by the out store.
    The +/-i plane neighbors carry the cross-thread span.
    """
    m = n - 2
    span = share_span_formula(m)
    off = lambda di, dj, dk: (di + 1) * n * n + (dj + 1) * n + (dk + 1)
    terms = ((0, n * n), (1, n), (2, 1))
    body = [Ref("S000", "in", addr_terms=terms, addr_base=off(0, 0, 0))]
    for name, (di, dj, dk) in (
        ("SmI", (-1, 0, 0)), ("SpI", (1, 0, 0)),
        ("SmJ", (0, -1, 0)), ("SpJ", (0, 1, 0)),
        ("SmK", (0, 0, -1)), ("SpK", (0, 0, 1)),
    ):
        body.append(
            Ref(
                name,
                "in",
                addr_terms=terms,
                addr_base=off(di, dj, dk),
                share_span=span if di != 0 else None,
            )
        )
    body.append(
        Ref("O0", "out", addr_terms=((0, m * m), (1, m), (2, 1)))
    )
    nest = Loop(
        trip=m,
        body=(Loop(trip=m, body=(Loop(trip=m, body=tuple(body)),)),),
    )
    return LoopNestSpec(
        name=f"stencil3d{n}",
        arrays=(("out", m * m * m), ("in", n * n * n)),
        nests=(nest,),
    )
