"""Conv2d 3x3 and 7-point stencil-3D iteration spaces (BASELINE.json config 4).

Non-GEMM affine nests exercising multi-term addresses with constant bases
(neighbor offsets).  Authored in the reference's generated-sampler style (see
``pluss.models.polybench`` docstring); the reference itself has no such kernels,
so the share-span choice is ours: refs whose address depends on the parallel
iterator *plus a nonzero offset* (halo rows/planes) reach across chunk
boundaries, so they carry the cross-thread test with the generated formula
``(trip+1)*trip+1`` of the loop just below the parallel one.
"""

from __future__ import annotations

from pluss.spec import Loop, LoopNestSpec, Ref, share_span_formula


def conv2d(n: int = 128) -> LoopNestSpec:
    """3x3 convolution: ``out[i][j] = sum_{di,dj} W[di][dj] * in[i+di][j+dj]``.

    ``in`` is n x n, ``out`` is (n-2) x (n-2), W is 3x3.  Per (i,j): 9
    interleaved (W load, in load) pairs then the out store.
    """
    m = n - 2
    span = share_span_formula(m)
    body = []
    for di in range(3):
        for dj in range(3):
            body.append(Ref(f"W{di}{dj}", "W", addr_terms=(), addr_base=di * 3 + dj))
            body.append(
                Ref(
                    f"I{di}{dj}",
                    "in",
                    addr_terms=((0, n), (1, 1)),
                    addr_base=di * n + dj,
                    share_span=span if di != 0 else None,
                )
            )
    body.append(Ref("O0", "out", addr_terms=((0, m), (1, 1)),
                    is_write=True))
    nest = Loop(trip=m, body=(Loop(trip=m, body=tuple(body)),))
    return LoopNestSpec(
        name=f"conv2d{n}",
        arrays=(("out", m * m), ("in", n * n), ("W", 9)),
        nests=(nest,),
    )


def stencil3d(n: int = 32) -> LoopNestSpec:
    """7-point 3D stencil: center + 6 face neighbors, parallel over i planes.

    ``in``/``out`` are n^3; interior (n-2)^3 is updated.  Neighbor loads are
    emitted center-first then -i,+i,-j,+j,-k,+k, followed by the out store.
    The +/-i plane neighbors carry the cross-thread span.
    """
    m = n - 2
    span = share_span_formula(m)
    off = lambda di, dj, dk: (di + 1) * n * n + (dj + 1) * n + (dk + 1)
    terms = ((0, n * n), (1, n), (2, 1))
    body = [Ref("S000", "in", addr_terms=terms, addr_base=off(0, 0, 0))]
    for name, (di, dj, dk) in (
        ("SmI", (-1, 0, 0)), ("SpI", (1, 0, 0)),
        ("SmJ", (0, -1, 0)), ("SpJ", (0, 1, 0)),
        ("SmK", (0, 0, -1)), ("SpK", (0, 0, 1)),
    ):
        body.append(
            Ref(
                name,
                "in",
                addr_terms=terms,
                addr_base=off(di, dj, dk),
                share_span=span if di != 0 else None,
            )
        )
    body.append(
        Ref("O0", "out", addr_terms=((0, m * m), (1, m), (2, 1)),
            is_write=True)
    )
    nest = Loop(
        trip=m,
        body=(Loop(trip=m, body=(Loop(trip=m, body=tuple(body)),)),),
    )
    return LoopNestSpec(
        name=f"stencil3d{n}",
        arrays=(("out", m * m * m), ("in", n * n * n)),
        nests=(nest,),
    )


def fdtd2d(n: int = 64, tsteps: int = 2) -> LoopNestSpec:
    """fdtd-2d: per timestep, three interleaved sweeps over ey/ex/hz —
    time-stepped multi-nest with halo reads (ppcg-style rectangular interior;
    the boundary row/col updates of PolyBench's first loop are folded into
    the interior sweeps for rectangularity).

    The interior is ``m = n - 2`` per dimension: sweeps are centered at
    ``(i+1, j+1)`` and the hz sweep reads the ``+1`` neighbors
    (``ex[i][j+1]``, ``ey[i+1][j]``), so an ``n - 1`` interior would walk
    one full row/column past the ``n x n`` arrays — the spec analyzer's
    bounds prover (``pluss lint``, PL101) rejects exactly that shape."""
    m = n - 2
    span = share_span_formula(m)
    terms = ((0, n), (1, 1))
    off = lambda di, dj: (di + 1) * n + (dj + 1)

    def sweep(dst, srcs, t):
        body = []
        for nm, arr, (di, dj) in srcs:
            body.append(Ref(f"{nm}{t}", arr, addr_terms=terms,
                            addr_base=off(di, dj),
                            share_span=span if di != 0 else None))
        body.append(Ref(f"{dst}s{t}", dst, addr_terms=terms,
                        addr_base=off(0, 0), is_write=True))
        return Loop(trip=m, body=(Loop(trip=m, body=tuple(body)),))

    nests = []
    for t in range(tsteps):
        nests.append(sweep("ey", (("eyc", "ey", (0, 0)),
                                  ("hzm", "hz", (-1, 0))), t))
        nests.append(sweep("ex", (("exc", "ex", (0, 0)),
                                  ("hzj", "hz", (0, -1))), t))
        nests.append(sweep("hz", (("hzc", "hz", (0, 0)),
                                  ("exn", "ex", (0, 1)),
                                  ("eyn", "ey", (1, 0))), t))
    return LoopNestSpec(
        name=f"fdtd2d{n}x{tsteps}",
        arrays=(("ey", n * n), ("ex", n * n), ("hz", n * n)),
        nests=tuple(nests),
    )


def heat3d(n: int = 24, tsteps: int = 2) -> LoopNestSpec:
    """heat-3d: alternating 7-point sweeps A->B then B->A per timestep."""
    m = n - 2
    span = share_span_formula(m)
    terms = ((0, n * n), (1, n), (2, 1))
    off = lambda di, dj, dk: (di + 1) * n * n + (dj + 1) * n + (dk + 1)

    def sweep(src, dst, t):
        body = [Ref(f"{src}c{t}", src, addr_terms=terms,
                    addr_base=off(0, 0, 0))]
        for nm, d in (("mI", (-1, 0, 0)), ("pI", (1, 0, 0)),
                      ("mJ", (0, -1, 0)), ("pJ", (0, 1, 0)),
                      ("mK", (0, 0, -1)), ("pK", (0, 0, 1))):
            body.append(Ref(f"{src}{nm}{t}", src, addr_terms=terms,
                            addr_base=off(*d),
                            share_span=span if d[0] != 0 else None))
        body.append(Ref(f"{dst}o{t}", dst, addr_terms=terms,
                        addr_base=off(0, 0, 0), is_write=True))
        return Loop(trip=m, body=(
            Loop(trip=m, body=(Loop(trip=m, body=tuple(body)),)),
        ))

    nests = []
    for t in range(tsteps):
        nests.append(sweep("A", "B", t))
        nests.append(sweep("B", "A", t))
    return LoopNestSpec(
        name=f"heat3d{n}x{tsteps}",
        arrays=(("A", n * n * n), ("B", n * n * n)),
        nests=tuple(nests),
    )
