"""PolyBench specs: 2mm / 3mm / syrk (BASELINE.json config 3) plus the
4.2 triangular family — syrk_tri, trmm, symm, covariance, correlation.

The reference ships only the generated GEMM sampler; these specs are authored in
the same ppcg/pluss style it was generated from (``/root/reference/c_lib/test/
gemm.ppcg_omp.c:72-98``): the outermost loop of every nest is the parallel dim,
loads precede the store of the same statement, and the accumulation statement
re-loads and re-stores its output element each k iteration (GEMM's C2/C3 pair,
``…omp.cpp:214-300``).

Share spans follow the generated formula ``(trip+1)*trip+1`` of the j loop
(``…omp.cpp:202``) and are attached to exactly the refs whose row index does not
involve the parallel iterator — those are the reuses that cross simulated
threads, as B0 does in GEMM (``gemm_sampler.rs:196-201``).  (For triangular
nests the criterion generalizes to: the ref's address recurs across
parallel iterations — see each model's docstring.)

``syrk`` uses the rectangular (full-matrix) PolyBench 3.x form so all loops
stay rectangular.  PolyBench 4.2's triangular ``j <= i`` variant needs
value-dependent inner bounds (quadratic clock offsets); the engine's
triangular support lives behind ``Loop.bound_coef`` — see
:func:`syrk_triangular` below and ``tests/test_triangular.py``.  The
reference itself has no triangular sampler (its one workload is rectangular
GEMM, ``/root/reference/c_lib/test/gemm.ppcg_omp.c:90-96``), so this is
capability-surface extension, not parity.
"""

from __future__ import annotations

from pluss.spec import Loop, LoopNestSpec, Ref, share_span_formula


def _matmul_nest(n: int, out: str, a: str, b: str, init_pair: bool) -> Loop:
    """One ``out = (init) ; out += a*b`` nest in generated-sampler style.

    ``init_pair``: True emits load+store (the ``*= beta`` pattern, GEMM C0/C1),
    False emits a single store (the ``= 0`` pattern of 2mm/3mm's first nests).
    """
    span = share_span_formula(n)
    o = lambda nm, w=False: Ref(nm, out, addr_terms=((0, n), (1, 1)),
                                is_write=w)
    head = (o(f"{out}0"), o(f"{out}1", w=True)) if init_pair \
        else (o(f"{out}0", w=True),)
    inner = Loop(
        trip=n,
        body=(
            Ref(f"{a}0", a, addr_terms=((0, n), (2, 1))),
            Ref(f"{b}0", b, addr_terms=((2, n), (1, 1)), share_span=span),
            o(f"{out}2"),
            o(f"{out}3", w=True),
        ),
    )
    return Loop(trip=n, body=(Loop(trip=n, body=head + (inner,)),))


def mm2(n: int = 128) -> LoopNestSpec:
    """2mm: ``tmp = alpha*A*B`` then ``D = beta*D + tmp*C``."""
    return LoopNestSpec(
        name=f"2mm{n}",
        arrays=(("tmp", n * n), ("A", n * n), ("B", n * n), ("C", n * n), ("D", n * n)),
        nests=(
            _matmul_nest(n, "tmp", "A", "B", init_pair=False),
            _matmul_nest(n, "D", "tmp", "C", init_pair=True),
        ),
    )


def mm3(n: int = 128) -> LoopNestSpec:
    """3mm: ``E = A*B``, ``F = C*D``, ``G = E*F``."""
    return LoopNestSpec(
        name=f"3mm{n}",
        arrays=(
            ("E", n * n), ("A", n * n), ("B", n * n),
            ("F", n * n), ("C", n * n), ("D", n * n),
            ("G", n * n),
        ),
        nests=(
            _matmul_nest(n, "E", "A", "B", init_pair=False),
            _matmul_nest(n, "F", "C", "D", init_pair=False),
            _matmul_nest(n, "G", "E", "F", init_pair=False),
        ),
    )


def syrk(n: int = 128) -> LoopNestSpec:
    """syrk (rectangular): ``C = beta*C + alpha*A*A^T``.

    ``A1 = A[j][k]`` is the cross-thread reference: its row index j does not
    involve the parallel iterator i, so its reuses span whole i iterations —
    the structural twin of GEMM's B0.
    """
    span = share_span_formula(n)
    c = lambda nm, w=False: Ref(nm, "C", addr_terms=((0, n), (1, 1)),
                                is_write=w)
    inner = Loop(
        trip=n,
        body=(
            Ref("A0", "A", addr_terms=((0, n), (2, 1))),
            Ref("A1", "A", addr_terms=((1, n), (2, 1)), share_span=span),
            c("C2"),
            c("C3", w=True),
        ),
    )
    nest = Loop(trip=n, body=(Loop(trip=n,
                                   body=(c("C0"), c("C1", w=True), inner)),))
    return LoopNestSpec(
        name=f"syrk{n}",
        arrays=(("C", n * n), ("A", n * n)),
        nests=(nest,),
    )


def syr2k(n: int = 128) -> LoopNestSpec:
    """syr2k (rectangular): ``C = beta*C + alpha*(A*B^T + B*A^T)``.

    BOTH operand arrays carry the symmetric moving/sweeping ref pair
    (``A[i][k]``/``A[j][k]`` and ``B[i][k]``/``B[j][k]``), so this is the
    two-overlay stress shape: each array gets its own interleave overlay
    (pluss.overlay) inside one nest.  ``A1``/``B1`` are the cross-thread
    references (row index j does not involve the parallel iterator), like
    GEMM's B0 (``/root/reference/src/gemm_sampler.rs:196-201``).
    """
    span = share_span_formula(n)
    c = lambda nm, w=False: Ref(nm, "C", addr_terms=((0, n), (1, 1)),
                                is_write=w)
    inner = Loop(
        trip=n,
        body=(
            Ref("A0", "A", addr_terms=((0, n), (2, 1))),
            Ref("B1", "B", addr_terms=((1, n), (2, 1)), share_span=span),
            Ref("B0", "B", addr_terms=((0, n), (2, 1))),
            Ref("A1", "A", addr_terms=((1, n), (2, 1)), share_span=span),
            c("C2"),
            c("C3", w=True),
        ),
    )
    nest = Loop(trip=n, body=(Loop(trip=n,
                                   body=(c("C0"), c("C1", w=True), inner)),))
    return LoopNestSpec(
        name=f"syr2k{n}",
        arrays=(("C", n * n), ("A", n * n), ("B", n * n)),
        nests=(nest,),
    )


def syrk_triangular(n: int = 128) -> LoopNestSpec:
    """syrk, PolyBench 4.2 triangular form: only ``j <= i`` is touched.

    Mirrors the 4.2 kernel statement-for-statement: per parallel iteration
    ``i``, a bounded j-loop scales ``C[i][j]``, then the k-loop re-walks the
    bounded j-loop accumulating ``alpha*A[i][k]*A[j][k]``.  Both j-loops
    carry ``bound_coef=(1, 1)`` (trip ``i+1`` at parallel index ``i``); the
    cross-thread reference is ``A1 = A[j][k]`` as in the rectangular form.
    """
    span = share_span_formula(n)
    c01 = Loop(trip=n, bound_coef=(1, 1), body=(
        Ref("C0", "C", addr_terms=((0, n), (1, 1))),
        Ref("C1", "C", addr_terms=((0, n), (1, 1)), is_write=True),
    ))
    accum = Loop(trip=n, body=(
        Loop(trip=n, bound_coef=(1, 1), body=(
            Ref("A0", "A", addr_terms=((0, n), (1, 1))),
            Ref("A1", "A", addr_terms=((2, n), (1, 1)), share_span=span),
            Ref("C2", "C", addr_terms=((0, n), (2, 1))),
            Ref("C3", "C", addr_terms=((0, n), (2, 1)), is_write=True),
        )),
    ))
    return LoopNestSpec(
        name=f"syrk_tri{n}",
        arrays=(("C", n * n), ("A", n * n)),
        nests=(Loop(trip=n, body=(c01, accum)),),
    )


def symm(n: int = 128) -> LoopNestSpec:
    """symm, PolyBench 4.2: ``C := alpha*A*B + beta*C`` with symmetric A.

    Per (i, j): the bounded k-loop (``k < i`` — ``bound_coef=(0, 1)``, zero
    trip at i=0) does ``C[k][j] += alpha*B[i][j]*A[i][k]`` (loads B, A,
    C[k][j]; store) and accumulates ``temp2 += B[k][j]*A[i][k]`` (loads B,
    A — temp2 is a register, not modeled, per the generated-sampler style
    that only walks array refs); then the tail statement loads
    ``B[i][j]``, ``A[i][i]`` (diagonal: one squared-index-free term
    ``i*(n+1)``), ``C[i][j]`` and stores ``C[i][j]``.
    ``B0 = B[k][j]`` is the cross-thread reference.
    """
    span = share_span_formula(n)
    kloop = Loop(
        trip=max(n - 1, 1), bound_coef=(0, 1),
        body=(
            Ref("B1", "B", addr_terms=((0, n), (1, 1))),
            Ref("A0", "A", addr_terms=((0, n), (2, 1))),
            # C[k][j] and B[k][j] have no parallel-iterator term: their
            # reuses cross simulated threads, so both carry the span
            # (module convention — the structural twins of GEMM's B0)
            Ref("C0", "C", addr_terms=((2, n), (1, 1)), share_span=span),
            Ref("C1", "C", addr_terms=((2, n), (1, 1)), share_span=span,
                is_write=True),
            Ref("B0", "B", addr_terms=((2, n), (1, 1)), share_span=span),
            Ref("A1", "A", addr_terms=((0, n), (2, 1))),
        ),
    )
    tail = (
        Ref("B2", "B", addr_terms=((0, n), (1, 1))),
        Ref("A2", "A", addr_terms=((0, n + 1),)),
        Ref("C2", "C", addr_terms=((0, n), (1, 1))),
        Ref("C3", "C", addr_terms=((0, n), (1, 1)), is_write=True),
    )
    nest = Loop(trip=n, body=(Loop(trip=n, body=(kloop,) + tail),))
    return LoopNestSpec(
        name=f"symm{n}",
        arrays=(("C", n * n), ("A", n * n), ("B", n * n)),
        nests=(nest,),
    )


def covariance(n: int = 128) -> LoopNestSpec:
    """covariance, PolyBench 4.2 (the cov kernel's triangular nest).

    ``for i: for (j = i; j < n; j++)`` — varying START and varying TRIP on
    the same loop (``start_coef=1``, ``bound_coef=(n, -1)``).  Per (i, j):
    zero-store ``cov[i][j]``; the k-loop accumulates
    ``data[k][i]*data[k][j]`` re-loading/storing ``cov[i][j]`` each step
    (generated-sampler style); then the two tail statements
    ``cov[i][j] /= (float_n - 1)`` (load + store) and
    ``cov[j][i] = cov[i][j]`` (load + symmetric store).
    ``D1 = data[k][j]`` carries the share span: column ``j`` recurs across
    parallel iterations (every ``i <= j`` revisits it), so its reuses cross
    simulated threads, while ``D0 = data[k][i]``'s column IS the parallel
    iterator — thread-private.
    """
    span = share_span_formula(n)
    cov_ij = lambda nm, w=False: Ref(nm, "cov", addr_terms=((0, n), (1, 1)),
                                     is_write=w)
    kloop = Loop(trip=n, body=(
        Ref("D0", "data", addr_terms=((2, n), (0, 1))),
        Ref("D1", "data", addr_terms=((2, n), (1, 1)), share_span=span),
        cov_ij("C1"),
        cov_ij("C2", w=True),
    ))
    jloop = Loop(
        trip=n, start_coef=1, bound_coef=(n, -1),
        body=(
            cov_ij("C0", w=True),                           # zero store
            kloop,
            cov_ij("C3"),                                   # /= load
            cov_ij("C4", w=True),                           # /= store
            cov_ij("C5"),                                   # symm load
            Ref("C6", "cov", addr_terms=((1, n), (0, 1)),
                is_write=True),                             # cov[j][i] store
        ),
    )
    return LoopNestSpec(
        name=f"covariance{n}",
        arrays=(("cov", n * n), ("data", n * n)),
        nests=(Loop(trip=n, body=(jloop,)),),
    )


def correlation(n: int = 128) -> LoopNestSpec:
    """correlation, PolyBench 4.2 (square ``data`` for one size parameter).

    Four parallel nests back-to-back — the longest nest chain in the model
    zoo, mixing rectangular and triangular shapes: (1) column means over
    ``data`` (parallel j, reduce over i; tail = the ``/= float_n``
    load+store), (2) column stddevs (same shape, re-reading ``mean``;
    tail = the ``/=``, ``sqrt`` and epsilon-clamp statements, each a
    load+store of ``stddev[j]``), (3) the normalization sweep (parallel i
    over rows: ``data[i][j] -= mean[j]`` then ``data[i][j] /= ...`` —
    BOTH statements' load/load/store triples), (4) the correlation
    triangle (parallel i, ``j = i+1 .. n-1`` via
    ``start_coef``/``bound_coef``, covariance-style accumulation with the
    symmetric store).  Statements are linearized generated-sampler style
    (loads precede the store); the only non-modeled access is the scalar
    epilogue ``corr[n-1][n-1] = 1``, which sits outside every parallel
    nest.  Share spans follow the module convention (refs with no
    parallel-iterator address term): nest 3's ``mean[j]``/``stddev[j]``
    and nest 4's ``D5 = data[k][j]``.
    """
    span = share_span_formula(n)

    def column_reduce(out: str, extra_inner: tuple, tail_pairs: int) -> Loop:
        """``out[j] = 0; for i: out[j] += f(data[i][j], ...)`` plus
        ``tail_pairs`` load+store tail statements on ``out[j]`` — the
        shared shape of the mean and stddev nests."""
        o = lambda k, w=False: Ref(f"{out}{k}", out, addr_terms=((0, 1),),
                                   is_write=w)
        inner = Loop(trip=n, body=(
            Ref(f"D_{out}", "data", addr_terms=((1, n), (0, 1))),
            *extra_inner, o("_a"), o("_b", w=True),
        ))
        tail = tuple(o(f"_t{i}", w=bool(i % 2))
                     for i in range(2 * tail_pairs))
        return Loop(trip=n, body=(o("_z", w=True), inner) + tail)

    n1 = column_reduce("mean", (), tail_pairs=1)
    n2 = column_reduce(
        "stddev", (Ref("M5", "mean", addr_terms=((0, 1),)),), tail_pairs=3)
    data_ij = lambda nm, w=False: Ref(nm, "data",
                                      addr_terms=((0, n), (1, 1)),
                                      is_write=w)
    n3 = Loop(trip=n, body=(
        Loop(trip=n, body=(
            data_ij("D2"),
            Ref("M6", "mean", addr_terms=((1, 1),), share_span=span),
            data_ij("D3", w=True),
            data_ij("D4"),
            Ref("S5", "stddev", addr_terms=((1, 1),), share_span=span),
            data_ij("D5n", w=True),
        )),
    ))
    corr_ij = lambda nm, w=False: Ref(nm, "corr",
                                      addr_terms=((0, n), (1, 1)),
                                      is_write=w)
    n4 = Loop(trip=max(n - 1, 1), body=(
        Ref("C0", "corr", addr_terms=((0, n + 1),),
            is_write=True),                             # corr[i][i] = 1
        Loop(
            trip=max(n - 1, 1), start=1, start_coef=1,
            bound_coef=(n - 1, -1),
            body=(
                corr_ij("C1", w=True),                  # corr[i][j] = 0
                Loop(trip=n, body=(
                    Ref("D4", "data", addr_terms=((2, n), (0, 1))),
                    Ref("D5", "data", addr_terms=((2, n), (1, 1)),
                        share_span=span),
                    corr_ij("C2"), corr_ij("C3", w=True),
                )),
                corr_ij("C4"),                          # symm load
                Ref("C5", "corr", addr_terms=((1, n), (0, 1)),
                    is_write=True),                     # store ji
            ),
        ),
    ))
    return LoopNestSpec(
        name=f"correlation{n}",
        arrays=(("data", n * n), ("mean", n), ("stddev", n),
                ("corr", n * n)),
        nests=(n1, n2, n3, n4),
    )


def trmm(n: int = 128) -> LoopNestSpec:
    """trmm, PolyBench 4.2: ``B := alpha*A*B`` with lower-triangular A.

    The inner k loop runs ``k in [i+1, n)`` — a varying START as well as a
    varying trip: ``start=1, start_coef=1, bound_coef=(n-1, -1)``
    (spec.Loop).  Per (i, j): the k-loop accumulates
    ``B[i][j] += A[k][i]*B[k][j]`` (loads A, B[k][j], B[i][j]; store), then
    ``B[i][j] *= alpha`` (load + store).  ``B0 = B[k][j]`` is the
    cross-thread reference (its address has no parallel-iterator term, like
    GEMM's B0).
    """
    span = share_span_formula(n)
    b_ij = lambda nm, w=False: Ref(nm, "B", addr_terms=((0, n), (1, 1)),
                                   is_write=w)
    kloop = Loop(
        trip=max(n - 1, 1), start=1, step=1,
        bound_coef=(n - 1, -1), start_coef=1,
        body=(
            Ref("A0", "A", addr_terms=((2, n), (0, 1))),
            Ref("B0", "B", addr_terms=((2, n), (1, 1)), share_span=span),
            b_ij("B1"),
            b_ij("B2", w=True),
        ),
    )
    nest = Loop(trip=n, body=(
        Loop(trip=n, body=(kloop, b_ij("B3"), b_ij("B4", w=True))),
    ))
    return LoopNestSpec(
        name=f"trmm{n}",
        arrays=(("A", n * n), ("B", n * n)),
        nests=(nest,),
    )
