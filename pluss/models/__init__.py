"""Workload specs (the reference's per-kernel generated samplers, declaratively).

Each builder returns a :class:`pluss.spec.LoopNestSpec`.  ``gemm`` reproduces the
reference's only shipped workload; the others cover the BASELINE.json configs
(PolyBench 2mm/3mm/syrk, conv2d 3x3, stencil-3D).

Since the frontend (PR 8, :mod:`pluss.frontend`) the hand-written
registry is a TEST CORPUS, not the only ingestion path: new nests enter
as DSL/pragma-C source through ``pluss import``, and
``pluss import --register`` persists them as codec-JSON files that
:func:`register_spec_dir` folds back into ``REGISTRY`` — point
``PLUSS_SPEC_DIR`` at such a directory and every entry point (CLI
``--model``, serve ``{"model": ...}`` requests, sweeps) sees the
imported specs as first-class models.  File-registered specs are
fixed-size (the size is baked into the source they were derived from);
their builders accept and ignore the conventional ``n`` argument.
"""

from pluss.models.gemm import gemm
from pluss.models.linalg import (atax, bicg, doitgen, gemver, gesummv,
                                 jacobi2d, mvt)
from pluss.models.polybench import (correlation, covariance, mm2, mm3,
                                    symm, syr2k, syrk, syrk_triangular, trmm)
from pluss.models.solvers import (cholesky, durbin, floyd_warshall,
                                  gramschmidt, lu, ludcmp, seidel2d,
                                  trisolv)
from pluss.models.stencils import conv2d, fdtd2d, heat3d, stencil3d

REGISTRY = {
    "gemm": gemm,
    "2mm": mm2,
    "3mm": mm3,
    "syrk": syrk,
    "syr2k": syr2k,
    "syrk_tri": syrk_triangular,
    "trmm": trmm,
    "symm": symm,
    "covariance": covariance,
    "correlation": correlation,
    "conv2d": conv2d,
    "stencil3d": stencil3d,
    "atax": atax,
    "mvt": mvt,
    "bicg": bicg,
    "gesummv": gesummv,
    "doitgen": doitgen,
    "jacobi2d": jacobi2d,
    "gemver": gemver,
    "fdtd2d": fdtd2d,
    "heat3d": heat3d,
    "trisolv": trisolv,
    "durbin": durbin,
    "gramschmidt": gramschmidt,
    "floyd_warshall": floyd_warshall,
    "cholesky": cholesky,
    "lu": lu,
    "ludcmp": ludcmp,
    "seidel2d": seidel2d,
}

def register_spec_dir(path: str, registry: dict | None = None) -> list[str]:
    """Fold ``pluss import --register`` codec-JSON files into the
    registry.  Returns the names added; files that fail the codec are
    skipped with a stderr notice (a broken file must not take down every
    entry point's import), and hand-written builders are never shadowed.
    """
    import os
    import sys

    reg = REGISTRY if registry is None else registry
    added: list[str] = []
    try:
        entries = sorted(os.listdir(path))
    except OSError as e:
        print(f"pluss.models: cannot read PLUSS_SPEC_DIR {path}: {e}",
              file=sys.stderr)
        return added
    for fn in entries:
        if not fn.endswith(".json"):
            continue
        full = os.path.join(path, fn)
        try:
            from pluss.spec_codec import load_spec_file

            spec = load_spec_file(full)
        except Exception as e:  # noqa: BLE001 — typed InvalidRequest or IO
            print(f"pluss.models: skipping {full}: {e}", file=sys.stderr)
            continue
        if spec.name in reg:
            print(f"pluss.models: {full}: name {spec.name!r} already "
                  "registered; not shadowing", file=sys.stderr)
            continue
        reg[spec.name] = _fixed_size_builder(spec)
        added.append(spec.name)
    return added


def _fixed_size_builder(spec):
    """Builder for a file-registered spec: fixed-size (the size is baked
    into the source it was derived from).  A caller-supplied ``n`` is
    accepted for interface compatibility (the CLI always passes one) but
    NOTICED on stderr once per spec — a serve client asking for
    {"model": "x", "n": 2048} must not silently get the baked size
    labeled as its request."""
    import sys

    warned = []

    def build(n=None):
        if n is not None and not warned:
            warned.append(True)
            print(f"pluss.models: {spec.name!r} is a file-registered "
                  f"fixed-size spec; ignoring n={n} (re-import the "
                  "source at another size to change it)",
                  file=sys.stderr)
        return spec

    return build


import os as _os

_spec_dir = _os.environ.get("PLUSS_SPEC_DIR")
if _spec_dir:
    register_spec_dir(_spec_dir)


__all__ = [
    "gemm", "mm2", "mm3", "syrk", "syr2k", "conv2d", "stencil3d",
    "atax", "mvt", "bicg", "gesummv", "doitgen", "jacobi2d",
    "gemver", "fdtd2d", "heat3d", "syrk_triangular", "trmm", "symm",
    "covariance", "correlation", "trisolv", "durbin", "gramschmidt",
    "floyd_warshall", "cholesky", "lu", "ludcmp", "seidel2d",
    "REGISTRY", "register_spec_dir",
]
