"""Workload specs (the reference's per-kernel generated samplers, declaratively).

Each builder returns a :class:`pluss.spec.LoopNestSpec`.  ``gemm`` reproduces the
reference's only shipped workload; the others cover the BASELINE.json configs
(PolyBench 2mm/3mm/syrk, conv2d 3x3, stencil-3D).
"""

from pluss.models.gemm import gemm
from pluss.models.linalg import (atax, bicg, doitgen, gemver, gesummv,
                                 jacobi2d, mvt)
from pluss.models.polybench import (correlation, covariance, mm2, mm3,
                                    symm, syr2k, syrk, syrk_triangular, trmm)
from pluss.models.solvers import (cholesky, durbin, floyd_warshall,
                                  gramschmidt, lu, ludcmp, seidel2d,
                                  trisolv)
from pluss.models.stencils import conv2d, fdtd2d, heat3d, stencil3d

REGISTRY = {
    "gemm": gemm,
    "2mm": mm2,
    "3mm": mm3,
    "syrk": syrk,
    "syr2k": syr2k,
    "syrk_tri": syrk_triangular,
    "trmm": trmm,
    "symm": symm,
    "covariance": covariance,
    "correlation": correlation,
    "conv2d": conv2d,
    "stencil3d": stencil3d,
    "atax": atax,
    "mvt": mvt,
    "bicg": bicg,
    "gesummv": gesummv,
    "doitgen": doitgen,
    "jacobi2d": jacobi2d,
    "gemver": gemver,
    "fdtd2d": fdtd2d,
    "heat3d": heat3d,
    "trisolv": trisolv,
    "durbin": durbin,
    "gramschmidt": gramschmidt,
    "floyd_warshall": floyd_warshall,
    "cholesky": cholesky,
    "lu": lu,
    "ludcmp": ludcmp,
    "seidel2d": seidel2d,
}

__all__ = [
    "gemm", "mm2", "mm3", "syrk", "syr2k", "conv2d", "stencil3d",
    "atax", "mvt", "bicg", "gesummv", "doitgen", "jacobi2d",
    "gemver", "fdtd2d", "heat3d", "syrk_triangular", "trmm", "symm",
    "covariance", "correlation", "trisolv", "durbin", "gramschmidt",
    "floyd_warshall", "cholesky", "lu", "ludcmp", "seidel2d",
    "REGISTRY",
]
