"""PolyBench GEMM spec: ``C[i][j] = beta*C[i][j] + alpha*A[i][k]*B[k][j]``.

Reproduces the reference's generated GEMM sampler
(``/root/reference/src/gemm_sampler.rs:56-293``,
``c_lib/test/sampler/gemm-t4-pluss-pro-model-ri-omp.cpp:37-333``) derived from the
ppcg-parallelized source ``c_lib/test/gemm.ppcg_omp.c:90-96``:

.. code-block:: c

    #pragma pluss parallel          // outer c0 loop chunked over threads
    for (c0 ...) for (c1 ...) {
        C[c0][c1] *= beta;          // refs C0 (load), C1 (store)
        for (c2 ...)
            C[c0][c1] += alpha*A[c0][c2]*B[c2][c1];  // A0, B0, C2, C3
    }

Reference order per (c0,c1): C0, C1, then per c2: A0, B0, C2, C3 — exactly the
state-machine transition chain C0→C1→(A0→B0→C2→C3)* (``gemm_sampler.rs:135-266``).

Only B0 carries a cross-thread ("share") reuse test: B[c2][c1] is carried by the
c1 loop, which sits *above* nothing parallel but spans whole c0 rows; the
generated threshold is ``(trip+1)*trip + 1`` = 16513 for trip=128
(``gemm_sampler.rs:196-199``, ``…omp.cpp:202-203``).

Every address uses row-major stride equal to the problem size for all three
arrays (``get_addr``, ``gemm_sampler.rs:34-38`` — the reference hardcodes 128;
correct only because NI=NJ=NK, SURVEY.md Q8).  We keep stride = n.
"""

from __future__ import annotations

from pluss.spec import Loop, LoopNestSpec, Ref, share_span_formula


def gemm(n: int = 128) -> LoopNestSpec:
    span = share_span_formula(n)
    # C0/C2 are the loads, C1/C3 the stores of the two C statements
    c0 = lambda name, w=False: Ref(name, "C", addr_terms=((0, n), (1, 1)),
                                   is_write=w)
    inner = Loop(
        trip=n,
        body=(
            Ref("A0", "A", addr_terms=((0, n), (2, 1))),
            Ref("B0", "B", addr_terms=((2, n), (1, 1)), share_span=span),
            c0("C2"),
            c0("C3", w=True),
        ),
    )
    nest = Loop(
        trip=n,
        body=(Loop(trip=n, body=(c0("C0"), c0("C1", w=True), inner)),),
    )
    return LoopNestSpec(
        name=f"gemm{n}",
        arrays=(("C", n * n), ("A", n * n), ("B", n * n)),
        nests=(nest,),
    )
