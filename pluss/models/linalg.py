"""PolyBench linear-algebra kernels beyond the matmul family.

Rectangular affine nests in the reference's generated-sampler style (see
``pluss.models.polybench``): operand loads precede the accumulator's
load+store pair (GEMM's A0/B0 then C2/C3, ``/root/reference/c_lib/test/
sampler/gemm-t4-pluss-pro-model-ri-omp.cpp:151-300``), and refs whose address
does not involve the parallel iterator carry the cross-thread share test with
the generated span formula ``(trip+1)*trip+1`` of the inner loop
(``…omp.cpp:202``, ``gemm_sampler.rs:196-201``).

These kernels exercise spec shapes the matmul family does not: matvec nests
(2-deep), transposed access (column-major coefficient on the parallel dim),
3-D arrays (doitgen), and time-stepped alternating nests (jacobi2d).
"""

from __future__ import annotations

from pluss.spec import Loop, LoopNestSpec, Ref, share_span_formula


def _accum(out: str, terms, tag: str = "") -> tuple[Ref, Ref]:
    """The accumulator's load+store pair (GEMM's C2/C3 pattern)."""
    return (Ref(f"{out}{tag}2", out, addr_terms=terms),
            Ref(f"{out}{tag}3", out, addr_terms=terms, is_write=True))


def atax(n: int = 128) -> LoopNestSpec:
    """atax: ``tmp = A x`` then ``y += A^T tmp`` (y accumulated per row).

    Nest 2 writes ``y[j]`` under parallel ``i`` — a store whose address does
    not involve the parallel iterator, the transposed-accumulation shape.
    """
    span = share_span_formula(n)
    n1 = Loop(trip=n, body=(
        Ref("T0", "tmp", addr_terms=((0, 1),)),
        Ref("T1", "tmp", addr_terms=((0, 1),), is_write=True),
        Loop(trip=n, body=(
            Ref("A0", "A", addr_terms=((0, n), (1, 1))),
            Ref("X0", "x", addr_terms=((1, 1),), share_span=span),
            *_accum("tmp", ((0, 1),)),
        )),
    ))
    n2 = Loop(trip=n, body=(
        Loop(trip=n, body=(
            Ref("A1", "A", addr_terms=((0, n), (1, 1))),
            Ref("T2", "tmp", addr_terms=((0, 1),)),
            Ref("Y2", "y", addr_terms=((1, 1),), share_span=span),
            Ref("Y3", "y", addr_terms=((1, 1),), share_span=span,
                is_write=True),
        )),
    ))
    return LoopNestSpec(
        name=f"atax{n}",
        arrays=(("tmp", n), ("y", n), ("A", n * n), ("x", n)),
        nests=(n1, n2),
    )


def mvt(n: int = 128) -> LoopNestSpec:
    """mvt: ``x1 += A y1`` and ``x2 += A^T y2`` — row- and column-major walks
    of the same matrix under the same parallel dim."""
    span = share_span_formula(n)
    row = Loop(trip=n, body=(
        Loop(trip=n, body=(
            Ref("A0", "A", addr_terms=((0, n), (1, 1))),
            Ref("Y10", "y1", addr_terms=((1, 1),), share_span=span),
            *_accum("x1", ((0, 1),)),
        )),
    ))
    col = Loop(trip=n, body=(
        Loop(trip=n, body=(
            Ref("A1", "A", addr_terms=((0, 1), (1, n))),
            Ref("Y20", "y2", addr_terms=((1, 1),), share_span=span),
            *_accum("x2", ((0, 1),)),
        )),
    ))
    return LoopNestSpec(
        name=f"mvt{n}",
        arrays=(("x1", n), ("x2", n), ("A", n * n), ("y1", n), ("y2", n)),
        nests=(row, col),
    )


def bicg(n: int = 128) -> LoopNestSpec:
    """bicg: ``s += r[i]*A[i][:]`` and ``q[i] += A[i][:]*p`` fused per row —
    one nest updating a shared vector and a private scalar together."""
    span = share_span_formula(n)
    nest = Loop(trip=n, body=(
        Ref("Q0", "q", addr_terms=((0, 1),)),
        Ref("Q1", "q", addr_terms=((0, 1),), is_write=True),
        Loop(trip=n, body=(
            Ref("A0", "A", addr_terms=((0, n), (1, 1))),
            Ref("R0", "r", addr_terms=((0, 1),)),
            Ref("S2", "s", addr_terms=((1, 1),), share_span=span),
            Ref("S3", "s", addr_terms=((1, 1),), share_span=span,
                is_write=True),
            Ref("P0", "p", addr_terms=((1, 1),), share_span=span),
            *_accum("q", ((0, 1),)),
        )),
    ))
    return LoopNestSpec(
        name=f"bicg{n}",
        arrays=(("s", n), ("q", n), ("A", n * n), ("r", n), ("p", n)),
        nests=(nest,),
    )


def gesummv(n: int = 128) -> LoopNestSpec:
    """gesummv: ``y = alpha*A*x + beta*B*x`` — two matrices streamed against
    one shared vector in a single inner loop."""
    span = share_span_formula(n)
    nest = Loop(trip=n, body=(
        Ref("T0", "tmp", addr_terms=((0, 1),), is_write=True),
        Ref("Y0", "y", addr_terms=((0, 1),), is_write=True),
        Loop(trip=n, body=(
            Ref("A0", "A", addr_terms=((0, n), (1, 1))),
            Ref("X0", "x", addr_terms=((1, 1),), share_span=span),
            *_accum("tmp", ((0, 1),), "t"),
            Ref("B0", "B", addr_terms=((0, n), (1, 1))),
            Ref("X1", "x", addr_terms=((1, 1),), share_span=span),
            *_accum("y", ((0, 1),)),
        )),
        Ref("T4", "tmp", addr_terms=((0, 1),)),
        Ref("Y4", "y", addr_terms=((0, 1),)),
        Ref("Y5", "y", addr_terms=((0, 1),), is_write=True),
    ))
    return LoopNestSpec(
        name=f"gesummv{n}",
        arrays=(("tmp", n), ("y", n), ("A", n * n), ("B", n * n), ("x", n)),
        nests=(nest,),
    )


def doitgen(n: int = 32) -> LoopNestSpec:
    """doitgen: ``sum[p] = Σ_s A[r][q][s]*C4[s][p]`` then write-back — a 3-D
    data array under a 2-deep parallel nest with a private temporary."""
    span = share_span_formula(n)
    nest = Loop(trip=n, body=(          # r (parallel)
        Loop(trip=n, body=(             # q
            Loop(trip=n, body=(         # p
                Ref("S0", "sum", addr_terms=((2, 1),)),
                Ref("S1", "sum", addr_terms=((2, 1),),
                    is_write=True),
                Loop(trip=n, body=(     # s
                    Ref("A0", "A", addr_terms=((0, n * n), (1, n), (3, 1))),
                    Ref("C0", "C4", addr_terms=((3, n), (2, 1)), share_span=span),
                    *_accum("sum", ((2, 1),)),
                )),
            )),
            Loop(trip=n, body=(         # p write-back
                Ref("S4", "sum", addr_terms=((2, 1),)),
                Ref("A4", "A", addr_terms=((0, n * n), (1, n), (2, 1)),
                    is_write=True),
            )),
        )),
    ))
    return LoopNestSpec(
        name=f"doitgen{n}",
        arrays=(("sum", n), ("A", n * n * n), ("C4", n * n)),
        nests=(nest,),
    )


def jacobi2d(n: int = 64, tsteps: int = 2) -> LoopNestSpec:
    """jacobi2d: ``tsteps`` alternating 5-point sweeps A->B then B->A —
    the time-stepped multi-nest shape (per-thread LAT state and clocks
    persist across nests, as across the reference's sequential nests)."""
    m = n - 2
    span = share_span_formula(m)

    def sweep(src: str, dst: str, t: int) -> Loop:
        off = lambda di, dj: (di + 1) * n + (dj + 1)
        terms = ((0, n), (1, 1))
        body = [Ref(f"{src}c{t}", src, addr_terms=terms, addr_base=off(0, 0))]
        for nm, (di, dj) in (("mI", (-1, 0)), ("pI", (1, 0)),
                             ("mJ", (0, -1)), ("pJ", (0, 1))):
            body.append(Ref(f"{src}{nm}{t}", src, addr_terms=terms,
                            addr_base=off(di, dj),
                            share_span=span if di != 0 else None))
        # the store hits the SAME n-stride array the next sweep reads: write
        # dst[i+1][j+1] at its real interior address, not a compacted layout
        body.append(Ref(f"{dst}o{t}", dst,
                        addr_terms=((0, n), (1, 1)), addr_base=off(0, 0),
                        is_write=True))
        return Loop(trip=m, body=(Loop(trip=m, body=tuple(body)),))

    nests = []
    for t in range(tsteps):
        nests.append(sweep("A", "B", t))
        nests.append(sweep("B", "A", t))
    return LoopNestSpec(
        name=f"jacobi2d{n}x{tsteps}",
        arrays=(("A", n * n), ("B", n * n)),
        nests=tuple(nests),
    )


def gemver(n: int = 128) -> LoopNestSpec:
    """gemver: rank-2 update ``A += u1 v1^T + u2 v2^T``, then ``x += beta
    A^T y``, ``x += z``, ``w += alpha A x`` — four nests over one matrix."""
    span = share_span_formula(n)
    rank2 = Loop(trip=n, body=(
        Loop(trip=n, body=(
            Ref("A0", "A", addr_terms=((0, n), (1, 1))),
            Ref("U10", "u1", addr_terms=((0, 1),)),
            Ref("V10", "v1", addr_terms=((1, 1),), share_span=span),
            Ref("U20", "u2", addr_terms=((0, 1),)),
            Ref("V20", "v2", addr_terms=((1, 1),), share_span=span),
            Ref("A1", "A", addr_terms=((0, n), (1, 1)),
                is_write=True),
        )),
    ))
    xaty = Loop(trip=n, body=(
        Loop(trip=n, body=(
            Ref("A2", "A", addr_terms=((1, n), (0, 1))),
            Ref("Y0", "y", addr_terms=((1, 1),), share_span=span),
            Ref("X2", "x", addr_terms=((0, 1),)),
            Ref("X3", "x", addr_terms=((0, 1),), is_write=True),
        )),
    ))
    xz = Loop(trip=n, body=(
        Ref("X4", "x", addr_terms=((0, 1),)),
        Ref("Z0", "z", addr_terms=((0, 1),)),
        Ref("X5", "x", addr_terms=((0, 1),), is_write=True),
    ))
    wax = Loop(trip=n, body=(
        Loop(trip=n, body=(
            Ref("A3", "A", addr_terms=((0, n), (1, 1))),
            Ref("X6", "x", addr_terms=((1, 1),), share_span=span),
            *_accum("w", ((0, 1),)),
        )),
    ))
    return LoopNestSpec(
        name=f"gemver{n}",
        arrays=(("A", n * n), ("u1", n), ("v1", n), ("u2", n), ("v2", n),
                ("x", n), ("y", n), ("z", n), ("w", n)),
        nests=(rank2, xaty, xz, wax),
    )
