"""PolyBench 4.2 solver/medley specs: trisolv, durbin, gramschmidt,
floyd_warshall.

Authored in the same ppcg/pluss generated-sampler style as
``/root/reference/c_lib/test/gemm.ppcg_omp.c:72-98`` (outermost loop =
the parallel dim, loads precede the store of the same statement, an
accumulation statement re-loads and re-stores its output element every
step, scalars live in registers and are not walked — the convention the
generated GEMM sampler encodes at ``…omp.cpp:214-300``).

These four cover the remaining PolyBench kernels expressible under the
spec language's affine contract (``pluss.spec.Loop``: inner bounds and
starts affine in the parallel index, bounded loops not nested inside
each other).  Each stresses a distinct corner of the engine:

- ``trisolv``: the canonical triangular solve — one bounded inner loop
  plus rectangular tail refs after it (nonzero ``offset_k`` on the tail).
- ``durbin``: NEGATIVE address coefficients (``r[k-i-1]``/``y[k-i-1]``
  walk arrays backwards; ``addr_base=-1``) and three sibling bounded
  loops with refs between them.
- ``gramschmidt``: rectangular i-loops nested inside the bounded
  ``j in [k+1, n)`` loop (``start_coef=1`` with ``bound_coef=(n-1,-1)``),
  plus diagonal refs ``R[k][k]``.
- ``floyd_warshall``: ONE array under three access patterns, one of them
  parallel-invariant (``path[i][j]`` has no ``k`` term — every simulated
  thread re-touches the same address set each iteration).

``cholesky`` and ``lu`` are DOUBLY-triangular: their per-iteration access
counts are quadratic in the parallel index (cholesky's ``k < j < i``
chains two bounds; lu multiplies two parallel-bounded trips).  They ride
the quad position contract (``Loop.bound_level`` +
``pluss.spec.flatten_nest_quad``: exact degree-2 closed-form stream
positions via ``tri(x) = x*(x-1)/2`` terms).  Triply-triangular shapes
(nussinov's ``k in (i, j)`` cross-bounds) stay out of contract.
"""

from __future__ import annotations

from pluss.spec import Loop, LoopNestSpec, Ref, share_span_formula


def trisolv(n: int = 128) -> LoopNestSpec:
    """trisolv: ``x = L^-1 b`` by forward substitution.

    Per parallel iteration ``i``: ``x[i] = b[i]`` (load b, store x); the
    bounded ``j < i`` loop does ``x[i] -= L[i][j]*x[j]`` (loads L, x[j],
    x[i]; store x[i]); then ``x[i] /= L[i][i]`` (loads x[i], L[i][i];
    store x[i]).  ``x[j]`` is the cross-thread reference: every later
    parallel iteration re-reads the prefix ``x[0..i)``.
    """
    span = share_span_formula(n)
    x_i = lambda nm, w=False: Ref(nm, "x", addr_terms=((0, 1),),
                                  is_write=w)
    jloop = Loop(trip=max(n - 1, 1), bound_coef=(0, 1), body=(
        Ref("L0", "L", addr_terms=((0, n), (1, 1))),
        Ref("X1", "x", addr_terms=((1, 1),), share_span=span),
        x_i("X2"),
        x_i("X3", w=True),
    ))
    nest = Loop(trip=n, body=(
        Ref("B0", "b", addr_terms=((0, 1),)),
        x_i("X0", w=True),
        jloop,
        x_i("X4"),
        Ref("L1", "L", addr_terms=((0, n + 1),)),      # diagonal L[i][i]
        x_i("X5", w=True),
    ))
    return LoopNestSpec(
        name=f"trisolv{n}",
        arrays=(("x", n), ("L", n * n), ("b", n)),
        nests=(nest,),
    )


def durbin(n: int = 128) -> LoopNestSpec:
    """durbin: Levinson-Durbin recursion on a Toeplitz system.

    Parallel loop ``k in [1, n)`` (start=1, trip n-1); all three inner
    loops run ``i < k`` (``bound_coef=(1, 1)``).  Per k: the sum loop
    loads ``r[k-i-1]`` (addr ``k - i - 1``: terms ``((0,1),(1,-1))``,
    base −1 — a backwards walk) and ``y[i]``; then ``r[k]`` (the alpha
    statement); the z-loop loads ``y[i]``, ``y[k-i-1]`` and stores
    ``z[i]``; the copy loop loads ``z[i]`` and stores ``y[i]``; finally
    ``y[k]`` is stored.  Every prefix-indexed ref (y, z, and the
    backwards r walk) recurs across parallel iterations — all carry the
    share span; ``r[k]``/``y[k]`` ride the parallel iterator and stay
    thread-private.  Scalars (alpha, beta, sum) are registers.
    """
    span = share_span_formula(n)
    back = lambda nm, arr: Ref(nm, arr, addr_terms=((0, 1), (1, -1)),
                               addr_base=-1, share_span=span)
    sum_loop = Loop(trip=max(n - 1, 1), bound_coef=(1, 1), body=(
        back("R0", "r"),
        Ref("Y0", "y", addr_terms=((1, 1),), share_span=span),
    ))
    z_loop = Loop(trip=max(n - 1, 1), bound_coef=(1, 1), body=(
        Ref("Y1", "y", addr_terms=((1, 1),), share_span=span),
        back("Y2", "y"),
        Ref("Z0", "z", addr_terms=((1, 1),), share_span=span,
            is_write=True),
    ))
    copy_loop = Loop(trip=max(n - 1, 1), bound_coef=(1, 1), body=(
        Ref("Z1", "z", addr_terms=((1, 1),), share_span=span),
        Ref("Y3", "y", addr_terms=((1, 1),), share_span=span,
            is_write=True),
    ))
    nest = Loop(trip=n - 1, start=1, body=(
        sum_loop,
        Ref("R1", "r", addr_terms=((0, 1),)),
        z_loop,
        copy_loop,
        Ref("Y4", "y", addr_terms=((0, 1),), is_write=True),
    ))
    return LoopNestSpec(
        name=f"durbin{n}",
        arrays=(("y", n), ("z", n), ("r", n)),
        nests=(nest,),
    )


def gramschmidt(n: int = 128) -> LoopNestSpec:
    """gramschmidt: QR by modified Gram-Schmidt (square m = n).

    Per parallel iteration ``k``: the norm loop loads ``A[i][k]`` twice
    (the two operand occurrences of ``A[i][k]*A[i][k]``); ``R[k][k]`` is
    stored; the Q loop loads ``A[i][k]``, ``R[k][k]`` and stores
    ``Q[i][k]``; then ``j in [k+1, n)`` (``start_coef=1``,
    ``bound_coef=(n-1,-1)``) runs two rectangular i-loops: the projection
    (``R[k][j] += Q[i][k]*A[i][j]`` — zero-store, then load Q, load A,
    load+store R) and the update (``A[i][j] -= Q[i][k]*R[k][j]`` — load
    A, load Q, load R, store A).  Column ``j > k`` of A is re-read AND
    re-written by every earlier parallel iteration, and column ``k`` was
    written as some earlier iteration's ``j`` — so all A refs carry the
    share span; Q and R columns/rows ride the parallel iterator.
    """
    span = share_span_formula(n)
    a_ik = lambda nm: Ref(nm, "A", addr_terms=((1, n), (0, 1)),
                          share_span=span)
    r_kk = lambda nm, w=False: Ref(nm, "R", addr_terms=((0, n + 1),),
                                   is_write=w)
    norm_loop = Loop(trip=n, body=(a_ik("A0"), a_ik("A1")))
    q_loop = Loop(trip=n, body=(
        a_ik("A2"),
        r_kk("R1"),
        Ref("Q0", "Q", addr_terms=((1, n), (0, 1)), is_write=True),
    ))
    q_ik = lambda nm: Ref(nm, "Q", addr_terms=((2, n), (0, 1)))
    r_kj = lambda nm, w=False: Ref(nm, "R", addr_terms=((0, n), (1, 1)),
                                   is_write=w)
    a_ij = lambda nm, w=False: Ref(nm, "A", addr_terms=((2, n), (1, 1)),
                               share_span=span, is_write=w)
    proj_loop = Loop(trip=n, body=(
        q_ik("Q1"), a_ij("A3"), r_kj("R3"), r_kj("R4", w=True),
    ))
    update_loop = Loop(trip=n, body=(
        a_ij("A4"), q_ik("Q2"), r_kj("R5"), a_ij("A5", w=True),
    ))
    jloop = Loop(
        trip=max(n - 1, 1), start=1, start_coef=1, bound_coef=(n - 1, -1),
        body=(r_kj("R2", w=True), proj_loop, update_loop),
    )
    nest = Loop(trip=n, body=(norm_loop, r_kk("R0", w=True), q_loop,
                              jloop))
    return LoopNestSpec(
        name=f"gramschmidt{n}",
        arrays=(("A", n * n), ("R", n * n), ("Q", n * n)),
        nests=(nest,),
    )


def cholesky(n: int = 128) -> LoopNestSpec:
    """cholesky, PolyBench 4.2: in-place ``A = L*L^T`` factor (lower part).

    Per parallel iteration ``i``: the ``j < i`` loop (bound (0,1) on the
    parallel level) runs the DOUBLY-bounded ``k < j`` loop
    (``bound_coef=(0, 1), bound_level=1``) doing ``A[i][j] -=
    A[i][k]*A[j][k]`` (loads A_ik, A_jk, A_ij; store A_ij), then
    ``A[i][j] /= A[j][j]`` (loads A_ij, A_jj; store); the second ``k < i``
    loop accumulates ``A[i][i] -= A[i][k]^2`` (two operand loads, load
    A_ii, store); finally ``A[i][i] = sqrt(A[i][i])`` (load + store).
    Rows ``j``/``k`` below ``i`` recur across parallel iterations —
    ``A[j][k]`` and ``A[j][j]`` carry the share span; row-``i`` refs are
    thread-private.
    """
    span = share_span_formula(n)
    a_ij = lambda nm, w=False: Ref(nm, "A", addr_terms=((0, n), (1, 1)),
                                   is_write=w)
    a_ii = lambda nm, w=False: Ref(nm, "A", addr_terms=((0, n + 1),),
                                   is_write=w)
    kloop = Loop(trip=max(n - 1, 1), bound_coef=(0, 1), bound_level=1,
                 body=(
        Ref("A0", "A", addr_terms=((0, n), (2, 1))),
        Ref("A1", "A", addr_terms=((1, n), (2, 1)), share_span=span),
        a_ij("A2"),
        a_ij("A3", w=True),
    ))
    jloop = Loop(trip=max(n - 1, 1), bound_coef=(0, 1), body=(
        kloop,
        a_ij("A4"),
        Ref("A5", "A", addr_terms=((1, n + 1),), share_span=span),
        a_ij("A6", w=True),
    ))
    k2loop = Loop(trip=max(n - 1, 1), bound_coef=(0, 1), body=(
        Ref("A7", "A", addr_terms=((0, n), (1, 1))),
        Ref("A8", "A", addr_terms=((0, n), (1, 1))),
        a_ii("A9"),
        a_ii("A10", w=True),
    ))
    nest = Loop(trip=n, body=(jloop, k2loop, a_ii("A11"),
                              a_ii("A12", w=True)))
    return LoopNestSpec(
        name=f"cholesky{n}",
        arrays=(("A", n * n),),
        nests=(nest,),
    )


def lu(n: int = 128) -> LoopNestSpec:
    """lu, PolyBench 4.2: in-place LU decomposition.

    Per parallel iteration ``i``: the ``j < i`` part mirrors cholesky's
    but multiplies ``A[i][k]*A[k][j]`` (column walk) and divides by the
    pivot ``A[j][j]``; the second part runs ``j in [i, n)``
    (``start_coef=1, bound_coef=(n, -1)`` — varying start AND trip) whose
    body is the ``k < i`` loop doing ``A[i][j] -= A[i][k]*A[k][j]`` — two
    parallel-bounded loops NESTED (trip product ``(n-i)*i``), the other
    quadratic shape.  ``A[k][j]``/``A[j][j]`` rows sit below ``i`` and
    carry the share span.
    """
    span = share_span_formula(n)
    a_ij = lambda nm, w=False: Ref(nm, "A", addr_terms=((0, n), (1, 1)),
                                   is_write=w)
    a_kj = lambda nm: Ref(nm, "A", addr_terms=((2, n), (1, 1)),
                          share_span=span)
    kloop = Loop(trip=max(n - 1, 1), bound_coef=(0, 1), bound_level=1,
                 body=(
        Ref("A0", "A", addr_terms=((0, n), (2, 1))),
        a_kj("A1"),
        a_ij("A2"),
        a_ij("A3", w=True),
    ))
    jloop = Loop(trip=max(n - 1, 1), bound_coef=(0, 1), body=(
        kloop,
        a_ij("A4"),
        Ref("A5", "A", addr_terms=((1, n + 1),), share_span=span),
        a_ij("A6", w=True),
    ))
    k2loop = Loop(trip=max(n - 1, 1), bound_coef=(0, 1), body=(
        Ref("A7", "A", addr_terms=((0, n), (2, 1))),
        a_kj("A8"),
        a_ij("A9"),
        a_ij("A10", w=True),
    ))
    j2loop = Loop(trip=n, start_coef=1, bound_coef=(n, -1), body=(k2loop,))
    nest = Loop(trip=n, body=(jloop, j2loop))
    return LoopNestSpec(
        name=f"lu{n}",
        arrays=(("A", n * n),),
        nests=(nest,),
    )


def ludcmp(n: int = 128) -> LoopNestSpec:
    """ludcmp, PolyBench 4.2: LU factor + forward/back substitution.

    Three nests in one spec — the integration stress case (per-thread LAT
    tables and clocks persist across nests, as across the reference's
    sequential nests):

    1. the LU nest (identical structure to :func:`lu` — quad contract);
    2. forward substitution ``L y = b``: per i, load ``b[i]``; the
       ``j < i`` loop loads ``A[i][j]``, ``y[j]`` (cross-thread) and
       re-walks the running sum in a register; store ``y[i]``;
    3. back substitution ``U x = y`` with a DESCENDING parallel loop
       (``i = n-1 .. 0``: start n-1, step -1): load ``y[i]``; the
       ``j in [i+1, n)`` loop loads ``A[i][j]`` and ``x[j]``
       (cross-thread); then ``A[i][i]`` and the ``x[i]`` store.  With the
       parallel INDEX k (i = n-1-k), the j loop is start=n, start_coef=-1,
       trip = a + b*k with (a, b) = (0, 1).
    """
    span = share_span_formula(n)
    # nest 1 IS lu's nest (frozen dataclasses — safely shared); any fix to
    # the LU spec lands in both models by construction
    lu_nest = lu(n).nests[0]

    fwd_j = Loop(trip=max(n - 1, 1), bound_coef=(0, 1), body=(
        Ref("F0", "A", addr_terms=((0, n), (1, 1))),
        Ref("F1", "y", addr_terms=((1, 1),), share_span=span),
    ))
    fwd = Loop(trip=n, body=(
        Ref("B0", "b", addr_terms=((0, 1),)),
        fwd_j,
        Ref("Y0", "y", addr_terms=((0, 1),), is_write=True),
    ))

    back_j = Loop(trip=max(n - 1, 1), start=n, start_coef=-1,
                  bound_coef=(0, 1), body=(
        Ref("U0", "A", addr_terms=((0, n), (1, 1))),
        Ref("X0", "x", addr_terms=((1, 1),), share_span=span),
    ))
    back = Loop(trip=n, start=n - 1, step=-1, body=(
        Ref("Y1", "y", addr_terms=((0, 1),)),
        back_j,
        Ref("U1", "A", addr_terms=((0, n + 1),)),
        Ref("X1", "x", addr_terms=((0, 1),), is_write=True),
    ))
    return LoopNestSpec(
        name=f"ludcmp{n}",
        arrays=(("A", n * n), ("b", n), ("y", n), ("x", n)),
        nests=(lu_nest, fwd, back),
    )


def seidel2d(n: int = 64, tsteps: int = 8) -> LoopNestSpec:
    """seidel2d, PolyBench 4.2: in-place 9-point Gauss-Seidel sweeps.

    The parallel loop is the OUTER time loop (the ppcg pragma convention,
    ``/root/reference/c_lib/test/gemm.ppcg_omp.c:90``): every simulated
    thread revisits the identical address set each time step, so ALL nine
    loads and the store are parallel-invariant (floyd_warshall has one
    such pattern among three; here it is the whole nest) and all carry
    the share span.
    """
    m = n - 2
    span = share_span_formula(m)
    off = lambda di, dj: (di + 1) * n + (dj + 1)
    body = []
    for nm, (di, dj) in (("mm", (-1, -1)), ("mc", (-1, 0)), ("mp", (-1, 1)),
                         ("cm", (0, -1)), ("cc", (0, 0)), ("cp", (0, 1)),
                         ("pm", (1, -1)), ("pc", (1, 0)), ("pp", (1, 1))):
        body.append(Ref(f"A{nm}", "A", addr_terms=((1, n), (2, 1)),
                        addr_base=off(di, dj), share_span=span))
    body.append(Ref("Ao", "A", addr_terms=((1, n), (2, 1)),
                    addr_base=off(0, 0), share_span=span, is_write=True))
    nest = Loop(trip=tsteps, body=(
        Loop(trip=m, body=(Loop(trip=m, body=tuple(body)),)),
    ))
    return LoopNestSpec(
        name=f"seidel2d{n}x{tsteps}",
        arrays=(("A", n * n),),
        nests=(nest,),
    )


def floyd_warshall(n: int = 128) -> LoopNestSpec:
    """floyd_warshall: all-pairs shortest paths; parallel over ``k``.

    Per (k, i, j): ``path[i][j] = min(path[i][j], path[i][k]+path[k][j])``
    — loads path[i][j], path[i][k], path[k][j], stores path[i][j].  One
    array, three patterns: ``path[i][j]`` is PARALLEL-INVARIANT (no k
    term — every simulated thread revisits the identical address set),
    ``path[k][j]`` rides row k, and ``path[i][k]`` walks column k (which
    earlier iterations wrote as their ``j = k``).  Every ref's reuses can
    cross threads, so all four carry the share span and the per-reuse
    distance test classifies them individually.
    """
    span = share_span_formula(n)
    p_ij = lambda nm, w=False: Ref(nm, "path", addr_terms=((1, n), (2, 1)),
                               share_span=span, is_write=w)
    inner = Loop(trip=n, body=(
        p_ij("P0"),
        Ref("P1", "path", addr_terms=((1, n), (0, 1)), share_span=span),
        Ref("P2", "path", addr_terms=((0, n), (2, 1)), share_span=span),
        p_ij("P3", w=True),
    ))
    nest = Loop(trip=n, body=(Loop(trip=n, body=(inner,)),))
    return LoopNestSpec(
        name=f"floyd_warshall{n}",
        arrays=(("path", n * n),),
        nests=(nest,),
    )
