"""PolyBench 4.2 solver/medley specs: trisolv, durbin, gramschmidt,
floyd_warshall.

Authored in the same ppcg/pluss generated-sampler style as
``/root/reference/c_lib/test/gemm.ppcg_omp.c:72-98`` (outermost loop =
the parallel dim, loads precede the store of the same statement, an
accumulation statement re-loads and re-stores its output element every
step, scalars live in registers and are not walked — the convention the
generated GEMM sampler encodes at ``…omp.cpp:214-300``).

These four cover the remaining PolyBench kernels expressible under the
spec language's affine contract (``pluss.spec.Loop``: inner bounds and
starts affine in the parallel index, bounded loops not nested inside
each other).  Each stresses a distinct corner of the engine:

- ``trisolv``: the canonical triangular solve — one bounded inner loop
  plus rectangular tail refs after it (nonzero ``offset_k`` on the tail).
- ``durbin``: NEGATIVE address coefficients (``r[k-i-1]``/``y[k-i-1]``
  walk arrays backwards; ``addr_base=-1``) and three sibling bounded
  loops with refs between them.
- ``gramschmidt``: rectangular i-loops nested inside the bounded
  ``j in [k+1, n)`` loop (``start_coef=1`` with ``bound_coef=(n-1,-1)``),
  plus diagonal refs ``R[k][k]``.
- ``floyd_warshall``: ONE array under three access patterns, one of them
  parallel-invariant (``path[i][j]`` has no ``k`` term — every simulated
  thread re-touches the same address set each iteration).

Doubly-triangular kernels (cholesky, lu, ludcmp, nussinov) have inner
trip counts quadratic in the parallel index — outside the affine
contract by design (``pluss.spec.loop_size_affine`` rejects them); they
would need the general sort path with value-dependent masks per level.
"""

from __future__ import annotations

from pluss.spec import Loop, LoopNestSpec, Ref, share_span_formula


def trisolv(n: int = 128) -> LoopNestSpec:
    """trisolv: ``x = L^-1 b`` by forward substitution.

    Per parallel iteration ``i``: ``x[i] = b[i]`` (load b, store x); the
    bounded ``j < i`` loop does ``x[i] -= L[i][j]*x[j]`` (loads L, x[j],
    x[i]; store x[i]); then ``x[i] /= L[i][i]`` (loads x[i], L[i][i];
    store x[i]).  ``x[j]`` is the cross-thread reference: every later
    parallel iteration re-reads the prefix ``x[0..i)``.
    """
    span = share_span_formula(n)
    x_i = lambda nm: Ref(nm, "x", addr_terms=((0, 1),))
    jloop = Loop(trip=max(n - 1, 1), bound_coef=(0, 1), body=(
        Ref("L0", "L", addr_terms=((0, n), (1, 1))),
        Ref("X1", "x", addr_terms=((1, 1),), share_span=span),
        x_i("X2"),
        x_i("X3"),
    ))
    nest = Loop(trip=n, body=(
        Ref("B0", "b", addr_terms=((0, 1),)),
        x_i("X0"),
        jloop,
        x_i("X4"),
        Ref("L1", "L", addr_terms=((0, n + 1),)),      # diagonal L[i][i]
        x_i("X5"),
    ))
    return LoopNestSpec(
        name=f"trisolv{n}",
        arrays=(("x", n), ("L", n * n), ("b", n)),
        nests=(nest,),
    )


def durbin(n: int = 128) -> LoopNestSpec:
    """durbin: Levinson-Durbin recursion on a Toeplitz system.

    Parallel loop ``k in [1, n)`` (start=1, trip n-1); all three inner
    loops run ``i < k`` (``bound_coef=(1, 1)``).  Per k: the sum loop
    loads ``r[k-i-1]`` (addr ``k - i - 1``: terms ``((0,1),(1,-1))``,
    base −1 — a backwards walk) and ``y[i]``; then ``r[k]`` (the alpha
    statement); the z-loop loads ``y[i]``, ``y[k-i-1]`` and stores
    ``z[i]``; the copy loop loads ``z[i]`` and stores ``y[i]``; finally
    ``y[k]`` is stored.  Every prefix-indexed ref (y, z, and the
    backwards r walk) recurs across parallel iterations — all carry the
    share span; ``r[k]``/``y[k]`` ride the parallel iterator and stay
    thread-private.  Scalars (alpha, beta, sum) are registers.
    """
    span = share_span_formula(n)
    back = lambda nm, arr: Ref(nm, arr, addr_terms=((0, 1), (1, -1)),
                               addr_base=-1, share_span=span)
    sum_loop = Loop(trip=max(n - 1, 1), bound_coef=(1, 1), body=(
        back("R0", "r"),
        Ref("Y0", "y", addr_terms=((1, 1),), share_span=span),
    ))
    z_loop = Loop(trip=max(n - 1, 1), bound_coef=(1, 1), body=(
        Ref("Y1", "y", addr_terms=((1, 1),), share_span=span),
        back("Y2", "y"),
        Ref("Z0", "z", addr_terms=((1, 1),), share_span=span),
    ))
    copy_loop = Loop(trip=max(n - 1, 1), bound_coef=(1, 1), body=(
        Ref("Z1", "z", addr_terms=((1, 1),), share_span=span),
        Ref("Y3", "y", addr_terms=((1, 1),), share_span=span),
    ))
    nest = Loop(trip=n - 1, start=1, body=(
        sum_loop,
        Ref("R1", "r", addr_terms=((0, 1),)),
        z_loop,
        copy_loop,
        Ref("Y4", "y", addr_terms=((0, 1),)),
    ))
    return LoopNestSpec(
        name=f"durbin{n}",
        arrays=(("y", n), ("z", n), ("r", n)),
        nests=(nest,),
    )


def gramschmidt(n: int = 128) -> LoopNestSpec:
    """gramschmidt: QR by modified Gram-Schmidt (square m = n).

    Per parallel iteration ``k``: the norm loop loads ``A[i][k]`` twice
    (the two operand occurrences of ``A[i][k]*A[i][k]``); ``R[k][k]`` is
    stored; the Q loop loads ``A[i][k]``, ``R[k][k]`` and stores
    ``Q[i][k]``; then ``j in [k+1, n)`` (``start_coef=1``,
    ``bound_coef=(n-1,-1)``) runs two rectangular i-loops: the projection
    (``R[k][j] += Q[i][k]*A[i][j]`` — zero-store, then load Q, load A,
    load+store R) and the update (``A[i][j] -= Q[i][k]*R[k][j]`` — load
    A, load Q, load R, store A).  Column ``j > k`` of A is re-read AND
    re-written by every earlier parallel iteration, and column ``k`` was
    written as some earlier iteration's ``j`` — so all A refs carry the
    share span; Q and R columns/rows ride the parallel iterator.
    """
    span = share_span_formula(n)
    a_ik = lambda nm: Ref(nm, "A", addr_terms=((1, n), (0, 1)),
                          share_span=span)
    r_kk = lambda nm: Ref(nm, "R", addr_terms=((0, n + 1),))
    norm_loop = Loop(trip=n, body=(a_ik("A0"), a_ik("A1")))
    q_loop = Loop(trip=n, body=(
        a_ik("A2"),
        r_kk("R1"),
        Ref("Q0", "Q", addr_terms=((1, n), (0, 1))),
    ))
    q_ik = lambda nm: Ref(nm, "Q", addr_terms=((2, n), (0, 1)))
    r_kj = lambda nm: Ref(nm, "R", addr_terms=((0, n), (1, 1)))
    a_ij = lambda nm: Ref(nm, "A", addr_terms=((2, n), (1, 1)),
                          share_span=span)
    proj_loop = Loop(trip=n, body=(
        q_ik("Q1"), a_ij("A3"), r_kj("R3"), r_kj("R4"),
    ))
    update_loop = Loop(trip=n, body=(
        a_ij("A4"), q_ik("Q2"), r_kj("R5"), a_ij("A5"),
    ))
    jloop = Loop(
        trip=max(n - 1, 1), start=1, start_coef=1, bound_coef=(n - 1, -1),
        body=(r_kj("R2"), proj_loop, update_loop),
    )
    nest = Loop(trip=n, body=(norm_loop, r_kk("R0"), q_loop, jloop))
    return LoopNestSpec(
        name=f"gramschmidt{n}",
        arrays=(("A", n * n), ("R", n * n), ("Q", n * n)),
        nests=(nest,),
    )


def floyd_warshall(n: int = 128) -> LoopNestSpec:
    """floyd_warshall: all-pairs shortest paths; parallel over ``k``.

    Per (k, i, j): ``path[i][j] = min(path[i][j], path[i][k]+path[k][j])``
    — loads path[i][j], path[i][k], path[k][j], stores path[i][j].  One
    array, three patterns: ``path[i][j]`` is PARALLEL-INVARIANT (no k
    term — every simulated thread revisits the identical address set),
    ``path[k][j]`` rides row k, and ``path[i][k]`` walks column k (which
    earlier iterations wrote as their ``j = k``).  Every ref's reuses can
    cross threads, so all four carry the share span and the per-reuse
    distance test classifies them individually.
    """
    span = share_span_formula(n)
    p_ij = lambda nm: Ref(nm, "path", addr_terms=((1, n), (2, 1)),
                          share_span=span)
    inner = Loop(trip=n, body=(
        p_ij("P0"),
        Ref("P1", "path", addr_terms=((1, n), (0, 1)), share_span=span),
        Ref("P2", "path", addr_terms=((0, n), (2, 1)), share_span=span),
        p_ij("P3"),
    ))
    nest = Loop(trip=n, body=(Loop(trip=n, body=(inner,)),))
    return LoopNestSpec(
        name=f"floyd_warshall{n}",
        arrays=(("path", n * n),),
        nests=(nest,),
    )
