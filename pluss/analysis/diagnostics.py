"""Structured diagnostics for the LoopNestSpec static analyzer.

Every finding of :mod:`pluss.analysis` is a :class:`Diagnostic` with a
STABLE code — the code, not the message text, is the machine-readable
contract (tests and tooling key on it; wording may improve freely).

Code families mirror the analyzer's four passes:

- ``PL1xx`` bounds   (:mod:`pluss.analysis.bounds`): address-range proofs
  against the declared array sizes.
- ``PL2xx`` share    (:mod:`pluss.analysis.sharespan`): ``share_span``
  consistency against the recomputed carrying-loop formula and the race
  detector's cross-thread classification.
- ``PL3xx`` race     (:mod:`pluss.analysis.deps`): affine dependence tests
  (GCD + Banerjee-style bounds) on the parallel dimension.
- ``PL4xx`` contract (:mod:`pluss.analysis.contract`): the structural
  restrictions ``spec.flatten_nest`` / ``flatten_nest_quad`` enforce,
  surfaced as records with tree paths instead of bare ``ValueError``.
- ``PL30x`` (304/305) schedule (:mod:`pluss.analysis.schedule`):
  placement-refined race/reuse verdicts under a concrete chunk schedule
  (emitted by ``pluss analyze``, never by the schedule-blind ``lint``).
- ``PL5xx`` falseshare (:mod:`pluss.analysis.falseshare`): line-granular
  cross-thread false-sharing detection (also ``analyze``-only — it needs
  the machine model's element and line widths).
- ``PL6xx`` frontend (:mod:`pluss.frontend`): authoring-time rejections
  from the loop-nest DSL and the pragma-C parser (non-affine constructs,
  out-of-grammar steps, missing pragmas, malformed source) — emitted
  BEFORE a spec exists, so they carry source locations instead of tree
  paths.  PL609 wraps an analyzer rejection of a frontend-derived spec.
- ``PL7xx`` prediction (:mod:`pluss.analysis.ri`): the sampling-free
  symbolic reuse-interval predictor — typed "not statically derivable"
  refusals (PL701), enumeration-budget refusals (PL702), derivation-method
  notes (PL703), and the prover soundness alarm (PL704: exact plateau
  outside the heuristic MrcBracket — a bug in exactly one of the two).
- ``PL8xx`` interference (:mod:`pluss.analysis.interference`): the
  cross-nest co-tenancy composition — severe predicted interference at
  the declared cache size (PL801), proven-bounded benign co-tenancy
  (PL802), and the typed refusal when a workload pair lies outside the
  composition model's contract (PL803 — never a silent approximation).
- ``PL9xx`` tuning (:mod:`pluss.analysis.tune`): the proof-carrying
  schedule auto-optimizer — proven-best schedule with margin (PL901),
  tie-within-epsilon set (PL902), typed refusal when a candidate falls
  off the derivability ladder (PL903 — the PL701/702 cause chain
  attaches), and the ``--check`` cross-validation alarm when a live
  engine run disagrees with the predicted winner (PL904).
- ``PL95x`` transform (:mod:`pluss.analysis.transform`): the
  loop-transformation legality prover — proven-legal transform with the
  witness dependence vectors (PL951), proven-illegal with the concrete
  violating pair (PL952), typed refusal when the nest is outside the
  dependence-vector contract (PL953 — the PL601/PL701 cause chain
  attaches, never a silent guess), and the transform ``--check``
  cross-validation alarm when a live engine run of the transformed spec
  disagrees with its static MRC prediction (PL954).

Severity semantics: ERROR means the spec is wrong (out-of-bounds access,
undeclared array, contract violation) — ``pluss lint`` exits nonzero.
WARNING flags suspicious-but-runnable facts (hand-copied span mismatch,
cross-thread conflicts the ``#pragma pluss parallel`` contract merely
asserts away).  INFO records classifications (carried levels) for tooling.
"""

from __future__ import annotations

import dataclasses
import enum
import json


class Severity(enum.IntEnum):
    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # "error", not "Severity.ERROR", in reports
        return self.name.lower()


#: code -> (pass family, one-line meaning).  The single source of truth for
#: the README's diagnostic-code table (tests assert the two agree).
CODES: dict[str, tuple[str, str]] = {
    "PL101": ("bounds", "reference address range escapes its array"),
    "PL102": ("bounds", "reference targets an undeclared array"),
    "PL103": ("bounds", "declared array is never referenced"),
    "PL104": ("bounds", "duplicate array declaration"),
    "PL105": ("bounds", "array declared with a non-positive size"),
    "PL201": ("share", "share_span is not a meaningful threshold"),
    "PL202": ("share", "share_span differs from the recomputed "
                       "carrying-loop formula"),
    "PL203": ("share", "reference can observe cross-thread reuse but "
                       "carries no share_span"),
    "PL204": ("share", "share_span on a reference with no cross-thread "
                       "reuse"),
    "PL301": ("race", "cross-thread write-write conflict on the parallel "
                      "dimension"),
    "PL302": ("race", "cross-thread read-write conflict on the parallel "
                      "dimension"),
    "PL303": ("race", "reuse carried-level classification"),
    "PL304": ("race", "conflict is provably thread-private under the "
                      "analyzed chunk schedule (placement-refined)"),
    "PL305": ("race", "schedule-refined reuse classification"),
    "PL501": ("falseshare", "cross-thread write-write false sharing on a "
                            "cache line (same line, different elements)"),
    "PL502": ("falseshare", "cross-thread read-write false sharing on a "
                            "cache line (same line, different elements)"),
    "PL503": ("falseshare", "write references proven free of false "
                            "sharing under the analyzed schedule"),
    "PL401": ("contract", "the parallel (outermost) loop must be "
                          "rectangular"),
    "PL402": ("contract", "inner bound leaves the declared [0, trip] "
                          "range"),
    "PL403": ("contract", "addr term depth exceeds the loop chain depth"),
    "PL404": ("contract", "bound_level must name an enclosing loop"),
    "PL405": ("contract", "outside the quadratic position contract"),
    "PL406": ("contract", "duplicate reference name inside one nest"),
    "PL407": ("contract", "spec rejected by flatten"),
    "PL601": ("frontend", "non-affine expression (subscript, bound, or "
                          "operator outside the affine grammar)"),
    "PL602": ("frontend", "loop step outside the frontend grammar"),
    "PL603": ("frontend", "parallel marker missing on a top-level loop "
                          "nest (or placed on an inner loop)"),
    "PL604": ("frontend", "loop variable shadows an enclosing loop "
                          "variable"),
    "PL605": ("frontend", "malformed source (tokenizer/parser rejection)"),
    "PL606": ("frontend", "reference to an undeclared array or wrong "
                          "subscript arity"),
    "PL607": ("frontend", "loop bound/start outside the lowerable affine "
                          "contract"),
    "PL608": ("frontend", "authoring-API misuse (ref outside a loop, "
                          "duplicate array, out-of-scope index)"),
    "PL609": ("frontend", "frontend-derived spec rejected by the static "
                          "analyzer"),
    "PL701": ("prediction", "reuse distribution not statically derivable "
                            "(spec outside the position contract or the "
                            "address model is invalid)"),
    "PL702": ("prediction", "exact derivation exceeds the enumeration "
                            "budget and no closed form applies "
                            "(PLUSS_PREDICT_BUDGET)"),
    "PL703": ("prediction", "derivation method note: closed-form periodic "
                            "or dense polynomial counting"),
    "PL704": ("prediction", "exact MRC plateau lies outside the static "
                            "footprint bracket — prover soundness "
                            "violation"),
    "PL801": ("interference", "severe co-tenancy interference: predicted "
                              "miss-ratio inflation above threshold at "
                              "the declared cache size"),
    "PL802": ("interference", "benign co-tenancy: miss-ratio inflation "
                              "proven below threshold at the declared "
                              "cache size"),
    "PL803": ("interference", "co-tenancy pair outside the composition "
                              "model's contract (typed refusal, never a "
                              "silent approximation)"),
    "PL901": ("tuning", "proven-best schedule: every competitor scored "
                        "worse beyond the tie epsilon or was dominance-"
                        "pruned (margin attached)"),
    "PL902": ("tuning", "schedule tie within epsilon: the canonical pick "
                        "plus the full tie set"),
    "PL903": ("tuning", "tune refused: a candidate schedule fell off the "
                        "derivability ladder (PL701/702 cause chain "
                        "attached, never a silent approximation)"),
    "PL904": ("tuning", "tuned-winner cross-check alarm: live engine run "
                        "disagrees with the predicted MRC beyond the "
                        "epsilon"),
    "PL951": ("transform", "transform proven legal: every dependence "
                           "vector stays lexicographically nonnegative "
                           "(witness vectors attached)"),
    "PL952": ("transform", "transform proven illegal: a dependence "
                           "vector would be reversed (concrete violating "
                           "pair attached)"),
    "PL953": ("transform", "transform refused: nest outside the "
                           "dependence-vector contract (PL601/PL701 "
                           "cause chain attached, never a silent guess)"),
    "PL954": ("transform", "transformed-spec cross-check alarm: live "
                           "engine run disagrees with the static MRC "
                           "prediction beyond the epsilon"),
}


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One analyzer finding, addressable into the Loop/Ref tree.

    ``path`` spells the tree position in attribute syntax
    (``nests[0].body[1].body[2]``); ``ref``/``array``/``nest`` carry the
    same identity as plain fields for JSON consumers.
    """

    code: str
    severity: Severity
    message: str
    path: str = ""
    nest: int | None = None
    ref: str | None = None
    array: str | None = None
    model: str | None = None

    def format(self) -> str:
        where = self.path or (f"nests[{self.nest}]" if self.nest is not None
                              else "")
        bits = [b for b in (
            f"{self.model}:" if self.model else None,
            where or None,
            f"[{self.code} {self.severity}]",
            self.message,
        ) if b]
        return " ".join(bits)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["severity"] = str(self.severity)
        return {k: v for k, v in d.items() if v is not None and v != ""}


def shown(names: list[str], limit: int = 4) -> str:
    """Truncated pair/name list for diagnostic messages: the first
    ``limit`` entries plus a '+N more' tail (one home for the idiom the
    race, schedule, and false-sharing passes all use)."""
    return ", ".join(names[:limit]) + (
        f" (+{len(names) - limit} more)" if len(names) > limit else "")


def error_count(diags: list[Diagnostic]) -> int:
    return sum(1 for d in diags if d.severity is Severity.ERROR)


def with_model(diags: list[Diagnostic], model: str) -> list[Diagnostic]:
    """Stamp a model name onto diagnostics (batch-lint labeling)."""
    return [dataclasses.replace(d, model=model) for d in diags]


def format_text(diags: list[Diagnostic], min_severity: Severity =
                Severity.WARNING) -> str:
    """Human report: one line per diagnostic at or above ``min_severity``
    (INFO-level classifications stay JSON-only by default)."""
    return "\n".join(d.format() for d in diags
                     if d.severity >= min_severity)


def format_json(diags: list[Diagnostic]) -> str:
    return json.dumps(
        {
            "diagnostics": [d.to_dict() for d in diags],
            "errors": error_count(diags),
            "warnings": sum(1 for d in diags
                            if d.severity is Severity.WARNING),
        },
        indent=1,
    )


def sort_key(d: Diagnostic):
    """Stable report order: errors first, then code, then tree position."""
    return (-int(d.severity), d.code, d.nest if d.nest is not None else -1,
            d.path)
