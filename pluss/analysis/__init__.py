"""Static analysis over the Loop/Ref/LoopNestSpec IR.

Four passes feed one structured-diagnostics stream
(:mod:`pluss.analysis.diagnostics` — stable PLxxx codes):

1. contract  (PL4xx) — the flatten-time structural restrictions, with
   tree paths (:mod:`pluss.analysis.contract`);
2. bounds    (PL1xx) — exact address-range proofs against the declared
   array sizes (:mod:`pluss.analysis.bounds`);
3. race/deps (PL3xx) — GCD + Banerjee-style dependence tests proving or
   refuting cross-thread conflicts on the parallel dimension, plus
   per-reference carried-level classification
   (:mod:`pluss.analysis.deps`);
4. share     (PL2xx) — ``share_span`` recomputation from the carrying
   loop and consistency with the race classification
   (:mod:`pluss.analysis.sharespan`).

Everything here is host-side Python/numpy over the declarative spec —
no JAX, no device, no stream enumeration — so ``pluss lint`` runs before
(and without) any XLA compilation.

Entry points: :func:`lint_spec` for one spec, ``pluss lint`` (see
:mod:`pluss.cli`) for the CLI surface, and ``--verify`` on the engine
modes for the opt-in pre-pass.
"""

from __future__ import annotations

from pluss.analysis import (bounds, contract, deps, falseshare, footprint,
                            schedule, sharespan)
from pluss.analysis.diagnostics import (CODES, Diagnostic, Severity,
                                        error_count, format_json,
                                        format_text, sort_key, with_model)
from pluss.config import DEFAULT, SamplerConfig
from pluss.spec import LoopNestSpec


def lint_spec(spec: LoopNestSpec) -> list[Diagnostic]:
    """Run all four schedule-blind passes over one spec; diagnostics
    sorted errors-first.

    Contract errors gate the semantic passes per nest: a nest the flatten
    rejects has no well-defined iteration domain, so bounds/race/share
    skip it instead of reasoning from garbage.
    """
    diags = contract.check(spec)
    bad = frozenset(d.nest for d in diags
                    if d.severity is Severity.ERROR and d.nest is not None)
    diags += bounds.check(spec, skip_nests=bad)
    ana = deps.analyze(spec, skip_nests=bad)  # profiled once, shared below
    diags += deps.check(spec, skip_nests=bad, analysis=ana)
    diags += sharespan.check(spec, ana.classes)
    return sorted(diags, key=sort_key)


def analyze_spec(spec: LoopNestSpec,
                 cfg: SamplerConfig = DEFAULT
                 ) -> tuple[list[Diagnostic], "footprint.Footprint"]:
    """The schedule-AWARE analysis (``pluss analyze``): the lint passes
    with the race stream placement-refined under ``cfg``'s chunk schedule
    (PL304/PL305 — :mod:`pluss.analysis.schedule`), plus line-granular
    false-sharing detection (PL5xx — :mod:`pluss.analysis.falseshare`)
    and the footprint/MRC-bound report (:mod:`pluss.analysis.footprint`).

    Returns ``(diagnostics, footprint)``.  The schedule-blind PL301/PL302
    findings are REPLACED by their placement-refined versions (same codes
    when a pair provably crosses threads, PL304 INFO when the schedule
    serializes every pair); everything else from :func:`lint_spec` is
    kept as-is.
    """
    diags = contract.check(spec)
    bad = frozenset(d.nest for d in diags
                    if d.severity is Severity.ERROR and d.nest is not None)
    diags += bounds.check(spec, skip_nests=bad)
    ana = deps.analyze(spec, skip_nests=bad)
    blind = deps.check(spec, skip_nests=bad, analysis=ana)
    diags += [d for d in blind if d.code not in ("PL301", "PL302")]
    diags += schedule.check(spec, cfg, analysis=ana, skip_nests=bad)
    diags += sharespan.check(spec, ana.classes)
    diags += falseshare.check(spec, cfg, analysis=ana, skip_nests=bad)
    return sorted(diags, key=sort_key), footprint.footprints(
        spec, cfg, skip_nests=bad)


__all__ = [
    "CODES", "Diagnostic", "Severity", "lint_spec", "analyze_spec",
    "error_count", "format_text", "format_json", "with_model",
    "bounds", "contract", "deps", "falseshare", "footprint", "schedule",
    "sharespan",
]
