"""Symbolic reuse-interval analysis: the sampling-free static MRC path.

For every reference pair of an affine nest the reuse polyhedron — the set
of iteration-vector pairs whose two accesses touch the same cache line
with no intervening touch — has an exact lattice-point count, because
stream positions and element addresses are closed forms of the iteration
vector (:class:`pluss.spec.FlatRef`).  This pass derives the engine's
per-thread reuse-interval histograms from those counts alone, composes
them through the CRI dilation model (:mod:`pluss.cri`) and the AET solver
(:mod:`pluss.mrc`) exactly as a sampled run would, and proves the MRC's
plateau location statically — zero device dispatches, bit-identical
histograms to :func:`pluss.engine.run`.

Derivability ladder (each rung exact; the next is the fallback):

1. **Closed-form periodic** (:func:`_closed_form`) — the Ehrhart-style
   uniform-reuse case.  For a single rectangular nest under the static
   chunk schedule, one owned chunk is one PERIOD of the thread's stream:
   consecutive periods shift every address by a constant
   ``addr_coefs[0]*step*T*CS``.  When that shift is cache-line-aligned
   (or zero) for every array, the per-period line sets are exact
   translates, so any reuse reaches back at most
   ``G = floor(span/|shift|) + 1`` periods and the per-period reuse-event
   multiset is EXACTLY periodic from period ``G`` on.  The derivation
   enumerates ``G + 2`` head periods, verifies ``events(G) ==
   events(G+1)`` (the lattice-count soundness check), multiplies the
   steady multiset across the remaining periods, and reconstructs a
   ragged tail from a ``G + 1``-period suffix window.  Work is
   ``O(T * G * CS * body)`` — independent of the trip count, which is
   what makes ``gemm`` at n=1024 (4.3e9 accesses) derivable in
   milliseconds-to-seconds on the host.
2. **Dense polynomial counting** (:func:`_dense`) — triangular and
   quad-contract families (and any rectangular shape that fails the
   uniformity precondition, e.g. syrk's mixed ``A[i][k]``/``A[j][k]``
   parallel coefficients): the polyhedra are enumerated per thread in
   position-ordered blocks against a carried last-access table
   (:func:`pluss.analysis.polycount.scan_events`).  Exact for every
   shape the engine accepts; cost is the access count, gated by
   ``PLUSS_PREDICT_BUDGET``.
3. **Typed verdict** — outside both (contract/lint rejection: PL701;
   enumeration beyond budget: PL702) the prediction is refused with a
   machine-readable diagnostic, never approximated.

The exact plateau (:func:`predict`) must land inside the heuristic
``MrcBracket`` of :mod:`pluss.analysis.footprint` — violation emits
PL704, the cross-prover soundness alarm.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from pluss import cri, mrc
from pluss.analysis import footprint as footprint_mod
from pluss.analysis import polycount as pc
from pluss.analysis.diagnostics import Diagnostic, Severity
from pluss.config import DEFAULT, SamplerConfig
from pluss.sched import ChunkSchedule
from pluss.spec import (LoopNestSpec, SpecContractError, flatten_nest,
                        nest_has_bounds, nest_has_varying_start,
                        nest_iteration_size, nest_iteration_sizes)

#: default enumeration budget (lattice cells) — covers every registry
#: family at its default size densely AND the gemm-1024 closed form
BUDGET_DEFAULT = 1 << 28


def predict_budget() -> int:
    from pluss.utils.envknob import env_int

    return env_int("PLUSS_PREDICT_BUDGET", BUDGET_DEFAULT)


class _Fallback(Exception):
    """Closed-form preconditions failed mid-derivation; try denser rung."""


@dataclasses.dataclass
class Prediction:
    """Statically derived per-thread histograms of one spec.

    ``noshare``/``share`` use the exact dict formats of
    ``SamplerResult.noshare_list()``/``share_list()`` so bit-identity to
    an engine run is plain ``==``.  ``method`` is the ladder rung taken
    (``closed-form`` | ``dense``); None when not derivable.
    """

    model: str
    thread_num: int
    derivable: bool
    method: str | None
    noshare: list[dict] | None
    share: list[dict] | None
    accesses: int
    diagnostics: list[Diagnostic]
    #: closed-form only: the verified period horizon G (reuse reaches at
    #: most this many chunks back); None for dense
    periods: int | None = None
    footprint: footprint_mod.Footprint | None = None

    def matches_engine(self, res) -> bool:
        """Bit-identity against a ``SamplerResult``."""
        return (self.derivable
                and self.noshare == res.noshare_list()
                and self.share == res.share_list()
                and self.accesses == res.max_iteration_count)


@dataclasses.dataclass
class PredictReport:
    """One model's full static prediction: histograms + MRC + plateau."""

    prediction: Prediction
    bracket: footprint_mod.MrcBracket
    rihist: dict | None = None
    curve: np.ndarray | None = None
    plateau: int | None = None
    #: None when the plateau is unreachable in the modeled cache range;
    #: False triggers the PL704 soundness alarm
    plateau_in_bracket: bool | None = None

    @property
    def refined_bracket(self) -> footprint_mod.MrcBracket:
        """The exact plateau REPLACES the heuristic bounds wherever it
        is derivable and sound; the PR-3 bracket stays as the fallback."""
        if self.plateau is not None and self.plateau_in_bracket:
            return self.bracket.refined(self.plateau)
        return self.bracket


def _diag(code: str, sev: Severity, msg: str, model: str) -> Diagnostic:
    return Diagnostic(code=code, severity=sev, message=msg, model=model)


# ---------------------------------------------------------------------------
# dense polynomial counting (rung 2)


def _dense(spec: LoopNestSpec, cfg: SamplerConfig,
           flats: list) -> tuple[list[dict], list[dict]]:
    """Exact per-thread histograms by blocked polyhedron enumeration."""
    T = cfg.thread_num
    bases = dict(zip((a for a, _ in spec.arrays), spec.line_bases(cfg)))
    counts = dict(zip((a for a, _ in spec.arrays), spec.line_counts(cfg)))
    n_lines = spec.total_lines(cfg)
    noshare, share = [], []
    scheds = [ChunkSchedule(cfg.chunk_size, nest.trip, nest.start,
                            nest.step, T) for nest in spec.nests]
    for tid in range(T):
        last_pos = np.full(n_lines, -1, np.int64)
        nsh: dict = {}
        shr: dict = {}
        base = 0
        for nest, sched, frs in zip(spec.nests, scheds, flats):
            if nest.trip <= 0:
                continue
            gs = pc.owned_iterations(sched, tid)
            if not len(gs):
                continue
            clks = pc.start_clocks(nest, gs, base)
            cells = sum(pc.ref_box_cells(fr) for fr in frs)
            for i0, i1 in pc.iteration_blocks(gs, cells):
                pos, line, span = pc.nest_block_events(
                    nest, frs, gs[i0:i1], clks[i0:i1],
                    bases.__getitem__, counts.__getitem__, cfg)
                nk, nc, sk, sc, _ = pc.scan_events(last_pos, pos, line,
                                                   span)
                pc.bump(nsh, nk, nc)
                pc.bump(shr, sk, sc)
            base = int(clks[-1]) + int(
                nest_iteration_sizes(nest, gs[-1:])[0])
        cold = float(int((last_pos >= 0).sum()))
        out = {-1: cold}
        out.update(sorted(nsh.items()))
        noshare.append(out)
        share.append({T - 1: dict(sorted(shr.items()))} if shr else {})
    return noshare, share


# ---------------------------------------------------------------------------
# closed-form periodic counting (rung 1)


def _uniform_reject(spec: LoopNestSpec, cfg: SamplerConfig,
                    flats: list) -> str | None:
    """None when the closed-form preconditions hold, else the reason."""
    if len(spec.nests) != 1:
        return "multiple nests (clocks persist across them)"
    nest = spec.nests[0]
    if nest.trip <= 0:
        return "empty parallel loop"
    if nest_has_bounds(nest) or nest_has_varying_start(nest):
        return "triangular/varying-start nest (polynomial counts apply)"
    per_arr: dict[str, set] = {}
    for fr in flats[0]:
        per_arr.setdefault(fr.ref.array, set()).add(fr.addr_coefs[0])
    T, CS = cfg.thread_num, cfg.chunk_size
    for a, cs in per_arr.items():
        if len(cs) > 1:
            return (f"array {a}: references disagree on the parallel "
                    "address coefficient (period shift is not uniform)")
        shift = next(iter(cs)) * nest.step * T * CS
        if shift and (shift * cfg.ds) % cfg.cls:
            return (f"array {a}: period shift of {shift} elements is "
                    "not cache-line-aligned")
    return None


def _inner_extremes(frs) -> dict:
    """Per-array (lo, hi, c0): extremes of each ref's g-independent
    address part over its full inner box (an affine form's extremes over
    a box are the sums of per-axis extremes — closed form, no
    enumeration), plus the shared parallel coefficient.  The g term is
    excluded; period translation shifts both extremes equally."""
    by_array: dict[str, tuple] = {}
    for fr in frs:
        lo = hi = fr.ref.addr_base
        for l in range(1, len(fr.trips)):
            base_l = fr.addr_coefs[l] * fr.starts[l]
            ext = fr.addr_coefs[l] * fr.steps[l] * (fr.trips[l] - 1)
            lo += base_l + min(0, ext)
            hi += base_l + max(0, ext)
        cur = by_array.get(fr.ref.array)
        if cur is None:
            by_array[fr.ref.array] = (lo, hi, fr.addr_coefs[0])
        else:
            by_array[fr.ref.array] = (min(cur[0], lo), max(cur[1], hi),
                                      cur[2])
    return by_array


def _period_horizon(spec: LoopNestSpec, cfg: SamplerConfig,
                    frs: list) -> int:
    """G: reuse reaches at most G owned chunks (periods) back.

    Per array: touching periods of any line lie inside an interval of
    ``floor(span/|shift|) + 1`` periods (span = the period touch set's
    line span, shift = the per-period line translation), so the most
    recent predecessor is at most ``floor(span/|shift|)`` periods back;
    a zero shift repeats the same set every period (predecessor distance
    1).  The +1 margin absorbs line-boundary straddle and is re-verified
    by the ``events(G) == events(G+1)`` check.
    """
    nest = spec.nests[0]
    T, CS = cfg.thread_num, cfg.chunk_size
    by_array = _inner_extremes(frs)
    G = 1
    for a, (lo, hi, c0) in by_array.items():
        # one period's parallel extent: CS consecutive g values
        par = c0 * nest.step * (CS - 1)
        span_el = (hi + max(0, par)) - (lo + min(0, par))
        shift_lines = abs(c0 * nest.step * T * CS) * cfg.ds // cfg.cls
        if shift_lines == 0:
            G = max(G, 1)
        else:
            span_lines = span_el * cfg.ds // cfg.cls + 1
            G = max(G, span_lines // shift_lines + 1)
    return G


def _closed_form(spec: LoopNestSpec, cfg: SamplerConfig, flats: list,
                 fp: footprint_mod.Footprint,
                 budget: int) -> tuple[list[dict], list[dict], int]:
    """The periodic derivation; raises :class:`_Fallback` on any failed
    precondition or verification so the caller can take the dense rung."""
    nest = spec.nests[0]
    frs = flats[0]
    T, CS = cfg.thread_num, cfg.chunk_size
    S = nest_iteration_size(nest)
    sched = ChunkSchedule(CS, nest.trip, nest.start, nest.step, T)
    G = _period_horizon(spec, cfg, frs)
    cells_per_iter = sum(pc.ref_box_cells(fr) for fr in frs)
    planned = 0
    for tid in range(T):
        t_chunks = sched.chunks_of_thread(tid)
        if not t_chunks:
            continue
        b, e = sched.chunk_index_range(t_chunks[-1])
        t_partial = (e - b) < CS
        full = len(t_chunks) - (1 if t_partial else 0)
        periods = min(full, G + 2)
        if t_partial:
            periods += (G + 2) if full > G + 2 else 1
        planned += periods
    planned *= CS * cells_per_iter
    if planned > budget:
        raise _Fallback(
            f"closed form needs ~{planned} cells (period horizon G={G}) "
            f"over the {budget} budget")
    bases = dict(zip((a for a, _ in spec.arrays), spec.line_bases(cfg)))
    counts = dict(zip((a for a, _ in spec.arrays), spec.line_counts(cfg)))
    n_lines = spec.total_lines(cfg)

    def run_block(gs, clks, last_pos, nsh, shr, count_from=None) -> None:
        for i0, i1 in pc.iteration_blocks(gs, cells_per_iter):
            pos, line, span = pc.nest_block_events(
                nest, frs, gs[i0:i1], clks[i0:i1],
                bases.__getitem__, counts.__getitem__, cfg)
            nk, nc, sk, sc, _ = pc.scan_events(last_pos, pos, line, span,
                                               count_from)
            pc.bump(nsh, nk, nc)
            pc.bump(shr, sk, sc)

    noshare, share = [], []
    for tid in range(T):
        chunks = sched.chunks_of_thread(tid)
        cold = float(int(fp.per_thread[tid].sum()))
        if not chunks:
            noshare.append({-1: 0.0})
            share.append({})
            continue
        b_last, e_last = sched.chunk_index_range(chunks[-1])
        tail_len = e_last - b_last
        partial = tail_len < CS
        P_full = len(chunks) - (1 if partial else 0)

        def period(p):
            gs = chunks[p] * CS + np.arange(CS, dtype=np.int64)
            clks = (np.int64(p) * CS + np.arange(CS, dtype=np.int64)) * S
            return gs, clks

        nsh: dict = {}
        shr: dict = {}
        last_pos = np.full(n_lines, -1, np.int64)
        deltas = {}
        for p in range(min(P_full, G + 2)):
            gs, clks = period(p)
            d_n: dict = {}
            d_s: dict = {}
            run_block(gs, clks, last_pos, d_n, d_s)
            for k, v in d_n.items():
                nsh[k] = nsh.get(k, 0.0) + v
            for k, v in d_s.items():
                shr[k] = shr.get(k, 0.0) + v
            if p >= G:
                deltas[p] = (d_n, d_s)
        if P_full > G + 2:
            if deltas[G] != deltas[G + 1]:
                raise _Fallback(
                    f"period multisets diverge at horizon G={G} "
                    "(uniformity verification failed)")
            reps = P_full - (G + 2)
            for k, v in deltas[G + 1][0].items():
                nsh[k] = nsh.get(k, 0.0) + v * reps
            for k, v in deltas[G + 1][1].items():
                shr[k] = shr.get(k, 0.0) + v * reps
            if partial:
                # ragged tail: a G+1-period suffix window re-creates the
                # exact predecessor state any tail access can reach
                lp2 = np.full(n_lines, -1, np.int64)
                tail_start = np.int64(P_full) * CS * S
                for p in range(P_full - (G + 1), P_full):
                    gs, clks = period(p)
                    run_block(gs, clks, lp2, nsh, shr,
                              count_from=int(tail_start))
                gs = np.arange(b_last, e_last, dtype=np.int64)
                clks = (np.int64(P_full) * CS
                        + np.arange(tail_len, dtype=np.int64)) * S
                run_block(gs, clks, lp2, nsh, shr,
                          count_from=int(tail_start))
        elif partial:
            gs = np.arange(b_last, e_last, dtype=np.int64)
            clks = (np.int64(P_full) * CS
                    + np.arange(tail_len, dtype=np.int64)) * S
            run_block(gs, clks, last_pos, nsh, shr)
        # mass balance: every access is one reuse event or one cold line
        total = sum(nsh.values()) + sum(shr.values()) + cold
        expect = float(int(fp.per_thread_accesses[tid]))
        if total != expect:
            raise _Fallback(
                f"thread {tid}: closed-form mass {total} != stream "
                f"length {expect} (soundness check failed)")
        out = {-1: cold}
        out.update(sorted(nsh.items()))
        noshare.append(out)
        share.append({T - 1: dict(sorted(shr.items()))} if shr else {})
    return noshare, share, G


# ---------------------------------------------------------------------------
# the ladder


def derive(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
           budget: int | None = None) -> Prediction:
    """Derive the per-thread reuse-interval histograms statically.

    Never raises for an in-contract spec: refusals come back as a
    non-derivable :class:`Prediction` with PL701/PL702 diagnostics.
    """
    from pluss import obs

    if budget is None:
        budget = predict_budget()
    model = spec.name
    diags: list[Diagnostic] = []
    try:
        flats = [flatten_nest(nest) for nest in spec.nests]
    except SpecContractError as e:
        diags.append(_diag(
            "PL701", Severity.WARNING,
            f"reuse distribution not statically derivable: spec outside "
            f"the position contract ({e.code}: {e})", model))
        return Prediction(model, cfg.thread_num, False, None, None, None,
                          0, diags)
    from pluss.analysis import lint_spec

    lint_errs = [d for d in lint_spec(spec)
                 if d.severity is Severity.ERROR]
    if lint_errs:
        diags.append(_diag(
            "PL701", Severity.WARNING,
            "reuse distribution not statically derivable: the address "
            f"model is invalid ({len(lint_errs)} lint ERROR(s), first "
            f"{lint_errs[0].code})", model))
        return Prediction(model, cfg.thread_num, False, None, None, None,
                          0, diags)
    with obs.span("ri.derive", model=model, threads=cfg.thread_num):
        fp = footprint_mod.footprints(spec, cfg)
        reject = _uniform_reject(spec, cfg, flats)
        if reject is None:
            try:
                noshare, share, G = _closed_form(spec, cfg, flats, fp,
                                                 budget)
                diags.append(_diag(
                    "PL703", Severity.INFO,
                    f"closed-form periodic derivation: period horizon "
                    f"G={G}, {fp.accesses} accesses counted without "
                    "enumeration", model))
                return Prediction(model, cfg.thread_num, True,
                                  "closed-form", noshare, share,
                                  int(fp.accesses), diags, periods=G,
                                  footprint=fp)
            except _Fallback as f:
                reject = str(f)
        cells = pc.spec_cells(spec)
        if cells > budget:
            diags.append(_diag(
                "PL702", Severity.WARNING,
                f"prediction enumeration of {cells} lattice cells "
                f"exceeds the {budget}-cell budget and no closed form "
                f"applies ({reject}); raise PLUSS_PREDICT_BUDGET to "
                "force the dense derivation", model))
            return Prediction(model, cfg.thread_num, False, None, None,
                              None, int(fp.accesses), diags,
                              footprint=fp)
        noshare, share = _dense(spec, cfg, flats)
        diags.append(_diag(
            "PL703", Severity.INFO,
            f"dense polynomial-count derivation: {cells} lattice cells "
            f"({reject})", model))
        return Prediction(model, cfg.thread_num, True, "dense", noshare,
                          share, int(fp.accesses), diags,
                          footprint=fp)


def predict(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
            budget: int | None = None) -> PredictReport:
    """Full static prediction: histograms -> CRI -> AET MRC -> plateau,
    checked against the PR-3 bracket (PL704 on violation)."""
    pred = derive(spec, cfg, budget)
    fp = pred.footprint
    bracket = footprint_mod.mrc_bracket(spec, cfg, fp)
    report = PredictReport(pred, bracket)
    if not pred.derivable:
        return report
    report.rihist = cri.distribute(pred.noshare, pred.share,
                                   cfg.thread_num)
    report.curve = mrc.aet_mrc(report.rihist, cfg)
    report.plateau = mrc.plateau_of(report.rihist, report.curve)
    if report.plateau is not None:
        report.plateau_in_bracket = (
            bracket.c_lo <= report.plateau <= bracket.c_hi)
        if not report.plateau_in_bracket:
            pred.diagnostics.append(_diag(
                "PL704", Severity.ERROR,
                f"exact MRC plateau at cache size {report.plateau} lies "
                f"outside the static bracket [{bracket.c_lo}, "
                f"{bracket.c_hi}] — one of the provers is unsound",
                pred.model))
    return report


#: stated MRC tolerance of the predict≡engine contract.  Since r15 the
#: CRI pass accumulates floats in SORTED key order (pluss/cri.py), so
#: equal histograms compose to BIT-IDENTICAL curves regardless of dict
#: insertion or device-merge order — ``mrc_exact`` is the expected
#: outcome on every family, and the epsilon is kept only as a stated
#: contract bound, not an observed error
MRC_EPS = 1e-9


def check_against_engine(report: PredictReport, res,
                         cfg: SamplerConfig) -> tuple[bool, dict]:
    """The ``--check`` contract: histograms bit-identical to the engine,
    composed MRC within :data:`MRC_EPS` relative L2 (equal histograms
    compose to the same curve up to float summation order), and the
    exact plateau (when reached) inside the heuristic bracket."""
    pred = report.prediction
    hist_ok = pred.matches_engine(res)
    ref_curve = mrc.aet_mrc(
        cri.distribute(res.noshare_list(), res.share_list(),
                       cfg.thread_num), cfg)
    err = mrc.l2_error(report.curve, ref_curve) \
        if report.curve is not None else float("inf")
    mrc_exact = report.curve is not None and np.array_equal(
        report.curve, ref_curve)
    bracket_ok = report.plateau_in_bracket is not False
    ok = hist_ok and err <= MRC_EPS and bracket_ok
    return ok, {
        "histogram_identical": hist_ok,
        "mrc_exact": mrc_exact,
        "mrc_l2_error": err,
        "plateau_in_bracket": report.plateau_in_bracket,
    }


def report_doc(report: PredictReport) -> dict:
    """JSON view of one prediction (the CLI/serve/sweep block)."""
    pred = report.prediction
    doc: dict = {
        "derivable": pred.derivable,
        "method": pred.method,
        "accesses": pred.accesses,
        "threads": pred.thread_num,
        "mrc_plateau_bounds": [report.bracket.c_lo, report.bracket.c_hi],
        "mrc_floor": report.bracket.floor,
    }
    if pred.periods is not None:
        doc["period_horizon"] = pred.periods
    if pred.derivable:
        doc["cold"] = [float(h.get(-1, 0.0)) for h in pred.noshare]
        doc["histogram_keys"] = len(report.rihist)
        doc["histogram_mass"] = float(sum(report.rihist.values()))
        doc["mrc_points"] = int(len(report.curve))
        doc["mrc_terminal"] = float(report.curve[-1])
    if report.plateau is not None:
        doc["mrc_plateau_exact"] = report.plateau
        doc["plateau_in_bracket"] = report.plateau_in_bracket
    if pred.diagnostics:
        doc["diagnostics"] = [d.to_dict() for d in pred.diagnostics]
    return doc
