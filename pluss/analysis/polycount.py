"""Exact lattice-point machinery for the symbolic reuse-interval pass.

:mod:`pluss.analysis.ri` derives reuse-interval histograms *statically* —
no engine dispatch, no stream walk on a device.  What makes that possible
is that every supported nest shape gives each reference occurrence a
closed-form stream position and element address in the iteration vector
(:class:`pluss.spec.FlatRef`): rectangular families are pure affine forms
(the Ehrhart-style uniform case — lattice counts of the reuse polyhedra
are periodic in the chunk schedule, see :func:`pluss.analysis.ri` for the
closed-form composition), and the triangular/quad-contract families add
``tri(x) = x*(x-1)/2`` terms that stay exact polynomial counts.

This module holds the shared counting kernels:

- :func:`flatref_events` evaluates one FlatRef's (position, line, span)
  lattice over a set of owned parallel iterations — the same arithmetic
  as the engine's ``_ref_window`` (:mod:`pluss.engine`), in host numpy,
  so the derived events are bit-identical to the device enumeration.
- :func:`scan_events` turns a position-ordered event block into exact
  reuse intervals against a carried last-access table — the PARDA-style
  decomposition of :mod:`pluss.ops.reuse`, vectorized per block.
- :func:`pow2_floor` is the reference's insert-time log2 binning
  (``1 << (x.bit_length() - 1)``) as integer bit-smearing — no float
  ``log2`` anywhere, so binning is exact for any 63-bit reuse.

Everything here is integer numpy on the host; nothing imports jax.
"""

from __future__ import annotations

import numpy as np

from pluss.config import SamplerConfig
from pluss.spec import (FlatRef, Loop, LoopNestSpec, flatten_nest,
                        nest_iteration_sizes)

#: enumeration cells one event block may materialize (memory bound; the
#: iteration axis is blocked to stay under it)
BLOCK_CELLS = 1 << 22


def pow2_floor(x: np.ndarray) -> np.ndarray:
    """Highest power of two <= x, elementwise, for x >= 1 (int64).

    The reference's insert-time binning is ``1 << (bit_length - 1)``
    (``_pluss_histogram_update``, utils.rs:142-152); bit-smearing computes
    the same without a Python loop or float rounding.
    """
    x = np.asarray(x, np.int64)
    for s in (1, 2, 4, 8, 16, 32):
        x = x | (x >> s)
    return x - (x >> 1)


def tri(x):
    """tri(x) = x*(x-1)//2 — the quad contract's closed-form term."""
    return x * (x - 1) // 2


def ref_box_cells(fr: FlatRef) -> int:
    """Lattice cells one parallel iteration of this ref enumerates (the
    static inner box; bounded levels count at their declared maximum)."""
    n = 1
    for t in fr.trips[1:]:
        n *= max(int(t), 0)
    return n


def nest_cells(nest: Loop) -> int:
    """Enumeration cells of one nest = trip * sum of per-ref boxes."""
    return max(int(nest.trip), 0) * sum(
        ref_box_cells(fr) for fr in flatten_nest(nest))


def spec_cells(spec: LoopNestSpec) -> int:
    """Total enumeration cells of a dense derivation of ``spec``."""
    return sum(nest_cells(nest) for nest in spec.nests)


def flatref_events(fr: FlatRef, nest: Loop, gs: np.ndarray,
                   clks: np.ndarray, line_base: int, line_count: int,
                   cfg: SamplerConfig):
    """(pos, line, span) int64 arrays of one ref over parallel iterations
    ``gs`` (global indices) with per-iteration start clocks ``clks``.

    Replicates the engine's ``_ref_window`` evaluation exactly: positions
    are the thread-stream clock at the access, addresses the affine form
    over iteration VALUES, lines ``base + addr*ds//cls``.  Invalid lattice
    cells (bounded levels) are masked out.  Lines are clipped into the
    array's range — out-of-range addresses are impossible for lint-clean
    specs (PL101 gates prediction), the clip just keeps a hostile spec
    from indexing outside the last-access table.
    """
    d = len(fr.trips)
    nd = 1 + (d - 1)

    def axis(arr, ax):
        return np.asarray(arr, np.int64).reshape(
            (1,) * ax + (-1,) + (1,) * (nd - ax - 1))

    g = axis(gs, 0)
    pos = axis(clks, 0) + fr.offset + fr.offset_k * g
    if fr.offset_g2:
        pos = pos + fr.offset_g2 * tri(g)
    addr = fr.ref.addr_base + fr.addr_coefs[0] * (
        nest.start + g * nest.step)
    valid = np.ones((len(gs),) + tuple(int(t) for t in fr.trips[1:]),
                    bool)
    idxs = {}
    for l in range(1, d):
        idx = axis(np.arange(int(fr.trips[l])), l)
        idxs[l] = idx
        sk = fr.pos_strides_k[l] if fr.pos_strides_k else 0
        pos = pos + idx * (fr.pos_strides[l] + sk * g)
        if fr.pos_quads and fr.pos_quads[l]:
            pos = pos + fr.pos_quads[l] * tri(idx)
        if fr.bounds and fr.bounds[l] is not None:
            a, b = fr.bounds[l]
            valid = valid & (idx < a + b * g)
        if fr.addr_coefs[l]:
            start_l = fr.starts[l]
            if fr.starts_k and fr.starts_k[l]:
                start_l = start_l + fr.starts_k[l] * g
            addr = addr + fr.addr_coefs[l] * (start_l + idx * fr.steps[l])
    for lv, a, b, rl in fr.inner_bounds or ():
        valid = valid & (idxs[lv] < a + b * idxs[rl])
    line = line_base + np.clip(addr * cfg.ds // cfg.cls, 0,
                               line_count - 1)
    if valid.all():
        # rectangular fast path: no constrained level, every lattice cell
        # is an access — a plain broadcast copy beats the boolean gather
        line = np.ascontiguousarray(
            np.broadcast_to(line, valid.shape)).ravel()
        pos = np.ascontiguousarray(
            np.broadcast_to(pos, valid.shape)).ravel()
    else:
        line = np.broadcast_to(line, valid.shape)[valid]
        pos = np.broadcast_to(pos, valid.shape)[valid]
    span = np.full(len(line), fr.ref.share_span or 0, np.int64)
    return pos, line, span


def nest_block_events(nest: Loop, frs: list[FlatRef], gs: np.ndarray,
                      clks: np.ndarray, line_base_of, line_count_of,
                      cfg: SamplerConfig):
    """Concatenated (pos, line, span) of every ref of ``nest`` over the
    iteration block ``gs`` — one scan_events input."""
    parts = [
        flatref_events(fr, nest, gs, clks, line_base_of(fr.ref.array),
                       line_count_of(fr.ref.array), cfg)
        for fr in frs
    ]
    return (np.concatenate([p[0] for p in parts]),
            np.concatenate([p[1] for p in parts]),
            np.concatenate([p[2] for p in parts]))


def scan_events(last_pos: np.ndarray, pos: np.ndarray, line: np.ndarray,
                span: np.ndarray, count_from: int | None = None):
    """One exact reuse scan of a position-ordered event block.

    ``last_pos`` is the carried dense last-access table (global line ->
    last stream position, -1 cold), updated in place; blocks MUST arrive
    in nondecreasing position order per thread.  Returns
    ``(ns_keys, ns_cnts, sh_keys, sh_cnts, n_cold)``: log2-binned noshare
    reuse keys with counts, raw share reuse keys with counts, and the
    number of first-touch (cold) accesses in the block.  Classification
    is the reference's: share iff ``span > 0 and 2*reuse > span`` using
    the LATER access's span; cold accesses emit no event (the end-of-run
    flush accounts for them).  ``count_from``: only accesses at positions
    >= it contribute events (the table still updates from all — the
    suffix-window tail composition of :mod:`pluss.analysis.ri`).
    """
    empty = np.empty(0, np.int64)
    if not len(pos):
        return empty, empty, empty, empty, 0
    # one composite-key argsort beats lexsort's two stable passes; the
    # key packs (line, pos) losslessly whenever both fit 63 bits
    p_hi = int(pos.max())
    l_hi = int(line.max())
    shift = max(p_hi, 0).bit_length()
    if l_hi.bit_length() + shift < 63:
        order = np.argsort((line << shift) | pos)
    else:
        order = np.lexsort((pos, line))
    ls, ps, sp = line[order], pos[order], span[order]
    first = np.empty(len(ls), bool)
    first[0] = True
    first[1:] = ls[1:] != ls[:-1]
    prev = np.empty(len(ls), np.int64)
    prev[1:][~first[1:]] = ps[:-1][~first[1:]]
    prev[first] = last_pos[ls[first]]
    # update the carry before any early return: last event per line
    last = np.empty(len(ls), bool)
    last[-1] = True
    last[:-1] = ls[1:] != ls[:-1]
    last_pos[ls[last]] = ps[last]
    reuse = ps - prev
    seen = prev >= 0
    n_cold = int((~seen).sum())
    if count_from is not None:
        counted = ps >= count_from
        n_cold = int((~seen & counted).sum())
        seen = seen & counted
    shr = seen & (sp > 0) & (2 * reuse > sp)
    nsh = seen & ~shr
    if nsh.any():
        # unique BEFORE binning: raw reuses are massively duplicated in
        # the uniform families, so the bit-smear runs on the few distinct
        # values; pow2_floor is monotone, so equal bins are adjacent
        rk, rc = np.unique(reuse[nsh], return_counts=True)
        bk = pow2_floor(rk)
        cut = np.flatnonzero(np.concatenate(([True], bk[1:] != bk[:-1])))
        ns_keys, ns_cnts = bk[cut], np.add.reduceat(rc, cut)
    else:
        ns_keys, ns_cnts = empty, empty
    sh_keys, sh_cnts = np.unique(reuse[shr], return_counts=True) \
        if shr.any() else (empty, empty)
    return ns_keys, ns_cnts, sh_keys, sh_cnts, n_cold


def bump(hist: dict, keys: np.ndarray, cnts: np.ndarray) -> None:
    """Add (keys, counts) into a {int: float} histogram dict — the same
    value format as ``SamplerResult.noshare_dict``/``share_dict``."""
    for k, c in zip(keys.tolist(), cnts.tolist()):
        hist[k] = hist.get(k, 0.0) + float(c)


def owned_iterations(sched, tid: int) -> np.ndarray:
    """Global iteration indices thread ``tid`` owns, execution order."""
    CS = sched.chunk_size
    out = []
    for cid in sched.chunks_of_thread(tid):
        b, e = sched.chunk_index_range(cid)
        out.append(np.arange(b, e, dtype=np.int64))
    if not out:
        return np.empty(0, np.int64)
    return np.concatenate(out)


def start_clocks(nest: Loop, gs: np.ndarray, base: int) -> np.ndarray:
    """Per-iteration start clocks of a thread's owned iterations ``gs``:
    ``base`` (the thread's clock entering the nest) plus the exclusive
    running sum of the exact per-iteration access counts."""
    if not len(gs):
        return np.empty(0, np.int64)
    sizes = np.asarray(nest_iteration_sizes(nest, gs), np.int64)
    return base + np.concatenate(
        ([0], np.cumsum(sizes[:-1], dtype=np.int64)))


def iteration_blocks(gs: np.ndarray, cells_per_iter: int,
                     budget: int = BLOCK_CELLS):
    """Split an iteration vector into contiguous blocks of at most
    ``budget`` enumeration cells (always at least one iteration)."""
    step = max(1, budget // max(1, cells_per_iter))
    for i in range(0, len(gs), step):
        yield i, min(i + step, len(gs))
