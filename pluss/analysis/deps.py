"""Affine dependence / race detection on the parallel dimension.

PLUSS trusts the ``#pragma pluss parallel`` assertion: the outermost loop
of every nest is chunked over simulated threads with no further checking.
This pass proves or refutes that trust statically.  For a pair of
references on the same array the question is whether two DISTINCT parallel
iterations can touch the same element::

    addr_1(k1, i⃗) = addr_2(k2, j⃗),   k1 != k2

with both sides affine (:class:`pluss.analysis.walk.AddrForm`).  The test
is exact in ``k`` — the parallel axis is enumerated (it is the quantity
under test, and the per-``k`` inner domains of triangular nests make it
non-rectangular) — and Banerjee-style in the inner indices: at fixed
``(k1, k2)`` the inner contribution must land in its exact interval
``[lo1-hi2, hi1-lo2]`` AND satisfy the GCD divisibility condition.  A
refutation is therefore a proof; a confirmation is conservative in the
usual dependence-analysis sense (interval + gcd, not full ILP), which is
the right polarity for a race detector.

Granularity is the ELEMENT, not the cache line: races are a property of
data addresses.  The share/reuse machinery is line-granular, so the
cross-check against the engine's dynamic share split
(``tests/test_analysis.py``) uses sizes where rows align to lines.

Classification (:func:`classify`) answers three questions per reference,
all consumed by the share-span pass and the dynamic cross-check:

- ``carried_level``: the OUTERMOST loop level that can carry a self-reuse
  of the reference (0 = the parallel loop; None = no self-reuse at all).
- ``cross_parallel``: some same-array reference pair (including itself)
  conflicts across distinct parallel iterations — under chunked
  scheduling some schedule places the two iterations on different
  simulated threads, so this is exactly "the reuse can cross threads".
  Same-nest pairs compare parallel indices (``k1 != k2``); pairs in
  DIFFERENT nests are also reuses (the per-thread last-access tables
  persist across the back-to-back nests) and compare parallel VALUES.
  Races (PL30x) stay same-nest: nests never run concurrently.
- ``cross_observed``: the directed refinement — this reference can be the
  LATER access of such a pair (``k_prev < k_obs`` within a nest, or the
  partner sitting in an earlier nest).  Dynamically the later access is
  where the reuse (and the share test) is observed, so this is the bit a
  ``share_span`` annotation encodes.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from pluss.analysis.diagnostics import Diagnostic, Severity, shown
from pluss.analysis.walk import (AddrForm, RefSite, addr_form,
                                 inner_profile, ref_sites)
from pluss.spec import LoopNestSpec, SpecContractError


@dataclasses.dataclass(frozen=True)
class SiteProfile:
    site: RefSite
    form: AddrForm
    alive: np.ndarray   # [trip0] bool
    lo: np.ndarray      # [trip0] inner-contribution min
    hi: np.ndarray      # [trip0] inner-contribution max


@dataclasses.dataclass(frozen=True)
class RefClass:
    site: RefSite
    carried_level: int | None
    cross_parallel: bool
    cross_observed: bool


def _profile(site: RefSite) -> SiteProfile | None:
    try:
        form = addr_form(site)
    except SpecContractError:
        return None  # the contract pass owns this report
    alive, lo, hi = inner_profile(form)
    return SiteProfile(site, form, alive, lo, hi)


#: k1-axis block size of the pair test: bounds transient memory to
#: ~6 * BLOCK * trip0 int64 cells per test instead of O(trip0^2), so a
#: ``--verify`` pre-pass at n=4096 stays tens of MB, not gigabytes.
_PAIR_BLOCK = 1024


def _feasible(p1: SiteProfile, p2: SiteProfile, rel,
              delta: int = 0) -> bool:
    """True when ``addr_1(k1, ·) - addr_2(k2, ·) = delta`` has a feasible
    solution with ``rel(k1, k2)`` (a broadcastable boolean relation on the
    two parallel-index grids).  Exact over k; GCD + interval (Banerjee)
    over inner indices.  ``delta=0`` is the same-element (race) test; the
    false-sharing pass probes the sub-line offsets ``0 < |delta| < E``.
    """
    f1, f2 = p1.form, p2.form
    g = math.gcd(f1.inner_gcd(), f2.inner_gcd())
    k2 = np.arange(f2.trip0, dtype=np.int64)[None, :]
    base2 = f2.const + f2.k_coef * k2
    for b0 in range(0, f1.trip0, _PAIR_BLOCK):
        k1 = np.arange(b0, min(b0 + _PAIR_BLOCK, f1.trip0),
                       dtype=np.int64)[:, None]
        sl = slice(b0, b0 + len(k1))
        # need: (inner_1 - inner_2) = D(k1, k2)
        D = base2 - (f1.const + f1.k_coef * k1) + delta
        L = p1.lo[sl, None] - p2.hi[None, :]
        H = p1.hi[sl, None] - p2.lo[None, :]
        divisible = (D % g == 0) if g else (D == 0)
        mask = p1.alive[sl, None] & p2.alive[None, :] & rel(k1, k2)
        if bool(np.any(mask & (D >= L) & (D <= H) & divisible)):
            return True
    return False


def _pair_conflict(p1: SiteProfile, p2: SiteProfile,
                   directed: bool = False) -> bool:
    """Same-nest pair test: distinct parallel iterations, ``k1 != k2``
    (``directed``: ``k1 > k2``, i.e. site 1 is the later access).  The
    undirected test is symmetric in (p1, p2)."""
    if p1.form.trip0 != p2.form.trip0 or p1.form.trip0 <= 1:
        return False
    rel = (lambda k1, k2: k1 > k2) if directed \
        else (lambda k1, k2: k1 != k2)
    return _feasible(p1, p2, rel)


def _cross_nest_conflict(p1: SiteProfile, p2: SiteProfile) -> bool:
    """Different-nest pair test: nests run back-to-back over the SAME
    per-thread last-access tables (LoopNestSpec docstring), so a later
    nest's access can observe a reuse of an earlier nest's — at ANY pair
    of parallel indices.  "Crosses the parallel dimension" then means the
    two occurrences' parallel VALUES differ (the nests may disagree on
    start/step, e.g. ludcmp's descending back-substitution).  Symmetric.
    """
    l1, l2 = p1.site.chain[0], p2.site.chain[0]
    rel = lambda k1, k2: (l1.start + l1.step * k1) \
        != (l2.start + l2.step * k2)
    return _feasible(p1, p2, rel)


def _self_carried_levels(p: SiteProfile) -> list[int]:
    """Loop levels that can carry a self-reuse of the reference.

    Level 0 uses the exact-in-k pair test.  Level ``d >= 1`` asks for two
    occurrences with equal indices above ``d``, differing index AT ``d``,
    and equal addresses: ``B_d*Δ_d + Σ_{l>d} B_l*Δ_l = 0`` with
    ``Δ_d != 0`` — tested with static-maximum delta ranges (gcd +
    interval), conservative like the inner half of the pair test.
    """
    out = []
    if _pair_conflict(p, p):
        out.append(0)
    form = p.form
    maxes = [lv[-1] for lv in form.levels]        # static max trips
    for d in range(1, len(form.coefs) + 1):
        td = maxes[d - 1]
        if td < 2:
            continue
        bd = form.coefs[d - 1]
        if bd == 0:
            out.append(d)
            continue
        span = 0
        g = 0
        for l in range(d + 1, len(form.coefs) + 1):
            c, t = form.coefs[l - 1], maxes[l - 1]
            if c and t >= 2:
                span += abs(c) * (t - 1)
                g = math.gcd(g, abs(c))
        deltas = np.arange(1, td, dtype=np.int64) * bd
        feasible = (np.abs(deltas) <= span)
        feasible &= (deltas % g == 0) if g else (deltas == 0)
        if bool(feasible.any()):
            out.append(d)
    return out


@dataclasses.dataclass
class Analysis:
    """One spec's profiled sites + classification, computed ONCE and shared
    by the race pass and the share-span pass (profiling and the pair tests
    are the expensive half of the lint).

    ``classes`` is keyed by the site's tree PATH — globally unique even
    when ref names collide (name collisions are only a PL406 warning, and
    must never shadow another ref's diagnostics).  ``groups`` is per
    (nest, array): the race pass's scope, since nests execute sequentially
    and only same-nest conflicts are parallel races.  ``array_groups`` is
    per array across nests: the REUSE scope, since per-thread last-access
    tables persist across nests.
    """

    profiles: list[SiteProfile]
    groups: dict[tuple[int, str], list[SiteProfile]]
    array_groups: dict[str, list[SiteProfile]]
    classes: dict[str, RefClass]
    _memo: dict[tuple, bool]
    _index: dict[int, int]  # id(profile) -> position

    def conflict(self, p: SiteProfile, q: SiteProfile) -> bool:
        """Memoized same-nest undirected pair test (symmetric)."""
        key = ("same", *sorted((self._index[id(p)], self._index[id(q)])))
        if key not in self._memo:
            self._memo[key] = _pair_conflict(p, q)
        return self._memo[key]

    def xconflict(self, p: SiteProfile, q: SiteProfile) -> bool:
        """Memoized cross-nest conflict test (symmetric)."""
        key = ("x", *sorted((self._index[id(p)], self._index[id(q)])))
        if key not in self._memo:
            self._memo[key] = _cross_nest_conflict(p, q)
        return self._memo[key]


def analyze(spec: LoopNestSpec,
            skip_nests: frozenset[int] = frozenset()) -> Analysis:
    sites = [s for s in ref_sites(spec) if s.nest not in skip_nests]
    profiles = [p for p in map(_profile, sites) if p is not None]
    groups: dict[tuple[int, str], list[SiteProfile]] = {}
    arrays: dict[str, list[SiteProfile]] = {}
    for p in profiles:
        groups.setdefault((p.site.nest, p.site.ref.array), []).append(p)
        arrays.setdefault(p.site.ref.array, []).append(p)
    ana = Analysis(profiles, groups, arrays, {}, {},
                   {id(p): i for i, p in enumerate(profiles)})
    for p in profiles:
        group = groups[(p.site.nest, p.site.ref.array)]
        cross = any(ana.conflict(p, q) for q in group)
        # directed (k1 > k2) is a sub-relation of undirected (k1 != k2):
        # only partners the memoized undirected test confirmed can succeed
        observed = cross and any(_pair_conflict(p, q, directed=True)
                                 for q in group if ana.conflict(p, q))
        # cross-nest reuse: the per-thread LAT persists across nests, so
        # an earlier nest's touch of the same address at a different
        # parallel VALUE is an observable parallel-crossing reuse here
        for q in arrays[p.site.ref.array]:
            if q.site.nest == p.site.nest:
                continue
            earlier = q.site.nest < p.site.nest
            if cross and (observed or not earlier):
                continue  # nothing left to learn from this pair
            if ana.xconflict(p, q):
                cross = True
                observed = observed or earlier
        levels = _self_carried_levels(p)
        ana.classes[p.site.path] = RefClass(
            site=p.site,
            carried_level=min(levels) if levels else None,
            cross_parallel=cross,
            cross_observed=observed,
        )
    return ana


def classify(spec: LoopNestSpec,
             skip_nests: frozenset[int] = frozenset()) -> dict[str, RefClass]:
    """Per-reference classification, keyed by tree path."""
    return analyze(spec, skip_nests).classes


def check(spec: LoopNestSpec,
          skip_nests: frozenset[int] = frozenset(),
          analysis: Analysis | None = None) -> list[Diagnostic]:
    """Race diagnostics: PL301 (write-write) / PL302 (read-write) per
    conflicting same-array pair, one diagnostic per (nest, array, code)
    aggregating the pairs; PL303 INFO classification for every annotated
    (``share_span``) reference.

    Conflicts are WARNINGS, not errors: PLUSS models intentionally racy
    kernels (floyd_warshall's parallel-invariant stores, seidel2d's whole
    nest) — their locality is exactly what the sampler measures.  The
    lint's job is to make the pragma's assertion visible, not to forbid
    it.
    """
    diags: list[Diagnostic] = []
    ana = analysis if analysis is not None else analyze(spec, skip_nests)
    for (ni, array), group in sorted(ana.groups.items()):
        pairs: dict[str, list[str]] = {"PL301": [], "PL302": []}
        first_path: dict[str, str] = {}
        for i, p in enumerate(group):
            for q in group[i:]:
                if not (p.site.ref.is_write or q.site.ref.is_write):
                    continue
                if not ana.conflict(p, q):
                    continue
                code = "PL301" if (p.site.ref.is_write
                                   and q.site.ref.is_write) else "PL302"
                pairs[code].append(f"{p.site.ref.name}~{q.site.ref.name}")
                first_path.setdefault(code, p.site.path)
        for code, names in pairs.items():
            if not names:
                continue
            kind = "write-write" if code == "PL301" else "read-write"
            diags.append(Diagnostic(
                code=code, severity=Severity.WARNING,
                message=f"{kind} conflict on '{array}' across parallel "
                        f"iterations: {shown(names)} — the parallel "
                        "pragma asserts this is intended",
                path=first_path[code], nest=ni, array=array,
            ))
    for path, rc in sorted(ana.classes.items()):
        if rc.site.ref.share_span is None:
            continue
        lvl = rc.carried_level
        diags.append(Diagnostic(
            code="PL303", severity=Severity.INFO,
            message=(f"reuse carried at level "
                     f"{'none' if lvl is None else lvl}"
                     + (" (parallel)" if lvl == 0 else "")
                     + f"; cross-thread observable: {rc.cross_observed}"),
            path=path, nest=rc.site.nest, ref=rc.site.ref.name,
            array=rc.site.ref.array,
        ))
    return diags
