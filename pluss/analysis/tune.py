"""Proof-carrying static schedule auto-optimizer (``pluss tune``, PL9xx).

PLUSS exists to evaluate parallelization choices *without running them*
(PAPER.md §0); this pass is where the repo finally ACTS on its own
analysis.  Given a workload and a candidate space over
``(threads, chunk, window, share_cap)``, the optimizer scores every
schedule entirely on the host — the PR-12 derivability ladder
(:func:`pluss.analysis.ri.predict`) composed through CRI + AET and the
PR-15 hierarchy model's LLC read-off
(:func:`pluss.model.hierarchy.level_readoffs`) — and returns a TYPED,
proof-carrying verdict instead of a bare argmin:

- **PL901** proven-best schedule: every competitor was either fully
  derived and scored worse by more than the tie epsilon, or discarded by
  the dominance proof below.  The winning/runner-up margin attaches.
- **PL902** tie-within-epsilon: two or more schedules score within
  ``TIE_EPS`` of the optimum (e.g. chunk size at ``threads=1``, or the
  window/share_cap axes, which shape the dispatch but provably never the
  static miss ratio).  The canonical pick (fewest threads, smallest
  chunk) is named, with the full tie set attached.
- **PL903** typed refusal: some candidate that pruning could not discard
  fell off the derivability ladder (PL701/PL702) — no proven-best claim
  exists, and the cause chain attaches.  Never a silent approximation.
- **PL904** cross-check alarm (``--check`` only): a live engine run
  under the winning schedule disagreed with the predicted MRC beyond
  :data:`pluss.analysis.ri.MRC_EPS` — a soundness bug in exactly one of
  the two stacks.

**Dominance pruning** (the reason the search is exhaustive-with-pruning,
not exhaustive): a candidate is discarded WITHOUT full derivation only
when both of its cheap static quantities are dominated — its exact
per-thread footprint (the compulsory floor ``cold/N`` from
:func:`pluss.analysis.footprint.mrc_bracket`, exact for any schedule)
already exceeds the incumbent's fully-derived score by more than the tie
epsilon, and its plateau bracket can only tighten that claim (a target
below ``c_lo`` means the true curve sits strictly ABOVE the floor).
Soundness: every replacement model this repo prices — the exact LRU AET
read-off, the associativity Poisson model, and the random-replacement
fixed point — carries the cold mass additively, so any schedule's miss
ratio at ANY cache size is >= its compulsory floor.  A floor-dominated
candidate therefore can neither take PL901 nor enter the PL902 tie set.
Candidates are derived in floor-ascending order, which both maximizes
pruning and guarantees a pruned candidate could never have become the
incumbent.

Every full derivation rides the same budget gate as ``pluss predict``
(``PLUSS_PREDICT_BUDGET``); the search makes ZERO device dispatches
(witnessed in bench via :data:`pluss.engine.DEVICE_DISPATCHES`).
"""

from __future__ import annotations

import dataclasses

from pluss import obs
from pluss.analysis import footprint as footprint_mod
from pluss.analysis import ri as ri_mod
from pluss.analysis.diagnostics import Diagnostic, Severity
from pluss.config import DEFAULT, SHARE_CAP, SamplerConfig
from pluss.model import hierarchy as hier_mod
from pluss.spec import LoopNestSpec

#: two schedules within this of each other are a PL902 tie, not a win —
#: the same epsilon the engine cross-check uses, so "proven better" here
#: and "matches the engine" in --check mean the same distance
TIE_EPS = ri_mod.MRC_EPS

#: default search axes: the sweep's conventional thread/chunk grid, one
#: canonical dispatch shape (full scan, default share cap).  The window
#: and share_cap axes shape the DISPATCH, never the static miss ratio —
#: widening them only grows the PL902 tie set (asserted in tests).
DEFAULT_THREADS = (1, 2, 4, 8)
DEFAULT_CHUNKS = (1, 2, 4, 8)


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One schedule point of the search space."""

    threads: int
    chunk: int
    window: int | None = None
    share_cap: int = SHARE_CAP

    def cfg(self, base: SamplerConfig, cache_kb: int) -> SamplerConfig:
        """The SamplerConfig this candidate scores under: its schedule
        axes on ``base``, with the curve capacity pinned to the tuning
        target so the LLC read-off is never range-capped."""
        return dataclasses.replace(base, thread_num=self.threads,
                                   chunk_size=self.chunk,
                                   cache_kb=cache_kb)

    def label(self) -> str:
        w = "-" if self.window is None else str(self.window)
        return (f"threads={self.threads} chunk={self.chunk} "
                f"window={w} share_cap={self.share_cap}")


def space(threads=DEFAULT_THREADS, chunks=DEFAULT_CHUNKS,
          windows=(None,), share_caps=(SHARE_CAP,)) -> list[Candidate]:
    """The cross product of the four schedule axes, canonical order."""
    return [Candidate(int(t), int(c), w, int(s))
            for t in threads for c in chunks
            for w in windows for s in share_caps]


@dataclasses.dataclass
class ScoredCandidate:
    """One candidate's search record: the cheap static quantities are
    always present; ``report``/``score`` only after full derivation."""

    candidate: Candidate
    floor: float                  # exact compulsory lower bound (cold/N)
    c_lo: int                     # plateau bracket, from mrc_bracket
    c_hi: int
    pruned: bool = False
    report: object = None         # ri.PredictReport when derived
    score: float | None = None    # LLC read-off when derivable
    refused: bool = False

    def doc(self) -> dict:
        c = self.candidate
        d = {"threads": c.threads, "chunk": c.chunk, "window": c.window,
             "share_cap": c.share_cap, "floor": self.floor,
             "bracket": [self.c_lo, self.c_hi], "pruned": self.pruned}
        if self.score is not None:
            d["score"] = self.score
        if self.refused:
            d["refused"] = True
        return d


@dataclasses.dataclass
class TuneReport:
    """The search's full proof record: every candidate's disposition,
    the typed verdict, and the diagnostics that carry it."""

    model: str
    target_kb: int
    target_entries: int
    hier: hier_mod.HierarchyConfig
    candidates: list[ScoredCandidate]
    code: str                           # PL901 | PL902 | PL903
    winner: ScoredCandidate | None
    ties: list[ScoredCandidate]         # winner included when PL902
    margin: float | None                # vs best non-tied runner-up
    diagnostics: list[Diagnostic]

    @property
    def n_pruned(self) -> int:
        return sum(1 for s in self.candidates if s.pruned)

    @property
    def n_derived(self) -> int:
        return sum(1 for s in self.candidates if s.score is not None)

    def doc(self) -> dict:
        d = {
            "model": self.model,
            "target_kb": self.target_kb,
            "target_entries": self.target_entries,
            "hierarchy": {"levels_kb": list(self.hier.levels_kb),
                          "assoc": self.hier.assoc,
                          "policy": self.hier.policy},
            "verdict": self.code,
            "candidates": [s.doc() for s in self.candidates],
            "n_pruned": self.n_pruned,
            "n_derived": self.n_derived,
        }
        if self.winner is not None:
            d["winner"] = self.winner.doc()
            d["tie"] = [s.candidate.label() for s in self.ties]
            if self.margin is not None:
                d["margin"] = self.margin
        d["diagnostics"] = [g.to_dict() for g in self.diagnostics]
        return d


def _score_of(rep, cfg: SamplerConfig,
              hier: hier_mod.HierarchyConfig) -> float | None:
    """The tuning objective: the declared LLC's miss ratio under the
    configured assoc/policy model — the last
    :func:`~pluss.model.hierarchy.level_readoffs` row, which is the
    reference-exact LRU read-off in the default geometry."""
    if rep.rihist is None:
        return None
    return float(hier_mod.level_readoffs(rep.rihist, cfg,
                                         hier)[-1]["miss_ratio"])


def tune(spec: LoopNestSpec, base_cfg: SamplerConfig = DEFAULT,
         candidates: list[Candidate] | None = None,
         hier: hier_mod.HierarchyConfig | None = None,
         budget: int | None = None,
         tie_eps: float = TIE_EPS) -> TuneReport:
    """Search the candidate space, return the proof-carrying verdict.

    Pure host math end to end: zero device dispatches.  ``budget`` rides
    the same ``PLUSS_PREDICT_BUDGET`` gate as ``pluss predict`` (None =
    the env knob / default); pruned candidates never spend any of it.
    """
    cands = candidates if candidates is not None else space()
    if not cands:
        raise ValueError("tune: empty candidate space")
    hier = hier or hier_mod.HierarchyConfig.from_env()
    target_kb = int(hier.levels_kb[-1])
    target_entries = hier_mod.entries_of_kb(target_kb)
    if budget is None:
        budget = ri_mod.predict_budget()

    with obs.span("tune.search", model=spec.name, candidates=len(cands)):
        scored: list[ScoredCandidate] = []
        for cand in cands:
            cfg = cand.cfg(base_cfg, target_kb)
            br = footprint_mod.mrc_bracket(spec, cfg)
            scored.append(ScoredCandidate(cand, float(br.floor),
                                          int(br.c_lo), int(br.c_hi)))
        # floor-ascending derivation order: maximal pruning, and a pruned
        # candidate provably could never have become the incumbent (the
        # incumbent's score >= its own floor >= every later floor seen)
        order = sorted(range(len(scored)),
                       key=lambda i: (scored[i].floor, i))
        # the static score is invariant along the window/share_cap axes
        # (they shape the dispatch, not the reuse distribution), so one
        # derivation per (threads, chunk) covers the whole fiber
        memo: dict[tuple[int, int], tuple[object, float | None]] = {}
        best: ScoredCandidate | None = None
        refusal_chain: list[Diagnostic] = []
        for i in order:
            s = scored[i]
            if best is not None and best.score is not None \
                    and s.floor > best.score + tie_eps:
                # dominance proof: compulsory floor (exact footprint)
                # already beaten; the bracket only tightens the claim
                # (target below c_lo => true score strictly above floor)
                s.pruned = True
                obs.counter_add("tune.pruned")
                continue
            cand = s.candidate
            key = (cand.threads, cand.chunk)
            cfg = cand.cfg(base_cfg, target_kb)
            fresh = key not in memo
            if fresh:
                rep = ri_mod.predict(spec, cfg, budget=budget)
                sc = _score_of(rep, cfg, hier)
                memo[key] = (rep, sc)
                obs.counter_add("tune.derived")
            else:
                rep, sc = memo[key]
                obs.counter_add("tune.memo_hits")
            if sc is None:
                # off the derivability ladder: the PL701/702 chain rides
                # the report; the whole tune becomes a PL903 refusal
                s.refused = True
                if fresh:
                    refusal_chain += [
                        d for d in rep.prediction.diagnostics
                        if d.code in ("PL701", "PL702")]
                continue
            s.report, s.score = rep, sc
            if best is None or sc < best.score:
                best = s

    diags: list[Diagnostic] = []
    if any(s.refused for s in scored):
        n_ref = sum(1 for s in scored if s.refused)
        diags.append(Diagnostic(
            "PL903", Severity.WARNING,
            f"tune refused: {n_ref} candidate schedule(s) fell off the "
            "derivability ladder — no proven-best claim (cause chain "
            "attached); raise PLUSS_PREDICT_BUDGET or narrow the space"))
        diags += refusal_chain
        return TuneReport(spec.name, target_kb, target_entries, hier,
                          scored, "PL903", None, [], None, diags)

    derived = [s for s in scored if s.score is not None]
    best_score = min(s.score for s in derived)
    ties = [s for s in derived if s.score <= best_score + tie_eps]
    # canonical pick: fewest threads, then smallest chunk/window/cap —
    # deterministic, so tune's answer is reproducible run to run
    winner = min(ties, key=lambda s: (
        s.candidate.threads, s.candidate.chunk,
        s.candidate.window or 0, s.candidate.share_cap))
    # proven margin LOWER BOUND: a derived runner-up contributes its
    # exact score; a pruned candidate contributes its compulsory floor
    # (<= its true score, so the bound stays sound)
    tie_ids = {id(s) for s in ties}
    rest = [s.score if s.score is not None else s.floor
            for s in scored if id(s) not in tie_ids]
    margin = (min(rest) - winner.score) if rest else None
    if len(ties) > 1:
        code = "PL902"
        diags.append(Diagnostic(
            "PL902", Severity.INFO,
            f"{len(ties)} schedules tie within {tie_eps:g} at predicted "
            f"miss {winner.score:.6g} ({target_kb} KB LLC); canonical "
            f"pick {winner.candidate.label()}"))
    else:
        code = "PL901"
        m = f", margin >= {margin:.6g} over every competitor" \
            if margin is not None else ""
        diags.append(Diagnostic(
            "PL901", Severity.INFO,
            f"proven-best schedule {winner.candidate.label()}: predicted "
            f"miss {winner.score:.6g} at {target_kb} KB LLC{m} "
            f"({len(scored)} candidates: {len(derived)} derived, "
            f"{sum(1 for s in scored if s.pruned)} pruned by dominance)"))
    # the winner's own derivation notes (PL703 method, PL704 alarm if
    # the prover ever trips) ride the tune report too
    diags += list(winner.report.prediction.diagnostics)
    return TuneReport(spec.name, target_kb, target_entries, hier, scored,
                      code, winner, ties if len(ties) > 1 else [winner],
                      margin, diags)


def check_winner(spec: LoopNestSpec, report: TuneReport,
                 base_cfg: SamplerConfig = DEFAULT
                 ) -> tuple[bool, dict, list[Diagnostic]]:
    """The ``--check`` cross-validation: run the engine ONCE under the
    winning schedule and require the predicted histograms bit-identical
    and the MRC within :data:`~pluss.analysis.ri.MRC_EPS`
    (:func:`~pluss.analysis.ri.check_against_engine`).  Disagreement is
    the PL904 alarm — a soundness bug in the predictor, the engine, or
    the tuner's composition of them.  The ONLY device work in tune."""
    from pluss import engine

    if report.winner is None:
        raise ValueError("check_winner: no winner (refused tune report)")
    w = report.winner
    cfg = w.candidate.cfg(base_cfg, report.target_kb)
    res = engine.run(spec, cfg, w.candidate.share_cap,
                     window_accesses=w.candidate.window)
    ok, detail = ri_mod.check_against_engine(w.report, res, cfg)
    diags: list[Diagnostic] = []
    if not ok:
        diags.append(Diagnostic(
            "PL904", Severity.ERROR,
            f"tuned-winner cross-check failed for "
            f"{w.candidate.label()}: live engine run disagrees with the "
            f"predicted MRC beyond {ri_mod.MRC_EPS:g} ({detail})"))
    return ok, detail, diags
