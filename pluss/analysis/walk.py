"""Shared spec-tree walking and affine address forms for the analyzer.

Every pass reasons about the same two objects:

- :class:`RefSite` — one static reference plus its enclosing loop chain
  and a printable tree path (``nests[0].body[1].body[2]``).
- :class:`AddrForm` — the reference's element address as an affine form of
  the parallel INDEX ``k`` and the per-level inner indices::

      addr = const + k_coef*k + sum(coefs[l-1] * idx_l)   for levels l >= 1

  derived from ``addr = addr_base + sum(c_l * value_l)`` with
  ``value_l = start_l + start_coef_l*k + step_l*idx_l`` (and
  ``value_0 = start_0 + step_0*k``), exactly the engine's address rule
  (:func:`pluss.engine._ref_window`).

The iteration domain is captured per level as ``("const", trip)``,
``("k", a, b, trip)`` (trip ``a + b*k``, clamped to ``[0, trip]``) or
``("idx", m, a, b, trip)`` (trip ``a + b*idx_m`` — the quad contract).
:func:`inner_profile` turns that into exact per-``k`` vectors:
aliveness (does the ref execute at ``k`` at all) and min/max of the
inner-index contribution — interval arithmetic is exact for an affine
function over a box, and the one dependent-level case (quad) is folded by
enumerating the referenced index, so the profile stays exact for every
in-contract nest shape.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from pluss.spec import Loop, LoopNestSpec, Ref, SpecContractError


@dataclasses.dataclass(frozen=True)
class RefSite:
    ref: Ref
    chain: tuple[Loop, ...]   # enclosing loops, outermost (parallel) first
    nest: int                 # index into spec.nests
    path: str                 # "nests[0].body[1].body[2]"

    @property
    def depth(self) -> int:
        return len(self.chain)


def ref_sites(spec: LoopNestSpec) -> list[RefSite]:
    """All references of the spec with their chains and tree paths."""
    out: list[RefSite] = []

    def walk(item, chain: tuple[Loop, ...], ni: int, path: str) -> None:
        if isinstance(item, Ref):
            out.append(RefSite(item, chain, ni, path))
            return
        for bi, b in enumerate(item.body):
            walk(b, chain + (item,), ni, f"{path}.body[{bi}]")

    for ni, nest in enumerate(spec.nests):
        walk(nest, (), ni, f"nests[{ni}]")
    return out


def loop_sites(spec: LoopNestSpec):
    """All loops as ``(loop, chain_of_enclosing_loops, nest_index, path)``."""
    out = []

    def walk(item, chain: tuple[Loop, ...], ni: int, path: str) -> None:
        if isinstance(item, Ref):
            return
        out.append((item, chain, ni, path))
        for bi, b in enumerate(item.body):
            walk(b, chain + (item,), ni, f"{path}.body[{bi}]")

    for ni, nest in enumerate(spec.nests):
        walk(nest, (), ni, f"nests[{ni}]")
    return out


@dataclasses.dataclass(frozen=True)
class AddrForm:
    const: int
    k_coef: int
    coefs: tuple[int, ...]                 # per inner level 1..depth-1
    levels: tuple[tuple, ...]              # domain descriptor per inner level
    trip0: int                             # parallel trip count

    def inner_gcd(self) -> int:
        """gcd of the inner coefficients whose level can move (trip >= 2) —
        the divisibility half of the Banerjee/GCD feasibility test.  0 when
        no inner index can move (the inner contribution is then exactly 0)."""
        g = 0
        for c, lv in zip(self.coefs, self.levels):
            if c and lv[-1] >= 2:
                g = math.gcd(g, abs(c))
        return g


def addr_form(site: RefSite) -> AddrForm:
    """The site's address as an affine form of (k, inner indices).

    Raises :class:`SpecContractError` (PL403) for addr terms outside the
    chain — callers skip such refs; the contract pass reports them.
    """
    d = len(site.chain)
    coefs = [0] * d
    for depth, coef in site.ref.addr_terms:
        if not 0 <= depth < d:
            raise SpecContractError(
                f"ref {site.ref.name}: addr term depth {depth} exceeds "
                f"loop chain depth {d}",
                "PL403",
            )
        coefs[depth] += coef
    nest = site.chain[0]
    const = site.ref.addr_base + sum(
        c * l.start for c, l in zip(coefs, site.chain)
    )
    k_coef = coefs[0] * nest.step + sum(
        c * l.start_coef for c, l in zip(coefs[1:], site.chain[1:])
    )
    inner = tuple(c * l.step for c, l in zip(coefs[1:], site.chain[1:]))
    levels = []
    for l in site.chain[1:]:
        if l.bound_coef is None:
            levels.append(("const", l.trip))
        elif l.bound_level == 0:
            levels.append(("k", *l.bound_coef, l.trip))
        else:
            levels.append(("idx", l.bound_level, *l.bound_coef, l.trip))
    return AddrForm(const, k_coef, inner, tuple(levels), nest.trip)


def _interval(coef: int, trips: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(lo, hi) of ``coef * idx`` over ``idx in [0, trips)`` — zero where
    the level is empty (callers mask aliveness separately)."""
    span = np.maximum(trips - 1, 0) * coef
    return np.minimum(span, 0), np.maximum(span, 0)


def inner_profile(form: AddrForm) -> tuple[np.ndarray, np.ndarray,
                                           np.ndarray]:
    """Exact per-``k`` domain profile: ``(alive, lo, hi)`` arrays [trip0].

    ``alive[k]`` — the ref executes at parallel index ``k`` (every
    enclosing level has a nonempty range there); ``lo/hi[k]`` — exact
    min/max of ``sum(coefs[l]*idx_l)`` over the valid inner domain at
    ``k``.  Quad levels (trip depending on an inner index ``m``) are
    folded by enumerating ``idx_m`` — exact, and cheap because the quad
    contract allows a single referenced level per dependent loop.
    """
    ks = np.arange(max(form.trip0, 0), dtype=np.int64)
    referenced = sorted({lv[1] for lv in form.levels if lv[0] == "idx"})

    def trips_of(lv, mvals=None) -> np.ndarray:
        kind = lv[0]
        if kind == "const":
            return np.full_like(ks if mvals is None else mvals, lv[1])
        if kind == "k":
            _, a, b, trip = lv
            t = a + b * ks
        else:  # "idx" — only called with mvals set
            _, _m, a, b, trip = lv
            t = a + b * mvals
        return np.clip(t, 0, trip)

    alive = np.ones_like(ks, bool)
    lo = np.zeros_like(ks)
    hi = np.zeros_like(ks)
    # independent levels: exact interval per k.  Levels that other loops'
    # bounds reference are folded with their dependents below instead.
    for l, (c, lv) in enumerate(zip(form.coefs, form.levels), start=1):
        if lv[0] == "idx" or l in referenced:
            continue
        t = trips_of(lv)
        alive &= t >= 1
        l_, h_ = _interval(c, t)
        lo, hi = lo + l_, hi + h_
    # dependent groups: enumerate the referenced level's index.  The k
    # axis is processed in blocks so the [K_block, M] fold stays tens of
    # MB at any problem size (same discipline as deps._PAIR_BLOCK).
    for m in referenced:
        m_lv = form.levels[m - 1]
        if m_lv[0] == "idx":
            # chained inner bounds are out of contract; the contract pass
            # reports it — be conservative here by treating the chain at
            # its static maximum (never hides an alive domain)
            m_lv = ("const", m_lv[-1])
        tm = trips_of(m_lv)                       # [K] trips of level m
        mmax = int(tm.max(initial=0))
        if mmax < 1:
            alive &= False
            continue
        mvals = np.arange(mmax, dtype=np.int64)   # [M]
        big = np.int64(np.iinfo(np.int64).max // 4)
        kblock = max(1, (1 << 22) // mmax)
        for b0 in range(0, len(ks), kblock):
            sl = slice(b0, min(b0 + kblock, len(ks)))
            kb = sl.stop - sl.start
            valid = mvals[None, :] < tm[sl, None]      # [Kb, M]
            cell_lo = form.coefs[m - 1] * mvals[None, :] \
                + np.zeros((kb, 1), np.int64)
            cell_hi = cell_lo.copy()
            for c, lv in zip(form.coefs, form.levels):
                if lv[0] != "idx" or lv[1] != m:
                    continue
                t = np.broadcast_to(trips_of(lv, mvals)[None, :],
                                    (kb, mmax))
                valid = valid & (t >= 1)
                l_, h_ = _interval(c, t)
                cell_lo, cell_hi = cell_lo + l_, cell_hi + h_
            any_cell = valid.any(axis=1)
            alive[sl] &= any_cell
            lo[sl] += np.where(
                any_cell, np.where(valid, cell_lo, big).min(axis=1), 0)
            hi[sl] += np.where(
                any_cell, np.where(valid, cell_hi, -big).max(axis=1), 0)
    return alive, lo, hi


def addr_range(form: AddrForm) -> tuple[int, int] | None:
    """Exact (min, max) element address over the whole iteration domain,
    or None when the reference never executes."""
    alive, lo, hi = inner_profile(form)
    if not alive.any():
        return None
    ks = np.arange(form.trip0, dtype=np.int64)
    base = form.const + form.k_coef * ks
    return (int((base + lo)[alive].min()), int((base + hi)[alive].max()))
