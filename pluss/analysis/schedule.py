"""Placement refinement: race/reuse verdicts under the REAL chunk schedule.

The race pass (:mod:`pluss.analysis.deps`) asks whether two DISTINCT
parallel iterations can touch the same element — the schedule-blind
question, right for ``pluss lint`` (a spec should be safe under *any*
schedule).  But PLUSS's engine runs one concrete static schedule: chunk
``cid`` of the parallel loop is served by thread ``cid % T``
(:class:`pluss.sched.ChunkSchedule`), so two conflicting iterations whose
chunks land on the SAME thread are executed sequentially by one simulated
thread — no race, and exactly the pairs whose reuse the per-thread
last-access tables can observe.

This pass re-runs the dependence tests with the owner map folded into the
pair relation (exact: the parallel axis is enumerated, and the owner of
parallel index ``k`` is the closed form ``(k // chunk_size) % T`` — the
index-space twin of ``ChunkSchedule.static_tid``, valid for any
start/step because chunk ownership is index-based):

- ``cross_thread``: a feasible conflicting pair lands on two DIFFERENT
  threads — the placement-refined race verdict.  PL301/PL302 findings
  whose every feasible pair is same-thread downgrade to PL304 (INFO).
- ``observed``: a feasible DIRECTED pair lands on ONE thread — the
  refined "can the per-thread LAT observe this reuse" bit, sharpening the
  PL303 classification (PL305).  Cross-nest reuse pairs (the LAT persists
  across nests) refine the same way: both endpoints must be owned by the
  same thread under each nest's own schedule.

Soundness polarity is inherited from deps: refutations are proofs
(interval+gcd over-approximates the inner feasible set, and the k/owner
part is exact), confirmations are conservative.  The refined sets are
always subsets of the schedule-blind ones (the owner relation only
restricts), and the dynamic cross-check in ``tests/test_schedule.py``
pins: dynamically observed cross-parallel reuses ⊆ refined ⊆ unrefined,
for every registry model.
"""

from __future__ import annotations

import dataclasses

from pluss.analysis import deps
from pluss.analysis.diagnostics import Diagnostic, Severity, shown
from pluss.config import SamplerConfig
from pluss.spec import LoopNestSpec


def owner_of(cfg: SamplerConfig):
    """Index-space owner map of the static schedule: parallel index ``k``
    (0-based, any nest) is served by thread ``(k // CS) % T``."""
    CS, T = cfg.chunk_size, cfg.thread_num
    return lambda k: (k // CS) % T


@dataclasses.dataclass(frozen=True)
class SchedClass:
    """Schedule-refined classification of one reference."""

    site: object
    #: some same-array conflict pair puts this ref's iteration on a
    #: DIFFERENT thread than its partner (same nest — nests never race)
    cross_thread: bool
    #: the per-thread LAT can observe a parallel-crossing reuse at this
    #: ref under the schedule (same-thread directed pair, or a same-thread
    #: partner in an earlier nest)
    observed: bool
    #: outermost level carrying an OBSERVABLE self-reuse under the
    #: schedule (level 0 demands a same-thread pair; inner levels are
    #: same-thread by construction), or None
    carried_level: int | None


@dataclasses.dataclass
class SchedAnalysis:
    cfg: SamplerConfig
    base: deps.Analysis
    classes: dict[str, SchedClass]
    #: (nest, array, code) -> (cross_thread_pairs, private_pairs): the
    #: placement-refined split of each PL301/PL302 finding's pair list
    race_split: dict[tuple[int, str, str], tuple[list[str], list[str]]]


def _pair_cross_thread(p, q, own) -> bool:
    """Same-nest conflict on two different threads (symmetric)."""
    if p.form.trip0 != q.form.trip0 or p.form.trip0 <= 1:
        return False
    return deps._feasible(
        p, q, lambda k1, k2: (k1 != k2) & (own(k1) != own(k2)))


def _pair_same_thread_observed(p, q, own) -> bool:
    """Directed same-nest pair on ONE thread: q's earlier iteration and
    p's later one both run on the same simulated thread, so p's LAT holds
    q's touch."""
    if p.form.trip0 != q.form.trip0 or p.form.trip0 <= 1:
        return False
    return deps._feasible(
        p, q, lambda k1, k2: (k1 > k2) & (own(k1) == own(k2)))


def _cross_nest_same_thread(p, q, own) -> bool:
    """Cross-nest reuse pair owned by one thread with differing parallel
    VALUES (the dynamic observation records the previous touch's parallel
    value — see tests' InstrumentedOracle)."""
    l1, l2 = p.site.chain[0], q.site.chain[0]
    return deps._feasible(
        p, q,
        lambda k1, k2: ((l1.start + l1.step * k1)
                        != (l2.start + l2.step * k2))
        & (own(k1) == own(k2)))


def refine(spec: LoopNestSpec, cfg: SamplerConfig,
           analysis: deps.Analysis | None = None,
           skip_nests: frozenset[int] = frozenset()) -> SchedAnalysis:
    """Placement-refine a spec's dependence analysis under ``cfg``'s
    schedule.  Reuses the schedule-blind :class:`deps.Analysis` (profiles
    + memoized pair tests) — refined tests only run on pairs the blind
    test already confirmed (the owner relation is a sub-relation)."""
    ana = analysis if analysis is not None \
        else deps.analyze(spec, skip_nests)
    own = owner_of(cfg)
    memo: dict[tuple, bool] = {}

    def cross(p, q) -> bool:
        key = ("x", *sorted((ana._index[id(p)], ana._index[id(q)])))
        if key not in memo:
            memo[key] = ana.conflict(p, q) and _pair_cross_thread(p, q, own)
        return memo[key]

    classes: dict[str, SchedClass] = {}
    race_split: dict[tuple[int, str, str], tuple[list[str], list[str]]] = {}
    for (ni, array), group in sorted(ana.groups.items()):
        for i, p in enumerate(group):
            for q in group[i:]:
                if not (p.site.ref.is_write or q.site.ref.is_write):
                    continue
                if not ana.conflict(p, q):
                    continue
                code = "PL301" if (p.site.ref.is_write
                                   and q.site.ref.is_write) else "PL302"
                xt, priv = race_split.setdefault((ni, array, code),
                                                 ([], []))
                label = f"{p.site.ref.name}~{q.site.ref.name}"
                (xt if cross(p, q) else priv).append(label)

    for p in ana.profiles:
        group = ana.groups[(p.site.nest, p.site.ref.array)]
        cross_thread = any(cross(p, q) for q in group
                           if ana.conflict(p, q))
        observed = any(_pair_same_thread_observed(p, q, own)
                       for q in group if ana.conflict(p, q))
        if not observed:
            for q in ana.array_groups[p.site.ref.array]:
                if q.site.nest >= p.site.nest:
                    continue  # observation needs an EARLIER partner
                if ana.xconflict(p, q) and \
                        _cross_nest_same_thread(p, q, own):
                    observed = True
                    break
        levels = deps._self_carried_levels(p)
        if 0 in levels and not _pair_same_thread_observed(p, p, own):
            levels = [l for l in levels if l != 0]
        classes[p.site.path] = SchedClass(
            site=p.site,
            cross_thread=cross_thread,
            observed=observed,
            carried_level=min(levels) if levels else None,
        )
    return SchedAnalysis(cfg, ana, classes, race_split)


def check(spec: LoopNestSpec, cfg: SamplerConfig,
          analysis: deps.Analysis | None = None,
          skip_nests: frozenset[int] = frozenset()) -> list[Diagnostic]:
    """Placement-refined race diagnostics + sharpened reuse classification.

    Replaces the schedule-blind PL301/PL302 stream for ``pluss analyze``:
    a finding whose every feasible pair is same-thread downgrades to PL304
    (INFO — the schedule serializes it); findings with at least one
    genuinely cross-thread pair keep their code and severity, with the
    schedule named.  PL305 (INFO) carries the refined per-reference
    classification next to lint's schedule-blind PL303.
    """
    sa = refine(spec, cfg, analysis, skip_nests)
    T, CS = cfg.thread_num, cfg.chunk_size
    sched_s = f"T={T}, chunk={CS}"
    diags: list[Diagnostic] = []
    for (ni, array, code), (xt, priv) in sorted(sa.race_split.items()):
        kind = "write-write" if code == "PL301" else "read-write"
        if xt:
            diags.append(Diagnostic(
                code=code, severity=Severity.WARNING,
                message=f"{kind} conflict on '{array}' lands on two "
                        f"threads under the schedule ({sched_s}): "
                        f"{shown(xt)} — the parallel pragma asserts this "
                        "is intended",
                nest=ni, array=array,
            ))
        elif priv:
            diags.append(Diagnostic(
                code="PL304", severity=Severity.INFO,
                message=f"{kind} conflict on '{array}' is thread-private "
                        f"under the schedule ({sched_s}): every feasible "
                        f"pair lands on one thread ({shown(priv)}) — "
                        f"{code} downgraded",
                nest=ni, array=array,
            ))
    for path, sc in sorted(sa.classes.items()):
        if sc.site.ref.share_span is None:
            continue
        lvl = sc.carried_level
        diags.append(Diagnostic(
            code="PL305", severity=Severity.INFO,
            message=(f"under the schedule ({sched_s}): observable reuse "
                     f"carried at level {'none' if lvl is None else lvl}"
                     + (" (parallel)" if lvl == 0 else "")
                     + f"; LAT-observable cross-parallel reuse: "
                       f"{sc.observed}; conflicts cross threads: "
                       f"{sc.cross_thread}"),
            path=path, nest=sc.site.nest, ref=sc.site.ref.name,
            array=sc.site.ref.array,
        ))
    return diags
