"""Footprint & MRC bound prover: distinct-line counts, schedule-aware.

PLUSS predicts a miss-ratio curve without running the program; this pass
closes the loop by predicting, *statically*, the quantities that anchor
that curve — and in a form strong enough to be a machine-checkable
soundness oracle for both the analyzer and the sampler:

- **Per-(thread, array) footprint**: the exact number of distinct cache
  lines each simulated thread touches under the real chunk schedule.
  This IS the engine's cold-miss count (the per-thread last-access tables
  flush one cold entry per distinct line at the end of the run), so
  ``predicted_cold(spec, cfg) == res.noshare_dense[:, 0]`` exactly — for
  every supported nest shape, including the quadratic contract (the
  per-``k`` domain folding of :mod:`pluss.analysis.walk` is exact there).
- **Per-level footprints**: sound lower/upper bounds on the distinct
  lines one iteration of each loop level touches — the candidate
  working-set sizes where the MRC bends (Cascaval-style symbolic reuse
  analysis reads the same quantity off the dependence structure).
- **MRC bracket** (:func:`mrc_bracket`): closed-form bounds the sampled
  curve must satisfy.  The *floor* is exact: the curve's terminal plateau
  value equals ``cold/N`` (AET's survival function bottoms out at the
  cold fraction).  The plateau *location* is bracketed by ``[c_lo,
  c_hi]``: ``c_hi`` comes from the telescoping bound (per-line reuse
  times within one thread sum to at most that thread's stream length, so
  the AET cursor integral is at most ``cold + Σ_t FP_t·L_t / N`` at
  T=1; dilation-scaled for T>1), and ``c_lo`` from a *guaranteed* reuse
  time — a single-reference array invariant at some loop level with a
  line-injective finer map must produce a reuse of exactly that level's
  closed-form stride, which lower-bounds the histogram's largest key and
  hence where the curve can flatten.

Everything here is host-side numpy over the spec — no JAX, no stream
enumeration (address SETS are enumerated per reference, which is the
array size, not the access count).
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from pluss.analysis.schedule import owner_of
from pluss.analysis.walk import (addr_form, inner_profile, loop_sites,
                                 ref_sites)
from pluss.config import DEFAULT, SamplerConfig
from pluss.spec import (LoopNestSpec, SpecContractError, flatten_nest,
                        nest_has_bounds, nest_has_varying_start,
                        nest_iteration_size_affine, nest_iteration_sizes)

#: cells per enumeration block (the k axis is blocked to stay under it)
_ENUM_BUDGET = 1 << 22


@dataclasses.dataclass(frozen=True)
class LevelFootprint:
    """Distinct-line bounds of ONE iteration of one loop's body."""

    nest: int
    path: str
    depth: int
    lines_lo: int
    lines_hi: int


@dataclasses.dataclass
class Footprint:
    """Schedule-aware footprint report of one spec."""

    arrays: tuple[str, ...]
    per_array: np.ndarray            # [A] distinct lines, whole run
    per_thread: np.ndarray           # [T, A] distinct lines per thread
    accesses: int                    # total accesses (closed form)
    per_thread_accesses: np.ndarray  # [T]
    levels: tuple[LevelFootprint, ...]

    @property
    def total(self) -> int:
        return int(self.per_array.sum())

    @property
    def cold(self) -> np.ndarray:
        """Predicted per-thread cold-miss counts [T] — the engine's
        ``noshare_dense[:, 0]``."""
        return self.per_thread.sum(axis=1).astype(np.int64)


@dataclasses.dataclass(frozen=True)
class MrcBracket:
    """Static bounds the sampled (CRI + AET) MRC must satisfy."""

    floor: float          # exact terminal plateau value (cold fraction)
    c_lo: int             # plateau cannot start before this cache size
    c_hi: int             # plateau must be reached by this cache size
    guaranteed_reuse: int  # the closed-form reuse time behind c_lo (0=none)

    def refined(self, c_exact: int) -> "MrcBracket":
        """Collapse the heuristic bounds onto an exact plateau location
        proven by the symbolic reuse-interval pass (:mod:`pluss.analysis
        .ri`) — the floor and the guaranteed-reuse witness are already
        exact and carry over unchanged."""
        return MrcBracket(self.floor, c_exact, c_exact,
                          self.guaranteed_reuse)


def _grid_levels(form) -> list[int]:
    """Inner levels that must be enumerated: nonzero-coefficient levels
    plus any level an enumerated level's bound references."""
    dims = {l for l in range(1, len(form.coefs) + 1)
            if form.coefs[l - 1] != 0}
    for l in sorted(dims):
        lv = form.levels[l - 1]
        if lv[0] == "idx":
            dims.add(lv[1])
    return sorted(dims)


def _site_line_masks(site, cfg: SamplerConfig, count: int,
                     T: int, glob: np.ndarray, pt: np.ndarray) -> None:
    """OR the site's touched lines into the array's global [count] and
    per-thread [T, count] boolean masks (exact, schedule-aware)."""
    try:
        form = addr_form(site)
    except SpecContractError:
        return
    alive, _, _ = inner_profile(form)
    ks = np.nonzero(alive)[0].astype(np.int64)
    if not len(ks):
        return
    own = owner_of(cfg)
    dims = _grid_levels(form)
    trips = [form.levels[l - 1][-1] for l in dims]
    box = int(np.prod(trips, dtype=np.int64)) if dims else 1
    # Monotone fast path: when the ADDRESS is k-independent (k_coef ==
    # 0) and every k-bounded level's domain moves monotonically with k
    # (slopes all >= 0 or all <= 0 — idx-bounded levels are fine either
    # way: their constraint is per-m-value and m's own range is covered
    # by the same argument), the line set at a thread's EXTREME owned k
    # is a superset of every other owned k's.  One box evaluation per
    # thread replaces trip0 of them — without this, a k-invariant sweep
    # like gemm's B or a growing triangle like syrk_tri's A[j][k]
    # enumerates trip0 copies of a million-cell box.
    k_slopes = [form.levels[l - 1][2] for l in dims
                if form.levels[l - 1][0] == "k"]
    stamp_threads: dict[int, np.ndarray] | None = None
    if form.k_coef == 0 and (all(b >= 0 for b in k_slopes)
                             or all(b <= 0 for b in k_slopes)):
        pick = (lambda a: a.max()) if all(b >= 0 for b in k_slopes) \
            else (lambda a: a.min())
        reps: dict[int, list[int]] = {}
        tids = own(ks)
        for t in np.unique(tids):
            reps.setdefault(int(pick(ks[tids == t])), []).append(int(t))
        stamp_threads = {k_: np.asarray(ts) for k_, ts in reps.items()}
        ks = np.asarray(sorted(stamp_threads), np.int64)
    kblock = max(1, _ENUM_BUDGET // max(1, box))
    nd = 1 + len(dims)

    def axis(arr, ax):
        return np.asarray(arr, np.int64).reshape(
            (1,) * ax + (-1,) + (1,) * (nd - ax - 1))

    for b0 in range(0, len(ks), kblock):
        kb = ks[b0:b0 + kblock]
        kx = axis(kb, 0)
        addr = form.const + form.k_coef * kx
        valid = np.ones((len(kb),) + tuple(trips), bool)
        idxs = {}
        for ax, l in enumerate(dims, start=1):
            idxs[l] = axis(np.arange(trips[ax - 1]), ax)
            addr = addr + form.coefs[l - 1] * idxs[l]
        for ax, l in enumerate(dims, start=1):
            lv = form.levels[l - 1]
            if lv[0] == "k":
                _, a, bb, trip = lv
                valid = valid & (idxs[l] < np.clip(a + bb * kx, 0, trip))
            elif lv[0] == "idx":
                _, m, a, bb, trip = lv
                ref = idxs.get(m)
                if ref is None:   # out-of-contract chain: static maximum
                    continue
                valid = valid & (idxs[l] < np.clip(a + bb * ref, 0, trip))
        line = addr * cfg.ds // cfg.cls
        valid = valid & (line >= 0) & (line < count)
        if stamp_threads is not None:
            lineb = np.broadcast_to(line, valid.shape)
            for i, k_ in enumerate(kb.tolist()):
                row = lineb[i][valid[i]]
                glob[row] = True
                for t in stamp_threads[k_]:
                    pt[t, row] = True
            continue
        line = np.broadcast_to(line, valid.shape)[valid]
        tid = np.broadcast_to(own(kx), valid.shape)[valid]
        glob[line] = True
        pt[tid, line] = True


def per_thread_accesses(spec: LoopNestSpec,
                        cfg: SamplerConfig = DEFAULT,
                        skip_nests: frozenset[int] = frozenset()
                        ) -> np.ndarray:
    """[T] exact access counts per simulated thread (closed form — the
    engine's per-thread stream lengths).  ``skip_nests`` must match the
    line-mask accounting's: a contract-rejected nest contributes neither
    lines nor accesses, or every ``cold/N`` quantity downstream skews."""
    T = cfg.thread_num
    out = np.zeros(T, np.int64)
    own = owner_of(cfg)
    for ni, nest in enumerate(spec.nests):
        if nest.trip <= 0 or ni in skip_nests:
            continue
        ks = np.arange(nest.trip, dtype=np.int64)
        np.add.at(out, own(ks), nest_iteration_sizes(nest, ks))
    return out


def _distinct_addr_stats(coefs, trips) -> tuple[int, int] | None:
    """(count, span) of the exact distinct-value set of ``Σ c_l·x_l``
    over the box, or None past the enumeration budget.  Partial sums are
    deduplicated per axis — exact, and keeps the working set bounded by
    the value span rather than the box volume."""
    vals = np.zeros(1, np.int64)
    for c, t in zip(coefs, trips):
        if c == 0 or t <= 1:
            continue
        vals = (vals[:, None]
                + c * np.arange(t, dtype=np.int64)[None, :]).ravel()
        if vals.size > _ENUM_BUDGET:
            return None
        vals = np.unique(vals)
    return len(vals), int(vals.max() - vals.min())


def _level_bounds(spec: LoopNestSpec, cfg: SamplerConfig,
                  skip_nests: frozenset[int]) -> tuple[LevelFootprint, ...]:
    """Sound (lo, hi) distinct-line bounds of one body iteration of every
    loop: hi = Σ per-ref exact maxima (union ≤ sum), lo = max per-ref
    minima (union ≥ any member)."""
    E = max(1, cfg.cls // cfg.ds)
    sites = ref_sites(spec)
    out = []
    for loop, chain, ni, path in loop_sites(spec):
        if ni in skip_nests:
            continue
        dl = len(chain)   # this loop's depth in its nest
        lo = hi = 0
        ok = True
        for s in sites:
            if s.nest != ni or len(s.chain) <= dl or s.chain[dl] is not loop:
                continue
            try:
                form = addr_form(s)
            except SpecContractError:
                continue
            coefs, t_hi, t_lo = [], [], []
            for l in range(dl + 1, len(s.chain)):
                lv = form.levels[l - 1]
                trip = lv[-1]
                coefs.append(form.coefs[l - 1])
                t_hi.append(trip)
                if lv[0] == "const":
                    t_lo.append(trip)
                else:
                    a, b = lv[-3], lv[-2]
                    ref_hi = form.trip0 - 1 if lv[0] == "k" \
                        else form.levels[lv[1] - 1][-1] - 1
                    t_lo.append(int(np.clip(min(a, a + b * ref_hi),
                                            0, trip)))
            s_hi = _distinct_addr_stats(coefs, t_hi)
            s_lo = _distinct_addr_stats(coefs, t_lo)
            if s_hi is None or s_lo is None:
                ok = False
                break
            n_hi, span_hi = s_hi
            n_lo, _ = s_lo
            hi += min(n_hi, span_hi // E + 1)
            lo = max(lo, -(-n_lo // E))
        if ok and (lo or hi):
            out.append(LevelFootprint(ni, path, dl, lo, hi))
    return tuple(out)


def footprints(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
               skip_nests: frozenset[int] = frozenset()) -> Footprint:
    """Exact schedule-aware footprint of a spec (line space = the
    engine's: ``cfg.ds`` bytes/element, ``cfg.cls``-byte lines, arrays on
    line boundaries)."""
    T = cfg.thread_num
    names = tuple(a for a, _ in spec.arrays)
    counts = spec.line_counts(cfg)
    globs = {a: np.zeros(c, bool) for (a, _), c in zip(spec.arrays, counts)}
    pts = {a: np.zeros((T, c), bool)
           for (a, _), c in zip(spec.arrays, counts)}
    for site in ref_sites(spec):
        if site.nest in skip_nests or site.ref.array not in globs:
            continue
        arr = site.ref.array
        _site_line_masks(site, cfg, counts[spec.array_index(arr)],
                         T, globs[arr], pts[arr])
    per_array = np.array([int(globs[a].sum()) for a in names], np.int64)
    per_thread = np.stack([pts[a].sum(axis=1) for a in names],
                          axis=1).astype(np.int64)
    pta = per_thread_accesses(spec, cfg, skip_nests)
    return Footprint(
        arrays=names,
        per_array=per_array,
        per_thread=per_thread,
        accesses=int(pta.sum()),
        per_thread_accesses=pta,
        levels=_level_bounds(spec, cfg, skip_nests),
    )


def predicted_cold(spec: LoopNestSpec,
                   cfg: SamplerConfig = DEFAULT) -> np.ndarray:
    """[T] predicted cold-miss counts — must equal the engine's
    ``res.noshare_dense[:, 0]`` exactly (the soundness oracle)."""
    return footprints(spec, cfg).cold


def _line_injective(coefs, trips, E: int) -> bool:
    """True when the (line-space) map ``Σ c_l·x_l // E`` is injective over
    the box — each line touched at most once per traversal."""
    cs = []
    for c, t in zip(coefs, trips):
        if c == 0 or t <= 1:
            continue
        if E > 1:
            if c % E:
                return False
            c //= E
        cs.append((abs(c), t))
    cs.sort()
    span = 0
    for c, t in cs:
        if span >= c:
            return False
        span += c * (t - 1)
    return True


def guaranteed_reuse(spec: LoopNestSpec,
                     cfg: SamplerConfig = DEFAULT) -> int:
    """Largest reuse time PROVEN to occur: a single-reference array,
    invariant at some loop level with a line-injective finer map, touches
    each of its lines once per level iteration — consecutive touches are
    exactly the level's closed-form position stride apart.  0 when no
    reference qualifies (the bracket's lower bound then degenerates)."""
    E = max(1, cfg.cls // cfg.ds)
    T, CS = cfg.thread_num, cfg.chunk_size
    by_arr: dict[str, list] = {}
    for s in ref_sites(spec):
        by_arr.setdefault(s.ref.array, []).append(s)
    best = 0
    for arr, ss in by_arr.items():
        if len(ss) != 1:
            continue   # other refs could split the per-line gaps
        s = ss[0]
        nest = spec.nests[s.nest]
        # the proof uses constant strides and shift-invariant positions
        if nest_has_bounds(nest) or nest_has_varying_start(nest):
            continue
        try:
            form = addr_form(s)
        except SpecContractError:
            continue
        frs = [fr for fr in flatten_nest(nest) if fr.ref is s.ref]
        if not frs:
            continue
        fr = frs[0]
        d = len(s.chain)
        if any(t < 1 for t in fr.trips):
            continue

        def noshare_gap(gap: int) -> bool:
            # the guaranteed reuse must land in the NOSHARE histogram
            # (share events take the racetrack rebinning instead)
            span = s.ref.share_span
            return gap >= 1 and not (span is not None and 2 * gap > span)

        for l in range(1, d):
            if form.coefs[l - 1] != 0 or fr.trips[l] < 2:
                continue
            if not _line_injective(form.coefs[l:], fr.trips[l + 1:], E):
                continue
            gap = fr.pos_strides[l]
            if noshare_gap(gap):
                best = max(best, gap)
        n0, n1 = nest_iteration_size_affine(nest)
        if form.k_coef == 0 and nest.trip >= 2 and n1 == 0 \
                and (T == 1 or CS >= 2) \
                and _line_injective(form.coefs, fr.trips[1:], E):
            if noshare_gap(n0):
                best = max(best, n0)
    return best


def mrc_bracket(spec: LoopNestSpec, cfg: SamplerConfig = DEFAULT,
                fp: Footprint | None = None) -> MrcBracket:
    """Static bounds on the sampled MRC (see module docstring).

    The floor is exact for any T.  The plateau-location bounds are proven
    for T=1 (no CRI dilation); for T>1 ``c_hi`` scales the telescoping
    bound by the dilation factor T plus an NBD tail allowance, and
    ``c_lo`` halves the guaranteed key once more (dilated masses rebin at
    ≥ half the pre-dilation key) — both validated by the bracket tests.
    """
    if fp is None:
        fp = footprints(spec, cfg)
    N = max(fp.accesses, 1)
    cold = int(fp.cold.sum())
    floor = cold / N
    L = fp.per_thread_accesses
    fp_t = fp.per_thread.sum(axis=1)
    l_max = int(L.max(initial=0))
    base = ((l_max + 1) * cold + int((fp_t * L).sum())) / N
    T = cfg.thread_num
    if T == 1:
        c_hi = int(math.ceil(base)) + 1
    else:
        c_hi = int(math.ceil(T * base
                             + 64 * T * math.sqrt(max(l_max, 1)))) + 1
    t_g = guaranteed_reuse(spec, cfg)
    c_lo = 0
    if t_g >= 1 and cold:
        key = 1 << (t_g.bit_length() - 1)
        if T > 1:
            key //= 2
        c_lo = (key * cold) // N
    return MrcBracket(floor, int(c_lo), c_hi, t_g)
