"""Dependence direction/distance vectors per conflicting reference pair.

The PR-1 deps core (:mod:`pluss.analysis.deps`) answers ONE question —
"can two references touch the same element across the parallel
dimension?" — exactly in ``k`` and Banerjee-style in the inner indices.
Loop TRANSFORMATIONS (interchange, tiling, fusion — :mod:`pluss.
analysis.transform`) need the finer classical object: for every
conflicting pair, the set of *dependence direction vectors* over the
common loop levels, each with a concrete witness instance pair.

For a rectangular nest every address is affine in the per-level loop
INDICES (:func:`pluss.analysis.walk.addr_form`)::

    addr_1(x⃗) = addr_2(y⃗)   with   x_j, y_j in [0, trip_j)

A dependence edge ``src -> dst`` exists when some solution has the dst
instance executing after the src instance; its direction vector is the
per-common-level sign of ``iv_dst - iv_src`` and its distance vector is
one concrete such delta (THE distance when the dependence is uniform).
The solver enumerates the ``3^c`` sign patterns and searches each for a
witness with an exact depth-first walk over the per-level contribution
groups, pruned by interval + gcd reachability of the remaining suffix —
so every reported vector carries a CONCRETE instance pair (the PL952
requirement downstream), and an exhausted pattern is a proof of
infeasibility, not a guess.  The walk is budgeted
(``PLUSS_DEPVEC_BUDGET`` nodes per nest); blowing the budget is a typed
refusal (the PL953 cause chain), never a silent approximation.

Triangular/quad nests couple the per-level ranges (the trip depends on
an outer index), which breaks the independent-group search — those nests
refuse with the same typed cause the PR-12 predictor uses for its ladder
(PL601/PL701 class: the nest is outside the rectangular vector
contract).

The vectors are surfaced on the ``pluss analyze --json`` doc
(``doc["depvectors"]``) and appended as evidence to the PL301/302 race
findings (:func:`annotate_races`), and they are the sole input of the
transform legality prover.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import re

from pluss.analysis.walk import RefSite, addr_form, ref_sites
from pluss.spec import Loop, LoopNestSpec, Ref, SpecContractError
from pluss.utils.envknob import env_int

#: DFS node budget per nest (all pairs, all sign patterns).  The repo's
#: registry shapes prune to a few thousand nodes per pair at n=128; the
#: default leaves two orders of magnitude of headroom.
DEFAULT_BUDGET = 1 << 18


def vector_budget() -> int:
    return env_int("PLUSS_DEPVEC_BUDGET", DEFAULT_BUDGET, minimum=1)


class VectorBudgetExceeded(Exception):
    """The witness walk ran out of nodes — typed refusal, never a guess."""


@dataclasses.dataclass(frozen=True)
class DepEdge:
    """One dependence edge ``src -> dst`` with direction + witness.

    ``sigma`` is the direction vector over the COMMON loop levels
    (sink minus source, entries in {-1, 0, +1}, lexicographically
    nonnegative by construction); ``distance`` is the witness delta
    (the exact distance whenever the dependence is uniform).
    ``src_iv``/``dst_iv`` are the concrete witness instances — full
    per-level index vectors over each site's own chain.
    """

    src: RefSite
    dst: RefSite
    sigma: tuple[int, ...]
    distance: tuple[int, ...]
    src_iv: tuple[int, ...]
    dst_iv: tuple[int, ...]
    kind: str                      # "flow" | "anti" | "output"

    @property
    def carried(self) -> int | None:
        """The outermost level carrying the dependence (first nonzero
        direction entry); None = loop-independent."""
        for lvl, s in enumerate(self.sigma):
            if s:
                return lvl
        return None

    def label(self) -> str:
        vec = ",".join("<" if s > 0 else ">" if s < 0 else "="
                       for s in self.sigma)
        return f"{self.src.ref.name}->{self.dst.ref.name} ({vec})"

    def doc(self) -> dict:
        return {
            "src": self.src.ref.name, "dst": self.dst.ref.name,
            "src_path": self.src.path, "dst_path": self.dst.path,
            "array": self.src.ref.array, "kind": self.kind,
            "vector": list(self.sigma), "distance": list(self.distance),
            "src_iv": list(self.src_iv), "dst_iv": list(self.dst_iv),
            "carried": self.carried,
        }


@dataclasses.dataclass
class NestVectors:
    """One nest's dependence-vector record: the edges, or the typed
    refusal cause when the nest is outside the vector contract."""

    nest: int
    edges: list[DepEdge]
    refused: str | None = None     # cause text; None when computed


def _body_path(path: str) -> tuple[int, ...]:
    return tuple(int(m) for m in re.findall(r"body\[(\d+)\]", path))


def _rect_refusal(nest: Loop, ni: int) -> str | None:
    """The PL601/PL701-class cause text when the nest is outside the
    rectangular vector contract, else None."""

    def walk(item) -> str | None:
        if isinstance(item, Ref):
            return None
        if item.bound_coef is not None or item.start_coef:
            return ("triangular loop (bound_coef/start_coef) couples the "
                    "per-level index ranges — outside the rectangular "
                    "vector contract (PL601/PL701-class cause)")
        for b in item.body:
            cause = walk(b)
            if cause is not None:
                return cause
        return None

    return walk(nest)


def common_depth(p1: RefSite, p2: RefSite) -> int:
    """Number of loop levels the two same-nest sites share (>= 1: the
    nest root is always common)."""
    b1 = _body_path(p1.path)[:-1]   # body indices leading to each loop
    b2 = _body_path(p2.path)[:-1]
    c = 1
    while c <= min(len(b1), len(b2)) and b1[:c] == b2[:c]:
        c += 1
    return min(c, p1.depth, p2.depth)


# --- the per-pattern witness search ----------------------------------------


@dataclasses.dataclass
class _Group:
    """One independent contribution group of the pair equation: a set of
    candidate assignments each adding ``value`` to the left-hand side.
    ``tag`` maps an assignment back to the instance vectors."""

    tag: tuple                     # ("common", j) | ("t1", j) | ("t2", j)
    lo: int
    hi: int
    gcd: int
    candidates: object             # callable -> iterator of (value, assign)
    count: int                     # candidate-set size (search order)


def _d_range(sigma: int, trip: int) -> tuple[int, int] | None:
    """Allowed ``y - x`` range under one direction sign, or None when
    empty (a nonzero sign needs the level to be able to move)."""
    if sigma == 0:
        return (0, 0)
    if trip < 2:
        return None
    return (1, trip - 1) if sigma > 0 else (-(trip - 1), -1)


def _common_group(j: int, trip: int, c1: int, c2: int,
                  dlo: int, dhi: int) -> _Group:
    """Contribution ``c2*y - c1*x`` of one common level with
    ``y - x in [dlo, dhi]`` and both indices in ``[0, trip)``."""
    T = trip - 1
    if c1 == c2 == 0:
        # no address contribution: one canonical assignment suffices
        def cands():
            yield 0, (max(0, -dlo), max(0, -dlo) + dlo)

        return _Group(("common", j), 0, 0, 0, cands, 1)
    if c1 == c2:
        cc = c1

        def cands():
            for d in range(dlo, dhi + 1):
                yield cc * d, (max(0, -d), max(0, -d) + d)

        vals = (cc * dlo, cc * dhi)
        return _Group(("common", j), min(vals), max(vals),
                      abs(cc), cands, dhi - dlo + 1)
    if c1 == 0:
        ylo, yhi = max(0, dlo), min(T, T + dhi)

        def cands():
            for y in range(ylo, yhi + 1):
                yield c2 * y, (min(T, y - dlo), y)

        vals = (c2 * ylo, c2 * yhi)
        return _Group(("common", j), min(vals), max(vals),
                      abs(c2), cands, yhi - ylo + 1)
    if c2 == 0:
        xlo, xhi = max(0, -dhi), min(T, T - dlo)

        def cands():
            for x in range(xlo, xhi + 1):
                yield -c1 * x, (x, max(0, x + dlo))

        vals = (-c1 * xlo, -c1 * xhi)
        return _Group(("common", j), min(vals), max(vals),
                      abs(c1), cands, xhi - xlo + 1)

    # both nonzero, different: enumerate (x, d) jointly (budget-guarded)
    def cands():
        for x in range(0, T + 1):
            for d in range(max(dlo, -x), min(dhi, T - x) + 1):
                yield c2 * (x + d) - c1 * x, (x, x + d)

    corners = [c2 * (x + d) - c1 * x
               for x in (0, T) for d in (dlo, dhi)
               if 0 <= x + d <= T] or [0]
    return _Group(("common", j), min(corners), max(corners),
                  math.gcd(abs(c1), abs(c2)), cands,
                  (T + 1) * (dhi - dlo + 1))


def _tail_group(tag: tuple, coef: int, trip: int, sign: int) -> _Group:
    """Contribution ``sign * coef * idx`` of a non-shared level."""
    T = trip - 1
    cc = sign * coef
    if cc == 0:
        # no address contribution: one canonical assignment suffices
        def cands():
            yield 0, 0

        return _Group(tag, 0, 0, 0, cands, 1)

    def cands():
        for v in range(0, T + 1):
            yield cc * v, v

    vals = (0, cc * T)
    return _Group(tag, min(vals), max(vals), abs(cc), cands, T + 1)


def _search(groups: list[_Group], target: int,
            budget: list[int]) -> dict | None:
    """Exact DFS for one assignment summing to ``target``; interval +
    gcd pruning over the remaining suffix.  Returns {tag: assign} or
    None (a PROOF of infeasibility); raises on budget exhaustion."""
    # small candidate sets first: the large-stride groups stay in the
    # suffix, where their shared gcd prunes whole subtrees at once
    groups = sorted(groups, key=lambda g: g.count)
    n = len(groups)
    suf_lo = [0] * (n + 1)
    suf_hi = [0] * (n + 1)
    suf_g = [0] * (n + 1)
    for i in range(n - 1, -1, -1):
        suf_lo[i] = suf_lo[i + 1] + groups[i].lo
        suf_hi[i] = suf_hi[i + 1] + groups[i].hi
        suf_g[i] = math.gcd(suf_g[i + 1], groups[i].gcd)

    out: dict = {}

    def walk(i: int, rem: int) -> bool:
        budget[0] -= 1
        if budget[0] < 0:
            raise VectorBudgetExceeded()
        if not suf_lo[i] <= rem <= suf_hi[i]:
            return False
        if (rem % suf_g[i] if suf_g[i] else rem) != 0:
            return False
        if i == n:
            return True
        for value, assign in groups[i].candidates():
            if walk(i + 1, rem - value):
                out[groups[i].tag] = assign
                return True
        return False

    return out if walk(0, target) else None


def _pattern_witness(f1, f2, c: int, sigma: tuple[int, ...],
                     budget: list[int]) -> tuple | None:
    """A concrete ``(iv1, iv2)`` solving ``addr_1(iv1) == addr_2(iv2)``
    with the given per-common-level direction signs, or None."""
    c1 = (f1.k_coef,) + f1.coefs
    c2 = (f2.k_coef,) + f2.coefs
    t1 = (f1.trip0,) + tuple(lv[1] for lv in f1.levels)
    t2 = (f2.trip0,) + tuple(lv[1] for lv in f2.levels)
    if any(t < 1 for t in t1) or any(t < 1 for t in t2):
        return None
    groups: list[_Group] = []
    for j in range(c):
        rng = _d_range(sigma[j], t1[j])
        if rng is None:
            return None
        groups.append(_common_group(j, t1[j], c1[j], c2[j], *rng))
    for j in range(c, len(t1)):
        groups.append(_tail_group(("t1", j), c1[j], t1[j], -1))
    for j in range(c, len(t2)):
        groups.append(_tail_group(("t2", j), c2[j], t2[j], +1))
    sol = _search(groups, f1.const - f2.const, budget)
    if sol is None:
        return None
    iv1 = [0] * len(t1)
    iv2 = [0] * len(t2)
    for tag, assign in sol.items():
        kind, j = tag
        if kind == "common":
            iv1[j], iv2[j] = assign
        elif kind == "t1":
            iv1[j] = assign
        else:
            iv2[j] = assign
    return tuple(iv1), tuple(iv2)


def _edge_kind(src: RefSite, dst: RefSite) -> str:
    if src.ref.is_write and dst.ref.is_write:
        return "output"
    return "flow" if src.ref.is_write else "anti"


def _lex(sigma: tuple[int, ...]) -> int:
    """-1 / 0 / +1: lexicographic sign of a direction pattern."""
    for s in sigma:
        if s:
            return 1 if s > 0 else -1
    return 0


def pair_edges(p1: RefSite, p2: RefSite,
               budget: list[int]) -> list[DepEdge]:
    """All dependence edges between two same-nest sites (``p1`` may be
    ``p2``: self-dependences), each with direction vector + witness.
    Edges are normalized so the source is the program-earlier access and
    the vector is lexicographically nonnegative."""
    f1, f2 = addr_form(p1), addr_form(p2)
    same = p1.path == p2.path
    c = p1.depth if same else common_depth(p1, p2)
    edges: list[DepEdge] = []
    for sigma in itertools.product((-1, 0, 1), repeat=c):
        lex = _lex(sigma)
        if same and lex <= 0:
            continue  # self: delta==0 is the same instance; -sigma mirrors
        wit = _pattern_witness(f1, f2, c, sigma, budget)
        if wit is None:
            continue
        iv1, iv2 = wit
        delta = tuple(iv2[j] - iv1[j] for j in range(c))
        if lex > 0 or (lex == 0
                       and _body_path(p1.path) < _body_path(p2.path)):
            src, dst, siv, div = p1, p2, iv1, iv2
            vec, dist = sigma, delta
        else:
            src, dst, siv, div = p2, p1, iv2, iv1
            vec = tuple(-s for s in sigma)
            dist = tuple(-d for d in delta)
        edges.append(DepEdge(src, dst, vec, dist, siv, div,
                             _edge_kind(src, dst)))
    return edges


def fusion_backward_witness(p1: RefSite, p2: RefSite,
                            budget: list[int]) -> tuple | None:
    """Fusion-preventing backward dependence test for a cross-nest pair
    (``p1`` in the earlier nest, ``p2`` in the later): a conflict with
    the later nest's instance at a strictly SMALLER outer-loop index —
    after fusing the (compatible) outer loops that instance would run
    before its source.  Returns the witness ``(iv1, iv2)`` or None
    (a proof there is none)."""
    f1, f2 = addr_form(p1), addr_form(p2)
    return _pattern_witness(f1, f2, 1, (-1,), budget)


def nest_vectors(spec: LoopNestSpec, ni: int,
                 budget: int | None = None) -> NestVectors:
    """All write-involving dependence edges of one nest, or the typed
    refusal when the nest is outside the vector contract."""
    nest = spec.nests[ni]
    cause = _rect_refusal(nest, ni)
    if cause is not None:
        return NestVectors(ni, [], cause)
    sites = [s for s in ref_sites(spec) if s.nest == ni]
    remaining = [budget if budget is not None else vector_budget()]
    edges: list[DepEdge] = []
    try:
        by_array: dict[str, list[RefSite]] = {}
        for s in sites:
            by_array.setdefault(s.ref.array, []).append(s)
        for arr in sorted(by_array):
            group = by_array[arr]
            for i, p in enumerate(group):
                for q in group[i:]:
                    if not (p.ref.is_write or q.ref.is_write):
                        continue
                    try:
                        edges += pair_edges(p, q, remaining)
                    except SpecContractError:
                        continue  # the contract pass owns this report
    except VectorBudgetExceeded:
        return NestVectors(ni, [], (
            "dependence witness search exceeded the "
            f"PLUSS_DEPVEC_BUDGET node budget ({vector_budget()}) — "
            "typed refusal (PL702-class cause), never a guess"))
    edges.sort(key=lambda e: (e.src.path, e.dst.path, e.sigma))
    return NestVectors(ni, edges)


def spec_vectors(spec: LoopNestSpec,
                 budget: int | None = None) -> list[NestVectors]:
    return [nest_vectors(spec, ni, budget)
            for ni in range(len(spec.nests))]


# --- doc / rendering / race-evidence surfaces ------------------------------


def doc_of(vectors: list[NestVectors]) -> dict:
    """The ``doc["depvectors"]`` block of ``pluss analyze --json``."""
    nests = []
    for nv in vectors:
        if nv.refused is not None:
            nests.append({"nest": nv.nest, "refused": nv.refused})
        else:
            nests.append({"nest": nv.nest,
                          "edges": [e.doc() for e in nv.edges]})
    return {"nests": nests,
            "edges": sum(len(nv.edges) for nv in vectors)}


def render(doc: dict) -> list[str]:
    """The rendered table block of the analyze text report: one line per
    dependence edge (direction, distance, kind, carried level)."""
    lines = ["depvectors:"]
    for nd in doc["nests"]:
        if "refused" in nd:
            lines.append(f"  nest {nd['nest']}: refused — {nd['refused']}")
            continue
        for e in nd["edges"]:
            vec = "(" + ",".join(str(v) for v in e["vector"]) + ")"
            dist = "(" + ",".join(str(v) for v in e["distance"]) + ")"
            carried = ("loop-independent" if e["carried"] is None
                       else f"carried@{e['carried']}")
            lines.append(
                f"  nest {nd['nest']} {e['array']}: {e['src']}->"
                f"{e['dst']} {e['kind']} dir {vec} dist {dist} "
                f"({carried})")
        if not nd["edges"]:
            lines.append(f"  nest {nd['nest']}: no write-involving "
                         "dependences")
    return lines


def annotate_races(diags: list, vectors: list[NestVectors]) -> list:
    """Append the dependence-vector evidence to PL301/302 findings: the
    race verdict names the conflicting pairs; the vectors SAY WHY (the
    per-level directions that let two parallel iterations collide)."""
    import dataclasses as dc

    by_key: dict[tuple[int, str], list[str]] = {}
    for nv in vectors:
        for e in nv.edges:
            if e.carried == 0:   # only parallel-carried edges are races
                by_key.setdefault((nv.nest, e.src.ref.array), []).append(
                    e.label())
    out = []
    for d in diags:
        evid = by_key.get((d.nest, d.array)) if d.code in ("PL301",
                                                           "PL302") else None
        if evid:
            seen: list[str] = []
            for s in evid:
                if s not in seen:
                    seen.append(s)
            from pluss.analysis.diagnostics import shown

            d = dc.replace(d, message=d.message
                           + f" [dep vectors: {shown(seen)}]")
        out.append(d)
    return out
