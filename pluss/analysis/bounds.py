"""Bounds proofs: exact address ranges vs declared array sizes.

Every reference's element address is affine in the iteration indices
(:class:`pluss.analysis.walk.AddrForm`), so its exact min/max over the
iteration domain is computable without enumeration of the access stream:
interval arithmetic over a box is exact for affine forms, the parallel
axis is enumerated (triangular nests make per-``k`` inner domains vary),
and quad levels fold their one referenced index (the
``flatten_nest_quad`` closed-form contract guarantees there is only one).
The proof obligation is::

    0 <= min(addr)  and  max(addr) < declared array size

declared sizes being ``LoopNestSpec.arrays``.  A violation is PL101 —
always an ERROR: the engine would happily enumerate the out-of-range
addresses into neighboring arrays' cache-line ranges and corrupt the
reuse accounting silently.
"""

from __future__ import annotations

from pluss.analysis.diagnostics import Diagnostic, Severity
from pluss.analysis.walk import addr_form, addr_range, ref_sites
from pluss.spec import LoopNestSpec, SpecContractError


def check(spec: LoopNestSpec,
          skip_nests: frozenset[int] = frozenset()) -> list[Diagnostic]:
    diags: list[Diagnostic] = []
    sizes: dict[str, int] = {}
    for ai, (name, n) in enumerate(spec.arrays):
        if name in sizes:
            diags.append(Diagnostic(
                code="PL104", severity=Severity.ERROR,
                message=f"array '{name}' declared twice (arrays[{ai}]); "
                        "line-id bases would silently use the first",
                path=f"arrays[{ai}]", array=name,
            ))
            continue
        sizes[name] = n
        if n <= 0:
            diags.append(Diagnostic(
                code="PL105", severity=Severity.ERROR,
                message=f"array '{name}' declared with size {n}",
                path=f"arrays[{ai}]", array=name,
            ))
    used: set[str] = set()
    for site in ref_sites(spec):
        used.add(site.ref.array)
        if site.nest in skip_nests:
            continue
        if site.ref.array not in sizes:
            diags.append(Diagnostic(
                code="PL102", severity=Severity.ERROR,
                message=f"ref {site.ref.name} targets undeclared array "
                        f"'{site.ref.array}'",
                path=site.path, nest=site.nest, ref=site.ref.name,
                array=site.ref.array,
            ))
            continue
        if sizes[site.ref.array] <= 0:
            continue  # PL105 already reported; a range proof is moot
        try:
            rng = addr_range(addr_form(site))
        except SpecContractError:
            continue  # the contract pass owns malformed addr terms
        if rng is None:
            continue  # the reference never executes (empty domain)
        lo, hi = rng
        size = sizes[site.ref.array]
        if lo < 0 or hi >= size:
            diags.append(Diagnostic(
                code="PL101", severity=Severity.ERROR,
                message=f"ref {site.ref.name}: address range [{lo}, {hi}] "
                        f"escapes array '{site.ref.array}' of size {size}",
                path=site.path, nest=site.nest, ref=site.ref.name,
                array=site.ref.array,
            ))
    for name in sizes:
        if name not in used:
            diags.append(Diagnostic(
                code="PL103", severity=Severity.WARNING,
                message=f"array '{name}' is declared but never referenced "
                        "(a dead declaration — it only widens the global "
                        "line-id space)",
                array=name,
            ))
    return diags
