"""Contract lint: the flatten-time ValueErrors as addressed diagnostics.

``spec.flatten_nest`` / ``flatten_nest_quad`` enforce the declarative
contract with scattered raises; each now carries a stable code
(:class:`pluss.spec.SpecContractError`).  This pass walks the tree FIRST,
re-performing the cheap structural checks with precise paths, then runs
the real flatten as the authority — anything the walk missed (deep quad
algebra, degree-3 shapes) surfaces through the exception's code, with the
nest as the address.  Duplicate findings (same code, same nest) are
folded so a violation reports once, at the best path available.
"""

from __future__ import annotations

from pluss.analysis.diagnostics import Diagnostic, Severity
from pluss.analysis.walk import loop_sites, ref_sites
from pluss.spec import LoopNestSpec, SpecContractError, flatten_nest


def check(spec: LoopNestSpec) -> list[Diagnostic]:
    diags: list[Diagnostic] = []

    def add(code: str, message: str, path: str, ni: int, **kw) -> None:
        diags.append(Diagnostic(code=code, severity=Severity.ERROR,
                                message=message, path=path, nest=ni, **kw))

    for ni, nest in enumerate(spec.nests):
        if nest.bound_coef is not None or nest.start_coef:
            add("PL401",
                "the parallel (outermost) loop must be rectangular; "
                "bound_coef/start_coef are for inner loops",
                f"nests[{ni}]", ni)

    for loop, chain, ni, path in loop_sites(spec):
        level = len(chain)
        if level == 0 or loop.bound_coef is None:
            continue
        if not 0 <= loop.bound_level < level:
            add("PL404",
                f"bound_level {loop.bound_level} must name an enclosing "
                f"loop (this loop sits at depth {level})", path, ni)
            continue
        a, b = loop.bound_coef
        ref_trip = spec.nests[ni].trip if loop.bound_level == 0 \
            else chain[loop.bound_level].trip
        ends = (a, a + b * (ref_trip - 1))
        if min(ends) < 0 or max(ends) > loop.trip:
            add("PL402",
                f"bound {loop.bound_coef} leaves [0, trip={loop.trip}] "
                f"over referenced indices [0, {ref_trip - 1}]", path, ni)
        if loop.bound_level > 0:
            ref = chain[loop.bound_level]
            if ref.start or ref.step != 1 or ref.start_coef:
                add("PL405",
                    "the bound-referenced level must have start=0, "
                    "step=1, start_coef=0 (index == value)", path, ni)

    seen_names: dict[tuple[int, str], str] = {}
    for site in ref_sites(spec):
        d = len(site.chain)
        for depth, _coef in site.ref.addr_terms:
            if not 0 <= depth < d:
                add("PL403",
                    f"ref {site.ref.name}: addr term depth {depth} "
                    f"exceeds loop chain depth {d}", site.path, site.nest,
                    ref=site.ref.name, array=site.ref.array)
                break
        key = (site.nest, site.ref.name)
        if key in seen_names:
            diags.append(Diagnostic(
                code="PL406", severity=Severity.WARNING,
                message=f"ref name '{site.ref.name}' appears twice in "
                        f"nest {site.nest} (also at {seen_names[key]}) — "
                        "diagnostics and per-ref tooling key on the name",
                path=site.path, nest=site.nest, ref=site.ref.name,
            ))
        seen_names.setdefault(key, site.path)

    # the flatten itself is the authority: whatever the walk above missed
    # (quad position algebra, degree-3 shapes) lands here with its code
    found = {(d.code, d.nest) for d in diags}
    for ni, nest in enumerate(spec.nests):
        try:
            flatten_nest(nest)
        except SpecContractError as e:
            if (e.code, ni) not in found:
                add(e.code, str(e), f"nests[{ni}]", ni)
        except ValueError as e:
            if ("PL407", ni) not in found:
                add("PL407", f"flatten rejected the nest: {e}",
                    f"nests[{ni}]", ni)
    return diags
