"""Cross-nest co-tenancy interference: static composed-MRC prediction.

The CRI model (PAPER.md §0, :mod:`pluss.cri`) dilates a THREAD-LOCAL
reuse of length n by the other threads' interleaved accesses with
k ~ NegativeBinomial(r=n, p=1/T): every thread owns a 1/T share of the
merged access stream.  Nothing in that derivation needs the co-runners
to be the SAME program — it needs each runner's share of the stream.
This module generalizes the dilation from T identical threads of one
nest to K co-scheduled workloads with heterogeneous access rates — the
multi-tenant cache scenario every coalesced `pluss serve` dispatch
creates — and reads each workload's DEGRADED miss-ratio curve off the
merged stream's AET clock:

1. **Composition** (:func:`compose`): thread i of workload w owns
   ``p_w = (rate_w / T_w) / sum_k rate_k`` of the merged stream
   (``rate_w`` derived statically from the PR-12 symbolic prediction's
   access counts, overridable).  Each workload's thread-local
   histograms are dilated by :func:`pluss.cri.nbd_dilate_p` at ``p_w``
   — the racetrack share split keeps its WORKLOAD-LOCAL racer count
   (disjoint address spaces: co-tenants dilate each other's reuses but
   never consume each other's shared values).  K=1 reduces to
   ``cri.distribute`` exactly (p = 1/T).
2. **Read-off**: the merged histogram's AET eviction times t*(c)
   (:func:`pluss.mrc.aet_times`) are the shared cache's clock; workload
   w's degraded miss ratio at size c is ITS survival at the MERGED
   stream's t*(c) (:func:`pluss.mrc.survival_at`).
3. **Verdicts**: PL801 (severe: predicted miss-ratio inflation above
   ``PLUSS_INTERFERENCE_THRESHOLD`` at the declared cache size), PL802
   (benign co-tenancy, inflation proven below threshold), PL803 (typed
   refusal — a workload outside the composition contract is never
   silently approximated).
4. **Oracle** (:func:`oracle_mrcs`): an interleaved schedule-simulation
   twin in the falseshare.py tradition — per-thread line-id streams
   walked straight off the spec, a deterministic proportional-fair
   virtual-time interleave weighted by each thread's stream share, and
   EXACT LRU stack distances (Bennett–Kruskal) on the merged stream.
   `pluss cotenancy --check` pins the composed prediction against it at
   small n.

Like every pass in :mod:`pluss.analysis`, this is pure host math on
tiny histograms — zero device dispatches.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from pluss import cri
from pluss import mrc as mrc_mod
from pluss.analysis import ri as ri_mod
from pluss.analysis.diagnostics import Diagnostic, Severity
from pluss.config import DEFAULT, NBD_CUTOFF_COEF, SamplerConfig
from pluss.sched import ChunkSchedule
from pluss.spec import LoopNestSpec, Ref
from pluss.utils.envknob import env_float

#: PL801/PL802 decision bar: absolute miss-ratio inflation at the
#: declared cache size (PLUSS_INTERFERENCE_THRESHOLD overrides)
DEFAULT_THRESHOLD = 0.05

#: model-vs-oracle acceptance at small n.  The NBD interleave model is a
#: probabilistic approximation of a deterministic schedule AND the
#: thread-local histograms are log2-binned, so at n=16 (40-90-entry
#: curves) even the SOLO model sits 0.2-0.7 max-abs from an exact
#: simulation at the coarse small-c bins.  The meaningful pins, tuned
#: against the 7-pair x T in {1,2,4} registry sweep: the mean absolute
#: error over the curve, the agreement at the curve's large-cache end
#: (where every workload must reach its compulsory floor), and — the
#: composition-specific bound — the composed curve's max error may not
#: exceed the solo model's own oracle error by more than a margin: the
#: cross-nest composition must not ADD model error.
ORACLE_MAE_EPS = 0.25
ORACLE_EDGE_EPS = 0.10
ORACLE_MAX_MARGIN = 0.35


def interference_threshold() -> float:
    return env_float("PLUSS_INTERFERENCE_THRESHOLD", DEFAULT_THRESHOLD,
                     minimum=0.0)


@dataclasses.dataclass(frozen=True)
class WorkloadInput:
    """One co-scheduled workload: its thread-local histograms (the
    exact ``SamplerResult.noshare_list()``/``share_list()`` shapes, from
    either a static prediction or a sampled run), schedule config, and
    access rate (merged-stream weight; accesses per unit time)."""

    name: str
    noshare: list[dict]
    share: list[dict]
    cfg: SamplerConfig
    rate: float
    accesses: int
    spec: LoopNestSpec | None = None  # needed only by the oracle


@dataclasses.dataclass(frozen=True)
class WorkloadVerdict:
    name: str
    p: float                 # per-thread merged-stream ownership share
    solo_mr: float           # miss ratio alone at the declared cache
    degraded_mr: float       # miss ratio co-scheduled, same cache
    inflation: float         # degraded - solo (absolute)
    code: str                # PL801 | PL802


@dataclasses.dataclass
class CotenancyReport:
    workloads: tuple[str, ...]
    cache_kb: int
    threshold: float
    verdicts: list[WorkloadVerdict]
    solo_curves: list[np.ndarray]
    degraded_curves: list[np.ndarray]
    composed: list[dict]     # per-workload merged-clock histograms
    merged: dict             # their key-wise sum: the shared stream
    diagnostics: list[Diagnostic]

    @property
    def refused(self) -> bool:
        return any(d.code == "PL803" for d in self.diagnostics)

    def doc(self) -> dict:
        return {
            "workloads": list(self.workloads),
            "cache_kb": self.cache_kb,
            "threshold": self.threshold,
            "verdicts": [dataclasses.asdict(v) for v in self.verdicts],
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "degraded_mrc": [
                [[int(c), float(m)] for c, m in mrc_mod.dedup_lines(curve)]
                for curve in self.degraded_curves
            ],
        }


def distribute_p(noshare: list[dict], share: list[dict],
                 p: float) -> dict:
    """Heterogeneous-rate ``cri.distribute``: dilate one workload's
    thread-local histograms into merged-stream time, with every foreign
    access (same-workload sibling threads AND co-tenant workloads)
    charged through the single ownership share ``p``.  Same sorted
    deterministic accumulation order as the solo pass."""
    rihist: dict = {}
    for k, v in sorted(cri.merge(noshare).items()):
        if k < 0:
            cri.histogram_update(rihist, k, v)
            continue
        if p < 1.0:
            keys, pmf = cri.nbd_dilate_p(p, k)
            for kk, vv in zip(keys, pmf):
                cri.histogram_update(rihist, int(kk), v * float(vv))
        else:
            cri.histogram_update(rihist, k, v)
    merged: dict[int, dict] = {}
    for h in share:
        for n_key, hist in h.items():
            m = merged.setdefault(n_key, {})
            for r, c in hist.items():
                m[r] = m.get(r, 0.0) + c
    cut = NBD_CUTOFF_COEF * (1.0 - p)
    for n_key in sorted(merged):
        hist = merged[n_key]
        n = float(n_key)
        if p >= 1.0:
            for r in sorted(hist):
                cri.histogram_update(rihist, r, hist[r])
            continue
        items = sorted(hist.items())
        rs = np.fromiter((k for k, _ in items), np.int64, len(items))
        cs = np.fromiter((v for _, v in items), np.float64, len(items))
        big = rs >= cut
        ri_parts = [np.rint(rs[big] / p).astype(np.int64)]
        w_parts = [cs[big]]
        for r, c in zip(rs[~big].tolist(), cs[~big].tolist()):
            keys, pmf = cri.nbd_dilate_p(p, r)
            ri_parts.append(keys)
            w_parts.append(c * pmf)
        rivals = np.concatenate(ri_parts)
        w = np.concatenate(w_parts)
        if rivals.size:
            cri._racetrack_emit(rivals, w, n, rihist)
    return rihist


def from_models(names: list[str], cfg: SamplerConfig = DEFAULT,
                n: int = 16,
                rates: list[float] | None = None
                ) -> tuple[list[WorkloadInput], list[Diagnostic]]:
    """Build workload inputs from registry models via the PR-12 static
    predictor — zero device dispatches.  A workload the predictor
    refuses (PL701/PL702) becomes a PL803 refusal here: composing an
    approximated histogram would be a silent lie about a pair."""
    from pluss.models import REGISTRY

    inputs: list[WorkloadInput] = []
    diags: list[Diagnostic] = []
    for i, name in enumerate(names):
        spec = REGISTRY[name](n)
        pred = ri_mod.derive(spec, cfg)
        if not pred.derivable:
            why = ", ".join(sorted({d.code for d in pred.diagnostics
                                    if d.code in ("PL701", "PL702")}))
            diags.append(Diagnostic(
                "PL803", Severity.WARNING,
                f"workload {name!r} is outside the composition contract: "
                f"not statically derivable ({why or 'no histogram'})",
                model=name))
            continue
        rate = float(rates[i]) if rates is not None else float(pred.accesses)
        if rate <= 0.0:
            diags.append(Diagnostic(
                "PL803", Severity.WARNING,
                f"workload {name!r} has a non-positive access rate "
                f"({rate:g}); the ownership share is undefined",
                model=name))
            continue
        inputs.append(WorkloadInput(name, pred.noshare, pred.share, cfg,
                                    rate, int(pred.accesses), spec=spec))
    return inputs, diags


def compose(inputs: list[WorkloadInput],
            cfg: SamplerConfig = DEFAULT,
            threshold: float | None = None) -> CotenancyReport:
    """The cross-nest CRI composition pass over K >= 2 workloads."""
    if len(inputs) < 2:
        raise ValueError(f"co-tenancy needs >= 2 workloads, got "
                         f"{len(inputs)}")
    threshold = interference_threshold() if threshold is None \
        else float(threshold)
    names = tuple(w.name for w in inputs)
    total_rate = sum(w.rate for w in inputs)
    diags: list[Diagnostic] = []
    ps = [(w.rate / w.cfg.thread_num) / total_rate for w in inputs]
    composed = [distribute_p(w.noshare, w.share, p)
                for w, p in zip(inputs, ps)]
    merged = cri.merge(composed)
    times = mrc_mod.aet_times(merged, cfg)
    solo_curves, degraded_curves, verdicts = [], [], []
    for w, p, h in zip(inputs, ps, composed):
        solo = mrc_mod.aet_mrc(
            cri.distribute(w.noshare, w.share, w.cfg.thread_num), cfg)
        degraded = mrc_mod.survival_at(h, times)
        solo_curves.append(solo)
        degraded_curves.append(degraded)
        c = min(cfg.aet_cache_entries, len(solo) - 1, len(degraded) - 1)
        solo_mr = float(solo[c])
        deg_mr = float(degraded[min(c, len(degraded) - 1)])
        inflation = deg_mr - solo_mr
        code = "PL801" if inflation > threshold else "PL802"
        verdicts.append(WorkloadVerdict(w.name, p, solo_mr, deg_mr,
                                        inflation, code))
        if code == "PL801":
            diags.append(Diagnostic(
                "PL801", Severity.WARNING,
                f"severe interference on {w.name!r} co-scheduled with "
                f"{', '.join(x for x in names if x != w.name)}: miss "
                f"ratio {solo_mr:.4g} -> {deg_mr:.4g} "
                f"(+{inflation:.4g} > {threshold:g}) at "
                f"{cfg.cache_kb} KB", model=w.name))
        else:
            diags.append(Diagnostic(
                "PL802", Severity.INFO,
                f"benign co-tenancy for {w.name!r}: miss-ratio inflation "
                f"{inflation:.4g} <= {threshold:g} at {cfg.cache_kb} KB",
                model=w.name))
    return CotenancyReport(names, cfg.cache_kb, threshold, verdicts,
                           solo_curves, degraded_curves, composed, merged,
                           diags)


def analyze_models(names: list[str], cfg: SamplerConfig = DEFAULT,
                   n: int = 16,
                   rates: list[float] | None = None
                   ) -> CotenancyReport:
    """`pluss cotenancy`'s whole pipeline: derive -> compose -> verdict.
    A refused workload yields a report whose diagnostics carry PL803 and
    whose curves cover only the composable survivors (still >= 2, else
    the report is pure refusal)."""
    inputs, refusals = from_models(names, cfg, n, rates)
    if len(inputs) < 2:
        return CotenancyReport(tuple(names), cfg.cache_kb,
                               interference_threshold(), [], [], [], [],
                               {}, refusals)
    rep = compose(inputs, cfg)
    rep.diagnostics = refusals + rep.diagnostics
    return rep


# ---------------------------------------------------------------------------
# interleaved schedule-simulation oracle (the numpy twin `--check` trusts)


def thread_line_streams(spec: LoopNestSpec,
                        cfg: SamplerConfig) -> list[np.ndarray]:
    """Per-thread cache-line access streams, walked straight off the
    spec with the engine's chunk schedule — the same walk the
    tests/oracle.py sampler performs, recording the touched (array,
    line) sequence instead of reuse histograms."""
    line_ids: dict[tuple[str, int], int] = {}
    streams: list[list[int]] = [[] for _ in range(cfg.thread_num)]

    def lid(array: str, line: int) -> int:
        key = (array, line)
        v = line_ids.get(key)
        if v is None:
            v = line_ids[key] = len(line_ids)
        return v

    def walk(tid: int, item, ivs: list[int], pnest) -> None:
        if isinstance(item, Ref):
            addr = item.addr_base + sum(c * ivs[d]
                                        for d, c in item.addr_terms)
            streams[tid].append(lid(item.array,
                                    addr * cfg.ds // cfg.cls))
            return
        trip, start = item.trip, item.start
        if item.bound_coef is not None or item.start_coef:
            pstart, pstep = pnest
            k0 = (ivs[0] - pstart) // pstep
            if item.bound_coef is not None:
                a, b = item.bound_coef
                ref_idx = k0 if item.bound_level == 0 \
                    else ivs[item.bound_level]
                trip = a + b * ref_idx
            start = start + item.start_coef * k0
        for i in range(trip):
            v = start + i * item.step
            for b in item.body:
                walk(tid, b, ivs + [v], pnest)

    for nest in spec.nests:
        pnest = (nest.start, nest.step)
        sched = ChunkSchedule(cfg.chunk_size, nest.trip, nest.start,
                              nest.step, cfg.thread_num)
        for tid in range(cfg.thread_num):
            for cid in sched.chunks_of_thread(tid):
                b0, e0 = sched.chunk_index_range(cid)
                for i in range(b0, e0):
                    v = sched.start + i * sched.step
                    for b in nest.body:
                        walk(tid, b, [v], pnest)
    return [np.asarray(s, np.int64) for s in streams]


def _interleave(streams: list[tuple[int, float, np.ndarray]]
                ) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic proportional-fair merge: the j-th access of a
    stream with weight u lands at virtual time (j+1)/u; ties break by
    stream order.  Returns (merged line ids, merged workload ids)."""
    times, lines, wids, seqs, sids = [], [], [], [], []
    for si, (wid, weight, s) in enumerate(streams):
        if not s.size:
            continue
        times.append((np.arange(1, s.size + 1, dtype=np.float64)) / weight)
        lines.append(s)
        wids.append(np.full(s.size, wid, np.int64))
        seqs.append(np.arange(s.size, dtype=np.int64))
        sids.append(np.full(s.size, si, np.int64))
    t = np.concatenate(times)
    order = np.lexsort((np.concatenate(seqs), np.concatenate(sids), t))
    return np.concatenate(lines)[order], np.concatenate(wids)[order]


def _stack_distances(lines: np.ndarray) -> np.ndarray:
    """Exact LRU stack depths (Bennett–Kruskal, Fenwick tree): out[i] is
    the stack depth of access i's line (1 = most recent), or 0 for a
    cold miss.  A hit in a cache of size c is depth <= c."""
    n = lines.size
    bit = np.zeros(n + 1, np.int64)

    def add(i: int, v: int) -> None:
        i += 1
        while i <= n:
            bit[i] += v
            i += i & (-i)

    def prefix(i: int) -> int:       # sum of [0, i]
        i += 1
        s = 0
        while i > 0:
            s += bit[i]
            i -= i & (-i)
        return s

    last: dict[int, int] = {}
    out = np.zeros(n, np.int64)
    for i in range(n):
        ln = int(lines[i])
        j = last.get(ln)
        if j is not None:
            # distinct lines with last occurrence in (j, i-1], + itself
            out[i] = prefix(i - 1) - prefix(j) + 1
            add(j, -1)
        last[ln] = i
        add(i, 1)
    return out


def oracle_mrcs(inputs: list[WorkloadInput],
                cfg: SamplerConfig = DEFAULT) -> list[np.ndarray]:
    """Per-workload exact-LRU MRCs of the interleaved merged stream.
    Workload line ids are namespaced (disjoint address spaces, the same
    contract the composition assumes); curve index is cache size in
    lines, curve length capped like :func:`pluss.mrc.aet_mrc`."""
    streams: list[tuple[int, float, np.ndarray]] = []
    offset = 0
    for wi, w in enumerate(inputs):
        if w.spec is None:
            raise ValueError(f"oracle needs specs; workload {w.name!r} "
                             "has none")
        per_tid = thread_line_streams(w.spec, w.cfg)
        space = max((int(s.max()) + 1 for s in per_tid if s.size),
                    default=0)
        for s in per_tid:
            streams.append((wi, w.rate / w.cfg.thread_num, s + offset))
        offset += space
    lines, wids = _interleave(streams)
    depth = _stack_distances(lines)
    out: list[np.ndarray] = []
    for wi, w in enumerate(inputs):
        mine = depth[wids == wi]
        total = float(mine.size)
        cold = float((mine == 0).sum())
        hot = mine[mine > 0]
        c_max = min(int(hot.max(initial=0)), cfg.aet_cache_entries)
        hist = np.bincount(hot, minlength=c_max + 1)[:c_max + 1]
        # miss at size c <=> depth > c (cold misses everywhere)
        deeper = float(hot.size) - np.cumsum(hist, dtype=np.float64)
        curve = (cold + deeper) / (total or 1.0)
        out.append(curve)
    return out


def check_against_oracle(report: CotenancyReport,
                         inputs: list[WorkloadInput],
                         cfg: SamplerConfig = DEFAULT
                         ) -> tuple[bool, dict]:
    """``pluss cotenancy --check``: composed per-workload curves against
    the schedule-simulation oracle, three pins per workload (see the
    ORACLE_* constants): curve MAE, large-cache-end agreement, and the
    no-added-error bound vs the workload's SOLO model-vs-oracle gap."""
    oracle = oracle_mrcs(inputs, cfg)
    per: list[dict] = []
    ok = True
    max_abs_overall = 0.0
    for w, pred, orc in zip(inputs, report.degraded_curves, oracle):
        pred = np.asarray(pred, float)
        m = min(len(pred), len(orc))
        diff = np.abs(pred[:m] - orc[:m]) if m else np.zeros(1)
        err, mae = float(diff.max()), float(diff.mean())
        edge = float(diff[-1])
        solo = mrc_mod.aet_mrc(
            cri.distribute(w.noshare, w.share, w.cfg.thread_num), cfg)
        solo_orc = oracle_mrcs([w], cfg)[0]
        ms = min(len(solo), len(solo_orc))
        base = float(np.max(np.abs(solo[:ms] - solo_orc[:ms]))) if ms \
            else 0.0
        w_ok = (mae <= ORACLE_MAE_EPS and edge <= ORACLE_EDGE_EPS
                and err <= base + ORACLE_MAX_MARGIN)
        per.append({"workload": w.name, "max_abs_err": err, "mae": mae,
                    "edge_err": edge, "solo_max_abs_err": base,
                    "ok": w_ok})
        ok = ok and w_ok
        max_abs_overall = max(max_abs_overall, err)
    return ok, {"ok": ok, "max_abs_err": max_abs_overall,
                "mae_eps": ORACLE_MAE_EPS, "edge_eps": ORACLE_EDGE_EPS,
                "max_margin": ORACLE_MAX_MARGIN, "per_workload": per}
