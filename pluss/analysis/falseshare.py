"""Line-granular false-sharing detection (PL5xx).

The element-granular race pass asks "same ELEMENT, different threads".
The dominant parallel-cache pathology PLUSS's share/no-share split exists
to model is one level coarser: two threads touching the same CACHE LINE
at *different* elements — no data race, but the line ping-pongs between
caches exactly as if there were one.  This pass lowers each reference's
affine address map to line granularity and proves or flags that pattern
per same-nest, same-array reference pair (≥ one write, like the race
pass; nests never run concurrently, so cross-nest pairs cannot falsely
share).

Machine model: element width ``w`` per array (``Ref.dtype_bytes``
override, else ``SamplerConfig.ds``) and line size ``cfg.cls`` give
``E = cls // w`` elements per line.  Two accesses falsely share iff::

    addr1 - addr2 = d,   0 < |d| < E,   floor(addr1/E) == floor(addr2/E)

(arrays start on line boundaries — ``LoopNestSpec.line_bases`` — so the
floor is taken in array-local element space).  The test enumerates the
sub-line offsets ``d`` and decides each with the same exact-in-k,
Banerjee-in-the-inner-indices machinery as the race pass
(:func:`pluss.analysis.deps._feasible` with ``delta=d``), restricted to
pairs the schedule places on two DIFFERENT threads
(:func:`pluss.analysis.schedule.owner_of`).  The same-line alignment
condition is checked through the refs' achievable address residues mod
``E`` (an exact residue-set fold over the affine form — conservative
only in that it is decoupled from the offset feasibility), so a padded
layout whose rows are line-aligned REFUTES false sharing outright.

Polarity matches the race pass: refutation is a proof; confirmation is
conservative.  ``tests/test_falseshare.py`` validates the verdicts
against a line-granular simulation of the engine's schedule on several
model families, adversarial intra-line stride-1 specs, and padded vs
unpadded struct layouts.
"""

from __future__ import annotations

import math

import numpy as np

from pluss.analysis import deps
from pluss.analysis.diagnostics import Diagnostic, Severity, shown
from pluss.analysis.schedule import owner_of
from pluss.analysis.walk import ref_sites
from pluss.config import SamplerConfig
from pluss.spec import LoopNestSpec


def array_width(spec: LoopNestSpec, array: str, cfg: SamplerConfig) -> int:
    """Element width in bytes of ``array``: the refs' consistent
    ``dtype_bytes`` override, else the machine default ``cfg.ds``.
    Disagreeing overrides fall back to ``cfg.ds`` (the engine's rule)."""
    widths = {s.ref.dtype_bytes for s in ref_sites(spec)
              if s.ref.array == array and s.ref.dtype_bytes is not None}
    if len(widths) == 1:
        return widths.pop()
    return cfg.ds


_BIG = np.int64(np.iinfo(np.int64).max // 4)


def _split_profile(form, E: int):
    """Residue-coupled value profile of the INNER contribution mod ``E``.

    Splits the inner levels into line-SILENT ones (coefficient divisible
    by ``E`` — they move whole lines, never the within-line offset) and
    INTRA-line ones, and folds the intra levels into per-residue value
    intervals: ``(m_lo[ρ], m_hi[ρ], m_valid[ρ])`` bounds the intra sum
    over exactly the index combinations whose residue is ``ρ``.  This is
    what couples the same-line alignment condition to the offset
    feasibility — the decoupled test confirms false sharing on perfectly
    line-aligned rows (gemm's C), which a line-granular simulation
    refutes.  Bounded levels use static maximum trips: an over-
    approximation of the achievable set, so refutations stay sound.

    Returns ``(s_lo, s_hi, m_lo, m_hi, m_valid, g_all)`` with ``g_all``
    the gcd of all movable inner coefficients (the classic divisibility
    half, unchanged).
    """
    s_lo = s_hi = 0
    g_all = 0
    m_lo = np.full(E, _BIG)
    m_hi = np.full(E, -_BIG)
    m_valid = np.zeros(E, bool)
    m_lo[0] = m_hi[0] = 0
    m_valid[0] = True
    for c, lv in zip(form.coefs, form.levels):
        t = int(lv[-1])
        if c == 0 or t <= 1:
            continue
        g_all = math.gcd(g_all, abs(c))
        span = c * (t - 1)
        if c % E == 0:
            s_lo += min(span, 0)
            s_hi += max(span, 0)
            continue
        vals = c * np.arange(t, dtype=np.int64)
        res = vals % E
        c_lo = np.full(E, _BIG)
        c_hi = np.full(E, -_BIG)
        np.minimum.at(c_lo, res, vals)
        np.maximum.at(c_hi, res, vals)
        c_valid = c_hi >= c_lo
        # fold: new[ρ] ranges over old[ρ1] + cur[ρ2], ρ1+ρ2 ≡ ρ (mod E)
        n_lo = np.full(E, _BIG)
        n_hi = np.full(E, -_BIG)
        n_valid = np.zeros(E, bool)
        for r2 in np.nonzero(c_valid)[0]:
            rho = (np.arange(E) + r2) % E
            ok = m_valid
            np.minimum.at(n_lo, rho[ok], m_lo[ok] + c_lo[r2])
            np.maximum.at(n_hi, rho[ok], m_hi[ok] + c_hi[r2])
            n_valid[rho[ok]] = True
        m_lo, m_hi, m_valid = n_lo, n_hi, n_valid
    return s_lo, s_hi, m_lo, m_hi, m_valid, g_all


def _line_pair_feasible(p, q, own, E: int) -> int | None:
    """Smallest-|d| feasible cross-thread same-line pair at element
    offset ``d`` (``addr_p - addr_q = d``, ``0 < |d| < E``), or None when
    every sub-line offset is refuted.

    Same line forces the offset to equal the residue difference exactly
    (``d = r1 - r2`` with both residues inside the line), so the test
    enumerates ``(d, r1)`` and asks whether the residue-restricted inner
    intervals admit the required difference — exact in the parallel
    indices and their owners, Banerjee within each residue class.
    """
    f1, f2 = p.form, q.form
    if f1.trip0 != f2.trip0 or f1.trip0 <= 1:
        return None
    s1lo, s1hi, m1lo, m1hi, m1v, ga1 = _split_profile(f1, E)
    s2lo, s2hi, m2lo, m2hi, m2v, ga2 = _split_profile(f2, E)
    g = math.gcd(ga1, ga2)
    k2 = np.arange(f2.trip0, dtype=np.int64)[None, None, :]
    own2 = own(k2)
    base2 = f2.const + f2.k_coef * k2
    for b0 in range(0, f1.trip0, deps._PAIR_BLOCK):
        # block-level grids (pair mask, Banerjee interval, base offsets)
        # are residue/offset-INDEPENDENT: hoist them out of the (d, r1)
        # sweep — the schedule-blind interval (exact per-k inner domain,
        # incl. triangular clipping) intersects the residue-restricted
        # one below
        k1 = np.arange(b0, min(b0 + deps._PAIR_BLOCK, f1.trip0),
                       dtype=np.int64)[None, :, None]
        sl = slice(b0, b0 + k1.shape[1])
        pair_ok = (p.alive[sl][None, :, None] & q.alive[None, None, :]
                   & (k1 != k2) & (own(k1) != own2))
        if not bool(pair_ok.any()):
            continue
        L0 = p.lo[sl][None, :, None] - q.hi[None, None, :]
        H0 = p.hi[sl][None, :, None] - q.lo[None, None, :]
        D0 = base2 - (f1.const + f1.k_coef * k1)
        div0 = (D0 % g == 0) if g else None   # d-invariant when g | d
        kr1 = (-f1.const - f1.k_coef * k1) % E    # rho1 = (r1 + kr1) % E
        kr2 = (-f2.const - f2.k_coef * k2) % E
        for mag in range(1, E):
            for d in (mag, -mag):
                r1s = np.arange(max(0, d), E + min(0, d),
                                dtype=np.int64)[:, None, None]
                if r1s.shape[0] == 0:
                    continue
                D = D0 + d
                rho1 = (r1s + kr1) % E
                rho2 = (r1s - d + kr2) % E
                ok = pair_ok & m1v[rho1] & m2v[rho2]
                lo = s1lo - s2hi + m1lo[rho1] - m2hi[rho2]
                hi = s1hi - s2lo + m1hi[rho1] - m2lo[rho2]
                divisible = (div0 if g and d % g == 0 else
                             ((D % g == 0) if g else (D == 0)))
                feas = (ok & (D >= np.maximum(lo, L0))
                        & (D <= np.minimum(hi, H0)) & divisible)
                if bool(np.any(feas)):
                    return d
    return None


def _pad_suggestion(p, E: int, w: int, cls: int) -> str:
    """Padding advice from the write ref's parallel-axis stride."""
    stride = abs(p.form.k_coef)
    if stride == 0:
        return ("the reference is parallel-invariant — privatize or pad "
                "the shared element to a full line")
    if stride % E == 0:
        return ("the parallel stride is line-aligned; the sharing comes "
                "from an inner index — pad the inner extent to a "
                f"multiple of {E} elements")
    padded = -(-stride // E) * E
    return (f"line stride {stride * w} B per parallel iteration is not a "
            f"multiple of cls={cls} B — pad the per-iteration extent "
            f"from {stride} to {padded} elements")


def check(spec: LoopNestSpec, cfg: SamplerConfig,
          analysis: deps.Analysis | None = None,
          skip_nests: frozenset[int] = frozenset()) -> list[Diagnostic]:
    """PL501 (write-write) / PL502 (read-write) false-sharing findings per
    (nest, array), plus PL503 (INFO) for written arrays where every
    sub-line offset is refuted — the machine-checkable 'padding worked'
    verdict."""
    ana = analysis if analysis is not None \
        else deps.analyze(spec, skip_nests)
    own = owner_of(cfg)
    diags: list[Diagnostic] = []
    for (ni, array), group in sorted(ana.groups.items()):
        if not any(p.site.ref.is_write for p in group):
            continue
        w = array_width(spec, array, cfg)
        E = max(1, cfg.cls // max(1, w))
        found: dict[str, list[str]] = {"PL501": [], "PL502": []}
        detail: dict[str, str] = {}
        if E > 1:
            for i, p in enumerate(group):
                for q in group[i:]:
                    if not (p.site.ref.is_write or q.site.ref.is_write):
                        continue
                    d = _line_pair_feasible(p, q, own, E)
                    if d is None:
                        continue
                    code = "PL501" if (p.site.ref.is_write
                                       and q.site.ref.is_write) else "PL502"
                    found[code].append(
                        f"{p.site.ref.name}~{q.site.ref.name}@{d:+d}")
                    wp = p if p.site.ref.is_write else q
                    detail.setdefault(code, _pad_suggestion(
                        wp, E, w, cfg.cls))
        emitted = False
        for code, names in found.items():
            if not names:
                continue
            emitted = True
            kind = "write-write" if code == "PL501" else "read-write"
            diags.append(Diagnostic(
                code=code, severity=Severity.WARNING,
                message=f"cross-thread {kind} false sharing on '{array}' "
                        f"({E} elements/line): {shown(names)}; "
                        f"{detail[code]}",
                nest=ni, array=array,
            ))
        if not emitted:
            why = (f"element width {w} B fills a line" if E <= 1 else
                   f"every sub-line offset (|d| < {E}) is refuted under "
                   f"the schedule (T={cfg.thread_num}, "
                   f"chunk={cfg.chunk_size})")
            diags.append(Diagnostic(
                code="PL503", severity=Severity.INFO,
                message=f"no false sharing on written array '{array}': "
                        f"{why}",
                nest=ni, array=array,
            ))
    return diags
