"""Loop-transformation legality prover + spec-to-spec transformer.

PR 16's ``pluss tune`` optimizes the *runtime* knobs of a frozen nest;
this module moves the nest itself: the classic locality levers —
**interchange**, **tiling**, **fusion** — proven legal (or illegal, or
refused) from the dependence direction vectors of
:mod:`pluss.analysis.depvec` and applied as a pure spec-to-spec rewrite,
so every transformed nest is an ordinary :class:`~pluss.spec.
LoopNestSpec` that rides the whole existing stack unchanged: lint, the
PR-12 static predictor, the PR-15 hierarchy read-offs, ``pluss serve``
registration, and the engine ``--check`` bit-identity gate.

Legality rules (the textbook conditions, each carrying its proof):

========== ==============================================================
interchange legal iff, after permuting the band positions, every
            dependence direction vector stays lexicographically
            nonnegative (a reversed vector would run a sink before its
            source).
tiling      single-level tiling is strip-mining — the iteration ORDER is
            unchanged, always legal under the rectangular/divisibility
            contract.  Multi-level tiling hoists tile loops above the
            band and is legal iff the band is FULLY PERMUTABLE: every
            vector with all-zero components before the band has
            nonnegative components throughout the band (Wolf–Lam).
fusion      legal iff no fusion-preventing backward dependence: a
            cross-nest conflict whose later-nest instance sits at a
            strictly smaller outer index would, after fusing, run
            before its source.
========== ==============================================================

Imperfect nests are first PERFECTIZED by loop distribution (gemm's
``i{j{C0,C1,k{...}}}`` splits into ``i{j{C0,C1}}`` + ``i{j{k{...}}}``),
itself proven legal (no dependence from a later body group back into an
earlier one) — so ``pluss transform gemm --interchange 0,2`` is the real
compiler composite distribute-then-permute, not a toy.

Verdicts are typed, never a silent guess: PL951 proven-legal (the
re-checked witness vectors attach), PL952 proven-illegal (the concrete
violating instance pair attaches — a brute-force iteration-space oracle
confirms it in tests), PL953 typed refusal chaining the PL601/PL701
causes when a nest is outside the dependence-vector contract.  PL954 is
the transform ``--check`` alarm: the live engine run of the transformed
spec disagrees with its static MRC prediction.

``search_transforms`` extends the PL901 dominance-pruned tune search
over the transform space — interchange pairs, a tile-size ladder derived
per declared memory level, adjacent fusions — and reports the
proven-best *transformed* schedule with its static MRC delta against the
untransformed winner.
"""

from __future__ import annotations

import dataclasses

from pluss.analysis import depvec
from pluss.analysis import ri as ri_mod
from pluss.analysis import tune as tune_mod
from pluss.analysis.diagnostics import Diagnostic, Severity, shown
from pluss.config import DEFAULT, SamplerConfig
from pluss.model import hierarchy as hier_mod
from pluss import spec as spec_mod
from pluss.spec import Loop, LoopNestSpec, Ref


# --- report ----------------------------------------------------------------


@dataclasses.dataclass
class TransformReport:
    """One transform request's proof record: the typed verdict, the
    transformed spec when legal, the checked dependence vectors, and —
    for PL952 — the concrete violating instance pair."""

    model: str
    kind: str                       # "interchange" | "tile" | "fuse"
    params: dict
    code: str                       # PL951 | PL952 | PL953
    spec: LoopNestSpec | None
    diagnostics: list[Diagnostic]
    edges: list[dict]               # docs of every dependence edge checked
    violation: dict | None = None   # PL952: the violating pair + witness
    provenance: dict | None = None  # instance mapping back to the original

    def label(self) -> str:
        if self.kind == "interchange":
            return f"interchange({self.params['a']},{self.params['b']})"
        if self.kind == "tile":
            t = ",".join(f"{l}:{s}" for l, s in self.params["tiles"])
            return f"tile({t})"
        return f"fuse({self.params['a']}+{self.params['b']})"

    def doc(self) -> dict:
        from pluss import spec_codec

        d = {"model": self.model, "kind": self.kind,
             "params": self.params, "verdict": self.code,
             "edges": self.edges,
             "diagnostics": [g.to_dict() for g in self.diagnostics]}
        if self.violation is not None:
            d["violation"] = self.violation
        if self.provenance is not None:
            d["provenance"] = self.provenance
        if self.spec is not None:
            d["spec"] = spec_codec.spec_to_json(self.spec)
        return d


def _refuse(spec: LoopNestSpec, kind: str, params: dict,
            cause: str) -> TransformReport:
    return TransformReport(spec.name, kind, params, "PL953", None, [
        Diagnostic("PL953", Severity.WARNING,
                   f"{kind} refused: {cause}")], [])


def _illegal(spec: LoopNestSpec, kind: str, params: dict, edge: depvec.
             DepEdge, why: str, extra: dict | None = None
             ) -> TransformReport:
    viol = dict(edge.doc())
    if extra:
        viol.update(extra)
    return TransformReport(spec.name, kind, params, "PL952", None, [
        Diagnostic(
            "PL952", Severity.ERROR,
            f"{kind} proven illegal: {why} — violating pair "
            f"{edge.src.ref.name}@{list(edge.src_iv)} -> "
            f"{edge.dst.ref.name}@{list(edge.dst_iv)} "
            f"({edge.kind}, dir {list(edge.sigma)})")],
        [edge.doc()], violation=viol)


# --- tree rewriting helpers ------------------------------------------------


def _band_chain(nest: Loop, b: int) -> list[Loop] | None:
    """Loops at levels 0..b when the nest is perfect through level b-1
    (single-Loop bodies), else None."""
    chain, cur = [], nest
    for lvl in range(b + 1):
        chain.append(cur)
        if lvl == b:
            break
        if len(cur.body) != 1 or not isinstance(cur.body[0], Loop):
            return None
        cur = cur.body[0]
    return chain


def _rewrite_terms(item, fn):
    """Map every ref's addr terms through ``fn(depth, coef) ->
    [(depth', coef'), ...]``, recursively."""
    if isinstance(item, Ref):
        terms: list[tuple[int, int]] = []
        for d, c in item.addr_terms:
            terms += fn(d, c)
        return dataclasses.replace(
            item, addr_terms=tuple(sorted(terms)))
    return dataclasses.replace(
        item, body=tuple(_rewrite_terms(x, fn) for x in item.body))


def _distribute(loop: Loop, levels: int) -> list[Loop]:
    """Perfectize ``loop`` through ``levels`` band levels by loop
    distribution: each maximal run of Refs and each Loop child of an
    imperfect body becomes its own copy of the enclosing chain.  Returns
    the distributed nests in program order (a single element when the
    nest was already perfect)."""
    if levels == 0 or all(isinstance(x, Ref) for x in loop.body):
        return [loop]
    if len(loop.body) == 1 and isinstance(loop.body[0], Loop):
        return [dataclasses.replace(loop, body=(sub,))
                for sub in _distribute(loop.body[0], levels - 1)]
    out: list[Loop] = []
    run: list[Ref] = []
    for x in loop.body:
        if isinstance(x, Ref):
            run.append(x)
            continue
        if run:
            out.append(dataclasses.replace(loop, body=tuple(run)))
            run = []
        for sub in _distribute(x, levels - 1):
            out.append(dataclasses.replace(loop, body=(sub,)))
    if run:
        out.append(dataclasses.replace(loop, body=tuple(run)))
    return out


def _ref_names(item, acc: list[str]):
    if isinstance(item, Ref):
        acc.append(item.name)
    else:
        for x in item.body:
            _ref_names(x, acc)


def _group_index(nests: list[Loop]) -> dict[str, int]:
    """ref name -> distributed-group index (names are unique per nest
    by the PL406 contract)."""
    out: dict[str, int] = {}
    for g, n in enumerate(nests):
        names: list[str] = []
        _ref_names(n, names)
        for nm in names:
            out[nm] = g
    return out


def _respan(spec: LoopNestSpec) -> LoopNestSpec:
    """Re-derive every share_span through the PR-8 pipeline: transformed
    carrying loops get transformed spans, never stale copies."""
    from pluss.frontend.lower import derive_spans

    def strip(item):
        if isinstance(item, Ref):
            return dataclasses.replace(item, share_span=None)
        return dataclasses.replace(item,
                                   body=tuple(strip(x) for x in item.body))

    bare = dataclasses.replace(spec, nests=tuple(
        strip(n) for n in spec.nests))
    return derive_spans(bare)


def _distribution_violation(vectors: depvec.NestVectors,
                            groups: dict[str, int]) -> depvec.DepEdge | None:
    """The first dependence edge pointing from a later distributed group
    back into an earlier one (illegal to split), else None."""
    for e in vectors.edges:
        if groups[e.src.ref.name] > groups[e.dst.ref.name]:
            return e
    return None


# --- interchange -----------------------------------------------------------


def interchange(spec: LoopNestSpec, a: int, b: int,
                nest: int = 0) -> TransformReport:
    """Swap band levels ``a`` and ``b`` of one nest, distributing first
    when the nest is imperfect.  Legal iff the distribution is legal and
    every deep-group dependence vector stays lexicographically
    nonnegative after the swap."""
    params = {"a": a, "b": b, "nest": nest}
    if not (0 <= nest < len(spec.nests)):
        return _refuse(spec, "interchange", params,
                       f"nest {nest} does not exist")
    if not (0 <= a < b):
        return _refuse(spec, "interchange", params,
                       "need band levels 0 <= a < b")
    vectors = depvec.nest_vectors(spec, nest)
    if vectors.refused is not None:
        return _refuse(spec, "interchange", params, vectors.refused)
    if spec_mod.nest_depth(spec.nests[nest]) <= b:
        return _refuse(spec, "interchange", params,
                       f"nest {nest} has no level {b}")
    dist = _distribute(spec.nests[nest], b)
    groups = _group_index(dist)
    bad = _distribution_violation(vectors, groups)
    if bad is not None:
        return _illegal(spec, "interchange", params, bad,
                        "perfectizing distribution would run the sink "
                        "group before its source group")
    checked: list[dict] = []
    for e in vectors.edges:
        if groups[e.src.ref.name] != groups[e.dst.ref.name]:
            continue  # cross-group: order fixed by the nest sequence
        if len(e.sigma) <= b:
            continue  # shallow group: does not contain the band
        perm = list(e.sigma)
        perm[a], perm[b] = perm[b], perm[a]
        doc = dict(e.doc())
        doc["permuted"] = perm
        checked.append(doc)
        if depvec._lex(tuple(perm)) < 0:
            return _illegal(
                spec, "interchange", params, e,
                f"direction vector {list(e.sigma)} becomes "
                f"lexicographically negative {perm} after the swap",
                extra={"permuted": perm})

    def swap_term(d, c):
        nd = b if d == a else a if d == b else d
        return [(nd, c)]

    new_nests: list[Loop] = []
    prov_nests: list[dict] = []
    for gi, g in enumerate(dist):
        chain = _band_chain(g, b)
        if chain is None:   # shallow ref-run group: untouched
            new_nests.append(g)
            prov_nests.append({"orig_nest": nest, "map": "identity"})
            continue
        body = tuple(_rewrite_terms(x, swap_term) for x in chain[b].body)
        for lvl in range(b, -1, -1):
            # positions a and b exchange their loop parameters
            src = chain[b] if lvl == a else chain[a] if lvl == b \
                else chain[lvl]
            body = (dataclasses.replace(src, body=body),)
        new_nests.append(body[0])
        perm = list(range(b + 1))
        perm[a], perm[b] = perm[b], perm[a]
        prov_nests.append({"orig_nest": nest, "map": "interchange",
                           "a": a, "b": b, "perm": perm})
    tspec = dataclasses.replace(
        spec, name=f"{spec.name}_ic{a}{b}",
        nests=spec.nests[:nest] + tuple(new_nests)
        + spec.nests[nest + 1:])
    tspec = _respan(tspec)
    prov = {"kind": "interchange", "params": params, "nests": (
        [{"orig_nest": i, "map": "identity"} for i in range(nest)]
        + prov_nests
        + [{"orig_nest": i, "map": "identity"}
           for i in range(nest + 1, len(spec.nests))])}
    n_dist = len(dist)
    diags = [Diagnostic(
        "PL951", Severity.INFO,
        f"interchange({a},{b}) proven legal on nests[{nest}]"
        + (f" after distribution into {n_dist} nests" if n_dist > 1
           else "")
        + f": {len(checked)} dependence vector(s) re-checked, all stay "
        f"lexicographically nonnegative "
        f"[{shown([str(c['vector']) for c in checked]) or 'none'}]")]
    return TransformReport(spec.name, "interchange", params, "PL951",
                           tspec, diags, checked, provenance=prov)


# --- tiling ----------------------------------------------------------------


def tile(spec: LoopNestSpec, tiles: list[tuple[int, int]],
         nest: int = 0) -> TransformReport:
    """Tile a contiguous band of levels with per-level sizes.  The tile
    loop keeps the original start and steps by ``step*size``; the point
    loop spans ``[0, size)`` with the original step, so per-instance
    addresses are bit-identical.  Single-level = strip-mining (order-
    preserving); multi-level requires the band fully permutable."""
    tiles = sorted(tiles)
    params = {"tiles": [list(t) for t in tiles], "nest": nest}
    if not tiles:
        return _refuse(spec, "tile", params, "no tile levels given")
    levels = [l for l, _ in tiles]
    a, b = levels[0], levels[-1]
    if levels != list(range(a, b + 1)):
        return _refuse(spec, "tile", params,
                       f"tile levels {levels} are not a contiguous band")
    if a < 0:
        return _refuse(spec, "tile", params, "negative tile level")
    if not (0 <= nest < len(spec.nests)):
        return _refuse(spec, "tile", params, f"nest {nest} does not exist")
    vectors = depvec.nest_vectors(spec, nest)
    if vectors.refused is not None:
        return _refuse(spec, "tile", params, vectors.refused)
    if spec_mod.nest_depth(spec.nests[nest]) <= b:
        return _refuse(spec, "tile", params, f"nest {nest} has no "
                       f"level {b}")
    dist = _distribute(spec.nests[nest], b)
    groups = _group_index(dist)
    bad = _distribution_violation(vectors, groups)
    if bad is not None:
        return _illegal(spec, "tile", params, bad,
                        "perfectizing distribution would run the sink "
                        "group before its source group")
    sizes = {l: s for l, s in tiles}
    for g in dist:
        chain = _band_chain(g, b)
        if chain is None:
            continue
        for l in range(a, b + 1):
            t, s = chain[l].trip, sizes[l]
            if s < 2 or s >= t or t % s:
                return _refuse(
                    spec, "tile", params,
                    f"tile size {s} at level {l} must satisfy "
                    f"2 <= size < trip and divide trip ({t})")
    checked: list[dict] = []
    if b > a:   # multi-level: band must be fully permutable
        for e in vectors.edges:
            if groups[e.src.ref.name] != groups[e.dst.ref.name]:
                continue
            if len(e.sigma) <= b:
                continue
            doc = dict(e.doc())
            checked.append(doc)
            if all(s == 0 for s in e.sigma[:a]) \
                    and any(s < 0 for s in e.sigma[a:b + 1]):
                return _illegal(
                    spec, "tile", params, e,
                    f"band [{a},{b}] is not fully permutable: vector "
                    f"{list(e.sigma)} has a negative component inside "
                    "the band with no positive component before it")
    width = b - a + 1

    def tile_term(d, c):
        if d < a:
            return [(d, c)]
        if d <= b:
            return [(a + (d - a), c), (b + 1 + (d - a), c)]
        return [(d + width, c)]

    new_nests: list[Loop] = []
    prov_nests: list[dict] = []
    for g in dist:
        chain = _band_chain(g, b)
        if chain is None:
            new_nests.append(g)
            prov_nests.append({"orig_nest": nest, "map": "identity"})
            continue
        body = tuple(_rewrite_terms(x, tile_term) for x in chain[b].body)
        for l in range(b, a - 1, -1):   # point loops, innermost first
            s = sizes[l]
            body = (Loop(trip=s, body=body, start=0, step=chain[l].step),)
        for l in range(b, a - 1, -1):   # tile loops above them
            s = sizes[l]
            body = (dataclasses.replace(
                chain[l], trip=chain[l].trip // s,
                step=chain[l].step * s, body=body),)
        for l in range(a - 1, -1, -1):  # untouched outer levels
            body = (dataclasses.replace(chain[l], body=body),)
        new_nests.append(body[0])
        prov_nests.append({"orig_nest": nest, "map": "tile", "a": a,
                           "b": b, "sizes": [sizes[l]
                                             for l in range(a, b + 1)]})
    suffix = "_".join(f"{l}x{s}" for l, s in tiles)
    tspec = dataclasses.replace(
        spec, name=f"{spec.name}_tile{suffix}",
        nests=spec.nests[:nest] + tuple(new_nests)
        + spec.nests[nest + 1:])
    tspec = _respan(tspec)
    prov = {"kind": "tile", "params": params, "nests": (
        [{"orig_nest": i, "map": "identity"} for i in range(nest)]
        + prov_nests
        + [{"orig_nest": i, "map": "identity"}
           for i in range(nest + 1, len(spec.nests))])}
    why = ("strip-mine preserves the iteration order" if b == a else
           f"band [{a},{b}] proven fully permutable over "
           f"{len(checked)} dependence vector(s)")
    vecs = shown([str(c["vector"]) for c in checked]) or "no carried vectors"
    diags = [Diagnostic(
        "PL951", Severity.INFO,
        f"tile({','.join(f'{l}:{s}' for l, s in tiles)}) proven legal "
        f"on nests[{nest}]: {why} [{vecs}]")]
    return TransformReport(spec.name, "tile", params, "PL951", tspec,
                           diags, checked, provenance=prov)


# --- fusion ----------------------------------------------------------------


def fuse(spec: LoopNestSpec, na: int, nb: int) -> TransformReport:
    """Fuse two ADJACENT nests with identical outer loops.  Legal iff no
    fusion-preventing backward dependence: a cross-nest conflict whose
    later-nest instance sits at a strictly smaller outer index."""
    params = {"a": na, "b": nb}
    if nb != na + 1 or not (0 <= na and nb < len(spec.nests)):
        return _refuse(spec, "fuse", params,
                       "fusion needs two adjacent nests a, a+1")
    la, lb = spec.nests[na], spec.nests[nb]
    for ni in (na, nb):
        v = depvec.nest_vectors(spec, ni)
        if v.refused is not None:
            return _refuse(spec, "fuse", params,
                           f"nests[{ni}]: {v.refused}")
    if (la.trip, la.start, la.step) != (lb.trip, lb.start, lb.step):
        return _refuse(
            spec, "fuse", params,
            f"outer loops differ: nests[{na}] is (trip={la.trip}, "
            f"start={la.start}, step={la.step}) vs nests[{nb}] "
            f"(trip={lb.trip}, start={lb.start}, step={lb.step})")
    sites_a = [s for s in depvec.ref_sites(spec) if s.nest == na]
    sites_b = [s for s in depvec.ref_sites(spec) if s.nest == nb]
    budget = [depvec.vector_budget()]
    checked: list[dict] = []
    try:
        for p in sites_a:
            for q in sites_b:
                if p.ref.array != q.ref.array:
                    continue
                if not (p.ref.is_write or q.ref.is_write):
                    continue
                wit = depvec.fusion_backward_witness(p, q, budget)
                pair_doc = {"src": p.ref.name, "dst": q.ref.name,
                            "array": p.ref.array,
                            "backward": wit is not None}
                checked.append(pair_doc)
                if wit is not None:
                    iv1, iv2 = wit
                    e = depvec.DepEdge(
                        p, q, (-1,), (iv2[0] - iv1[0],), iv1, iv2,
                        depvec._edge_kind(p, q))
                    return _illegal(
                        spec, "fuse", params, e,
                        f"fusion-preventing backward dependence: "
                        f"nests[{nb}] instance at outer index "
                        f"{iv2[0]} conflicts with nests[{na}] instance "
                        f"at outer index {iv1[0]}")
    except depvec.VectorBudgetExceeded:
        return _refuse(spec, "fuse", params,
                       "dependence witness search exceeded the "
                       "PLUSS_DEPVEC_BUDGET node budget")
    names_a: list[str] = []
    _ref_names(la, names_a)
    renames: dict[str, str] = {}

    def rename(item):
        if isinstance(item, Ref):
            if item.name in names_a:
                new = item.name + "_f"
                while new in names_a or new in renames:
                    new += "f"
                renames[new] = item.name
                return dataclasses.replace(item, name=new)
            return item
        return dataclasses.replace(item,
                                   body=tuple(rename(x) for x in item.body))

    fused = dataclasses.replace(la, body=la.body + rename(lb).body)
    tspec = dataclasses.replace(
        spec, name=f"{spec.name}_fuse{na}{nb}",
        nests=spec.nests[:na] + (fused,) + spec.nests[nb + 1:])
    tspec = _respan(tspec)
    prov = {"kind": "fuse", "params": params, "nests": (
        [{"orig_nest": i, "map": "identity"} for i in range(na)]
        + [{"orig_nest": na, "map": "fuse", "other_nest": nb,
            "names_a": names_a, "renames": renames}]
        + [{"orig_nest": i, "map": "identity"}
           for i in range(nb + 1, len(spec.nests))])}
    diags = [Diagnostic(
        "PL951", Severity.INFO,
        f"fuse({na}+{nb}) proven legal: {len(checked)} cross-nest "
        "conflict pair(s) checked, none carries a backward dependence")]
    return TransformReport(spec.name, "fuse", params, "PL951", tspec,
                           diags, checked, provenance=prov)


# --- instance mapping (oracle support + provenance doc) --------------------


def instance_mapper(prov: dict):
    """A function mapping a transformed access instance back to its
    original identity: ``fn(new_nest, ref_name, values) -> (orig_nest,
    orig_ref_name, orig_values)`` where ``values`` is the per-level loop
    VALUE vector along the instance's chain.  This is what lets the
    brute-force test oracle check that every claimed-legal transform
    preserves the order of every conflicting pair."""
    nests = prov["nests"]

    def fn(ni: int, name: str, values: tuple):
        nd = nests[ni]
        kind = nd["map"]
        if kind == "identity":
            return nd["orig_nest"], name, tuple(values)
        if kind == "interchange":
            # the permutation is an involution (a swap of a and b)
            out = list(values)
            a, b = nd["a"], nd["b"]
            if len(values) > b:
                out[a], out[b] = values[b], values[a]
            return nd["orig_nest"], name, tuple(out)
        if kind == "tile":
            a, b = nd["a"], nd["b"]
            width = b - a + 1
            out = list(values[:a])
            for j in range(width):   # value = tile part + point part
                out.append(values[a + j] + values[a + width + j])
            out += list(values[a + 2 * width:])
            return nd["orig_nest"], name, tuple(out)
        if kind == "fuse":
            if name in nd["renames"]:
                return nd["other_nest"], nd["renames"][name], \
                    tuple(values)
            if name in nd["names_a"]:
                return nd["orig_nest"], name, tuple(values)
            return nd["other_nest"], name, tuple(values)
        raise ValueError(f"unknown provenance map {kind!r}")

    return fn


# --- the transform search (tune --transforms) ------------------------------


def tile_ladder(spec: LoopNestSpec, trips: list[int],
                cfg: SamplerConfig,
                hier: hier_mod.HierarchyConfig) -> list[int]:
    """Candidate tile sizes, one rung per declared memory level: the
    largest power of two whose square working set (per array) fits the
    level, snapped down to a common divisor of the band trips."""
    arrays = max(1, len(spec.arrays))
    sizes: set[int] = set()
    for kb in hier.levels_kb:
        cap = kb * 1024 // (cfg.ds * arrays)
        s = 1
        while (s * 2) * (s * 2) <= cap:
            s *= 2
        while s >= 2 and any(t % s or s >= t for t in trips):
            s //= 2
        if s >= 2:
            sizes.add(s)
    return sorted(sizes)


def enumerate_transforms(spec: LoopNestSpec,
                         cfg: SamplerConfig = DEFAULT,
                         hier: hier_mod.HierarchyConfig | None = None,
                         nest: int = 0) -> list[TransformReport]:
    """The transform candidate space for one nest: every interchange
    pair over the deep band, the tile ladder (full-band and innermost
    strip-mine), and every adjacent fusion.  Returns ALL reports —
    legal, illegal, and refused — so the search doc shows the whole
    disposition; only PL951 entries are scored."""
    hier = hier or hier_mod.HierarchyConfig.from_env()
    out: list[TransformReport] = []
    depth = spec_mod.nest_depth(spec.nests[nest]) if spec.nests else 0
    for a in range(depth):
        for b in range(a + 1, depth):
            out.append(interchange(spec, a, b, nest=nest))
    # the primary chain: follow the unique Loop child at each level
    chain_trips: list[int] = []
    item = spec.nests[nest] if spec.nests else None
    while isinstance(item, Loop):
        chain_trips.append(item.trip)
        loops = [x for x in item.body if isinstance(x, Loop)]
        item = loops[0] if len(loops) == 1 else None
    band = list(range(min(depth, len(chain_trips))))
    if len(band) >= 2:
        trips = [chain_trips[l] for l in band]
        for s in tile_ladder(spec, trips, cfg, hier):
            out.append(tile(spec, [(l, s) for l in band], nest=nest))
    if depth >= 1 and chain_trips:
        for s in tile_ladder(spec, chain_trips[-1:], cfg, hier):
            out.append(tile(spec, [(len(chain_trips) - 1, s)],
                            nest=nest))
    for na in range(len(spec.nests) - 1):
        out.append(fuse(spec, na, na + 1))
    # dedupe by label (full-band tile can coincide with strip-mine)
    seen: set[str] = set()
    uniq: list[TransformReport] = []
    for r in out:
        if r.label() not in seen:
            seen.add(r.label())
            uniq.append(r)
    return uniq


@dataclasses.dataclass
class TransformEntry:
    transform: TransformReport
    tune: tune_mod.TuneReport | None    # None unless PL951 + derivable

    def score(self) -> float | None:
        if self.tune is not None and self.tune.winner is not None:
            return self.tune.winner.score
        return None

    def doc(self) -> dict:
        d = {"transform": self.transform.label(),
             "verdict": self.transform.code}
        if self.tune is not None:
            d["tune"] = {"verdict": self.tune.code,
                         "winner": (self.tune.winner.doc()
                                    if self.tune.winner else None)}
        if self.score() is not None:
            d["score"] = self.score()
        return d


@dataclasses.dataclass
class TransformTuneReport:
    """``pluss tune --transforms``: the schedule search re-run per legal
    transform, with the static MRC delta against the untransformed
    winner."""

    model: str
    target_kb: int
    hier: hier_mod.HierarchyConfig
    base: tune_mod.TuneReport
    entries: list[TransformEntry]
    best: TransformEntry | None      # None = identity wins (or refusal)
    delta: float | None              # best score - identity score (<0 win)
    diagnostics: list[Diagnostic]

    def best_spec(self) -> LoopNestSpec | None:
        return self.best.transform.spec if self.best else None

    def doc(self) -> dict:
        d = {"model": self.model, "target_kb": self.target_kb,
             "base": self.base.doc(),
             "transforms": [e.doc() for e in self.entries],
             "diagnostics": [g.to_dict() for g in self.diagnostics]}
        if self.best is not None:
            d["best"] = self.best.doc()
            d["best_transform"] = self.best.transform.label()
        if self.delta is not None:
            d["delta"] = self.delta
        return d


def search_transforms(spec: LoopNestSpec,
                      base_cfg: SamplerConfig = DEFAULT,
                      candidates: list[tune_mod.Candidate] | None = None,
                      hier: hier_mod.HierarchyConfig | None = None,
                      budget: int | None = None) -> TransformTuneReport:
    """Extend the PL901 dominance-pruned schedule search over the
    transform space: tune the untransformed spec, then every proven-
    legal transformed spec, and report the best (transform, schedule)
    pair with its static LLC miss-ratio delta."""
    hier = hier or hier_mod.HierarchyConfig.from_env()
    base = tune_mod.tune(spec, base_cfg, candidates, hier, budget)
    entries: list[TransformEntry] = []
    for tr in enumerate_transforms(spec, base_cfg, hier):
        if tr.code != "PL951" or tr.spec is None:
            entries.append(TransformEntry(tr, None))
            continue
        rep = tune_mod.tune(tr.spec, base_cfg, candidates, hier, budget)
        entries.append(TransformEntry(tr, rep))
    base_score = base.winner.score if base.winner is not None else None
    scored = [e for e in entries if e.score() is not None]
    best = min(scored, key=lambda e: e.score()) if scored else None
    delta = None
    diags: list[Diagnostic] = []
    if best is not None and base_score is not None:
        delta = best.score() - base_score
        if delta < -tune_mod.TIE_EPS:
            diags.append(Diagnostic(
                "PL901", Severity.INFO,
                f"proven-best transformed schedule: "
                f"{best.transform.label()} + "
                f"{best.tune.winner.candidate.label()} predicts miss "
                f"{best.score():.6g} at {base.target_kb} KB LLC — "
                f"{-delta:.6g} below the untransformed winner "
                f"({base_score:.6g})"))
        else:
            best = None
            diags.append(Diagnostic(
                "PL901", Severity.INFO,
                f"no transform beats the untransformed winner "
                f"(best transformed score within epsilon of "
                f"{base_score:.6g}); keeping the identity schedule"))
    elif base_score is None:
        diags.append(Diagnostic(
            "PL903", Severity.WARNING,
            "transform search refused: the untransformed tune fell off "
            "the derivability ladder"))
    n_legal = sum(1 for e in entries if e.transform.code == "PL951")
    diags.append(Diagnostic(
        "PL951", Severity.INFO,
        f"transform space: {len(entries)} candidate(s), {n_legal} "
        f"proven legal, "
        f"{sum(1 for e in entries if e.transform.code == 'PL952')} "
        f"proven illegal, "
        f"{sum(1 for e in entries if e.transform.code == 'PL953')} "
        "refused"))
    return TransformTuneReport(spec.name, base.target_kb, hier, base,
                               entries, best, delta, diags)


# --- the --check cross-validation (PL954) ----------------------------------


def check_transform(report: TransformReport,
                    cfg: SamplerConfig = DEFAULT,
                    budget: int | None = None
                    ) -> tuple[bool, dict, list[Diagnostic]]:
    """Run the live engine ONCE on the transformed spec and require its
    static MRC prediction to match bit-identically (closed-form rungs)
    or within :data:`~pluss.analysis.ri.MRC_EPS` (dense).  Disagreement
    is the PL954 alarm.  A prediction refusal is reported as a skip
    (ok, with the refusal codes in the detail), mirroring ``pluss
    predict``'s ladder semantics."""
    from pluss import engine

    if report.spec is None:
        raise ValueError("check_transform: no transformed spec "
                         f"(verdict {report.code})")
    rep = ri_mod.predict(report.spec, cfg, budget=budget)
    if rep.rihist is None:
        codes = sorted({d.code for d in rep.prediction.diagnostics})
        return True, {"skipped": True, "codes": codes}, []
    res = engine.run(report.spec, cfg)
    ok, detail = ri_mod.check_against_engine(rep, res, cfg)
    diags: list[Diagnostic] = []
    if not ok:
        diags.append(Diagnostic(
            "PL954", Severity.ERROR,
            f"transformed-spec cross-check failed for "
            f"{report.label()} on {report.model}: live engine run "
            f"disagrees with the static MRC prediction beyond "
            f"{ri_mod.MRC_EPS:g} ({detail})"))
    return ok, detail, diags


# --- CLI parameter parsing -------------------------------------------------


def parse_interchange(text: str) -> tuple[int, int]:
    """``"0,2"`` -> (0, 2)."""
    parts = text.split(",")
    if len(parts) != 2:
        raise ValueError("--interchange wants 'a,b' (two band levels)")
    return int(parts[0]), int(parts[1])


def parse_tile(text: str) -> list[tuple[int, int]]:
    """``"0:8,1:8"`` -> [(0, 8), (1, 8)]."""
    tiles = []
    for part in text.split(","):
        if ":" not in part:
            raise ValueError("--tile wants 'level:size[,level:size...]'")
        l, s = part.split(":", 1)
        tiles.append((int(l), int(s)))
    return tiles


def parse_fuse(text: str) -> tuple[int, int]:
    """``"0+1"`` -> (0, 1)."""
    parts = text.split("+")
    if len(parts) != 2:
        raise ValueError("--fuse wants 'a+b' (two adjacent nest indices)")
    return int(parts[0]), int(parts[1])
