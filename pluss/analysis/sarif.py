"""SARIF 2.1.0 export of PLxxx findings.

``pluss lint/analyze/predict --sarif <path>`` writes the diagnostics
stream as a single-run SARIF log so CI systems render them as native
code-scanning annotations.  The mapping is deliberately small and
lossless where SARIF has a slot:

- one ``run`` with ``tool.driver.name = "pluss"``; every PLxxx code that
  occurs becomes a ``rules`` entry (id = code, shortDescription = the
  registered :data:`pluss.analysis.diagnostics.CODES` summary);
- one ``result`` per diagnostic: ``ruleId``/``level``/``message``; the
  model and IR tree path have no file/line to anchor to (specs are
  in-memory IR), so they travel in ``message.text`` and under
  ``properties`` (``model``, ``path``, ``nest``, ``ref``, ``array``)
  where SARIF consumers keep them queryable;
- severity map: ERROR -> ``error``, WARNING -> ``warning``,
  INFO -> ``note`` (the SARIF ``kind`` stays the default ``fail``).
"""

from __future__ import annotations

import json

from pluss.analysis.diagnostics import CODES, Diagnostic, Severity

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")

_LEVEL = {
    Severity.ERROR: "error",
    Severity.WARNING: "warning",
    Severity.INFO: "note",
}


def _rule(code: str) -> dict:
    family, summary = CODES.get(code, ("unknown", code))
    return {
        "id": code,
        "shortDescription": {"text": summary},
        "properties": {"family": family},
    }


def _result(d: Diagnostic) -> dict:
    props = {k: v for k, v in (
        ("model", d.model), ("path", d.path), ("nest", d.nest),
        ("ref", d.ref), ("array", d.array),
    ) if v is not None and v != ""}
    out = {
        "ruleId": d.code,
        "level": _LEVEL[d.severity],
        "message": {"text": d.format()},
    }
    if props:
        out["properties"] = props
    return out


def to_sarif(diags: list[Diagnostic], tool_version: str = "0") -> dict:
    """The SARIF 2.1.0 document (a plain JSON-able dict)."""
    rules = sorted({d.code for d in diags})
    return {
        "version": SARIF_VERSION,
        "$schema": SARIF_SCHEMA,
        "runs": [{
            "tool": {"driver": {
                "name": "pluss",
                "informationUri": "https://github.com/",
                "version": tool_version,
                "rules": [_rule(c) for c in rules],
            }},
            "results": [_result(d) for d in diags],
        }],
    }


def write_sarif(path: str, diags: list[Diagnostic],
                tool_version: str = "0") -> None:
    with open(path, "w") as f:
        json.dump(to_sarif(diags, tool_version), f, indent=2)
        f.write("\n")


def validate(doc: dict) -> list[str]:
    """Structural round-trip check (no jsonschema dependency): the
    invariants the export guarantees and the tests pin.  Returns a list
    of violations (empty = valid)."""
    errs = []
    if doc.get("version") != SARIF_VERSION:
        errs.append(f"version {doc.get('version')!r} != {SARIF_VERSION}")
    if not str(doc.get("$schema", "")).startswith("https://"):
        errs.append("$schema missing")
    runs = doc.get("runs")
    if not isinstance(runs, list) or len(runs) != 1:
        return errs + ["runs must be a one-element list"]
    run = runs[0]
    driver = run.get("tool", {}).get("driver", {})
    if driver.get("name") != "pluss":
        errs.append("tool.driver.name != pluss")
    rule_ids = {r.get("id") for r in driver.get("rules", [])}
    for i, res in enumerate(run.get("results", [])):
        if res.get("ruleId") not in rule_ids:
            errs.append(f"results[{i}].ruleId {res.get('ruleId')!r} "
                        "not declared in driver.rules")
        if res.get("level") not in ("error", "warning", "note"):
            errs.append(f"results[{i}].level invalid")
        if not res.get("message", {}).get("text"):
            errs.append(f"results[{i}].message.text missing")
        if res.get("ruleId") not in CODES:
            errs.append(f"results[{i}].ruleId not a registered PL code")
    return errs
