"""Share-span validation: recompute the carrying-loop formula, flag copies.

A ``Ref.share_span`` is the generated threshold the reference's state
machine compares reuses against (``2*reuse > span`` ⇒ cross-thread
"share"; see ``pluss.spec`` module docstring).  The generated form is
``share_span_formula(trip, start, step)`` of the CARRYING loop — in the
reference's GEMM sampler, the loop just below the parallel dimension on
the ref's chain (``gemm_sampler.rs:196-199``: the c1 loop's
``(trip+1)*trip+1`` = 16513).  Model authors copy that formula by hand;
this pass recomputes it from the chain and flags drift:

- PL201 (ERROR): the span is no threshold at all (``<= 1`` classifies
  every reuse as cross-thread, including distance-1 self reuse).
- PL202 (WARNING): the span differs from the recomputed carrying-loop
  value — the hand-copied-constant hazard.  Warning, not error: several
  seeded families deliberately use the problem-size formula where the
  carrying loop's trip is ``n-1`` (durbin, cholesky's j<i chain …), which
  shifts the threshold by a few percent without flipping any realistic
  classification.  The lint makes the drift visible; flipping thresholds
  is a model decision.
- PL203/PL204 (INFO): span annotations inconsistent with the race
  detector's cross-thread classification (missing where a cross-thread
  reuse is observable, inert where none is).
"""

from __future__ import annotations

from pluss.analysis.diagnostics import Diagnostic, Severity
from pluss.spec import LoopNestSpec, share_span_formula


def recomputed_span(site) -> int:
    """The carrying-loop formula for a ref site: the loop just below the
    parallel dimension on the ref's chain (the generated convention), or
    the parallel loop itself for depth-1 refs."""
    loop = site.chain[1] if len(site.chain) > 1 else site.chain[0]
    return share_span_formula(loop.trip, loop.start, loop.step)


def check(spec: LoopNestSpec, classes: dict) -> list[Diagnostic]:
    """``classes``: :func:`pluss.analysis.deps.classify` output (keyed by
    tree path, so name collisions can never shadow a finding) — the share
    validation rides the race detector's classification."""
    diags: list[Diagnostic] = []
    for path, rc in sorted(classes.items()):
        site = rc.site
        name = site.ref.name
        span = site.ref.share_span
        common = dict(path=path, nest=site.nest, ref=name,
                      array=site.ref.array)
        if span is None:
            if rc.cross_observed:
                diags.append(Diagnostic(
                    code="PL203", severity=Severity.INFO,
                    message=f"ref {name} can observe a reuse carried by "
                            "the parallel loop but has no share_span — "
                            "such reuses will always classify as private",
                    **common,
                ))
            continue
        if span <= 1:
            diags.append(Diagnostic(
                code="PL201", severity=Severity.ERROR,
                message=f"share_span={span} is not a meaningful threshold "
                        "(every reuse, including distance-1 self reuse, "
                        "would classify as cross-thread)",
                **common,
            ))
            continue
        want = recomputed_span(site)
        # a degenerate recomputation (<= 1: varying-start loops make the
        # static formula meaningless) must not be "suggested" — PL201
        # would reject the suggested value
        if span != want and want > 1:
            diags.append(Diagnostic(
                code="PL202", severity=Severity.WARNING,
                message=f"share_span={span} differs from the recomputed "
                        f"carrying-loop formula {want} "
                        "(hand-copied constant?)",
                **common,
            ))
        if not rc.cross_observed:
            diags.append(Diagnostic(
                code="PL204", severity=Severity.INFO,
                message=f"ref {name} carries share_span={span} but the "
                        "race detector refutes any parallel-carried "
                        "reuse at it — the span can never trigger",
                **common,
            ))
    return diags
